"""Entry point (ref: train.py:12-134).

Lifecycle: install signal handlers -> build Trainer (setup) -> run the loop ->
route any exception through the exit-policy table -> always exit 0 so Slurm
never marks the job failed (ref: train.py:119,129).
"""

import sys

from fault_tolerant_llm_training_tpu.ft.handler import (
    classify_exception,
    handle_exit,
)
from fault_tolerant_llm_training_tpu.ft.signals import SignalFlag
from fault_tolerant_llm_training_tpu.obs import events
from fault_tolerant_llm_training_tpu.training.loop import Trainer
from fault_tolerant_llm_training_tpu.utils.config import get_args
from fault_tolerant_llm_training_tpu.utils.logging import (
    AUDIT_COMPLETED,
    init_logger,
    logger,
)


def train(cfg) -> None:
    # Handlers installed before any setup work — a signal during the model
    # build is deferred to a phase boundary instead of being fatal
    # (the reference registers at train.py:89-90, after ~35 s of setup).
    flag = SignalFlag()
    flag.register()
    trainer = None
    try:
        # Signals are deferred (blocked at the OS level) for the whole
        # native-heavy setup: they stay pending and are handled at the first
        # loop boundary with a fully-built trainer — so a preemption during
        # setup still gets a checkpoint+resubmit instead of a dead job.
        with flag.deferred():
            trainer = Trainer(cfg, signal_flag=flag)
        trainer.run()
        # ref: train.py:118 — audit string byte-identical; the paired event
        # closes the flight-recorder chain for goodput stitching.
        events.emit_audit(logger, AUDIT_COMPLETED, "complete",
                          step=trainer.training_step)
        events.flush()
        sys.exit(0)
    except Exception as e:
        error_type = classify_exception(e)  # ref: train.py:122-126
        if error_type == -1:
            # The reference swallows the traceback entirely; log it so code
            # errors are debuggable from the Slurm .out file.
            logger.exception("Unhandled exception (routing to exit handler)")
        # A second signal (Slurm's grace-period SIGTERM chasing the USR1)
        # must not interrupt the checkpoint write — the reference's
        # truncation race (SURVEY.md §5.3).
        try:
            with flag.deferred():
                handle_exit(trainer, error_type, logger)
        except Exception:
            # The exit-0 contract (Slurm must never mark the job failed,
            # ref train.py:119,129) holds even when the handler itself
            # fails — e.g. the checkpoint write dying on a pod whose peers
            # are gone. The traceback is the diagnostic.
            logger.exception("Exit handler failed; exit code preserved")
        sys.exit(0)  # ref: train.py:129 — exit 0 even on error
    finally:
        if trainer is not None:
            try:
                trainer.close()
            except Exception:
                # The exit-0 contract (Slurm must never mark the job failed,
                # ref train.py:119,129) survives a teardown failure.
                logger.exception("close() failed; exit code preserved")


if __name__ == "__main__":
    init_logger()  # ref: train.py:132
    train(get_args())  # ref: train.py:133-134
