"""Headline benchmark: tokens/sec/chip, GPT-2-125M-class @ seq 2048
(BASELINE.json metric), full training step (fwd+bwd+AdamW), bf16.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "tokens/sec/chip", "vs_baseline": N}

``vs_baseline`` compares against the only empirical anchor the reference
publishes: 6,380 tokens/s/GPU — measured on its ~8.05B model on a GH200
(BASELINE.md), not on this 125M config, so the ratio is an anchor, not an
apples-to-apples speedup.
"""

import json
import os
import sys
import time

import numpy as np

REFERENCE_TOKENS_PER_SEC = 6380.0  # BASELINE.md throughput row


def main():
    import jax
    from jax.sharding import NamedSharding

    from fault_tolerant_llm_training_tpu.models import get_config
    from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
    from fault_tolerant_llm_training_tpu.parallel.sharding import batch_pspec
    from fault_tolerant_llm_training_tpu.utils.harness import (
        synthetic_batch,
        synthetic_state_and_step,
    )
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    on_tpu = jax.default_backend() != "cpu"
    seq = 2048
    batch = int(os.environ.get("BENCH_BATCH", "8" if on_tpu else "1"))
    steps = int(os.environ.get("BENCH_STEPS", "60" if on_tpu else "3"))
    warmup = 5 if on_tpu else 1

    cfg = get_config("gpt2-125m", vocab_size=50257, seq_len=seq,
                     attention_impl=os.environ.get("BENCH_ATTN", "auto"),
                     layer_impl=os.environ.get("BENCH_LAYER_IMPL", "loop"),
                     remat=bool(int(os.environ.get("BENCH_REMAT", "0"))))
    mesh = make_mesh()  # all local devices on the data axis
    n_chips = len(mesh.devices.flatten())

    with use_mesh(mesh):
        state, step_fn = synthetic_state_and_step(cfg, mesh=mesh)
        toks, labels = synthetic_batch(
            cfg, batch, sharding=NamedSharding(mesh, batch_pspec()))

        # hard_sync: block_until_ready alone does not wait for execution on
        # the tunneled TPU backend (utils/sync.py), so timing anchors on a
        # value fetch that depends on the whole donated-state chain.
        for _ in range(warmup):
            state, metrics = step_fn(state, toks, labels)
        hard_sync(metrics)

        # Two timed passes, best-of: the tunneled backend occasionally
        # stalls a single pass by an order of magnitude (a one-off 12.4k
        # reading in an otherwise steady 113k+ band, ROUND_NOTES.md);
        # throughput noise on a dedicated chip only ever LOWERS a pass,
        # so max is the honest estimator and one bad pass cannot poison
        # the recorded result.
        passes = 2 if on_tpu else 1
        pass_times = []
        for _ in range(passes):
            t0 = time.perf_counter()
            for _ in range(steps):
                state, metrics = step_fn(state, toks, labels)
            hard_sync(metrics)
            pass_times.append(time.perf_counter() - t0)
        dt = min(pass_times)
        # Both pass times are recorded (ADVICE r3): best-of-N absorbs
        # one-off tunnel stalls, but a PERSISTENT gap between passes
        # (periodic recompilation, host interference on every other pass)
        # must stay visible in the artifact rather than being silently
        # reported as the optimistic tail.
        if max(pass_times) > 1.05 * dt:
            print(f"bench: pass spread {[round(t, 2) for t in pass_times]} s "
                  f"(reporting best)", file=sys.stderr, flush=True)
        assert np.isfinite(float(metrics["loss"]))

    tokens_per_sec = batch * seq * steps / dt
    per_chip = tokens_per_sec / n_chips

    # MFU makes the line honest on its own (VERDICT r4 weak #5): the
    # vs_baseline anchor is the reference's ~8.05B model on a GH200
    # (6,380 tokens/s ~= 31% of 989 bf16 TFLOP/s), while this row is a
    # 125M-class model — tokens/s across model sizes over-concludes, the
    # FLOP-normalized utilization does not.
    from fault_tolerant_llm_training_tpu.utils.metrics import (
        mfu as mfu_of,
        transformer_flops_per_token,
    )

    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(state.params))
    # Exclude the input-embedding table: the gather does no matmul FLOPs
    # (the untied LM head stays counted — its matmul is real work).
    n_matmul_params = n_params - cfg.vocab_size * cfg.dim
    flops_per_token = transformer_flops_per_token(
        n_matmul_params, seq, cfg.dim, cfg.n_layers, causal=True)
    V5E_BF16_PEAK = 197e12  # TPU v5e peak bf16 FLOP/s (public spec)
    # The peak constant is v5e-specific: only claim MFU on an actual TPU
    # backend, and emit the peak used so the number is auditable.
    chip_mfu = (mfu_of(per_chip, flops_per_token, V5E_BF16_PEAK)
                if jax.default_backend() == "tpu" else None)
    print(json.dumps({
        "metric": "tokens/sec/chip (GPT-2-125M-class, seq 2048, bf16, "
                  f"bs {batch}, full train step, backend {jax.default_backend()})",
        "value": round(per_chip, 1),
        "unit": "tokens/sec/chip",
        "vs_baseline": round(per_chip / REFERENCE_TOKENS_PER_SEC, 3),
        "vs_baseline_note": "anchor is the reference's 8.05B model on GH200 "
                            "(6,380 tokens/s, ~31% MFU); this config is "
                            "125M-class, so compare mfu, not raw tokens/s",
        "mfu": round(chip_mfu, 4) if chip_mfu is not None else None,
        "mfu_peak_flops": V5E_BF16_PEAK if chip_mfu is not None else None,
        "pass_seconds": [round(t, 3) for t in pass_times],
    }))


if __name__ == "__main__":
    try:
        main()
    except Exception:
        # The tunneled TPU backend occasionally drops a compile/execute RPC
        # (transient HTTP 500 from the remote compiler). One retry protects
        # the recorded result from a blip; a second failure is real.
        import traceback

        traceback.print_exc()
        print("bench: transient failure, retrying once",
              file=sys.stderr, flush=True)
        time.sleep(5)
        main()
