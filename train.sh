#!/bin/bash
# Slurm batch driver (ref: train.sh:1-30), TPU edition.
#
# Contract kept from the reference:
# - `sbatch train.sh [prev_jobid]` — optional positional arg becomes
#   --checkpoint-id so the chained job resumes (ref: train.sh:24-27)
# - `--signal=USR1@120` arms the pre-timeout warning (ref: train.sh:12)
# - `--no-requeue`: the framework resubmits itself (ref: train.sh:14,
#   utils.py:84)
# - default TRAINING_CMD ships with fault injection ON so every run doubles
#   as a failure-path test (ref: train.sh:21-22)
#
# TPU differences: one task per TPU host (srun spans the pod slice), no
# container directive (the image is expected to carry JAX/libtpu), and the
# headline config is the GPT-2-125M-class model from BASELINE.json.
#SBATCH --job-name=ftllm_tpu
#SBATCH --partition=normal
#SBATCH --nodes=1
#SBATCH --ntasks-per-node=1
#SBATCH --time=00:06:00
#SBATCH --output=logs/output_%j.out
#SBATCH --signal=USR1@120
#SBATCH --no-requeue

# Overridable from the environment so a scheduler-shim harness
# (scripts/demo_sbatch_chain.sh) can drive THIS script with a small
# config; the default below is the reference's own shape with fault
# injection ON (ref: train.sh:21-22). The override variable is
# namespaced (ADVICE r4): sbatch defaults to --export=ALL, so a generic
# name like TRAINING_CMD lying around an operator's shell would silently
# replace the flagship config; FTL_TRAINING_CMD_OVERRIDE cannot collide
# by accident.
TRAINING_CMD="${FTL_TRAINING_CMD_OVERRIDE:-}"
if [ -z "${TRAINING_CMD:-}" ]; then
TRAINING_CMD=" --model gpt2-125m \
               --sequence-length 2048 \
               --batch-size 1 \
               --learning-rate 5e-5 \
               --lr-warmup-steps 100 \
               --training-steps 1400 \
               --raise-error \
               --error-step 600"
fi

if [ -n "$1" ]; then
    TRAINING_CMD="$TRAINING_CMD \
     --checkpoint-id $1"
fi
export WORKDIR="${WORKDIR:-$(pwd)}"

# The resolved command is logged so an environment-supplied TRAINING_CMD
# (sbatch defaults to --export=ALL) can never silently replace the
# flagship config without a trace in logs/output_%j.out.
echo "train.sh: TRAINING_CMD=$TRAINING_CMD"
exec srun --unbuffered python "$WORKDIR/train.py" $TRAINING_CMD
