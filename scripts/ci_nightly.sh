#!/bin/bash
# Nightly CI: the heavy verification the per-commit tier-1 run skips
# (ROADMAP "chaos-in-CI cadence" follow-up).
#
# 1. slow-marked suite — chaos end-to-end through train.py, the
#    speculative and prefix-cache compiled stream-equality tests;
# 2. chaos survival campaign — the five fault classes under the
#    fake_slurm shim plus the deploy scenario (publish -> hot reload ->
#    verify drill: a live serve absorbs two publishes with requests in
#    flight, rejects a chaos-corrupted one, bit-matches a fresh
#    restore), with the per-class survival verdicts diffed against the
#    committed receipt logs/chaos_campaign.txt (goodput and MTTR
#    columns are wall-clock noisy, so only class + survived are pinned;
#    a class flipping to "no" fails the night) and the deploy drill's
#    key checks pinned line-for-line; the fleet scenario (two heartbeat-
#    leased hosts, one SIGKILLed mid-decode, the router fences it and
#    migrates its journaled requests onto the survivor with bit-exact
#    replayed continuations) is pinned the same way, as is the tiered
#    scenario (a --handoff drain ships checksummed KV-block artifacts,
#    chaos corrupts one handoff and one spill artifact, the router and
#    the survivor CRC-reject exactly the poisoned ones and fall back to
#    committed-prefix replay, all streams bit-match an unfailed
#    reference), and the disagg scenario (two dedicated prefill engines
#    stream committed KV-block shipments to a dedicated decode engine;
#    chaos SIGKILLs one prefill host mid-prompt — its requests
#    re-prefill on the surviving peer — and flips a byte in one
#    shipment, which the router CRC-rejects into committed-prefix
#    replay; zero lost, every engine drains leak-clean, and all streams
#    bit-match an unfailed colocated reference), and the kvstore
#    scenario (one host publishes a shared prompt train into the
#    fleet-global block store, chaos poisons the published artifact and
#    SIGKILLs the publisher mid-decode; cache-affinity routing still
#    placed the follow-up request with the train, overflow intake
#    landed on the cold host by slot domination, the fetching survivor
#    CRC-rejects exactly once into local recompute, the shared train's
#    content address published exactly once fleet-wide, a post-mortem
#    journal fold finds no torn state and no leaked refcounts, and all
#    streams bit-match an unfailed single-host reference);
# 3. shared_prefix decode bench — re-runs the prefix-caching scenario
#    and holds it to the committed BENCH_decode_prefix_cpu.json
#    acceptance bars: cached N=8 prefill <= 2x N=1 and
#    kv_prefix_hit_rate > 0.8 (the hit rate is deterministic and must
#    equal the receipt exactly; timings are machine-dependent);
# 4. fused_decode bench — re-runs the burst-decode scenario and pins
#    the dispatch contract from BENCH_decode_fused_cpu.json: every
#    burst-n point spends <= 1/n + eps dispatches AND host syncs per
#    token, and the fused sampling epilogue's greedy streams are
#    bit-identical to the unfused host-sampled baseline (throughput
#    numbers are machine-dependent and not pinned);
# 5. mixed_prefill bench — re-runs the packed-prefill scenario and pins
#    the BENCH_prefill_packed_cpu.json acceptance bars: packed streams
#    bit-match sequential within each kernel, decode rounds ran between
#    packed rounds, packed occupancy reached 1.0 on the full wave, and
#    packed prefill wall-clock beats sequential on the gather lane
#    (the speedup magnitude is machine-dependent; >= 1x is the bar);
# 6. tree_spec bench — re-runs the tree-vs-linear speculation sweep at
#    a fixed draft budget and pins the BENCH_decode_tree_cpu.json
#    acceptance bars: the best tree shape beats the linear k-chain on
#    accepted tokens per verify dispatch (> 1x), the exact-mode point's
#    greedy streams bit-match non-spec decode, and every point drained
#    through the strict block leak guard (acceptance magnitudes are
#    draft-noise-seeded and machine-independent only in sign, so the
#    gain bar — not its value — is pinned);
# 7. serving_load bench — re-runs the trace-driven load harness (seeded
#    poisson + bursty arrivals, spec off/on) and pins the
#    BENCH_serving_latency_cpu.json bars: zero dropped requests, every
#    point completes all 24, per-point generated-token counts equal the
#    receipt exactly (tick-based arrivals make the load deterministic),
#    and p99 TTFT/TPOT stay under loose absolute ceilings (latency
#    magnitudes are machine-dependent and not pinned);
# 8. spill_preempt bench — re-runs the spill-vs-head-of-line-wait
#    scenario and pins the BENCH_kv_spill_cpu.json bars: spill-on beats
#    spill-off on the late short request's TTFT (> 1x; the magnitude is
#    machine-dependent), at least one export+restore round-trip actually
#    happened with zero CRC rejects, and both modes' streams bit-match
#    the unconstrained reference;
# 9. kv_quant bench — re-runs the int8-vs-bf16 fixed-byte-budget
#    scenario and pins the BENCH_kv_quant_cpu.json bars: int8
#    kv_blocks_total >= 1.9x bf16 at the same pool bytes (and the
#    per-block byte ratio itself >= 1.9x), the concurrency gain at the
#    admission gate >= 1x, and the held-out-shard perplexity shift
#    stays under a 5% ceiling (greedy flips are recorded, never
#    pinned); then compiles the fused-dequant parity check at D=64 and
#    D=128 over the adversarial pool matrix and requires it green;
# 10. disagg bench — re-runs the disaggregated-vs-colocated scenario at
#    equal total slots/blocks and pins the BENCH_disagg_cpu.json bars:
#    colocated p99 decode-round latency (~TPOT) under the long-prompt
#    burst exceeds the dedicated decode engine's (> 1x; the magnitude
#    is machine-dependent), zero dropped requests on either side, and
#    the disaggregated streams bit-match the colocated ones;
# 11. global_prefix bench — re-runs the fleet-global KV store scenario
#    (N hosts, one shared long prefix) and pins the
#    BENCH_kv_store_cpu.json bars: cross-host prefix hit rate > 0.5
#    (and equal to the receipt exactly — block accounting is
#    deterministic), aggregate prefill seconds with the shared store
#    beat N independent caches (magnitude is machine-dependent; the
#    direction is the bar), zero dropped requests, zero CRC rejects
#    without chaos, and every store-fed stream bit-matches the
#    store-less reference;
# 12. fleet observability plane — (a) federation drill: two live
#    /metrics servers behind heartbeat leases (ports discovered from
#    the lease values, the real path), the aggregator's fleet rollups
#    must bit-match the per-host sums (gauges, counters, every
#    cumulative histogram bucket) with host=-labelled re-export and
#    HELP/TYPE deduped, and the CLI --once mode must render the same
#    scrape; (b) the chaos campaign's fleet post-mortem timeline
#    (postmortem_fleet.txt) must exist and its SIGKILL -> fence ->
#    migrate chain must appear in HLC (causal) order spanning both
#    hosts; (c) bench-regression sentinel: scripts/bench_trend.py green
#    over every committed BENCH_*.json, then demonstrably red (exit 3,
#    metric named) on a synthetic fixture with one pinned headline
#    metric degraded 12%.
# 13. kv transport — (a) the campaign's transport drill (chaos poisons
#    one mem-lane push's fabric metadata AND the same request's fs
#    payload, a second push takes only the mem poison: the ladder must
#    degrade mem -> fs -> committed-prefix replay with zero requests
#    lost, the frozen [KV XPORT] fallback audits present, every other
#    train landing zero-copy on the mem lane, and all streams
#    bit-matching an unfailed colocated reference) is pinned
#    line-for-line; (b) transport bench — re-runs the mem-vs-fs lane
#    scenario and pins the BENCH_kv_transport_cpu.json bars: mem-lane
#    per-train shipment landing beats the fs lane (> 1x; the magnitude
#    is machine-dependent), the staggered-prefix store asks hit
#    partially (rate > 0, deterministic and equal to the receipt), both
#    lanes' streams and the partial-hit streams bit-exact, zero
#    dropped, zero uninjected lane fallbacks.
# 14. adapter serving — (a) adapter bench: re-runs the batched
#    heterogeneous-adapter-decode vs sequential per-adapter scenario at
#    a fixed adapter-pool byte budget and pins the
#    BENCH_adapter_serving_cpu.json bars: batched beats sequential
#    (> 1x; the magnitude is machine-dependent), every stream
#    bit-matches its sequential single-tenant run, zero dropped; (b)
#    adapter publish/reject drill: a CRC-manifested adapter artifact
#    publishes through published.json's tenant->adapter sub-pointer and
#    verifies green, then one flipped payload byte must fail
#    verify_pointer naming the adapter AND be rejected at page-in with
#    the adapter pool untouched.
#
# Runs on CPU in a few minutes (tiny models, synthetic data).
set -euo pipefail

cd "$(dirname "$0")/.."
. scripts/demo_common.sh
demo_cpu_env
WORK=${CI_WORKDIR:-/tmp/ftl_ci_nightly}
rm -rf "$WORK"
mkdir -p "$WORK"

echo "== slow-marked suite"
python -m pytest tests/ -q -m slow --continue-on-collection-errors \
    -p no:cacheprovider -p no:randomly

echo "== chaos survival campaign (5 fault classes + deploy/fleet/tiered/disagg/kvstore/transport drills)"
export FAKE_SLURM_DIR="$WORK/slurm"
cat > "$WORK/requeue.sh" <<EOF
#!/bin/bash
#SBATCH --output=$WORK/slurm/requeue_%j.out
echo "requeue accepted: job \$SLURM_JOB_ID"
EOF
python scripts/chaos_campaign.py --seed 0 \
    --workdir "$WORK/campaign" \
    --sbatch "scripts/fake_slurm/sbatch $WORK/requeue.sh" \
    --out "$WORK/chaos_campaign.txt"

# survival verdicts must match the committed receipt class-for-class
extract_survival() {
    awk '/^class /{t=1; next} t && /^-+$/{next} t && NF==0{exit} t{print $1, $2}' "$1"
}
extract_survival logs/chaos_campaign.txt   > "$WORK/want.survival"
extract_survival "$WORK/chaos_campaign.txt" > "$WORK/got.survival"
if ! diff -u "$WORK/want.survival" "$WORK/got.survival"; then
    echo "FAIL: survival table drifted from committed logs/chaos_campaign.txt"
    exit 1
fi
echo "ok: survival verdicts match the committed receipt"

# the deploy drill's substance, not just its one-word verdict: both
# hot swaps carried live requests, the corrupt publish was rejected,
# and the post-swap streams bit-matched a fresh restore
for want in \
    "ok: swap 10->20 carried in-flight requests" \
    "ok: swap 20->30 carried in-flight requests" \
    "ok: corrupt publish rejected before load; serving continues on step 30" \
    "ok: post-swap streams bit-identical to a fresh restore of step 30"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: deploy drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: deploy drill (publish -> hot reload -> verify) checks present"

# the fleet migration drill's substance: the SIGKILLed host was
# declared dead and fenced, its requests were migrated with a committed
# prefix replayed, nothing was lost, the slow-but-alive host was NOT
# declared dead, the survivor drained leak-clean, and every stream
# bit-matched an unfailed single-host reference serve
for want in \
    "ok: host h0 SIGKILLed mid-decode by chaos (rc -9)" \
    "ok: router declared h0 dead and fenced it" \
    "ok: zero requests lost: all 4 served" \
    "ok: heartbeat-delayed h1 stayed under its ttl (no false dead verdict)" \
    "ok: survivor drained leak-clean and exited 0 (got rc 0)" \
    "ok: migrated streams bit-identical to the unfailed reference serve" \
    "ok: stitched trace: migrated request spans h0 and h1, replay count matches the journal committed prefix"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: fleet drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: fleet drill (lease -> dead verdict -> fence -> migrate) checks present"

# the tiered drill's substance: the --handoff drain exported checksummed
# block artifacts, chaos poisoned one handoff and one spill artifact,
# the router and the survivor CRC-rejected exactly the poisoned ones
# (falling back to committed-prefix replay), the good artifact's blocks
# were imported instead of replayed, the survivor's constrained pool
# spilled to the host tier and drained leak-clean across both tiers,
# and every stream bit-matched an unfailed reference serve
for want in \
    "ok: h0 drained via --handoff and exported both in-flight requests' blocks" \
    "ok: chaos flipped a payload byte in h0's first handoff artifact (manifest spared)" \
    "ok: router CRC-rejected exactly the corrupt artifact and shipped the other" \
    "ok: survivor imported the verified artifact's blocks instead of replaying" \
    "ok: survivor's constrained pool spilled a request to the host tier and chaos corrupted the artifact" \
    "ok: poisoned spill artifact CRC-rejected at restore and fell back to committed-prefix replay" \
    "ok: survivor drained leak-clean across device pool + spill tier and exited 0 (got rc 0)" \
    "ok: all streams (imported, replayed, spill-restored) bit-identical to the unfailed reference serve"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: tiered drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: tiered drill (handoff export -> CRC gate -> import-or-replay, spill -> reject -> replay) checks present"

# the disagg drill's substance: a prefill engine was SIGKILLed
# mid-prompt and its requests re-prefilled on the surviving prefill
# peer, chaos poisoned one of the survivor's block shipments and the
# router CRC-rejected exactly that one into committed-prefix replay,
# every request decoded on the dedicated decode engine, both surviving
# engines drained leak-clean, and all streams bit-matched an unfailed
# colocated reference serve
for want in \
    "ok: prefill host pre0 SIGKILLed mid-prompt by chaos (rc -9)" \
    "ok: router declared pre0 dead and fenced it" \
    "ok: dead host's mid-prompt requests re-prefilled on the surviving prefill peer" \
    "ok: chaos flipped a payload byte in one of pre1's shipments (manifest spared)" \
    "ok: router CRC-rejected exactly the poisoned shipment" \
    "ok: every request handed to the decode engine exactly once" \
    "ok: zero requests lost: all 4 served" \
    "ok: all four streams decoded on the dedicated decode engine" \
    "ok: prefill survivor drained leak-clean and exited 0" \
    "ok: decode engine drained leak-clean and exited 0" \
    "ok: disaggregated streams (shipped-block imports and the CRC-reject replay alike) bit-identical to the unfailed colocated reference" \
    "ok: stitched trace: all four requests flagged disaggregated with the decode host on the critical path"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: disagg drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: disagg drill (prefill kill -> re-prefill, ship corrupt -> CRC reject -> replay, decode placement) checks present"

# the kvstore drill's substance: the publisher's train was poisoned and
# the publisher SIGKILLed, cache-affinity placement still landed the
# follow-up request with the published train, the fetching host
# CRC-rejected exactly once into local recompute, exactly one publish
# happened fleet-wide (content-address dedup), nothing was lost, no
# torn store state survived the kill, and every stream bit-matched an
# unfailed single-host reference serve
for want in \
    "ok: h0 published the shared train to the fleet store" \
    "ok: chaos poisoned the published store artifact (manifest spared)" \
    "ok: publishing host h0 SIGKILLed mid-decode (rc -9)" \
    "ok: cache-affinity placement: req1 landed with the published train on h0" \
    "ok: free slots dominate affinity: overflow intake landed on the cold host h1" \
    "ok: content-address dedup: shared prompt train published exactly once fleet-wide, by h0" \
    "ok: exactly one CRC reject, on h1, degrading to local recompute (got 1)" \
    "ok: zero requests lost: all 4 served" \
    "ok: store post-mortem: exactly the one poisoned train fails CRC" \
    "ok: no leaked store refcounts: every journaled fetch ref was released" \
    "ok: store-fetched, reject-recomputed and migrated streams all bit-identical to the unfailed single-host reference serve"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: kvstore drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: kvstore drill (publish -> poison -> affinity place -> CRC reject -> recompute) checks present"

# the transport drill's substance: one pushed train lost BOTH its mem
# metadata and its fs payload (ladder bottoms out at replay), a second
# lost only its mem metadata (one rung down, onto the fs artifact),
# the untouched trains landed zero-copy on the mem lane, the frozen
# [KV XPORT] fallback audit fired for both poisoned trains, nothing
# was lost or leaked, and every stream bit-matched an unfailed
# colocated reference
for want in \
    "ok: chaos poisoned exactly the first mem push's fabric metadata (mem_corrupt, ordinal 0)" \
    "ok: every exported train was pushed to the shared fabric" \
    "ok: zero requests lost: decode completed 4/4 across all three degradation rungs" \
    "ok: all decode streams — mem-landed, fs-degraded and replayed alike — bit-identical to the unfailed colocated reference" \
    "ok: untouched trains landed zero-copy on the mem lane" \
    "ok: degradation ladder: two mem->fs fallbacks, one of which fell through to replay (fallbacks 2, rejects 1)" \
    "ok: audit trail: [KV XPORT] fallback lane mem logged for both poisoned trains (got 2)" \
    "ok: no leaked KV blocks on either role after the ladder"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: transport drill check missing from report: $want"
        exit 1
    fi
done
echo "ok: transport drill (mem poison -> fs artifact -> committed-prefix replay, zero loss) checks present"

echo "== shared_prefix bench vs committed receipt"
python scripts/decode_bench.py --scenario shared_prefix \
    --out "$WORK/bench_prefix.json"
python - "$WORK/bench_prefix.json" BENCH_decode_prefix_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
ratio = got["prefill_ratio_n8_vs_n1_cached"]
rate = got["kv_prefix_hit_rate_n8"]
assert ratio <= 2.0, f"cached N8/N1 prefill {ratio}x > 2x acceptance bar"
assert rate > 0.8, f"kv_prefix_hit_rate {rate} <= 0.8 acceptance bar"
assert rate == want["kv_prefix_hit_rate_n8"], (
    f"hit rate is workload-deterministic: got {rate}, "
    f"receipt {want['kv_prefix_hit_rate_n8']}")
print(f"ok: cached N8/N1 prefill {ratio:.2f}x (<= 2x), "
      f"hit rate {rate:.3f} (> 0.8, matches receipt)")
EOF

echo "== fused_decode bench vs committed receipt"
python scripts/decode_bench.py --scenario fused_decode --requests 8 \
    --out "$WORK/bench_fused.json"
python - "$WORK/bench_fused.json" BENCH_decode_fused_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
EPS = 0.05
for p in got["points"]:
    n = p["burst"]
    for key in ("dispatches_per_token", "host_syncs_per_token"):
        assert p[key] <= 1.0 / n + EPS, (
            f"{p['kernel']} burst={n}: {key} {p[key]} > 1/{n} + {EPS}")
    assert p["bit_match_burst1"], (
        f"{p['kernel']} burst={n} stream diverged from per-token decode")
assert got["fused_bit_match_host_sampler"], (
    "fused epilogue greedy streams diverged from host-sampled baseline")
assert want["fused_bit_match_host_sampler"], "committed receipt is stale"
worst = max(p["dispatches_per_token"] for p in got["points"]
            if p["burst"] == max(got["burst_ns"]))
print(f"ok: burst {got['burst_ns']} dispatches/token bounded by 1/n + "
      f"{EPS} (worst at n={max(got['burst_ns'])}: {worst}), fused == "
      f"host-sampled bitwise")
EOF

echo "== mixed_prefill bench vs committed receipt"
python scripts/decode_bench.py --scenario mixed_prefill \
    --out "$WORK/bench_packed.json"
python - "$WORK/bench_packed.json" BENCH_prefill_packed_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
for p in got["points"]:
    assert p["streams_bitmatch_sequential"], (
        f"{p['kernel']} {p['mode']}: packed diverged from sequential")
    if p["mode"] == "packed":
        assert p["packed_occupancy"] == 1.0, (
            f"{p['kernel']}: full-wave occupancy {p['packed_occupancy']} "
            f"< 1.0")
        assert p["prefill_speedup_vs_sequential"] >= 1.0, (
            f"{p['kernel']}: packed prefill slower than sequential "
            f"({p['prefill_speedup_vs_sequential']}x)")
    expect_inplace = p["prefill_chunks"] if p["kernel"] == "pallas" else 0
    assert p["prefill_inplace_chunks"] == expect_inplace, (
        f"{p['kernel']} {p['mode']}: in-place chunk counter "
        f"{p['prefill_inplace_chunks']} != {expect_inplace} — the wrong "
        f"kernel served the chunks")
assert got["decode_between_packed_rounds"], (
    "no decode round ran between packed prefill rounds")
assert want["decode_between_packed_rounds"], "committed receipt is stale"
print(f"ok: packed == sequential bitwise on both kernels, gather lane "
      f"{got['value']}x sequential prefill (>= 1x), decode interleaved "
      f"with packed rounds")
EOF

echo "== tree_spec bench vs committed receipt"
python scripts/decode_bench.py --scenario tree_spec --vocab-size 64 \
    --out "$WORK/bench_tree.json"
python - "$WORK/bench_tree.json" BENCH_decode_tree_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
assert got["value"] > 1.0, (
    f"best tree shape ({got['best_shape']}) no longer beats the linear "
    f"k-chain: {got['value']}x accepted/round at equal draft budget")
for p in got["points"]:
    assert p["leak_guard_clean"], (
        f"{p['shape']}/{p['verify_impl']}: drain left leaked KV blocks")
    if p["verify_impl"] == "exact":
        assert p["bit_match_greedy"] and p["mismatched_streams"] == 0, (
            f"exact-mode tree point diverged from non-spec decode "
            f"({p['mismatched_streams']} stream(s))")
assert any(p["verify_impl"] == "exact" for p in got["points"]), (
    "sweep lost its exact-mode bit-exactness point")
assert want["value"] > 1.0, "committed receipt is stale"
best = max((p for p in got["points"] if p["verify_impl"] == "chunk"
            and p["shape"] != "linear"),
           key=lambda p: p["accepted_per_round"])
print(f"ok: tree {got['best_shape']} {got['value']}x linear accepted/"
      f"round at budget {got['draft_budget']} (branch util "
      f"{best['branch_utilization']}), exact point bitwise == non-spec, "
      f"all drains leak-clean")
EOF

echo "== serving_load bench vs committed receipt"
python scripts/decode_bench.py --scenario serving_load --vocab-size 64 \
    --requests 24 --out "$WORK/bench_serving.json"
python - "$WORK/bench_serving.json" BENCH_serving_latency_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
TTFT_CEIL_MS, TPOT_CEIL_MS = 2000.0, 200.0
assert got["dropped_total"] == 0, (
    f"load harness dropped {got['dropped_total']} request(s)")
want_pts = {(p["process"], p["spec"]): p for p in want["points"]}
for p in got["points"]:
    key = (p["process"], p["spec"])
    w = want_pts[key]
    assert p["requests_completed"] == 24, (
        f"{key}: only {p['requests_completed']}/24 requests completed")
    assert p["tokens_generated"] == w["tokens_generated"], (
        f"{key}: tick-seeded load is deterministic: generated "
        f"{p['tokens_generated']} tokens, receipt {w['tokens_generated']}")
    assert p["ttft_p99_ms"] <= TTFT_CEIL_MS, (
        f"{key}: p99 TTFT {p['ttft_p99_ms']} ms > {TTFT_CEIL_MS} ms ceiling")
    assert p["tpot_p99_ms"] <= TPOT_CEIL_MS, (
        f"{key}: p99 TPOT {p['tpot_p99_ms']} ms > {TPOT_CEIL_MS} ms ceiling")
worst = max(p["ttft_p99_ms"] for p in got["points"])
print(f"ok: serving load 4/4 points completed 24/24 (0 dropped), token "
      f"counts match receipt, worst p99 TTFT {worst} ms (<= "
      f"{TTFT_CEIL_MS:.0f} ms), p99 TPOT under {TPOT_CEIL_MS:.0f} ms")
EOF

echo "== spill_preempt bench vs committed receipt"
python scripts/decode_bench.py --scenario spill_preempt \
    --out "$WORK/bench_spill.json"
python - "$WORK/bench_spill.json" BENCH_kv_spill_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
assert got["bit_exact_vs_unconstrained"], (
    "constrained streams (spill off or on) diverged from the "
    "unconstrained reference")
assert got["value"] > 1.0, (
    f"spill-on no longer beats head-of-line wait on late-request TTFT "
    f"({got['value']}x)")
on = got["spill_on"]
assert on["spill_exports"] >= 1 and on["spill_restores"] >= 1, (
    f"spill-on point never round-tripped a block artifact "
    f"(exports {on['spill_exports']}, restores {on['spill_restores']})")
assert on["spill_rejects"] == 0, (
    f"{on['spill_rejects']} spill artifact(s) CRC-rejected without chaos")
assert got["spill_off"]["spill_exports"] == 0, (
    "spill-off baseline exported blocks — the A/B is contaminated")
assert want["bit_exact_vs_unconstrained"], "committed receipt is stale"
print(f"ok: spill-on {got['value']}x spill-off on late-request TTFT "
      f"(> 1x), {on['spill_exports']} export(s)/{on['spill_restores']} "
      f"restore(s), 0 rejects, streams bit-exact vs unconstrained")
EOF

echo "== kv_quant bench vs committed receipt"
python scripts/decode_bench.py --scenario kv_quant \
    --out "$WORK/bench_kv_quant.json"
python - "$WORK/bench_kv_quant.json" BENCH_kv_quant_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
PPL_REL_CEIL = 0.05
assert got["blocks_ratio"] >= 1.9, (
    f"int8 pool holds only {got['blocks_ratio']}x the bf16 blocks at the "
    f"same byte budget (>= 1.9x acceptance bar)")
assert got["bytes_per_block_ratio"] >= 1.9, (
    f"int8 bytes/block ratio {got['bytes_per_block_ratio']} < 1.9x — the "
    f"scale-pool overhead grew")
assert got["concurrency_gain"] >= 1.0, (
    f"extra int8 blocks bought no concurrency at the admission gate "
    f"({got['concurrency_gain']}x)")
ppl = got["held_out_perplexity"]
assert abs(ppl["perplexity_rel_delta"]) <= PPL_REL_CEIL, (
    f"held-out perplexity moved {ppl['perplexity_rel_delta']:+.4f} "
    f"under int8 KV (|delta| ceiling {PPL_REL_CEIL})")
assert want["blocks_ratio"] >= 1.9, "committed receipt is stale"
print(f"ok: int8 {got['blocks_ratio']}x blocks at "
      f"{got['pool_budget_bytes']} pool bytes (bytes/block "
      f"{got['bytes_per_block_ratio']}x), concurrency "
      f"{got['concurrency_gain']}x, held-out perplexity delta "
      f"{ppl['perplexity_rel_delta']:+.4f} (|ceil| {PPL_REL_CEIL})")
EOF

echo "== disagg bench vs committed receipt"
python scripts/decode_bench.py --scenario disagg \
    --out "$WORK/bench_disagg.json"
python - "$WORK/bench_disagg.json" BENCH_disagg_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
ratio = got["decode_p99_tpot_interference_ratio"]
assert ratio > 1.0, (
    f"disaggregation bought nothing: colocated/disagg p99 decode-round "
    f"ratio {ratio}x (must beat colocated at equal total capacity)")
assert got["dropped"] == 0, (
    f"{got['dropped']} request(s) dropped under the disagg split")
assert got["bit_exact"], (
    "disaggregated streams diverged from the colocated reference — the "
    "shipped-block import path is no longer bit-exact")
assert got["split"]["prefill_slots"] + got["split"]["decode_slots"] \
    == got["slots_total"], "split does not sum to the colocated capacity"
assert want["decode_p99_tpot_interference_ratio"] > 1.0 \
    and want["bit_exact"], "committed receipt is stale"
print(f"ok: disagg decode p99 {ratio}x better than colocated under the "
      f"long-prompt burst ({got['requests']} requests, "
      f"{got['split']['prefill_slots']}+{got['split']['decode_slots']} "
      f"vs {got['slots_total']} slots, "
      f"{got['disaggregated']['shipments_per_long_request']} shipments "
      f"per long request), 0 dropped, bit-exact")
EOF

echo "== global_prefix bench vs committed receipt"
python scripts/decode_bench.py --scenario global_prefix \
    --out "$WORK/bench_kvstore.json"
python - "$WORK/bench_kvstore.json" BENCH_kv_store_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
rate = got["cross_host_hit_rate"]
assert rate > 0.5, (
    f"cross-host prefix hit rate {rate} <= 0.5 acceptance bar — the "
    f"shared store no longer serves the fleet's common prefix")
assert rate == want["cross_host_hit_rate"], (
    f"hit rate is block-accounting-deterministic: got {rate}, "
    f"receipt {want['cross_host_hit_rate']}")
assert got["aggregate_prefill_seconds_store"] \
    < got["aggregate_prefill_seconds_independent"], (
    f"shared store aggregate prefill "
    f"{got['aggregate_prefill_seconds_store']}s no longer beats "
    f"{got['hosts']} independent caches "
    f"({got['aggregate_prefill_seconds_independent']}s)")
assert got["dropped"] == 0, (
    f"{got['dropped']} request(s) dropped under the store path")
assert got["store_rejects"] == 0, (
    f"{got['store_rejects']} store artifact(s) CRC-rejected without "
    f"chaos")
assert got["store_fetches"] >= got["hosts"] - 1, (
    f"only {got['store_fetches']} cross-host fetches for "
    f"{got['hosts']} hosts — the store never actually fed the fleet")
assert got["bit_exact"], (
    "store-fed streams diverged from the store-less reference")
assert want["bit_exact"] and want["dropped"] == 0, (
    "committed receipt is stale")
speedup = (got["aggregate_prefill_seconds_independent"]
           / got["aggregate_prefill_seconds_store"])
print(f"ok: fleet store cross-host hit rate {rate} (> 0.5, matches "
      f"receipt), aggregate prefill {speedup:.2f}x faster than "
      f"{got['hosts']} independent caches, "
      f"{got['store_publishes']} publish(es)/"
      f"{got['store_fetches']} fetch(es), 0 rejects, 0 dropped, "
      f"bit-exact")
EOF

echo "== kv transport bench vs committed receipt"
python scripts/decode_bench.py --scenario transport \
    --out "$WORK/bench_transport.json"
python - "$WORK/bench_transport.json" BENCH_kv_transport_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
speedup = got["mem_lane_landing_speedup"]
assert speedup > 1.0, (
    f"mem lane bought nothing: fs/mem per-train landing ratio "
    f"{speedup}x (zero-copy landing must beat re-reading artifacts)")
assert got["bit_exact"], (
    "transported streams diverged — a lane or the partial-hit path is "
    "no longer bit-exact against its reference")
assert got["dropped"] == 0, (
    f"{got['dropped']} request(s) dropped across the lanes")
assert got["lane_fallbacks"] == 0, (
    f"{got['lane_fallbacks']} mem->fs fallback(s) without chaos — the "
    f"metadata verify is rejecting clean trains")
rate = got["partial_hit_rate"]
assert rate > 0, (
    f"partial hit rate {rate}: staggered prefix asks never landed as "
    f"sub-train hits")
assert rate == want["partial_hit_rate"], (
    f"partial-hit rate is block-accounting-deterministic: got {rate}, "
    f"receipt {want['partial_hit_rate']}")
assert got["partial_hits"]["streams_bit_exact"], (
    "partial-hit streams diverged from the storeless reference")
assert want["mem_lane_landing_speedup"] > 1.0 and want["bit_exact"] \
    and want["dropped"] == 0, "committed receipt is stale"
print(f"ok: mem lane lands trains {speedup}x faster than the fs lane "
      f"(fs {got['shipment_landing']['fs_ms_per_train']} ms -> mem "
      f"{got['shipment_landing']['mem_ms_per_train']} ms per train), "
      f"partial hit rate {rate} (matches receipt), "
      f"{got['requests']} requests/lane, 0 dropped, 0 fallbacks, "
      f"bit-exact")
EOF

echo "== fused-dequant parity check (int8 KV, D=64/128)"
python - <<'EOF'
import sys

sys.path.insert(0, ".")
from scripts.kernel_checks import check_quantized_decode_parity

ok = check_quantized_decode_parity()
ok &= check_quantized_decode_parity(h=8, kv=4, d=128)
assert ok, "quantized decode parity check failed"
print("ok: fused-dequant kernels within error bounds at D=64 and D=128")
EOF

echo "== adapter serving bench vs committed receipt"
python scripts/decode_bench.py --scenario adapter_serving \
    --out "$WORK/bench_adapter.json"
python - "$WORK/bench_adapter.json" BENCH_adapter_serving_cpu.json <<'EOF'
import json
import sys

got = json.load(open(sys.argv[1]))
want = json.load(open(sys.argv[2]))
speedup = got["batched_vs_sequential_speedup"]
assert speedup > 1.0, (
    f"heterogeneous batching bought nothing: batched/sequential wall "
    f"ratio {speedup}x at fixed pool bytes")
assert got["bit_exact"], (
    "batched adapter streams diverged from their sequential "
    "single-tenant runs — the fused adapter lane is no longer "
    "bit-exact")
assert got["dropped"] == 0, (
    f"{got['dropped']} request(s) dropped across the modes")
assert got["adapters"] >= 3 and got["pool_bytes"] == want["pool_bytes"], (
    "the fixed-pool-budget comparison drifted from the receipt's "
    "geometry")
assert want["batched_vs_sequential_speedup"] > 1.0 \
    and want["bit_exact"] and want["dropped"] == 0, (
    "committed receipt is stale")
print(f"ok: batched heterogeneous-adapter decode beats sequential "
      f"per-adapter serving {speedup}x at fixed pool bytes "
      f"({got['pool_bytes']} B, {got['adapters']} adapters + null, "
      f"{got['requests']} requests), bit-exact, 0 dropped")
EOF

echo "== adapter publish/reject drill (verified sub-pointer, corrupt page-in)"
ADPT_DIR="$WORK/adapter_drill"
rm -rf "$ADPT_DIR"
mkdir -p "$ADPT_DIR"
python - "$ADPT_DIR" <<'EOF'
import os
import sys

sys.path.insert(0, ".")
root = sys.argv[1]

from fault_tolerant_llm_training_tpu.checkpoint.manager import (
    write_manifest)
from fault_tolerant_llm_training_tpu.deploy.publish import (
    Publisher, adapter_pointer, verify_pointer)
from fault_tolerant_llm_training_tpu.inference.adapters import (
    AdapterIntegrityError, AdapterLayout, AdapterManager,
    init_adapter_factors, write_adapter_artifact)
from fault_tolerant_llm_training_tpu.models.configs import get_config

cfg = get_config("tiny", vocab_size=64, layer_impl="loop")
layout = AdapterLayout.from_cfg(cfg, 4)

step_dir = os.path.join(root, "checkpoint_pub", "20")
os.makedirs(step_dir)
with open(os.path.join(step_dir, "payload.bin"), "wb") as fh:
    fh.write(b"weights" * 64)
write_manifest(step_dir, 20)

facts = init_adapter_factors(layout, seed=3, scale=0.5)
ent = write_adapter_artifact(root, "tenant-a", 20, facts, rank=4,
                             alpha=32.0)
art = os.path.join(root, ent["path"])
sub = adapter_pointer(root, "tenant-a", art)
assert sub is not None and sub["rank"] == 4
ptr = Publisher(root, "pub").publish(20, adapters={"tenant-a": sub})
assert ptr is not None
assert verify_pointer(root, ptr) == (True, "ok")
print("ok: adapter artifact published as a tenant sub-pointer and "
      "verified green (manifest digest + per-file CRC)")

victim = sorted(f for f in os.listdir(art) if f.endswith(".npy"))[0]
with open(os.path.join(art, victim), "r+b") as fh:
    fh.seek(-1, os.SEEK_END)
    b = fh.read(1)
    fh.seek(-1, os.SEEK_END)
    fh.write(bytes([b[0] ^ 0xFF]))
ok, detail = verify_pointer(root, ptr)
assert not ok and "adapter tenant-a" in detail, detail
print("ok: one flipped payload byte fails verify-before-load naming "
      "the adapter")

written = []
mgr = AdapterManager(layout, 2 * layout.pages_per_adapter + 1,
                     lambda rows, pages: written.append(rows))
mgr.register("tenant-a", art)
try:
    mgr.page_in("tenant-a")
    raise AssertionError("corrupt artifact paged in")
except AdapterIntegrityError:
    pass
assert mgr.allocator.used_count == 0 and not written
print("ok: corrupt adapter rejected at page-in with the adapter pool "
      "untouched (0 pages allocated, 0 pages written)")
EOF

echo "== fleet metrics federation drill (2 hosts -> rollups == per-host sums)"
FED_DIR="$WORK/feddrill"
rm -rf "$FED_DIR"
mkdir -p "$FED_DIR"
python - "$FED_DIR" <<'EOF'
import sys
import urllib.request

sys.path.insert(0, ".")
from fault_tolerant_llm_training_tpu.ft.lease import (FileKVStore,
                                                      LeaseRegistry)
from fault_tolerant_llm_training_tpu.obs import federate
from fault_tolerant_llm_training_tpu.obs.federate import (
    Federator, parse_metrics_text)
from fault_tolerant_llm_training_tpu.obs.prometheus import MetricsServer
from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

root = sys.argv[1]
store = FileKVStore(root + "/store")
specs = {"h0": (12.5, 128, [0.03, 0.08, 0.4]),
         "h1": (30.0, 320, [0.06, 0.9])}
servers, per_host = [], {}
for host, (tps, tok, ttfts) in sorted(specs.items()):
    reg = MetricRegistry()
    reg.gauge("ftl_serve_tokens_per_sec", "decode throughput").set(tps)
    reg.counter("ftl_serve_tokens_generated_total", "tokens").inc(tok)
    hist = reg.histogram("ftl_serve_ttft_seconds", "ttft")
    for v in ttfts:
        hist.observe(v)
    srv = MetricsServer(registry=reg, port=0)
    port = srv.start()
    servers.append(srv)
    LeaseRegistry(store, host_id=host).renew(
        slots_free=4, blocks_free=64, block_size=16, metrics_port=port)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
        per_host[host] = parse_metrics_text(
            resp.read().decode("utf-8"))

# the aggregator discovers the ports from the lease values and scrapes
# the same endpoints over loopback — the real path, no injection
fed = Federator(root + "/store", slo_ttft_ms=100.0)
text = fed.render()
with open(root + "/federated.txt", "w") as fh:
    fh.write(text)
meta, samples = parse_metrics_text(text)
got = {}
for name, labels, value in samples:
    got.setdefault(name, []).append((labels, value))


def host_sum(sample_name):
    return sum(v for _m, ss in per_host.values()
               for n, lb, v in ss if n == sample_name)


assert got["fleet_hosts_live"][0][1] == 2
assert got["fleet_hosts_scraped"][0][1] == 2
assert got["fleet_scrape_failures_total"][0][1] == 0
# bit-match: the rollups ARE the per-host sums, not approximations
assert got["fleet_tokens_per_sec"][0][1] \
    == host_sum("ftl_serve_tokens_per_sec") == 42.5
assert got["fleet_ftl_serve_tokens_generated_total"][0][1] \
    == host_sum("ftl_serve_tokens_generated_total") == 448
assert got["fleet_ttft_seconds_count"][0][1] \
    == host_sum("ftl_serve_ttft_seconds_count") == 5
assert got["fleet_ttft_seconds_sum"][0][1] \
    == round(host_sum("ftl_serve_ttft_seconds_sum"), 9)
fleet_buckets = {lb["le"]: v
                 for lb, v in got["fleet_ttft_seconds_bucket"]}
for le, v in fleet_buckets.items():
    per = sum(val for _m, ss in per_host.values()
              for n, lb, val in ss
              if n == "ftl_serve_ttft_seconds_bucket"
              and lb["le"] == le)
    assert v == per, f"bucket le={le}: fleet {v} != per-host sum {per}"
# every per-host series is re-exported with a host= label
hosts = {lb["host"] for lb, _v in got["ftl_serve_tokens_per_sec"]}
assert hosts == {"h0", "h1"}
# HELP/TYPE exactly once per family across both hosts
for line in ("# TYPE ftl_serve_ttft_seconds histogram",
             "# TYPE ftl_serve_tokens_per_sec gauge",
             "# TYPE fleet_ttft_seconds histogram"):
    assert text.count(line) == 1, line
# 3 of 5 requests under the 100 ms SLO bar at bucket resolution
slo = {lb["slo"]: v for lb, v in got["fleet_slo_attainment"]}
assert slo["ttft"] == 0.6, slo
# the CLI --once path renders the identical scrape (modulo lease age)
rc = federate.main(["--store", root + "/store", "--once",
                    "--out", root + "/federated_cli.txt"])
assert rc == 0
cli = open(root + "/federated_cli.txt").read()
assert "fleet_tokens_per_sec 42.5" in cli
assert "fleet_hosts_live 2" in cli
for srv in servers:
    srv.stop()
print("ok: federation drill — fleet rollups bit-match the per-host "
      "sums (tokens/s 42.5, counters 448, ttft count 5, every "
      "cumulative bucket), host= re-export + deduped headers, "
      "SLO attainment 0.6, CLI --once green")
EOF

echo "== chaos post-mortem timeline (fleet scenario, HLC causal order)"
if ! test -s "$WORK/campaign/seed0/postmortem_fleet.txt"; then
    echo "FAIL: campaign did not emit postmortem_fleet.txt"
    exit 1
fi
for want in \
    "ok: post-mortem timeline generated from the scenario's event/trace/journal trails" \
    "ok: post-mortem annotates the chaos kill, the fence verdict and the migration" \
    "ok: SIGKILL -> fence -> migrate chain appears in HLC (causal) order in the post-mortem timeline" \
    "ok: the annotated kill belongs to host h0's trail" \
    "ok: the timeline spans the surviving host's trail too"
do
    if ! grep -qF "$want" "$WORK/chaos_campaign.txt"; then
        echo "FAIL: fleet post-mortem check missing from report: $want"
        exit 1
    fi
done
echo "ok: fleet post-mortem (SIGKILL -> fence -> migrate in HLC order) checks present"

echo "== bench-regression sentinel (committed receipts, then a synthetic regression)"
python scripts/bench_trend.py --no-history
# a 12% drop in a pinned higher-is-better headline metric must fail
# with exit 3 and name the metric
SENT_DIR="$WORK/bench_sentinel"
rm -rf "$SENT_DIR"
mkdir -p "$SENT_DIR"
python - "$SENT_DIR" <<'EOF'
import json
import sys

src = json.load(open("BENCH_disagg_cpu.json"))
src["value"] = round(src["value"] * 0.88, 6)
json.dump(src, open(sys.argv[1] + "/BENCH_disagg_cpu.json", "w"))
EOF
rc=0
python scripts/bench_trend.py --no-history \
    --current-dir "$SENT_DIR" > "$SENT_DIR/verdict.txt" || rc=$?
if [ "$rc" -ne 3 ]; then
    echo "FAIL: sentinel exited $rc on a 12% regression (want 3)"
    exit 1
fi
if ! grep -q "REGRESSION: BENCH_disagg_cpu.json value" "$SENT_DIR/verdict.txt"; then
    echo "FAIL: sentinel did not name the regressed metric"
    cat "$SENT_DIR/verdict.txt"
    exit 1
fi
echo "ok: bench sentinel green on committed receipts, red (exit 3, metric named) on the synthetic regression"

echo "OK: nightly green (slow suite, chaos survival, fleet migration, tiered handoff+spill, prefix bench, fused decode, packed prefill, tree spec, serving latency, kv spill, kv quant + parity, disagg, fleet kv store, kv transport, adapter serving + publish drill, federation drill, fleet post-mortem, bench sentinel)"
