"""A/B one train-step variant at the headline bench shape and print tokens/s.

Same methodology as bench.py (mesh, donation, hard_sync, best-of-N passes)
but parameterized so MFU experiments can be compared on the chip:

    python scripts/mfu_sweep.py --set fused_qkv=1
    python scripts/mfu_sweep.py --set rope_impl=xla qkv_layout=bhsd
    python scripts/mfu_sweep.py --ce-block 8192
    python scripts/mfu_sweep.py --force-fused-ce

NOTE: qkv_layout only matters under rope_impl=xla — the default fused
rope supersedes it (models/configs.py).

Prints one line: ``variant=<tag> tokens_per_sec=<N> ms_per_step=<N>``.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def parse_val(v: str):
    for cast in (int, float):
        try:
            return cast(v)
        except ValueError:
            pass
    return v


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--set", nargs="*", default=[], metavar="KEY=VAL",
                   help="TransformerConfig overrides (int/float/str coerced)")
    p.add_argument("--model", default="gpt2-125m")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--steps", type=int, default=60)
    p.add_argument("--passes", type=int, default=2)
    p.add_argument("--ce-block", type=int, default=None,
                   help="force the vocab-blocked CE with this block size")
    p.add_argument("--force-fused-ce", action="store_true",
                   help="force the fused head+CE dispatch (AUTO_MIN_BYTES=0)")
    p.add_argument("--tiles", default=None,
                   help="flash tile override 'fq,fk,dqq,dqk,dkq,dkk'")
    args = p.parse_args()

    import jax
    from jax.sharding import NamedSharding

    from fault_tolerant_llm_training_tpu.models import get_config
    from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
    from fault_tolerant_llm_training_tpu.parallel.sharding import batch_pspec
    from fault_tolerant_llm_training_tpu.utils.harness import (
        synthetic_batch,
        synthetic_state_and_step,
    )
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    overrides = dict(kv.split("=", 1) for kv in args.set)
    overrides = {k: parse_val(v) for k, v in overrides.items()}
    if args.ce_block is not None:
        import functools

        from fault_tolerant_llm_training_tpu.training import step as step_mod
        orig = step_mod.cross_entropy_loss
        step_mod.cross_entropy_loss = functools.partial(
            orig, ce_block=args.ce_block)
    if args.force_fused_ce:
        from fault_tolerant_llm_training_tpu.ops import fused_ce
        fused_ce.AUTO_MIN_BYTES = 0
        from fault_tolerant_llm_training_tpu.ops import cross_entropy
        cross_entropy.AUTO_THRESHOLD = 0

    if args.tiles:
        from fault_tolerant_llm_training_tpu.ops import flash_attention as fa
        (fa.FWD_BLOCK_Q, fa.FWD_BLOCK_K, fa.DQ_BLOCK_Q, fa.DQ_BLOCK_K,
         fa.DKV_BLOCK_Q, fa.DKV_BLOCK_K) = map(int, args.tiles.split(","))

    base = dict(vocab_size=50257, seq_len=2048)
    base.update(overrides)  # --set may override vocab_size/seq_len too
    cfg = get_config(args.model, **base)
    mesh = make_mesh()
    with use_mesh(mesh):
        state, step_fn = synthetic_state_and_step(cfg, mesh=mesh,
                                                  grad_accum=args.grad_accum)
        toks, labels = synthetic_batch(
            cfg, args.batch_size, sharding=NamedSharding(mesh, batch_pspec()))
        for _ in range(5):
            state, metrics = step_fn(state, toks, labels)
        hard_sync(metrics)
        dt = float("inf")
        for _ in range(args.passes):
            t0 = time.perf_counter()
            for _ in range(args.steps):
                state, metrics = step_fn(state, toks, labels)
            hard_sync(metrics)
            dt = min(dt, time.perf_counter() - t0)
        loss = float(metrics["loss"])
    assert loss == loss, "nonfinite loss"
    tag = ",".join(args.set) or "base"
    if args.ce_block is not None:
        tag += f",ce_block={args.ce_block}"
    if args.force_fused_ce:
        tag += ",fused_ce"
    if args.tiles:
        tag += f",tiles={args.tiles}"
    if args.grad_accum > 1:
        tag += f",accum={args.grad_accum}"
    tps = args.batch_size * cfg.seq_len * args.steps / dt
    print(f"variant={tag} tokens_per_sec={tps:.0f} "
          f"ms_per_step={dt / args.steps * 1000:.2f} loss={loss:.4f}",
          flush=True)


if __name__ == "__main__":
    main()
