"""Continuous-batching decode throughput/latency bench (inference/).

Builds an InferenceEngine (random params by default, or a real checkpoint
via --checkpoint-path/--checkpoint-job-id), drives the scheduler with
synthetic concurrent requests, and writes a BENCH_decode_*.json receipt
with the serving headline numbers: tokens/sec, tokens/sec/slot, p50/p95
per-decode-iteration latency, and (paged layout) block-pool utilization.

Two scenarios:

- ``uniform`` (default): N identical requests, the steady-state decode
  number. Writes BENCH_decode_<model>_<backend>.json.
- ``long_context``: mixed short/long prompts where the long prompts EXCEED
  the largest prefill bucket (chunked prefill) and the paged pool holds the
  SAME cache memory budget as a ring config — the receipt shows the paged
  layout sustaining more concurrent requests at fixed HBM. Runs BOTH
  layouts and writes BENCH_decode_paged_<backend>.json.

Engine builds AOT-compile every bucket, so the JAX persistent compilation
cache is enabled by default (--compile-cache-dir '' disables); the receipt
records cold-vs-warm build seconds (the warm number is what a restarted
server actually pays).

Run on the chip:  python scripts/decode_bench.py --model tiny --slots 8
CPU smoke:        JAX_PLATFORMS=cpu python scripts/decode_bench.py
Long context:     JAX_PLATFORMS=cpu python scripts/decode_bench.py \
                      --scenario long_context
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_stream(engine, requests, eos=None):
    """Drive one request list through a fresh Scheduler; returns metrics."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    sched = Scheduler(engine, eos_token_id=eos)
    for r in requests:
        sched.submit(r)
    t0 = time.monotonic()
    sched.run()
    m = sched.metrics()
    m["wall_seconds"] = time.monotonic() - t0
    return m


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny")
    p.add_argument("--vocab-size", type=int, default=0)
    p.add_argument("--layer-impl", default="loop", choices=("loop", "scan"))
    p.add_argument("--scenario", default="uniform",
                   choices=("uniform", "long_context", "spec_decode",
                            "shared_prefix", "fused_decode",
                            "mixed_prefill", "tree_spec", "serving_load",
                            "spill_preempt", "kv_quant", "disagg",
                            "global_prefix", "transport",
                            "adapter_serving"))
    p.add_argument("--burst-ns", default="1,4,8",
                   help="fused_decode scenario: comma-separated burst "
                        "lengths (tokens per dispatch) to sweep")
    p.add_argument("--spec-ks", default="2,4,8,12",
                   help="spec_decode scenario: comma-separated draft "
                        "depths to sweep")
    p.add_argument("--spec-trees", default="2,2,1;3,1,1;2,1,1,1",
                   help="tree_spec scenario: semicolon-separated tree "
                        "shapes (comma fan-outs); all must spend the same "
                        "draft-token budget as the linear chain they race")
    p.add_argument("--slots", type=int, default=4,
                   help="decode slots (long_context: the RING config's "
                        "slot count, which sets the cache memory budget)")
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--warmup-requests", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kv-layout", default="paged", choices=("paged", "ring"))
    p.add_argument("--kv-block-size", type=int, default=16)
    p.add_argument("--kv-num-blocks", type=int, default=0)
    p.add_argument("--prefill-buckets", default="")
    p.add_argument("--compile-cache-dir", default=None,
                   help="JAX persistent compilation cache ('' disables)")
    p.add_argument("--no-warm-build", action="store_true",
                   help="skip the second engine build that measures the "
                        "warm (cache-hit) build time")
    p.add_argument("--checkpoint-path", default="")
    p.add_argument("--checkpoint-job-id", default="")
    p.add_argument("--out", default="")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.data.tokenizer import load_tokenizer
    from fault_tolerant_llm_training_tpu.inference.engine import (
        DEFAULT_COMPILE_CACHE_DIR,
        InferenceEngine,
        enable_compilation_cache,
    )
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cache_dir = (DEFAULT_COMPILE_CACHE_DIR if args.compile_cache_dir is None
                 else args.compile_cache_dir)
    cache_on = enable_compilation_cache(cache_dir)

    vocab = args.vocab_size or load_tokenizer("byte").vocab_size
    cfg = get_config(args.model, vocab_size=vocab,
                     layer_impl=args.layer_impl)
    backend = jax.default_backend()
    rng = np.random.default_rng(args.seed)

    params = None
    if not args.checkpoint_path:
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(args.seed),
                            jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def build(max_len, **kw):
        t0 = time.monotonic()
        if args.checkpoint_path:
            eng = InferenceEngine.from_checkpoint(
                args.checkpoint_path, args.checkpoint_job_id, cfg,
                max_len=max_len, **kw)
        else:
            eng = InferenceEngine(cfg, params, max_len=max_len, **kw)
        return eng, time.monotonic() - t0

    def reqs(specs, tag):
        return [Request(id=f"{tag}{i}",
                        prompt=rng.integers(3, vocab, size=pl).tolist(),
                        max_new_tokens=gen)
                for i, (pl, gen) in enumerate(specs)]

    if args.scenario == "long_context":
        result = _long_context(args, build, reqs)
    elif args.scenario == "spec_decode":
        result = _spec_decode(args, reqs, vocab)
    elif args.scenario == "shared_prefix":
        result = _shared_prefix(args, vocab)
    elif args.scenario == "fused_decode":
        result = _fused_decode(args, vocab)
    elif args.scenario == "mixed_prefill":
        result = _mixed_prefill(args, vocab)
    elif args.scenario == "tree_spec":
        result = _tree_spec(args, vocab)
    elif args.scenario == "serving_load":
        result = _serving_load(args, vocab)
    elif args.scenario == "spill_preempt":
        result = _spill_preempt(args, vocab)
    elif args.scenario == "kv_quant":
        result = _kv_quant(args, vocab)
    elif args.scenario == "disagg":
        result = _disagg(args, vocab)
    elif args.scenario == "global_prefix":
        result = _global_prefix(args, vocab)
    elif args.scenario == "transport":
        result = _transport(args, vocab)
    elif args.scenario == "adapter_serving":
        result = _adapter_serving(args, vocab)
    else:
        result = _uniform(args, build, reqs, backend)
    result["compile_cache"] = cache_dir if cache_on else ""

    print(json.dumps(result))
    default_name = {"long_context": "BENCH_decode_paged",
                    "spec_decode": "BENCH_decode_spec",
                    "shared_prefix": "BENCH_decode_prefix",
                    "fused_decode": "BENCH_decode_fused",
                    "mixed_prefill": "BENCH_prefill_packed",
                    "tree_spec": "BENCH_decode_tree",
                    "serving_load": "BENCH_serving_latency",
                    "spill_preempt": "BENCH_kv_spill",
                    "kv_quant": "BENCH_kv_quant",
                    "disagg": "BENCH_disagg",
                    "global_prefix": "BENCH_kv_store",
                    "transport": "BENCH_kv_transport",
                    "adapter_serving": "BENCH_adapter_serving"}.get(
        args.scenario, f"BENCH_decode_{args.model}")
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f"{default_name}_{backend}.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(f"wrote {out}")


def _uniform(args, build, reqs, backend):
    buckets = (tuple(int(b) for b in args.prefill_buckets.split(","))
               if args.prefill_buckets else None)
    max_len = args.max_len or args.prompt_len + args.max_new_tokens
    kw = dict(slots=args.slots, prefill_buckets=buckets,
              kv_layout=args.kv_layout)
    if args.kv_layout == "paged":
        kw.update(kv_block_size=args.kv_block_size,
                  kv_num_blocks=args.kv_num_blocks or None)
    engine, build_seconds = build(max_len, **kw)
    warm_seconds = None
    if not args.no_warm_build:
        # second build from the same process: every AOT compile hits the
        # persistent cache — the restart cost a real redeploy pays
        engine = None
        engine, warm_seconds = build(max_len, **kw)

    # warmup: touch every prefill bucket/decode program once off the clock
    _run_stream(engine, reqs([(args.prompt_len, args.max_new_tokens)]
                             * max(args.warmup_requests, 1), "warm"))
    engine.reset()
    m = _run_stream(engine, reqs([(args.prompt_len, args.max_new_tokens)]
                                 * args.requests, "req"))

    result = {
        "metric": (f"decode tokens/sec/slot ({args.model}, {args.slots} "
                   f"slots, prompt {args.prompt_len}, gen "
                   f"{args.max_new_tokens}, kv {args.kv_layout}, backend "
                   f"{backend})"),
        "value": round(m["tokens_per_sec_per_slot"], 1),
        "unit": "tokens/sec/slot",
        "kv_layout": args.kv_layout,
        "tokens_per_sec": round(m["tokens_per_sec"], 1),
        "decode_p50_ms": round(m["decode_p50_ms"], 3),
        "decode_p95_ms": round(m["decode_p95_ms"], 3),
        "requests": m["requests_completed"],
        "tokens_generated": m["tokens_generated"],
        "max_concurrent": m["max_concurrent"],
        "iterations": m["iterations"],
        "wall_seconds": round(m["wall_seconds"], 3),
        "engine_build_seconds": round(build_seconds, 3),
        "engine_build_seconds_warm": (None if warm_seconds is None
                                      else round(warm_seconds, 3)),
        "restored_step": engine.restored_step,
    }
    if args.kv_layout == "paged":
        result["kv_block_size"] = engine.block_size
        result["kv_blocks_total"] = engine.num_blocks - 1
        result["kv_block_utilization_peak"] = round(
            m["kv_block_utilization_peak"], 3)
    return result


def _long_context(args, build, reqs):
    """Mixed short/long traffic, ring vs paged at the SAME cache budget.

    The budget is the ring config's reservation: slots * max_len cached
    positions. The paged pool gets exactly that many positions
    (budget/block_size usable blocks + the null block) but 4x the slots —
    concurrency is then bounded by actual per-request need (admission by
    free-block count), not by reservation. Long prompts exceed the paged
    config's largest bucket (64), so they exercise chunked prefill; the
    ring config needs its full bucket ladder (largest = max_len) to accept
    them at all.
    """
    import jax

    max_len = args.max_len or 256
    bs = args.kv_block_size
    budget_positions = args.slots * max_len
    short, long_ = (24, 16), (160, 32)  # (prompt, gen)
    specs = [short if i % 2 == 0 else long_ for i in range(args.requests)]

    paged_kw = dict(slots=args.slots * 4, prefill_buckets=(16, 32, 64),
                    kv_layout="paged", kv_block_size=bs,
                    kv_num_blocks=budget_positions // bs + 1)
    ring_kw = dict(slots=args.slots, kv_layout="ring")

    paged, paged_build = build(max_len, **paged_kw)
    _run_stream(paged, reqs(specs[:2], "warm"))
    paged.reset()
    pm = _run_stream(paged, reqs(specs, "req"))
    paged_summary = {
        "slots": paged_kw["slots"],
        "prefill_buckets": list(paged_kw["prefill_buckets"]),
        "kv_block_size": bs,
        "kv_blocks_total": pm["kv_blocks_total"],
        "tokens_per_sec": round(pm["tokens_per_sec"], 1),
        "max_concurrent": pm["max_concurrent"],
        "kv_block_utilization_peak": round(
            pm["kv_block_utilization_peak"], 3),
        "prefill_chunks": pm["prefill_chunks"],
        "decode_p50_ms": round(pm["decode_p50_ms"], 3),
        "requests": pm["requests_completed"],
        "engine_build_seconds": round(paged_build, 3),
    }
    paged = None  # free the pool before the ring engine builds

    ring, ring_build = build(max_len, **ring_kw)
    _run_stream(ring, reqs(specs[:2], "warm"))
    ring.reset()
    rm = _run_stream(ring, reqs(specs, "req"))
    ring_summary = {
        "slots": args.slots,
        "tokens_per_sec": round(rm["tokens_per_sec"], 1),
        "max_concurrent": rm["max_concurrent"],
        "decode_p50_ms": round(rm["decode_p50_ms"], 3),
        "requests": rm["requests_completed"],
        "engine_build_seconds": round(ring_build, 3),
    }

    return {
        "metric": (f"long-context paged decode tokens/sec ({args.model}, "
                   f"mixed prompts {short[0]}/{long_[0]}, max_len "
                   f"{max_len}, cache budget {budget_positions} positions, "
                   f"backend {jax.default_backend()})"),
        "value": paged_summary["tokens_per_sec"],
        "unit": "tokens/sec",
        "cache_budget_positions": budget_positions,
        "long_prompt_exceeds_largest_bucket": long_[0] > 64,
        "paged": paged_summary,
        "ring": ring_summary,
        "concurrency_gain": round(
            pm["max_concurrent"] / max(rm["max_concurrent"], 1), 2),
    }


def _spec_decode(args, reqs, vocab):
    """Speculative vs plain greedy decode at the SAME cache memory budget.

    Target: ``tiny-4l`` with layers 2/3's output projections (attention wo,
    ffn w2) zeroed — those blocks become exact residual identities. Draft:
    the 2-layer ``tiny`` preset SHARING the target's embeddings, first two
    layers, final norm and output head, so draft logits equal target
    logits and greedy acceptance is ~100% — the regime a distilled draft
    approaches. One extra point with an INDEPENDENTLY-initialized draft
    shows the low-acceptance floor.

    Both verify implementations are swept (engine ``spec_verify_impl``):

    - ``chunk`` points carry the CPU-visible throughput win — one
      (slots, k+1) forward batches the verify FLOPs into one GEMM pass.
      Greedy streams are COMPARED against the baseline and the mismatch
      count recorded, not asserted: bf16 GEMM accumulation is shape-
      dependent, and over ~6k greedy positions a one-ulp logit near-tie
      occasionally flips an argmax between the S=k+1 and S=1 programs.
    - the ``exact`` point (mid k) micro-steps k+1 S=1 forwards inside the
      verify program — same shapes as the decode step, so its stream is
      ASSERTED bit-equal to the baseline. Its win is dispatch
      elimination (1 verify program per round vs k+1 decode dispatches),
      which pays on accelerators but is invisible on CPU where dispatch
      is ~free next to compute — expect ~1x here, by design.

    Cache memory is held fixed in LAYER-blocks (one (block, heads, bs,
    head_dim) K+V block pair per layer): baseline 72 usable blocks x 4
    layers = 288; spec 48 x 4 (target) + 48 x 2 (draft) = 288 — and both
    admit the same 4-way concurrency (12 blocks/request at prompt 32 +
    gen 160, block size 16; the 4 slots are the binding cap on both
    sides). The long decode phase is the point: the spec side pays
    prefill TWICE (target + draft pools), so short generations understate
    the steady-state decode win.
    """
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    # seq_len=256: the tiny presets ship 128, too short for the 192-token
    # requests below (RoPE table length; parameters are unaffected)
    tcfg = get_config("tiny-4l", vocab_size=vocab, seq_len=256)
    dcfg = get_config("tiny", vocab_size=vocab, seq_len=256)
    model = Transformer(tcfg)
    tparams = model.init(jax.random.PRNGKey(args.seed),
                         jnp.zeros((1, tcfg.seq_len), jnp.int32))["params"]
    tparams = jax.tree_util.tree_map(lambda x: x, dict(tparams))
    for lyr in ("layers_2", "layers_3"):
        for mod, proj in (("attention", "wo"), ("feed_forward", "w2")):
            node = dict(tparams[lyr][mod][proj])
            for leaf in node:
                node[leaf] = jnp.zeros_like(node[leaf])
            tparams[lyr] = dict(tparams[lyr])
            tparams[lyr][mod] = dict(tparams[lyr][mod])
            tparams[lyr][mod][proj] = node
    dparams = {k: tparams[k] for k in ("tok_embeddings", "norm", "output",
                                       "layers_0", "layers_1")}
    rand_draft = Transformer(dcfg).init(
        jax.random.PRNGKey(args.seed + 1),
        jnp.zeros((1, dcfg.seq_len), jnp.int32))["params"]

    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    prompt_len, gen, slots, bs = 32, 160, 4, 16
    max_len = prompt_len + gen
    base_usable, spec_usable = 72, 48
    common = dict(slots=slots, max_len=max_len, prefill_buckets=(16, 32),
                  kv_layout="paged", kv_block_size=bs)
    request_specs = [(prompt_len, gen)] * args.requests

    def fixed_reqs(tag):
        # every engine must see the IDENTICAL prompt set or the bit-match
        # assertion compares different streams (the shared module-level rng
        # advances per call)
        lrng = np.random.default_rng(args.seed + 123)
        return [Request(id=f"{tag}{i}",
                        prompt=lrng.integers(3, vocab, size=pl).tolist(),
                        max_new_tokens=g)
                for i, (pl, g) in enumerate(request_specs)]

    def run(engine):
        _run_stream(engine, reqs(request_specs[:2], "warm"))
        engine.reset()
        return _run_stream(engine, reqs(request_specs, "req"))

    base = InferenceEngine(tcfg, tparams, kv_num_blocks=base_usable + 1,
                           **common)
    bm = run(base)
    base_streams = None
    sched_probe = None
    # capture baseline token streams for the bit-match assertion
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler
    base.reset()
    sched_probe = Scheduler(base, eos_token_id=None)
    for r in fixed_reqs("bit"):
        sched_probe.submit(r)
    base_streams = {c.request_id: c.tokens for c in sched_probe.run()}
    base = None

    points = []
    ks = [int(k) for k in args.spec_ks.split(",")]
    mid_k = ks[len(ks) // 2]
    sweep = ([(k, dparams, "shared-prefix", "chunk") for k in ks]
             + [(mid_k, dparams, "shared-prefix", "exact"),
                (mid_k, rand_draft, "random", "chunk")])
    for k, draft, tag, impl in sweep:
        eng = InferenceEngine(tcfg, tparams, draft_cfg=dcfg,
                              draft_params=draft, spec_k=k,
                              kv_num_blocks=spec_usable + 1,
                              draft_num_blocks=spec_usable + 1,
                              spec_verify_impl=impl, **common)
        m = run(eng)
        eng.reset()
        sched = Scheduler(eng, eos_token_id=None)
        for r in fixed_reqs("bit"):
            sched.submit(r)
        streams = {c.request_id: c.tokens for c in sched.run()}
        mismatched = sum(streams[rid] != base_streams[rid]
                         for rid in base_streams)
        bit_match = mismatched == 0
        if impl == "exact":
            # the tentpole invariant: micro-step verify shares the decode
            # program's op shapes, so this holds by construction, not by
            # luck of the backend's GEMM tiling
            assert bit_match, (
                f"exact-impl spec k={k} ({tag}) diverged from greedy "
                f"baseline in {mismatched} stream(s)")
        points.append({
            "k": k,
            "draft": tag,
            "verify_impl": impl,
            "tokens_per_sec": round(m["tokens_per_sec"], 1),
            "speedup_vs_baseline": round(
                m["tokens_per_sec"] / bm["tokens_per_sec"], 2),
            "acceptance_rate": round(m["spec_acceptance_rate"], 3),
            "spec_rounds": m["spec_rounds"],
            "decode_p50_ms": round(m["decode_p50_ms"], 3),
            "bit_match_greedy": bit_match,
            "mismatched_streams": mismatched,
        })
        eng = None

    best = max((p for p in points if p["draft"] == "shared-prefix"
                and p["verify_impl"] == "chunk"),
               key=lambda p: p["speedup_vs_baseline"])
    return {
        "metric": (f"speculative decode speedup (tiny-4l target, tiny "
                   f"draft, prompt {prompt_len}, gen {gen}, "
                   f"{slots} slots, fixed layer-block budget, chunk "
                   f"verify, backend {jax.default_backend()})"),
        "value": best["speedup_vs_baseline"],
        "unit": "x tokens/sec vs non-spec baseline",
        "baseline_tokens_per_sec": round(bm["tokens_per_sec"], 1),
        "baseline_decode_p50_ms": round(bm["decode_p50_ms"], 3),
        "layer_block_budget": {"baseline": base_usable * 4,
                               "spec": spec_usable * 4 + spec_usable * 2},
        "kv_blocks": {"baseline": base_usable,
                      "spec_target": spec_usable, "spec_draft": spec_usable},
        "points": points,
    }


def _shared_prefix(args, vocab):
    """Prefix caching: N requests sharing a long system prompt, cache
    on/off — prefill time ~O(1) in N.

    Every request is a 432-token shared "system prompt" (27 full
    16-position blocks, block-aligned) plus an 8-token unique suffix.
    With the cache on, request 1 pays the full 440-position prefill and
    inserts its committed blocks into the radix tree; requests 2..N hit
    all 27 shared blocks and prefill only their 8 suffix positions —
    total prefill work is 440 + (N-1)*8 positions instead of N*440, so
    the wall-clock prefill time is ~O(1) in N while the cache-off runs
    scale linearly. (The prefix must be long enough that the N=1 cost
    amortizes the per-chunk dispatch overhead a hit request's one
    16-wide suffix chunk still pays — with a short prefix that fixed
    cost, not skipped compute, dominates the ratio on CPU.) At N=8 the
    hit rate is 7*432/(8*440) = 0.859 (the ``kv_prefix_hit_rate``
    gauge, scraped from a per-run registry) and the cached prefill
    total must stay <= 2x the N=1 cost. Prefill wall
    time is the scheduler's own ``prefill_seconds`` accumulator (timed
    around ``engine.prefill`` only, so decode cost can't smear the
    number); each point takes the min of ``--prefix-repeats`` runs to
    shave scheduler-noise off the small-N points.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    # seq_len=512 for the RoPE table (tiny preset ships 128)
    cfg = get_config(args.model, vocab_size=vocab, seq_len=512)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    bs, gen, slots = 16, 16, 8
    shared_len, suffix_len = 432, 8       # 27 aligned blocks + suffix
    prompt_len = shared_len + suffix_len
    lrng = np.random.default_rng(args.seed + 7)
    shared = lrng.integers(3, vocab, size=shared_len).tolist()
    suffixes = [lrng.integers(3, vocab, size=suffix_len).tolist()
                for _ in range(8)]
    engine = InferenceEngine(cfg, params, slots=slots,
                             max_len=prompt_len + gen + bs,
                             prefill_buckets=(16, 32, 64),
                             kv_layout="paged", kv_block_size=bs)
    repeats = getattr(args, "prefix_repeats", 3)

    def run_point(n, cache_on):
        engine.enable_prefix_cache = cache_on
        best = None
        for _ in range(repeats):
            engine.reset()
            reg = MetricRegistry()
            sched = Scheduler(engine, eos_token_id=None, registry=reg)
            for i in range(n):
                sched.submit(Request(id=f"r{i}",
                                     prompt=shared + suffixes[i],
                                     max_new_tokens=gen))
            t0 = time.monotonic()
            sched.run()
            m = sched.metrics()
            m["wall_seconds"] = time.monotonic() - t0
            scrape = reg.render()
            gauge = [ln for ln in scrape.splitlines()
                     if ln.startswith("kv_prefix_hit_rate ")]
            m["hit_rate_scrape"] = (float(gauge[0].split()[-1])
                                    if gauge else None)
            if best is None or m["prefill_seconds"] < best["prefill_seconds"]:
                best = m
        return best

    # warmup: touch every bucket, the COW program and the decode program
    run_point(2, True)

    ns = (1, 2, 4, 8)
    points = []
    for cache_on in (True, False):
        for n in ns:
            m = run_point(n, cache_on)
            points.append({
                "n": n,
                "prefix_cache": cache_on,
                "prefill_seconds": round(m["prefill_seconds"], 4),
                "prefill_chunks": m["prefill_chunks"],
                "hit_rate": (round(m.get("prefix_hit_rate", 0.0), 4)
                             if cache_on else None),
                "hit_rate_scrape": (round(m["hit_rate_scrape"], 4)
                                    if m["hit_rate_scrape"] is not None
                                    else None),
                "cow_copies": m.get("prefix_cow_copies", 0) if cache_on
                else 0,
                "kv_blocks_shared_final": (m.get("kv_blocks_shared", 0)
                                           if cache_on else 0),
                "tokens_per_sec": round(m["tokens_per_sec"], 1),
                "requests": m["requests_completed"],
            })

    by = {(p["n"], p["prefix_cache"]): p for p in points}
    ratio_cached = (by[(8, True)]["prefill_seconds"]
                    / by[(1, True)]["prefill_seconds"])
    ratio_uncached = (by[(8, False)]["prefill_seconds"]
                      / by[(1, False)]["prefill_seconds"])
    return {
        "metric": (f"shared-prefix prefill time at N=8 vs N=1, prefix "
                   f"cache on ({args.model}, shared {shared_len} + unique "
                   f"{suffix_len} tok, gen {gen}, {slots} slots, backend "
                   f"{jax.default_backend()})"),
        "value": round(ratio_cached, 2),
        "unit": "x N=1 prefill seconds (uncached scales ~linearly)",
        "prefill_ratio_n8_vs_n1_cached": round(ratio_cached, 2),
        "prefill_ratio_n8_vs_n1_uncached": round(ratio_uncached, 2),
        "kv_prefix_hit_rate_n8": by[(8, True)]["hit_rate_scrape"],
        "shared_prefix_tokens": shared_len,
        "unique_suffix_tokens": suffix_len,
        "kv_block_size": bs,
        "points": points,
    }


def _global_prefix(args, vocab):
    """Fleet-global KV store: N hosts x a shared-prompt burst, with and
    without the content-addressed block store (inference/kvstore.py).

    Four simulated hosts each serve one request carrying the same
    432-token shared prompt (27 aligned 16-position blocks) plus a
    unique 8-token suffix. Hosts are one engine reset per host — each
    host's prefix cache starts COLD, which is exactly the "N independent
    caches" baseline. With the store wired, host 0 publishes its
    committed train once and every later host admits through the batched
    verify-before-first-device-write fetch, prefilling only its 8 suffix
    positions; the receipt pins the cross-host hit rate (fetched tokens
    over the remote hosts' prompt tokens, 3*432/(3*440) ~ 0.98 > 0.5),
    the aggregate prefill seconds beating the independent baseline
    (440 + 3*8 positions of prefill instead of 4*440), zero dropped
    requests, and the fetched streams bit-matching the store-less runs.
    Each mode takes the min of 3 repeats (fresh store dir per repeat so
    dedup cannot carry across them).
    """
    import shutil
    import tempfile
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    # seq_len=512 for the RoPE table (tiny preset ships 128)
    cfg = get_config(args.model, vocab_size=vocab, seq_len=512)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    bs, gen, hosts = 16, 16, 4
    shared_len, suffix_len = 432, 8
    prompt_len = shared_len + suffix_len
    lrng = np.random.default_rng(args.seed + 7)
    shared = lrng.integers(3, vocab, size=shared_len).tolist()
    suffixes = [lrng.integers(3, vocab, size=suffix_len).tolist()
                for _ in range(hosts)]
    engine = InferenceEngine(cfg, params, slots=2,
                             max_len=prompt_len + gen + bs,
                             prefill_buckets=(16, 32, 64),
                             kv_layout="paged", kv_block_size=bs)

    def run_fleet(store_root):
        streams = {}
        agg = {"prefill_seconds": 0.0, "fetch_blocks": 0, "fetches": 0,
               "publishes": 0, "rejects": 0, "completed": 0}
        for h in range(hosts):
            engine.enable_prefix_cache = True
            engine.reset()  # each host's LOCAL cache starts cold
            store = (BlockStore(store_root, writer=f"h{h}")
                     if store_root else None)
            sched = Scheduler(engine, eos_token_id=None,
                              registry=MetricRegistry(), kv_store=store)
            sched.submit(Request(id=f"h{h}",
                                 prompt=shared + suffixes[h],
                                 max_new_tokens=gen))
            sched.run()
            m = sched.metrics()
            agg["prefill_seconds"] += m["prefill_seconds"]
            agg["fetch_blocks"] += sched.store_fetch_blocks
            agg["fetches"] += sched.store_fetches
            agg["publishes"] += sched.store_publishes
            agg["rejects"] += sched.store_rejects
            agg["completed"] += m["requests_completed"]
            streams.update({c.request_id: c.tokens
                            for c in sched.completed})
        return agg, streams

    run_fleet(None)  # warmup: every bucket + the decode program

    best_store = best_solo = ref_streams = None
    for _ in range(3):
        solo, solo_streams = run_fleet(None)
        if ref_streams is None:
            ref_streams = solo_streams
        if best_solo is None or (solo["prefill_seconds"]
                                 < best_solo["prefill_seconds"]):
            best_solo = solo
        root = tempfile.mkdtemp(prefix="kvstore_bench_")
        try:
            fleet, fleet_streams = run_fleet(root)
        finally:
            shutil.rmtree(root, ignore_errors=True)
        fleet["bit_exact"] = fleet_streams == ref_streams
        if best_store is None or (fleet["prefill_seconds"]
                                  < best_store["prefill_seconds"]):
            best_store = fleet

    remote_tokens = (hosts - 1) * prompt_len
    hit_rate = best_store["fetch_blocks"] * bs / remote_tokens
    return {
        "metric": (f"cross-host prefix hit rate over {hosts} hosts x one "
                   f"shared-prompt request (shared {shared_len} + unique "
                   f"{suffix_len} tok, gen {gen}, backend "
                   f"{jax.default_backend()})"),
        "value": round(hit_rate, 4),
        "unit": "fetched tokens / remote hosts' prompt tokens",
        "cross_host_hit_rate": round(hit_rate, 4),
        "aggregate_prefill_seconds_store": round(
            best_store["prefill_seconds"], 4),
        "aggregate_prefill_seconds_independent": round(
            best_solo["prefill_seconds"], 4),
        "store_publishes": best_store["publishes"],
        "store_fetches": best_store["fetches"],
        "store_fetch_blocks": best_store["fetch_blocks"],
        "store_rejects": best_store["rejects"],
        "requests_expected": hosts,
        "requests_completed": best_store["completed"],
        "dropped": hosts - best_store["completed"],
        "bit_exact": best_store["bit_exact"],
        "hosts": hosts,
        "shared_prefix_tokens": shared_len,
        "unique_suffix_tokens": suffix_len,
        "kv_block_size": bs,
    }


def _fused_decode(args, vocab):
    """Fused decode: kernel (gather vs pallas) x burst n, plus the fused
    sampling epilogue against its unfused host-sampled baseline.

    All requests are GREEDY so every stream comparison is exact:

    - kernel x burst grid: each point drives the full scheduler with
      ``decode_burst=n``; its streams are asserted bit-identical to the
      same kernel's burst-1 streams (``_bank_burst`` truncation included
      — gen is deliberately not a burst multiple), and the scheduler's
      own dispatch accounting gives dispatches/token and host-syncs/token
      (2 active-slot batching means the bar is 1/(n * slots), but the
      receipt pins only the burst bound <= 1/n + eps).
    - fused vs unfused: same engine, T decode iterations either through
      the fused program (token ids sync, 4 bytes/slot) or through
      ``decode_logits`` + host ``sample_slot_tokens`` (a (slots, vocab)
      fp32 plane per step). Streams are ASSERTED bit-identical — both
      regimes trace the SAME sampler.py epilogue — and the timing ratio
      is the sync-elimination win (modest on CPU where the "sync" is a
      copy; the dispatch/token column is the accelerator-relevant bound).

    Headline value: dispatches/token at the largest burst — the ISSUE's
    "n tokens for ONE dispatch + ONE host sync" contract, measured.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.sampler import (
        sample_slot_tokens)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = get_config(args.model, vocab_size=vocab,
                     layer_impl=args.layer_impl)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    slots, prompt_len, gen, bs = 4, 32, 45, 16
    max_len = prompt_len + gen + bs
    ns = [int(n) for n in args.burst_ns.split(",")]
    lrng = np.random.default_rng(args.seed + 31)
    prompts = [lrng.integers(3, vocab, size=prompt_len).tolist()
               for _ in range(args.requests)]

    def run(engine, n):
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None, decode_burst=n)
        for i, pr in enumerate(prompts):
            sched.submit(Request(id=f"r{i}", prompt=pr,
                                 max_new_tokens=gen))
        t0 = time.monotonic()
        out = sched.run()
        m = sched.metrics()
        m["wall_seconds"] = time.monotonic() - t0
        return m, {c.request_id: c.tokens for c in out}

    points = []
    baseline_tps = None
    for kernel in ("gather", "pallas"):
        engine = InferenceEngine(cfg, params, slots=slots, max_len=max_len,
                                 prefill_buckets=(16, 32), kv_layout="paged",
                                 kv_block_size=bs, paged_kernel=kernel)
        run(engine, max(ns))                       # warm every program
        _, seq_streams = run(engine, 1)
        if kernel == "gather":
            gather_streams, gather_engine = seq_streams, engine
            mismatched = 0
        else:
            # RECORDED, not asserted: the in-place kernel's online softmax
            # reorders the fp32 reduction, so a bf16 logit near-tie can
            # legitimately flip a greedy argmax (same caveat the spec
            # chunk-verify points document). The bit-pinned comparisons
            # are within-kernel: burst-vs-sequential and fused-vs-host.
            mismatched = sum(seq_streams[r] != gather_streams[r]
                             for r in gather_streams)
        for n in ns:
            m, streams = run(engine, n)
            assert streams == seq_streams, (
                f"burst={n} kernel={kernel} diverged from per-token decode")
            if kernel == "gather" and n == 1:
                baseline_tps = m["tokens_per_sec"]
            points.append({
                "kernel": kernel,
                "burst": n,
                "tokens_per_sec": round(m["tokens_per_sec"], 1),
                "speedup_vs_gather_burst1": (
                    None if baseline_tps is None
                    else round(m["tokens_per_sec"] / baseline_tps, 2)),
                "dispatches_per_token": round(m["dispatches_per_token"], 4),
                "host_syncs_per_token": round(m["host_syncs_per_token"], 4),
                "decode_p50_ms": round(m["decode_p50_ms"], 3),
                "bit_match_burst1": True,          # asserted above
                "greedy_streams_mismatched_vs_gather": mismatched,
            })
        engine = None if kernel == "pallas" else engine

    # fused epilogue vs unfused host-sampled baseline, engine level
    eng = gather_engine
    nb = -(-max_len // bs)                         # blocks per slot, ceil
    rows = np.arange(1, slots * nb + 1, dtype=np.int32).reshape(slots, nb)
    temperature = np.zeros(slots, np.float32)
    top_p = np.ones(slots, np.float32)
    seeds = np.zeros(slots, np.int32)
    active = np.ones(slots, bool)

    def decode_loop(fused):
        eng.reset()
        toks = np.array([eng.prefill(s, prompts[s], block_row=rows[s])
                         for s in range(slots)], np.int32)
        stream = [toks.copy()]
        t0 = time.monotonic()
        for step in range(1, gen):
            steps = np.full(slots, step, np.int32)
            if fused:
                toks = eng.decode_step(toks, active, temperature, top_p,
                                       seeds, steps, block_tables=rows)
            else:
                logits = eng.decode_logits(toks, active, block_tables=rows)
                toks = np.asarray(sample_slot_tokens(
                    logits, seeds, steps, temperature, top_p, eng.top_k))
            stream.append(np.asarray(toks).copy())
        return time.monotonic() - t0, np.stack(stream)

    decode_loop(True)                              # warm both programs
    decode_loop(False)
    fused_s, fused_stream = decode_loop(True)
    unfused_s, unfused_stream = decode_loop(False)
    fused_bit_match = bool((fused_stream == unfused_stream).all())
    assert fused_bit_match, "fused epilogue diverged from host sampler"

    best = min(points, key=lambda p: p["dispatches_per_token"])
    return {
        "metric": (f"decode dispatches/token at burst {max(ns)} "
                   f"({args.model}, {slots} slots, prompt {prompt_len}, "
                   f"gen {gen}, backend {jax.default_backend()})"),
        "value": best["dispatches_per_token"],
        "unit": "dispatches/token (1/(burst*slots) ideal; 1.0 = per-token)",
        "burst_ns": ns,
        "slots": slots,
        "gen_tokens": gen,
        "fused_bit_match_host_sampler": fused_bit_match,
        "fused_decode_seconds": round(fused_s, 4),
        "unfused_decode_seconds": round(unfused_s, 4),
        "fused_vs_unfused_speedup": round(unfused_s / fused_s, 2),
        "points": points,
    }


def _mixed_prefill(args, vocab):
    """Batched multi-request prefill: packed (P, bucket) rounds vs the
    sequential one-prompt-at-a-time lane, across both paged kernels.

    Two workloads per kernel (gather, pallas), one engine each (compiled
    with BOTH the sequential bucket ladder and the packed programs, so
    the two lanes share every byte of weights and cache):

    - prefill wall-clock: N multi-chunk prompts served packed
      (``prefill_batch=P``) and sequentially (``prefill_batch=1``)
      through the SAME engine. Token streams are ASSERTED bit-identical
      within each kernel — the packed batch is a parallel GEMM dimension
      and every row walks the same chunk buckets, so packing cannot
      change bytes. Across kernels, greedy mismatches are RECORDED, not
      asserted (the in-place chunk kernel's online softmax reorders the
      fp32 reduction — the fused_decode caveat). ``prefill_seconds`` is
      the scheduler's own accumulator, timed around the prefill
      dispatches only, so decode cost cannot smear it; each point takes
      the min over repeats.
    - decode under prefill load: short requests decode while long
      prompts stream through the packed lane. A packed round is BOUNDED
      (at most P x bucket positions per dispatch), so decode rounds run
      BETWEEN packed rounds — asserted from a dispatch timeline — and
      the receipt records the decode-iteration latency percentiles paid
      under that load.

    Headline value: packed-vs-sequential prefill wall-clock speedup at
    N concurrent requests on the gather kernel (the bit-exact lane).
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    # seq_len=256 for the RoPE table (tiny preset ships 128)
    cfg = get_config(args.model, vocab_size=vocab, seq_len=256)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    slots, bs, pb = 8, 16, 4
    n = slots                                  # one full concurrent wave
    prompt_len, gen = 96, 16                   # 3 chunks each (32, 32, 32)
    max_len = prompt_len + gen + bs
    lrng = np.random.default_rng(args.seed + 41)
    prompts = [lrng.integers(3, vocab, size=prompt_len).tolist()
               for _ in range(n)]
    sampling = [(0.0, 1.0, 0)] * (n - 2) + [(0.8, 0.9, 7), (0.7, 0.9, 11)]

    def wave():
        return [Request(id=f"r{i}", prompt=list(prompts[i]),
                        max_new_tokens=gen, temperature=t, top_p=tp,
                        seed=sd)
                for i, (t, tp, sd) in enumerate(sampling)]

    def run(engine, prefill_batch, requests):
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None,
                          prefill_batch=prefill_batch)
        for r in requests:
            sched.submit(r)
        t0 = time.monotonic()
        out = sched.run()
        m = sched.metrics()
        m["wall_seconds"] = time.monotonic() - t0
        return m, {c.request_id: c.tokens for c in out}

    repeats = 3
    points = []
    gather_streams = gather_engine = None
    headline = None
    for kernel in ("gather", "pallas"):
        engine = InferenceEngine(cfg, params, slots=slots, max_len=max_len,
                                 prefill_buckets=(16, 32),
                                 kv_layout="paged", kv_block_size=bs,
                                 paged_kernel=kernel, prefill_batch=pb)
        run(engine, pb, wave())                # warm every program
        run(engine, 1, wave())
        best, streams = {}, {}
        for mode, p in (("sequential", 1), ("packed", pb)):
            for _ in range(repeats):
                m, s = run(engine, p, wave())
                if (mode not in best or m["prefill_seconds"]
                        < best[mode]["prefill_seconds"]):
                    best[mode] = m
                streams[mode] = s
        assert streams["packed"] == streams["sequential"], (
            f"packed prefill diverged from sequential ({kernel})")
        if kernel == "gather":
            gather_streams, gather_engine = streams["sequential"], engine
            mismatched = 0
        else:
            mismatched = sum(streams["sequential"][r] != gather_streams[r]
                             for r in gather_streams)
        speedup = (best["sequential"]["prefill_seconds"]
                   / best["packed"]["prefill_seconds"])
        if kernel == "gather":
            headline = speedup
        for mode in ("sequential", "packed"):
            m = best[mode]
            points.append({
                "kernel": kernel,
                "mode": mode,
                "prefill_seconds": round(m["prefill_seconds"], 4),
                "prefill_chunks": m["prefill_chunks"],
                "prefill_inplace_chunks": m["prefill_inplace_chunks"],
                "packed_rounds": m["prefill_packed_rounds"],
                "packed_occupancy": round(m["prefill_packed_occupancy"], 3),
                "tokens_per_sec": round(m["tokens_per_sec"], 1),
                "streams_bitmatch_sequential": True,   # asserted above
                "greedy_mismatch_vs_gather": mismatched,
            })
        points[-1]["prefill_speedup_vs_sequential"] = round(speedup, 2)
        if kernel == "pallas":
            engine = None

    # decode under prefill load: 4 shorts prefill in round 1 and decode
    # while the 4 long prompts stream through the remaining packed rounds
    eng = gather_engine
    timeline = []
    orig_pp, orig_ds = eng.prefill_packed, eng.decode_step

    def spy_pp(*a, **k):
        timeline.append("P")
        return orig_pp(*a, **k)

    def spy_ds(*a, **k):
        timeline.append("D")
        return orig_ds(*a, **k)

    eng.prefill_packed, eng.decode_step = spy_pp, spy_ds
    mixed = ([Request(id=f"s{i}",
                      prompt=lrng.integers(3, vocab, size=16).tolist(),
                      max_new_tokens=40) for i in range(4)]
             + [Request(id=f"l{i}", prompt=list(prompts[i]),
                        max_new_tokens=8) for i in range(4)])
    eng.reset()
    sched = Scheduler(eng, eos_token_id=None, prefill_batch=pb)
    for r in mixed:
        sched.submit(r)
    sched.run()
    lm = sched.metrics()
    eng.prefill_packed, eng.decode_step = orig_pp, orig_ds
    first_p = timeline.index("P")
    last_p = len(timeline) - 1 - timeline[::-1].index("P")
    decode_between = "D" in timeline[first_p:last_p]
    assert decode_between, ("no decode round ran between packed prefill "
                            "rounds — the bounded-round interleave broke")

    return {
        "metric": (f"packed prefill speedup vs sequential at N={n} "
                   f"({args.model}, prompt {prompt_len}, {slots} slots, "
                   f"prefill_batch {pb}, gather kernel, backend "
                   f"{jax.default_backend()})"),
        "value": round(headline, 2),
        "unit": "x sequential prefill seconds (same engine, same streams)",
        "requests": n,
        "prefill_batch": pb,
        "prompt_len": prompt_len,
        "prefill_buckets": [16, 32],
        "decode_between_packed_rounds": decode_between,
        "decode_under_prefill_load_p50_ms": round(lm["decode_p50_ms"], 3),
        "decode_under_prefill_load_p95_ms": round(lm["decode_p95_ms"], 3),
        "decode_under_prefill_load_requests": lm["requests_completed"],
        "points": points,
    }


def _tree_spec(args, vocab):
    """Tree vs linear speculation at a FIXED draft-token budget.

    Every speculative point spends the SAME draft budget per round and
    differs only in how the proposed tokens are arranged: a linear
    k-chain (plain ``spec_round``) vs branching ``spec_tree`` shapes
    with the identical node count. The draft is the TARGET's own weights
    perturbed by ~1% gaussian noise — accepted often, wrong often enough
    that its argmax chain derails mid-round, which is exactly the regime
    where a sibling branch rescues the rest of the round instead of
    forfeiting it.

    The comparison metric is ACCEPTED TOKENS PER VERIFY DISPATCH: each
    round is ONE verify-program dispatch regardless of shape, so at
    equal budget this isolates what the tree arrangement buys. Wall
    clock is recorded but CPU-incidental (the tree verify does more
    FLOPs per dispatch than the chain's accepted prefix would need — the
    win is acceptance at fixed dispatch count, which prices in on
    accelerators where dispatch latency dominates the tiny-S GEMMs).

    The sweep points run the ``chunk`` verify implementation — the real
    ancestor-masked tree forward, the only one that SCORES siblings (the
    ``exact`` escape hatch walks just the primary chain, so a tree can
    never beat its own chain there). Greedy streams of the chunk points
    are compared to the non-spec baseline and mismatch counts RECORDED,
    not asserted — the multi-branch forward's bf16 accumulation is
    shape-dependent (the spec_decode caveat). One extra EXACT-mode tree
    point carries the bit-exactness contract: its greedy stream is
    ASSERTED identical to the baseline. Every drain runs the strict
    block leak guard. The receipt FAILS unless the best tree shape beats
    the linear chain on accepted/round at equal budget.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, parse_spec_tree)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    # seq_len=256 for the RoPE table (tiny preset ships 128)
    cfg = get_config(args.model, vocab_size=vocab, seq_len=256)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    # near-miss draft: the target plus 0.4% noise on every parameter
    # leaf — accepted ~25% per node, derails mid-round often enough that
    # siblings rescue ~20% of accepted tokens (the branch-util gauge)
    eps = 0.004
    leaves, treedef = jax.tree_util.tree_flatten(params)
    keys = jax.random.split(jax.random.PRNGKey(args.seed + 77), len(leaves))
    draft = jax.tree_util.tree_unflatten(treedef, [
        l + jnp.asarray(eps, l.dtype)
        * jax.random.normal(k, l.shape, l.dtype)
        for l, k in zip(leaves, keys)])

    shapes = [parse_spec_tree(s) for s in args.spec_trees.split(";")]
    budget = shapes[0].size - 1
    assert all(s.size - 1 == budget for s in shapes), (
        "--spec-trees shapes must all spend the same draft-token budget")

    slots, prompt_len, gen, bs = 2, 24, 48, 16
    max_len = prompt_len + gen + bs
    common = dict(slots=slots, max_len=max_len, prefill_buckets=(16, 32),
                  kv_layout="paged", kv_block_size=bs)
    lrng = np.random.default_rng(args.seed + 123)
    prompts = [lrng.integers(3, vocab, size=prompt_len).tolist()
               for _ in range(8)]
    warm_prompts = [lrng.integers(3, vocab, size=prompt_len).tolist()
                    for _ in range(2)]

    def drive(engine, plist, gen_tokens=gen):
        sched = Scheduler(engine, eos_token_id=None)
        for i, pr in enumerate(plist):
            sched.submit(Request(id=f"r{i}", prompt=list(pr),
                                 max_new_tokens=gen_tokens))
        t0 = time.monotonic()
        out = sched.run()        # strict leak guard runs at this drain
        m = sched.metrics()
        m["wall_seconds"] = time.monotonic() - t0
        return m, {c.request_id: c.tokens for c in out}

    base = InferenceEngine(cfg, params, **common)
    drive(base, warm_prompts)
    base.reset()
    bm, base_streams = drive(base, prompts)
    base = None

    points = []
    sweep = ([("linear", None, budget, "chunk")]
             + [(",".join(str(f) for f in s.fanouts), s, s.depth, "chunk")
                for s in shapes]
             + [(",".join(str(f) for f in shapes[0].fanouts), shapes[0],
                 shapes[0].depth, "exact")])
    for tag, shape, k, impl in sweep:
        eng = InferenceEngine(
            cfg, params, draft_cfg=cfg, draft_params=draft, spec_k=k,
            spec_tree=None if shape is None else tag,
            spec_verify_impl=impl, **common)
        drive(eng, warm_prompts)
        eng.reset()
        m, streams = drive(eng, prompts)
        mismatched = sum(streams[rid] != base_streams[rid]
                         for rid in base_streams)
        if impl == "exact":
            # the escape-hatch contract: primary-chain micro-step verify
            # shares the decode program's op shapes, so this holds by
            # construction (tests/test_spec_decode.py pins it too)
            assert mismatched == 0, (
                f"exact-impl tree {tag} diverged from greedy baseline "
                f"in {mismatched} stream(s)")
        if shape is None:
            accepted = (m["spec_accepted_tokens"]
                        / max(m["spec_rounds"], 1))
        else:
            accepted = m["spec_accepted_per_round"]
        points.append({
            "shape": tag,
            "verify_impl": impl,
            "nodes": 1 + budget,
            "draft_tokens_per_round": budget,
            "accepted_per_round": round(accepted, 3),
            "acceptance_rate": round(m["spec_acceptance_rate"], 3),
            "spec_rounds": m["spec_rounds"],
            "branch_utilization": (
                None if shape is None
                else round(m["spec_tree_branch_utilization"], 3)),
            "tokens_per_sec": round(m["tokens_per_sec"], 1),
            "wall_seconds": round(m["wall_seconds"], 3),
            "bit_match_greedy": mismatched == 0,
            "mismatched_streams": mismatched,
            "leak_guard_clean": True,     # strict audit inside run()
        })
        eng = None

    linear_pt = points[0]
    best = max((p for p in points[1:] if p["verify_impl"] == "chunk"),
               key=lambda p: p["accepted_per_round"])
    gain = best["accepted_per_round"] / max(linear_pt["accepted_per_round"],
                                            1e-9)
    assert gain > 1.0, (
        f"no tree shape beat the linear {budget}-chain on accepted tokens "
        f"per verify dispatch (best {best['shape']}: "
        f"{best['accepted_per_round']} vs {linear_pt['accepted_per_round']})")
    return {
        "metric": (f"tree vs linear speculation, accepted tokens per "
                   f"verify dispatch at a fixed {budget}-draft-token "
                   f"budget ({args.model}, vocab {vocab}, prompt "
                   f"{prompt_len}, gen {gen}, {slots} slots, {eps:g} "
                   f"draft noise, chunk verify, backend "
                   f"{jax.default_backend()})"),
        "value": round(gain, 2),
        "unit": "x linear k-chain accepted/round at equal draft budget",
        "best_shape": best["shape"],
        "draft_budget": budget,
        "draft_noise": eps,
        "baseline_tokens_per_sec": round(bm["tokens_per_sec"], 1),
        "points": points,
    }


def _serving_load(args, vocab):
    """Latency under LOAD: seeded arrival processes instead of a fixed-N
    batch dropped on the scheduler at t=0.

    The other scenarios measure steady-state throughput with every request
    present up front; real serving latency (TTFT especially) is dominated
    by what ARRIVES while the slots are busy. This scenario drives the
    scheduler through an arrival schedule measured in TICKS — one tick per
    scheduler loop iteration — so the load pattern is deterministic across
    machines while the latencies stay wall-clock-true:

    - ``poisson``: exponential interarrivals (mean 2 ticks) — sustained
      random load with occasional coincident arrivals.
    - ``bursty``: waves of 6 requests landing on the same tick every 24
      ticks — the queue-depth spike that separates p99 TTFT from p50.

    Prompt and output lengths are mixed per request (seeded draws from
    short/medium/long), and the grid crosses both processes with spec
    decoding off/on (the draft is the TARGET's own weights — the
    acceptance ceiling, so the spec points price the round structure
    under load, not draft quality). TTFT/TPOT percentiles come from the
    scheduler's own per-request Completion timestamps (the same numbers
    the [LATENCY] drain audit and /metrics histograms report); the
    zero-dropped-requests pin is the load-shedding contract: every
    submitted request completes.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    # seq_len=256 for the RoPE table (tiny preset ships 128)
    cfg = get_config(args.model, vocab_size=vocab, seq_len=256)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    slots, bs, spec_k = 4, 16, 4
    prompt_lens, gen_lens = (8, 24, 64), (8, 16, 32)
    n = args.requests
    common = dict(slots=slots, max_len=128, prefill_buckets=(16, 32, 64),
                  kv_layout="paged", kv_block_size=bs)
    engines = {
        False: InferenceEngine(cfg, params, **common),
        True: InferenceEngine(cfg, params, draft_cfg=cfg,
                              draft_params=params, spec_k=spec_k, **common),
    }

    def workload(process):
        # seeded by PROCESS only, so the spec on/off points of one process
        # serve the identical prompt set and are directly comparable
        lrng = np.random.default_rng(
            args.seed + {"poisson": 11, "bursty": 22}[process])
        ticks, t = [], 0
        for i in range(n):
            if process == "poisson":
                t += int(lrng.exponential(2.0))
            else:
                t = (i // 6) * 24
            ticks.append(t)
        specs = [(int(lrng.choice(prompt_lens)), int(lrng.choice(gen_lens)))
                 for _ in range(n)]
        prompts = [lrng.integers(3, vocab, size=pl).tolist()
                   for pl, _ in specs]
        return ticks, specs, prompts

    def warm(engine):
        lrng = np.random.default_rng(args.seed + 999)
        _run_stream(engine, [
            Request(id=f"warm{i}",
                    prompt=lrng.integers(3, vocab, size=pl).tolist(),
                    max_new_tokens=4)
            for i, pl in enumerate(prompt_lens)])
        engine.reset()

    def drive(engine, process):
        ticks, specs, prompts = workload(process)
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None,
                          registry=MetricRegistry())
        submitted, tick = 0, 0
        t0 = time.monotonic()
        while submitted < n or sched.pending():
            while submitted < n and ticks[submitted] <= tick:
                sched.submit(Request(id=f"req{submitted}",
                                     prompt=prompts[submitted],
                                     max_new_tokens=specs[submitted][1]))
                submitted += 1
            if sched.pending():
                sched.step()
            tick += 1
        m = sched.metrics()
        m["wall_seconds"] = time.monotonic() - t0
        return m

    points = []
    for spec_on in (False, True):
        engine = engines[spec_on]
        warm(engine)
        for process in ("poisson", "bursty"):
            m = drive(engine, process)
            assert m["requests_completed"] == n, (
                f"{process} spec={spec_on}: dropped "
                f"{n - m['requests_completed']} of {n} requests")
            points.append({
                "process": process,
                "spec": spec_on,
                "requests_submitted": n,
                "requests_completed": m["requests_completed"],
                "dropped": n - m["requests_completed"],
                "tokens_generated": m["tokens_generated"],
                "max_concurrent": m["max_concurrent"],
                "ttft_p50_ms": round(m["ttft_p50_ms"], 2),
                "ttft_p95_ms": round(m["ttft_p95_ms"], 2),
                "ttft_p99_ms": round(m["ttft_p99_ms"], 2),
                "tpot_p50_ms": round(m["tpot_p50_ms"], 3),
                "tpot_p95_ms": round(m["tpot_p95_ms"], 3),
                "tpot_p99_ms": round(m["tpot_p99_ms"], 3),
                "tokens_per_sec": round(m["tokens_per_sec"], 1),
                "wall_seconds": round(m["wall_seconds"], 3),
            })
        engines[spec_on] = None

    worst = max(points, key=lambda p: p["ttft_p99_ms"])
    return {
        "metric": (f"p99 TTFT under seeded arrival load ({args.model}, "
                   f"vocab {vocab}, {slots} slots, {n} requests/point, "
                   f"mixed prompts {list(prompt_lens)} x gen "
                   f"{list(gen_lens)}, poisson+bursty arrivals, spec "
                   f"off/on k={spec_k}, backend {jax.default_backend()})"),
        "value": worst["ttft_p99_ms"],
        "unit": "ms p99 TTFT (worst point across the arrival x spec grid)",
        "slots": slots,
        "requests_per_point": n,
        "prompt_lens": list(prompt_lens),
        "gen_lens": list(gen_lens),
        "spec_k": spec_k,
        "dropped_total": sum(p["dropped"] for p in points),
        "worst_point": {"process": worst["process"], "spec": worst["spec"]},
        "points": points,
    }


def _spill_preempt(args, vocab):
    """Spill-to-host preemption vs head-of-line wait (the scheduler's
    tiered-KV lifecycle, inference/kv_cache.py + scheduler.py).

    A block pool sized BELOW the working set (17 usable blocks for three
    requests needing 20) plus a short interactive request arriving behind
    two long generations. With the spill tier OFF the short request
    head-of-line waits: its TTFT is the whole remaining decode of a long
    request. With ``--spill-dir`` set the scheduler preempts the coldest
    long request — exports its private blocks to a checksummed host
    artifact, frees the device row, admits the short request, and
    restores the victim on demand — so the short request's TTFT drops to
    roughly one spill export + its own prefill. Both runs must produce
    streams BITWISE identical to an unconstrained-pool reference (the
    fold_in(seed, step) statelessness the restore leans on); the receipt
    reports the TTFT both ways, the speedup, and the spill traffic
    (exports/restores/bytes). Each mode takes the best of
    ``--spill-repeats`` runs so first-run compilation doesn't smear the
    wall-clock numbers.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = get_config(args.model, vocab_size=vocab, seq_len=128)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    bs, slots, num_blocks = 8, 4, 18  # 17 usable; A/B/C need 8+8+4
    rng = np.random.default_rng(args.seed + 3)
    reqs = [
        Request(id="long0", prompt=rng.integers(3, vocab, size=17).tolist(),
                max_new_tokens=40, seed=1),
        Request(id="long1", prompt=rng.integers(3, vocab, size=19).tolist(),
                max_new_tokens=40, seed=2),
        Request(id="short", prompt=rng.integers(3, vocab, size=16).tolist(),
                max_new_tokens=12, temperature=0.8, top_p=0.9, seed=3),
    ]

    def build(num_blocks=None):
        return InferenceEngine(cfg, params, slots=slots, max_len=128,
                               prefill_buckets=(16, 32), kv_layout="paged",
                               kv_block_size=bs, kv_num_blocks=num_blocks)

    ref_sched = Scheduler(build())
    for r in reqs:
        ref_sched.submit(r)
    ref_sched.run()
    ref = {c.request_id: c.tokens for c in ref_sched.completed}

    repeats = getattr(args, "spill_repeats", 3)

    def run_mode(spill_on):
        best = None
        for _ in range(repeats):
            spill_dir = tempfile.mkdtemp(prefix="bench_spill_")
            shipped = []

            def note_spill(art_dir, ordinal):
                shipped.append(sum(
                    os.path.getsize(os.path.join(art_dir, n))
                    for n in os.listdir(art_dir)))

            engine = build(num_blocks=num_blocks)
            sched = Scheduler(engine,
                              spill_dir=spill_dir if spill_on else None,
                              on_spill=note_spill if spill_on else None)
            for r in reqs:
                sched.submit(r)
            t0 = time.monotonic()
            sched.run()
            wall = time.monotonic() - t0
            out = {c.request_id: c.tokens for c in sched.completed}
            assert out == ref, (
                "streams drifted from the unconstrained-pool reference "
                f"(spill_on={spill_on})")
            ttft = {c.request_id: c.ttft_seconds for c in sched.completed}
            point = {
                "wall_seconds": round(wall, 4),
                "ttft_short_ms": round(ttft["short"] * 1e3, 2),
                "ttft_ms": {k: round(v * 1e3, 2)
                            for k, v in sorted(ttft.items())},
                "spill_exports": sched.spill_exports,
                "spill_restores": sched.spill_restores,
                "spill_rejects": sched.spill_rejects,
                "spill_bytes": int(sum(shipped)),
            }
            shutil.rmtree(spill_dir, ignore_errors=True)
            if best is None or point["ttft_short_ms"] < \
                    best["ttft_short_ms"]:
                best = point
        return best

    off = run_mode(False)
    on = run_mode(True)
    assert on["spill_exports"] >= 1 and on["spill_restores"] >= 1, \
        "the constrained pool never spilled — scenario geometry broken"
    assert off["spill_exports"] == 0
    speedup = off["ttft_short_ms"] / max(on["ttft_short_ms"], 1e-9)
    return {
        "bench": "kv_spill",
        "scenario": "spill_preempt",
        "model": args.model,
        "backend": jax.default_backend(),
        "metric": (f"late-request TTFT, spill-to-host preemption vs "
                   f"head-of-line wait ({args.model}, vocab {vocab}, "
                   f"{slots} slots, {num_blocks - 1} usable blocks x "
                   f"{bs} positions, 2 long generations + 1 short, "
                   f"streams asserted bit-identical to an unconstrained "
                   f"reference, backend {jax.default_backend()})"),
        "value": round(speedup, 2),
        "unit": "x TTFT speedup for the late short request (off/on)",
        "block_size": bs,
        "num_blocks": num_blocks,
        "slots": slots,
        "bit_exact_vs_unconstrained": True,
        "spill_off": off,
        "spill_on": on,
    }


def _kv_quant(args, vocab):
    """int8 paged KV vs bf16 at the SAME pool byte budget (--kv-dtype).

    The budget is a bf16 pool sized below the traffic's working set so
    admission gates on free blocks (the long_context regime). The int8
    pool gets exactly that many BYTES — data at 1 byte/element plus the
    per-(block, kv-head) fp32 scale rows — which buys ~2x the blocks
    (the scale overhead keeps it just under: 2/(1 + 4/(block_size *
    head_dim))). Both engines run the fused-dequant pallas kernels (the
    int8 serving default) over identical greedy traffic; the receipt
    reports:

    - ``kv_blocks_total`` ratio at the fixed budget (nightly bar: >= 1.9x)
      and the concurrency that buys at the paged admission gate;
    - the greedy argmax flip rate between the bf16 and int8 streams —
      RECORDED, never asserted: int8 storage legitimately perturbs
      logits by ~the quantization step, so near-ties flip (the bit-pinned
      contracts are within-dtype; kernel_checks bounds the numeric gap);
    - teacher-forced NLL/perplexity on a held-out shard (fresh rng
      stream, never part of the traffic): prefill the context through
      each pool, then score every next true token via ``decode_logits``
      — the KV path is the ONLY thing that differs, so the delta is the
      accuracy price of int8 KV.
    """
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        block_bytes, blocks_per_slot, init_paged_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = get_config(args.model, vocab_size=vocab,
                     layer_impl=args.layer_impl)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    slots, prompt_len, gen, bs = 8, 24, 16, args.kv_block_size
    max_len = prompt_len + gen + bs
    n_req = max(args.requests, 12)
    rng = np.random.default_rng(args.seed + 7)
    prompts = [rng.integers(3, vocab, size=prompt_len).tolist()
               for _ in range(n_req)]

    # the byte budget, measured off probe pools (no engine build): a bf16
    # pool gating concurrency at ~half the slots, and whatever whole
    # number of int8 blocks fits in exactly those bytes
    bpb = {
        dt: block_bytes(init_paged_cache(
            cfg, 1, max_len, bs, num_blocks=2,
            dtype=jnp.int8 if dt == "int8" else None))
        for dt in ("bf16", "int8")}
    usable = {"bf16": 12}
    budget_bytes = usable["bf16"] * bpb["bf16"]
    usable["int8"] = budget_bytes // bpb["int8"]

    def run(engine):
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None)
        for i, pr in enumerate(prompts):
            sched.submit(Request(id=f"r{i}", prompt=pr,
                                 max_new_tokens=gen))
        t0 = time.monotonic()
        out = sched.run()
        m = sched.metrics()
        m["wall_seconds"] = time.monotonic() - t0
        return m, {c.request_id: c.tokens for c in out}

    # held-out shard for the teacher-forced NLL: its own rng stream, and
    # only as many sequences as fit the SMALLER (bf16) pool at full length
    nb = blocks_per_slot(max_len, bs)
    held_slots = max(usable["bf16"] // nb, 1)
    hrng = np.random.default_rng(args.seed + 97)
    held = hrng.integers(3, vocab, size=(held_slots, prompt_len + gen))
    rows = np.zeros((slots, nb), np.int32)
    rows[:held_slots] = np.arange(
        1, held_slots * nb + 1, dtype=np.int32).reshape(held_slots, nb)
    active = np.arange(slots) < held_slots

    def held_out_nll(engine):
        engine.reset()
        toks = np.zeros(slots, np.int32)
        for s in range(held_slots):
            engine.prefill(s, held[s, :prompt_len].tolist(),
                           block_row=rows[s])
        total = 0.0
        for i in range(prompt_len, prompt_len + gen - 1):
            toks[:held_slots] = held[:, i]
            logits = np.asarray(
                engine.decode_logits(toks, active, block_tables=rows),
                np.float64)
            logp = logits - np.log(
                np.exp(logits - logits.max(-1, keepdims=True)).sum(-1,
                       keepdims=True)) - logits.max(-1, keepdims=True)
            total -= logp[np.arange(held_slots), held[:, i + 1]].sum()
        return total / (held_slots * (gen - 1))

    summaries, streams, nlls = {}, {}, {}
    for dt in ("bf16", "int8"):
        kw = dict(slots=slots, prefill_buckets=(16, 32), kv_layout="paged",
                  kv_block_size=bs, kv_num_blocks=usable[dt] + 1,
                  paged_kernel="pallas")
        if dt == "int8":
            kw["kv_dtype"] = "int8"
        t0 = time.monotonic()
        engine = InferenceEngine(cfg, params, max_len=max_len, **kw)
        build_s = time.monotonic() - t0
        run(engine)                                    # warm every program
        m, streams[dt] = run(engine)
        assert m["kv_dtype"] == dt and m["kv_bytes_per_block"] == bpb[dt]
        nlls[dt] = held_out_nll(engine)
        summaries[dt] = {
            "kv_blocks_total": m["kv_blocks_total"],
            "kv_bytes_per_block": m["kv_bytes_per_block"],
            "pool_bytes": m["kv_blocks_total"] * m["kv_bytes_per_block"],
            "tokens_per_sec": round(m["tokens_per_sec"], 1),
            "max_concurrent": m["max_concurrent"],
            "kv_block_utilization_peak": round(
                m["kv_block_utilization_peak"], 3),
            "decode_p50_ms": round(m["decode_p50_ms"], 3),
            "requests": m["requests_completed"],
            "engine_build_seconds": round(build_s, 3),
        }
        engine = None                                  # free the pool

    flipped_reqs = sum(streams["int8"][r] != streams["bf16"][r]
                       for r in streams["bf16"])
    # positional mismatches overcount actual argmax flips: once one token
    # flips, the remaining stream decodes on divergent context — so the
    # first-divergence position per request is recorded alongside
    flipped_toks = sum(
        a != b for r in streams["bf16"]
        for a, b in zip(streams["bf16"][r], streams["int8"][r]))
    total_toks = sum(len(t) for t in streams["bf16"].values())
    first_flips = sorted(
        next(i for i, (a, b) in enumerate(zip(streams["bf16"][r],
                                              streams["int8"][r]))
             if a != b)
        for r in streams["bf16"] if streams["int8"][r] != streams["bf16"][r])

    blocks_ratio = (summaries["int8"]["kv_blocks_total"]
                    / summaries["bf16"]["kv_blocks_total"])
    ppl = {dt: float(np.exp(nlls[dt])) for dt in nlls}
    return {
        "bench": "kv_quant",
        "scenario": "kv_quant",
        "model": args.model,
        "backend": jax.default_backend(),
        "metric": (f"int8 KV blocks at the bf16 pool byte budget "
                   f"({args.model}, vocab {vocab}, {slots} slots, "
                   f"{n_req} greedy requests prompt {prompt_len} gen "
                   f"{gen}, block size {bs}, fused-dequant pallas "
                   f"kernels, backend {jax.default_backend()})"),
        "value": round(blocks_ratio, 3),
        "unit": "x kv_blocks_total at fixed pool bytes",
        "pool_budget_bytes": int(budget_bytes),
        "kv_block_size": bs,
        "paged_kernel": "pallas",
        "bytes_per_block_ratio": round(bpb["bf16"] / bpb["int8"], 3),
        "blocks_ratio": round(blocks_ratio, 3),
        "concurrency_gain": round(
            summaries["int8"]["max_concurrent"]
            / max(summaries["bf16"]["max_concurrent"], 1), 2),
        "bf16": summaries["bf16"],
        "int8": summaries["int8"],
        "greedy_flips": {
            "recorded_not_asserted": True,
            "requests_compared": n_req,
            "requests_flipped": int(flipped_reqs),
            "tokens_mismatched": int(flipped_toks),
            "token_mismatch_rate": round(
                flipped_toks / max(total_toks, 1), 4),
            "first_flip_positions": [int(i) for i in first_flips],
        },
        "held_out_perplexity": {
            "sequences": held_slots,
            "scored_tokens": held_slots * (gen - 1),
            "nll_bf16": round(nlls["bf16"], 6),
            "nll_int8": round(nlls["int8"], 6),
            "perplexity_bf16": round(ppl["bf16"], 4),
            "perplexity_int8": round(ppl["int8"], 4),
            "perplexity_delta": round(ppl["int8"] - ppl["bf16"], 4),
            "perplexity_rel_delta": round(
                (ppl["int8"] - ppl["bf16"]) / ppl["bf16"], 6),
        },
    }


def _disagg(args, vocab):
    """Disaggregated prefill/decode vs colocated at EQUAL total capacity.

    The interference a colocated server can't hide: a burst of long
    prompts lands while short interactive streams are decoding, and
    every scheduler iteration that runs a 64-token prefill chunk delays
    the next token of every active decode stream by that chunk's
    compute. Splitting the same 4 slots / same block pool into a
    2-slot prefill engine and a 2-slot decode engine moves the chunk
    work off the decode host entirely — the decode engine only ever
    imports committed block shipments (the device puts the colocated
    path never pays) and runs pure decode rounds.

    Both systems serve the identical seeded workload: steady short
    requests (mixed greedy/sampled) plus a same-tick burst of long
    prompts. The disaggregated pipeline is pumped in one process, so
    per-request Completion wall-clocks would charge the decode engine
    for prefill compute it never runs on its own host; instead both
    sides sample PER-DECODE-ROUND latency — the wall time of each
    scheduler iteration entered with at least one active decode slot,
    which is exactly the TPOT a caller streaming tokens observes
    (one committed token per active stream per round). The colocated
    samples include whatever prefill chunks shared the iteration; the
    decode engine's include its shipment imports. Each mode takes the
    best of two measured runs after a warmup pass.

    Receipt bars (pinned by scripts/ci_nightly.sh):

    - ``decode_p99_tpot_interference_ratio`` > 1.0 — colocated p99
      decode-round latency over disaggregated, at equal total slots
      and blocks;
    - ``dropped`` == 0 — every submitted request completes, on the
      decode engine for the disaggregated side;
    - ``bit_exact`` — the disaggregated streams (shipped-block imports,
      greedy and sampled alike) match the colocated streams token for
      token, every repeat.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    cfg = get_config(args.model, vocab_size=vocab, seq_len=256,
                     layer_impl=args.layer_impl)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    bs, buckets, max_len = 8, (16, 32, 64), 256
    n_short, short_prompt, short_gen = 8, 16, 32
    n_long, long_prompt, long_gen = 4, 192, 8
    repeats = 2

    def build(slots):
        return InferenceEngine(cfg, params, slots=slots, max_len=max_len,
                               prefill_buckets=buckets, kv_layout="paged",
                               kv_block_size=bs)

    # equal total capacity: 4 slots / 128 blocks colocated, split 2+2
    # slots / 64+64 blocks disaggregated (kv_num_blocks defaults to
    # slots * max_len / block_size on both sides)
    colo = build(4)
    pre_eng, dec_eng = build(2), build(2)

    wrng = np.random.default_rng(args.seed + 5)
    requests, arrivals = [], []
    for i in range(n_short):
        kw = ({} if i % 2 == 0 else
              {"temperature": 0.8, "top_p": 0.9})
        requests.append(Request(
            id=f"short{i}",
            prompt=wrng.integers(3, vocab, size=short_prompt).tolist(),
            max_new_tokens=short_gen, seed=100 + i, **kw))
        arrivals.append(2 * i)
    for i in range(n_long):
        requests.append(Request(
            id=f"long{i}",
            prompt=wrng.integers(3, vocab, size=long_prompt).tolist(),
            max_new_tokens=long_gen, seed=200 + i))
        arrivals.append(3)                       # the same-tick burst
    order = sorted(range(len(requests)), key=lambda i: arrivals[i])
    n = len(requests)

    def clone(r, **extra):
        return Request(id=r.id, prompt=list(r.prompt),
                       max_new_tokens=r.max_new_tokens,
                       temperature=r.temperature, top_p=r.top_p,
                       seed=r.seed, **extra)

    def drive_colocated():
        colo.reset()
        sched = Scheduler(colo, eos_token_id=None,
                          registry=MetricRegistry())
        samples, submitted, tick = [], 0, 0
        while submitted < n or sched.pending():
            while submitted < n and arrivals[order[submitted]] <= tick:
                sched.submit(clone(requests[order[submitted]]))
                submitted += 1
            if sched.pending():
                decoding = bool(sched.active)
                t0 = time.monotonic()
                sched.step()
                if decoding:
                    samples.append(time.monotonic() - t0)
            tick += 1
        streams = {c.request_id: c.tokens for c in sched.completed}
        return samples, streams, len(sched.completed)

    def drive_disagg(ship_dir):
        pre_eng.reset()
        dec_eng.reset()
        ships = {}

        def on_ship(req, art_dir, ordinal, seq, start, end, length):
            ships.setdefault(req.id, []).append(
                {"artifact": art_dir, "seq": seq, "start_block": start,
                 "end_block": end, "length": length})

        pre = Scheduler(pre_eng, eos_token_id=None, role="prefill",
                        ship_dir=ship_dir, on_ship=on_ship,
                        registry=MetricRegistry())
        dec = Scheduler(dec_eng, eos_token_id=None, role="decode",
                        registry=MetricRegistry())
        samples, submitted, handed, tick = [], 0, 0, 0
        while len(dec.completed) < n:
            while submitted < n and arrivals[order[submitted]] <= tick:
                pre.submit(clone(requests[order[submitted]]))
                submitted += 1
            if pre.pending():
                pre.step()                       # the prefill host's clock
            for c in pre.completed[handed:]:
                r = next(q for q in requests if q.id == c.request_id)
                dec.submit(clone(r, committed=tuple(c.tokens)),
                           shipments=ships.get(r.id), ship_gen=0)
            handed = len(pre.completed)
            if dec.pending():
                decoding = bool(dec.active)
                t0 = time.monotonic()
                dec.step()                       # the decode host's clock
                if decoding:
                    samples.append(time.monotonic() - t0)
            tick += 1
        streams = {c.request_id: c.tokens for c in dec.completed}
        return samples, streams, len(dec.completed)

    def p99(samples):
        return float(np.percentile(np.asarray(samples) * 1000.0, 99))

    def p50(samples):
        return float(np.percentile(np.asarray(samples) * 1000.0, 50))

    # warmup compiles every bucket, the decode programs, and the
    # shipment export/import paths on both sides
    warm_dir = tempfile.mkdtemp(prefix="disagg_warm_")
    try:
        drive_colocated()
        drive_disagg(warm_dir)
    finally:
        shutil.rmtree(warm_dir, ignore_errors=True)

    colo_runs, dis_runs, bit_exact, dropped = [], [], True, 0
    ref_streams = None
    for _ in range(repeats):
        ship_dir = tempfile.mkdtemp(prefix="disagg_bench_")
        try:
            c_samples, c_streams, c_done = drive_colocated()
            d_samples, d_streams, d_done = drive_disagg(ship_dir)
        finally:
            shutil.rmtree(ship_dir, ignore_errors=True)
        dropped += (n - c_done) + (n - d_done)
        bit_exact = bit_exact and (c_streams == d_streams)
        if ref_streams is None:
            ref_streams = c_streams
        bit_exact = bit_exact and (c_streams == ref_streams)
        colo_runs.append(c_samples)
        dis_runs.append(d_samples)

    colo_best = min(colo_runs, key=p99)
    dis_best = min(dis_runs, key=p99)
    ratio = p99(colo_best) / p99(dis_best)
    return {
        "bench": "disagg",
        "scenario": "disagg",
        "model": args.model,
        "backend": jax.default_backend(),
        "metric": (f"colocated / disaggregated p99 decode-round latency "
                   f"(~TPOT) under a same-tick long-prompt burst "
                   f"({args.model}, vocab {vocab}, 4 slots total both "
                   f"sides, {n_short} short prompt {short_prompt} gen "
                   f"{short_gen} mixed greedy/sampled + {n_long} long "
                   f"prompt {long_prompt} gen {long_gen}, chunk "
                   f"{max(buckets)}, block size {bs}, best of {repeats}, "
                   f"backend {jax.default_backend()})"),
        "value": round(ratio, 3),
        "unit": "x p99 decode-round latency, colocated over disaggregated",
        "decode_p99_tpot_interference_ratio": round(ratio, 3),
        "dropped": int(dropped),
        "bit_exact": bool(bit_exact),
        "requests": n,
        "slots_total": 4,
        "split": {"prefill_slots": 2, "decode_slots": 2},
        "kv_block_size": bs,
        "prefill_buckets": list(buckets),
        "colocated": {
            "decode_round_p50_ms": round(p50(colo_best), 3),
            "decode_round_p99_ms": round(p99(colo_best), 3),
            "decode_rounds_sampled": len(colo_best),
        },
        "disaggregated": {
            "decode_round_p50_ms": round(p50(dis_best), 3),
            "decode_round_p99_ms": round(p99(dis_best), 3),
            "decode_rounds_sampled": len(dis_best),
            "shipments_per_long_request": long_prompt // max(buckets),
        },
    }


def _transport(args, vocab):
    """Mem-lane vs fs-lane KV transport at EQUAL capacity, plus the
    sub-train (partial prefix) hit rate of the fleet store.

    Part 1 — shipment landing. The same disaggregated prefill/decode
    split (2+2 slots) serves the identical seeded workload twice: once
    over the fs lane (artifact files re-read, CRC'd and device_put on
    the decode host — what crossing hosts costs) and once over the mem
    lane (the prefill host pushes the block train's device arrays into
    the shared fabric at export; the decode host verifies manifest
    METADATA — geometry, lengths, chain digest — and lands the whole
    train in one scatter, never touching payload bytes). Landing
    latency is the decode host's per-train import wall time,
    ``transport.land_seconds[lane] / trains landed``, best of two
    measured runs after a warmup. Both lanes must reproduce the
    colocated reference streams BITWISE — the speedup is worthless if
    the bytes aren't the same.

    Part 2 — sub-train addressability. A publisher commits
    staggered-length full trains to a fleet store; fetchers then ask
    for proper PREFIXES of those trains. Every prefix ask must hit
    PARTIALLY (import only the covered blocks, chunk-prefill the
    rest), and the fetched streams must match storeless references.

    Receipt bars (pinned by scripts/ci_nightly.sh and bench_trend):

    - ``mem_lane_landing_speedup`` > 1.0 — fs over mem per-train
      landing latency at fixed capacity;
    - ``bit_exact`` — fs, mem and partial-hit streams all match their
      references token for token;
    - ``partial_hit_rate`` > 0 — staggered prefix asks land as
      sub-train hits, not misses;
    - ``dropped`` == 0.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.inference.transport import (
        MemFabric, MemTransport, make_transport)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    cfg = get_config(args.model, vocab_size=vocab, seq_len=256,
                     layer_impl=args.layer_impl)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    bs, buckets, max_len = 8, (16, 32, 64), 256
    repeats = 2

    def build(slots):
        return InferenceEngine(cfg, params, slots=slots, max_len=max_len,
                               prefill_buckets=buckets, kv_layout="paged",
                               kv_block_size=bs)

    colo = build(4)
    pre_eng, dec_eng = build(2), build(2)

    # staggered-length prompts: four lengths, mixed greedy/sampled, so
    # trains of 2..12 blocks cross the lane under one fixed capacity
    wrng = np.random.default_rng(args.seed + 9)
    lengths = (16, 48, 64, 96)
    requests = []
    for i in range(8):
        kw = ({} if i % 2 == 0 else {"temperature": 0.8, "top_p": 0.9})
        requests.append(Request(
            id=f"r{i}",
            prompt=wrng.integers(
                3, vocab, size=lengths[i % len(lengths)]).tolist(),
            max_new_tokens=16, seed=300 + i, **kw))
    n = len(requests)

    def clone(r, **extra):
        return Request(id=r.id, prompt=list(r.prompt),
                       max_new_tokens=r.max_new_tokens,
                       temperature=r.temperature, top_p=r.top_p,
                       seed=r.seed, **extra)

    def drive_colocated():
        colo.reset()
        sched = Scheduler(colo, eos_token_id=None,
                          registry=MetricRegistry())
        for r in requests:
            sched.submit(clone(r))
        sched.run()
        return {c.request_id: c.tokens for c in sched.completed}

    def drive_lane(lane, ship_dir):
        """One full prefill -> decode pass over ``lane``; returns
        (streams, per-train landing seconds, completed, fallbacks)."""
        pre_eng.reset()
        dec_eng.reset()
        fabric = MemFabric() if lane == "mem" else None
        ships = {}

        def on_ship(req, art_dir, ordinal, seq, start, end, length):
            ships.setdefault(req.id, []).append(
                {"artifact": art_dir, "seq": seq, "start_block": start,
                 "end_block": end, "length": length})

        pre = Scheduler(pre_eng, eos_token_id=None, role="prefill",
                        ship_dir=ship_dir, on_ship=on_ship,
                        transport=make_transport(lane, fabric=fabric),
                        registry=MetricRegistry())
        dec = Scheduler(dec_eng, eos_token_id=None, role="decode",
                        transport=make_transport(lane, fabric=fabric),
                        registry=MetricRegistry())
        for r in requests:
            pre.submit(clone(r))
        pre.run()
        first = {c.request_id: c.tokens for c in pre.completed}
        for r in requests:
            dec.submit(clone(r, committed=tuple(first[r.id])),
                       shipments=ships.get(r.id), ship_gen=0)
        dec.run()
        streams = {c.request_id: c.tokens for c in dec.completed}
        landed = (dec.mem_lane_imports if lane == "mem"
                  else len(dec.completed))
        per_train = (dec.transport.land_seconds[lane] / landed
                     if landed else float("inf"))
        return streams, per_train, len(dec.completed), dec.lane_fallbacks

    # warmup compiles prefill buckets, decode programs and both lanes'
    # export/land paths
    warm = tempfile.mkdtemp(prefix="xport_warm_")
    try:
        drive_colocated()
        drive_lane("fs", os.path.join(warm, "fs"))
        drive_lane("mem", os.path.join(warm, "mem"))
    finally:
        shutil.rmtree(warm, ignore_errors=True)

    ref = drive_colocated()
    lane_best = {"fs": float("inf"), "mem": float("inf")}
    bit_exact, dropped, fallbacks = True, 0, 0
    for _ in range(repeats):
        root = tempfile.mkdtemp(prefix="xport_bench_")
        try:
            for lane in ("fs", "mem"):
                streams, per_train, done, fb = drive_lane(
                    lane, os.path.join(root, lane))
                lane_best[lane] = min(lane_best[lane], per_train)
                bit_exact = bit_exact and (streams == ref)
                dropped += n - done
                fallbacks += fb
        finally:
            shutil.rmtree(root, ignore_errors=True)

    # Part 2: staggered prefix asks against published full trains
    store_root = tempfile.mkdtemp(prefix="xport_store_")
    prefixes = (40, 72)                 # 5 and 9 of the 12 blocks
    full_len, fetches, partial, fetch_exact = 96, 0, 0, True
    try:
        fabric = MemFabric()
        base = [wrng.integers(3, vocab, size=full_len).tolist()
                for _ in range(2)]
        pub = Scheduler(build(4), eos_token_id=None,
                        kv_store=BlockStore(store_root, writer="pub"),
                        transport=MemTransport(fabric),
                        registry=MetricRegistry())
        for i, p in enumerate(base):
            pub.submit(Request(id=f"pub{i}", prompt=p, max_new_tokens=4,
                               seed=400 + i))
        pub.run()
        asks = [Request(id=f"ask{i}_{j}", prompt=p[:cut],
                        max_new_tokens=8, seed=500 + 10 * i + j)
                for i, p in enumerate(base)
                for j, cut in enumerate(prefixes)]
        noref = Scheduler(build(4), eos_token_id=None,
                          registry=MetricRegistry())
        for r in asks:
            noref.submit(clone(r))
        noref.run()
        want = {c.request_id: c.tokens for c in noref.completed}
        fet = Scheduler(build(4), eos_token_id=None,
                        kv_store=BlockStore(store_root, writer="fetch"),
                        transport=MemTransport(fabric),
                        registry=MetricRegistry())
        for r in asks:
            fet.submit(clone(r))
        fet.run()
        got = {c.request_id: c.tokens for c in fet.completed}
        fetches, partial = fet.store_fetches, fet.store_partial_hits
        fetch_exact = got == want
        bit_exact = bit_exact and fetch_exact
        dropped += len(asks) - len(fet.completed)
    finally:
        shutil.rmtree(store_root, ignore_errors=True)

    speedup = lane_best["fs"] / lane_best["mem"]
    return {
        "bench": "kv_transport",
        "scenario": "transport",
        "model": args.model,
        "backend": jax.default_backend(),
        "metric": (f"fs / mem lane per-train shipment-landing latency on "
                   f"the decode host at equal capacity ({args.model}, "
                   f"vocab {vocab}, 2+2 slots, {n} staggered prompts "
                   f"{'/'.join(str(x) for x in lengths)} tokens, block "
                   f"size {bs}, best of {repeats}, backend "
                   f"{jax.default_backend()})"),
        "value": round(speedup, 3),
        "unit": "x per-train landing latency, fs lane over mem lane",
        "mem_lane_landing_speedup": round(speedup, 3),
        "bit_exact": bool(bit_exact),
        "dropped": int(dropped),
        "lane_fallbacks": int(fallbacks),
        "requests": n,
        "kv_block_size": bs,
        "prefill_buckets": list(buckets),
        "shipment_landing": {
            "fs_ms_per_train": round(lane_best["fs"] * 1000.0, 3),
            "mem_ms_per_train": round(lane_best["mem"] * 1000.0, 3),
            "trains_per_run": n,
        },
        "partial_hits": {
            "store_fetches": int(fetches),
            "partial_hits": int(partial),
            "partial_hit_rate": round(partial / fetches, 3) if fetches
            else 0.0,
            "prefix_asks": len(prefixes) * 2,
            "published_trains": 2,
            "train_blocks": full_len // bs,
            "streams_bit_exact": bool(fetch_exact),
        },
        "partial_hit_rate": round(partial / fetches, 3) if fetches
        else 0.0,
    }


def _adapter_serving(args, vocab):
    """Batched heterogeneous-adapter decode vs sequential per-adapter
    serving at a FIXED adapter-pool byte budget.

    K tenants' LoRA adapters (plus the null adapter — base-only traffic)
    share one base model. The BATCHED mode serves all tenants' requests
    through one scheduler: slots carrying DIFFERENT adapters batch into
    the same fused decode dispatch, each gathering its own adapter pages
    via its slot's page-table row. The SEQUENTIAL mode is what a
    per-adapter deployment does at the same pool budget: one scheduler
    pass per tenant, only that tenant's requests admitted, so the slot
    batch runs mostly empty while every other tenant queues. Same
    engine, same compiled programs, same resident pool — the ONLY
    difference is whether heterogeneous adapters may share a dispatch.

    Receipt bars (pinned by scripts/ci_nightly.sh and bench_trend):

    - ``batched_vs_sequential_speedup`` > 1.0 — wall-time ratio at equal
      pool bytes;
    - ``bit_exact`` — every batched stream matches its sequential
      single-tenant run token for token (and the null-adapter stream is
      the base model's);
    - ``dropped`` == 0 — both modes complete every request.
    """
    import shutil
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.inference.adapters import (
        init_adapter_factors, write_adapter_artifact)
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    cfg = get_config(args.model, vocab_size=vocab,
                     layer_impl=args.layer_impl)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(args.seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    rank, slots, repeats = 4, 4, 2
    adapters = ("t0", "t1", "t2", "")  # three tenants + base-only lane

    eng = InferenceEngine(cfg, params, slots=slots, max_len=64,
                          prefill_buckets=(16,), kv_layout="paged",
                          kv_block_size=8, adapter_rank=rank)
    layout = eng._adapter_layout
    pool_bytes = eng.adapter_num_pages * layout.page_elems * 4

    root = tempfile.mkdtemp(prefix="bench_adapters_")
    try:
        for i, name in enumerate(a for a in adapters if a):
            facts = init_adapter_factors(layout, seed=args.seed + 10 + i,
                                         scale=0.5)
            ent = write_adapter_artifact(root, name, 1, facts, rank=rank,
                                         alpha=32.0)
            eng.adapters.register(name, os.path.join(root, ent["path"]))

        # two requests per tenant, mixed greedy/sampled — each tenant's
        # streams are seeded, so batched and sequential runs must agree
        wrng = np.random.default_rng(args.seed + 5)
        requests = []
        for i, name in enumerate(adapters * 2):
            kw = ({} if i % 2 == 0 else {"temperature": 0.8,
                                         "top_p": 0.9})
            requests.append(Request(
                id=f"r{i}", adapter=name,
                prompt=wrng.integers(3, vocab,
                                     size=8 + (i % 4) * 2).tolist(),
                max_new_tokens=16, seed=700 + i, **kw))
        n = len(requests)

        def clone(r):
            return Request(id=r.id, prompt=list(r.prompt),
                           max_new_tokens=r.max_new_tokens,
                           temperature=r.temperature, top_p=r.top_p,
                           seed=r.seed, adapter=r.adapter)

        def drive(reqs):
            eng.reset()
            sched = Scheduler(eng, eos_token_id=None,
                              registry=MetricRegistry())
            for r in reqs:
                sched.submit(clone(r))
            t0 = time.monotonic()
            sched.run()
            dt = time.monotonic() - t0
            return ({c.request_id: c.tokens for c in sched.completed},
                    dt, sched)

        def run_batched():
            return drive(requests)

        def run_sequential():
            streams, total = {}, 0.0
            for name in adapters:
                got, dt, _ = drive([r for r in requests
                                    if r.adapter == name])
                streams.update(got)
                total += dt
            return streams, total

        run_batched()  # warmup: compiles + pages every adapter in
        run_sequential()
        bat_t, seq_t = float("inf"), float("inf")
        for _ in range(repeats):
            bat_streams, dt, bat_sched = run_batched()
            bat_t = min(bat_t, dt)
            seq_streams, dt = run_sequential()
            seq_t = min(seq_t, dt)

        bit_exact = bat_streams == seq_streams
        tokens = sum(len(t) for t in bat_streams.values())
        dropped = (n - len(bat_streams)) + (n - len(seq_streams))
        am = bat_sched.metrics()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    return {
        "scenario": "adapter_serving",
        "model": args.model,
        "slots": slots,
        "adapter_rank": rank,
        "adapters": len([a for a in adapters if a]),
        "pool_pages": eng.adapter_num_pages,
        "pool_bytes": pool_bytes,
        "pages_per_adapter": layout.pages_per_adapter,
        "requests": n,
        "tokens": tokens,
        "batched_seconds": round(bat_t, 4),
        "sequential_seconds": round(seq_t, 4),
        "batched_tok_per_s": round(tokens / bat_t, 2),
        "sequential_tok_per_s": round(tokens / seq_t, 2),
        "batched_vs_sequential_speedup": round(seq_t / bat_t, 3),
        "adapter_pageins": int(am["adapter_pageins"]),
        "adapter_evictions": int(am["adapter_evictions"]),
        "bit_exact": bool(bit_exact),
        "dropped": int(dropped),
    }


if __name__ == "__main__":
    main()
