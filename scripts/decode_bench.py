"""Continuous-batching decode throughput/latency bench (inference/).

Builds an InferenceEngine (random params by default, or a real checkpoint
via --checkpoint-path/--checkpoint-job-id), drives the scheduler with
synthetic concurrent requests, and writes BENCH_decode_<model>_<backend>.json
with the serving headline numbers: tokens/sec, tokens/sec/slot, and p50/p95
per-decode-iteration latency.

Run on the chip:  python scripts/decode_bench.py --model tiny --slots 8
CPU smoke:        JAX_PLATFORMS=cpu python scripts/decode_bench.py
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="tiny")
    p.add_argument("--vocab-size", type=int, default=0)
    p.add_argument("--layer-impl", default="loop", choices=("loop", "scan"))
    p.add_argument("--slots", type=int, default=4)
    p.add_argument("--max-len", type=int, default=0)
    p.add_argument("--prompt-len", type=int, default=32)
    p.add_argument("--max-new-tokens", type=int, default=64)
    p.add_argument("--requests", type=int, default=16)
    p.add_argument("--warmup-requests", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--checkpoint-path", default="")
    p.add_argument("--checkpoint-job-id", default="")
    p.add_argument("--out", default="")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.data.tokenizer import load_tokenizer
    from fault_tolerant_llm_training_tpu.inference.engine import InferenceEngine
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request,
        Scheduler,
    )
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    vocab = args.vocab_size or load_tokenizer("byte").vocab_size
    cfg = get_config(args.model, vocab_size=vocab,
                     layer_impl=args.layer_impl)
    max_len = args.max_len or min(cfg.seq_len,
                                  args.prompt_len + args.max_new_tokens)

    t0 = time.monotonic()
    if args.checkpoint_path:
        engine = InferenceEngine.from_checkpoint(
            args.checkpoint_path, args.checkpoint_job_id, cfg,
            slots=args.slots, max_len=max_len)
    else:
        model = Transformer(cfg)
        params = model.init(jax.random.PRNGKey(args.seed),
                            jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
        engine = InferenceEngine(cfg, params, slots=args.slots,
                                 max_len=max_len)
    build_seconds = time.monotonic() - t0

    rng = np.random.default_rng(args.seed)

    def _requests(n, tag):
        return [Request(id=f"{tag}{i}",
                        prompt=rng.integers(3, vocab,
                                            size=args.prompt_len).tolist(),
                        max_new_tokens=args.max_new_tokens)
                for i in range(n)]

    # warmup: touch every prefill bucket/decode program once off the clock
    warm = Scheduler(engine, eos_token_id=None)
    for r in _requests(max(args.warmup_requests, 1), "warm"):
        warm.submit(r)
    warm.run()
    engine.reset()

    sched = Scheduler(engine, eos_token_id=None)
    for r in _requests(args.requests, "req"):
        sched.submit(r)
    t0 = time.monotonic()
    sched.run()
    wall = time.monotonic() - t0
    m = sched.metrics()

    backend = jax.default_backend()
    result = {
        "metric": (f"decode tokens/sec/slot ({args.model}, {args.slots} "
                   f"slots, prompt {args.prompt_len}, gen "
                   f"{args.max_new_tokens}, backend {backend})"),
        "value": round(m["tokens_per_sec_per_slot"], 1),
        "unit": "tokens/sec/slot",
        "tokens_per_sec": round(m["tokens_per_sec"], 1),
        "decode_p50_ms": round(m["decode_p50_ms"], 3),
        "decode_p95_ms": round(m["decode_p95_ms"], 3),
        "requests": m["requests_completed"],
        "tokens_generated": m["tokens_generated"],
        "max_concurrent": m["max_concurrent"],
        "iterations": m["iterations"],
        "wall_seconds": round(wall, 3),
        "engine_build_seconds": round(build_seconds, 3),
        "restored_step": engine.restored_step,
    }
    print(json.dumps(result))
    out = args.out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        f"BENCH_decode_{args.model}_{backend}.json")
    with open(out, "w") as f:
        json.dump(result, f, indent=1)
        f.write("\n")
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
