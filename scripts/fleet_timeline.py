"""Fleet timeline: fold every host's observability trail into ONE
HLC-ordered, anomaly-annotated timeline — the post-mortem view.

A fleet incident leaves its evidence scattered: each host's flight
recorder (``events_*.jsonl``), each process's span trail
(``trace_*.jsonl``), the request journal (one file per writer), and the
block-store journal. Reading them one host at a time with wall-clock
ordering lies under clock skew — a router 2 s ahead appears to fence a
host *before* the SIGKILL it reacted to. Every record is now stamped
with a hybrid logical clock (obs/hlc.py), so this tool merges all
trails and sorts by HLC: causal order, skew-proof. Records predating
the HLC stamp fall back to their wall clock (sorted before stamped
records at the same instant) and are flagged ``~`` in the output.

Anomalies are annotated inline so the chain of an incident reads top to
bottom: chaos injections, dead-host fence verdicts, migrations,
requeues, CRC rejects (handoff / shipment / spill / store fetch /
corrupt publish), and hot-reload swaps. ``scripts/chaos_campaign.py``
emits one of these timelines per scenario as its post-mortem report.

Usage:
    python scripts/fleet_timeline.py <dir-or-file> [more paths...]
    python scripts/fleet_timeline.py run/ --anomalies-only
    python scripts/fleet_timeline.py run/ --json --out timeline.json

See also (same trails, different folds):
    scripts/latency_report.py  — per-request TTFT/TPOT critical paths
    scripts/goodput_report.py  — restart-chain goodput %, MTTR, lost time
"""

import argparse
import glob
import json
import os
import sys
from typing import Dict, Iterable, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fault_tolerant_llm_training_tpu.obs import events, hlc  # noqa: E402
from fault_tolerant_llm_training_tpu.utils.logging import (  # noqa: E402
    AUDIT_FLEETSCOPE_TIMELINE_FMT,
    init_logger,
    logger,
)

# source stream inferred from record shape (no filename contract needed)
#   trace    — has "span" + "trace_id"          (obs/reqtrace.py)
#   store    — has "w" + "key"                  (inference/kvstore.py)
#   event    — has "kind" + "job"               (obs/events.py)
#   journal  — has "kind" + "id"                (inference/journal.py)


def classify(rec: Dict) -> Optional[str]:
    if "span" in rec and "trace_id" in rec:
        return "trace"
    if "w" in rec and "key" in rec:
        return "store"
    if "kind" in rec and "job" in rec:
        return "event"
    if "kind" in rec and "id" in rec:
        return "journal"
    return None


def annotate(stream: str, rec: Dict) -> Optional[str]:
    """Anomaly tag for one record, or None for routine traffic."""
    kind = str(rec.get("kind", rec.get("span", "")))
    text = " ".join(str(rec.get(k, ""))
                    for k in ("action", "reason", "detail", "fault"))
    blob = f"{kind} {text}".lower()
    if kind.startswith("chaos_") or rec.get("fault"):
        return "CHAOS"
    if kind in ("fleet_dead", "fenced") or (
            kind == "fleet_leave" and rec.get("reason") == "fenced"):
        return "FENCE"
    if "migrate" in blob or kind == "migration":
        return "MIGRATE"
    if kind in ("fleet_requeue", "requeue") or stream == "journal" and \
            kind == "requeue":
        return "REQUEUE"
    if "reject" in blob or "crc" in blob:
        return "CRC-REJECT"
    if "reload" in blob or kind == "weights_reload_rejected" or \
            kind == "reload_pause":
        return "RELOAD"
    if kind in ("signal", "exit") and str(rec.get("reason", "")) not in (
            "", "eos", "length", "drain", "done"):
        return "EXIT"
    return None


def _who(stream: str, rec: Dict) -> str:
    if stream == "store":
        return str(rec.get("w", "?"))
    if stream == "journal":
        return str(rec.get("host", rec.get("w", "?")))
    job = str(rec.get("job", ""))
    host = str(rec.get("host", ""))
    return job or host or "?"


def _summary(stream: str, rec: Dict) -> str:
    if stream == "trace":
        bits = [rec.get("span", "?"), f"req={rec.get('id', '?')}"]
        if rec.get("dur") is not None:
            bits.append(f"dur={float(rec['dur']):.4f}s")
    elif stream == "store":
        bits = [rec.get("kind", "?"), f"key={str(rec.get('key', ''))[:12]}"]
        if rec.get("blocks"):
            bits.append(f"blocks={rec['blocks']}")
    elif stream == "journal":
        bits = [rec.get("kind", "?"), f"req={rec.get('id', '?')}",
                f"gen={rec.get('gen', 0)}"]
        if rec.get("committed") is not None:
            bits.append(f"committed={len(rec['committed'])}")
        if rec.get("tokens") is not None:
            bits.append(f"tokens={len(rec['tokens'])}")
    else:
        bits = [rec.get("kind", "?")]
        for k in ("step", "id", "reason", "fault", "action", "src", "dst",
                  "replayed"):
            if rec.get(k) not in (None, ""):
                bits.append(f"{k}={rec[k]}")
    return " ".join(str(b) for b in bits)


def collect(paths: Iterable[str]) -> List[str]:
    """Expand files / dirs / globs to the JSONL files to fold."""
    files: List[str] = []
    for raw in paths:
        hits = glob.glob(raw)
        for path in (hits if hits else [raw]):
            if os.path.isdir(path):
                for root, _dirs, names in os.walk(path):
                    files.extend(os.path.join(root, n)
                                 for n in sorted(names)
                                 if n.endswith(".jsonl"))
            elif os.path.isfile(path):
                files.append(path)
    return sorted(set(files))


def build_timeline(files: Iterable[str]) -> List[Dict]:
    """Read every record, stamp a sort key, classify + annotate.

    Sort key: the record's HLC when present; otherwise one synthesized
    from its wall clock (``pack(t_us, 0)``) so pre-HLC trails still
    interleave sensibly — those entries carry ``stamped=False``."""
    entries: List[Dict] = []
    for path in files:
        try:
            with open(path) as fh:
                lines = fh.readlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn tail of a killed writer
            if not isinstance(rec, dict):
                continue
            stream = classify(rec)
            if stream is None:
                continue
            stamp = rec.get("hlc")
            stamped = bool(stamp)
            if not stamped:
                stamp = hlc.pack(int(float(rec.get("t", 0.0)) * 1e6), 0)
            entries.append({
                "hlc": str(stamp), "stamped": stamped,
                "t": float(rec.get("t", 0.0)), "stream": stream,
                "who": _who(stream, rec),
                "what": _summary(stream, rec),
                "anomaly": annotate(stream, rec),
                "file": os.path.basename(path), "rec": rec})
    entries.sort(key=lambda e: (e["hlc"], e["t"], e["who"]))
    return entries


def format_timeline(entries: List[Dict], anomalies_only: bool = False,
                    limit: int = 0) -> str:
    shown = [e for e in entries
             if not anomalies_only or e["anomaly"]]
    if limit:
        shown = shown[-limit:]
    hosts = sorted({e["who"] for e in entries})
    n_anom = sum(1 for e in entries if e["anomaly"])
    out = [f"fleet timeline: {len(entries)} record(s) from "
           f"{len(hosts)} participant(s) ({', '.join(hosts)}), "
           f"{n_anom} anomalie(s), HLC order",
           ""]
    width = max((len(e["who"]) for e in shown), default=4)
    for e in shown:
        mark = "!" if e["anomaly"] else ("~" if not e["stamped"] else " ")
        tag = f" [{e['anomaly']}]" if e["anomaly"] else ""
        out.append(f"{e['hlc']} {mark} {e['who']:<{width}} "
                   f"{e['stream']:<7} {e['what']}{tag}")
    if anomalies_only and not shown:
        out.append("(no anomalies)")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog="Related folds over the same trails:\n"
               "  scripts/latency_report.py   per-request TTFT/TPOT "
               "critical paths + SLO attainment\n"
               "  scripts/goodput_report.py   restart-chain goodput %, "
               "MTTR, lost time by failure class")
    p.add_argument("paths", nargs="+",
                   help="event/trace/journal JSONL files, directories, "
                        "or globs")
    p.add_argument("--out", default="",
                   help="write the timeline here instead of stdout")
    p.add_argument("--json", action="store_true",
                   help="emit the timeline entries as JSON")
    p.add_argument("--anomalies-only", action="store_true",
                   help="show only annotated (anomalous) records")
    p.add_argument("--limit", type=int, default=0,
                   help="show only the last N records (0 = all)")
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL for this fold's own audit "
                        "event")
    args = p.parse_args(argv)

    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="fleet_timeline", host=0)
    files = collect(args.paths)
    entries = build_timeline(files)
    if not entries:
        print(f"no records found under: {', '.join(args.paths)}",
              file=sys.stderr)
        return 1
    hosts = {e["who"] for e in entries}
    n_anom = sum(1 for e in entries if e["anomaly"])
    events.emit_audit(
        logger, AUDIT_FLEETSCOPE_TIMELINE_FMT.format(
            events=len(entries), hosts=len(hosts), anomalies=n_anom),
        "fleetscope_timeline", events=len(entries), hosts=len(hosts),
        anomalies=n_anom)
    if args.json:
        text = json.dumps([{k: v for k, v in e.items() if k != "rec"}
                           for e in entries], indent=2) + "\n"
    else:
        text = format_timeline(entries,
                               anomalies_only=args.anomalies_only,
                               limit=args.limit)
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(text)
    else:
        sys.stdout.write(text)
    events.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
