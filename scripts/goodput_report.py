"""Goodput report: stitch flight-recorder event logs across a restart chain.

The reference proves fault tolerance by eyeballing three Slurm ``.out``
files; this tool reads the structured event logs the same runs emit
(``<ckpt-path>/events/events_<jobid>.jsonl``, obs/events.py) and prints the
production reliability numbers: goodput %, MTTR per restart, tokens
re-trained after each resume, and time lost per failure class.

Usage:
    python scripts/goodput_report.py <events-dir-or-file> [more paths...]
    python scripts/goodput_report.py 'ckpts/events/events_*.jsonl'

Paths may be JSONL files, directories (all ``*.jsonl`` inside), or globs;
all events are pooled and grouped per job id before stitching.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fault_tolerant_llm_training_tpu.obs.goodput import (  # noqa: E402
    format_report,
    load_chain,
    stitch,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="+",
                   help="event-log files, directories, or globs")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of the table")
    args = p.parse_args(argv)

    events = load_chain(args.paths)
    if not events:
        print(f"no events found under: {', '.join(args.paths)}",
              file=sys.stderr)
        return 1
    report = stitch(events)
    if args.json:
        out = {
            "jobs": report.jobs,
            "wall_seconds": report.wall_seconds,
            "productive_seconds": report.productive_seconds,
            "replay_seconds": report.replay_seconds,
            "goodput_pct": report.goodput_pct,
            "mttr_seconds": report.mttr_seconds,
            "steps_reached": report.steps_reached,
            "tokens_trained": report.tokens_trained,
            "tokens_replayed": report.tokens_replayed,
            "lost_by_class": report.lost_by_class,
            "restarts": [
                {"from_job": r.from_job, "to_job": r.to_job,
                 "failure": r.failure, "mttr_seconds": r.mttr_seconds,
                 "replay_seconds": r.replay_seconds,
                 "replayed_steps": r.replayed_steps,
                 "replayed_tokens": r.replayed_tokens,
                 "restored_step": r.restored_step}
                for r in report.restarts
            ],
        }
        print(json.dumps(out, indent=2))
    else:
        print(format_report(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
