"""Convert checkpoints between the reference's torch format and this
framework's Orbax layout — the migration path for reference users.

The reference saves ``checkpoint_{JOBID}.ckpt`` via one ``torch.save``
(ref: utils.py:74-81); this framework saves an Orbax directory
``{path}/checkpoint_{JOBID}/{step}`` (checkpoint/manager.py). Both
directions preserve every tensor bit-for-bit (see checkpoint/convert.py),
so training resumed from a converted checkpoint continues exactly like a
native resume.

Usage (model flags must match the checkpoint's shape):

  # torch -> TPU: bring a reference checkpoint here, then resume with
  #   train.py --checkpoint-id <job-id> ...
  python scripts/convert_checkpoint.py to-tpu \
      --input checkpoints/checkpoint_444664.ckpt \
      --checkpoint-path checkpoints --job-id 444664 \
      --model llama3-8b --vocab-size 131072 --batch-size 1

  # TPU -> torch: produce a file the reference's train.py can load
  #   (torch.load + load_state_dict, ref train.py:20-24,56-77)
  python scripts/convert_checkpoint.py to-torch \
      --checkpoint-path checkpoints --job-id local \
      --model gpt2-125m --vocab-size 50257 \
      --output checkpoints/checkpoint_local.ckpt
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    common = dict(model="gpt2-125m", vocab_size=0, sequence_length=2048)
    for name in ("to-tpu", "to-torch"):
        s = sub.add_parser(name)
        s.add_argument("--model", type=str, default=common["model"])
        s.add_argument("--vocab-size", type=int, required=True)
        s.add_argument("--sequence-length", type=int,
                       default=common["sequence_length"])
        s.add_argument("--layer-impl", type=str, default="loop",
                       choices=["loop", "scan"],
                       help="Trunk form of the TPU-side checkpoint (must "
                            "match the --layer-impl it was/will be trained "
                            "with); the torch side is always the "
                            "reference's per-layer layout")
        s.add_argument("--learning-rate", type=float, default=1e-5)
        s.add_argument("--lr-warmup-steps", type=int, default=10)
        s.add_argument("--lr-schedule", type=str, default="constant",
                       choices=["constant", "cosine"],
                       help="must match the training run so the exported "
                            "current lr is the schedule's true value")
        s.add_argument("--lr-decay-steps", type=int, default=0)
        s.add_argument("--checkpoint-path", type=str, required=True,
                       help="Orbax checkpoint root (as in train.py)")
        s.add_argument("--job-id", type=str, required=True,
                       help="the {JOBID} in checkpoint_{JOBID}")
        s.add_argument("--step", type=int, default=None,
                       help="Orbax step (default: latest / training_step)")
    sub.choices["to-tpu"].add_argument(
        "--input", type=str, required=True, help="reference .ckpt file")
    sub.choices["to-tpu"].add_argument(
        "--batch-size", type=int, default=1,
        help="training batch size: the data position resumes at "
             "step*batch-size samples (the reference's replay semantics, "
             "ref train.py:36-39)")
    sub.choices["to-torch"].add_argument(
        "--output", type=str, required=True, help="reference .ckpt to write")
    args = p.parse_args(argv)

    import numpy as np
    import torch

    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.checkpoint.convert import (
        state_from_torch_ckpt,
        state_to_torch_ckpt,
    )
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )
    from fault_tolerant_llm_training_tpu.models import Transformer, get_config
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import make_optimizer

    import ml_dtypes

    def _t2n(t):
        """torch tensor -> numpy, routing bf16 through a uint16 view
        (torch cannot .numpy() a BFloat16 tensor)."""
        if not hasattr(t, "numpy"):
            return t
        if t.dtype == torch.bfloat16:
            return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
        return t.numpy()

    def _n2t(a):
        """numpy -> torch tensor, same bf16 routing for from_numpy."""
        if not isinstance(a, np.ndarray):
            return a
        a = np.ascontiguousarray(a)
        if a.dtype == ml_dtypes.bfloat16:
            return torch.from_numpy(a.view(np.uint16)).view(torch.bfloat16)
        return torch.from_numpy(a)

    cfg = get_config(args.model, vocab_size=args.vocab_size,
                     seq_len=args.sequence_length,
                     layer_impl=args.layer_impl)
    model = Transformer(cfg)
    optimizer = make_optimizer(args.learning_rate, args.lr_warmup_steps)
    mngr = CheckpointManager(args.checkpoint_path, args.job_id,
                             enable_async=False)

    if args.cmd == "to-tpu":
        if cfg.moe_experts:
            p.error("MoE models cannot import reference checkpoints: the "
                    "reference model is dense, so the torch file has no "
                    "experts/router params (ref model.py:218-254)")
        ckpt = torch.load(args.input, map_location="cpu",
                          weights_only=False)
        ckpt["model"] = {k: _t2n(v) for k, v in ckpt["model"].items()}
        for entry in ckpt["optimizer"]["state"].values():
            for k in ("exp_avg", "exp_avg_sq"):
                entry[k] = _t2n(entry[k])
        state = state_from_torch_ckpt(ckpt, model, optimizer,
                                      cfg.param_dtype)
        step = int(ckpt["training_step"])
        if args.step is not None and args.step != step:
            # state.step is the checkpoint's training_step; saving it under
            # a different step number would silently desync model and data
            p.error(f"--step {args.step} does not match the checkpoint's "
                    f"training_step {step}; omit --step for to-tpu")
        # Reference replay semantics (ref train.py:36-39): after N steps the
        # map-style loader has consumed N*batch_size samples. Resume the
        # converted checkpoint with --data-loading map (the mode the
        # reference's trainer actually uses); the packed iterator's position
        # is not reconstructible from a reference checkpoint.
        data_state = {"kind": "map",
                      "next_index": step * args.batch_size}
        mngr.save(step, state, data_state, wait=True)
        print(f"wrote {mngr.directory}/{step} (resume with "
              f"train.py --checkpoint-id {args.job_id} --data-loading map)")
    else:
        def init_fn(key):
            params = model.init(key, jnp.zeros((1, 8), jnp.int32))["params"]
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=optimizer.init(params))

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        # concrete single-device shardings: checkpoints written by sharded
        # meshes (fsdp/ep/pp runs) need an explicit placement to restore
        # outside their original topology. Restore to host CPU — the state
        # goes straight to numpy, and an fsdp-scale model would not fit
        # unsharded on one accelerator's HBM
        one = jax.sharding.SingleDeviceSharding(
            jax.local_devices(backend="cpu")[0])
        abstract = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=one),
            abstract)
        state, _, step = mngr.restore(abstract, step=args.step)
        out = state_to_torch_ckpt(state, cfg.n_layers, args.learning_rate,
                                  warmup_steps=args.lr_warmup_steps,
                                  lr_schedule=args.lr_schedule,
                                  decay_steps=args.lr_decay_steps)
        out["model"] = {k: _n2t(v) for k, v in out["model"].items()}
        for entry in out["optimizer"]["state"].values():
            entry["step"] = torch.tensor(float(entry["step"]))
            entry["exp_avg"] = _n2t(entry["exp_avg"])
            entry["exp_avg_sq"] = _n2t(entry["exp_avg_sq"])
        os.makedirs(os.path.dirname(os.path.abspath(args.output)),
                    exist_ok=True)
        torch.save(out, args.output)
        print(f"wrote {args.output} (step {step})")
    mngr.close()


if __name__ == "__main__":
    main()
