# Shared setup for the fault-chain demos (sourced by
# demo_fault_chain.sh and demo_sbatch_chain.sh): CPU-only JAX env with
# the compile cache, plus a synthetic-parquet generator. Keeping this in
# one file stops the two demos' environments from drifting.

demo_cpu_env() {
    export JAX_PLATFORMS=cpu
    unset PALLAS_AXON_POOL_IPS || true
    export JAX_COMPILATION_CACHE_DIR=${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_test_compile_cache}
    export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
}

# demo_make_parquet <output-path>
demo_make_parquet() {
    python - "$1" <<'EOF'
import sys
import numpy as np, pyarrow as pa, pyarrow.parquet as pq
rng = np.random.default_rng(0)
words = ['alpha','bravo','charlie','delta','echo','foxtrot']
docs = [' '.join(rng.choice(words, size=int(rng.integers(20,200)))) for _ in range(256)]
pq.write_table(pa.table({'text': docs}), sys.argv[1])
EOF
}
