"""Build a real Hugging Face fast tokenizer offline + a real-text corpus.

The reference trains with a hub tokenizer (ref: utils.py:133-137) that a
zero-egress TPU pod cannot download. This script makes the HF-tokenizer
data path measurable anyway (VERDICT round-1 missing item #2): it harvests
genuine English prose from the host (package docs, READMEs, changelogs,
license texts), trains a byte-level BPE on it with the `tokenizers`
library — the same Rust tokenization runtime every modern HF tokenizer
uses — and saves a `PreTrainedTokenizerFast` directory that
``--tokenizer-name-or-path <dir>`` loads through the exact
``AutoTokenizer.from_pretrained`` path the reference uses. Also writes the
harvested corpus as a `text`-column parquet (the reference's data
contract, ref: utils.py:118) for a real-data training run.

Usage:
  python scripts/build_bpe_tokenizer.py OUT_DIR [--vocab 16384]
  -> OUT_DIR/tokenizer/   (load with --tokenizer-name-or-path)
     OUT_DIR/corpus.parquet
"""

import argparse
import glob
import gzip
import os
import re
import sys


def harvest(max_bytes: int = 32 * 2**20):
    """Yield documents of real English prose found on the host."""
    roots = [
        "/usr/share/doc/*/README*", "/usr/share/doc/*/copyright",
        "/usr/share/doc/*/changelog*", "/usr/share/common-licenses/*",
        "/opt/venv/lib/python*/site-packages/*/README*",
        "/opt/venv/lib/python*/site-packages/*.dist-info/METADATA",
    ]
    seen = 0
    for pattern in roots:
        for path in sorted(glob.glob(pattern)):
            try:
                if path.endswith(".gz"):
                    raw = gzip.open(path, "rb").read(1 << 20)
                else:
                    raw = open(path, "rb").read(1 << 20)
                text = raw.decode("utf-8", errors="ignore")
            except OSError:
                continue
            # Keep prose-looking content only: drop control chars, require
            # some alphabetic density per paragraph.
            for para in re.split(r"\n\s*\n", text):
                para = para.strip()
                letters = sum(c.isalpha() for c in para)
                if len(para) >= 200 and letters / len(para) > 0.6:
                    yield para
                    seen += len(para)
                    if seen >= max_bytes:
                        return


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--vocab", type=int, default=16384)
    ap.add_argument("--max-mb", type=int, default=32)
    args = ap.parse_args()

    docs = list(harvest(args.max_mb * 2**20))
    total = sum(len(d) for d in docs)
    print(f"harvested {len(docs)} documents, {total / 2**20:.1f} MiB",
          flush=True)
    if total < 2**20:
        print("not enough text found on this host", file=sys.stderr)
        sys.exit(1)

    from tokenizers import ByteLevelBPETokenizer

    tok = ByteLevelBPETokenizer()
    tok.train_from_iterator(
        docs, vocab_size=args.vocab, min_frequency=2,
        special_tokens=["<pad>", "<bos>", "<eos>"])

    from transformers import PreTrainedTokenizerFast

    os.makedirs(args.out_dir, exist_ok=True)
    tok_path = os.path.join(args.out_dir, "tokenizer")
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok._tokenizer,
        pad_token="<pad>", bos_token="<bos>", eos_token="<eos>")
    fast.save_pretrained(tok_path)
    print(f"tokenizer ({fast.vocab_size} tokens) -> {tok_path}", flush=True)

    import pyarrow as pa
    import pyarrow.parquet as pq

    corpus = os.path.join(args.out_dir, "corpus.parquet")
    pq.write_table(pa.table({"text": docs}), corpus)
    print(f"corpus -> {corpus}", flush=True)


if __name__ == "__main__":
    main()
