"""Seeded chaos survival campaign: inject -> die/drain -> resume -> verify.

Runs each fault class end-to-end through the real CLI (train.py): a fresh
tiny-model job takes one scheduled fault (chaos/schedule.py grammar), the
exit policy runs (save / no-save / requeue), a chained job resumes from the
survivors' checkpoints, and the audit trail + flight-recorder event logs are
machine-checked — the same strings the reference's README greps for, plus
the integrity/fallback trail this repo adds. Per-scenario goodput % and MTTR
come from stitching the scenario's event logs (obs/goodput.py).

Usage:
    python scripts/chaos_campaign.py --seed 0
    python scripts/chaos_campaign.py --scenarios ckpt_corrupt,loader_stall \
        --out logs/chaos_campaign.txt

Scenario matrix (all seeded; faults land at step 12 of a 30-step run,
periodic checkpoints every 5 steps):

  sigusr1      SIGUSR1 via os.kill at step 12 -> save @13 + requeue
               attempt -> resume @13
  sigterm      SIGTERM at step 12 -> NO save -> resume from periodic @10
               (steps 11-12 are replayed, visible in the goodput report)
  exception    the reference's simulated error -> save @13, no requeue ->
               resume @13
  ckpt_corrupt error -> fault save @13 -> injector flips a seeded byte in
               the committed step-13 state -> the resume DETECTS it
               (integrity manifest), falls back to @10 audited, resumes
  loader_stall 2 s prefetch-worker stall at step 15; the run completes
               with every one of its 30 full-precision losses bit-equal
               to the clean baseline's (no token replayed, none skipped)
  deploy       continuous-deployment loop (deploy/): a publishing train
               run commits steps 5..30; a live serve.py --follow process
               starts on a rolled-back publish of step 10, absorbs hot
               swaps to 20 and 30 WITHOUT dropping its in-flight
               requests, rejects a chaos-corrupted publish of step 15
               (verify-before-load) while continuing to serve on 30, and
               its post-swap output streams bit-match a fresh serve
               restored directly at step 30
  fleet        serving-fleet migration (inference/fleet.py + router.py):
               two fleet hosts register heartbeat leases; the router
               admits 4 requests (3 greedy + 1 sampled) from an intake
               file; host h0 is SIGKILLed mid-decode (host_kill, no
               drain), the router's lease sweep declares it dead,
               tombstones it and migrates its in-flight requests onto
               h1, which replays each journaled committed prefix; h1
               also absorbs a heartbeat_delay SHORTER than the ttl
               (slow-but-alive must not trip the verdict). Zero lost
               requests, survivor drains leak-clean, and every stream —
               including the migrated, mid-decode ones — bit-matches an
               unfailed single-host reference serve

  kvstore      fleet-global KV-block store (inference/kvstore.py): two
               fleet hosts share a content-addressed store; h0 publishes
               the four requests' shared prefix train, chaos poisons
               exactly that artifact (store_corrupt, manifest spared)
               and later SIGKILLs h0 mid-decode; cache-affinity routing
               still lands the second request on h0 while the overflow
               goes to h1, whose one fetch CRC-rejects and degrades to
               local recompute. Exactly one publish, exactly one reject,
               zero lost, no torn store state, and every stream
               bit-matches an unfailed single-host reference serve

  disagg       disaggregated prefill/decode serving (inference/fleet.py
               --role): two dedicated prefill engines stream committed
               KV blocks to one dedicated decode engine over the
               checksummed artifact path; chaos SIGKILLs prefill host
               pre0 mid-prompt (prefill_kill, between chunk commits) so
               the router re-prefills its requests on pre1, and flips a
               payload byte in one of pre1's shipments (ship_corrupt,
               manifest spared) so the router CRC-rejects exactly that
               shipment and hands the request to decode as a committed-
               prefix replay. Zero requests lost, every engine drains
               leak-clean, and all four decode streams bit-match an
               unfailed colocated reference serve

  transport    pluggable KV transport (inference/transport.py): an
               in-process prefill/decode scheduler pair shares a
               MemFabric; every exported train is pushed over the mem
               lane, and chaos poisons the FIRST push's fabric manifest
               metadata (mem_corrupt, push ordinal 0) while a payload
               byte flip also corrupts the SAME request's fs artifact —
               its whole ladder fails down to the committed-prefix
               replay; a second request gets only the mem poison and
               degrades one rung to the fs artifact. Every remaining
               train lands on the mem lane, zero requests are lost, no
               blocks leak, and all streams bit-match an unfailed
               colocated reference — the full mem -> fs -> replay
               degradation with nothing dropped at any rung

Bit-exactness evidence: full-precision ``loss`` floats from the step
events, compared against a clean baseline run with the same seed; for
ckpt_corrupt, additionally the integrity manifest of the fallback step dir
is compared CRC-for-CRC against the exception scenario's same-step dir —
two independent runs, identical bytes.

Resumed jobs on some CPU containers die in a known post-restore native
crash (see ROADMAP.md) AFTER the restore/fallback audits land; the
campaign treats those exit codes as survivable-with-note and verifies on
the audit trail, which is durable by the flight-recorder flush contract.
"""

import argparse
import json
import os
import re
import shutil
import signal as _signal
import subprocess
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fault_tolerant_llm_training_tpu.obs.goodput import (  # noqa: E402
    load_chain,
    stitch,
)
from fault_tolerant_llm_training_tpu.obs import reqtrace  # noqa: E402
from scripts import fleet_timeline  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCENARIOS = ("sigusr1", "sigterm", "exception", "ckpt_corrupt",
             "loader_stall", "deploy", "fleet", "tiered", "disagg",
             "kvstore", "transport")
# Known container-level post-restore native crash codes (SIGABRT/SIGSEGV,
# as rc or negative signal): the resumed process dies after the restore
# audits are flushed. Survival is then judged on the audit trail.
CRASH_RCS = {134, 139, -6, -11}


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = env.get(
        "JAX_COMPILATION_CACHE_DIR", "/tmp/jax_test_compile_cache")
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    # serve.py and deploy/publish.py run as -m modules
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _make_parquet(path: str, seed: int) -> None:
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(seed)
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(20, 120))))
            for _ in range(128)]
    pq.write_table(pa.table({"text": docs}), path)


def _train_argv(parquet: str, ckpt_path: str, seed: int, **over):
    base = {
        "--dataset": parquet,
        "--checkpoint-path": ckpt_path,
        "--tokenizer-name-or-path": "byte",
        "--model": "tiny",
        "--sequence-length": "128",
        "--batch-size": "2",
        "--training-steps": "30",
        "--lr-warmup-steps": "5",
        "--learning-rate": "1e-3",
        "--logging-frequency": "1",
        "--checkpoint-frequency": "5",
        "--seed": str(seed),
    }
    base.update({k: str(v) for k, v in over.items()})
    argv = [sys.executable, os.path.join(REPO, "train.py")]
    for k, v in base.items():
        argv.append(k)
        if v != "":
            argv.append(v)
    return argv


def _run(argv, job_id: str, timeout: int = 300):
    env = _env()
    env["SLURM_JOB_ID"] = job_id
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.send_signal(_signal.SIGABRT)
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        return 124, out
    return proc.returncode, out


class _ServeDriver:
    """Background serve.py with line tailing.

    The deploy scenario interleaves publishes with a LIVE decode stream,
    so the serve process's stdout is pumped on a thread and the driver
    blocks on specific audit lines (``wait_for``) to sequence its moves —
    the same reader-thread pattern the serve e2e tests use."""

    def __init__(self, argv, job_id: str):
        env = _env()
        env["SLURM_JOB_ID"] = job_id
        self.proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True,
                                     env=env)
        self.lines = []
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self):
        for line in self.proc.stdout:
            with self._lock:
                self.lines.append(line.rstrip("\n"))

    def wait_for(self, pattern: str, timeout: float = 240.0):
        """Block until any output line so far matches ``pattern``;
        returns the re.Match or None on timeout / process exit. Every
        call scans the whole buffer (the scenario's patterns are all
        distinct), so out-of-order completions are never skipped."""
        rx = re.compile(pattern)
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                snapshot = list(self.lines)
            for line in snapshot:
                m = rx.search(line)
                if m:
                    return m
            if time.monotonic() >= deadline:
                return None
            if (self.proc.poll() is not None
                    and len(snapshot) == len(self.lines)):
                return None
            time.sleep(0.05)

    def output(self) -> str:
        with self._lock:
            return "\n".join(self.lines)

    def finish(self, timeout: int = 90) -> int:
        try:
            self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()
        self._thread.join(timeout=5)
        return self.proc.returncode


def _serve_argv(ckpts: str, job_id: str, extra):
    return [sys.executable, "-m",
            "fault_tolerant_llm_training_tpu.inference.serve",
            "--checkpoint-path", ckpts, "--checkpoint-job-id", job_id,
            "--model", "tiny", "--tokenizer-name-or-path", "byte",
            "--slots", "2", "--max-len", "256", "--no-eos",
            "--log-frequency", "2"] + list(extra)


def _event_losses(events_dir: str, job_id: str) -> dict:
    """step -> full-precision loss from the job's step events (stronger
    than the 2-decimal log lines for bit-exact comparison)."""
    path = os.path.join(events_dir, f"events_{job_id}.jsonl")
    losses = {}
    if not os.path.isfile(path):
        return losses
    with open(path) as fh:
        for line in fh:
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if ev.get("kind") == "step" and "loss" in ev:
                losses[int(ev["step"])] = ev["loss"]
    return losses


def _state_digest(ckpt_root: str, job_id: str, step: int):
    """Per-array (dtype, shape, crc32-of-bytes) list for a saved step.

    The integrity manifest's file-level CRCs detect corruption WITHIN one
    checkpoint, but Orbax's ocdbt container is not byte-deterministic
    across runs (content-addressed data-file names, timestamped
    metadata), so cross-run identity has to be checked at the restored
    array-value level."""
    import zlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np
    import orbax.checkpoint as ocp

    d = os.path.join(ckpt_root, f"checkpoint_{job_id}")
    if not os.path.isdir(os.path.join(d, str(step))):
        return None
    mngr = ocp.CheckpointManager(d)
    try:
        r = mngr.restore(step, args=ocp.args.Composite(
            state=ocp.args.PyTreeRestore()))
    finally:
        mngr.close()
    digest = []
    for leaf in jax.tree_util.tree_leaves(r["state"]):
        arr = np.asarray(leaf)
        digest.append((str(arr.dtype), tuple(arr.shape),
                       zlib.crc32(arr.tobytes()) & 0xFFFFFFFF))
    return digest


class Result:
    def __init__(self, name):
        self.name = name
        self.survived = True
        self.notes = []
        self.goodput_pct = None
        self.mttr_seconds = None
        self.replayed_steps = None

    def check(self, cond: bool, what: str):
        if cond:
            self.notes.append(f"ok: {what}")
        else:
            self.survived = False
            self.notes.append(f"FAIL: {what}")
        return cond

    def note(self, what: str):
        self.notes.append(f"note: {what}")


def _write_postmortem(name: str, work: str) -> str:
    """Fold a scenario's event/trace/journal trails into one HLC-ordered,
    anomaly-annotated timeline (scripts/fleet_timeline.py) and write it
    next to the scenario's workdir as ``postmortem_<name>.txt``. Returns
    the timeline text ('' when the scenario left no trails)."""
    base = os.path.join(work, name)
    if not os.path.isdir(base):
        return ""
    files = fleet_timeline.collect([base])
    entries = fleet_timeline.build_timeline(files)
    if not entries:
        return ""
    text = fleet_timeline.format_timeline(entries)
    out = os.path.join(work, f"postmortem_{name}.txt")
    with open(out, "w") as fh:
        fh.write(text)
    print(f"   post-mortem timeline -> {out}")
    return text


def _check_fleet_postmortem(res: Result, timeline: str) -> None:
    """The fleet drill's causal chain, read off the post-mortem: chaos
    SIGKILLs h0, the router renders the fence verdict, then migrates —
    in HLC order, spanning both hosts' trails plus the router's."""
    if not res.check(bool(timeline),
                     "post-mortem timeline generated from the scenario's "
                     "event/trace/journal trails"):
        return
    lines = timeline.splitlines()

    def first_idx(pred):
        return next((i for i, ln in enumerate(lines) if pred(ln)), None)

    kill = first_idx(lambda ln: "[CHAOS]" in ln and "host_kill" in ln)
    fence = first_idx(lambda ln: "[FENCE]" in ln and "fleet_dead" in ln)
    migrate = first_idx(lambda ln: "[MIGRATE]" in ln)
    res.check(kill is not None and fence is not None
              and migrate is not None,
              "post-mortem annotates the chaos kill, the fence verdict "
              "and the migration")
    if None in (kill, fence, migrate):
        return
    res.check(kill < fence < migrate,
              "SIGKILL -> fence -> migrate chain appears in HLC (causal) "
              "order in the post-mortem timeline")
    res.check("h0" in lines[kill],
              "the annotated kill belongs to host h0's trail")
    res.check("fleet_h1" in timeline or " h1 " in timeline,
              "the timeline spans the surviving host's trail too")


def _resume_rc_ok(res: Result, rc: int, out: str) -> bool:
    if rc == 0:
        return True
    if rc in CRASH_RCS and "Resuming training from training_step" in out:
        res.note(f"resumed job hit the known container post-restore crash "
                 f"(rc={rc}) after the restore audits landed")
        return True
    return False


def _stitch_scenario(res: Result, events_dir: str):
    events = load_chain([events_dir])
    if not events:
        res.note("no event logs found for goodput stitching")
        return
    rep = stitch(events)
    res.goodput_pct = rep.goodput_pct
    res.mttr_seconds = rep.mttr_seconds
    res.replayed_steps = sum(r.replayed_steps for r in rep.restarts)


def run_scenario(name: str, work: str, parquet: str, seed: int,
                 baseline_losses: dict, sbatch: str = "") -> Result:
    res = Result(name)
    ckpts = os.path.join(work, name, "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(ckpts, exist_ok=True)
    job_a, job_b = f"{name}_a", f"{name}_b"

    if name == "loader_stall":
        # checkpoint-frequency 0 to match the baseline oracle: pre-save
        # drains consume steps without emitting their step events, so a
        # checkpointing run records fewer loss events (by design, not loss
        # of determinism) and the 30-vs-30 comparison would be unfair.
        rc, out = _run(_train_argv(
            parquet, ckpts, seed,
            **{"--chaos": "step=15:loader_stall=2s",
               "--checkpoint-frequency": "0"}), job_a)
        res.check(rc == 0, f"run completed rc=0 (got {rc})")
        res.check("[CHAOS] Injected loader_stall at step 15" in out,
                  "stall injection audited")
        res.check("Training completed" in out, "run trained to completion")
        losses = _event_losses(events_dir, job_a)
        res.check(len(losses) == 30, f"all 30 step losses recorded "
                                     f"(got {len(losses)})")
        res.check(losses == baseline_losses,
                  "every loss bit-equals the clean baseline (no token "
                  "replayed or skipped across the stall)")
        _stitch_scenario(res, events_dir)
        return res

    fault_over = {"--chaos": f"step=12:{name}"}
    if name == "sigusr1":
        marker = os.path.join(work, name, "resubmitted")
        fault_over["--resubmit-command"] = (
            sbatch or f"touch {marker}")
    rc, out = _run(_train_argv(parquet, ckpts, seed, **fault_over), job_a)
    res.check(rc == 0, f"fault job exits 0 (got {rc})")
    res.check(f"[CHAOS] Injected {name} at step 12" in out,
              "injection audited")

    if name == "sigusr1":
        res.check("[EXIT HANDLER] Job timed out, saving checkpoint." in out,
                  "USR1 routed to the timeout save policy")
        res.check("Checkpoint saved at step 13" in out, "fault save @13")
        res.check("sbatch requeued" in out, "requeue attempted")
        if not sbatch:
            res.check(os.path.isfile(marker), "resubmit command ran")
        expect_resume = 13
    elif name == "sigterm":
        res.check("[EXIT HANDLER] Job cancelled, terminating." in out,
                  "SIGTERM routed to the no-save cancel policy")
        res.check("Checkpoint saved at step" not in out,
                  "cancel writes no checkpoint")
        expect_resume = 10  # newest periodic save (freq 5, steps 5+10 kept)
    elif name == "exception":
        res.check("[EXIT HANDLER] Error during training encountered, "
                  "saving checkpoint." in out,
                  "error routed to the save-no-requeue policy")
        res.check("Checkpoint saved at step 13" in out, "fault save @13")
        res.check("sbatch requeued" not in out, "code error never requeues")
        expect_resume = 13
    else:  # ckpt_corrupt
        res.check("Checkpoint saved at step 13" in out, "fault save @13")
        res.check("[CHAOS] Corrupted checkpoint step 13" in out,
                  "committed checkpoint corrupted post-manifest")
        expect_resume = 10  # verified fallback target

    rc2, out2 = _run(_train_argv(parquet, ckpts, seed,
                                 **{"--checkpoint-id": job_a}), job_b)
    res.check(_resume_rc_ok(res, rc2, out2),
              f"resume job survives (rc={rc2})")
    if name == "ckpt_corrupt":
        res.check("[CKPT VERIFY] Checkpoint step 13 failed integrity check"
                  in out2, "corruption detected at restore")
        res.check("[CKPT VERIFY] Falling back to checkpoint step 10" in out2,
                  "audited automatic fallback to newest passing step")
    m = re.search(r"Resuming training from training_step (\d+)", out2)
    res.check(m is not None and int(m.group(1)) == expect_resume,
              f"resumed at step {expect_resume} "
              f"(got {m.group(1) if m else 'none'})")

    resumed_losses = _event_losses(events_dir, job_b)
    if resumed_losses:
        mismatch = [s for s, l in resumed_losses.items()
                    if baseline_losses.get(s) != l]
        res.check(not mismatch,
                  f"{len(resumed_losses)} post-resume losses bit-equal the "
                  f"baseline (mismatched steps: {mismatch or 'none'})")
    else:
        res.note("no post-resume step events (container crash window); "
                 "bit-exactness evidenced by the audit trail and the "
                 "cross-scenario checkpoint CRC comparison")
    _stitch_scenario(res, events_dir)
    return res


def run_deploy_scenario(work: str, parquet: str, seed: int) -> Result:
    """Deployment-loop scenario: train-with-publish, then a live serve
    absorbs 2 hot swaps with requests in flight, rejects a corrupt
    publish, and bit-matches a fresh restore (module docstring)."""
    from fault_tolerant_llm_training_tpu.deploy.publish import (
        Publisher,
        read_pointer,
    )

    res = Result("deploy")
    ckpts = os.path.join(work, "deploy", "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(ckpts, exist_ok=True)
    job = "deploy_a"

    # 1. publishing train run: every periodic manifest commit (steps
    # 5..30, keep 6 so none is GC'd) moves published.json, ending at 30
    rc, out = _run(_train_argv(parquet, ckpts, seed,
                               **{"--checkpoint-frequency": "5",
                                  "--checkpoint-keep": "6",
                                  "--publish": ""}), job)
    res.check(rc == 0, f"publishing train run exits 0 (got {rc})")
    res.check("[DEPLOY] Published checkpoint step 30" in out,
              "trainer published the final periodic save")
    ptr = read_pointer(ckpts)
    res.check(ptr is not None and ptr.step == 30,
              "published.json points at step 30 after training")
    if not res.survived:
        return res

    # 2. roll the pointer BACK to step 10 through the operator CLI so the
    # serve under test starts two publishes behind the trainer's tip
    rc, _ = _run([sys.executable, "-m",
                  "fault_tolerant_llm_training_tpu.deploy.publish",
                  "--checkpoint-path", ckpts, "--job-id", job,
                  "--step", "10"], "deploy_pub10")
    ptr = read_pointer(ckpts)
    res.check(rc == 0 and ptr is not None and ptr.step == 10,
              "publish CLI re-pointed the deployment at step 10")

    # 3. live serve on the step-10 publish, tailing a request file
    reqs = os.path.join(work, "deploy", "requests.jsonl")
    open(reqs, "w").close()
    serve_events = os.path.join(work, "deploy", "serve_events.jsonl")
    drv = _ServeDriver(_serve_argv(ckpts, job, [
        "--step", "10", "--seed", str(seed), "--follow",
        "--poll-seconds", "0.2", "--request-file", reqs,
        "--event-log", serve_events]), "deploy_serve")
    outputs = {}
    w3 = [("w3a", "india juliett kilo lima"),
          ("w3b", "mike november oscar papa quebec")]
    try:
        res.check(drv.wait_for(r"Serving ready \| model tiny \| "
                               r"checkpoint step 10",
                               timeout=420) is not None,
                  "serve restored the published step-10 checkpoint")

        # wave 1: long greedy requests that stay in flight across BOTH
        # swaps (the publishes below land a few decode iterations in)
        with open(reqs, "a") as fh:
            for rid in ("w1a", "w1b"):
                fh.write(json.dumps({
                    "id": rid,
                    "prompt": "alpha bravo charlie delta echo foxtrot "
                              "golf hotel",
                    "max_new_tokens": 96, "temperature": 0.0}) + "\n")
        res.check(drv.wait_for(r"Serve step: \d+ \| Active: [12]")
                  is not None, "wave-1 requests admitted and decoding")

        publisher = Publisher(ckpts, job)
        for old, new in ((10, 20), (20, 30)):
            publisher.publish(new)
            m = drv.wait_for(rf"\[DEPLOY\] Weights reloaded: "
                             rf"step {old} -> {new} \| (\d+) in-flight")
            res.check(m is not None, f"publish of step {new} hot-swapped "
                                     f"into the running engine")
            res.check(m is not None and int(m.group(1)) >= 1,
                      f"swap {old}->{new} carried in-flight requests "
                      f"(active={m.group(1) if m else '?'})")

        # the swaps must not have dropped or truncated wave 1
        for rid in ("w1a", "w1b"):
            m = drv.wait_for(rf"Request {rid} done \| length \| "
                             rf"prompt \d+ tok \| generated (\d+) tok")
            res.check(m is not None and int(m.group(1)) == 96,
                      f"{rid} ran to its full 96 tokens across both swaps")

        # 4. corrupt publish: chaos flips a committed byte of step 15
        # AFTER the pointer moves; verify-before-load must reject it
        rc, out = _run([sys.executable, "-m",
                        "fault_tolerant_llm_training_tpu.deploy.publish",
                        "--checkpoint-path", ckpts, "--job-id", job,
                        "--step", "15",
                        "--chaos", "step=15:publish_corrupt",
                        "--seed", str(seed)], "deploy_pub15")
        res.check(rc == 0 and
                  "[CHAOS] Injected publish_corrupt at step 15" in out,
                  "chaos-corrupted publish of step 15 committed")
        res.check(drv.wait_for(r"\[DEPLOY\] Publish of step 15 rejected: "
                               r".*; serving continues on step 30")
                  is not None,
                  "corrupt publish rejected before load; serving "
                  "continues on step 30")

        # wave 3: decoded WHOLLY on the swapped step-30 weights — these
        # output reprs are the bit-match reference
        with open(reqs, "a") as fh:
            for rid, prompt in w3:
                fh.write(json.dumps({"id": rid, "prompt": prompt,
                                     "max_new_tokens": 24,
                                     "temperature": 0.0}) + "\n")
        for rid, _ in w3:
            m = drv.wait_for(rf"Request {rid} output: (.+)$")
            res.check(m is not None,
                      f"{rid} completed on the swapped step-30 weights")
            if m is not None:
                outputs[rid] = m.group(1)

        # drain exactly like training: SIGUSR1 finishes in-flight, exit 0
        drv.proc.send_signal(_signal.SIGUSR1)
        rc = drv.finish()
    finally:
        if drv.proc.poll() is None:
            drv.proc.kill()
            drv.finish(timeout=10)
    out = drv.output()
    res.check(rc == 0, f"serve drained and exited 0 (got {rc})")
    res.check("[EXIT HANDLER] Drained;" in out, "drain audited")

    # flight recorder agrees with the log lines
    kinds = []
    if os.path.isfile(serve_events):
        with open(serve_events) as fh:
            for line in fh:
                try:
                    kinds.append(json.loads(line).get("kind"))
                except json.JSONDecodeError:
                    pass
    res.check(kinds.count("weights_reload") == 2 and
              kinds.count("weights_reload_rejected") == 1,
              "flight recorder: exactly 2 swaps + 1 rejection")

    # 5. fresh serve restored directly at step 30, same prompts/knobs:
    # greedy streams must be bit-identical to the hot-swapped process's
    argv = _serve_argv(ckpts, job, ["--step", "30", "--seed", str(seed),
                                    "--max-new-tokens", "24"])
    for _, prompt in w3:
        argv += ["--prompt", prompt]
    rc, out2 = _run(argv, "deploy_fresh", timeout=600)
    res.check(rc == 0, f"fresh step-30 serve exits 0 (got {rc})")
    fresh = dict(re.findall(r"Request (req\d+) output: (.+)", out2))
    res.check(len(outputs) == 2 and
              fresh.get("req0") == outputs.get("w3a") and
              fresh.get("req1") == outputs.get("w3b"),
              "post-swap streams bit-identical to a fresh restore of "
              "step 30")
    _stitch_scenario(res, events_dir)
    return res


def run_fleet_scenario(work: str, parquet: str, seed: int) -> Result:
    """Serving-fleet migration scenario: SIGKILL one of two fleet hosts
    mid-decode and prove the router migrates its in-flight requests onto
    the survivor with zero loss and bit-exact continuations (module
    docstring)."""
    res = Result("fleet")
    base = os.path.join(work, "fleet")
    ckpts = os.path.join(base, "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(base, exist_ok=True)
    job = "fleet_a"

    # 1. a checkpoint for the fleet to serve (short run; the scenario is
    # about serving faults, not training ones)
    rc, out = _run(_train_argv(parquet, ckpts, seed,
                               **{"--training-steps": "10",
                                  "--checkpoint-frequency": "5"}), job)
    if not res.check(rc == 0, f"fleet training checkpoint committed "
                              f"(got rc {rc})"):
        return res

    store = os.path.join(base, "store")
    jdir = os.path.join(base, "journal")
    intake = os.path.join(base, "intake.jsonl")
    reqs = [
        {"id": "req0", "prompt": "alpha bravo charlie delta",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 11},
        {"id": "req1", "prompt": "echo foxtrot golf hotel",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 12},
        {"id": "req2", "prompt": "india juliett kilo lima",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 13},
        {"id": "req3", "prompt": "mike november oscar papa",
         "max_new_tokens": 48, "temperature": 0.8, "seed": seed + 14},
    ]
    with open(intake, "w") as fh:
        for r in reqs:
            fh.write(json.dumps(r) + "\n")

    def host_argv(hid, chaos):
        return [sys.executable, "-m",
                "fault_tolerant_llm_training_tpu.inference.fleet",
                "--host-id", hid, "--store", store, "--journal-dir", jdir,
                "--checkpoint-path", ckpts, "--checkpoint-job-id", job,
                "--model", "tiny", "--tokenizer-name-or-path", "byte",
                "--slots", "2", "--max-len", "256", "--no-eos",
                "--lease-ttl", "2.0", "--max-run-seconds", "240",
                "--seed", str(seed), "--chaos", chaos,
                "--event-log", os.path.join(base, f"events_{hid}.jsonl")]

    # 2. two hosts: h0 takes a SIGKILL at decode iteration 12 (mid-decode,
    # committed tokens already journaled); h1 takes a 1 s heartbeat stall —
    # SHORTER than the 2 s ttl, so it must NOT be declared dead
    h0 = _ServeDriver(host_argv("h0", "step=12:host_kill"), "fleet_h0")
    h1 = _ServeDriver(host_argv("h1", "step=3:heartbeat_delay=1s"),
                      "fleet_h1")
    router = None
    try:
        res.check(h0.wait_for(r"\[FLEET\] Host h0 joined", timeout=420)
                  is not None, "host h0 joined the fleet with a lease")
        res.check(h1.wait_for(r"\[FLEET\] Host h1 joined", timeout=420)
                  is not None, "host h1 joined the fleet with a lease")

        # 3. router admits the intake and supervises the leases
        router = _ServeDriver(
            [sys.executable, "-m",
             "fault_tolerant_llm_training_tpu.inference.router",
             "--store", store, "--journal-dir", jdir, "--intake", intake,
             "--expected", "4", "--max-seconds", "180",
             "--poll-seconds", "0.1",
             "--event-log", os.path.join(base, "events_router.jsonl")],
            "fleet_router")
        rrc = router.finish(timeout=200)
        res.check(rrc == 0, f"router completed and exited 0 (got {rrc})")
        rc0 = h0.finish(timeout=15)
        # 4. drain the survivor exactly like a single serve
        h1.proc.send_signal(_signal.SIGUSR1)
        rc1 = h1.finish(timeout=120)
    finally:
        for drv in (h0, h1, router):
            if drv is not None and drv.proc.poll() is None:
                drv.proc.kill()
                drv.finish(timeout=10)
    rout = router.output()
    out0, out1 = h0.output(), h1.output()

    res.check(rc0 == -9 and "[CHAOS] Injected host_kill" in out0,
              f"host h0 SIGKILLed mid-decode by chaos (rc {rc0})")
    res.check("[FLEET] Host h0 declared dead" in rout
              and "fencing and migrating" in rout,
              "router declared h0 dead and fenced it")
    migrs = [int(n) for n in re.findall(
        r"\[FLEET\] Migrating request req\d+: h0 -> h1 \(gen \d+, (\d+) "
        r"committed token\(s\) replayed\)", rout)]
    res.check(bool(migrs) and any(n >= 1 for n in migrs),
              f"migration replayed a committed prefix onto the survivor "
              f"(committed counts {migrs})")
    res.check(re.search(r"Fleet router complete: 4 request\(s\) done, "
                        r"\d+ migrated, 0 lost", rout) is not None,
              "zero requests lost: all 4 served")
    res.check("Injected heartbeat_delay" in out1
              and "Host h1 declared dead" not in rout,
              "heartbeat-delayed h1 stayed under its ttl (no false dead "
              "verdict)")
    res.check(rc1 == 0 and "Fleet drain leak guard: clean" in out1,
              f"survivor drained leak-clean and exited 0 (got rc {rc1})")

    # flight recorder agrees with the log lines: one dead verdict, at
    # least one migration, no verdict against the slow-but-alive host
    kinds = []
    ev_path = os.path.join(base, "events_router.jsonl")
    if os.path.isfile(ev_path):
        with open(ev_path) as fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                kinds.append((ev.get("kind"), ev.get("host")))
    res.check(kinds.count(("fleet_dead", "h0")) == 1
              and ("fleet_dead", "h1") not in kinds
              and sum(1 for k, _ in kinds if k == "fleet_migrate") >= 1,
              "flight recorder: exactly one dead verdict (h0) + the "
              "migrations")

    # 5. unfailed reference: ONE serve.py tails the same intake (same ids,
    # seeds, sampling params) — every fleet stream, including the
    # migrated mid-decode ones, must bit-match it
    ref_reqs = os.path.join(base, "ref_requests.jsonl")
    shutil.copy(intake, ref_reqs)
    ref = _ServeDriver(_serve_argv(ckpts, job, [
        "--seed", str(seed), "--follow", "--poll-seconds", "0.2",
        "--request-file", ref_reqs]), "fleet_ref")
    try:
        for r in reqs:
            res.check(ref.wait_for(rf"Request {r['id']} output: ",
                                   timeout=420) is not None,
                      f"reference serve completed {r['id']}")
        ref.proc.send_signal(_signal.SIGUSR1)
        ref_rc = ref.finish()
    finally:
        if ref.proc.poll() is None:
            ref.proc.kill()
            ref.finish(timeout=10)
    res.check(ref_rc == 0, f"reference serve exited 0 (got {ref_rc})")
    fleet_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                    out0 + "\n" + out1))
    ref_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                  ref.output()))
    res.check(
        len(fleet_outputs) == 4 and all(
            fleet_outputs.get(f"req{i}") == ref_outputs.get(f"req{i}")
            for i in range(4)),
        "migrated streams bit-identical to the unfailed reference serve")

    # 6. request-trace stitch (obs/reqtrace.py): every process wrote a
    # trace_<name>.jsonl next to its event log; joined by trace_id, the
    # migrated request must show ONE trail that spans both hosts, and its
    # migration span's replayed count must equal the journal committed
    # prefix the router logged
    migr_by_id = {rid: int(n) for rid, n in re.findall(
        r"\[FLEET\] Migrating request (req\d+): h0 -> h1 \(gen \d+, (\d+) "
        r"committed token\(s\) replayed\)", rout)}
    traced = {r["request_id"]: r
              for r in reqtrace.stitch([base]) if r["request_id"]}
    trace_ok = bool(migr_by_id)
    for rid, committed in migr_by_id.items():
        tr = traced.get(rid)
        trace_ok = (trace_ok and tr is not None and tr["migrated"]
                    and {"h0", "h1"} <= set(tr["hosts"])
                    and tr["replayed"] == committed)
    res.check(trace_ok,
              "stitched trace: migrated request spans h0 and h1, replay "
              "count matches the journal committed prefix")
    _stitch_scenario(res, events_dir)
    return res


def run_tiered_scenario(work: str, parquet: str, seed: int) -> Result:
    """Tiered KV-block lifecycle scenario: a ``--handoff`` drain ships
    in-flight requests' committed blocks as checksummed artifacts, chaos
    corrupts the FIRST one (``handoff_corrupt``), and the survivor — run
    with a pool too small for its own two requests, so the spill tier
    fires, with ``spill_corrupt`` poisoning its first spill artifact —
    must finish all four streams bit-identical to an unfailed single-host
    reference: verified artifacts import, corrupt ones CRC-reject into
    committed-prefix replay, and the drain leak guard stays strict-clean
    across the device pool and the spill tier."""
    res = Result("tiered")
    base = os.path.join(work, "tiered")
    ckpts = os.path.join(base, "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(base, exist_ok=True)
    job = "tiered_a"

    rc, out = _run(_train_argv(parquet, ckpts, seed,
                               **{"--training-steps": "10",
                                  "--checkpoint-frequency": "5"}), job)
    if not res.check(rc == 0, f"tiered training checkpoint committed "
                              f"(got rc {rc})"):
        return res

    store = os.path.join(base, "store")
    jdir = os.path.join(base, "journal")
    intake = os.path.join(base, "intake.jsonl")
    reqs = [
        {"id": "req0", "prompt": "alpha bravo charlie delta",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 11},
        {"id": "req1", "prompt": "echo foxtrot golf hotel",
         "max_new_tokens": 48, "temperature": 0.7, "top_p": 0.9,
         "seed": seed + 12},
        {"id": "req2", "prompt": "india juliett kilo lima",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 13},
        {"id": "req3", "prompt": "mike november oscar papa",
         "max_new_tokens": 48, "temperature": 0.8, "seed": seed + 14},
    ]
    with open(intake, "w") as fh:
        for r in reqs:
            fh.write(json.dumps(r) + "\n")

    def host_argv(hid, chaos, extra=()):
        return [sys.executable, "-m",
                "fault_tolerant_llm_training_tpu.inference.fleet",
                "--host-id", hid, "--store", store, "--journal-dir", jdir,
                "--checkpoint-path", ckpts, "--checkpoint-job-id", job,
                "--model", "tiny", "--tokenizer-name-or-path", "byte",
                "--slots", "2", "--max-len", "256", "--no-eos",
                "--lease-ttl", "2.0", "--max-run-seconds", "240",
                "--seed", str(seed), "--chaos", chaos,
                "--event-log",
                os.path.join(base, f"events_{hid}.jsonl")] + list(extra)

    # h0: unconstrained pool, --handoff, a SIGUSR1 drain at decode
    # iteration 10 and a byte flip in its FIRST handoff artifact.
    # h1 (the survivor): 8 usable blocks against two requests needing 5
    # each — the second admission MUST spill the first — plus a byte flip
    # in its first spill artifact, so one restore CRC-rejects into replay.
    h0 = _ServeDriver(host_argv(
        "h0", "step=10:sigusr1;step=0:handoff_corrupt", ["--handoff"]),
        "tiered_h0")
    h1 = _ServeDriver(host_argv(
        "h1", "step=0:spill_corrupt",
        ["--kv-num-blocks", "9",
         "--spill-dir", os.path.join(base, "spill_h1")]), "tiered_h1")
    router = None
    try:
        res.check(h0.wait_for(r"\[FLEET\] Host h0 joined", timeout=420)
                  is not None, "host h0 joined the fleet with a lease")
        res.check(h1.wait_for(r"\[FLEET\] Host h1 joined", timeout=420)
                  is not None, "host h1 joined the fleet with a lease")
        router = _ServeDriver(
            [sys.executable, "-m",
             "fault_tolerant_llm_training_tpu.inference.router",
             "--store", store, "--journal-dir", jdir, "--intake", intake,
             "--expected", "4", "--max-seconds", "180",
             "--poll-seconds", "0.1",
             "--event-log", os.path.join(base, "events_router.jsonl")],
            "tiered_router")
        rrc = router.finish(timeout=200)
        res.check(rrc == 0, f"router completed and exited 0 (got {rrc})")
        rc0 = h0.finish(timeout=60)
        h1.proc.send_signal(_signal.SIGUSR1)
        rc1 = h1.finish(timeout=120)
    finally:
        for drv in (h0, h1, router):
            if drv is not None and drv.proc.poll() is None:
                drv.proc.kill()
                drv.finish(timeout=10)
    rout = router.output()
    out0, out1 = h0.output(), h1.output()

    # --- handoff half: exports on h0, verify-or-replay at the router
    exports = re.findall(r"\[HANDOFF\] Block-shipment export request "
                         r"(req\d+)", out0)
    res.check(rc0 == 0 and len(exports) == 2,
              f"h0 drained via --handoff and exported both in-flight "
              f"requests' blocks (rc {rc0}, exports {exports})")
    res.check("[CHAOS] Injected handoff_corrupt" in out0,
              "chaos flipped a payload byte in h0's first handoff "
              "artifact (manifest spared)")
    rejects = re.findall(r"\[HANDOFF\] Block-shipment reject request "
                         r"(req\d+)", rout)
    ships = re.findall(r"\[HANDOFF\] Block-shipment ship request "
                       r"(req\d+)", rout)
    res.check(len(rejects) == 1 and len(ships) == 1
              and set(rejects) | set(ships) == set(exports),
              f"router CRC-rejected exactly the corrupt artifact and "
              f"shipped the other (rejects {rejects}, ships {ships})")
    imports = re.findall(r"\[HANDOFF\] Block-shipment import request "
                         r"(req\d+)", out1)
    res.check(imports == ships,
              f"survivor imported the verified artifact's blocks instead "
              f"of replaying (imports {imports})")
    res.check(re.search(r"Fleet router complete: 4 request\(s\) done, "
                        r"\d+ migrated, 0 lost", rout) is not None,
              "zero requests lost: all 4 served")

    # --- spill half: h1's pool forces a preemption, chaos poisons it
    res.check("[KV TIER] Spill export" in out1
              and "[CHAOS] Injected spill_corrupt" in out1,
              "survivor's constrained pool spilled a request to the host "
              "tier and chaos corrupted the artifact")
    res.check("[KV TIER] Spill reject" in out1,
              "poisoned spill artifact CRC-rejected at restore and fell "
              "back to committed-prefix replay")
    res.check(rc1 == 0 and "Fleet drain leak guard: clean" in out1,
              f"survivor drained leak-clean across device pool + spill "
              f"tier and exited 0 (got rc {rc1})")

    # --- bit-exactness: every stream (handoff-imported, CRC-reject
    # replayed, spill-restored) vs ONE unfailed single-host serve
    ref_reqs = os.path.join(base, "ref_requests.jsonl")
    shutil.copy(intake, ref_reqs)
    ref = _ServeDriver(_serve_argv(ckpts, job, [
        "--seed", str(seed), "--follow", "--poll-seconds", "0.2",
        "--request-file", ref_reqs]), "tiered_ref")
    try:
        for r in reqs:
            res.check(ref.wait_for(rf"Request {r['id']} output: ",
                                   timeout=420) is not None,
                      f"reference serve completed {r['id']}")
        ref.proc.send_signal(_signal.SIGUSR1)
        ref_rc = ref.finish()
    finally:
        if ref.proc.poll() is None:
            ref.proc.kill()
            ref.finish(timeout=10)
    res.check(ref_rc == 0, f"reference serve exited 0 (got {ref_rc})")
    tier_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                   out0 + "\n" + out1))
    ref_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                  ref.output()))
    res.check(
        len(tier_outputs) == 4 and all(
            tier_outputs.get(f"req{i}") == ref_outputs.get(f"req{i}")
            for i in range(4)),
        "all streams (imported, replayed, spill-restored) bit-identical "
        "to the unfailed reference serve")
    _stitch_scenario(res, events_dir)
    return res


def run_disagg_scenario(work: str, parquet: str, seed: int) -> Result:
    """Disaggregated prefill/decode scenario: two dedicated prefill
    engines stream committed KV blocks to one dedicated decode engine
    over the checksummed artifact path; chaos kills one prefill host
    mid-prompt and poisons one of the survivor's shipments (module
    docstring)."""
    res = Result("disagg")
    base = os.path.join(work, "disagg")
    ckpts = os.path.join(base, "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(base, exist_ok=True)
    job = "disagg_a"

    rc, out = _run(_train_argv(parquet, ckpts, seed,
                               **{"--training-steps": "10",
                                  "--checkpoint-frequency": "5"}), job)
    if not res.check(rc == 0, f"disagg training checkpoint committed "
                              f"(got rc {rc})"):
        return res

    store = os.path.join(base, "store")
    jdir = os.path.join(base, "journal")
    intake = os.path.join(base, "intake.jsonl")
    # Long prompts (70+ byte-tokens against 32-token prefill chunks):
    # every prefill takes >= 3 chunk commits, so the prefill_kill at
    # chunk ordinal 1 lands MID-PROMPT and the incremental pipeline
    # ships more than one artifact per request.
    prompts = [
        "alpha bravo charlie delta echo foxtrot golf hotel india "
        "juliett kilo lima",
        "mike november oscar papa quebec romeo sierra tango uniform "
        "victor whiskey",
        "zulu yankee xray whiskey victor uniform tango sierra romeo "
        "quebec papa oscar",
        "one two three four five six seven eight nine ten eleven "
        "twelve thirteen fourteen",
    ]
    reqs = []
    for i, prompt in enumerate(prompts):
        r = {"id": f"req{i}", "prompt": prompt, "max_new_tokens": 48,
             "temperature": 0.0, "seed": seed + 21 + i}
        if i == 3:
            r["temperature"] = 0.8
        reqs.append(r)
    with open(intake, "w") as fh:
        for r in reqs:
            fh.write(json.dumps(r) + "\n")

    def host_argv(hid, role, extra=()):
        return [sys.executable, "-m",
                "fault_tolerant_llm_training_tpu.inference.fleet",
                "--host-id", hid, "--store", store, "--journal-dir", jdir,
                "--checkpoint-path", ckpts, "--checkpoint-job-id", job,
                "--model", "tiny", "--tokenizer-name-or-path", "byte",
                "--max-len", "256", "--prefill-buckets", "16,32",
                "--no-eos", "--lease-ttl", "2.0",
                "--max-run-seconds", "240", "--seed", str(seed),
                "--role", role,
                "--event-log",
                os.path.join(base, f"events_{hid}.jsonl")] + list(extra)

    # pre0: SIGKILLed between its 2nd chunk's commit and its shipment
    # export — shipments stop mid-prompt, the router must re-prefill on
    # pre1. pre1: chaos flips a payload byte in its 5th shipment export
    # (manifest spared) — the router must CRC-reject exactly that
    # shipment and degrade that request to a committed-prefix replay.
    pre0 = _ServeDriver(host_argv(
        "pre0", "prefill",
        ["--slots", "2", "--chaos", "step=1:prefill_kill"]), "disagg_pre0")
    pre1 = _ServeDriver(host_argv(
        "pre1", "prefill",
        ["--slots", "2", "--chaos", "step=4:ship_corrupt"]), "disagg_pre1")
    d0 = _ServeDriver(host_argv("d0", "decode", ["--slots", "4"]),
                      "disagg_d0")
    router = None
    try:
        res.check(pre0.wait_for(r"\[FLEET\] Host pre0 joined", timeout=420)
                  is not None, "prefill host pre0 joined the fleet")
        res.check(pre1.wait_for(r"\[FLEET\] Host pre1 joined", timeout=420)
                  is not None, "prefill host pre1 joined the fleet")
        res.check(d0.wait_for(r"\[FLEET\] Host d0 joined", timeout=420)
                  is not None, "decode host d0 joined the fleet")
        router = _ServeDriver(
            [sys.executable, "-m",
             "fault_tolerant_llm_training_tpu.inference.router",
             "--store", store, "--journal-dir", jdir, "--intake", intake,
             "--expected", "4", "--max-seconds", "180",
             "--poll-seconds", "0.1",
             "--event-log", os.path.join(base, "events_router.jsonl")],
            "disagg_router")
        rrc = router.finish(timeout=200)
        res.check(rrc == 0, f"router completed and exited 0 (got {rrc})")
        rc_pre0 = pre0.finish(timeout=15)
        pre1.proc.send_signal(_signal.SIGUSR1)
        rc_pre1 = pre1.finish(timeout=120)
        d0.proc.send_signal(_signal.SIGUSR1)
        rc_d0 = d0.finish(timeout=120)
    finally:
        for drv in (pre0, pre1, d0, router):
            if drv is not None and drv.proc.poll() is None:
                drv.proc.kill()
                drv.finish(timeout=10)
    rout = router.output()
    out_pre0, out_pre1, out_d0 = pre0.output(), pre1.output(), d0.output()

    # --- prefill-side faults
    res.check(rc_pre0 == -9
              and "[CHAOS] Injected prefill_kill" in out_pre0,
              f"prefill host pre0 SIGKILLed mid-prompt by chaos "
              f"(rc {rc_pre0})")
    res.check("[FLEET] Host pre0 declared dead" in rout
              and "fencing and migrating" in rout,
              "router declared pre0 dead and fenced it")
    res.check(re.search(r"\[FLEET\] Migrating request req\d+: "
                        r"pre0 -> pre1", rout) is not None,
              "dead host's mid-prompt requests re-prefilled on the "
              "surviving prefill peer")
    res.check("[CHAOS] Injected ship_corrupt" in out_pre1
              and "Corrupted block shipment" in out_pre1,
              "chaos flipped a payload byte in one of pre1's shipments "
              "(manifest spared)")

    # --- the CRC gate: exactly the poisoned shipment rejected, its
    # request degraded to replay; every request still reached decode
    rejects = re.findall(r"\[DISAGG\] Shipment reject request (req\d+) "
                         r"seq (\d+)", rout)
    res.check(len(rejects) == 1,
              f"router CRC-rejected exactly the poisoned shipment "
              f"(rejects {rejects})")
    places = re.findall(r"\[DISAGG\] Placement decode request (req\d+)",
                        rout)
    res.check(sorted(places) == [r["id"] for r in reqs],
              f"every request handed to the decode engine exactly once "
              f"(placements {sorted(places)})")
    res.check(re.search(r"Fleet router complete: 4 request\(s\) done, "
                        r"\d+ migrated, 0 lost", rout) is not None,
              "zero requests lost: all 4 served")

    # --- decode side: imports for the clean shipments, replay for the
    # rejected one, and the streams all come off the decode engine
    res.check(len(re.findall(r"Request req\d+ output: ", out_d0)) == 4
              and "Request req" not in
              "\n".join(l for l in out_pre1.splitlines()
                        if "output:" in l),
              "all four streams decoded on the dedicated decode engine")
    res.check(rc_pre1 == 0
              and "Fleet drain leak guard: clean" in out_pre1,
              f"prefill survivor drained leak-clean and exited 0 "
              f"(got rc {rc_pre1})")
    res.check(rc_d0 == 0 and "Fleet drain leak guard: clean" in out_d0,
              f"decode engine drained leak-clean and exited 0 "
              f"(got rc {rc_d0})")

    # --- bit-exactness: one unfailed COLOCATED serve, same prompts,
    # seeds and prefill chunking — every disaggregated stream must match
    ref_reqs = os.path.join(base, "ref_requests.jsonl")
    shutil.copy(intake, ref_reqs)
    ref = _ServeDriver(_serve_argv(ckpts, job, [
        "--prefill-buckets", "16,32", "--seed", str(seed), "--follow",
        "--poll-seconds", "0.2", "--request-file", ref_reqs]),
        "disagg_ref")
    try:
        for r in reqs:
            res.check(ref.wait_for(rf"Request {r['id']} output: ",
                                   timeout=420) is not None,
                      f"reference serve completed {r['id']}")
        ref.proc.send_signal(_signal.SIGUSR1)
        ref_rc = ref.finish()
    finally:
        if ref.proc.poll() is None:
            ref.proc.kill()
            ref.finish(timeout=10)
    res.check(ref_rc == 0, f"reference serve exited 0 (got {ref_rc})")
    disagg_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                     out_d0))
    ref_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                  ref.output()))
    res.check(
        len(disagg_outputs) == 4 and all(
            disagg_outputs.get(f"req{i}") == ref_outputs.get(f"req{i}")
            for i in range(4)),
        "disaggregated streams (shipped-block imports and the CRC-reject "
        "replay alike) bit-identical to the unfailed colocated reference")

    # --- request-trace stitch: every trail crosses into the decode host
    # and is flagged disaggregated (block_ship/decode_placement spans)
    traced = {r["request_id"]: r
              for r in reqtrace.stitch([base]) if r["request_id"]}
    trace_ok = len(traced) == 4
    for r in reqs:
        tr = traced.get(r["id"])
        trace_ok = (trace_ok and tr is not None
                    and bool(tr.get("disaggregated"))
                    and "d0" in set(tr.get("hosts", ())))
    res.check(trace_ok,
              "stitched trace: all four requests flagged disaggregated "
              "with the decode host on the critical path")
    _stitch_scenario(res, events_dir)
    return res


def run_kvstore_scenario(work: str, parquet: str, seed: int) -> Result:
    """Fleet-global KV store scenario: poison the one published train
    (store_corrupt) and SIGKILL the publishing host mid-decode — the
    fetching host CRC-rejects exactly once, degrades to local recompute,
    the router's cache-affinity placement still lands the second request
    on the publisher, zero requests are lost, and every stream
    bit-matches an unfailed single-host reference serve (module
    docstring)."""
    res = Result("kvstore")
    base = os.path.join(work, "kvstore")
    ckpts = os.path.join(base, "ckpts")
    events_dir = os.path.join(ckpts, "events")
    os.makedirs(base, exist_ok=True)
    job = "kvstore_a"

    rc, out = _run(_train_argv(parquet, ckpts, seed,
                               **{"--training-steps": "10",
                                  "--checkpoint-frequency": "5"}), job)
    if not res.check(rc == 0, f"kvstore training checkpoint committed "
                              f"(got rc {rc})"):
        return res

    store = os.path.join(base, "store")
    jdir = os.path.join(base, "journal")
    kvstore_dir = os.path.join(base, "kvstore")
    intake = os.path.join(base, "intake.jsonl")
    # all four prompts share every FULL 16-token block (34-char shared
    # prefix, <=13-char tails keep the block boundary inside the shared
    # region), so they share ONE content-addressed train
    shared = "alpha bravo charlie delta echo fox"
    reqs = [
        {"id": "req0", "prompt": shared + " a1",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 11},
        {"id": "req1", "prompt": shared + " b2",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 12},
        {"id": "req2", "prompt": shared + " c3",
         "max_new_tokens": 48, "temperature": 0.0, "seed": seed + 13},
        {"id": "req3", "prompt": shared + " d4",
         "max_new_tokens": 48, "temperature": 0.8, "seed": seed + 14},
    ]

    def host_argv(hid, chaos):
        return [sys.executable, "-m",
                "fault_tolerant_llm_training_tpu.inference.fleet",
                "--host-id", hid, "--store", store, "--journal-dir", jdir,
                "--kv-store-dir", kvstore_dir,
                "--checkpoint-path", ckpts, "--checkpoint-job-id", job,
                "--model", "tiny", "--tokenizer-name-or-path", "byte",
                "--slots", "2", "--max-len", "256", "--no-eos",
                "--lease-ttl", "2.0", "--max-run-seconds", "240",
                "--seed", str(seed), "--chaos", chaos,
                "--event-log", os.path.join(base, f"events_{hid}.jsonl")]

    # h0 is the publisher: its first (and only) put is poisoned at
    # publish ordinal 0, then a SIGKILL at decode iteration 40 takes it
    # out mid-decode — the kill after a committed put is what the
    # manifest-commits-last ordering must make indistinguishable from a
    # clean put, and the torn-tail fold must absorb its journal
    h0 = _ServeDriver(host_argv(
        "h0", "step=0:store_corrupt;step=40:host_kill"), "kvstore_h0")
    h1 = _ServeDriver(host_argv("h1", ""), "kvstore_h1")
    router = None
    try:
        res.check(h0.wait_for(r"\[FLEET\] Host h0 joined", timeout=420)
                  is not None, "host h0 joined the fleet with a lease")
        res.check(h1.wait_for(r"\[FLEET\] Host h1 joined", timeout=420)
                  is not None, "host h1 joined the fleet with a lease")

        # stage the intake: req0 alone first, so h0 publishes the shared
        # train (poisoned) BEFORE the affinity-relevant requests arrive
        with open(intake, "w") as fh:
            fh.write(json.dumps(reqs[0]) + "\n")
        router = _ServeDriver(
            [sys.executable, "-m",
             "fault_tolerant_llm_training_tpu.inference.router",
             "--store", store, "--journal-dir", jdir, "--intake", intake,
             "--kv-store-dir", kvstore_dir,
             "--expected", "4", "--max-seconds", "180",
             "--poll-seconds", "0.1",
             "--event-log", os.path.join(base, "events_router.jsonl")],
            "kvstore_router")
        res.check(h0.wait_for(r"\[KV STORE\] publish", timeout=120)
                  is not None,
                  "h0 published the shared train to the fleet store")
        res.check(h0.wait_for(r"\[CHAOS\] Injected store_corrupt",
                              timeout=30) is not None,
                  "chaos poisoned the published store artifact "
                  "(manifest spared)")
        with open(intake, "a") as fh:
            for r in reqs[1:]:
                fh.write(json.dumps(r) + "\n")
        rrc = router.finish(timeout=200)
        res.check(rrc == 0, f"router completed and exited 0 (got {rrc})")
        rc0 = h0.finish(timeout=15)
        h1.proc.send_signal(_signal.SIGUSR1)
        rc1 = h1.finish(timeout=120)
    finally:
        for drv in (h0, h1, router):
            if drv is not None and drv.proc.poll() is None:
                drv.proc.kill()
                drv.finish(timeout=10)
    rout = router.output()
    out0, out1 = h0.output(), h1.output()

    res.check(rc0 == -9 and "[CHAOS] Injected host_kill" in out0,
              f"publishing host h0 SIGKILLed mid-decode (rc {rc0})")
    res.check("[FLEET] Host h0 declared dead" in rout,
              "router declared the dead publisher and migrated its work")
    # cache-affinity receipt: req1 arrived while h0 held the only copy
    # of the train AND fewer free blocks than h1 — without the affinity
    # term in pick_host it would have been placed on h1
    assigns = {}
    with open(os.path.join(jdir, "router.jsonl")) as fh:
        for line in fh:
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if rec.get("kind") == "assign":
                assigns.setdefault(str(rec.get("id")),
                                   str(rec.get("host")))
    res.check(assigns.get("req0") == "h0" and assigns.get("req1") == "h0",
              f"cache-affinity placement: req1 landed with the published "
              f"train on h0 (assigns {sorted(assigns.items())})")
    res.check(assigns.get("req2") == "h1" and assigns.get("req3") == "h1",
              f"free slots dominate affinity: overflow intake landed on "
              f"the cold host h1 (assigns {sorted(assigns.items())})")
    # the SHARED prompt train publishes exactly once fleet-wide
    # (content-address dedup: req2/req3 hash to the same terminal key on
    # h1 and skip the export). Migrated requests legitimately publish
    # NEW trains — their re-prefill covers prompt + committed tokens, a
    # longer chain with a different terminal hash — so the dedup pin is
    # per-key, not a global publish count. Exactly ONE CRC reject (h1's
    # first fetch; the recompute re-seeds its local cache so the next
    # admission never re-fetches).
    m_key = re.search(r"\[KV STORE\] publish key (\w+) request req0", out0)
    shared_key = m_key.group(1) if m_key is not None else ""
    n_shared = (out0 + out1).count(f"[KV STORE] publish key {shared_key}"
                                   ) if shared_key else 0
    n_rej = (out0 + out1).count("[KV STORE] reject")
    res.check(m_key is not None and n_shared == 1,
              f"content-address dedup: shared prompt train published "
              f"exactly once fleet-wide, by h0 (got {n_shared})")
    res.check(n_rej == 1 and "[KV STORE] reject" in out1
              and "falling back to local chunked prefill" in out1,
              f"exactly one CRC reject, on h1, degrading to local "
              f"recompute (got {n_rej})")
    res.check(re.search(r"Fleet router complete: 4 request\(s\) done, "
                        r"\d+ migrated, 0 lost", rout) is not None,
              "zero requests lost: all 4 served")
    res.check(rc1 == 0 and "Fleet drain leak guard: clean" in out1,
              f"survivor drained leak-clean and exited 0 (got rc {rc1})")

    # store post-mortem: the SIGKILL left no torn state — every visible
    # train either CRC-verifies or is the ONE poisoned artifact, and a
    # restarted handle folds the journals (h0's torn tail included)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KVBlockIntegrityError, verify_block_artifact)
    from fault_tolerant_llm_training_tpu.inference.kvstore import (
        BlockStore)
    post = BlockStore(kvstore_dir, writer="postmortem")
    folded = post.fold()          # raises on journal corruption
    bad = good = 0
    for key in folded:
        if not post.has(key):
            continue              # torn put: invisible by contract
        try:
            verify_block_artifact(post.train_dir(key))
            good += 1
        except KVBlockIntegrityError:
            bad += 1
    res.check(bad == 1,
              f"store post-mortem: exactly the one poisoned train fails "
              f"CRC ({bad} bad, {good} clean), no torn state survives")
    res.check(all(st.refs == 0 for st in folded.values()),
              "no leaked store refcounts: every journaled fetch ref was "
              "released")

    # unfailed single-host reference: every stream — fetched, locally
    # recomputed after the reject, and migrated alike — must bit-match
    ref_reqs = os.path.join(base, "ref_requests.jsonl")
    with open(ref_reqs, "w") as fh:
        for r in reqs:
            fh.write(json.dumps(r) + "\n")
    ref = _ServeDriver(_serve_argv(ckpts, job, [
        "--seed", str(seed), "--follow", "--poll-seconds", "0.2",
        "--request-file", ref_reqs]), "kvstore_ref")
    try:
        for r in reqs:
            res.check(ref.wait_for(rf"Request {r['id']} output: ",
                                   timeout=420) is not None,
                      f"reference serve completed {r['id']}")
        ref.proc.send_signal(_signal.SIGUSR1)
        ref_rc = ref.finish()
    finally:
        if ref.proc.poll() is None:
            ref.proc.kill()
            ref.finish(timeout=10)
    res.check(ref_rc == 0, f"reference serve exited 0 (got {ref_rc})")
    fleet_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                    out0 + "\n" + out1))
    ref_outputs = dict(re.findall(r"Request (req\d+) output: (.+)",
                                  ref.output()))
    res.check(
        len(fleet_outputs) == 4 and all(
            fleet_outputs.get(f"req{i}") == ref_outputs.get(f"req{i}")
            for i in range(4)),
        "store-fetched, reject-recomputed and migrated streams all "
        "bit-identical to the unfailed single-host reference serve")
    _stitch_scenario(res, events_dir)
    return res


def run_transport_scenario(work: str, parquet: str, seed: int) -> Result:
    """KV transport ladder scenario: chaos poisons the first mem-lane
    push's fabric metadata (``mem_corrupt``) AND a payload byte of the
    same request's fs artifact, so that request degrades mem -> fs ->
    committed-prefix replay; a second request takes only the mem poison
    and stops one rung down, on the fs artifact. Every other train lands
    zero-copy on the mem lane. Zero requests lost, no leaked blocks, all
    streams bit-identical to an unfailed colocated reference (module
    docstring). Runs in-process: the mem lane's fabric is process-local
    by design, so the two roles share one address space here just as
    colocated prefill/decode engines on one host would."""
    import glob as _glob
    import logging as _logging

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.chaos.injector import (
        ChaosInjector)
    from fault_tolerant_llm_training_tpu.chaos.schedule import (
        parse_schedule)
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.inference.transport import (
        MemFabric, MemTransport)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    res = Result("transport")
    base = os.path.join(work, "transport")
    os.makedirs(base, exist_ok=True)

    cfg = get_config("tiny", vocab_size=64, seq_len=128,
                     layer_impl="loop")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def build():
        return InferenceEngine(cfg, params, slots=2, max_len=128,
                               prefill_buckets=(16, 32),
                               kv_layout="paged", kv_block_size=8)

    rng = np.random.default_rng(seed + 31)
    reqs = [Request(id=f"req{i}",
                    prompt=rng.integers(3, 64, size=24 + 8 * i).tolist(),
                    max_new_tokens=12,
                    **({} if i % 2 == 0 else
                       {"temperature": 0.8, "top_p": 0.9}),
                    seed=seed + 50 + i)
            for i in range(4)]
    n = len(reqs)

    def clone(r, **extra):
        return Request(id=r.id, prompt=list(r.prompt),
                       max_new_tokens=r.max_new_tokens,
                       temperature=r.temperature, top_p=r.top_p,
                       seed=r.seed, **extra)

    # unfailed colocated reference: the streams every degradation rung
    # must reproduce bitwise
    ref = Scheduler(build(), registry=MetricRegistry())
    for r in reqs:
        ref.submit(clone(r))
    ref.run()
    ref_streams = {c.request_id: c.tokens for c in ref.completed}
    res.check(len(ref_streams) == n,
              f"colocated reference served all {n} requests")

    # capture the frozen [KV XPORT] audit trail the ladder must leave
    audit, handler = [], None

    class _Capture(_logging.Handler):
        def emit(self, record):
            audit.append(record.getMessage())

    sched_logger = _logging.getLogger()    # the scheduler audits to root
    handler = _Capture()
    old_level = sched_logger.level
    sched_logger.setLevel(_logging.INFO)   # audit lines log at INFO
    sched_logger.addHandler(handler)
    try:
        fabric = MemFabric()
        chaos = ChaosInjector(parse_schedule("step=0:mem_corrupt"),
                              seed=seed)
        poisoned = []

        def on_push(fab, handle, ordinal=0):
            hit = chaos.on_mem_push(fab, handle, ordinal)
            if hit:
                poisoned.append(hit)

        ships = {}

        def on_ship(req, art_dir, ordinal, seq, start, end, length):
            ships.setdefault(req.id, []).append(
                {"artifact": art_dir, "seq": seq, "start_block": start,
                 "end_block": end, "length": length, "lane": "mem"})

        pre = Scheduler(build(), role="prefill",
                        ship_dir=os.path.join(base, "ships"),
                        on_ship=on_ship,
                        transport=MemTransport(fabric, on_push=on_push),
                        registry=MetricRegistry())
        for r in reqs:
            pre.submit(clone(r))
        pre.run()
        first = {c.request_id: c.tokens for c in pre.completed}
        res.check(len(first) == n and pre.ship_exports >= n,
                  f"prefill committed and shipped all {n} requests "
                  f"({pre.ship_exports} train(s) exported)")
        res.check(len(poisoned) == 1,
                  "chaos poisoned exactly the first mem push's fabric "
                  "metadata (mem_corrupt, ordinal 0)")
        res.check(len(fabric) == pre.ship_exports,
                  "every exported train was pushed to the shared fabric")

        # rung 3 setup: the poisoned train's request ALSO loses its fs
        # artifact (one payload byte), so its ladder bottoms out at the
        # committed-prefix replay; find which request owns that train
        victim = next(r.id for r in reqs for s in ships[r.id]
                      if s["artifact"] == poisoned[0])
        # a second request takes ONLY the mem poison: one rung down
        second = next(r.id for r in reqs if r.id != victim)
        fabric.poison(ships[second][0]["artifact"])
        blk = sorted(_glob.glob(os.path.join(
            poisoned[0], "block_*.bin")))[0]
        raw = bytearray(open(blk, "rb").read())
        raw[3] ^= 0xFF
        open(blk, "wb").write(bytes(raw))

        dec = Scheduler(build(), role="decode",
                        transport=MemTransport(fabric),
                        registry=MetricRegistry())
        for r in reqs:
            dec.submit(clone(r, committed=tuple(first[r.id])),
                       shipments=ships.get(r.id), ship_gen=0)
        dec.run()
        streams = {c.request_id: c.tokens for c in dec.completed}
    finally:
        sched_logger.removeHandler(handler)
        sched_logger.setLevel(old_level)

    res.check(len(streams) == n,
              f"zero requests lost: decode completed {len(streams)}/{n} "
              f"across all three degradation rungs")
    res.check(streams == ref_streams,
              "all decode streams — mem-landed, fs-degraded and "
              "replayed alike — bit-identical to the unfailed colocated "
              "reference")
    res.check(dec.mem_lane_imports == n - 2,
              f"untouched trains landed zero-copy on the mem lane "
              f"({dec.mem_lane_imports} of {n})")
    res.check(dec.lane_fallbacks == 2 and dec.ship_rejects == 1,
              f"degradation ladder: two mem->fs fallbacks, one of which "
              f"fell through to replay (fallbacks "
              f"{dec.lane_fallbacks}, rejects {dec.ship_rejects})")
    fallbacks = [ln for ln in audit
                 if ln.startswith("[KV XPORT] fallback lane mem")]
    res.check(len(fallbacks) == 2,
              f"audit trail: [KV XPORT] fallback lane mem logged for "
              f"both poisoned trains (got {len(fallbacks)})")
    res.check(any(ln.startswith(f"[DISAGG] Shipment reject request "
                                f"{victim} ") for ln in audit),
              f"audit trail: shipment reject for the doubly-poisoned "
              f"request {victim} (replay rung)")
    res.check(pre.audit_block_leaks(strict=False) == []
              and dec.audit_block_leaks(strict=False) == [],
              "no leaked KV blocks on either role after the ladder")
    return res


def format_report(results, seed: int, wall: float, extra_notes) -> str:
    lines = []
    lines.append("Chaos survival campaign")
    lines.append(f"seed {seed} | scenarios {len(results)} | "
                 f"wall {wall:.0f} s | driver scripts/chaos_campaign.py")
    lines.append("")
    lines.append(f"{'class':<14} {'survived':<9} {'goodput%':>9} "
                 f"{'mttr_s':>8} {'replayed':>9}")
    lines.append("-" * 53)
    for r in results:
        gp = f"{r.goodput_pct:.1f}" if r.goodput_pct is not None else "-"
        mt = (f"{r.mttr_seconds:.1f}" if r.mttr_seconds is not None
              else "-")
        rp = (str(r.replayed_steps) if r.replayed_steps is not None
              else "-")
        lines.append(f"{r.name:<14} {'yes' if r.survived else 'NO':<9} "
                     f"{gp:>9} {mt:>8} {rp:>9}")
    lines.append("")
    for r in results:
        lines.append(f"[{r.name}]")
        for n in r.notes:
            lines.append(f"  {n}")
        lines.append("")
    for n in extra_notes:
        lines.append(n)
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="seeded chaos survival campaign (see module docstring)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--scenarios", default=",".join(SCENARIOS),
                   help=f"comma-separated subset of {SCENARIOS}")
    p.add_argument("--workdir", default="/tmp/ftl_chaos_campaign")
    p.add_argument("--out", default=os.path.join(REPO, "logs",
                                                 "chaos_campaign.txt"))
    p.add_argument("--sbatch", default="",
                   help="resubmit via this sbatch (e.g. scripts/fake_slurm/"
                        "sbatch) instead of a touch-marker command")
    args = p.parse_args(argv)

    wanted = [s.strip() for s in args.scenarios.split(",") if s.strip()]
    bad = [s for s in wanted if s not in SCENARIOS]
    if bad:
        p.error(f"unknown scenario(s) {bad}; known: {SCENARIOS}")

    work = os.path.join(args.workdir, f"seed{args.seed}")
    shutil.rmtree(work, ignore_errors=True)
    os.makedirs(work, exist_ok=True)
    parquet = os.path.join(work, "train_data.parquet")
    _make_parquet(parquet, args.seed)

    t0 = time.monotonic()
    print(f"== baseline (clean 30-step run, seed {args.seed})")
    base_ckpts = os.path.join(work, "baseline", "ckpts")
    rc, out = _run(_train_argv(parquet, base_ckpts, args.seed,
                               **{"--checkpoint-frequency": "0"}),
                   "baseline")
    if rc != 0 or "Training completed" not in out:
        print(out[-4000:])
        print("baseline run failed; aborting campaign", file=sys.stderr)
        return 1
    baseline_losses = _event_losses(os.path.join(base_ckpts, "events"),
                                    "baseline")
    if len(baseline_losses) != 30:
        print(f"baseline produced {len(baseline_losses)} step losses, "
              f"want 30; aborting", file=sys.stderr)
        return 1

    results = []
    for name in wanted:
        print(f"== scenario: {name}")
        if name == "deploy":
            res = run_deploy_scenario(work, parquet, args.seed)
        elif name == "fleet":
            res = run_fleet_scenario(work, parquet, args.seed)
        elif name == "tiered":
            res = run_tiered_scenario(work, parquet, args.seed)
        elif name == "disagg":
            res = run_disagg_scenario(work, parquet, args.seed)
        elif name == "kvstore":
            res = run_kvstore_scenario(work, parquet, args.seed)
        elif name == "transport":
            res = run_transport_scenario(work, parquet, args.seed)
        else:
            res = run_scenario(name, work, parquet, args.seed,
                               baseline_losses, sbatch=args.sbatch)
        timeline = _write_postmortem(name, work)
        if name == "fleet":
            _check_fleet_postmortem(res, timeline)
        results.append(res)
        print(f"   -> {'survived' if res.survived else 'FAILED'}")

    extra = []
    by_name = {r.name: r for r in results}
    if "ckpt_corrupt" in by_name and "exception" in by_name:
        # Two independent jobs, same seed: every array of their periodic
        # step-10 saves must be value-identical — the state the corrupt
        # scenario FELL BACK to is exactly the state an uncorrupted chain
        # had at that step.
        a = _state_digest(os.path.join(work, "ckpt_corrupt", "ckpts"),
                          "ckpt_corrupt_a", 10)
        b = _state_digest(os.path.join(work, "exception", "ckpts"),
                          "exception_a", 10)
        r = by_name["ckpt_corrupt"]
        r.check(a is not None and a == b,
                "fallback step-10 state array-for-array CRC-identical to "
                "the exception scenario's independent step-10 save "
                "(bit-exact state)")
        extra.append(
            "cross-scenario evidence: ckpt_corrupt's fallback source "
            "(step 10) and exception's step 10 were written by independent "
            "processes; every restored array matches CRC-for-CRC — the "
            "verified fallback resumes the exact state a clean run had.")

    wall = time.monotonic() - t0
    report = format_report(results, args.seed, wall, extra)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as fh:
        fh.write(report + "\n")
    print()
    print(report)
    print(f"\nreport written to {args.out}")
    return 0 if all(r.survived for r in results) else 2


if __name__ == "__main__":
    sys.exit(main())
