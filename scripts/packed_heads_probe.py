"""Prototype probe: flash FORWARD consuming (B, S, H*D) directly via
two-head 128-lane blocks — can the q/k/v input-side transpose copies die?

The round-4 state (ROUND_NOTES round-5 candidates): the last ~5 ms of the
copy family is the (B,S,H,D)->(B,H,S,D) relayout feeding the kernels. A
(1, block_q, dh=64) block on the UNtransposed (B, S, H*D) array is
illegal (the trailing block dim must be a multiple of 128 or full), but a
(1, block_q, 128) block covering TWO adjacent 64-wide heads is legal —
at the cost of lane-half slicing inside the kernel and a doubled body.

This probe times the forward only, at the bench shape, against the
production path (transpose + resident fwd kernel). If the packed form
does not clearly win here, the full-family surgery (5 kernels + GQA
mapping + backward residual plumbing) is not worth it.
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    b, s, h, d = 8, 2048, 12, 64
    if "--small" in sys.argv:  # CPU correctness shape
        b, s, h, d = 1, 256, 4, 64
    block_q, block_k = fa._blocks(s, fa.FWD_BLOCK_Q, fa.FWD_BLOCK_K)
    scale = 1.0 / (d ** 0.5)

    rng = np.random.default_rng(0)
    q_flat = jnp.asarray(rng.standard_normal((b, s, h * d)), jnp.bfloat16)
    k_flat = jnp.asarray(rng.standard_normal((b, s, h * d)), jnp.bfloat16)
    v_flat = jnp.asarray(rng.standard_normal((b, s, h * d)), jnp.bfloat16)

    # ---- production path: reshape+transpose, resident fwd kernel ----
    def prod(qf, kf, vf):
        qt = jnp.transpose(qf.reshape(b, s, h, d), (0, 2, 1, 3))
        kt = jnp.transpose(kf.reshape(b, s, h, d), (0, 2, 1, 3))
        vt = jnp.transpose(vf.reshape(b, s, h, d), (0, 2, 1, 3))
        out, _ = fa._flash_fwd_t(qt, kt, vt, True, fa._interpret())
        return out  # (B, H, S, D)

    # ---- packed path: (B, S, H*D) with two-head 128-lane blocks ----
    def packed_kernel(q_ref, k_ref, v_ref, o_ref):
        # q_ref/o_ref: (1, block_q, 128) at (bi, qi, pair);
        # k_ref/v_ref: (1, S, 128) at (bi, 0, pair). Two heads per step.
        q_start = pl.program_id(1) * block_q
        n_full, n_total = fa._k_block_bounds(q_start, block_q, s, block_k,
                                             True)
        o_halves = []
        for half in (slice(0, d), slice(d, 2 * d)):
            q2 = fa._prescale_q(q_ref[0, :, half], scale)

            def body(j, carry, masked, half=half, q2=q2):
                k_start = j * block_k
                k = k_ref[0, pl.ds(k_start, block_k), half]
                v = v_ref[0, pl.ds(k_start, block_k), half]
                return fa._online_softmax_step(q2, k, v, carry, q_start,
                                               k_start, masked)

            init = (jnp.full((block_q,), fa.NEG_INF, jnp.float32),
                    jnp.zeros((block_q,), jnp.float32),
                    jnp.zeros((block_q, d), jnp.float32))
            carry = jax.lax.fori_loop(
                0, n_full, functools.partial(body, masked=False), init)
            m, l, acc = jax.lax.fori_loop(
                n_full, n_total, functools.partial(body, masked=True), carry)
            o_halves.append((acc / l[:, None]).astype(o_ref.dtype))
        o_ref[0] = jnp.concatenate(o_halves, axis=-1)

    def packed(qf, kf, vf):
        return pl.pallas_call(
            packed_kernel,
            grid=(b, s // block_q, h // 2),
            in_specs=[
                pl.BlockSpec((1, block_q, 128),
                             lambda bi, qi, pi: (bi, qi, pi)),
                pl.BlockSpec((1, s, 128), lambda bi, qi, pi: (bi, 0, pi)),
                pl.BlockSpec((1, s, 128), lambda bi, qi, pi: (bi, 0, pi)),
            ],
            out_specs=pl.BlockSpec((1, block_q, 128),
                                   lambda bi, qi, pi: (bi, qi, pi)),
            out_shape=jax.ShapeDtypeStruct((b, s, h * d), qf.dtype),
            interpret=fa._interpret(),
        )(qf, kf, vf)

    # correctness first
    want = np.asarray(
        jnp.transpose(prod(q_flat, k_flat, v_flat),
                      (0, 2, 1, 3)).reshape(b, s, h * d), np.float32)
    got = np.asarray(packed(q_flat, k_flat, v_flat), np.float32)
    err = np.max(np.abs(got - want)) / (np.max(np.abs(want)) or 1.0)
    print(f"packed-vs-production rel err: {err:.3e}", flush=True)
    assert err < 2e-2, "packed kernel wrong"

    def timed(fn, tag):
        g = jax.jit(fn)
        out = g(q_flat, k_flat, v_flat)
        hard_sync(out)
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(30):
                out = g(q_flat, k_flat, v_flat)
            hard_sync(out)
            best = min(best, (time.perf_counter() - t0) / 30)
        print(f"{tag}: {best * 1000:.2f} ms", flush=True)
        return best

    t_prod = timed(prod, "transpose + resident fwd (production)")
    t_pack = timed(packed, "packed two-head fwd on (B,S,H*D)     ")
    print(f"packed/production ratio: {t_pack / t_prod:.3f}")


if __name__ == "__main__":
    main()
