#!/bin/bash
# Local (no-Slurm) reproduction of the reference's published evidence chain
# (ref: logs/output_444664.out -> 444671 -> 444691):
#
#   job 1: training is "preempted" (USR1, the Slurm pre-timeout signal)
#          -> checkpoint saved -> chain resubmitted
#   job 2: resumes at the saved step with zero loss of steps
#          -> deliberately injected error -> checkpoint saved, NO resubmit
#   job 3: resumes again -> manual cancel (SIGTERM, scancel)
#          -> terminates WITHOUT saving
#
# Produces logs/output_demo{1,2,3}.out with the same audit strings the
# reference's logs carry, then asserts the chain: saved step == resumed
# step (zero-step-loss), resubmit marker exists, and job 3 wrote nothing.
#
# Runs on CPU in ~2 min (tiny model, byte tokenizer, synthetic parquet).
set -euo pipefail

cd "$(dirname "$0")/.."
. scripts/demo_common.sh
WORK=${DEMO_WORKDIR:-/tmp/ftl_demo}
rm -rf "$WORK"
mkdir -p "$WORK" logs

demo_cpu_env
demo_make_parquet "$WORK/train_data.parquet"

COMMON=(--dataset "$WORK/train_data.parquet" --checkpoint-path "$WORK/ckpts"
        --tokenizer-name-or-path byte --model tiny --sequence-length 128
        --batch-size 2 --logging-frequency 10)

# --- job 1: preemption (USR1 ~ Slurm's --signal=USR1@120) ------------------
echo "== job 1: preempt with USR1 -> save + resubmit"
SLURM_JOB_ID=demo1 python train.py "${COMMON[@]}" --training-steps 100000 \
  --resubmit-command "touch $WORK/resubmitted" \
  > logs/output_demo1.out 2>&1 &
PID=$!
# Anchor the signal on the training-start log line, NOT a fixed sleep: a
# cold compile can outlast any constant, and USR1 before train.py's
# handlers are registered kills the job with the default disposition.
for _ in $(seq 1 120); do
    grep -q "Starting training!" logs/output_demo1.out 2>/dev/null && break
    sleep 2
done
sleep 10          # train a few hundred steps past the start
kill -USR1 $PID   # what Slurm sends 120 s before the time limit
wait $PID

# --- job 2: resume, then hit the injected fault ----------------------------
SAVED=$(grep -oP 'Checkpoint saved at step \K\d+' logs/output_demo1.out)
ERR=$((SAVED + 200))
echo "== job 2: resume from step $SAVED -> injected error at $ERR"
SLURM_JOB_ID=demo2 python train.py "${COMMON[@]}" --training-steps 100000 \
  --checkpoint-id demo1 --raise-error --error-step "$ERR" \
  > logs/output_demo2.out 2>&1

# --- job 3: resume again, then scancel (SIGTERM) ---------------------------
echo "== job 3: resume -> scancel (TERM) -> terminate without saving"
SLURM_JOB_ID=demo3 python train.py "${COMMON[@]}" --training-steps 100000 \
  --checkpoint-id demo2 \
  > logs/output_demo3.out 2>&1 &
PID=$!
sleep 15
kill -TERM $PID   # what scancel sends
wait $PID

# --- assertions (the reference verifies these by reading logs; here they
# --- are machine-checked — SURVEY.md §4 upgrade) ---------------------------
echo "== assertions"
grep -q "Job timed out, saving checkpoint" logs/output_demo1.out
grep -q "sbatch requeued" logs/output_demo1.out
test -f "$WORK/resubmitted"
RESUMED=$(grep -oP 'Resuming training from training_step \K\d+' logs/output_demo2.out)
[ "$SAVED" = "$RESUMED" ]   # zero steps lost (ref: saved @427, resumed @427)
grep -q "Error during training encountered, saving checkpoint" logs/output_demo2.out
! grep -q "sbatch requeued" logs/output_demo2.out   # code error: no resubmit
SAVED2=$(grep -oP 'Checkpoint saved at step \K\d+' logs/output_demo2.out)
RESUMED2=$(grep -oP 'Resuming training from training_step \K\d+' logs/output_demo3.out)
[ "$SAVED2" = "$RESUMED2" ]
grep -q "Job cancelled, terminating" logs/output_demo3.out
! grep -q "saving checkpoint" logs/output_demo3.out  # cancel: no save
echo "OK: preempt->save@$SAVED->resume@$RESUMED->error@$ERR->save@$SAVED2->resume@$RESUMED2->cancel"
