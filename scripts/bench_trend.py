"""Bench-regression sentinel: hold every committed BENCH receipt to its
own history.

ci_nightly re-runs each bench and asserts its scenario-specific bars,
but nothing watches the *committed receipts themselves* drift across
PRs — a PR that re-commits BENCH_disagg_cpu.json with the interference
ratio quietly down 15% passes every nightly bar that only checks
"> 1x". This sentinel closes that gap: it parses every committed
``BENCH_*.json``, maintains an append-only history
(``logs/bench_trend.jsonl``), and fails (exit 3, metric named) when any
pinned headline metric regresses more than ``--tolerance`` (default
10%) against the best value the history has ever recorded.

Only deliberately chosen headline metrics are pinned (the PINNED table
below) with an explicit better-direction each — wall-clock magnitudes
that ci_nightly already treats as machine-dependent are held to the
committed receipt trend, not re-measured here.

Usage:
    python scripts/bench_trend.py                       # committed receipts
    python scripts/bench_trend.py --current-dir /tmp/x  # compare a fresh /
                                                        # synthetic set
                                                        # against baseline
    python scripts/bench_trend.py --json                # machine-readable
"""

import argparse
import glob
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fault_tolerant_llm_training_tpu.obs import events  # noqa: E402
from fault_tolerant_llm_training_tpu.utils.logging import (  # noqa: E402
    AUDIT_FLEETSCOPE_TREND_OK_FMT,
    AUDIT_FLEETSCOPE_TREND_REGRESSION_FMT,
    init_logger,
    logger,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# receipt -> [(json key, better direction, label)]. One entry per
# headline number a PR would be embarrassed to regress silently.
PINNED: Dict[str, List[Tuple[str, str, str]]] = {
    "BENCH_decode_tiny_cpu.json": [
        ("value", "higher", "decode tokens/sec/slot")],
    "BENCH_decode_paged_cpu.json": [
        ("value", "higher", "long-context paged decode tokens/sec")],
    "BENCH_decode_fused_cpu.json": [
        ("value", "lower", "dispatches/token at burst 8")],
    "BENCH_decode_prefix_cpu.json": [
        ("value", "lower", "cached N8/N1 prefill ratio"),
        ("kv_prefix_hit_rate_n8", "higher", "prefix-cache hit rate")],
    "BENCH_decode_spec_cpu.json": [
        ("value", "higher", "speculative decode speedup")],
    "BENCH_decode_tree_cpu.json": [
        ("value", "higher", "tree vs linear accepted/dispatch")],
    "BENCH_prefill_packed_cpu.json": [
        ("value", "higher", "packed prefill speedup vs sequential")],
    "BENCH_serving_latency_cpu.json": [
        ("value", "lower", "worst-point p99 TTFT ms")],
    "BENCH_kv_spill_cpu.json": [
        ("value", "higher", "spill-on late-request TTFT speedup")],
    "BENCH_kv_quant_cpu.json": [
        ("blocks_ratio", "higher", "int8 blocks at fixed pool bytes"),
        ("concurrency_gain", "higher", "admission concurrency gain")],
    "BENCH_disagg_cpu.json": [
        ("value", "higher", "colocated/disagg p99 interference ratio")],
    "BENCH_kv_store_cpu.json": [
        ("cross_host_hit_rate", "higher", "fleet-store cross-host hit "
                                          "rate")],
    "BENCH_kv_transport_cpu.json": [
        ("mem_lane_landing_speedup", "higher", "mem-lane fs/mem "
                                               "per-train landing "
                                               "speedup"),
        ("partial_hit_rate", "higher", "sub-train partial prefix hit "
                                       "rate")],
    "BENCH_adapter_serving_cpu.json": [
        ("batched_vs_sequential_speedup", "higher",
         "batched heterogeneous-adapter decode vs sequential "
         "per-adapter serving at fixed pool bytes")],
}


def read_pinned(receipts_dir: str) -> Dict[str, Dict[str, float]]:
    """``{receipt: {metric: value}}`` for every pinned receipt present."""
    out: Dict[str, Dict[str, float]] = {}
    for receipt, metrics in sorted(PINNED.items()):
        path = os.path.join(receipts_dir, receipt)
        if not os.path.isfile(path):
            continue
        try:
            with open(path) as fh:
                data = json.load(fh)
        except (OSError, ValueError):
            continue
        got: Dict[str, float] = {}
        for key, _direction, _label in metrics:
            if key in data:
                try:
                    got[key] = float(data[key])
                except (TypeError, ValueError):
                    continue
        if got:
            out[receipt] = got
    return out


def load_history(path: str) -> List[Dict]:
    entries: List[Dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    entries.append(json.loads(line))
                except ValueError:
                    continue  # torn tail: keep the committed prefix
    except OSError:
        pass
    return entries


def baseline_from(history: List[Dict],
                  committed: Dict[str, Dict[str, float]],
                  receipt: str, key: str,
                  direction: str) -> Optional[float]:
    """Best value ever recorded for (receipt, key): the history's
    best, seeded by the committed receipt when history is empty."""
    values = [committed.get(receipt, {}).get(key)]
    for entry in history:
        values.append(entry.get("metrics", {}).get(receipt, {}).get(key))
    values = [v for v in values if v is not None]
    if not values:
        return None
    return max(values) if direction == "higher" else min(values)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--receipts-dir", default=REPO_ROOT,
                   help="where the committed BENCH_*.json receipts live "
                        "(the baseline; default: repo root)")
    p.add_argument("--current-dir", default="",
                   help="compare the receipts in this directory against "
                        "the baseline instead of the committed ones "
                        "(fresh bench output, or a synthetic-regression "
                        "fixture); only receipts present here are "
                        "checked, and history is NOT appended")
    p.add_argument("--history",
                   default=os.path.join(REPO_ROOT, "logs",
                                        "bench_trend.jsonl"),
                   help="append-only trend history (JSONL)")
    p.add_argument("--tolerance", type=float, default=0.10,
                   help="relative regression allowed in the worse "
                        "direction before the sentinel fails")
    p.add_argument("--no-history", action="store_true",
                   help="do not append this run to the history file")
    p.add_argument("--json", action="store_true",
                   help="emit the per-metric verdicts as JSON")
    p.add_argument("--event-log", default="",
                   help="flight-recorder JSONL for the sentinel's audit "
                        "event")
    args = p.parse_args(argv)

    init_logger()
    if args.event_log:
        events.configure(args.event_log, job="bench_trend", host=0)

    committed = read_pinned(args.receipts_dir)
    current = (read_pinned(args.current_dir) if args.current_dir
               else committed)
    history = load_history(args.history)

    verdicts: List[Dict] = []
    regressions: List[Dict] = []
    for receipt in sorted(current):
        for key, direction, label in PINNED[receipt]:
            cur = current[receipt].get(key)
            if cur is None:
                continue
            base = baseline_from(history, committed, receipt, key,
                                 direction)
            if base is None or base == 0:
                continue
            delta = (cur - base) / abs(base)
            worse = -delta if direction == "higher" else delta
            verdict = {"receipt": receipt, "metric": key, "label": label,
                       "direction": direction, "baseline": base,
                       "current": cur,
                       "delta_pct": round(delta * 100.0, 3),
                       "regressed": worse > args.tolerance}
            verdicts.append(verdict)
            if verdict["regressed"]:
                regressions.append(verdict)

    if not args.current_dir and not args.no_history and verdicts:
        os.makedirs(os.path.dirname(os.path.abspath(args.history)),
                    exist_ok=True)
        with open(args.history, "a") as fh:
            fh.write(json.dumps({"ts": time.time(),
                                 "receipts_dir": args.receipts_dir,
                                 "metrics": committed},
                                separators=(",", ":")) + "\n")

    if args.json:
        print(json.dumps({"verdicts": verdicts,
                          "regressions": len(regressions)}, indent=2))
    else:
        for v in verdicts:
            mark = "REGRESSION" if v["regressed"] else "ok"
            print(f"{mark}: {v['receipt']} {v['metric']} "
                  f"({v['label']}) {v['current']} vs baseline "
                  f"{v['baseline']} ({v['delta_pct']:+.1f}%, "
                  f"{v['direction']} is better)")

    if regressions:
        worst = max(regressions,
                    key=lambda v: (-v["delta_pct"]
                                   if v["direction"] == "higher"
                                   else v["delta_pct"]))
        events.emit_audit(
            logger, AUDIT_FLEETSCOPE_TREND_REGRESSION_FMT.format(
                receipt=worst["receipt"], metric=worst["metric"],
                delta_pct=worst["delta_pct"],
                baseline=worst["baseline"], current=worst["current"],
                direction=worst["direction"]),
            "fleetscope_trend", regressed=len(regressions),
            receipt=worst["receipt"], metric=worst["metric"],
            delta_pct=worst["delta_pct"])
        events.flush()
        return 3
    events.emit_audit(
        logger, AUDIT_FLEETSCOPE_TREND_OK_FMT.format(
            metrics=len(verdicts),
            receipts=len({v["receipt"] for v in verdicts}),
            tolerance_pct=int(round(args.tolerance * 100))),
        "fleetscope_trend", regressed=0, metrics=len(verdicts),
        receipts=len({v["receipt"] for v in verdicts}))
    events.flush()
    return 0


if __name__ == "__main__":
    sys.exit(main())
