"""Long-context attention fwd+bwd timing: in-kernel rope vs XLA rope.

The round-4 default (cfg.rope_impl='fused') moves RoPE into the flash
kernels for EVERY sequence length on the pallas path — the headline win
was measured at S=2048 (BASELINE.md round 4); this times the streaming
regime so the default is validated (or scoped) across the long-context
curve. B1/H12/D64 fwd+bwd, matching the round-2/3 long-context rows.

Run on the chip:  python scripts/longctx_bench.py [--sizes 4096,8192,...]
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--sizes", default="4096,8192,16384,32768")
    p.add_argument("--iters", type=int, default=8)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_rope,
    )
    from fault_tolerant_llm_training_tpu.ops.rope import (
        apply_rope,
        precompute_rope,
    )
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    b, h, d = 1, 12, 64
    for s in (int(x) for x in args.sizes.split(",")):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        k = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        v = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
        cos, sin = precompute_rope(d, s, 10000.0)
        cos2 = jnp.repeat(cos, 2, axis=-1)
        sin2 = jnp.repeat(sin, 2, axis=-1)

        def loss_xla(q, k, v):
            return jnp.sum(flash_attention(
                apply_rope(q, cos, sin), apply_rope(k, cos, sin), v,
                True).astype(jnp.float32) ** 2)

        def loss_rope(q, k, v):
            qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3))
                          for x in (q, k, v))
            return jnp.sum(flash_attention_rope(
                qt, kt, vt, cos2, sin2, True).astype(jnp.float32) ** 2)

        def timed(loss_fn, tag):
            # iterate INSIDE one jit with a data dependence so XLA cannot
            # hoist the work (ROUND_NOTES microbench trap); per-iteration
            # q perturbation depends on the previous grad.
            grad = jax.grad(loss_fn, argnums=(0, 1, 2))

            @jax.jit
            def run(q, k, v):
                def body(carry, _):
                    q, k, v = carry
                    dq, dk, dv = grad(q, k, v)
                    return (q + 1e-6 * dq.astype(q.dtype), k, v), None
                (q, _, _), _ = jax.lax.scan(body, (q, k, v), None,
                                            length=args.iters)
                return q

            out = run(q, k, v)
            hard_sync(out)
            t0 = time.perf_counter()
            out = run(q, k, v)
            hard_sync(out)
            dt = (time.perf_counter() - t0) / args.iters
            return dt

        t_xla = timed(loss_xla, "xla")
        t_rope = timed(loss_rope, "rope")
        print(f"S={s}: xla-rope {t_xla * 1000:.1f} ms  in-kernel rope "
              f"{t_rope * 1000:.1f} ms  ratio {t_rope / t_xla:.3f}",
              flush=True)


if __name__ == "__main__":
    main()
