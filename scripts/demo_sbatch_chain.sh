#!/bin/bash
# Drive the REAL train.sh through a local sbatch/srun shim
# (scripts/fake_slurm/) — one step closer to the reference's genuine
# Slurm evidence chain (ref logs/output_444664.out -> 444671) than
# demo_fault_chain.sh, which calls train.py directly:
#
#   sbatch train.sh   -> job A trains until the shim delivers the
#                        pre-timeout USR1 (the --signal=USR1@N
#                        semantics) -> save + SELF-resubmit via the
#                        handler's real `sbatch $WORKDIR/train.sh
#                        $SLURM_JOB_ID`
#   (shim sbatch)     -> job B: train.sh's own `$1 -> --checkpoint-id`
#                        plumbing resumes at the saved step; once the
#                        resume is verified the job is cancelled the
#                        Slurm way (scancel = SIGTERM -> terminate
#                        WITHOUT saving), closing the three-policy chain
#                        in two jobs.
#
# Asserts: saved step == resumed step (zero loss), the timeout/requeue/
# cancel audit strings, and both jobs logged under the #SBATCH
# --output=%j pattern. The only train.sh accommodation is the
# env-overridable FTL_TRAINING_CMD_OVERRIDE (its default stays the reference shape) —
# the contract rides unchanged onto a real cluster. CPU, ~2-3 min.
set -euo pipefail
cd "$(dirname "$0")/.."
REPO=$(pwd)
. scripts/demo_common.sh

export WORKDIR=${DEMO_WORKDIR:-/tmp/ftl_sbatch}
rm -rf "$WORKDIR"
mkdir -p "$WORKDIR/data" "$WORKDIR/logs" "$WORKDIR/checkpoints"
cp train.sh train.py "$WORKDIR/"
ln -s "$REPO/fault_tolerant_llm_training_tpu" "$WORKDIR/"

demo_cpu_env
demo_make_parquet "$WORKDIR/data/train_data.parquet"

export PATH="$REPO/scripts/fake_slurm:$PATH"
export FAKE_SLURM_DIR="$WORKDIR/.slurm"
# Seconds of training before the shim's USR1 (anchored on the job's
# "Starting training!" line, so compile time cannot race the handlers).
export FAKE_SLURM_USR1_AFTER=${FAKE_SLURM_USR1_AFTER:-20}
# Small config via train.sh's namespaced env override (ADVICE r4:
# FTL_TRAINING_CMD_OVERRIDE, collision-proof under sbatch --export=ALL);
# no --raise-error — the shim's USR1 IS the fault. The huge step target
# guarantees job A is mid-training when the signal lands; job B inherits
# it and is scancelled once its resume is verified (see header).
export FTL_TRAINING_CMD_OVERRIDE=" --model tiny --tokenizer-name-or-path byte \
  --sequence-length 128 --batch-size 2 --training-steps 100000 \
  --logging-frequency 50"

cd "$WORKDIR"
OUT=$(sbatch "$WORKDIR/train.sh")
echo "$OUT"
ID_A=${OUT##* }

fail() { echo "FAIL: $1"; shift; for f in "$@"; do echo "-- tail $f"; tail -8 "$f" 2>/dev/null; done; exit 1; }

deadline=$(( $(date +%s) + 420 ))
ID_B=""
while [ -z "$ID_B" ]; do
    [ "$(date +%s)" -gt "$deadline" ] && fail "no chained job appeared" "$WORKDIR/logs/output_$ID_A.out"
    sleep 5
    ID_B=$(ls "$FAKE_SLURM_DIR" | sed -n "s/^job_\([0-9]*\)\.pid$/\1/p" | grep -v "^$ID_A$" | head -1 || true)
done
echo "chained job: $ID_B (from $ID_A)"

LOG_A="$WORKDIR/logs/output_$ID_A.out"
LOG_B="$WORKDIR/logs/output_$ID_B.out"
while ! grep -q "Resuming training from training_step" "$LOG_B" 2>/dev/null; do
    [ "$(date +%s)" -gt "$deadline" ] && fail "job B never resumed" "$LOG_A" "$LOG_B"
    sleep 5
done
sleep 5  # let job B take a few post-resume steps
kill -TERM "$(cat "$FAKE_SLURM_DIR/job_$ID_B.pid")"
sleep 10

echo "== assertions"
SAVED=$(sed -n 's/.*Checkpoint saved at step \([0-9]*\).*/\1/p' "$LOG_A" | head -1)
RESUMED=$(sed -n 's/.*Resuming training from training_step \([0-9]*\).*/\1/p' "$LOG_B" | head -1)
grep -q "Job timed out, saving checkpoint." "$LOG_A" \
    || fail "job A missing the timeout-save audit string" "$LOG_A"
grep -q "sbatch requeued" "$LOG_A" \
    || fail "job A missing the requeue audit string" "$LOG_A"
grep -q "Job cancelled, terminating." "$LOG_B" \
    || fail "job B missing the scancel audit string" "$LOG_B"
[ -n "$SAVED" ] || fail "job A logged no saved step" "$LOG_A"
[ "$SAVED" = "$RESUMED" ] \
    || fail "saved step $SAVED != resumed step $RESUMED" "$LOG_A" "$LOG_B"
echo "OK: sbatch($ID_A) -> USR1+${FAKE_SLURM_USR1_AFTER}s -> saved@$SAVED -> self-resubmit -> sbatch($ID_B) resumed@$RESUMED -> scancel"
cp "$LOG_A" "$REPO/logs/output_sbatch_a.out"
cp "$LOG_B" "$REPO/logs/output_sbatch_b.out"
