"""Single-chip measurement of the pp_stage_unroll compute pattern.

The pipeline's ``--pp-stage-unroll`` question (parallel/pipeline.py
_stage_layers) could not be timed on multi-chip — but its COMPUTE pattern
can, on one chip: stacked (scan-form) layer params applied by (a) a
lax.scan over the stack vs (b) a static Python loop over ``tree[i]``
slices. (The loop trunk — separate param leaves — is the third point, the
headline bench.) Full train-step fwd+bwd timings at the bench shape.

Run on the chip:  python scripts/stage_unroll_bench.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fault_tolerant_llm_training_tpu.models import Transformer, get_config
    from fault_tolerant_llm_training_tpu.models.llama import TransformerBlock
    from fault_tolerant_llm_training_tpu.training.step import (
        cross_entropy_loss,
    )
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    cfg = get_config("gpt2-125m", vocab_size=50257, seq_len=2048,
                     layer_impl="scan")
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, cfg.seq_len)),
                       jnp.int32)
    labels = jnp.concatenate(
        [toks[:, 1:], jnp.full((8, 1), -100, jnp.int32)], axis=1)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    block = TransformerBlock(cfg)

    def trunk_scan(params, toks):
        return model.apply({"params": params}, toks)

    def trunk_unrolled(params, toks):
        # the _stage_layers unrolled pattern on the full stack: embed ->
        # static tree[i] slices -> norm -> head, all through the module's
        # own pieces so only the layer control flow differs
        x = model.apply({"params": params}, toks, method="embed")
        pos = jnp.arange(cfg.seq_len, dtype=jnp.int32)[None, :]
        stacked = params["layers"]["block"]
        for i in range(cfg.n_layers):
            layer = jax.tree_util.tree_map(lambda a: a[i], stacked)
            x = block.apply({"params": layer}, x, pos)
        return model.apply({"params": params}, x, method="head")

    def timed(fwd, tag):
        def loss_fn(params):
            return cross_entropy_loss(fwd(params, toks), labels)[0]

        g = jax.jit(jax.value_and_grad(loss_fn))
        out = g(params)
        hard_sync(out)
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            for _ in range(30):
                out = g(params)
            hard_sync(out)
            best = min(best, (time.perf_counter() - t0) / 30)
        print(f"{tag}: {best * 1000:.1f} ms/iter "
              f"({8 * cfg.seq_len / best / 1000:.1f}k tokens/s fwd+bwd)",
              flush=True)
        return best

    t_scan = timed(trunk_scan, "stacked + lax.scan      ")
    t_unroll = timed(trunk_unrolled, "stacked + static unroll ")
    print(f"unroll/scan ratio: {t_unroll / t_scan:.3f}")


if __name__ == "__main__":
    main()
