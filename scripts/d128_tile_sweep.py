"""D=128 tile mini-sweep (VERDICT r4 next-step #7).

Every tile constant in ops/flash_attention.py was tuned at D=64 (the
gpt2-125m bench head width). The flagship llama3-8b preset runs D=128 —
this sweep times the resident family's fwd+bwd at a llama-shaped GQA
config (h:kv = 4:1, D=128, S=2048 — the S*D budget boundary, so the
fused backward is engaged exactly as the flagship would) across tile
candidates, on the chip, to decide whether the D=64 constants transfer
or need a D=128 dispatch branch.

A second section sweeps the SERVING kernels' head-tile knobs
(ops/paged_attention.py ``DECODE_HEAD_TILE``/``CHUNK_HEAD_TILE``): the
paged decode and chunk kernels grid over kv heads one at a time by
default — at D=128 with 4 kv heads a wider per-dispatch head tile may
amortize the grid's scalar-prefetch overhead. Timed at a serving-shaped
pool (decode S=1 and the S=6 tree-verify/chunk window), knobs restored
after the sweep; 1 stays the recorded default unless the chip says
otherwise.

Run on the TPU:  python scripts/d128_tile_sweep.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    b, s, h, kv, d = 4, 2048, 8, 2, 128
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.bfloat16)

    def loss(q, k, v):
        return jnp.sum(fa.flash_attention(q, k, v, True).astype(
            jnp.float32) ** 2)

    defaults = dict(FWD_BLOCK_Q=fa.FWD_BLOCK_Q, FWD_BLOCK_K=fa.FWD_BLOCK_K,
                    DQ_BLOCK_Q=fa.DQ_BLOCK_Q, DQ_BLOCK_K=fa.DQ_BLOCK_K,
                    DKV_BLOCK_Q=fa.DKV_BLOCK_Q, DKV_BLOCK_K=fa.DKV_BLOCK_K)

    combos = [
        ("default D64 tiles (512,512|512,512|512,1024)", {}),
        ("fwd 256x512", dict(FWD_BLOCK_Q=256, FWD_BLOCK_K=512)),
        ("fwd 512x256", dict(FWD_BLOCK_Q=512, FWD_BLOCK_K=256)),
        ("fwd 256x256", dict(FWD_BLOCK_Q=256, FWD_BLOCK_K=256)),
        ("fwd 1024x512", dict(FWD_BLOCK_Q=1024, FWD_BLOCK_K=512)),
        ("dq 256x512", dict(DQ_BLOCK_Q=256, DQ_BLOCK_K=512)),
        ("dq 512x256", dict(DQ_BLOCK_Q=512, DQ_BLOCK_K=256)),
        ("dkv 512x512", dict(DKV_BLOCK_Q=512, DKV_BLOCK_K=512)),
        ("dkv 1024x512", dict(DKV_BLOCK_Q=1024, DKV_BLOCK_K=512)),
        ("dkv 256x1024", dict(DKV_BLOCK_Q=256, DKV_BLOCK_K=1024)),
    ]

    results = []
    for tag, over in combos:
        for name, val in {**defaults, **over}.items():
            setattr(fa, name, val)
        try:
            g = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2)))
            out = g(q, k, v)
            hard_sync(out[0])
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                for _ in range(20):
                    out = g(q, k, v)
                hard_sync(out[0])
                best = min(best, (time.perf_counter() - t0) / 20)
            results.append((best, tag))
            print(f"{tag:48s} {best * 1000:8.2f} ms", flush=True)
        except Exception as e:
            print(f"{tag:48s} FAILED: {str(e)[:120]}", flush=True)
    for name, val in defaults.items():
        setattr(fa, name, val)
    results.sort()
    print(f"\nbest: {results[0][1]} ({results[0][0] * 1000:.2f} ms); "
          f"default at {[r for r in results if 'default' in r[1]][0][0] * 1000:.2f} ms")

    _paged_head_tile_sweep()


def _paged_head_tile_sweep():
    """Serving kernels at D=128: DECODE_HEAD_TILE x CHUNK_HEAD_TILE."""
    import jax
    import jax.numpy as jnp

    import fault_tolerant_llm_training_tpu.ops.paged_attention as pa
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    slots, kv, h, bs, nb, d, s_q = 8, 4, 8, 16, 16, 128, 6
    rng = np.random.default_rng(5)
    n_pool = slots * nb + 1
    pool_k = jnp.asarray(rng.standard_normal((n_pool, kv, bs, d)),
                         jnp.bfloat16)
    pool_v = jnp.asarray(rng.standard_normal((n_pool, kv, bs, d)),
                         jnp.bfloat16)
    tables = jnp.asarray(
        rng.permutation(np.arange(1, slots * nb + 1)).reshape(slots, nb)
        .astype(np.int32))
    offsets = jnp.asarray(
        rng.integers(bs, nb * bs - s_q, size=slots).astype(np.int32))
    q1 = jnp.asarray(rng.standard_normal((slots, 1, h, d)), jnp.bfloat16)
    qs = jnp.asarray(rng.standard_normal((slots, s_q, h, d)), jnp.bfloat16)

    lanes = (("decode S=1", "DECODE_HEAD_TILE",
              lambda: jax.jit(pa.paged_decode_attention)),
             (f"chunk S={s_q}", "CHUNK_HEAD_TILE",
              lambda: jax.jit(pa.paged_chunk_attention)))
    print(f"\npaged head-tile sweep (slots={slots} kv={kv} h={h} d={d})")
    for tag, knob, make in lanes:
        default = getattr(pa, knob)
        q = q1 if knob == "DECODE_HEAD_TILE" else qs
        rows = []
        for tile in (1, 2, 4):
            setattr(pa, knob, tile)
            try:
                fn = make()              # fresh jit: the knob is baked in
                out = fn(q, pool_k, pool_v, tables, offsets)
                hard_sync(out)
                best = float("inf")
                for _ in range(2):
                    t0 = time.perf_counter()
                    for _ in range(50):
                        out = fn(q, pool_k, pool_v, tables, offsets)
                    hard_sync(out)
                    best = min(best, (time.perf_counter() - t0) / 50)
                rows.append((best, tile))
                print(f"  {tag:12s} {knob}={tile}   {best * 1e6:9.1f} us",
                      flush=True)
            except Exception as e:
                print(f"  {tag:12s} {knob}={tile}   FAILED: {str(e)[:100]}",
                      flush=True)
        setattr(pa, knob, default)
        if rows:
            rows.sort()
            print(f"  {tag:12s} best {knob}={rows[0][1]} "
                  f"({rows[0][0] * 1e6:.1f} us; default {default})")


if __name__ == "__main__":
    main()
