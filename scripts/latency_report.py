"""Latency report: stitch request-trace span logs across the serving fleet.

The serving twin of ``goodput_report.py``: every serving process (router,
fleet hosts, ``serve.py``) writes a crash-surviving span trail
(``trace_<name>.jsonl`` next to its ``--event-log``, obs/reqtrace.py); this
tool joins the trails by ``trace_id`` — so a request migrated between hosts
becomes ONE critical path — and prints per-request TTFT/TPOT, the hosts
each request visited, replayed-token counts, and p50/p95/p99 percentiles,
plus an SLO-attainment table when targets are given.

Usage:
    python scripts/latency_report.py <trace-dir-or-file> [more paths...]
    python scripts/latency_report.py run/ --slo-ttft-ms 500 --slo-tpot-ms 50
    python scripts/latency_report.py 'run/trace_*.jsonl' --json

Paths may be JSONL files, directories (all ``trace*.jsonl`` inside), or
globs; all spans are pooled and grouped per trace id before stitching.
"""

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fault_tolerant_llm_training_tpu.obs.reqtrace import (  # noqa: E402
    format_report,
    stitch,
)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("paths", nargs="+",
                   help="trace files, directories, or globs")
    p.add_argument("--json", action="store_true",
                   help="emit per-request records as JSON instead of the "
                        "table")
    p.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="TTFT SLO target in ms; adds the attainment line")
    p.add_argument("--slo-tpot-ms", type=float, default=None,
                   help="TPOT SLO target in ms; adds the attainment line")
    args = p.parse_args(argv)

    paths = []
    for raw in args.paths:
        hits = glob.glob(raw)
        paths.extend(hits if hits else [raw])
    reqs = stitch(paths)
    if not reqs:
        print(f"no trace spans found under: {', '.join(args.paths)}",
              file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(reqs, indent=2))
    else:
        print(format_report(
            reqs,
            slo_ttft=(args.slo_ttft_ms / 1e3
                      if args.slo_ttft_ms is not None else None),
            slo_tpot=(args.slo_tpot_ms / 1e3
                      if args.slo_tpot_ms is not None else None)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
