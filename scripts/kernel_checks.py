"""On-chip kernel correctness checks (run on the real TPU).

Complements the interpret-mode CPU tests (tests/test_flash_attention.py,
tests/test_ring_attention.py) with checks where the kernels actually run
compiled, at the tuned production tiles (VERDICT round-1 weak spot #6: the
tuned D=64 shapes had no on-chip parity pin):

1. flash-vs-XLA allclose at the production shapes (D=64), forward AND
   gradients: resident S=2048; S=4096 (streamed forward + FUSED backward
   within the S*D budget, GQA); S=16384 (streamed forward + the SPLIT
   streaming backward, the only dispatch above the budget).
2. A single-chip S=64k ring-carry check: the last ring position's work —
   its query block folded against all sp KV blocks through the carry
   kernels (ops/ring_flash.py) exactly as the per-device ring loop does —
   must match the corresponding rows of the streaming flash kernel's
   full-sequence output. This pins the carry kernels' numerics at the
   long-context scale they exist for, on one chip (the ring itself needs a
   multi-device 'sequence' axis; the per-step local math is what runs
   here). Peak HBM is reported to document memory parity with the
   streaming kernels (the round-1 einsum local math would need an
   (S/sp)^2 fp32 score tensor = 256 MB per kv-head-group at these shapes).

Prints one JSON line per check; exits non-zero on any failure.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax
import jax.numpy as jnp


def _mem_peak():
    try:
        stats = jax.local_devices()[0].memory_stats()
        return int(stats.get("peak_bytes_in_use", 0))
    except Exception:
        return -1


def check_flash_parity(s, h, kv, d, dtype=jnp.bfloat16):
    from fault_tolerant_llm_training_tpu.ops.attention import xla_attention
    from fault_tolerant_llm_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((1, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((1, s, kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((1, s, kv, d)), dtype)

    want = jax.jit(lambda q, k, v: xla_attention(q, k, v, causal=True))(
        q, k, v)
    got = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))(q, k, v)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))

    def loss_x(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True).astype(
            jnp.float32) ** 2)

    def loss_f(q, k, v):
        return jnp.sum(flash_attention(q, k, v, True).astype(
            jnp.float32) ** 2)

    gx = jax.jit(jax.grad(loss_x, argnums=(0, 1, 2)))(q, k, v)
    gf = jax.jit(jax.grad(loss_f, argnums=(0, 1, 2)))(q, k, v)
    gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
               for a, b in zip(gx, gf))
    # bf16 inputs with fp32 accumulators: elementwise |max| error tracks
    # the bf16 ulp of the magnitudes involved.
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0
    gscale = max(float(jnp.max(jnp.abs(a.astype(jnp.float32))))
                 for a in gx) or 1.0
    ok = err / scale < 2e-2 and gerr / gscale < 5e-2
    print(json.dumps({
        "check": f"flash_vs_xla_onchip s={s} h={h} kv={kv} d={d}",
        "max_abs_err_out": err, "max_abs_err_grad": gerr,
        "rel_out": err / scale, "rel_grad": gerr / gscale, "ok": ok,
    }), flush=True)
    return ok


def check_rope_fused_parity(s, h, kv, d, dtype=jnp.bfloat16):
    """In-kernel rope (the rope_impl='fused' production default) vs
    XLA-side apply_rope + the same flash kernels, compiled on the chip."""
    from fault_tolerant_llm_training_tpu.ops.flash_attention import (
        flash_attention,
        flash_attention_rope,
    )
    from fault_tolerant_llm_training_tpu.ops.rope import (
        apply_rope,
        precompute_rope,
    )

    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, s, h, d)), dtype)
    k = jnp.asarray(rng.standard_normal((1, s, kv, d)), dtype)
    v = jnp.asarray(rng.standard_normal((1, s, kv, d)), dtype)
    cos, sin = precompute_rope(d, s, 10000.0)
    cos2 = jnp.repeat(cos, 2, axis=-1)
    sin2 = jnp.repeat(sin, 2, axis=-1)

    def f_ref(q, k, v):
        return flash_attention(apply_rope(q, cos, sin),
                               apply_rope(k, cos, sin), v, True)

    def f_rope(q, k, v):
        qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
        return jnp.transpose(
            flash_attention_rope(qt, kt, vt, cos2, sin2, True), (0, 2, 1, 3))

    want = jax.jit(f_ref)(q, k, v)
    got = jax.jit(f_rope)(q, k, v)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    gx = jax.jit(jax.grad(
        lambda *a: jnp.sum(f_ref(*a).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gf = jax.jit(jax.grad(
        lambda *a: jnp.sum(f_rope(*a).astype(jnp.float32) ** 2),
        argnums=(0, 1, 2)))(q, k, v)
    gerr = max(float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                     - b.astype(jnp.float32))))
               for a, b in zip(gx, gf))
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0
    gscale = max(float(jnp.max(jnp.abs(a.astype(jnp.float32))))
                 for a in gx) or 1.0
    ok = err / scale < 2e-2 and gerr / gscale < 5e-2
    print(json.dumps({
        "check": f"rope_fused_vs_xla_rope_onchip s={s} h={h} kv={kv} d={d}",
        "max_abs_err_out": err, "max_abs_err_grad": gerr,
        "rel_out": err / scale, "rel_grad": gerr / gscale, "ok": ok,
    }), flush=True)
    return ok


def check_ring_carry_64k(s=65536, sp=8, h=4, kv=2, d=64):
    """Last-ring-position carry-kernel math == streaming flash at S=64k."""
    from fault_tolerant_llm_training_tpu.ops.flash_attention import (
        _interpret,
        flash_attention,
    )
    from fault_tolerant_llm_training_tpu.ops.ring_flash import (
        carry_fwd,
        finalize_carry,
        fresh_carry,
    )

    itp = _interpret()  # CPU sanity runs use pallas interpret mode

    s_loc = s // sp
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((1, s, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, s, kv, d)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, s, kv, d)), jnp.bfloat16)

    base = _mem_peak()
    full = jax.jit(lambda q, k, v: flash_attention(q, k, v, True))(q, k, v)
    full.block_until_ready()
    flash_peak = _mem_peak()

    my = sp - 1  # the position whose queries see every KV block

    @jax.jit
    def last_position(q, k, v):
        qt = jnp.transpose(q[:, my * s_loc:], (0, 2, 1, 3))
        m, l, acc = fresh_carry(1, h, s_loc, d)
        for t in range(sp):
            src = (my - t) % sp
            k_blk = jnp.transpose(
                k[:, src * s_loc:(src + 1) * s_loc], (0, 2, 1, 3))
            v_blk = jnp.transpose(
                v[:, src * s_loc:(src + 1) * s_loc], (0, 2, 1, 3))
            m, l, acc = carry_fwd(qt, k_blk, v_blk, m, l, acc,
                                  my * s_loc, src * s_loc, causal=True,
                                  interpret=itp)
        out, _ = finalize_carry(m, l, acc, q.dtype)
        return jnp.transpose(out, (0, 2, 1, 3))

    got = last_position(q, k, v)
    got.block_until_ready()
    ring_peak = _mem_peak()
    want = full[:, my * s_loc:]
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0
    ok = err / scale < 2e-2
    print(json.dumps({
        "check": f"ring_carry_vs_streaming_flash s={s} sp={sp} d={d}",
        "max_abs_err": err, "rel": err / scale,
        "peak_hbm_after_flash_mb": round((flash_peak - base) / 2**20, 1)
        if flash_peak > 0 else None,
        "peak_hbm_after_ring_mb": round((ring_peak - base) / 2**20, 1)
        if ring_peak > 0 else None,
        "einsum_score_tensor_would_be_mb": round(
            (s_loc * s_loc * 4 * (h // kv)) / 2**20, 1),
        "ok": ok,
    }), flush=True)
    return ok


def check_paged_decode_parity(slots=8, kv=2, h=4, bs=16, nb=16, d=64,
                              dtype=jnp.bfloat16):
    """Pallas paged-decode kernel vs the gather reference, compiled on the
    chip at serving shapes, over an adversarial pool: shuffled block order,
    garbage null block, freed tails fallen back to block 0, stale table
    entries aimed at orphaned blocks, two slots sharing prefix blocks, and
    offsets pinned to block boundaries. The CPU tests pin the same matrix
    in interpret mode (tests/test_paged_kernel.py); this pins the MOSAIC
    lowering at the tuned head widths."""
    from fault_tolerant_llm_training_tpu.ops.attention import (
        paged_cached_attention,
    )
    from fault_tolerant_llm_training_tpu.ops.paged_attention import (
        paged_decode_attention,
    )

    rng = np.random.default_rng(3)
    n_pool = slots * nb + 4                 # null + spare orphan blocks
    pool_k = jnp.asarray(rng.standard_normal((n_pool, kv, bs, d)), dtype)
    pool_v = jnp.asarray(rng.standard_normal((n_pool, kv, bs, d)), dtype)
    perm = rng.permutation(np.arange(1, slots * nb + 1))
    tables = perm.reshape(slots, nb).astype(np.int32)
    offsets = rng.integers(1, nb * bs - 1, size=slots).astype(np.int32)
    offsets[0] = 2 * bs                     # decode lands ON a boundary
    offsets[1] = bs - 1                     # last position of block 0
    for b in range(slots):                  # free blocks past the live tail
        tables[b, int(offsets[b]) // bs + 1:] = 0
    tables[2, -1] = n_pool - 1              # stale entry at an orphan block
    tables[3, :2] = tables[2, :2]           # shared prefix rows
    q = jnp.asarray(rng.standard_normal((slots, 1, h, d)), dtype)
    tables = jnp.asarray(tables)
    offsets = jnp.asarray(offsets)

    want = jax.jit(paged_cached_attention)(q, pool_k, pool_v, tables,
                                           offsets)
    got = jax.jit(paged_decode_attention)(q, pool_k, pool_v, tables,
                                          offsets)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0
    ok = err / scale < 2e-2
    print(json.dumps({
        "check": (f"paged_decode_vs_gather_onchip slots={slots} kv={kv} "
                  f"h={h} bs={bs} nb={nb} d={d}"),
        "max_abs_err": err, "rel": err / scale, "ok": ok,
    }), flush=True)
    return ok


def check_paged_chunk_parity(slots=8, kv=2, h=4, bs=16, nb=16, d=64, s_q=8,
                             dtype=jnp.bfloat16):
    """Pallas paged-chunk kernel (S > 1: chunked/packed prefill, chunk-mode
    spec-verify) vs the gather reference, compiled on the chip, over the
    same adversarial pool matrix as the decode check but with each slot's
    chunk STARTING at its offset — boundary-straddling chunks, stale table
    tails past the last row, shared prefix blocks. Also pins the masked-byte
    invariance compiled: rewriting every pool byte outside the rows' live
    sets must not move the output by a single bit."""
    from fault_tolerant_llm_training_tpu.ops.attention import (
        paged_cached_attention,
    )
    from fault_tolerant_llm_training_tpu.ops.paged_attention import (
        paged_chunk_attention,
    )

    rng = np.random.default_rng(4)
    n_pool = slots * nb + 4
    np_k = rng.standard_normal((n_pool, kv, bs, d))
    np_v = rng.standard_normal((n_pool, kv, bs, d))
    perm = rng.permutation(np.arange(1, slots * nb + 1))
    tables = perm.reshape(slots, nb).astype(np.int32)
    # offsets are chunk STARTS; rows reach offsets[b] + s_q - 1
    offsets = rng.integers(0, nb * bs - s_q, size=slots).astype(np.int32)
    offsets[0] = 2 * bs                     # chunk starts ON a boundary
    offsets[1] = bs - s_q // 2              # chunk STRADDLES a boundary
    for b in range(slots):                  # free blocks past the last row
        tables[b, (int(offsets[b]) + s_q - 1) // bs + 1:] = 0
    tables[2, -1] = n_pool - 1              # stale entry at an orphan block
    tables[3, :2] = tables[2, :2]           # shared prefix rows
    q = jnp.asarray(rng.standard_normal((slots, s_q, h, d)), dtype)
    pool_k, pool_v = jnp.asarray(np_k, dtype), jnp.asarray(np_v, dtype)
    jtables, joffsets = jnp.asarray(tables), jnp.asarray(offsets)

    want = jax.jit(paged_cached_attention)(q, pool_k, pool_v, jtables,
                                           joffsets)
    got = jax.jit(paged_chunk_attention)(q, pool_k, pool_v, jtables,
                                         joffsets)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0

    live = np.zeros((n_pool, bs), bool)
    for b in range(slots):
        for i in range(nb):
            for lane in range(bs):
                if i * bs + lane <= int(offsets[b]) + s_q - 1:
                    live[tables[b, i], lane] = True
    mask = live[:, None, :, None]
    k2 = jnp.asarray(np.where(mask, np_k, rng.standard_normal(np_k.shape)),
                     dtype)
    v2 = jnp.asarray(np.where(mask, np_v, rng.standard_normal(np_v.shape)),
                     dtype)
    got2 = jax.jit(paged_chunk_attention)(q, k2, v2, jtables, joffsets)
    invariant = bool(jnp.array_equal(got, got2))

    ok = err / scale < 2e-2 and invariant
    print(json.dumps({
        "check": (f"paged_chunk_vs_gather_onchip slots={slots} kv={kv} "
                  f"h={h} bs={bs} nb={nb} d={d} s_q={s_q}"),
        "max_abs_err": err, "rel": err / scale,
        "masked_bytes_bitwise_invariant": invariant, "ok": ok,
    }), flush=True)
    return ok


def check_tree_verify_parity(slots=8, kv=2, h=4, bs=16, nb=16, d=64,
                             dtype=jnp.bfloat16):
    """Ancestor-masked tree-verify: pallas in-place kernel vs the gather
    reference, compiled on the chip, over the adversarial pool matrix
    (shuffled tables, window starting ON and STRADDLING block boundaries,
    stale table tails, an orphan-block entry, shared prefix rows). The
    tree window is a real TreeShape's flattened rows — the exact (S, S)
    visibility matrix the engine bakes into its verify programs. Also
    pins the masked-byte bitwise invariance: rewriting every pool byte
    outside the committed prefixes + tree windows must not move a bit."""
    from fault_tolerant_llm_training_tpu.inference.engine import TreeShape
    from fault_tolerant_llm_training_tpu.ops.attention import (
        paged_tree_attention,
    )

    shape = TreeShape((2, 2, 1))
    s_q = shape.size
    anc = jnp.asarray(shape.anc_mask)
    rng = np.random.default_rng(6)
    n_pool = slots * nb + 4
    np_k = rng.standard_normal((n_pool, kv, bs, d))
    np_v = rng.standard_normal((n_pool, kv, bs, d))
    perm = rng.permutation(np.arange(1, slots * nb + 1))
    tables = perm.reshape(slots, nb).astype(np.int32)
    # offsets are committed lengths; tree row j sits at offsets[b] + j
    offsets = rng.integers(0, nb * bs - s_q, size=slots).astype(np.int32)
    offsets[0] = 2 * bs                     # window starts ON a boundary
    offsets[1] = bs - s_q // 2              # window STRADDLES a boundary
    for b in range(slots):                  # free blocks past the window
        tables[b, (int(offsets[b]) + s_q - 1) // bs + 1:] = 0
    tables[2, -1] = n_pool - 1              # stale entry at an orphan block
    tables[3, :2] = tables[2, :2]           # shared prefix rows
    q = jnp.asarray(rng.standard_normal((slots, s_q, h, d)), dtype)
    pool_k, pool_v = jnp.asarray(np_k, dtype), jnp.asarray(np_v, dtype)
    jtables, joffsets = jnp.asarray(tables), jnp.asarray(offsets)

    def ref(q, k, v, t, o):
        return paged_tree_attention(q, k, v, t, o, anc, impl="gather")

    def ker(q, k, v, t, o):
        return paged_tree_attention(q, k, v, t, o, anc, impl="pallas")

    want = jax.jit(ref)(q, pool_k, pool_v, jtables, joffsets)
    got = jax.jit(ker)(q, pool_k, pool_v, jtables, joffsets)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                - want.astype(jnp.float32))))
    scale = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0

    live = np.zeros((n_pool, bs), bool)
    for b in range(slots):
        for i in range(nb):
            for lane in range(bs):
                if i * bs + lane <= int(offsets[b]) + s_q - 1:
                    live[tables[b, i], lane] = True
    mask = live[:, None, :, None]
    k2 = jnp.asarray(np.where(mask, np_k, rng.standard_normal(np_k.shape)),
                     dtype)
    v2 = jnp.asarray(np.where(mask, np_v, rng.standard_normal(np_v.shape)),
                     dtype)
    got2 = jax.jit(ker)(q, k2, v2, jtables, joffsets)
    invariant = bool(jnp.array_equal(got, got2))

    ok = err / scale < 2e-2 and invariant
    print(json.dumps({
        "check": (f"tree_verify_vs_gather_onchip slots={slots} kv={kv} "
                  f"h={h} bs={bs} nb={nb} d={d} "
                  f"shape={','.join(map(str, shape.fanouts))}"),
        "max_abs_err": err, "rel": err / scale,
        "masked_bytes_bitwise_invariant": invariant, "ok": ok,
    }), flush=True)
    return ok


def _quantize_pool(np_pool):
    """Per-(block, kv-head) symmetric int8, the same rule the paged write
    path applies at local position 0 (inference/kv_cache.py)."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KV_QUANT_QMAX,
        QuantPool,
    )

    a = np.asarray(np_pool, np.float32)
    amax = np.max(np.abs(a), axis=(2, 3))
    scale = np.where(amax > 0, amax / KV_QUANT_QMAX, 1.0).astype(np.float32)
    q = np.clip(np.rint(a / scale[:, :, None, None]),
                -KV_QUANT_QMAX, KV_QUANT_QMAX).astype(np.int8)
    return QuantPool(q=jnp.asarray(q), scale=jnp.asarray(scale))


def check_quantized_decode_parity(slots=8, kv=2, h=4, bs=16, nb=16, d=64,
                                  dtype=jnp.bfloat16):
    """int8 KV pools, compiled: the fused-dequant pallas kernels (S=1
    decode, S>1 chunk, tree-verify) vs the int8 gather oracle must agree
    to kernel-numerics tolerance, and the int8 path vs the UNQUANTIZED
    bf16 gather reference must stay inside the per-block-scale
    quantization error bound — over the same adversarial pool matrix as
    the bf16 checks (garbage null block, freed tails at block 0, stale
    entries aimed at orphan blocks, shared/COW prefix rows, offsets ON
    and STRADDLING block boundaries)."""
    from fault_tolerant_llm_training_tpu.inference.engine import TreeShape
    from fault_tolerant_llm_training_tpu.ops.attention import (
        paged_cached_attention,
        paged_tree_attention,
    )
    from fault_tolerant_llm_training_tpu.ops.paged_attention import (
        paged_chunk_attention,
        paged_decode_attention,
    )

    shape = TreeShape((2, 2, 1))
    s_q = shape.size
    anc = jnp.asarray(shape.anc_mask)
    rng = np.random.default_rng(7)
    n_pool = slots * nb + 4
    np_k = rng.standard_normal((n_pool, kv, bs, d))
    np_v = rng.standard_normal((n_pool, kv, bs, d))
    perm = rng.permutation(np.arange(1, slots * nb + 1))
    tables = perm.reshape(slots, nb).astype(np.int32)
    offsets = rng.integers(s_q, nb * bs - s_q, size=slots).astype(np.int32)
    offsets[0] = 2 * bs                     # ON a block boundary
    offsets[1] = bs - s_q // 2              # chunk/window STRADDLES one
    for b in range(slots):                  # freed tails back at block 0
        tables[b, (int(offsets[b]) + s_q - 1) // bs + 1:] = 0
    tables[2, -1] = n_pool - 1              # stale entry at an orphan block
    tables[3, :2] = tables[2, :2]           # shared (COW-parent) rows
    pool_k, pool_v = jnp.asarray(np_k, dtype), jnp.asarray(np_v, dtype)
    qk, qv = _quantize_pool(np_k), _quantize_pool(np_v)
    jtables, joffsets = jnp.asarray(tables), jnp.asarray(offsets)
    q1 = jnp.asarray(rng.standard_normal((slots, 1, h, d)), dtype)
    qs = jnp.asarray(rng.standard_normal((slots, s_q, h, d)), dtype)

    def rel(got, want):
        e = float(jnp.max(jnp.abs(got.astype(jnp.float32)
                                  - want.astype(jnp.float32))))
        s = float(jnp.max(jnp.abs(want.astype(jnp.float32)))) or 1.0
        return e / s

    report, ok = {}, True
    # (path, fused-on-int8, oracle-on-int8, bf16 reference)
    paths = [
        ("decode",
         jax.jit(paged_decode_attention)(q1, qk, qv, jtables, joffsets),
         jax.jit(paged_cached_attention)(q1, qk, qv, jtables, joffsets),
         jax.jit(paged_cached_attention)(q1, pool_k, pool_v, jtables,
                                         joffsets)),
        ("chunk",
         jax.jit(paged_chunk_attention)(qs, qk, qv, jtables, joffsets),
         jax.jit(paged_cached_attention)(qs, qk, qv, jtables, joffsets),
         jax.jit(paged_cached_attention)(qs, pool_k, pool_v, jtables,
                                         joffsets)),
        ("tree",
         jax.jit(lambda *a: paged_tree_attention(*a, anc, impl="pallas"))(
             qs, qk, qv, jtables, joffsets),
         jax.jit(lambda *a: paged_tree_attention(*a, anc, impl="gather"))(
             qs, qk, qv, jtables, joffsets),
         jax.jit(lambda *a: paged_tree_attention(*a, anc, impl="gather"))(
             qs, pool_k, pool_v, jtables, joffsets)),
    ]
    for name, fused, oracle, ref16 in paths:
        r_oracle = rel(fused, oracle)   # kernel numerics, same int8 bytes
        r_quant = rel(fused, ref16)     # quantization error itself
        report[f"rel_{name}_vs_int8_oracle"] = r_oracle
        report[f"rel_{name}_vs_bf16_ref"] = r_quant
        ok &= r_oracle < 2e-2 and r_quant < 5e-2
    print(json.dumps({
        "check": (f"quantized_decode_parity slots={slots} kv={kv} h={h} "
                  f"bs={bs} nb={nb} d={d}"),
        **{k: round(v, 6) for k, v in report.items()}, "ok": ok,
    }), flush=True)
    return ok


def main():
    ok = True
    ok &= check_flash_parity(2048, 12, 12, 64)   # resident, bench shape
    ok &= check_flash_parity(4096, 4, 2, 64)     # streamed fwd + fused bwd, GQA
    ok &= check_flash_parity(16384, 4, 2, 64)    # split streaming bwd, GQA
    ok &= check_rope_fused_parity(2048, 12, 12, 64)  # in-kernel rope, bench
    ok &= check_rope_fused_parity(4096, 4, 2, 64)    # rope + streamed fwd
    # D=128 (the flagship llama head width; VERDICT r4 next-step #7): the
    # budgets and tiles were calibrated at D=64 — these pin that the
    # dispatch is CORRECT at double the head width, at the S*D boundary
    # (2048*128 == the fused-backward budget) and past it (split bwd).
    ok &= check_flash_parity(2048, 4, 2, 128)    # boundary, GQA
    ok &= check_flash_parity(4096, 4, 2, 128)    # above budget: split bwd
    ok &= check_rope_fused_parity(2048, 4, 2, 128)  # rope AT the boundary
    ok &= check_ring_carry_64k()
    ok &= check_ring_carry_64k(s=32768, sp=4, h=2, kv=2, d=128)
    ok &= check_paged_decode_parity()                       # serving, D=64
    ok &= check_paged_decode_parity(h=8, kv=4, d=128)       # flagship width
    ok &= check_paged_chunk_parity()                        # S>1 chunk, D=64
    ok &= check_paged_chunk_parity(h=8, kv=4, d=128)        # flagship width
    ok &= check_tree_verify_parity()                        # tree spec, D=64
    ok &= check_tree_verify_parity(h=8, kv=4, d=128)        # flagship width
    ok &= check_quantized_decode_parity()                   # int8 KV, D=64
    ok &= check_quantized_decode_parity(h=8, kv=4, d=128)   # flagship width
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
