#!/bin/bash
# Chaos campaign under the fake_slurm shim: four fault classes driven
# end-to-end by scripts/chaos_campaign.py, with the sigusr1 scenario's
# requeue going through the REAL sbatch interface (scripts/fake_slurm)
# instead of a touch-marker — the shim assigns a job id, honors
# #SBATCH --output, and backgrounds the batch script exactly like
# demo_sbatch_chain.sh. The survival report (per-class survived +
# goodput/MTTR) lands in logs/chaos_campaign.txt.
#
# Scenario set: sigusr1, sigterm, exception, ckpt_corrupt — the four
# process-killing classes; run scripts/chaos_campaign.py without
# --scenarios for the full five (adds loader_stall).
#
# Runs on CPU in ~1 min (tiny model, byte tokenizer, synthetic parquet).
set -euo pipefail

cd "$(dirname "$0")/.."
. scripts/demo_common.sh
WORK=${DEMO_WORKDIR:-/tmp/ftl_demo_chaos}
rm -rf "$WORK"
mkdir -p "$WORK" logs

demo_cpu_env
export FAKE_SLURM_DIR="$WORK/slurm"

# Batch script the exit handler's requeue hands to the shim. A production
# chain would resubmit train.sh; the demo's chained job just records that
# the sbatch round-trip (submit -> id -> output file -> run) happened,
# because the campaign runner drives the resume leg itself with the
# deterministic args the scenario needs.
cat > "$WORK/requeue.sh" <<EOF
#!/bin/bash
#SBATCH --output=$WORK/slurm/requeue_%j.out
echo "requeue accepted: job \$SLURM_JOB_ID"
EOF

python scripts/chaos_campaign.py --seed 0 \
  --scenarios sigusr1,sigterm,exception,ckpt_corrupt \
  --workdir "$WORK/campaign" \
  --sbatch "scripts/fake_slurm/sbatch $WORK/requeue.sh" \
  --out logs/chaos_campaign.txt

# The shim must have actually accepted the requeue: an id was assigned
# and the chained job's output file exists with its job id inside.
echo "== assertions (fake_slurm round-trip)"
ID=$(cat "$FAKE_SLURM_DIR/next_id")
for _ in $(seq 1 10); do
    grep -q "requeue accepted: job $ID" "$FAKE_SLURM_DIR/requeue_$ID.out" \
        2>/dev/null && break
    sleep 1
done
grep -q "requeue accepted: job $ID" "$FAKE_SLURM_DIR/requeue_$ID.out"
grep -q "sigusr1        yes" logs/chaos_campaign.txt
echo "OK: 4-scenario campaign survived; requeue chained through fake_slurm (job $ID)"
