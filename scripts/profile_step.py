"""Profile the training step and print a per-op-category device-time table.

The reference has no profiling subsystem (SURVEY.md §5.1); here
``jax.profiler`` traces are first-class: ``train.py --profile-dir`` records
one, and this tool both records and *reads* them — it parses the Chrome-trace
JSON the TPU runtime emits and aggregates device time by op family, which is
how the kernel/copy/fusion breakdown in BASELINE.md was measured.

Usage:
    python scripts/profile_step.py [--model gpt2-125m] [--batch-size 8]
        [--sequence-length 2048] [--steps 3] [--trace-dir /tmp/ftl_trace]

Works on any backend; on CPU the "device" is the host and times are
illustrative only.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The trace parser and the capture context live in obs/trace.py now (shared
# with train.py --trace-steps); re-exported here because this module-level
# name is the tool's API (tests/test_profile_tool.py imports it).
from fault_tolerant_llm_training_tpu.obs.trace import (  # noqa: E402
    capture,
    parse_trace,
)


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="gpt2-125m")
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--sequence-length", type=int, default=2048)
    p.add_argument("--steps", type=int, default=3)
    p.add_argument("--grad-accum", type=int, default=1)
    p.add_argument("--moe-experts", type=int, default=None)
    p.add_argument("--moe-top-k", type=int, default=None)
    p.add_argument("--trace-dir", default="/tmp/ftl_trace")
    p.add_argument("--top", type=int, default=15)
    args = p.parse_args()

    import jax

    from fault_tolerant_llm_training_tpu.models import get_config
    from fault_tolerant_llm_training_tpu.utils.harness import (
        synthetic_batch,
        synthetic_state_and_step,
    )
    from fault_tolerant_llm_training_tpu.utils.sync import hard_sync

    moe_over = {k: v for k, v in dict(
        moe_experts=args.moe_experts, moe_top_k=args.moe_top_k).items()
        if v is not None}  # don't clobber preset values with defaults
    cfg = get_config(args.model, seq_len=args.sequence_length, **moe_over)
    state, step = synthetic_state_and_step(cfg, grad_accum=args.grad_accum)
    toks, labels = synthetic_batch(cfg, args.batch_size)
    state, m = step(state, toks, labels)  # compile outside the trace
    hard_sync(m)

    with capture(args.trace_dir):
        for _ in range(args.steps):
            state, m = step(state, toks, labels)
        hard_sync(m)

    cats, total = parse_trace(args.trace_dir, args.steps)
    print(f"\ndevice time by op family ({args.model}, "
          f"bs {args.batch_size}, seq {cfg.seq_len}, "
          f"backend {jax.default_backend()}):")
    if not cats or total <= 0:
        print("  (no timed device-lane events in trace — CPU backends emit "
              "host-side traces only; run on TPU for the breakdown)")
        return
    print(f"{'ms/step':>10}  {'%':>5}  op family")
    for name, ms in sorted(cats.items(), key=lambda kv: -kv[1])[:args.top]:
        print(f"{ms:>10.2f}  {100 * ms / total:>5.1f}  {name}")
    print(f"{total:>10.2f}  100.0  TOTAL (device-busy)")


if __name__ == "__main__":
    main()
