"""Relative wall-clock of the 1F1B vs GPipe pipeline schedules (CPU mesh).

Real-ICI pipeline timing needs multi-chip hardware; what CAN be measured
anywhere is the SCHEDULE overhead ratio on the 8-virtual-device CPU mesh —
the compiled tick structure is identical to the TPU one (same shard_map,
same ppermutes, same tick counts), only the per-tick kernel speed differs.
This is the measurement behind the analytic bubble model in
parallel/pipeline.py's docstring (VERDICT r3 weak #2).

Run:
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python scripts/pp_bench.py
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_FORCE = "--xla_force_host_platform_device_count=8"
if _FORCE not in os.environ.get("XLA_FLAGS", ""):
    # append rather than setdefault: a pre-set XLA_FLAGS must not
    # silently drop the 8-device mesh this script requires
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " " + _FORCE).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from fault_tolerant_llm_training_tpu.models import Transformer, get_config
    from fault_tolerant_llm_training_tpu.parallel.mesh import (
        make_mesh,
        use_mesh,
    )
    from fault_tolerant_llm_training_tpu.parallel.sharding import (
        batch_pspec,
        param_pspecs,
    )
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import (
        make_optimizer,
        make_train_step,
    )

    # Wider-than-tiny so per-tick compute dominates dispatch overhead.
    cfg_base = get_config("tiny", dim=256, n_layers=4, n_heads=4,
                          n_kv_heads=4, vocab_size=2048,
                          attention_impl="xla", layer_impl="scan",
                          dtype=jnp.float32, param_dtype=jnp.float32)
    seq, reps = 128, 10

    def time_schedule(schedule, microbatches, batch, unroll=False):
        cfg = cfg_base.replace(pp_schedule=schedule, pp_stage_unroll=unroll)
        model = Transformer(cfg)
        opt = make_optimizer(1e-3, warmup_steps=2)
        mesh = make_mesh(dp=1, pp=2, fsdp=2)
        with use_mesh(mesh):
            def init_fn(key):
                p = model.init(key, jnp.zeros((1, seq), jnp.int32))["params"]
                return TrainState(step=jnp.zeros((), jnp.int32), params=p,
                                  opt_state=opt.init(p))

            abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
            specs = param_pspecs(abstract)
            sh = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
            state = jax.jit(init_fn, out_shardings=sh)(jax.random.PRNGKey(0))
            step_fn = jax.jit(make_train_step(model, opt, 1.0,
                                              microbatches=microbatches),
                              out_shardings=(sh, None))
            rng = np.random.default_rng(0)
            toks = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(
                np.int32)
            labels = np.concatenate(
                [toks[:, 1:], np.full((batch, 1), -100, np.int32)], axis=1)
            bsh = NamedSharding(mesh, batch_pspec())
            toks = jax.device_put(toks, bsh)
            labels = jax.device_put(labels, bsh)
            state, m = step_fn(state, toks, labels)  # compile
            jax.block_until_ready(m["packed"])
            t0 = time.perf_counter()
            for _ in range(reps):
                state, m = step_fn(state, toks, labels)
            jax.block_until_ready(m["packed"])
            dt = (time.perf_counter() - t0) / reps
        return dt, float(m["loss"])

    for micro in (8, 16):
        batch = micro * 2  # 2 rows per microbatch
        t_1f1b, l1 = time_schedule("1f1b", micro, batch)
        t_gpipe, l2 = time_schedule("gpipe", micro, batch)
        print(f"M={micro} P=2 batch={batch}: 1f1b {t_1f1b * 1000:.1f} ms "
              f"gpipe {t_gpipe * 1000:.1f} ms "
              f"ratio {t_1f1b / t_gpipe:.2f} "
              f"(analytic (M+2P-1)/(M+P-1) = {(micro + 3) / (micro + 1):.2f}) "
              f"loss {l1:.4f}/{l2:.4f}", flush=True)

    # Stage-body control flow: scan vs static unroll (--pp-stage-unroll).
    # NOTE a CPU-mesh timing cannot see the TPU cross-layer-fusion effect
    # the unroll exists for (the scan trunk's measured 19% there); this
    # only pins that the unrolled body computes the same function at
    # comparable CPU cost.
    t_scan, l1 = time_schedule("1f1b", 8, 16)
    t_unroll, l2 = time_schedule("1f1b", 8, 16, unroll=True)
    print(f"stage body M=8 P=2: scan {t_scan * 1000:.1f} ms "
          f"unroll {t_unroll * 1000:.1f} ms "
          f"ratio {t_unroll / t_scan:.2f} loss {l1:.4f}/{l2:.4f}",
          flush=True)


if __name__ == "__main__":
    main()
