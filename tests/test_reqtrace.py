"""Request-lifecycle tracing (obs/reqtrace.py) and latency observability.

Five layers of evidence:

1. recorder — spans survive a crash with no flush (line-buffered append,
   the flight-recorder discipline), a torn tail from a SIGKILLed writer
   is skipped at read time, and configure() carries pre-configuration
   ring contents into the file;
2. math — TTFT/TPOT derivation on synthetic traces: the done-span
   payload (serving monotonic clock) is preferred, wall-clock span
   deltas are the crashed-host fallback, and the nearest-rank
   percentile helper matches hand-computed ranks;
3. stitch — trace files from three processes (router + two fleet hosts)
   join by trace_id into ONE request whose hosts list spans the
   migration and whose replayed count matches the migration span;
4. metrics — the registry renders summary-style quantile lines for
   EVERY histogram and snapshot() exposes p50/p95/p99; a scheduler run
   over a fake engine populates the TTFT and TPOT histograms and emits
   the full intake->done span trail;
5. lifecycle (slow) — a real serve.py run with --metrics-port: /metrics
   is scraped LIVE mid-run for the latency histograms, and after the
   drain the trace file stitches into per-request TTFT/TPOT matching
   the [LATENCY] audit lines in the transcript.
"""

import json
import os
import re
import signal
import subprocess
import sys
import time
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.obs import reqtrace
from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_recorder():
    reqtrace._RECORDER = reqtrace.SpanRecorder()
    yield
    reqtrace._RECORDER.close()
    reqtrace._RECORDER = reqtrace.SpanRecorder()


# -------------------------------------------------------------- 1. recorder
def test_spans_survive_without_flush_and_torn_tail_is_skipped(tmp_path):
    """The crash contract: every emitted span is on disk BEFORE any
    flush/close (line-buffered append), and a torn final line — the
    mid-write SIGKILL — is skipped by the reader, not fatal."""
    path = str(tmp_path / "trace_h0.jsonl")
    rec = reqtrace.SpanRecorder(path, job="fleet_h0", host="h0")
    tid = reqtrace.mint_trace_id("req0")
    rec.emit(tid, "req0", "intake", prompt_tokens=4)
    rec.emit(tid, "req0", "prefill", dur=0.01, prompt_tokens=4)
    # no flush(), no close(): simulate SIGKILL by abandoning the handle
    spans = reqtrace.read_spans(path)
    assert [s["span"] for s in spans] == ["intake", "prefill"]
    assert all(s["trace_id"] == tid and s["host"] == "h0" for s in spans)
    assert spans[1]["dur"] == pytest.approx(0.01)

    with open(path, "a") as fh:
        fh.write('{"t": 1.0, "trace_id": "' + tid + '", "span": "dec')
    spans = reqtrace.read_spans(path)
    assert [s["span"] for s in spans] == ["intake", "prefill"]
    rec.close()


def test_configure_replays_preconfiguration_ring(tmp_path):
    """Spans emitted through the module singleton before configure()
    (e.g. intake minted before the CLI parsed --trace-log) land in the
    file once a path is configured."""
    tid = reqtrace.mint_trace_id("early")
    reqtrace.emit(tid, "early", "intake", prompt_tokens=2)
    path = str(tmp_path / "trace_router.jsonl")
    reqtrace.configure(path, job="router", host="router")
    reqtrace.emit(tid, "early", "queue", dur=0.5, where="router")
    reqtrace.flush()
    spans = reqtrace.read_spans(path)
    assert [s["span"] for s in spans] == ["intake", "queue"]
    # pre-configuration spans carry their original job/host stamp
    assert spans[1]["job"] == "router"


def test_derive_trace_path_and_mint():
    assert (reqtrace.derive_trace_path("/run/events_router.jsonl")
            == "/run/trace_router.jsonl")
    assert (reqtrace.derive_trace_path("/run/ev.jsonl")
            == "/run/trace_ev.jsonl")
    tid = reqtrace.mint_trace_id("req7")
    assert tid.startswith("req7-") and len(tid) == len("req7-") + 12
    assert reqtrace.mint_trace_id("req7") != tid  # collision-resistant


# ------------------------------------------------------------------ 2. math
def _span(t, tid, span, host="h0", **payload):
    d = {"t": t, "trace_id": tid, "id": "req0", "span": span,
         "job": "test", "host": host}
    d.update(payload)
    return d


def test_derive_prefers_done_payload_and_falls_back_to_wall_clock():
    tid = "req0-abc"
    # fallback path: no done payload — wall-clock deltas
    spans = [_span(100.0, tid, "intake"),
             _span(100.5, tid, "first_token"),
             _span(102.5, tid, "done", tokens=21, reason="length")]
    d = reqtrace.derive(spans)
    assert d["ttft"] == pytest.approx(0.5)
    assert d["tpot"] == pytest.approx(2.0 / 20)  # first token is prefill's
    assert d["tokens"] == 21 and d["done"] and d["reason"] == "length"

    # preferred path: the done span carries the serving clock's own numbers
    spans[-1] = _span(102.5, tid, "done", tokens=21, reason="length",
                      ttft=0.42, tpot=0.033)
    d = reqtrace.derive(spans)
    assert d["ttft"] == pytest.approx(0.42)
    assert d["tpot"] == pytest.approx(0.033)

    # crashed host: no done span at all — UNFINISHED, ttft still derivable
    d = reqtrace.derive(spans[:2])
    assert d["done"] is False and d["tpot"] is None
    assert d["ttft"] == pytest.approx(0.5)
    report = reqtrace.format_report([d])
    assert "UNFINISHED" in report


def test_nearest_rank_percentile():
    vals = [float(v) for v in range(1, 101)]  # 1..100
    assert reqtrace.percentile(vals, 0.5) == 50.0
    assert reqtrace.percentile(vals, 0.95) == 95.0
    assert reqtrace.percentile(vals, 0.99) == 99.0
    assert reqtrace.percentile([7.0], 0.99) == 7.0
    assert reqtrace.percentile([], 0.5) == 0.0


# ---------------------------------------------------------------- 3. stitch
def test_stitch_joins_migrated_trace_across_hosts(tmp_path):
    """A request assigned to h0, killed mid-decode, migrated to h1: the
    three processes' trace files join into ONE record that spans all
    hosts, counts the migration, and carries the replayed-prefix length
    the survivor replayed bit-exactly."""
    tid = "req0-deadbeef0123"
    router = reqtrace.SpanRecorder(str(tmp_path / "trace_router.jsonl"),
                                   job="router", host="router",
                                   clock=iter(np.arange(100.0, 200.0,
                                                        0.25)).__next__)
    h0 = reqtrace.SpanRecorder(str(tmp_path / "trace_h0.jsonl"),
                               job="fleet_h0", host="h0",
                               clock=iter(np.arange(101.0, 200.0,
                                                    0.25)).__next__)
    h1 = reqtrace.SpanRecorder(str(tmp_path / "trace_h1.jsonl"),
                               job="fleet_h1", host="h1",
                               clock=iter(np.arange(110.0, 200.0,
                                                    0.25)).__next__)
    router.emit(tid, "req0", "intake", prompt_tokens=5)
    router.emit(tid, "req0", "queue", dur=0.1, where="router")
    router.emit(tid, "req0", "placement", host="h0", gen=0)
    h0.emit(tid, "req0", "assign", gen=0, committed=0)
    h0.emit(tid, "req0", "prefill", dur=0.02, prompt_tokens=5,
            replayed=0)
    h0.emit(tid, "req0", "first_token", ttft=0.05)
    h0.emit(tid, "req0", "decode_round", tokens=1, mode="token")
    # h0 dies here (no flush needed — line-buffered); router migrates
    router.emit(tid, "req0", "migration", src="h0", dst="h1", gen=1,
                replayed=13)
    h1.emit(tid, "req0", "assign", gen=1, committed=13)
    h1.emit(tid, "req0", "prefill", dur=0.03, prompt_tokens=17,
            replayed=13)
    h1.emit(tid, "req0", "done", reason="length", tokens=48, ttft=0.05,
            tpot=0.002)
    for r in (router, h0, h1):
        r.close()

    reqs = reqtrace.stitch([str(tmp_path)])
    assert len(reqs) == 1
    r = reqs[0]
    assert r["request_id"] == "req0" and r["trace_id"] == tid
    assert r["hosts"] == ["router", "h0", "h1"]
    assert r["migrated"] and r["migrations"] == 1
    assert r["replayed"] == 13
    assert r["done"] and r["tokens"] == 48
    assert r["ttft"] == pytest.approx(0.05)
    assert r["tpot"] == pytest.approx(0.002)
    # the critical path is time-ordered across hosts despite interleaved
    # file reads
    ts = [p["t"] for p in r["critical_path"]]
    assert ts == sorted(ts)
    report = reqtrace.format_report([r], slo_ttft=0.5, slo_tpot=0.05)
    assert "router>h0>h1" in report
    assert "SLO" in report and "1/1 attained (100.0%)" in report


# --------------------------------------------------------------- 4. metrics
def test_registry_histograms_render_quantile_snapshots():
    """EVERY histogram — the pre-existing serving ones included — now
    renders summary-style p50/p95/p99 lines next to its buckets, and
    snapshot() carries the same quantiles (bucket-upper-bound
    resolution)."""
    reg = MetricRegistry()
    h = reg.histogram("ftl_test_latency_seconds", "test",
                      buckets=(0.01, 0.1, 1.0, 10.0))
    for v in [0.005] * 50 + [0.5] * 45 + [5.0] * 5:
        h.observe(v)
    text = reg.render()
    assert 'ftl_test_latency_seconds{quantile="0.5"} 0.01' in text
    assert 'ftl_test_latency_seconds{quantile="0.95"} 1' in text
    assert 'ftl_test_latency_seconds{quantile="0.99"} 10' in text
    snap = reg.snapshot()["ftl_test_latency_seconds"]
    series = snap["series"][""]
    assert series["count"] == 100
    assert series["p50"] == pytest.approx(0.01)
    assert series["p95"] == pytest.approx(1.0)
    assert series["p99"] == pytest.approx(10.0)


class _FakeEngine:
    """Deterministic engine double (test_inference.py idiom)."""

    def __init__(self, slots=2, max_len=64):
        self.slots = slots
        self.max_len = max_len

    def prefill(self, slot, prompt, temperature=0.0, top_p=1.0, seed=0):
        return 100 + slot

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps):
        return np.where(active, np.asarray(tokens) + 1, 0).astype(np.int32)


def test_scheduler_emits_span_trail_and_latency_histograms(tmp_path):
    """A traced request leaves the full intake->queue->prefill->
    first_token->decode_round->done trail, the scheduler's registry
    scrape carries the TTFT and TPOT histograms with quantile lines, and
    derive() on the trace reproduces the Completion's own numbers."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    path = str(tmp_path / "trace_serve.jsonl")
    reqtrace.configure(path, job="serve", host="0")
    reg = MetricRegistry()
    sched = Scheduler(_FakeEngine(slots=2), eos_token_id=None, registry=reg)
    tid = reqtrace.mint_trace_id("r0")
    reqtrace.emit(tid, "r0", "intake", prompt_tokens=2)
    sched.submit(Request(id="r0", prompt=[1, 2], max_new_tokens=6,
                         trace_id=tid))
    sched.submit(Request(id="r1", prompt=[1], max_new_tokens=3))  # untraced
    done = {c.request_id: c for c in sched.run()}
    reqtrace.flush()

    spans = reqtrace.read_spans(path)
    names = [s["span"] for s in spans if s["trace_id"] == tid]
    assert names[0] == "intake" and names[-1] == "done"
    assert {"queue", "prefill", "first_token", "decode_round"} <= set(names)
    assert names.count("decode_round") == 5  # 6 tokens - prefill's first
    # the untraced request emitted NOTHING (tracing is strictly opt-in)
    assert {s["trace_id"] for s in spans} == {tid}

    c = done["r0"]
    assert c.trace_id == tid
    assert c.tpot_seconds > 0
    d = reqtrace.derive([s for s in spans if s["trace_id"] == tid])
    assert d["ttft"] == pytest.approx(c.ttft_seconds)
    assert d["tpot"] == pytest.approx(c.tpot_seconds)
    assert d["tokens"] == 6 and d["decode_rounds"] == 5

    text = reg.render()
    assert "ftl_serve_ttft_seconds_count 2" in text
    assert "ftl_serve_tpot_seconds_count 2" in text
    assert 'ftl_serve_ttft_seconds{quantile="0.99"}' in text
    assert 'ftl_serve_tpot_seconds{quantile="0.5"}' in text
    m = sched.metrics()
    for k in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
              "tpot_p50_ms", "tpot_p95_ms", "tpot_p99_ms"):
        assert m[k] >= 0.0


# ------------------------------------------------------------- 5. lifecycle
def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_COMPILATION_CACHE_DIR"] = "/tmp/jax_test_compile_cache"
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    return env


def _save_tiny_checkpoint(tmp_path, job, step):
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import make_optimizer

    cfg = get_config("tiny", vocab_size=259, seq_len=128)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    state = TrainState(step=jnp.asarray(step, jnp.int32), params=params,
                       opt_state=make_optimizer(1e-4, 1).init(params))
    mngr = CheckpointManager(str(tmp_path), job, enable_async=False,
                             max_to_keep=2)
    mngr.save(step, state, {"next_index": 0}, wait=True)
    mngr.close()


@pytest.mark.slow
def test_serve_e2e_live_metrics_scrape_and_trace_stitch(tmp_path):
    """The whole pipeline against a REAL serve.py process: requests flow
    in through --request-file (one with a caller-minted trace_id), the
    latency histograms are scraped LIVE from /metrics while the process
    serves, and after a SIGUSR1 drain the trace file stitches into
    per-request TTFT/TPOT that match the [LATENCY] audit lines."""
    import socket

    _save_tiny_checkpoint(tmp_path, "trace_e2e", 5)
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    req_file = tmp_path / "requests.jsonl"
    with open(req_file, "w") as fh:
        fh.write(json.dumps({"id": "reqA", "prompt": "alpha bravo",
                             "max_new_tokens": 8,
                             "trace_id": "reqA-cafecafecafe"}) + "\n")
        fh.write(json.dumps({"id": "reqB", "prompt": "charlie delta echo",
                             "max_new_tokens": 8}) + "\n")
    event_log = tmp_path / "events_serve.jsonl"
    argv = [sys.executable, "-m",
            "fault_tolerant_llm_training_tpu.inference.serve",
            "--checkpoint-path", str(tmp_path),
            "--checkpoint-job-id", "trace_e2e", "--model", "tiny",
            "--vocab-size", "259", "--slots", "2", "--max-len", "64",
            "--max-new-tokens", "8", "--no-eos", "--follow",
            "--poll-seconds", "0.2",
            "--request-file", str(req_file),
            "--event-log", str(event_log),
            "--metrics-port", str(port)]
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            env=_env())
    scrape = None
    try:
        deadline = time.time() + 240
        trace_log = tmp_path / "trace_serve.jsonl"  # derived from event-log
        while time.time() < deadline:
            if proc.poll() is not None:
                break
            # both requests done => the histograms are populated; scrape
            # while the process is STILL serving (follow mode idles)
            try:
                spans = (reqtrace.read_spans(str(trace_log))
                         if trace_log.exists() else [])
            except OSError:
                spans = []
            if sum(1 for s in spans if s["span"] == "done") >= 2:
                scrape = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=10).read().decode()
                break
            time.sleep(0.3)
        assert scrape is not None, "requests never completed"
        proc.send_signal(signal.SIGUSR1)
        out, _ = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, out

    # live scrape: latency histograms with quantile snapshots were up
    # while the process served
    assert "ftl_serve_ttft_seconds_count 2" in scrape, scrape
    assert 'ftl_serve_ttft_seconds{quantile="0.99"}' in scrape
    assert "ftl_serve_tpot_seconds_count 2" in scrape
    assert 'ftl_serve_tpot_seconds{quantile="0.5"}' in scrape

    # the drain summary printed one [LATENCY] line per request
    lat = {}
    for m in re.finditer(r"\[LATENCY\] Request (\w+) \| trace ([\w.-]+) \| "
                         r"ttft (\d+) ms \| tpot ([\d.]+) ms \| (\d+) tok",
                         out):
        lat[m.group(1)] = (m.group(2), float(m.group(3)),
                           float(m.group(4)), int(m.group(5)))
    assert set(lat) == {"reqA", "reqB"}, out
    assert lat["reqA"][0] == "reqA-cafecafecafe"  # caller's id propagated

    # the trace file stitches to the same story
    reqs = {r["request_id"]: r for r in reqtrace.stitch([str(trace_log)])}
    assert set(reqs) == {"reqA", "reqB"}
    for rid in ("reqA", "reqB"):
        r = reqs[rid]
        assert r["done"] and r["tokens"] == 8
        assert r["ttft"] is not None and r["tpot"] is not None
        # [LATENCY] prints the same derive()d numbers (ms, rounded)
        assert round(r["ttft"] * 1e3) == lat[rid][1]
        assert r["tpot"] * 1e3 == pytest.approx(lat[rid][2], abs=0.005)
    assert reqs["reqA"]["trace_id"] == "reqA-cafecafecafe"

    # latency_report.py runs end-to-end over the same file
    rep = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "latency_report.py"),
         str(trace_log), "--slo-ttft-ms", "60000"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(), timeout=120)
    assert rep.returncode == 0, rep.stdout
    assert "Request latency report" in rep.stdout
    assert "reqA" in rep.stdout and "reqB" in rep.stdout
    assert "SLO (ttft <= 60000 ms): 2/2 attained (100.0%)" in rep.stdout
