"""Pallas flash attention vs the XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.ops.attention import xla_attention
from fault_tolerant_llm_training_tpu.ops.flash_attention import flash_attention


@pytest.mark.parametrize("s,h,kv,d", [
    (256, 4, 4, 32),
    (512, 4, 2, 32),
    # Full tuned operating point: the fwd (512, 1024) geometry (bk > bq:
    # exactly one masked k-phase per q-tile, n_total - n_full == 1) and
    # the fused backward at the full 512x512 tiles — shapes smaller than
    # the tuned blocks clamp them away and never hit these paths. (The
    # split STREAMING kernels' straddles are covered separately by
    # test_streaming_kernels_match, which forces them on.)
    (2048, 2, 1, 32),
    # d=64 is the PRODUCTION head dim (gpt2-125m and the tuned tile
    # tables) — round 1 tested d=32 only (VERDICT weak spot #6).
    (512, 2, 2, 64),
    (512, 4, 2, 64),   # GQA at d=64
    # Non-divisible S: 1536 degrades the tuned 1024-lane fwd K-tile to
    # 768 via _fit_block; 328 = 8 * 41 < every tuned block, so the whole
    # sequence becomes one full tile (the min(block, s) fallback); 1048 =
    # 8 * 131 has no divisor in [16, 1024] that is a multiple of 8, so
    # _fit_block returns the MINIMAL 8-row tile for every kernel.
    (1536, 2, 1, 64),
    (328, 2, 2, 64),
    (1048, 2, 2, 64),
])
def test_flash_matches_reference(s, h, kv, d):
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    want = xla_attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("s,h,kv,d", [
    (256, 2, 2, 32),   # single q/k block
    (512, 4, 2, 32),   # GQA group-sum + multi-block causal bounds
    (2048, 2, 1, 32),  # tuned dq(512,512)/dkv(512,1024) causal splits
])
def test_flash_gradients_match(s, h, kv, d):
    _check_gradients(s, h, kv, d)


def test_flash_gradients_match_d64():
    _check_gradients(512, 4, 2, 64)


@pytest.mark.parametrize("s,h,kv,d", [(512, 4, 2, 32), (512, 2, 2, 64)])
def test_resident_fused_backward_non_causal(s, h, kv, d):
    """The fused resident backward's non-causal branch (full k-loop
    bounds, no masked tail) — every other resident-family case runs
    causal=True, and the non-causal streaming tests force streaming on,
    so this branch is otherwise uncovered."""
    _check_gradients(s, h, kv, d, causal=False)


@pytest.mark.parametrize("s,h,kv,d", [(512, 4, 2, 32), (1024, 2, 2, 64)])
def test_fused_backward_with_streamed_forward(s, h, kv, d, monkeypatch):
    """When the forward streams but S*D is within RESIDENT_BWD_SD_BUDGET,
    the forward emits the PACKED lse layout and the backward runs the
    fused kernel — its packed entry-transpose path. Forced on at small S
    by lowering only the forward threshold."""
    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa
    monkeypatch.setattr(fa, "STREAM_THRESHOLD", 0)
    assert fa._lse_layout(s, d) == "packed"  # the combination under test
    assert fa._fused_bwd_fits(s, d)
    _check_gradients(s, h, kv, d, batch=2, seed=2)


@pytest.mark.parametrize("long_tiles", [False, True])
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("s,h,kv,d", [(512, 4, 2, 32), (2048, 2, 1, 32),
                                      (512, 2, 2, 64),
                                      # non-128-aligned tiles -> the
                                      # streaming family's LEGACY lse
                                      # layout (_lse_layout False), which
                                      # no other case reaches
                                      (648, 2, 2, 32)])
def test_streaming_kernels_match(s, h, kv, d, causal, long_tiles,
                                 monkeypatch):
    """The long-context streaming kernels (grid-streamed loop operand +
    scratch accumulators; selected above STREAM_THRESHOLD) must agree with
    the XLA reference, causal and non-causal (the non-causal branch has its
    own index maps and bounds). Forced on at small S so CI covers them;
    ``long_tiles`` additionally forces the S>=32k tile set, whose inverted
    ratios (dq block_k > block_q, dkv block_q > block_k) are geometries the
    default tiles never produce."""
    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa
    monkeypatch.setattr(fa, "STREAM_THRESHOLD", 0)
    # force the SPLIT streaming backward too: with only the forward
    # threshold lowered, the fused backward (viable within
    # RESIDENT_BWD_SD_BUDGET) would take over and the streaming dq/dkv
    # kernels would lose their coverage
    monkeypatch.setattr(fa, "RESIDENT_BWD_SD_BUDGET", 0)
    if long_tiles:
        monkeypatch.setattr(fa, "LONG_STREAM_THRESHOLD", 0)
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((2, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    want = xla_attention(q, k, v, causal=causal)
    got = fa.flash_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    g_ref = jax.grad(lambda *a: jnp.sum(xla_attention(*a, causal=causal) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(lambda *a: jnp.sum(fa.flash_attention(*a, causal) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


@pytest.mark.parametrize("s,h,kv,d,family", [
    (512, 4, 4, 64, "resident"),    # MHA, fused resident backward
    (512, 4, 2, 64, "resident"),    # GQA span rope-K scratch reuse
    (512, 4, 2, 64, "streaming"),   # split streaming kernels + rope
    (768, 2, 2, 32, "streaming"),   # non-128-aligned -> legacy lse + rope
])
def test_rope_fused_matches_xla_rope(s, h, kv, d, family, monkeypatch):
    """flash_attention_rope (RoPE inside the kernels via the J-matrix
    rotation, dq/dk emitted through the transpose rotation) must agree
    with apply_rope + flash_attention on raw q/k — forward and gradients,
    across both kernel families and GQA. This is the default TPU rope
    path (cfg.rope_impl='fused', BASELINE.md round 4)."""
    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa
    from fault_tolerant_llm_training_tpu.ops.rope import (
        apply_rope,
        precompute_rope,
    )
    if family == "streaming":
        monkeypatch.setattr(fa, "STREAM_THRESHOLD", 0)
        monkeypatch.setattr(fa, "RESIDENT_BWD_SD_BUDGET", 0)
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.standard_normal((2, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, s, kv, d)), jnp.float32)
    cos, sin = precompute_rope(d, s, 10000.0)
    cos2 = jnp.repeat(cos, 2, axis=-1)
    sin2 = jnp.repeat(sin, 2, axis=-1)

    def f_ref(q, k, v):
        return fa.flash_attention(apply_rope(q, cos, sin),
                                  apply_rope(k, cos, sin), v, True)

    def f_rope(q, k, v):
        qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
        return jnp.transpose(
            fa.flash_attention_rope(qt, kt, vt, cos2, sin2, True),
            (0, 2, 1, 3))

    np.testing.assert_allclose(np.asarray(f_rope(q, k, v)),
                               np.asarray(f_ref(q, k, v)),
                               rtol=2e-4, atol=2e-5)
    g_ref = jax.grad(lambda *a: jnp.sum(f_ref(*a) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_rope = jax.grad(lambda *a: jnp.sum(f_rope(*a) ** 2),
                      argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_rope):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_bhsd_entry_matches_bshd():
    """flash_attention_bhsd (head-major entry, no internal transposes)
    computes the identical function to flash_attention on transposed
    operands — forward and gradients."""
    from fault_tolerant_llm_training_tpu.ops.flash_attention import (
        flash_attention_bhsd,
    )
    rng = np.random.default_rng(4)
    q = jnp.asarray(rng.standard_normal((2, 256, 4, 32)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 256, 2, 32)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 256, 2, 32)), jnp.float32)

    def f_b(q, k, v):
        qt, kt, vt = (jnp.transpose(x, (0, 2, 1, 3)) for x in (q, k, v))
        return jnp.transpose(flash_attention_bhsd(qt, kt, vt, True),
                             (0, 2, 1, 3))

    np.testing.assert_allclose(np.asarray(f_b(q, k, v)),
                               np.asarray(flash_attention(q, k, v, True)),
                               rtol=1e-6, atol=1e-7)
    g_ref = jax.grad(lambda *a: jnp.sum(flash_attention(*a, True) ** 2),
                     argnums=(0, 1, 2))(q, k, v)
    g_b = jax.grad(lambda *a: jnp.sum(f_b(*a) ** 2),
                   argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_b):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)


def _check_gradients(s, h, kv, d, causal=True, batch=1, seed=1):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((batch, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((batch, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((batch, s, kv, d)), jnp.float32)

    g_ref = jax.grad(
        lambda *a: jnp.sum(xla_attention(*a, causal=causal) ** 2),
        argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(lambda *a: jnp.sum(flash_attention(*a, causal) ** 2),
                       argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_flash):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)


def test_rope_fused_dispatch_boundary():
    """rope_impl='fused' scopes itself to the fused-backward S*D budget:
    the streaming kernels re-rope K per tile fetch, measured net-negative
    past S=4096/D=64 on v5e (BASELINE.md round 4)."""
    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa

    assert fa.rope_fused_profitable(2048, 64)
    assert fa.rope_fused_profitable(4096, 64)
    assert not fa.rope_fused_profitable(8192, 64)
    assert fa.rope_fused_profitable(2048, 128)
    assert not fa.rope_fused_profitable(4096, 128)  # D=128 halves the S


def test_lse_layout_dispatch(monkeypatch):
    """The residual layout picker (VERDICT r4 weak #3): resident aligned
    shapes get the zero-padding blocked plane, streaming aligned shapes
    keep the packed row, unaligned shapes fall back to legacy, and the
    FTL_LSE_RESIDENT=legacy escape hatch works."""
    from fault_tolerant_llm_training_tpu.ops import flash_attention as fa

    monkeypatch.delenv("FTL_LSE_RESIDENT", raising=False)
    assert fa._lse_layout(2048, 64) == "blocked"   # resident, 128-aligned
    assert fa._lse_layout(2048, 128) == "blocked"  # exactly at the budget
    assert fa._lse_layout(256, 64) == "blocked"
    assert fa._lse_layout(2000, 64) == "legacy"    # not a 128-multiple
    assert fa._lse_layout(2048, 256) == "legacy"   # fused bwd won't fit:
    # the streaming backward has no blocked row_spec (review r5)
    assert fa._lse_layout(4096, 64) == "packed"    # streaming
    assert fa._lse_layout(65536, 64) == "packed"
    monkeypatch.setenv("FTL_LSE_RESIDENT", "legacy")
    assert fa._lse_layout(2048, 64) == "legacy"    # opt-out knob
    assert fa._lse_layout(4096, 64) == "packed"    # knob is resident-only
