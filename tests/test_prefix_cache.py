"""Prefix caching + copy-on-write (inference/prefix_cache.py + scheduler,
engine, ops integration).

Evidence ladder for content-addressed prefix reuse over the paged pool:

1. keying — chain hashes commit the ENTIRE token prefix per block (shared
   prefixes share keys, any earlier divergence changes every later key,
   partial trailing blocks are never keyed);
2. refcounts — the allocator's per-block refcount matrix: blocks are born
   at 1, incref/free nest correctly, shared blocks survive one holder's
   free, double-free and incref-of-unallocated fail loudly;
3. cache policy — match/acquire/insert against a real allocator, LRU
   eviction of childless refcount-1 nodes only (in-use prefixes are
   protected; chains unwind leaf-first), flush releases everything;
4. ops — a pool block referenced by TWO table rows gathers bitwise
   identically to two private copies of the same bytes (why sharing needs
   no kernel change);
5. scheduler lifecycle — against a fake cache-aware engine: shared
   admission increfs, full-prompt hits copy-on-write exactly once,
   eviction is the release valve under pool pressure (no head-of-line
   deadlock), a drain with shared blocks in flight frees every holder's
   reference exactly once, the post-drain leak guard audits and raises,
   and the /metrics surface carries the ROADMAP-named series;
6. streams — real compiled engines: cache-on streams (partial hits AND a
   COW full-prompt repeat) are BIT-identical to cache-off streams, the
   packed multi-request prefill lane reproduces the sequential lane's
   streams bitwise over a pre-warmed tree (partial hits and a full-hit
   COW repeat riding the same packed wave), and (slow) the speculative
   exact-verify path stays bit-identical to non-speculative decoding
   with shared prefixes in play.

Module scope imports nothing from the package (collect-only guard in
test_spec_decode.py).
"""

import logging

import numpy as np
import pytest

CACHE = "/tmp/jax_test_compile_cache"


# --------------------------------------------------------------- 1. keying
def test_chain_hashes_commit_whole_prefix():
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)

    a = chain_hashes([1, 2, 3, 4, 5, 6, 7, 8], block_size=4)
    assert len(a) == 2
    # shared first block -> shared first key; divergent second block ->
    # divergent second key
    b = chain_hashes([1, 2, 3, 4, 9, 9, 9, 9], block_size=4)
    assert b[0] == a[0] and b[1] != a[1]
    # divergence in block 0 poisons EVERY later key (chain, not per-block)
    c = chain_hashes([9, 2, 3, 4, 5, 6, 7, 8], block_size=4)
    assert c[0] != a[0] and c[1] != a[1]
    # partial trailing block contributes no key; shorter prefix = prefix of
    # the key list
    assert chain_hashes([1, 2, 3, 4, 5, 6], block_size=4) == a[:1]
    assert chain_hashes([1, 2, 3], block_size=4) == []


# ------------------------------------------------------------ 2. refcounts
def test_allocator_refcount_matrix():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)

    a = BlockAllocator(num_blocks=5)
    blocks = a.alloc(2)
    b0, b1 = blocks
    assert a.refcount(b0) == 1 and a.refcount(b1) == 1
    assert a.shared_count == 0
    a.incref([b0])
    assert a.refcount(b0) == 2 and a.shared_count == 1
    a.free([b0])                       # one holder gone, block survives
    assert a.refcount(b0) == 1 and a.used_count == 2
    a.free([b0, b1])                   # last holders: both return to pool
    assert a.refcount(b0) == 0 and a.free_count == a.capacity
    with pytest.raises(ValueError, match="double free"):
        a.free([b1])
    with pytest.raises(ValueError, match="unallocated"):
        a.incref([b1])
    # freed blocks are reusable
    again = a.alloc(4)
    assert again is not None and a.free_count == 0


# --------------------------------------------------------- 3. cache policy
def _cache(num_blocks=10, block_size=4):
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        PrefixCache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)

    alloc = BlockAllocator(num_blocks=num_blocks)
    return alloc, PrefixCache(alloc, block_size)


def test_match_insert_acquire_refcounts():
    alloc, pc = _cache()
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    slot_blocks = alloc.alloc(2)
    assert pc.insert(prompt, slot_blocks) == 2
    # each node holds the cache's own reference on top of the slot's
    assert all(alloc.refcount(b) == 2 for b in slot_blocks)
    # re-insert (e.g. a COW'd private copy) must NOT displace the canonical
    # blocks or take more references
    other = alloc.alloc(2)
    assert pc.insert(prompt, other) == 0
    assert all(alloc.refcount(b) == 2 for b in slot_blocks)
    alloc.free(other)

    hit = pc.match(prompt)
    assert hit.full and hit.tokens == 8 and hit.blocks == list(slot_blocks)
    hit = pc.match(prompt + [9])               # longer prompt: partial hit
    assert not hit.full and hit.tokens == 8
    hit = pc.match([1, 2, 3, 4, 9, 9, 9, 9])   # diverges in block 1
    assert hit.tokens == 4 and hit.blocks == [slot_blocks[0]]
    assert pc.match([9] * 8).blocks == []      # miss

    pc.acquire(hit)                            # the admitted slot's ref
    assert alloc.refcount(slot_blocks[0]) == 3
    alloc.free(hit.blocks)


def test_eviction_lru_childless_refcount1_only():
    alloc, pc = _cache()
    prompt = list(range(12))                   # 3 chained blocks
    blocks = alloc.alloc(3)
    pc.insert(prompt, blocks)
    alloc.free(blocks)                         # slot finished: cache-only
    assert alloc.used_count == 3

    # a live slot still reads the full chain: nothing is evictable
    pc.acquire(pc.match(prompt))
    assert pc.evict(3) == 0 and pc.cached_blocks == 3
    alloc.free(blocks)                         # slot done

    # now the chain unwinds leaf-first, LRU — one block per evict unit
    assert pc.evict(1) == 1
    assert pc.cached_blocks == 2 and alloc.refcount(blocks[2]) == 0
    assert pc.match(prompt).tokens == 8        # surviving prefix still hits
    assert pc.evict(99) == 2 and pc.cached_blocks == 0
    assert alloc.free_count == alloc.capacity
    assert pc.evictions == 3


def test_eviction_prefers_lru_branch():
    alloc, pc = _cache(block_size=4)
    old = [1, 2, 3, 4]
    new = [5, 6, 7, 8]
    b_old, b_new = alloc.alloc(1), alloc.alloc(1)
    pc.insert(old, b_old)
    pc.insert(new, b_new)
    alloc.free(b_old + b_new)
    pc.match(new)                              # touch: new becomes MRU
    assert pc.evict(1) == 1
    assert pc.match(old).blocks == [] and pc.match(new).blocks == b_new


def test_flush_releases_every_cache_reference():
    alloc, pc = _cache()
    blocks = alloc.alloc(2)
    pc.insert(list(range(8)), blocks)
    alloc.free(blocks)
    assert alloc.used_count == 2
    assert pc.flush() == 2
    assert pc.cached_blocks == 0 and alloc.free_count == alloc.capacity
    assert pc.evictions == 0                   # flush is not eviction


# --------------------------------------------------------------- 4. ops
def test_shared_block_gathers_bitwise_like_private_copy():
    """Two table rows pointing at the SAME pool block must gather exactly
    what two rows pointing at duplicated copies of those bytes gather —
    the device-side reason prefix sharing needs no kernel change."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.ops.attention import (
        gather_kv_blocks)

    rng = np.random.default_rng(3)
    K, bs, D = 2, 4, 8
    pool = rng.standard_normal((5, K, bs, D)).astype(np.float32)
    shared = jnp.asarray(pool)
    tables_shared = jnp.asarray([[1, 2], [1, 3]], jnp.int32)   # block 1 shared
    dup = pool.copy()
    dup[4] = pool[1]                                           # private copy
    tables_private = jnp.asarray([[1, 2], [4, 3]], jnp.int32)
    a = np.asarray(gather_kv_blocks(shared, tables_shared))
    b = np.asarray(gather_kv_blocks(jnp.asarray(dup), tables_private))
    assert (a == b).all()


# ------------------------------------------------- 5. scheduler lifecycle
class _FakeCacheEngine:
    """Cache-aware paged-engine double: advertises ``enable_prefix_cache``
    so the scheduler builds a PrefixCache, accepts the ``start_pos`` resume
    offset, and records ``cow_copy`` calls — no XLA anywhere."""

    def __init__(self, slots=4, max_len=64, block_size=8, num_blocks=None,
                 bucket=16):
        self.slots = slots
        self.max_len = max_len
        self.kv_layout = "paged"
        self.block_size = block_size
        self.max_blocks_per_slot = -(-max_len // block_size)
        self.num_blocks = num_blocks or slots * self.max_blocks_per_slot + 1
        self.bucket = bucket
        self.enable_prefix_cache = True
        self.cow_calls = []
        self.prefilled_positions = 0           # compute the cache absorbed

    def cow_copy(self, src, dst):
        self.cow_calls.append((src, dst))

    def prefill(self, slot, token_ids, block_row=None, temperature=0.0,
                top_p=1.0, seed=0, stop_check=None, on_chunk=None,
                start_pos=0):
        n = len(token_ids)
        start = start_pos
        self.prefilled_positions += n - start
        while start < n:
            start += min(self.bucket, n - start)
            if on_chunk is not None:
                on_chunk()
            if start < n and stop_check is not None and stop_check():
                return None
        return 1

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps,
                    block_tables=None):
        assert block_tables is not None
        return np.where(active, tokens + 1, 0).astype(np.int32)


def test_shared_admission_points_tables_at_same_blocks():
    """Second request sharing a 16-token (2-block) prefix reuses the first
    request's pool blocks: tables overlap, allocator reports them shared,
    prefill resumes past the hit, and the drained pool passes the leak
    audit with only cache-held blocks outstanding."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeCacheEngine(slots=2, max_len=32, block_size=8)
    sched = Scheduler(eng, eos_token_id=None)
    shared = list(range(100, 116))
    sched.submit(Request(id="a", prompt=shared + [1, 2, 3],
                         max_new_tokens=4))
    sched.submit(Request(id="b", prompt=shared + [7, 8, 9],
                         max_new_tokens=4))
    sched.step()                               # both admitted
    assert (sched.block_tables[0, :2] == sched.block_tables[1, :2]).all()
    assert sched.block_tables[0, 2] != sched.block_tables[1, 2]
    assert sched.allocator.shared_count == 2   # cache ref + two slot refs
    # request b prefilled only its 3-token tail (19 - 16 hit positions)
    assert eng.prefilled_positions == 19 + 3
    sched.run()
    m = sched.metrics()
    assert m["prefix_hits"] == 1 and m["prefix_hit_tokens"] == 16
    assert m["prefix_hit_rate"] == pytest.approx(16 / 38)
    assert m["prefix_cow_copies"] == 0 and not eng.cow_calls
    # drain contract: every outstanding block is cache-held, audit clean
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    assert sched.audit_block_leaks(strict=True) == []
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity


def test_full_prompt_hit_copies_on_write_once():
    """An identical block-aligned prompt is a FULL hit: prefill must resume
    at prompt_len - 1 to recover the last position's logits, which writes
    inside the final shared block — so admission COWs it into a private
    block, remaps the table, and never re-inserts the copy over the
    canonical cached block."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeCacheEngine(slots=2, max_len=32, block_size=8)
    sched = Scheduler(eng, eos_token_id=None)
    prompt = list(range(200, 216))             # exactly 2 blocks
    sched.submit(Request(id="a", prompt=list(prompt), max_new_tokens=4))
    sched.submit(Request(id="b", prompt=list(prompt), max_new_tokens=4))
    sched.step()
    assert len(eng.cow_calls) == 1
    src, dst = eng.cow_calls[0]
    # b shares block 0, owns a private copy of block 1
    assert sched.block_tables[0, 0] == sched.block_tables[1, 0]
    assert sched.block_tables[1, 1] == dst != sched.block_tables[0, 1] == src
    # b prefilled exactly ONE position (the last prompt token)
    assert eng.prefilled_positions == 16 + 1
    sched.run()
    m = sched.metrics()
    assert m["prefix_cow_copies"] == 1
    assert m["prefix_hit_tokens"] == 15        # resumed at prompt_len - 1
    # the canonical cached block is still the original, not the COW copy
    assert sched.prefix_cache.match(prompt).blocks[-1] == src
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity


def test_eviction_valve_prevents_head_of_line_deadlock():
    """Pool sized so cached prefixes from finished requests must be evicted
    before the next distinct request fits: without the valve the queue
    head would wait forever behind cache-held blocks."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    # 5 usable blocks; each request needs 3 (16 prompt + 4 gen @ bs 8) and
    # leaves 2 cached — the third admission must evict to fit
    eng = _FakeCacheEngine(slots=1, max_len=24, block_size=8, num_blocks=6)
    sched = Scheduler(eng, eos_token_id=None)
    for i in range(3):
        sched.submit(Request(id=f"r{i}",
                             prompt=list(range(100 * i, 100 * i + 16)),
                             max_new_tokens=4))
    sched.run()
    assert len(sched.completed) == 3
    m = sched.metrics()
    assert m["prefix_evictions"] > 0
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity


def test_drain_mid_decode_frees_shared_blocks_exactly_once():
    """Chaos-style drain with SHARED blocks in flight: two slots reading
    the same prefix blocks finish under drain, each releasing its own
    reference through the one uniform free path — the refcounted pool must
    come back to cache-only with no double-free and a clean audit."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeCacheEngine(slots=2, max_len=64, block_size=8, bucket=16)
    fired = {"on": False}
    sched = Scheduler(eng, eos_token_id=None, stop_check=lambda: fired["on"])
    shared = list(range(300, 316))
    sched.submit(Request(id="a", prompt=shared + [1], max_new_tokens=8))
    sched.submit(Request(id="b", prompt=shared + [2], max_new_tokens=8))
    sched.step()                               # both admitted, sharing
    assert sched.allocator.shared_count == 2
    fired["on"] = True                         # drain lands mid-decode
    # c's 40-token prompt spans multiple chunks past its 16-token hit, so
    # the drain probe fires between its prefill chunks and rolls it back
    sched.submit(Request(id="c", prompt=shared + list(range(24)),
                         max_new_tokens=8))
    while sched.pending():
        sched.step()
    assert [r.id for r in sched.unserved()] == ["c"]
    assert sorted(c.request_id for c in sched.completed) == ["a", "b"]
    # a and b each freed their references exactly once: only the cache's
    # remain, no block is shared, audit is clean
    assert sched.allocator.shared_count == 0
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    assert sched.audit_block_leaks(strict=True) == []
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity


def test_drain_mid_prefill_rolls_back_hit_references():
    """Drain firing INSIDE a chunked prefill that resumed from a hit: the
    admission rollback frees fresh AND acquired shared references exactly
    once — the shared blocks survive under the cache's reference and the
    request is reported unserved."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeCacheEngine(slots=2, max_len=64, block_size=8, bucket=16)
    fired = {"on": False}
    sched = Scheduler(eng, eos_token_id=None, stop_check=lambda: fired["on"])
    shared = list(range(400, 416))
    sched.submit(Request(id="warm", prompt=shared + [1], max_new_tokens=2))
    sched.run()                                # seeds the cache, completes
    sched.admission_open = True                # fresh serving phase
    fired["on"] = True                         # signal already pending
    sched.submit(Request(id="long", prompt=shared + list(range(40)),
                         max_new_tokens=4))
    while sched.pending():
        sched.step()
    assert [r.id for r in sched.unserved()] == ["long"]
    assert sched.allocator.shared_count == 0
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    assert sched.audit_block_leaks(strict=True) == []


def test_leak_guard_audits_once_and_raises_strict(caplog):
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeCacheEngine(slots=2, max_len=32, block_size=8)
    sched = Scheduler(eng, eos_token_id=None)
    sched.submit(Request(id="a", prompt=list(range(12)), max_new_tokens=2))
    sched.run()                                # clean: no audit, no raise
    assert not sched._leak_audited

    sched.allocator.alloc(1)                   # simulate a leaked block
    with caplog.at_level(logging.INFO):
        leaks = sched.audit_block_leaks(strict=False)
    assert len(leaks) == 1 and leaks[0].startswith("[KV LEAK] target pool")
    assert any("[KV LEAK]" in r.message for r in caplog.records)
    caplog.clear()
    with caplog.at_level(logging.INFO):
        with pytest.raises(RuntimeError, match="KV block leak"):
            sched.audit_block_leaks(strict=True)
    # audited exactly once — the latch stops repeat emissions
    assert not any("[KV LEAK]" in r.message for r in caplog.records)


def test_prefix_metrics_surface():
    """The ROADMAP-named series exist on the registry and move: gauge
    ``kv_prefix_hit_rate`` (unprefixed, like the chaos series), gauge
    ``kv_blocks_shared``, counter ``prefix_evictions_total``."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    reg = MetricRegistry()
    eng = _FakeCacheEngine(slots=2, max_len=32, block_size=8)
    sched = Scheduler(eng, eos_token_id=None, registry=reg)
    shared = list(range(16))
    sched.submit(Request(id="a", prompt=shared + [1], max_new_tokens=2))
    sched.submit(Request(id="b", prompt=shared + [2], max_new_tokens=2))
    sched.run()
    text = reg.render()
    values = {}
    for ln in text.splitlines():
        if ln and not ln.startswith("#") and " " in ln:
            name, val = ln.rsplit(" ", 1)
            values[name] = val
    assert float(values["kv_prefix_hit_rate"]) > 0
    assert "kv_blocks_shared" in values
    # the counter has no samples until the first eviction; the family
    # itself must already be declared on the scrape surface
    assert "# TYPE prefix_evictions_total counter" in text


# ------------------------------------------------------------- 6. streams
@pytest.fixture(scope="module")
def compiled_engine():
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64, layer_impl="loop")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, cfg.seq_len), jnp.int32)
    )["params"]
    eng = InferenceEngine(cfg, params, slots=2, max_len=48,
                          prefill_buckets=(16,), kv_layout="paged",
                          kv_block_size=16)
    return cfg, params, eng


def _run_streams(engine, reqs, cache_on):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    engine.enable_prefix_cache = cache_on
    engine.reset()
    sched = Scheduler(engine, eos_token_id=None)
    for r in reqs:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def test_cached_streams_bitmatch_uncached(compiled_engine):
    """Compiled end-to-end: greedy AND sampled requests sharing a 16-token
    (one block) prefix — plus an exact repeat that forces a full-hit COW —
    produce BIT-identical token streams with the cache on and off. Shared
    blocks are the same device bytes and resumed chunks run the identical
    bucket programs, so this must hold bitwise, not approximately."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    cfg, _, eng = compiled_engine
    rng = np.random.default_rng(7)
    shared = rng.integers(3, cfg.vocab_size, size=16).tolist()
    tails = [rng.integers(3, cfg.vocab_size, size=n).tolist()
             for n in (5, 9, 0)]
    reqs = [
        Request(id="greedy-a", prompt=shared + tails[0], max_new_tokens=8),
        Request(id="sampled", prompt=shared + tails[1], max_new_tokens=8,
                temperature=0.8, top_p=0.9, seed=3),
        Request(id="repeat", prompt=list(shared), max_new_tokens=8),
        Request(id="repeat2", prompt=list(shared), max_new_tokens=8),
    ]
    on_sched, on_out = _run_streams(eng, reqs, cache_on=True)
    m = on_sched.metrics()
    assert m["prefix_hits"] >= 3 and m["prefix_hit_tokens"] > 0
    assert m["prefix_cow_copies"] >= 1          # the full-prompt repeats
    assert on_sched.allocator.used_count == on_sched.prefix_cache.cached_blocks

    off_sched, off_out = _run_streams(eng, reqs, cache_on=False)
    assert off_sched.prefix_cache is None
    assert on_out == off_out
    assert len(on_out) == 4
    eng.enable_prefix_cache = True              # restore for other tests


def test_packed_prefill_streams_bitmatch_sequential_with_hits(compiled_engine):
    """Packed admission allocates before any same-wave insert, so hits come
    from a PRE-WARMED tree: warm one shared-prefix request to completion,
    then serve a wave with two partial hits and a full-hit COW repeat
    through the packed lane — streams must be BITWISE identical to the
    sequential lane over the same warmed cache (hit-resumed rows enter the
    packed program at their own start offsets, same chunk shapes)."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    cfg, params, eng = compiled_engine
    packed = InferenceEngine(cfg, params, slots=2, max_len=48,
                             prefill_buckets=(16,), kv_layout="paged",
                             kv_block_size=16, prefill_batch=2)
    rng = np.random.default_rng(13)
    shared = rng.integers(3, cfg.vocab_size, size=16).tolist()
    tails = [rng.integers(3, cfg.vocab_size, size=n).tolist() for n in (5, 9)]
    warm = Request(id="warm", prompt=shared + [4], max_new_tokens=2)
    wave = [
        Request(id="hit-a", prompt=shared + tails[0], max_new_tokens=6),
        Request(id="hit-b", prompt=shared + tails[1], max_new_tokens=6,
                temperature=0.8, top_p=0.9, seed=5),
        Request(id="repeat", prompt=list(shared), max_new_tokens=6),
    ]

    def run(engine, pb):
        engine.enable_prefix_cache = True
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None, prefill_batch=pb)
        sched.submit(warm)
        sched.run()                            # seeds the tree, completes
        for r in wave:
            sched.submit(r)
        sched.run()
        return sched, {c.request_id: c.tokens for c in sched.completed}

    seq_sched, seq_out = run(eng, 1)
    pak_sched, pak_out = run(packed, 2)
    assert pak_out == seq_out
    assert len(pak_out) == 4
    ms, mp = seq_sched.metrics(), pak_sched.metrics()
    assert mp["prefill_packed_rounds"] > 0
    assert mp["prefill_chunks"] == ms["prefill_chunks"]   # same chunking
    assert mp["prefix_hits"] == ms["prefix_hits"] >= 3
    assert mp["prefix_cow_copies"] >= 1        # the full-prompt repeat
    assert (pak_sched.allocator.used_count
            == pak_sched.prefix_cache.cached_blocks)
    pak_sched.prefix_cache.flush()
    assert pak_sched.allocator.free_count == pak_sched.allocator.capacity


@pytest.mark.slow
def test_spec_exact_shared_prefix_stream_bitmatches(compiled_engine):
    """Speculative decoding (exact verify) with prefix caching on: shared
    and repeated prompts still produce the non-speculative engine's exact
    greedy streams — the dual-pool admission (draft pool opts out of
    caching) and the COW path compose without breaking the PR-4 bitwise
    guarantee."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg, params, base = compiled_engine
    rng = np.random.default_rng(11)
    shared = rng.integers(3, cfg.vocab_size, size=16).tolist()
    reqs = [
        Request(id="a", prompt=shared + [5, 6, 7], max_new_tokens=6),
        Request(id="b", prompt=shared + [8, 9], max_new_tokens=6),
        Request(id="c", prompt=list(shared), max_new_tokens=6),
    ]
    _, want = _run_streams(base, reqs, cache_on=True)

    draft_params = Transformer(cfg).init(
        jax.random.PRNGKey(9), jnp.zeros((1, cfg.seq_len), jnp.int32)
    )["params"]
    spec = InferenceEngine(cfg, params, slots=2, max_len=48,
                           prefill_buckets=(16,), kv_layout="paged",
                           kv_block_size=16, draft_cfg=cfg,
                           draft_params=draft_params, spec_k=2,
                           spec_verify_impl="exact")
    spec_sched, got = _run_streams(spec, reqs, cache_on=True)
    assert got == want
    m = spec_sched.metrics()
    assert m["spec_rounds"] > 0
    assert m["prefix_hits"] >= 2 and m["prefix_cow_copies"] >= 1
    # draft pool opted out: fully free after drain, no cache interaction
    assert (spec_sched.draft_allocator.free_count
            == spec_sched.draft_allocator.capacity)
    assert (spec_sched.allocator.used_count
            == spec_sched.prefix_cache.cached_blocks)
    spec_sched.prefix_cache.flush()
    assert (spec_sched.allocator.free_count
            == spec_sched.allocator.capacity)
