"""Per-host sharded data loading (data/loader.py HostShardedDataLoader).

VERDICT r4 weak #2: the replicated loader tokenizes the full global batch
on every host — O(hosts) redundant work on the path SURVEY §7.3 #5 names as
the pod bottleneck. These tests pin the contract:

- the staged global batch is BIT-IDENTICAL to the replicated path's
  (virtual 8-device meshes, incl. sequence sharding and shuffle);
- the checkpointed position stays global/host-count-agnostic;
- on a real 2-process cluster the hosts tokenize DISJOINT row sets whose
  union is the full batch, and the training trajectory matches the
  replicated run line-for-line.
"""

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding

from fault_tolerant_llm_training_tpu.data.collator import CollatorForCLM
from fault_tolerant_llm_training_tpu.data.loader import (
    DataLoader,
    HostShardedDataLoader,
)
from fault_tolerant_llm_training_tpu.data.parquet import ParquetDataset
from fault_tolerant_llm_training_tpu.data.tokenizer import load_tokenizer
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.sharding import batch_pspec

SEQ = 32
BATCH = 8


def _loaders(parquet, mesh, shuffle_seed=None, steps=6):
    tok = load_tokenizer("byte")
    coll = CollatorForCLM(SEQ, tok.pad_token_id)
    mk = lambda: ParquetDataset(parquet, tok, SEQ, BATCH * steps,
                                shuffle_seed=shuffle_seed)
    sharding = NamedSharding(mesh, batch_pspec())
    return (DataLoader(mk(), BATCH, coll),
            HostShardedDataLoader(mk(), BATCH, coll, sharding, SEQ),
            sharding)


@pytest.mark.parametrize("mesh_kwargs", [
    dict(dp=4, fsdp=2),
    dict(dp=2, fsdp=2, sp=2),  # sequence sharding: per-device S slices
])
def test_staged_batches_bit_identical_to_replicated(tiny_parquet, mesh_kwargs):
    mesh = make_mesh(**mesh_kwargs)
    with use_mesh(mesh):
        rep, shd, sharding = _loaders(tiny_parquet, mesh)
        # single process: the host owns every row
        assert shd.host_rows.tolist() == list(range(BATCH))
        rep.resume()
        for _ in range(3):
            ri, rl = next(rep)
            si, sl = next(shd)
            gi, gl = shd.stage_global(si, sl)
            gri = jax.device_put(ri, sharding)
            grl = jax.device_put(rl, sharding)
            np.testing.assert_array_equal(np.asarray(gi), np.asarray(gri))
            np.testing.assert_array_equal(np.asarray(gl), np.asarray(grl))
        assert rep.get_state() == shd.get_state()  # global position agrees


def test_sharded_shuffle_and_resume_state(tiny_parquet):
    """Shuffle rides dataset.__getitem__ unchanged; a state saved by the
    sharded loader restores into the replicated one (host-count-agnostic)."""
    mesh = make_mesh(dp=8)
    with use_mesh(mesh):
        rep, shd, _ = _loaders(tiny_parquet, mesh, shuffle_seed=3)
        rep.resume()
        next(shd)
        state = shd.get_state()
        next(rep), next(rep)
        rep.set_state(state)  # rewind replicated to the sharded position
        ri, rl = next(rep)
        si, sl = next(shd)
        np.testing.assert_array_equal(ri, si)
        np.testing.assert_array_equal(rl, sl)


def test_host_subset_rows_and_counter(tiny_parquet):
    """Simulate one host of a 2-host pod by restricting the device filter:
    the loader materializes exactly the subset's rows (half the batch)."""
    mesh = make_mesh(dp=8)
    with use_mesh(mesh):
        rep, shd, sharding = _loaders(tiny_parquet, mesh)
        # carve out the devices owning rows 0..3 as a fake "host"
        keep = [e for e in shd._dev_slices if (e[1][0].start or 0) < 4]
        shd._dev_slices = keep
        rows = set()
        for _, (idx_b, _) in keep:
            rows.update(range(idx_b.start or 0, idx_b.stop))
        shd.host_rows = np.asarray(sorted(rows), dtype=np.int64)
        rep.resume()
        ri, rl = next(rep)
        si, sl = next(shd)
        assert si.shape == (4, SEQ)
        np.testing.assert_array_equal(si, ri[shd.host_rows])
        np.testing.assert_array_equal(sl, rl[shd.host_rows])
        assert shd.rows_tokenized == 4
        # position still advanced by the FULL global batch
        assert shd.get_state()["next_index"] == BATCH


_WORKER = """
import os, sys
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize(sys.argv[2], num_processes=2, process_id=pid)
import numpy as np
from jax.sharding import NamedSharding
from fault_tolerant_llm_training_tpu.data.collator import CollatorForCLM
from fault_tolerant_llm_training_tpu.data.loader import (
    DataLoader, HostShardedDataLoader)
from fault_tolerant_llm_training_tpu.data.parquet import ParquetDataset
from fault_tolerant_llm_training_tpu.data.tokenizer import load_tokenizer
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.sharding import batch_pspec
SEQ, BATCH = 32, 8
tok = load_tokenizer('byte')
coll = CollatorForCLM(SEQ, tok.pad_token_id)
mesh = make_mesh(dp=2)  # one device per process
with use_mesh(mesh):
    sharding = NamedSharding(mesh, batch_pspec())
    ds = ParquetDataset(sys.argv[3], tok, SEQ, BATCH * 4)
    shd = HostShardedDataLoader(ds, BATCH, coll, sharding, SEQ)
    # replicated oracle over a fresh dataset at the same position
    rep = DataLoader(ParquetDataset(sys.argv[3], tok, SEQ, BATCH * 4),
                     BATCH, coll)
    rep.resume()
    for _ in range(2):
        ri, rl = next(rep)
        si, sl = next(shd)
        gi, gl = shd.stage_global(si, sl)
        # every addressable shard must equal the oracle's slice
        for s in gi.addressable_shards:
            np.testing.assert_array_equal(np.asarray(s.data), ri[s.index])
        for s in gl.addressable_shards:
            np.testing.assert_array_equal(np.asarray(s.data), rl[s.index])
    print(f'rows={sorted(int(r) for r in shd.host_rows)} '
          f'tokenized={shd.rows_tokenized} state={shd.get_state()["next_index"]}',
          flush=True)
"""


def test_two_process_disjoint_tokenization(tmp_path, tiny_parquet):
    """Real 2-process cluster: the hosts' row sets are disjoint, their
    union is the whole batch, each tokenized only its half, and every
    device shard carries exactly the replicated oracle's rows."""
    import os
    import re
    import socket
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            coord = f"localhost:{s.getsockname()[1]}"
        env = {**os.environ, "PYTHONPATH": repo_root}
        env.pop("XLA_FLAGS", None)  # one device per process
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), coord, tiny_parquet],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        try:
            outs = [p.communicate(timeout=120)[0] for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            outs = [p.communicate()[0] for p in procs]
            continue
        if all(p.returncode == 0 for p in procs):
            break
    assert all(p.returncode == 0 for p in procs), outs
    rows = []
    for o in outs:
        m = re.search(r"rows=\[([\d, ]+)\] tokenized=(\d+) state=(\d+)", o)
        assert m, o
        rows.append([int(x) for x in m.group(1).split(",")])
        assert int(m.group(2)) == 2 * len(rows[-1])  # 2 batches, half each
        assert int(m.group(3)) == 2 * BATCH  # global position, both hosts
    assert not set(rows[0]) & set(rows[1]), rows
    assert sorted(rows[0] + rows[1]) == list(range(BATCH)), rows
