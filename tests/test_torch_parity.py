"""Cross-framework parity: this framework's model vs an independent PyTorch
implementation of the reference architecture (ref: model.py:9-380).

The strongest "same model" evidence we can produce without the reference's
hardware: a torch CPU model built from the architectural spec — RMSNorm with
fp32 internal math (model.py:24-48), complex-arithmetic RoPE (model.py:51-126,
the reference's own formulation, which doubles as the oracle for our real
cos/sin form), GQA via repeat_kv (model.py:129-138), SwiGLU with the
hidden-dim rounding (model.py:243-247), pre-norm blocks and an untied head
(model.py:310-380) — is loaded with the *identical* weights as the Flax model
and must agree on logits, the sum-CE/valid-token loss (train.py:94,101-102),
and gradients.
"""

import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")  # parity oracle; skip cleanly without it
import torch.nn.functional as F  # noqa: E402

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.training.step import cross_entropy_loss

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, attention_impl="xla")


def _rope_complex(x: torch.Tensor, theta: float) -> torch.Tensor:
    """The reference's complex-arithmetic RoPE (model.py:67-71,100-126)."""
    b, s, h, d = x.shape
    freqs = 1.0 / (theta ** (torch.arange(0, d, 2, dtype=torch.float32) / d))
    angles = torch.outer(torch.arange(s, dtype=torch.float32), freqs)
    cis = torch.polar(torch.ones_like(angles), angles)  # (S, D/2) complex
    xc = torch.view_as_complex(x.float().reshape(b, s, h, d // 2, 2))
    out = torch.view_as_real(xc * cis[None, :, None, :])
    return out.reshape(b, s, h, d).type_as(x)


def _rms_norm(x: torch.Tensor, scale: torch.Tensor, eps: float) -> torch.Tensor:
    xf = x.float()
    normed = xf * torch.rsqrt(xf.pow(2).mean(-1, keepdim=True) + eps)
    return normed.type_as(x) * scale


def _torch_forward(p, tokens: torch.Tensor, cfg) -> torch.Tensor:
    """Reference-architecture forward entirely from the flax param dict ``p``
    (kernels transposed to torch's (out, in) orientation on the fly)."""
    dh = cfg.head_dim
    n_rep = cfg.n_heads // cfg.kv_heads
    x = p["tok_embeddings"]["embedding"][tokens]  # (B, S, D)
    b, s, _ = x.shape
    for i in range(cfg.n_layers):
        lp = p[f"layers_{i}"]
        h = _rms_norm(x, lp["attention_norm"]["scale"], cfg.norm_eps)
        q = (h @ lp["attention"]["wq"]["kernel"]).reshape(b, s, cfg.n_heads, dh)
        k = (h @ lp["attention"]["wk"]["kernel"]).reshape(b, s, cfg.kv_heads, dh)
        v = (h @ lp["attention"]["wv"]["kernel"]).reshape(b, s, cfg.kv_heads, dh)
        q = _rope_complex(q, cfg.rope_theta)
        k = _rope_complex(k, cfg.rope_theta)
        # repeat_kv (model.py:129-138): expand KV heads to the query count
        k = k.repeat_interleave(n_rep, dim=2)
        v = v.repeat_interleave(n_rep, dim=2)
        q, k, v = (t.transpose(1, 2) for t in (q, k, v))  # (B, H, S, dh)
        scores = (q @ k.transpose(-1, -2)).float() / math.sqrt(dh)
        causal = torch.triu(torch.full((s, s), float("-inf")), diagonal=1)
        probs = torch.softmax(scores + causal, dim=-1).type_as(q)
        att = (probs @ v).transpose(1, 2).reshape(b, s, cfg.n_heads * dh)
        x = x + att @ lp["attention"]["wo"]["kernel"]
        h = _rms_norm(x, lp["ffn_norm"]["scale"], cfg.norm_eps)
        gate = F.silu(h @ lp["feed_forward"]["w1"]["kernel"])
        up = h @ lp["feed_forward"]["w3"]["kernel"]
        x = x + (gate * up) @ lp["feed_forward"]["w2"]["kernel"]
    x = _rms_norm(x, p["norm"]["scale"], cfg.norm_eps)
    return x @ p["output"]["kernel"]  # untied head (model.py:350-352)


def _to_torch_tree(params, requires_grad=False):
    return jax.tree_util.tree_map(
        lambda a: torch.tensor(np.asarray(a), requires_grad=requires_grad),
        params)


def _torch_loss(logits: torch.Tensor, labels: torch.Tensor,
                vocab_size: int) -> torch.Tensor:
    """ref train.py:94,101-102: sum-CE over (B*S, V) / valid-token count."""
    return F.cross_entropy(
        logits.float().view(-1, vocab_size), labels.view(-1),
        ignore_index=-100, reduction="sum") / (labels != -100).sum()


@pytest.fixture(scope="module")
def setup():
    cfg = get_config("tiny", **FP32)
    model = Transformer(cfg)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, cfg.seq_len)).astype(np.int32)
    labels = np.concatenate(
        [tokens[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
    params = model.init(jax.random.PRNGKey(7),
                        jnp.asarray(tokens))["params"]
    return cfg, model, params, tokens, labels


def test_logits_match_torch_reference(setup):
    cfg, model, params, tokens, labels = setup
    jax_logits = np.asarray(model.apply({"params": params},
                                        jnp.asarray(tokens)))
    with torch.no_grad():
        t_logits = _torch_forward(_to_torch_tree(params),
                                  torch.tensor(tokens, dtype=torch.long),
                                  cfg).numpy()
    np.testing.assert_allclose(jax_logits, t_logits, rtol=2e-4, atol=2e-4)


def test_loss_matches_torch_reference(setup):
    cfg, model, params, tokens, labels = setup
    jax_loss, n_valid = cross_entropy_loss(
        model.apply({"params": params}, jnp.asarray(tokens)),
        jnp.asarray(labels))
    with torch.no_grad():
        t_logits = _torch_forward(_to_torch_tree(params),
                                  torch.tensor(tokens, dtype=torch.long), cfg)
        t_labels = torch.tensor(labels, dtype=torch.long)
        t_loss = _torch_loss(t_logits, t_labels, cfg.vocab_size)
    assert int(n_valid) == int((t_labels != -100).sum())
    np.testing.assert_allclose(float(jax_loss), float(t_loss),
                               rtol=1e-5, atol=1e-6)


def test_train_step_matches_torch_reference(setup):
    """One full update — grad clip (coefficient semantics of utils.py:58-63),
    AdamW with torch defaults (train.py:68), LambdaLR warmup factor
    (utils.py:43-53) — must move the weights identically in both frameworks."""
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import (
        make_optimizer,
        make_train_step,
    )

    cfg, model, params, tokens, labels = setup
    lr, warmup, max_norm = 1e-3, 4, 1.0

    opt = make_optimizer(lr, warmup_steps=warmup)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    step_fn = make_train_step(model, opt, max_norm)
    new_state, _ = step_fn(state, jnp.asarray(tokens), jnp.asarray(labels))

    t_params = _to_torch_tree(params, requires_grad=True)
    leaves = [t for t in jax.tree_util.tree_leaves(t_params)]
    optimizer = torch.optim.AdamW(leaves, lr=lr, betas=(0.9, 0.999),
                                  eps=1e-8, weight_decay=0.01)
    sched = torch.optim.lr_scheduler.LambdaLR(
        optimizer, lambda s: min((s + 1) / (warmup + 1), 1.0))
    t_labels = torch.tensor(labels, dtype=torch.long)
    t_logits = _torch_forward(t_params,
                              torch.tensor(tokens, dtype=torch.long), cfg)
    _torch_loss(t_logits, t_labels, cfg.vocab_size).backward()
    torch.nn.utils.clip_grad_norm_(leaves, max_norm)  # ref: utils.py:58-63
    optimizer.step()
    sched.step()

    got = jax.tree_util.tree_map(np.asarray, new_state.params)
    want = jax.tree_util.tree_map(lambda t: t.detach().numpy(), t_params)
    flat_got = dict(jax.tree_util.tree_flatten_with_path(got)[0])
    flat_want = dict(jax.tree_util.tree_flatten_with_path(want)[0])
    assert flat_got.keys() == flat_want.keys()
    for path in flat_got:
        np.testing.assert_allclose(
            flat_got[path], flat_want[path], rtol=2e-4, atol=2e-6,
            err_msg=jax.tree_util.keystr(path))


def test_gradients_match_torch_reference(setup):
    cfg, model, params, tokens, labels = setup

    def jax_loss_fn(p):
        logits = model.apply({"params": p}, jnp.asarray(tokens))
        return cross_entropy_loss(logits, jnp.asarray(labels))[0]

    jax_grads = jax.grad(jax_loss_fn)(params)

    t_params = _to_torch_tree(params, requires_grad=True)
    t_labels = torch.tensor(labels, dtype=torch.long)
    t_logits = _torch_forward(t_params,
                              torch.tensor(tokens, dtype=torch.long), cfg)
    _torch_loss(t_logits, t_labels, cfg.vocab_size).backward()

    checks = [
        (("tok_embeddings", "embedding"),
         t_params["tok_embeddings"]["embedding"]),
        (("layers_0", "attention", "wq", "kernel"),
         t_params["layers_0"]["attention"]["wq"]["kernel"]),
        (("layers_1", "feed_forward", "w2", "kernel"),
         t_params["layers_1"]["feed_forward"]["w2"]["kernel"]),
        (("norm", "scale"), t_params["norm"]["scale"]),
        (("output", "kernel"), t_params["output"]["kernel"]),
    ]
    for path, t_leaf in checks:
        jg = jax_grads
        for key in path:
            jg = jg[key]
        np.testing.assert_allclose(
            np.asarray(jg), t_leaf.grad.numpy(), rtol=5e-4, atol=5e-5,
            err_msg="/".join(path))
