"""Training-step semantics: loss definition, determinism, loss decreases."""

import jax
import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    cross_entropy_loss,
    make_optimizer,
    make_train_step,
)


def test_cross_entropy_matches_manual():
    # sum-CE in fp32 over valid tokens / count (ref: train.py:94,101-102)
    rng = np.random.default_rng(0)
    logits = rng.standard_normal((2, 4, 7)).astype(np.float32)
    labels = np.array([[1, 2, -100, 3], [0, -100, -100, 6]], np.int32)
    loss, n = cross_entropy_loss(jnp.asarray(logits), jnp.asarray(labels))
    assert int(n) == 5
    total = 0.0
    for b in range(2):
        for s in range(4):
            if labels[b, s] == -100:
                continue
            row = logits[b, s] - logits[b, s].max()
            p = np.exp(row) / np.exp(row).sum()
            total += -np.log(p[labels[b, s]])
    np.testing.assert_allclose(float(loss), total / 5, rtol=1e-5)


def test_chunked_ce_matches_dense():
    """The vocab-blocked CE (ops/cross_entropy.py) is an exact
    reassociation of the dense fp32 logsumexp: values and gradients must
    agree to fp32 tolerance, including a non-divisible vocab tail and
    bf16 logits (the production dtype)."""
    rng = np.random.default_rng(7)
    b, s, v = 2, 8, 1000 + 7  # tail of 7 at block 256
    logits = rng.standard_normal((b, s, v)).astype(np.float32) * 3.0
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[0, 3] = -100
    labels[1, 0] = -100
    logits, labels = jnp.asarray(logits), jnp.asarray(labels)

    def dense(lg):
        return cross_entropy_loss(lg, labels, ce_block=0)[0]

    def chunked(lg):
        return cross_entropy_loss(lg, labels, ce_block=256)[0]

    ld, gd = jax.value_and_grad(dense)(logits)
    lc, gc = jax.value_and_grad(chunked)(logits)
    np.testing.assert_allclose(float(lc), float(ld), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                               rtol=1e-5, atol=1e-7)

    # bf16 logits: dlogits come back in bf16 through both paths
    lb = logits.astype(jnp.bfloat16)
    ld16, gd16 = jax.value_and_grad(dense)(lb)
    lc16, gc16 = jax.value_and_grad(chunked)(lb)
    assert gc16.dtype == jnp.bfloat16
    np.testing.assert_allclose(float(lc16), float(ld16), rtol=1e-3)
    np.testing.assert_allclose(np.asarray(gc16, np.float32),
                               np.asarray(gd16, np.float32),
                               rtol=5e-2, atol=1e-4)


def test_fused_head_ce_matches_head_then_ce():
    """The fused head+CE (ops/fused_ce.py) equals computing logits then
    the dense CE — values AND gradients wrt both the hidden states and
    the head weight — including a non-divisible vocab tail and bf16."""
    from fault_tolerant_llm_training_tpu.ops.fused_ce import fused_head_xent
    from fault_tolerant_llm_training_tpu.training.step import masked_mean_nll

    rng = np.random.default_rng(13)
    b, s, d, v = 2, 8, 16, 1000 + 7
    hidden = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[0, 2] = -100
    labels = jnp.asarray(labels)
    safe = jnp.where(labels == -100, 0, labels)

    def dense(h, w):
        return cross_entropy_loss(h @ w, labels, ce_block=0)[0]

    def fused(h, w):
        return masked_mean_nll(fused_head_xent(h, w, safe, 256), labels)[0]

    ld, (gh_d, gw_d) = jax.value_and_grad(dense, argnums=(0, 1))(hidden, w)
    lf, (gh_f, gw_f) = jax.value_and_grad(fused, argnums=(0, 1))(hidden, w)
    np.testing.assert_allclose(float(lf), float(ld), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_d),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_d),
                               rtol=1e-5, atol=1e-6)

    hb, wb = hidden.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    lf16, (gh16, gw16) = jax.value_and_grad(fused, argnums=(0, 1))(hb, wb)
    assert gh16.dtype == jnp.bfloat16 and gw16.dtype == jnp.bfloat16
    np.testing.assert_allclose(float(lf16), float(ld), rtol=2e-2)


def test_fused_head_ce_engages_in_model_loss(monkeypatch):
    """model_loss auto-routes large unsharded vocabs through the fused
    head+CE; the result matches the logits path bit-for-bit-ish."""
    import fault_tolerant_llm_training_tpu.ops.cross_entropy as ce_mod
    import fault_tolerant_llm_training_tpu.ops.fused_ce as fce_mod
    from fault_tolerant_llm_training_tpu.models import Transformer, get_config
    from fault_tolerant_llm_training_tpu.training.step import model_loss

    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.default_rng(17)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    labels = jnp.concatenate(
        [toks[:, 1:], jnp.full((2, 1), -100, jnp.int32)], axis=1)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    base, n0 = model_loss(model, params, toks, labels)  # logits path
    monkeypatch.setattr(ce_mod, "AUTO_THRESHOLD", 1)    # vocab 512 >= 1
    monkeypatch.setattr(fce_mod, "AUTO_MIN_BYTES", 0)   # tiny shapes count
    # The fused path actually engaged: its custom VJP is in the jaxpr
    # (the losses alone are identical by design, so they can't pin this).
    jaxpr = str(jax.make_jaxpr(
        lambda p, t, l: model_loss(model, p, t, l))(params, toks, labels))
    assert "fused_head_xent" in jaxpr
    fused, n1 = jax.jit(
        lambda p, t, l: model_loss(model, p, t, l))(params, toks, labels)
    assert int(n0) == int(n1)
    np.testing.assert_allclose(float(fused), float(base), rtol=1e-6)


def test_sharded_fused_head_ce_matches_dense(eight_devices):
    """The vocab-sharded fused head+CE (ops/fused_ce.py
    sharded_fused_head_xent, VERDICT r2 next-step #2): on a tp mesh each
    device blocks over its local V/shard slice and the online stats fold
    across shards with (B, S) psums. Values AND gradients (wrt hidden and
    the head weight) must match the dense unsharded form, including a
    vocab whose slice is smaller than the block and bf16 inputs."""
    from fault_tolerant_llm_training_tpu.ops.fused_ce import (
        sharded_fused_head_xent,
    )
    from fault_tolerant_llm_training_tpu.parallel.mesh import (
        make_mesh,
        use_mesh,
    )
    from fault_tolerant_llm_training_tpu.training.step import masked_mean_nll

    rng = np.random.default_rng(23)
    b, s, d, v = 2, 8, 16, 1024
    hidden = jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((d, v)) * 0.1, jnp.float32)
    labels = rng.integers(0, v, (b, s)).astype(np.int32)
    labels[0, 2] = -100
    labels = jnp.asarray(labels)
    safe = jnp.where(labels == -100, 0, labels)

    def dense(h, w):
        return cross_entropy_loss(h @ w, labels, ce_block=0)[0]

    ld, (gh_d, gw_d) = jax.value_and_grad(dense, argnums=(0, 1))(hidden, w)

    for mesh_kw in (dict(dp=2, tp=2), dict(dp=1, pp=2, tp=2)):
        mesh = make_mesh(**mesh_kw)
        with use_mesh(mesh):
            def sharded(h, w):
                return masked_mean_nll(
                    sharded_fused_head_xent(h, w, safe, 256), labels)[0]

            lf, (gh_f, gw_f) = jax.jit(jax.value_and_grad(
                sharded, argnums=(0, 1)))(hidden, w)
            np.testing.assert_allclose(float(lf), float(ld), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(gh_f), np.asarray(gh_d),
                                       rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(np.asarray(gw_f), np.asarray(gw_d),
                                       rtol=1e-5, atol=1e-6)

    # bf16 inputs keep their dtype on the grads (custom VJP contract)
    mesh = make_mesh(dp=2, tp=2)
    with use_mesh(mesh):
        def sharded(h, w):
            return masked_mean_nll(
                sharded_fused_head_xent(h, w, safe, 256), labels)[0]

        lf16, (gh16, gw16) = jax.jit(jax.value_and_grad(
            sharded, argnums=(0, 1)))(hidden.astype(jnp.bfloat16),
                                      w.astype(jnp.bfloat16))
        assert gh16.dtype == jnp.bfloat16 and gw16.dtype == jnp.bfloat16
        np.testing.assert_allclose(float(lf16), float(ld), rtol=2e-2)


def test_sharded_fused_head_ce_engages_in_model_loss(eight_devices,
                                                     monkeypatch):
    """model_loss auto-routes a large SHARDED vocab through the sharded
    fused head+CE on a tp mesh (previously it dispatched away to the
    dense per-shard fp32 form — VERDICT r2 weak #5); the loss matches the
    logits path."""
    import fault_tolerant_llm_training_tpu.ops.cross_entropy as ce_mod
    import fault_tolerant_llm_training_tpu.ops.fused_ce as fce_mod
    from fault_tolerant_llm_training_tpu.models import Transformer, get_config
    from fault_tolerant_llm_training_tpu.parallel.mesh import (
        make_mesh,
        use_mesh,
    )
    from fault_tolerant_llm_training_tpu.training.step import model_loss

    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    model = Transformer(cfg)
    rng = np.random.default_rng(29)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    labels = jnp.concatenate(
        [toks[:, 1:], jnp.full((4, 1), -100, jnp.int32)], axis=1)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]

    mesh = make_mesh(dp=2, tp=2)
    with use_mesh(mesh):
        base, n0 = jax.jit(
            lambda p, t, l: model_loss(model, p, t, l))(params, toks, labels)
        monkeypatch.setattr(ce_mod, "AUTO_THRESHOLD", 1)
        monkeypatch.setattr(fce_mod, "AUTO_MIN_BYTES", 0)
        jaxpr = str(jax.make_jaxpr(
            lambda p, t, l: model_loss(model, p, t, l))(params, toks, labels))
        assert "_sharded_fx" in jaxpr
        fused, n1 = jax.jit(
            lambda p, t, l: model_loss(model, p, t, l))(params, toks, labels)
        assert int(n0) == int(n1)
        np.testing.assert_allclose(float(fused), float(base), rtol=1e-5)


def test_chunked_ce_auto_dispatch_threshold():
    """ce_block=None auto-selects the blocked path only at large vocab —
    pinned by checking the jaxpr for the custom VJP primitive name."""
    from fault_tolerant_llm_training_tpu.ops.cross_entropy import (
        AUTO_THRESHOLD,
    )
    small = jnp.zeros((1, 4, 128), jnp.float32)
    labels = jnp.zeros((1, 4), jnp.int32)
    jaxpr_small = str(jax.make_jaxpr(
        lambda lg: cross_entropy_loss(lg, labels)[0])(small))
    assert "custom_vjp" not in jaxpr_small
    big = jnp.zeros((1, 4, AUTO_THRESHOLD), jnp.float32)
    jaxpr_big = str(jax.make_jaxpr(
        lambda lg: cross_entropy_loss(lg, labels)[0])(big))
    assert "custom_vjp" in jaxpr_big


def _run_steps(n_steps, seed=0):
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    step_fn = jax.jit(make_train_step(model, opt, grad_max_norm=1.0))
    rng = np.random.default_rng(123)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (n_steps, 2, 32)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), tokens[0])["params"]
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    losses = []
    for i in range(n_steps):
        labels = jnp.concatenate(
            [tokens[i, :, 1:], jnp.full((2, 1), -100, jnp.int32)], axis=1)
        state, metrics = step_fn(state, tokens[i], labels)
        losses.append(float(metrics["loss"]))
    return losses, state


def test_determinism_same_seed_same_losses():
    l1, _ = _run_steps(5)
    l2, _ = _run_steps(5)
    assert l1 == l2  # bit-exact


def test_loss_decreases_and_step_counts():
    losses, state = _run_steps(30)
    assert losses[-1] < losses[0]
    assert int(state.step) == 30
    assert all(np.isfinite(losses))


def test_grad_accum_matches_single_pass():
    """grad_accum=2 reproduces the one-pass step exactly: token-weighted
    slice accumulation equals the big-batch sum-CE/valid-count gradient
    (uneven -100 masking across slices exercises the weighting)."""
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    rng = np.random.default_rng(5)
    tokens = np.asarray(rng.integers(0, cfg.vocab_size, (4, 32)), np.int32)
    labels = np.concatenate(
        [tokens[:, 1:], np.full((4, 1), -100, np.int32)], axis=1)
    labels[0, :20] = -100  # slice 0 carries far fewer valid tokens
    labels[3, 5:9] = -100
    params = model.init(jax.random.PRNGKey(0),
                        jnp.asarray(tokens[:1]))["params"]

    def run(accum):
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt.init(params))
        step = jax.jit(make_train_step(model, opt, 1.0, grad_accum=accum))
        new_state, m = step(state, jnp.asarray(tokens), jnp.asarray(labels))
        return new_state, np.asarray(m["packed"]), int(m["num_tokens"])

    s1, m1, n1 = run(1)
    s2, m2, n2 = run(2)
    assert n1 == n2
    # fp32 reduction-order noise only: the one-pass CE sums every token in
    # one reduce, the accumulated form sums per-slice then combines
    np.testing.assert_allclose(m2, m1, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(s1.params),
                    jax.tree_util.tree_leaves(s2.params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=5e-5, atol=5e-6)


def _mixed_precision_state(param_dtype, n_steps=8, seed=0):
    """Train the tiny model with bf16 compute and ``param_dtype`` params
    (the --master-weights switch: loop.py sets param_dtype=fp32 while
    cfg.dtype stays bf16)."""
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.bfloat16,
                     param_dtype=param_dtype)
    model = Transformer(cfg)
    opt = make_optimizer(1e-2, warmup_steps=2)
    step_fn = jax.jit(make_train_step(model, opt, grad_max_norm=1.0))
    rng = np.random.default_rng(99)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (n_steps, 2, 32)),
                         jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), tokens[0])["params"]
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    losses = []
    for i in range(n_steps):
        labels = jnp.concatenate(
            [tokens[i, :, 1:], jnp.full((2, 1), -100, jnp.int32)], axis=1)
        state, metrics = step_fn(state, tokens[i], labels)
        losses.append(float(metrics["loss"]))
    return cfg, model, state, losses


def test_master_weights_fp32_dtypes_and_compute():
    """--master-weights fp32 (VERDICT r3 weak #4): params AND AdamW
    moments stay fp32 across steps while the forward computes in bf16
    (flax casts the fp32 master copy to cfg.dtype at use)."""
    cfg, model, state, _ = _mixed_precision_state(jnp.float32)
    for leaf in jax.tree_util.tree_leaves(state.params):
        assert leaf.dtype == jnp.float32
    # AdamW first/second moments inherit the master dtype
    import optax
    mu_nu = [state.opt_state[0].mu, state.opt_state[0].nu]
    for tree in mu_nu:
        for leaf in jax.tree_util.tree_leaves(tree):
            assert leaf.dtype == jnp.float32
    # compute is bf16: block outputs (captured intermediates) carry
    # cfg.dtype, not the param dtype
    toks = jnp.zeros((1, 32), jnp.int32)
    _, inter = model.apply({"params": state.params}, toks,
                           capture_intermediates=True)
    block_outs = inter["intermediates"]["layers_0"]["__call__"]
    assert block_outs[0].dtype == jnp.bfloat16


def test_master_weights_fp32_changes_trajectory():
    """The flag must DO something: with identical data/seed, the fp32-
    master trajectory departs from pure bf16 (update rounding differs),
    while staying finite and close."""
    _, _, state32, losses32 = _mixed_precision_state(jnp.float32)
    _, _, state16, losses16 = _mixed_precision_state(jnp.bfloat16)
    assert all(np.isfinite(losses32)) and all(np.isfinite(losses16))
    assert losses32 != losses16
    # same-config reproducibility guard (the difference above is the
    # dtype, not nondeterminism)
    _, _, _, again32 = _mixed_precision_state(jnp.float32)
    assert losses32 == again32


def test_master_weights_fp32_checkpoint_roundtrip(tmp_path):
    """A mixed-dtype TrainState (fp32 params/moments, bf16-compute
    config) round-trips through the checkpoint manager with dtypes
    preserved leaf-for-leaf and values bit-exact."""
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )
    cfg, model, state, _ = _mixed_precision_state(jnp.float32, n_steps=2)
    mngr = CheckpointManager(str(tmp_path), "mwtest")
    mngr.save(int(state.step), state, {"kind": "map", "next_index": 4,
                                       "shuffle_seed": None}, wait=True)
    restored_state, data_state, _ = mngr.restore(state)
    for a, b in zip(jax.tree_util.tree_leaves(state.params),
                    jax.tree_util.tree_leaves(restored_state.params)):
        assert a.dtype == b.dtype == jnp.float32
        np.testing.assert_array_equal(np.asarray(a, np.float32),
                                      np.asarray(b, np.float32))
    for a, b in zip(jax.tree_util.tree_leaves(state.opt_state),
                    jax.tree_util.tree_leaves(restored_state.opt_state)):
        assert a.dtype == b.dtype
    assert data_state["next_index"] == 4


def test_master_weights_fp32_converter_import():
    """state_from_torch_ckpt under --master-weights fp32: a reference
    (bf16) checkpoint imports with fp32 master params and fp32 moments."""
    from fault_tolerant_llm_training_tpu.checkpoint.convert import (
        state_from_torch_ckpt,
        state_to_torch_ckpt,
    )
    cfg, model, state, _ = _mixed_precision_state(jnp.float32, n_steps=2)
    opt = make_optimizer(1e-2, warmup_steps=2)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, learning_rate=1e-2,
                               warmup_steps=2)
    back = state_from_torch_ckpt(ckpt, model, opt, jnp.float32)
    for leaf in jax.tree_util.tree_leaves(back.params):
        assert leaf.dtype == jnp.float32
    assert int(back.step) == int(state.step)


def test_device_budget_dispatch(monkeypatch):
    """Budgets derive from the device instead of hardcoding v5e
    (VERDICT r3 weak #5): on a 16 GB part the bench-scale 131k-vocab
    logits footprint engages the fused head+CE; on a faked 95 GB part
    the same footprint materializes logits (12.9 GB < half of 95 GB) —
    pinned by recomputing the exact decision model_loss makes."""
    import fault_tolerant_llm_training_tpu.ops.fused_ce as fce_mod
    from fault_tolerant_llm_training_tpu.utils import device as dev_mod

    assert fce_mod.AUTO_MIN_BYTES is None  # derivation is the default
    # bs 8, seq 2048, vocab 131072: logits + cotangent ~ 12.9 GB
    logits_bytes = 8 * 2048 * 131072 * 6

    # auto_min_bytes resolves the helper lazily from utils.device at call
    # time, so utils.device is the one effective patch point
    monkeypatch.setattr(dev_mod, "device_hbm_bytes",
                        lambda default=0: 16 * 2**30)
    assert logits_bytes > fce_mod.auto_min_bytes()  # v5e: fused engages

    monkeypatch.setattr(dev_mod, "device_hbm_bytes",
                        lambda default=0: 95 * 2**30)
    assert logits_bytes < fce_mod.auto_min_bytes()  # v5p: logits fit

    # CPU/no-stats backends fall back to the v5e calibration value
    monkeypatch.undo()
    dev_mod.device_hbm_bytes.cache_clear()
    assert fce_mod.auto_min_bytes() > 0


def test_scoped_vmem_budget_scales(monkeypatch):
    """RESIDENT_BWD_SD_BUDGET scales linearly with the scoped-VMEM limit
    (FTL_SCOPED_VMEM_KIB, matching --xla_tpu_scoped_vmem_limit_kib): at
    the 16 MiB XLA default it is the calibrated 4096*64; doubling the
    limit doubles the S*D bound."""
    import importlib
    import os

    import fault_tolerant_llm_training_tpu.ops.flash_attention as fa

    assert fa.RESIDENT_BWD_SD_BUDGET == 4096 * 64  # default env
    monkeypatch.setenv("FTL_SCOPED_VMEM_KIB", str(2 * 16384))
    mod = importlib.reload(fa)
    try:
        assert mod.RESIDENT_BWD_SD_BUDGET == 2 * 4096 * 64
        assert mod._fused_bwd_fits(8192, 64)
        assert not mod._fused_bwd_fits(16384, 64)
    finally:
        monkeypatch.delenv("FTL_SCOPED_VMEM_KIB")
        importlib.reload(fa)
        assert fa.RESIDENT_BWD_SD_BUDGET == 4096 * 64
