"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 build note)
so DP/FSDP/TP/SP paths are testable with no TPU. Must run before jax imports.
"""

import os

# The axon remote-TPU plugin (registered by sitecustomize when
# PALLAS_AXON_POOL_IPS is set) dials the TPU tunnel from *every* python
# process, even under JAX_PLATFORMS=cpu. Tests must be hermetic: run pytest
# as `env -u PALLAS_AXON_POOL_IPS python -m pytest ...`; the pop below keeps
# subprocesses spawned by tests clean either way.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np
import pytest

import jax

# Numerics tests compare against fp64/fp32 oracles; JAX's *default* matmul
# precision truncates to bf16-class even on CPU in this build.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    assert jax.device_count() >= 8
    return jax.devices()[:8]


@pytest.fixture()
def tiny_parquet(tmp_path):
    """Synthetic 'text'-column parquet file (the reference's data contract:
    utils.py:118 'a parquet file containing a text column with documents')."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    docs = []
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
             "hotel", "india", "juliet"]
    for i in range(64):
        n = int(rng.integers(5, 120))
        docs.append(" ".join(rng.choice(words, size=n).tolist()))
    path = tmp_path / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), path)
    return str(path)
