"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 build note)
so DP/FSDP/TP/SP paths are testable with no TPU.

A sitecustomize hook may import jax at interpreter startup (before conftest
runs), so setting JAX_PLATFORMS via os.environ here is too late — the env
value has already latched. XLA_FLAGS, however, is read at *backend init*
(first device access), and ``jax.config.update`` can still retarget the
platform as long as no backend has been initialized. Both are done below;
subprocesses spawned by tests inherit the env vars and stay hermetic too.
"""

import os
import subprocess
import sys

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# Virtual devices serialize on few cores: a collective legitimately waits
# while its peers' compute grinds through the same core(s), and XLA's
# in-process stuck detector would abort the run (seen on the flagship-8B
# test: minutes of single-core RNG/GEMM between peers). Shared with the
# subprocess harness in test_fault_tolerance.py.
_COLLECTIVE_TIMEOUT_FLAGS = (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=3600"
    " --xla_cpu_collective_call_terminate_timeout_seconds=7200")


def _probe_collective_timeout_flags() -> str:
    """XLA treats unknown XLA_FLAGS as a CHECK-failure at backend init
    (parse_flags_from_env.cc aborts the process, not a warning), and the
    collective stuck-detector flags above only exist in newer jaxlibs. On an
    older jaxlib the first test to touch a device would kill the *entire*
    pytest session. Probe once per jaxlib version in a throwaway subprocess
    and drop the flags when unsupported."""
    import jaxlib

    cache = f"/tmp/_ftl_xla_collective_flag_probe_{jaxlib.__version__}"
    try:
        with open(cache) as f:
            return _COLLECTIVE_TIMEOUT_FLAGS if f.read() == "1" else ""
    except OSError:
        pass
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               XLA_FLAGS=_COLLECTIVE_TIMEOUT_FLAGS)
    ok = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        timeout=120).returncode == 0
    try:
        with open(cache, "w") as f:
            f.write("1" if ok else "0")
    except OSError:
        pass
    return _COLLECTIVE_TIMEOUT_FLAGS if ok else ""


COLLECTIVE_TIMEOUT_FLAGS = _probe_collective_timeout_flags()

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if COLLECTIVE_TIMEOUT_FLAGS and "xla_cpu_collective_call_warn_stuck" not in flags:
    flags += " " + COLLECTIVE_TIMEOUT_FLAGS
os.environ["XLA_FLAGS"] = flags

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

# Numerics tests compare against fp64/fp32 oracles; JAX's *default* matmul
# precision truncates to bf16-class even on CPU in this build.
jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: multi-second CPU tests (multi-round speculative streams, "
        "big layout matrices); tier-1 runs -m 'not slow'")
    config.addinivalue_line(
        "markers",
        "chaos: fault-injection scenarios (tests/test_chaos.py); the heavy "
        "end-to-end ones are also slow-marked")


_MP_PROBE_WORKER = """
import os, sys
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
os.environ.pop('XLA_FLAGS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
import jax
jax.config.update('jax_platforms', 'cpu')
jax.distributed.initialize(sys.argv[2], num_processes=2,
                           process_id=int(sys.argv[1]))
from jax.experimental import multihost_utils
multihost_utils.sync_global_devices('probe')  # cross-process XLA collective
print('MP_OK', flush=True)
os._exit(0)  # skip jax.distributed.shutdown: its barrier can stall atexit
"""


def _probe_multiprocess_cpu_jit() -> bool:
    """The multi-host pod tests run real 2-process jax.distributed clusters
    on the CPU backend. Some jaxlibs cannot execute multiprocess XLA
    computations on CPU at all — one process raises 'Multiprocess
    computations aren't implemented on the CPU backend' while its peer
    WEDGES inside the collective (and then the shutdown barrier burns its
    full 5-minute timeout). Each pod test would then eat its entire
    subprocess timeout x3 retries, starving the rest of the suite. Probe
    the exact failing op (a cross-process sync) once per jaxlib version in
    throwaway subprocesses and let the pod tests skip when it can't run."""
    import socket
    import time

    import jaxlib

    cache = f"/tmp/_ftl_multiprocess_cpu_probe_{jaxlib.__version__}"
    try:
        with open(cache) as f:
            return f.read() == "1"
    except OSError:
        pass
    with socket.socket() as s:
        s.bind(("localhost", 0))
        coord = f"localhost:{s.getsockname()[1]}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "PALLAS_AXON_POOL_IPS")}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-c", _MP_PROBE_WORKER, str(i), coord],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env)
        for i in range(2)]
    deadline = time.monotonic() + 90
    ok = True
    for p in procs:
        try:
            rc = p.wait(timeout=max(0.1, deadline - time.monotonic()))
            ok = ok and rc == 0
        except subprocess.TimeoutExpired:
            ok = False
    for p in procs:
        if p.poll() is None:
            p.kill()  # a wedged collective ignores SIGTERM
            p.wait()
    try:
        with open(cache, "w") as f:
            f.write("1" if ok else "0")
    except OSError:
        pass
    return ok


@pytest.fixture(scope="session")
def multiprocess_cpu_jit():
    """Pod tests that jit XLA computations across a real 2-process CPU
    cluster declare this fixture; it skips them on jaxlibs whose CPU
    backend cannot run multiprocess programs (see the probe above)."""
    if not _probe_multiprocess_cpu_jit():
        pytest.skip("this jaxlib's CPU backend cannot execute multiprocess "
                    "XLA computations (capability probe failed)")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    assert jax.device_count() >= 8
    return jax.devices()[:8]


@pytest.fixture()
def tiny_parquet(tmp_path):
    """Synthetic 'text'-column parquet file (the reference's data contract:
    utils.py:118 'a parquet file containing a text column with documents')."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    docs = []
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
             "hotel", "india", "juliet"]
    for i in range(64):
        n = int(rng.integers(5, 120))
        docs.append(" ".join(rng.choice(words, size=n).tolist()))
    path = tmp_path / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), path)
    return str(path)
