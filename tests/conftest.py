"""Test harness: force an 8-device virtual CPU mesh (SURVEY.md §4 build note)
so DP/FSDP/TP/SP paths are testable with no TPU.

A sitecustomize hook may import jax at interpreter startup (before conftest
runs), so setting JAX_PLATFORMS via os.environ here is too late — the env
value has already latched. XLA_FLAGS, however, is read at *backend init*
(first device access), and ``jax.config.update`` can still retarget the
platform as long as no backend has been initialized. Both are done below;
subprocesses spawned by tests inherit the env vars and stay hermetic too.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
# Virtual devices serialize on few cores: a collective legitimately waits
# while its peers' compute grinds through the same core(s), and XLA's
# in-process stuck detector would abort the run (seen on the flagship-8B
# test: minutes of single-core RNG/GEMM between peers). Shared with the
# subprocess harness in test_fault_tolerance.py.
COLLECTIVE_TIMEOUT_FLAGS = (
    "--xla_cpu_collective_call_warn_stuck_timeout_seconds=3600"
    " --xla_cpu_collective_call_terminate_timeout_seconds=7200")

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    flags = (flags + " --xla_force_host_platform_device_count=8").strip()
if "xla_cpu_collective_call_warn_stuck" not in flags:
    flags += " " + COLLECTIVE_TIMEOUT_FLAGS
os.environ["XLA_FLAGS"] = flags

import numpy as np
import pytest

import jax

jax.config.update("jax_platforms", "cpu")

# Numerics tests compare against fp64/fp32 oracles; JAX's *default* matmul
# precision truncates to bf16-class even on CPU in this build.
jax.config.update("jax_default_matmul_precision", "highest")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    assert jax.device_count() >= 8
    return jax.devices()[:8]


@pytest.fixture()
def tiny_parquet(tmp_path):
    """Synthetic 'text'-column parquet file (the reference's data contract:
    utils.py:118 'a parquet file containing a text column with documents')."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    docs = []
    words = ["alpha", "bravo", "charlie", "delta", "echo", "foxtrot", "golf",
             "hotel", "india", "juliet"]
    for i in range(64):
        n = int(rng.integers(5, 120))
        docs.append(" ".join(rng.choice(words, size=n).tolist()))
    path = tmp_path / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), path)
    return str(path)
