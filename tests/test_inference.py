"""Inference & serving subsystem (fault_tolerant_llm_training_tpu/inference/).

Three layers of evidence, mirroring how the training side is verified:

1. numerics — cached (prefill + stepwise decode) logits BIT-MATCH the
   uncached teacher-forcing forward, the property that makes serving a
   trained checkpoint trustworthy at all;
2. mechanics — slot-based continuous batching (admit/evict/drain) pinned
   against a fake engine, plus greedy/sampled determinism across engine
   rebuilds (the serving analogue of bit-exact training resume);
3. lifecycle — the real CLI chain: train a tiny model, restore the
   checkpoint in serve.py, run concurrent requests, SIGTERM mid-generation
   and assert the drain audit trail on exit 0 (the same grep-the-.out-file
   discipline as the trainer's exit handler).
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
CACHE = "/tmp/jax_test_compile_cache"


# --------------------------------------------------------------- 1. numerics
def _tiny_cfg(layer_impl="loop", vocab=64, seq_len=64):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl=layer_impl)


def _init_params(cfg, seed=0):
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    model = Transformer(cfg)
    tokens = jnp.zeros((1, cfg.seq_len), jnp.int32)
    return model, model.init(jax.random.PRNGKey(seed), tokens)["params"]


def test_cached_decode_bitmatches_uncached_forward():
    """Prefill writes the prompt's KV and decode extends it one token at a
    time; at EVERY position the cached logits must equal the teacher-forcing
    forward bitwise — same projections, same RoPE table values, same
    fp32-softmax attention order (ops/attention.py cached_attention)."""
    import jax
    import jax.numpy as jnp

    cfg = _tiny_cfg("loop")
    model, params = _init_params(cfg)
    rng = np.random.default_rng(0)
    T = 24
    toks = jnp.asarray(rng.integers(3, cfg.vocab_size, size=(1, T)),
                       jnp.int32)
    full = np.asarray(model.apply({"params": params}, toks))  # (1, T, V)

    from fault_tolerant_llm_training_tpu.inference.kv_cache import init_cache

    cache = init_cache(cfg, slots=1, max_len=32)
    P = 16  # prompt prefix; the rest decodes stepwise
    cached, (k, v) = model.apply(
        {"params": params}, toks[:, :P], cache.k, cache.v,
        jnp.zeros((1,), jnp.int32), method="forward_with_cache")
    np.testing.assert_array_equal(np.asarray(cached), full[:, :P])
    offset = jnp.full((1,), P, jnp.int32)
    for t in range(P, T):
        step, (k, v) = model.apply(
            {"params": params}, toks[:, t:t + 1], k, v, offset,
            method="forward_with_cache")
        np.testing.assert_array_equal(np.asarray(step)[:, 0], full[:, t])
        offset = offset + 1


@pytest.mark.parametrize("layer_impl", ["loop", "scan"])
def test_engine_greedy_matches_uncached_autoregression(layer_impl):
    """The engine end-to-end (AOT prefill bucket + donated decode, scan
    checkpoints converted to the loop trunk) reproduces the greedy
    continuation computed by repeatedly running the full uncached forward."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import InferenceEngine

    cfg = _tiny_cfg(layer_impl)
    model, params = _init_params(cfg)
    rng = np.random.default_rng(1)
    prompt = rng.integers(3, cfg.vocab_size, size=9).tolist()
    N = 6

    # reference: argmax-extend with the plain training forward
    seq = list(prompt)
    ref = []
    for _ in range(N):
        logits = model.apply({"params": params},
                             jnp.asarray([seq], jnp.int32))
        tok = int(np.argmax(np.asarray(logits)[0, -1]))
        ref.append(tok)
        seq.append(tok)

    # default layout is the paged block pool: the raw engine API needs the
    # slot's block-table row (the Scheduler's allocator provides it in
    # production; tests/test_paged_kv.py covers the allocator itself)
    engine = InferenceEngine(cfg, params, slots=2, max_len=32)
    row = np.arange(1, engine.max_blocks_per_slot + 1, dtype=np.int32)
    tables = np.zeros((2, engine.max_blocks_per_slot), np.int32)
    tables[0] = row
    got = [engine.prefill(0, prompt, block_row=row)]
    for step in range(1, N):
        toks = engine.decode_step(
            np.array([got[-1], 0], np.int32), np.array([True, False]),
            np.zeros(2, np.float32), np.ones(2, np.float32),
            np.zeros(2, np.int32), np.full(2, step, np.int32),
            block_tables=tables)
        got.append(int(toks[0]))
    assert got == ref


def test_generation_deterministic_across_engine_rebuilds():
    """Restart determinism (the serving analogue of bit-exact resume): a
    rebuilt engine reproduces greedy AND sampled generations — per-slot
    PRNG is fold_in(seed, step), independent of engine history."""
    from fault_tolerant_llm_training_tpu.inference.engine import InferenceEngine
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    cfg = _tiny_cfg("loop")
    _, params = _init_params(cfg)
    prompt = [5, 17, 9, 33]

    def _generate():
        engine = InferenceEngine(cfg, params, slots=2, max_len=32)
        sched = Scheduler(engine, eos_token_id=None)
        for i, temp in enumerate([0.0, 0.8]):
            sched.submit(Request(id=f"r{i}", prompt=prompt, max_new_tokens=5,
                                 temperature=temp, seed=7 + i))
        done = sched.run()
        return {c.request_id: c.tokens for c in done}

    assert _generate() == _generate()


# -------------------------------------------------------------- 2. mechanics
class _FakeEngine:
    """Deterministic engine double: slot s emits 100+s then counts up;
    'eos_at' slots emit the eos token after a set number of steps."""

    def __init__(self, slots=2, max_len=64):
        self.slots = slots
        self.max_len = max_len
        self.prefills = []

    def prefill(self, slot, prompt, temperature=0.0, top_p=1.0, seed=0):
        self.prefills.append((slot, tuple(prompt)))
        return 100 + slot

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps):
        return np.where(active, np.asarray(tokens) + 1, 0).astype(np.int32)


def test_scheduler_admits_evicts_and_refills():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeEngine(slots=2)
    sched = Scheduler(eng, eos_token_id=None)
    for i, n in enumerate([3, 5, 2]):  # staggered lengths force a refill
        sched.submit(Request(id=f"r{i}", prompt=[1, 2], max_new_tokens=n))
    done = sched.run()
    assert {c.request_id for c in done} == {"r0", "r1", "r2"}
    assert all(c.reason == "length" for c in done)
    by_id = {c.request_id: c for c in done}
    assert len(by_id["r0"].tokens) == 3
    assert len(by_id["r1"].tokens) == 5
    assert len(by_id["r2"].tokens) == 2
    # r2 was queued behind the first two and admitted into r0's freed slot
    assert sched.max_concurrent == 2
    assert eng.prefills[0][0] != eng.prefills[1][0]
    m = sched.metrics()
    assert m["requests_completed"] == 3
    assert m["tokens_generated"] == 10
    assert m["decode_p95_ms"] >= 0 and m["iterations"] == sched.iterations


def test_scheduler_eos_and_oversize_rejection():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeEngine(slots=1)
    sched = Scheduler(eng, eos_token_id=103)  # slot 0 emits 100,101,102,103
    sched.submit(Request(id="r0", prompt=[1], max_new_tokens=32))
    done = sched.run()
    assert done[0].reason == "eos" and done[0].tokens[-1] == 103
    with pytest.raises(ValueError, match="exceeds"):
        sched.submit(Request(id="big", prompt=[1] * 60, max_new_tokens=32))


def test_scheduler_drain_finishes_active_leaves_queue():
    """stop_admission() mid-flight (what serve.py does on SIGTERM): active
    slots run to completion, queued requests stay unserved, pending() goes
    False so the serve loop exits."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeEngine(slots=1)
    sched = Scheduler(eng, eos_token_id=None)
    for i in range(3):
        sched.submit(Request(id=f"r{i}", prompt=[1], max_new_tokens=4))
    sched.step()  # admits r0 only (1 slot)
    sched.stop_admission()
    while sched.pending():
        sched.step()
    assert [c.request_id for c in sched.completed] == ["r0"]
    assert [r.id for r in sched.unserved()] == ["r1", "r2"]
    assert not sched.pending()


# -------------------------------------------------------------- 3. lifecycle
def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["JAX_COMPILATION_CACHE_DIR"] = CACHE
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["PYTHONFAULTHANDLER"] = "1"
    return env


def _run_serve(argv, timeout=300, send_signal=None, wait_for=None):
    """Run serve.py, optionally signalling once ``wait_for`` appears."""
    import queue as _queue
    import threading as _threading

    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=_env())
    lines: "_queue.Queue" = _queue.Queue()

    def _reader():
        for line in proc.stdout:
            lines.put(line)
        lines.put(None)

    _threading.Thread(target=_reader, daemon=True).start()
    out, fired = [], False
    deadline = time.time() + timeout
    while True:
        try:
            line = lines.get(timeout=max(0.1, deadline - time.time()))
        except _queue.Empty:
            line = ""
        if line is None:
            break
        if line:
            out.append(line)
            if (send_signal is not None and not fired
                    and wait_for is not None and wait_for in line):
                proc.send_signal(send_signal)
                fired = True
        if time.time() > deadline:
            proc.kill()
            break
    try:
        proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
    return proc.returncode, "".join(out), fired


@pytest.fixture(scope="module")
def trained_ckpt(tmp_path_factory):
    """Train tiny for a few steps through the real CLI; returns the
    checkpoint root (job id 'serve_e2e')."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    tmp = tmp_path_factory.mktemp("serve_e2e")
    rng = np.random.default_rng(5)
    words = ["alpha", "bravo", "charlie", "delta", "echo"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(20, 120))))
            for _ in range(64)]
    parquet = tmp / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), parquet)

    env = _env()
    env["SLURM_JOB_ID"] = "serve_e2e"
    argv = [sys.executable, str(REPO / "train.py"),
            "--dataset", str(parquet),
            "--checkpoint-path", str(tmp / "ckpts"),
            "--tokenizer-name-or-path", "byte", "--model", "tiny",
            "--sequence-length", "128", "--batch-size", "2",
            "--training-steps", "6", "--checkpoint-frequency", "5",
            "--learning-rate", "1e-3", "--logging-frequency", "1"]
    proc = subprocess.run(argv, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, env=env,
                          timeout=600)
    assert proc.returncode == 0, proc.stdout
    assert "Training completed" in proc.stdout, proc.stdout
    return str(tmp / "ckpts")


def _serve_argv(ckpt, extra):
    return [sys.executable, "-m",
            "fault_tolerant_llm_training_tpu.inference.serve",
            "--checkpoint-path", ckpt, "--checkpoint-job-id", "serve_e2e",
            "--model", "tiny", "--slots", "2", "--max-len", "128",
            "--seed", "3"] + extra


def test_serve_restores_checkpoint_and_completes(trained_ckpt):
    """Happy path: restore the trained checkpoint, run >= 2 concurrent
    requests through the scheduler, finish every request, exit 0."""
    rc, out, _ = _run_serve(_serve_argv(trained_ckpt, [
        "--prompt", "alpha bravo", "--prompt", "charlie delta",
        "--prompt", "echo alpha", "--max-new-tokens", "8"]))
    assert rc == 0, out
    assert "Starting serving!" in out
    assert "Model loaded from checkpoint" in out
    assert "Serving ready | model tiny | checkpoint step 5 | slots 2" in out
    for i in range(3):
        assert f"Request req{i} done" in out, out
    assert "Prefix cache | lookups 3 |" in out  # summary audit, cache on
    assert "Serving completed" in out
    assert "[EXIT HANDLER]" not in out  # no drain on the happy path


def test_serve_sigterm_drains_and_exits_zero(trained_ckpt):
    """The receipt: SIGTERM mid-generation -> admission stops, in-flight
    requests finish, queued ones are reported unserved, process exits 0
    with the audit trail. Transcript saved to logs/serving_e2e.log."""
    rc, out, fired = _run_serve(_serve_argv(trained_ckpt, [
        "--prompt", "alpha bravo charlie", "--repeat", "40",
        "--max-new-tokens", "48", "--no-eos", "--log-frequency", "1"]),
        send_signal=signal.SIGTERM, wait_for="Serve step: 1 |")
    logdir = REPO / "logs"
    logdir.mkdir(exist_ok=True)
    (logdir / "serving_e2e.log").write_text(out)
    assert fired, out
    assert rc == 0, out
    assert "Signal 15 received, draining" in out, out
    assert "admission stopped." in out
    assert "[EXIT HANDLER] Drained;" in out
    assert "queued request(s) not admitted." in out
    # 40 identical prompts: every admission past the first hits the
    # first committed block, so the summary audit shows a nonzero rate
    assert "Prefix cache | lookups" in out
    assert "hit rate 0.000" not in out.split("Prefix cache | ")[1], out
    assert "Serving completed" in out
    # drained means NOT all 40 requests ran; at least the in-flight finished
    done = out.count("done | length")
    assert 0 < done < 40, out
