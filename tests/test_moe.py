"""Mixture-of-Experts (models/moe.py): routing math, dense-FFN equivalence,
aux loss, expert-parallel sharding, and the full train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.models.llama import FeedForward
from fault_tolerant_llm_training_tpu.models.moe import MoEFeedForward
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
)
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    make_optimizer,
    make_train_step,
)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, attention_impl="xla")


def _x(b=2, s=16, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.standard_normal((b, s, d)), jnp.float32)


def test_single_expert_equals_dense_ffn():
    """E=1, k=1, ample capacity: every token routes to the one expert with
    weight 1.0, so MoE(x) == FFN(x) with the same weights."""
    cfg = get_config("tiny-moe", moe_experts=1, moe_top_k=1,
                     moe_capacity_factor=2.0, **FP32)
    x = _x()
    moe = MoEFeedForward(cfg)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    got = moe.apply({"params": params}, x)
    dense_params = jax.tree_util.tree_map(lambda a: a[0],
                                          params["experts"])
    want = FeedForward(cfg).apply({"params": dense_params}, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_moe_matches_per_token_reference():
    """With capacity >= every token (no drops), the dispatch/combine einsum
    formulation equals the direct per-token mixture sum_k w_k * FFN_{e_k}(x)."""
    cfg = get_config("tiny-moe", moe_capacity_factor=8.0, **FP32)
    x = _x(seed=3)
    moe = MoEFeedForward(cfg)
    params = moe.init(jax.random.PRNGKey(1), x)["params"]
    got = np.asarray(moe.apply({"params": params}, x))

    b, s, d = x.shape
    xf = np.asarray(x).reshape(-1, d)
    gates = xf @ np.asarray(params["router"]["kernel"], np.float32)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(gates), axis=-1))
    want = np.zeros_like(xf)
    for i in range(xf.shape[0]):
        top = np.argsort(-probs[i])[: cfg.moe_top_k]
        w = probs[i][top] / probs[i][top].sum()
        for e, wi in zip(top, w):
            ep = jax.tree_util.tree_map(lambda a: a[e], params["experts"])
            y = FeedForward(cfg).apply({"params": ep},
                                       jnp.asarray(xf[i][None, None, :]))
            want[i] += wi * np.asarray(y)[0, 0]
    np.testing.assert_allclose(got.reshape(-1, d), want, rtol=2e-4,
                               atol=2e-4)


def test_sorted_dispatch_matches_capacity_without_drops():
    """With capacity ample enough that nothing drops, the dropless sorted
    ragged-dot dispatch computes the same mixture as the GShard capacity
    einsums — independent formulations of the same routing (the param tree
    is deliberately identical, so one init serves both). 'sorted' is an
    explicit opt-in: auto resolves to capacity, which measured faster on
    v5e (ragged_dot runs well below dense-GEMM efficiency there)."""
    cfg_cap = get_config("tiny-moe", moe_capacity_factor=8.0,
                         moe_impl="capacity", **FP32)
    cfg_srt = cfg_cap.replace(moe_impl="sorted")
    x = _x(seed=5)
    moe_cap = MoEFeedForward(cfg_cap)
    params = moe_cap.init(jax.random.PRNGKey(2), x)["params"]
    want = np.asarray(moe_cap.apply({"params": params}, x))
    got = np.asarray(MoEFeedForward(cfg_srt).apply({"params": params}, x))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_sorted_init_matches_capacity_init_distribution():
    """The sorted impl's stacked (E, in, out) kernels initialize with the
    same per-expert fan-in std as the capacity impl's vmapped per-expert
    lecun_normal — the expert dim must count as a batch axis, not receptive
    field (which would under-scale std by sqrt(E))."""
    cfg = get_config("tiny-moe", moe_impl="sorted", **FP32)
    x = _x()
    params = MoEFeedForward(cfg).init(jax.random.PRNGKey(0), x)["params"]
    cap_params = MoEFeedForward(cfg.replace(moe_impl="capacity")).init(
        jax.random.PRNGKey(1), x)["params"]
    for name in ("w1", "w2", "w3"):
        srt = np.asarray(params["experts"][name]["kernel"], np.float64)
        cap = np.asarray(cap_params["experts"][name]["kernel"], np.float64)
        assert srt.shape == cap.shape
        np.testing.assert_allclose(srt.std(), cap.std(), rtol=0.1)


def test_sorted_dispatch_is_dropless_and_differentiable():
    """Under a capacity factor where the capacity impl PROVABLY drops
    (capacity -> 1 slot per expert), the sorted impl still computes every
    (token, slot) pair — its output matches the dropless per-token mixture
    oracle — and gradients are finite."""
    cfg = get_config("tiny-moe", moe_impl="sorted",
                     moe_capacity_factor=1e-9, **FP32)
    x = _x(seed=7)
    moe = MoEFeedForward(cfg)
    params = moe.init(jax.random.PRNGKey(3), x)["params"]
    got = np.asarray(moe.apply({"params": params}, x))

    b, s, d = x.shape
    xf = np.asarray(x).reshape(-1, d)
    gates = xf @ np.asarray(params["router"]["kernel"], np.float32)
    probs = np.asarray(jax.nn.softmax(jnp.asarray(gates), axis=-1))
    want = np.zeros_like(xf)
    for i in range(xf.shape[0]):
        top = np.argsort(-probs[i])[: cfg.moe_top_k]
        w = probs[i][top] / probs[i][top].sum()
        for e, wi in zip(top, w):
            ep = jax.tree_util.tree_map(lambda a: a[e], params["experts"])
            y = FeedForward(cfg).apply({"params": ep},
                                       jnp.asarray(xf[i][None, None, :]))
            want[i] += wi * np.asarray(y)[0, 0]
    np.testing.assert_allclose(got.reshape(-1, d), want, rtol=2e-4,
                               atol=2e-4)
    # ...while the capacity impl at this factor drops all but one
    # (token, slot) pair per expert per row: some tokens come out zero
    cap = np.asarray(MoEFeedForward(cfg.replace(moe_impl="capacity")).apply(
        {"params": params}, x))
    assert np.sum(np.all(cap == 0, axis=-1)) > 0  # dropped tokens exist

    def loss(p, x):
        return jnp.sum(moe.apply({"params": p}, x) ** 2)

    grads = jax.jit(jax.grad(loss))(params, x)
    for path, leaf in jax.tree_util.tree_flatten_with_path(grads)[0]:
        assert np.all(np.isfinite(np.asarray(leaf))), path


def test_sorted_dispatch_full_train_step():
    """The sorted impl drives the full jitted train step (loss finite and
    decreasing on repeated steps)."""
    cfg = get_config("tiny-moe", moe_impl="sorted", **FP32)
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    step_fn = jax.jit(make_train_step(model, opt, 1.0))
    rng = np.random.default_rng(11)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 32)), jnp.int32)
    labels = jnp.concatenate(
        [toks[:, 1:], jnp.full((2, 1), -100, jnp.int32)], axis=1)
    params = model.init(jax.random.PRNGKey(0), toks)["params"]
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    losses = []
    for _ in range(5):
        state, metrics = step_fn(state, toks, labels)
        losses.append(float(metrics["loss"]))
    assert all(np.isfinite(x) for x in losses)
    assert losses[-1] < losses[0]


def test_capacity_drops_overflow_tokens_per_group():
    """capacity 1 with b=2 rows: the capacity ledger is per batch row
    (GShard groups) — EACH row keeps its first token per expert, so drops
    never leak across rows; every overflow token falls back to zero (the
    residual stream carries it — Switch semantics)."""
    cfg = get_config("tiny-moe", moe_experts=2, moe_top_k=1,
                     moe_capacity_factor=1e-9, moe_impl="capacity",
                     **FP32)  # capacity -> 1; sorted never drops
    x = _x(b=2, s=8, seed=7)
    moe = MoEFeedForward(cfg)
    params = moe.init(jax.random.PRNGKey(0), x)["params"]
    out = np.asarray(moe.apply({"params": params}, x))
    gates = np.asarray(x) @ np.asarray(params["router"]["kernel"],
                                       np.float32)
    for row in range(2):
        nonzero = np.flatnonzero(np.abs(out[row]).sum(-1) > 0)
        assert 1 <= len(nonzero) <= cfg.moe_experts, nonzero
        # the kept token for each expert is the FIRST of THIS row
        first_per_expert = {}
        for i, e in enumerate(np.argmax(gates[row], axis=-1)):
            first_per_expert.setdefault(int(e), i)
        assert sorted(first_per_expert.values()) == sorted(
            nonzero.tolist()), row


def test_aux_loss_formula_and_sow():
    cfg = get_config("tiny-moe", **FP32)
    x = _x(seed=5)
    moe = MoEFeedForward(cfg)
    params = moe.init(jax.random.PRNGKey(2), x)["params"]
    _, mut = moe.apply({"params": params}, x, mutable=["losses"])
    aux = float(jax.tree_util.tree_leaves(mut)[0])
    # perfectly balanced routing gives exactly 1.0; anything real is >= 1
    assert 0.99 <= aux < cfg.moe_experts, aux


def test_param_count_matches_init():
    cfg = get_config("tiny-moe", **FP32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    actual = sum(int(np.prod(p.shape))
                 for p in jax.tree_util.tree_leaves(params))
    assert actual == cfg.param_count(), (actual, cfg.param_count())


def _run_steps(cfg, mesh_kwargs, n_steps=3):
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    mesh = make_mesh(**mesh_kwargs)
    with use_mesh(mesh):
        def init_fn(key):
            params = model.init(key, jnp.zeros((1, 32), jnp.int32))["params"]
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt.init(params))

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        specs = param_pspecs(abstract)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        state = jax.jit(init_fn, out_shardings=shardings)(
            jax.random.PRNGKey(0))
        step_fn = jax.jit(make_train_step(model, opt, 1.0),
                          out_shardings=(shardings, None))
        rng = np.random.default_rng(11)
        bsh = NamedSharding(mesh, batch_pspec())
        losses = []
        for _ in range(n_steps):
            toks = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
            labels = np.concatenate(
                [toks[:, 1:], np.full((8, 1), -100, np.int32)], axis=1)
            state, metrics = step_fn(state, jax.device_put(toks, bsh),
                                     jax.device_put(labels, bsh))
            losses.append(float(metrics["loss"]))
    return losses, state


def test_ep_matches_single_device(eight_devices):
    """Expert-parallel training (experts sharded over 'expert', all-to-all
    from the shardings) reproduces the single-device loss trajectory.
    Pinned to the capacity impl: 'auto' currently resolves to capacity
    everywhere (moe.py), so the pin only guards against a future
    auto-heuristic change altering the reference trajectory."""
    cfg = get_config("tiny-moe", moe_impl="capacity", **FP32)
    base, _ = _run_steps(cfg, dict(dp=1, devices=[jax.devices()[0]]))
    ep, state = _run_steps(cfg, dict(dp=2, ep=4))
    np.testing.assert_allclose(base, ep, rtol=5e-5, atol=1e-6)
    # experts actually shard: leading E axis split over the expert axis
    w1 = state.params["layers_0"]["feed_forward"]["experts"]["w1"]["kernel"]
    assert w1.sharding.shard_shape(w1.shape)[0] == cfg.moe_experts // 4


def test_moe_scan_trunk_matches_loop():
    """The scanned trunk stacks the per-layer router aux losses (the
    'losses' collection scans with the layers); one train step from
    identical weights matches the loop form."""
    from fault_tolerant_llm_training_tpu.models.llama import (
        stack_layer_params,
    )

    cfg = get_config("tiny-moe", **FP32)
    loop_model = Transformer(cfg)
    scan_model = Transformer(cfg.replace(layer_impl="scan"))
    opt = make_optimizer(1e-3, warmup_steps=2)
    rng = np.random.default_rng(13)
    toks = rng.integers(0, cfg.vocab_size, (4, 32)).astype(np.int32)
    labels = np.concatenate(
        [toks[:, 1:], np.full((4, 1), -100, np.int32)], axis=1)
    params = loop_model.init(jax.random.PRNGKey(0),
                             jnp.zeros((1, 32), jnp.int32))["params"]

    def one_step(model, p):
        state = TrainState(step=jnp.zeros((), jnp.int32), params=p,
                           opt_state=opt.init(p))
        step_fn = jax.jit(make_train_step(model, opt, 1.0))
        _, m = step_fn(state, jnp.asarray(toks), jnp.asarray(labels))
        return np.asarray(m["packed"])

    a = one_step(loop_model, params)
    b = one_step(scan_model, stack_layer_params(params, cfg.n_layers))
    np.testing.assert_allclose(b, a, rtol=2e-5, atol=2e-6)


def test_moe_preset_validation():
    with pytest.raises(ValueError, match="moe_top_k"):
        get_config("tiny-moe", moe_top_k=9)
