"""Pipeline parallelism (parallel/pipeline.py): both schedules over the
'pipe' mesh axis — 1F1B (the training default: in-loop head+CE, explicit
gradients, O(P) activation memory) and GPipe (forward/eval + the legacy
autodiff fallback) — compute the same function as the plain scan trunk,
stage params actually shard, and the full train step matches single-device.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.pipeline import pipeline_apply
from fault_tolerant_llm_training_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
)
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    make_eval_step,
    make_optimizer,
    make_train_step,
)

from test_fault_tolerance import parquet  # noqa: F401  (shared fixture)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, attention_impl="xla",
            layer_impl="scan")


def _labels(toks):
    """Next-token labels with the -100 ignore tail (ref dataset.py:44-53)."""
    return np.concatenate(
        [toks[:, 1:], np.full((toks.shape[0], 1), -100, np.int32)], axis=1)


def _setup(seed=0, batch=4):
    cfg = get_config("tiny", **FP32)  # 2 layers -> pp=2, one layer per stage
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, (batch, 32)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(1), jnp.asarray(tokens))["params"]
    return cfg, model, params, tokens


def test_pipeline_logits_match_plain_scan(eight_devices):
    cfg, model, params, tokens = _setup()
    want = model.apply({"params": params}, jnp.asarray(tokens))
    mesh = make_mesh(dp=2, pp=2, fsdp=2)
    with use_mesh(mesh):
        got = jax.jit(lambda p, t: pipeline_apply(model, p, t))(
            params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_more_microbatches(eight_devices):
    cfg, model, params, tokens = _setup(batch=8)
    want = model.apply({"params": params}, jnp.asarray(tokens))
    mesh = make_mesh(dp=1, pp=2)
    with use_mesh(mesh):
        got = jax.jit(lambda p, t: pipeline_apply(model, p, t,
                                                  microbatches=4))(
            params, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_train_step_matches_single_device(eight_devices):
    """Full pp=2 x dp=2 x fsdp=2 train steps through the default 1F1B
    schedule (in-loop head+CE, explicitly assembled gradients, AdamW
    update on stage-sharded params) reproduce the single-device loss
    trajectory. The legacy autodiff/GPipe schedule is covered separately
    by test_pipeline_gpipe_schedule_matches_single_device."""
    cfg = get_config("tiny", **FP32)
    base, _ = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]))
    pp, _ = _run_train(cfg, dict(dp=2, pp=2, fsdp=2), microbatches=4)
    np.testing.assert_allclose(base, pp, rtol=5e-5, atol=1e-6)


def _run_train(cfg, mesh_kwargs, microbatches=0, grad_accum=1, n_steps=3,
               batch=8, seed=7):
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    mesh = make_mesh(**mesh_kwargs)
    with use_mesh(mesh):
        def init_fn(key):
            params = model.init(key, jnp.zeros((1, 32), jnp.int32))["params"]
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt.init(params))

        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        specs = param_pspecs(abstract)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), specs,
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        state = jax.jit(init_fn, out_shardings=shardings)(
            jax.random.PRNGKey(0))
        step_fn = jax.jit(
            make_train_step(model, opt, 1.0, microbatches=microbatches,
                            grad_accum=grad_accum),
            out_shardings=(shardings, None))
        rng = np.random.default_rng(seed)
        losses = []
        bsh = NamedSharding(mesh, batch_pspec())
        for _ in range(n_steps):
            toks = rng.integers(0, cfg.vocab_size, (batch, 32)).astype(
                np.int32)
            labels = _labels(toks)
            state, metrics = step_fn(state, jax.device_put(toks, bsh),
                                     jax.device_put(labels, bsh))
            losses.append(float(metrics["loss"]))
    return losses, state


def test_pipeline_gpipe_schedule_matches_single_device(eight_devices):
    """The legacy GPipe schedule (--pp-schedule gpipe: autodiff through the
    forward tick scan) still reproduces the single-device trajectory."""
    cfg = get_config("tiny", pp_schedule="gpipe", **FP32)
    base, _ = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]))
    pp, _ = _run_train(cfg, dict(dp=2, pp=2, fsdp=2), microbatches=4)
    np.testing.assert_allclose(base, pp, rtol=5e-5, atol=1e-6)


def test_pipeline_moe_matches_grad_accum(eight_devices):
    """MoE rides the 1F1B pipeline: the routers' sown aux losses are
    accumulated per-microbatch inside the tick loop (VERDICT r2 next-step
    #3), with exactly grad accumulation's semantics — each microbatch's
    aux weighted by its valid-token count. So a pp=2 run with M=4
    microbatches must reproduce the single-device --grad-accum 4
    trajectory bit-for-bit (same microbatch slicing), aux included."""
    cfg = get_config("tiny-moe", moe_impl="capacity",
                     moe_capacity_factor=8.0, **FP32)
    base, _ = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]),
                         grad_accum=4)
    pp, _ = _run_train(cfg, dict(dp=1, pp=2, fsdp=2), microbatches=4)
    assert all(np.isfinite(pp))
    np.testing.assert_allclose(base, pp, rtol=5e-5, atol=1e-6)
    # the aux is actually in the loss: a no-aux run must differ
    cfg0 = cfg.replace(moe_aux_weight=0.0)
    pp0, _ = _run_train(cfg0, dict(dp=1, pp=2, fsdp=2), microbatches=4)
    assert abs(pp0[0] - pp[0]) > 1e-6


def test_pipeline_composes_with_grad_accum(eight_devices):
    """--grad-accum slices the batch OUTSIDE the pipeline; each slice then
    runs the full 1F1B schedule with its own microbatch split. Because
    both mechanisms weight per-microbatch losses (and MoE aux) by valid
    tokens, pp=2 x (grad_accum=2, microbatches=2) must reproduce the
    single-device grad_accum=4 trajectory exactly — same 4 slices of the
    batch in the same order."""
    cfg = get_config("tiny-moe", moe_impl="capacity",
                     moe_capacity_factor=8.0, **FP32)
    base, _ = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]),
                         grad_accum=4)
    pp, _ = _run_train(cfg, dict(dp=1, pp=2, fsdp=2), microbatches=2,
                       grad_accum=2)
    np.testing.assert_allclose(base, pp, rtol=5e-5, atol=1e-6)


def test_pipeline_moe_eval_reports_pure_ce(eight_devices):
    """Eval of an MoE model on a pipeline mesh (previously hard-blocked):
    the GPipe forward path drops the routers' sown aux — which is exactly
    right for eval, whose contract is pure CE (training/step.py). The
    packed (sum_nll, n) must equal the single-device eval of the same
    params."""
    cfg = get_config("tiny-moe", moe_capacity_factor=8.0, **FP32)
    model = Transformer(cfg)
    rng = np.random.default_rng(31)
    toks = rng.integers(0, cfg.vocab_size, (8, 32)).astype(np.int32)
    labels = _labels(toks)
    params = model.init(jax.random.PRNGKey(2), jnp.asarray(toks))["params"]

    with use_mesh(make_mesh(dp=1, devices=[jax.devices()[0]])):
        want = jax.jit(make_eval_step(model))(
            params, jnp.asarray(toks), jnp.asarray(labels))
    mesh = make_mesh(dp=1, pp=2, fsdp=2)
    with use_mesh(mesh):
        bsh = NamedSharding(mesh, batch_pspec())
        got = jax.jit(make_eval_step(model, microbatches=4))(
            params, jax.device_put(toks, bsh), jax.device_put(labels, bsh))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-5, atol=1e-6)


def test_pipeline_blocked_vocab_tail(eight_devices):
    """At a vocab slice > the CE block size the in-loop head takes the
    blocked online-softmax path (shared with ops/fused_ce.py); trajectory
    still matches single-device."""
    cfg = get_config("tiny", vocab_size=32768, **FP32)  # vl=16384 > 8192
    base, _ = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]),
                         n_steps=2)
    pp, _ = _run_train(cfg, dict(dp=1, pp=2), microbatches=4, n_steps=2)
    np.testing.assert_allclose(base, pp, rtol=5e-5, atol=1e-6)


def test_pipeline_1f1b_activation_memory(eight_devices):
    """The point of 1F1B (VERDICT r2 next-step #1): activation memory is
    O(P), not O(M). Compare XLA's temp-buffer allocation for the compiled
    train step at M=8, P=2 against the GPipe schedule, whose autodiff
    stores every tick's residuals: 1F1B must allocate well under half the
    GPipe temps (measured 0.145x here; the stash ring holds 2P-1=3
    microbatch inputs and per-microbatch logits blocks vs GPipe's M+P-1=9
    tick residual sets + full-batch fp32 logits)."""
    cfg = get_config("tiny", **FP32)
    opt = make_optimizer(1e-3, warmup_steps=2)
    temps = {}
    for sched in ("1f1b", "gpipe"):
        m = Transformer(cfg.replace(pp_schedule=sched))
        mesh = make_mesh(dp=1, pp=2)
        with use_mesh(mesh):
            def init_fn(key):
                params = m.init(key, jnp.zeros((1, 32), jnp.int32))["params"]
                return TrainState(step=jnp.zeros((), jnp.int32),
                                  params=params,
                                  opt_state=opt.init(params))

            abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
            specs = param_pspecs(abstract)
            shardings = jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
            bsh = NamedSharding(mesh, batch_pspec())
            bstruct = jax.ShapeDtypeStruct((8, 32), jnp.int32, sharding=bsh)
            astate = jax.tree_util.tree_map(
                lambda a, sh: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                   sharding=sh),
                abstract, shardings)
            compiled = jax.jit(
                make_train_step(m, opt, 1.0, microbatches=8),
                out_shardings=(shardings, None)).lower(
                astate, bstruct, bstruct).compile()
            temps[sched] = compiled.memory_analysis().temp_size_in_bytes
    assert temps["1f1b"] < 0.5 * temps["gpipe"], temps


def test_pipeline_params_shard_by_stage(eight_devices):
    """Stage s stores only its layer slice: the leading layer axis of the
    stacked params shards over 'pipe'."""
    cfg, model, params, tokens = _setup()
    mesh = make_mesh(dp=1, pp=2, fsdp=2)
    specs = param_pspecs(params)
    wq_spec = specs["layers"]["block"]["attention"]["wq"]["kernel"]
    assert wq_spec == jax.sharding.PartitionSpec("pipe", "fsdp", "tensor")
    sharded = jax.device_put(
        params["layers"]["block"]["attention"]["wq"]["kernel"],
        NamedSharding(mesh, wq_spec))
    shard = sharded.sharding.shard_shape(sharded.shape)
    assert shard[0] == cfg.n_layers // 2  # one layer per stage at pp=2


def test_pipeline_head_not_replicated(eight_devices):
    """The vocab axis shards over 'pipe' (parallel/sharding.py), so each
    stage computes only its slice of the (B, S, V) head matmul — one head
    matmul total across the mesh, not P replicated copies (the round-1
    pipeline recomputed the model's largest matmul on every stage). Pinned
    on the optimized HLO: no dot in the compiled loss produces a full-V
    array, and the head dot produces V/pp columns per device."""
    import re

    from fault_tolerant_llm_training_tpu.training.step import model_loss

    cfg, model, params, tokens = _setup(batch=4)
    v = cfg.vocab_size
    mesh = make_mesh(dp=1, pp=2)
    labels = _labels(tokens)
    with use_mesh(mesh):
        fn = jax.jit(jax.grad(
            lambda p, t, l: model_loss(model, p, t, l)[0]))
        hlo = fn.lower(params, jnp.asarray(tokens),
                       jnp.asarray(labels)).compile().as_text()
    dot_shapes = re.findall(
        r"= f\d+\[([\d,]+)\]\{[\d,]*\} dot\(", hlo)
    last_dims = [int(s.split(",")[-1]) for s in dot_shapes if s]
    assert v // 2 in last_dims  # the sharded head matmul exists...
    assert v not in last_dims   # ...and no dot produces full-V logits
    # ...and no op of any kind materializes a full-V array per device
    assert not re.search(r"\[(?:[\d]+,)*%d\]" % v, hlo)


def test_pipeline_checkpoint_resumes_on_non_pipelined_mesh(tmp_path,
                                                           parquet):
    """Cross-topology resume across the pipe axis (SURVEY.md §7.3 hard
    part 3 extended): a checkpoint saved by a dp=2 x pp=2 x fsdp=2 run
    (stage-sharded layer stacks) resumes on a dp=2 x fsdp=4 mesh with a
    continuous loss trajectory."""
    from test_fault_tolerance import _args, _run

    common = {"--batch-size": "8", "--layer-impl": "scan",
              "--learning-rate": "1e-3", "--lr-warmup-steps": "5"}
    argv = _args(tmp_path, parquet, **dict(
        common, **{"--dp": "2", "--pp": "2", "--fsdp": "2",
                   "--microbatches": "4", "--raise-error": "",
                   "--error-step": "10"}))
    rc, out = _run(argv, job_id="ppx1", xla_devices=8)
    assert rc == 0, out
    assert "Checkpoint saved at step" in out

    argv = _args(tmp_path, parquet, **dict(
        common, **{"--checkpoint-id": "ppx1", "--dp": "2", "--fsdp": "4"}))
    rc, out2 = _run(argv, job_id="ppx2", xla_devices=8)
    assert rc == 0, out2
    assert "Resuming training from training_step 11" in out2
    assert "Training completed" in out2


def test_pipeline_requires_divisible_layers(eight_devices):
    cfg = get_config("tiny", n_layers=3, multiple_of=32, **FP32)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    mesh = make_mesh(dp=1, pp=2)
    with use_mesh(mesh):
        with pytest.raises(ValueError, match="not divisible by pp"):
            pipeline_apply(model, params, tokens)


def test_pipeline_mixed_precision_matches_single_device(eight_devices):
    """Mixed precision through 1F1B (ADVICE r3): with fp32 master params
    and bf16 compute, the in-loop head casts w to the compute dtype
    exactly where nn.Dense does, so the pipelined trajectory tracks the
    single-device one within bf16 rounding; param/grad dtypes stay fp32
    (the master copy) across the explicit-gradient update."""
    cfg = get_config("tiny", dtype=jnp.bfloat16, param_dtype=jnp.float32,
                     attention_impl="xla", layer_impl="scan")
    base, state_b = _run_train(cfg, dict(dp=1, devices=[jax.devices()[0]]))
    pp, state_p = _run_train(cfg, dict(dp=2, pp=2, fsdp=2), microbatches=4)
    # bf16 band: the schedules associate sums differently but round at
    # the same points, so the trajectories agree to bf16 noise
    np.testing.assert_allclose(base, pp, rtol=2e-2, atol=2e-2)
    for leaf in jax.tree_util.tree_leaves(state_p.params):
        assert leaf.dtype == jnp.float32


def test_pipeline_stage_unroll_matches_scan(eight_devices):
    """--pp-stage-unroll (the default — its compute pattern measured
    22.5% faster than the scanned body on the chip, BASELINE.md r4) vs
    --no-pp-stage-unroll: same function, bit-comparable trajectory
    (fp32), through the full 1F1B train step."""
    cfg_u = get_config("tiny", **FP32, pp_stage_unroll=True)
    cfg_s = get_config("tiny", **FP32, pp_stage_unroll=False)
    u, _ = _run_train(cfg_u, dict(dp=2, pp=2, fsdp=2), microbatches=4)
    s, _ = _run_train(cfg_s, dict(dp=2, pp=2, fsdp=2), microbatches=4)
    np.testing.assert_allclose(u, s, rtol=1e-6, atol=1e-7)
