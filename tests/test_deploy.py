"""Deployment loop (fault_tolerant_llm_training_tpu/deploy/).

Layers, cheapest first:

- pointer mechanics: atomic ``published.json`` writes (a concurrent
  reader never observes a torn pointer, no tmp litter), publish refuses
  a step without its integrity manifest;
- verify-before-load: a corrupted published step (or a manifest swapped
  after the digest was taken) is rejected WITHOUT loading, the audit +
  counter fire, serving state is untouched;
- watcher dedup: each (job, step, digest) publish is offered exactly once;
- the swap itself, against real tiny engines: in-flight slots survive a
  mid-stream hot reload un-dropped, admission reopens, and a request
  admitted AFTER the swap streams bit-identically to a fresh restore of
  the published step — the property the chaos campaign pins end-to-end;
- the adaptive-k controller: targets stay inside [1, k_max] on any
  observation sequence, walk down under rejection, recover on reset.
"""

import json
import os
import threading
from pathlib import Path

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.deploy.publish import (
    POINTER_NAME,
    Pointer,
    Publisher,
    manifest_digest,
    pointer_path,
    read_pointer,
    verify_pointer,
    write_pointer,
)
from fault_tolerant_llm_training_tpu.deploy.reload import (
    HotReloader,
    PointerWatcher,
)
from fault_tolerant_llm_training_tpu.obs import events as events_mod

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_recorder():
    events_mod._RECORDER = events_mod.FlightRecorder()
    yield
    events_mod._RECORDER = events_mod.FlightRecorder()


# ------------------------------------------------------------------ pointers
def _ptr(step, job="j", path="p", digest="d", draft=None):
    return Pointer(step=step, job_id=job, path=path,
                   manifest_digest=digest, draft=draft)


def test_pointer_write_read_roundtrip(tmp_path):
    root = str(tmp_path)
    draft = {"job_id": "dj", "step": 3, "path": "dp", "manifest_digest": "x"}
    write_pointer(root, _ptr(10, draft=draft))
    got = read_pointer(root)
    assert (got.step, got.job_id, got.path, got.manifest_digest) == \
        (10, "j", "p", "d")
    assert got.draft == draft
    assert got.version == 1


def test_pointer_reads_tolerate_garbage(tmp_path):
    root = str(tmp_path)
    assert read_pointer(root) is None  # no pointer yet
    Path(pointer_path(root)).write_text("{not json")
    assert read_pointer(root) is None
    Path(pointer_path(root)).write_text('{"version": 1}')  # missing keys
    assert read_pointer(root) is None


def test_pointer_updates_are_atomic_under_concurrent_reads(tmp_path):
    """A reader polling while the publisher rewrites the pointer many
    times must only ever see complete, monotonically-advancing pointers
    (the tmp-rename contract), and the writer leaves no tmp litter."""
    root = str(tmp_path)
    write_pointer(root, _ptr(0))
    stop = threading.Event()
    bad, seen = [], []

    def reader():
        while not stop.is_set():
            ptr = read_pointer(root)
            if ptr is None:
                bad.append("unreadable pointer mid-rewrite")
            else:
                seen.append(ptr.step)

    t = threading.Thread(target=reader)
    t.start()
    try:
        for step in range(1, 200):
            write_pointer(root, _ptr(step))
    finally:
        stop.set()
        t.join()
    assert not bad
    assert seen == sorted(seen), "pointer regressed mid-rewrite"
    assert [p for p in os.listdir(root) if p.startswith(POINTER_NAME)] == \
        [POINTER_NAME], "tmp litter left behind"


# ------------------------------------------------- publish + verify-before-load
def _fake_step_dir(tmp_path, job="pub", step=20):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        write_manifest,
    )

    d = tmp_path / f"checkpoint_{job}" / str(step)
    (d / "state").mkdir(parents=True)
    (d / "state" / "arr0.bin").write_bytes(os.urandom(4096))
    (d / "data.json").write_text('{"next_index": 0}')
    write_manifest(str(d), step)
    return d


def test_publish_refuses_step_without_manifest(tmp_path):
    d = tmp_path / "checkpoint_pub" / "10"
    (d / "state").mkdir(parents=True)
    (d / "state" / "arr0.bin").write_bytes(os.urandom(64))
    pub = Publisher(str(tmp_path), "pub")
    assert pub.publish(10) is None
    assert read_pointer(str(tmp_path)) is None


def test_publish_commits_verified_pointer_and_audits(tmp_path):
    d = _fake_step_dir(tmp_path, step=20)
    pub = Publisher(str(tmp_path), "pub")
    ptr = pub.publish(20)
    assert ptr is not None and ptr.step == 20
    assert ptr.manifest_digest == manifest_digest(str(d))
    assert verify_pointer(str(tmp_path), ptr) == (True, "ok")
    got = read_pointer(str(tmp_path))
    assert (got.step, got.job_id) == (20, "pub")
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("publish") == 1


def test_verify_pointer_rejects_corruption_and_manifest_swap(tmp_path):
    d = _fake_step_dir(tmp_path, step=20)
    ptr = Publisher(str(tmp_path), "pub").publish(20)

    # payload byte flip after publish: the per-file CRC catches it
    target = d / "state" / "arr0.bin"
    raw = bytearray(target.read_bytes())
    raw[100] ^= 0xFF
    target.write_bytes(bytes(raw))
    ok, detail = verify_pointer(str(tmp_path), ptr)
    assert not ok and "crc mismatch" in detail
    raw[100] ^= 0xFF
    target.write_bytes(bytes(raw))
    assert verify_pointer(str(tmp_path), ptr) == (True, "ok")

    # manifest replaced wholesale after the digest was taken: even though
    # the rewritten manifest matches the (also rewritten) files, the
    # pointer's digest pin catches the swap
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        write_manifest,
    )

    target.write_bytes(os.urandom(4096))
    write_manifest(str(d), 20)
    ok, detail = verify_pointer(str(tmp_path), ptr)
    assert not ok and "digest" in detail


def test_watcher_offers_each_publish_exactly_once(tmp_path):
    _fake_step_dir(tmp_path, step=10)
    _fake_step_dir(tmp_path, step=20)
    pub = Publisher(str(tmp_path), "pub")
    watcher = PointerWatcher(str(tmp_path))
    assert watcher.poll() is None  # nothing published yet
    pub.publish(10)
    assert watcher.poll().step == 10
    assert watcher.poll() is None  # deduped
    pub.publish(10)  # same step, same manifest -> same digest: no new offer
    assert watcher.poll() is None
    pub.publish(20)
    assert watcher.poll().step == 20
    assert watcher.poll() is None


# ------------------------------------------------------------- the swap itself
def _tiny_cfg(vocab=64, seq_len=64):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


def _init_params(cfg, seed=0):
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    model = Transformer(cfg)
    tokens = jnp.zeros((1, cfg.seq_len), jnp.int32)
    return model.init(jax.random.PRNGKey(seed), tokens)["params"]


def _save_train_checkpoint(tmp_path, job, step, params):
    """Write a real (verified, manifested) training checkpoint holding
    ``params`` — the tree restore_params expects, optimizer state
    included."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import make_optimizer

    state = TrainState(step=jnp.asarray(step, jnp.int32), params=params,
                       opt_state=make_optimizer(1e-4, 1).init(params))
    mngr = CheckpointManager(str(tmp_path), job, enable_async=False,
                             max_to_keep=4)
    mngr.save(step, state, {"next_index": 0}, wait=True)
    mngr.close()


def _greedy_request(rid, prompt, n):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    return Request(id=rid, prompt=list(prompt), max_new_tokens=n,
                   temperature=0.0)


def _run_to_completion(sched):
    done = []
    while sched.pending():
        done.extend(sched.step())
    return {c.request_id: c.tokens for c in done}


def test_hot_reload_preserves_in_flight_and_bitmatches_fresh_restore(
        tmp_path):
    """The acceptance property at unit scale: a swap mid-stream drops no
    in-flight slot, and a request admitted after the swap streams
    bit-identically to a fresh restore of the published step."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine,
        restore_params,
    )
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    cfg = _tiny_cfg()
    params_a = _init_params(cfg, seed=0)
    params_b = _init_params(cfg, seed=1)
    _save_train_checkpoint(tmp_path, "pub", 20, params_b)
    Publisher(str(tmp_path), "pub").publish(20)

    engine = InferenceEngine(cfg, params_a, slots=2, max_len=48)
    engine.restored_step = 0
    sched = Scheduler(engine)
    reloader = HotReloader(engine, sched, cfg, str(tmp_path))
    watcher = PointerWatcher(str(tmp_path))

    prompt = [5, 9, 2, 14, 7]
    sched.submit(_greedy_request("inflight", prompt, 12))
    for _ in range(4):
        sched.step()
    assert len(sched.active) == 1
    (slot,) = sched.active
    tokens_before = list(sched.active[slot].tokens)
    assert len(tokens_before) >= 4

    assert reloader.maybe_reload(watcher.poll()) is True
    assert reloader.reloads == 1 and reloader.rejects == 0
    assert engine.restored_step == 20
    # PAUSE/RESUME left the in-flight slot intact and admission open
    assert sched.admission_open
    assert list(sched.active) == [slot]
    assert sched.active[slot].tokens[:len(tokens_before)] == tokens_before
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("weights_reload") == 1

    # post-swap admission runs wholly under the published weights
    sched.submit(_greedy_request("fresh-path", prompt, 8))
    done = _run_to_completion(sched)
    assert len(done["inflight"]) == 12, "in-flight stream was truncated"

    # ground truth: a fresh restore of the published step
    restored, got = restore_params(str(tmp_path), "pub", cfg, step=20)
    assert got == 20
    engine_b = InferenceEngine(cfg, restored, slots=2, max_len=48)
    sched_b = Scheduler(engine_b)
    sched_b.submit(_greedy_request("reference", prompt, 8))
    ref = _run_to_completion(sched_b)
    assert done["fresh-path"] == ref["reference"], (
        "post-swap stream diverged from a fresh restore of the "
        "published step")


def test_reload_rejects_corrupt_publish_and_serving_continues(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine,
    )
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    cfg = _tiny_cfg()
    params_a = _init_params(cfg, seed=0)
    params_b = _init_params(cfg, seed=1)
    _save_train_checkpoint(tmp_path, "pub", 20, params_b)
    Publisher(str(tmp_path), "pub").publish(20)

    # corrupt AFTER the publish committed (the publish_corrupt shape)
    step_dir = tmp_path / "checkpoint_pub" / "20"
    victim = next(p for p in sorted((step_dir / "state").rglob("*"))
                  if p.is_file() and p.stat().st_size > 0)
    raw = bytearray(victim.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    victim.write_bytes(bytes(raw))

    engine = InferenceEngine(cfg, params_a, slots=2, max_len=48)
    engine.restored_step = 0
    sched = Scheduler(engine)
    reloader = HotReloader(engine, sched, cfg, str(tmp_path))
    watcher = PointerWatcher(str(tmp_path))

    leaf_before = np.asarray(
        next(iter(jax_leaves(engine.params))))  # snapshot one weight
    assert reloader.maybe_reload(watcher.poll()) is False
    assert reloader.rejects == 1 and reloader.reloads == 0
    assert engine.restored_step == 0
    assert sched.admission_open
    np.testing.assert_array_equal(
        np.asarray(next(iter(jax_leaves(engine.params)))), leaf_before)
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("weights_reload_rejected") == 1
    assert kinds.count("weights_reload") == 0
    # the rejected publish is not re-offered on the next poll
    assert watcher.poll() is None

    # serving still works end-to-end on the current weights
    sched.submit(_greedy_request("r", [5, 9, 2], 4))
    done = _run_to_completion(sched)
    assert len(done["r"]) == 4


def jax_leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


def test_engine_reload_rejects_mismatched_trees():
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine,
    )

    cfg = _tiny_cfg()
    engine = InferenceEngine(cfg, _init_params(cfg, seed=0), slots=1,
                             max_len=32)
    bigger = _tiny_cfg(vocab=96)
    with pytest.raises(ValueError, match="does not match"):
        engine.reload_params(_init_params(bigger, seed=1))
    with pytest.raises(ValueError, match="without a draft"):
        engine.reload_draft_params(_init_params(cfg, seed=1))


# ------------------------------------------------------------ adaptive width
def test_adaptive_k_stays_in_bounds_on_any_observation_sequence():
    from fault_tolerant_llm_training_tpu.inference.sampler import AdaptiveK

    ak = AdaptiveK(k_max=8)
    assert ak.rungs == (1, 2, 4, 8)
    rng = np.random.default_rng(0)
    for _ in range(500):
        k = int(rng.integers(1, 9))
        ak.observe("r", int(rng.integers(0, k + 1)), k)
        assert 1 <= ak.target_k("r") <= 8
        assert ak.target_k("r") in ak.rungs


def test_adaptive_k_walks_down_under_rejection_and_resets_optimistic():
    from fault_tolerant_llm_training_tpu.inference.sampler import AdaptiveK

    ak = AdaptiveK(k_max=8)
    assert ak.target_k("r") == 8, "no evidence -> optimistic"
    for _ in range(10):
        ak.observe("r", 0, 8)  # stale draft: nothing accepted
    assert ak.target_k("r") == 1, "full rejection degrades to plain decode"
    for _ in range(20):
        ak.observe("r", 8, 8)  # perfect acceptance recovers
    assert ak.target_k("r") == 8
    ak.observe("other", 0, 8)
    assert ak.round_k(["r", "other"]) == 1, "least-accepting stream rules"
    assert ak.round_k([]) == 8
    ak.reset()  # fresh draft installed
    assert ak.target_k("other") == 8
    ak.observe("gone", 0, 8)
    ak.forget("gone")
    assert ak.target_k("gone") == 8


def test_adaptive_k_validates_construction():
    from fault_tolerant_llm_training_tpu.inference.sampler import AdaptiveK

    with pytest.raises(ValueError):
        AdaptiveK(k_max=0)
    with pytest.raises(ValueError):
        AdaptiveK(k_max=4, decay=1.0)
    assert AdaptiveK(k_max=1).rungs == (1,)


def test_adaptive_spec_rounds_stream_matches_fixed_width(tmp_path):
    """Numerics guard for the compiled-ladder path: a greedy spec stream
    under the adaptive controller emits the same tokens as the fixed-width
    engine — narrower rounds change the proposal batching, not the
    accepted argmax chain."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine,
    )
    from fault_tolerant_llm_training_tpu.inference.sampler import AdaptiveK
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    cfg = _tiny_cfg()
    params = _init_params(cfg, seed=0)
    draft_params = _init_params(cfg, seed=3)
    prompt = [5, 9, 2, 14, 7]

    def stream(adaptive):
        engine = InferenceEngine(cfg, params, slots=2, max_len=48,
                                 draft_cfg=cfg,
                                 draft_params=draft_params, spec_k=4)
        sched = Scheduler(engine, adaptive_k=adaptive)
        sched.submit(_greedy_request("r", prompt, 10))
        return _run_to_completion(sched)["r"]

    fixed = stream(None)
    adaptive = stream(AdaptiveK(k_max=4))
    assert adaptive == fixed
