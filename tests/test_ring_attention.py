"""Ring attention == reference attention, on a real multi-device sequence
axis (8-device CPU mesh)."""

import jax
import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.ops.attention import xla_attention
from fault_tolerant_llm_training_tpu.ops.ring_attention import (
    ring_attention,
    zigzag_perm,
)
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh


def _qkv(b=2, s=64, h=4, kv=2, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    return q, k, v


def test_ring_matches_reference_sp4(eight_devices):
    q, k, v = _qkv()
    want = xla_attention(q, k, v, causal=True)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_ring_matches_reference_sp8_gqa(eight_devices):
    q, k, v = _qkv(b=1, s=128, h=8, kv=2, d=8, seed=3)
    want = xla_attention(q, k, v, causal=True)
    mesh = make_mesh(dp=1, sp=8)
    with use_mesh(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_perm_is_permutation():
    perm = zigzag_perm(64, 4)
    assert sorted(perm.tolist()) == list(range(64))
    # shard 0 of 4 holds chunks 0 and 7 (of 8): positions 0-7 then 56-63
    np.testing.assert_array_equal(perm[:16],
                                  list(range(8)) + list(range(56, 64)))


def test_zigzag_ring_matches_reference_sp4(eight_devices):
    q, k, v = _qkv()
    want = xla_attention(q, k, v, causal=True)
    perm = zigzag_perm(q.shape[1], 4)
    inv = np.argsort(perm)
    mesh = make_mesh(dp=2, sp=4)
    with use_mesh(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, zigzag=True))(
            q[:, perm], k[:, perm], v[:, perm])
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_ring_matches_reference_sp8_gqa(eight_devices):
    q, k, v = _qkv(b=1, s=128, h=8, kv=2, d=8, seed=3)
    want = xla_attention(q, k, v, causal=True)
    perm = zigzag_perm(q.shape[1], 8)
    inv = np.argsort(perm)
    mesh = make_mesh(dp=1, sp=8)
    with use_mesh(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, zigzag=True))(
            q[:, perm], k[:, perm], v[:, perm])
    np.testing.assert_allclose(np.asarray(got)[:, inv], np.asarray(want),
                               rtol=2e-4, atol=2e-5)


def test_zigzag_ring_gradients_match(eight_devices):
    q, k, v = _qkv(b=1, s=64, h=2, kv=2, d=8, seed=5)
    perm = zigzag_perm(q.shape[1], 4)
    inv = np.argsort(perm)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    def loss_zz(qp, kp, vp):
        return jnp.sum(ring_attention(qp, kp, vp, zigzag=True) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    mesh = make_mesh(dp=1, sp=4)
    with use_mesh(mesh):
        g_zz = jax.jit(jax.grad(loss_zz, argnums=(0, 1, 2)))(
            q[:, perm], k[:, perm], v[:, perm])
    for a, b in zip(g_ref, g_zz):
        np.testing.assert_allclose(np.asarray(b)[:, inv], np.asarray(a),
                                   rtol=5e-4, atol=5e-5)


def test_flash_ring_matches_xla_ring_both_layouts(eight_devices):
    """The Pallas carry-kernel ring (default) and the plain-einsum ring
    (impl='xla') are independent implementations of the same math — they
    must agree tightly (both accumulate in fp32)."""
    mesh = make_mesh(dp=1, sp=4)
    for zigzag in (False, True):
        q, k, v = _qkv(b=1, s=128, h=4, kv=2, d=16, seed=7)
        if zigzag:
            perm = zigzag_perm(q.shape[1], 4)
            q, k, v = q[:, perm], k[:, perm], v[:, perm]
        with use_mesh(mesh):
            flash = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, zigzag=zigzag, impl="flash"))(q, k, v)
            xla = jax.jit(lambda q, k, v: ring_attention(
                q, k, v, zigzag=zigzag, impl="xla"))(q, k, v)
        np.testing.assert_allclose(np.asarray(flash), np.asarray(xla),
                                   rtol=1e-5, atol=1e-6)


def test_flash_ring_bf16(eight_devices):
    """bf16 inputs (the production dtype) through the carry kernels."""
    q, k, v = _qkv(b=1, s=128, h=4, kv=2, d=16, seed=11)
    want = xla_attention(q, k, v, causal=True)
    mesh = make_mesh(dp=1, sp=4)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    with use_mesh(mesh):
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v))(qb, kb, vb)
    np.testing.assert_allclose(np.asarray(got, dtype=np.float32),
                               np.asarray(want), rtol=5e-2, atol=5e-2)


def test_ring_gradients_match(eight_devices):
    q, k, v = _qkv(b=1, s=64, h=2, kv=2, d=8, seed=5)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v) ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    mesh = make_mesh(dp=1, sp=4)
    with use_mesh(mesh):
        g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ref, g_ring):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-5)
