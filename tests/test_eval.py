"""Held-out evaluation: the eval step's aggregation math, determinism, and
the CLI wiring (--eval-dataset / --eval-frequency / --eval-batches).

No reference counterpart (SURVEY.md §5.5: training loss is the reference's
only metric) — this is a beyond-parity subsystem, so the tests pin down our
own contract: token-weighted mean NLL over a deterministic held-out pass.
"""

import re

import jax
import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.training.step import (
    cross_entropy_loss,
    make_eval_step,
)

from test_fault_tolerance import (  # reuse the CLI harness + data fixture
    _args,
    _run,
    parquet,  # noqa: F401  (imported fixtures register in this module)
)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _model_and_batch(seed=0):
    cfg = get_config("tiny", attention_impl="xla", **FP32)
    model = Transformer(cfg)
    rng = np.random.default_rng(seed)
    toks = rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels = np.concatenate(
        [toks[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
    # mask a few extra labels so num_valid != B*S (exercises the weighting)
    labels[0, :5] = -100
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32), jnp.int32))["params"]
    return model, params, jnp.asarray(toks), jnp.asarray(labels)


def test_eval_step_matches_loss_times_tokens():
    model, params, toks, labels = _model_and_batch()
    packed = jax.jit(make_eval_step(model))(params, toks, labels)
    logits = model.apply({"params": params}, toks)
    loss, n = cross_entropy_loss(logits, labels)
    np.testing.assert_allclose(float(packed[0]), float(loss) * float(n),
                               rtol=1e-6)
    assert float(packed[1]) == float(n) == 57  # 2*32 - 2 shifts - 5 masked


def test_eval_step_grad_accum_slices_match_full_batch():
    """grad_accum > 1 runs eval through the same lax.scan slicing as the
    train step (activation footprint parity — ADVICE round 1); the packed
    (sum_nll, num_valid) must be identical to the one-shot eval."""
    model, params, toks, labels = _model_and_batch()
    full = jax.jit(make_eval_step(model))(params, toks, labels)
    sliced = jax.jit(make_eval_step(model, grad_accum=2))(
        params, toks, labels)
    np.testing.assert_allclose(np.asarray(sliced), np.asarray(full),
                               rtol=1e-6)


def test_eval_step_is_deterministic():
    model, params, toks, labels = _model_and_batch()
    f = jax.jit(make_eval_step(model))
    a = np.asarray(f(params, toks, labels))
    b = np.asarray(f(params, toks, labels))
    np.testing.assert_array_equal(a, b)


def _eval_lines(out):
    return re.findall(
        r"Eval \| step (\d+) \| loss ([\d.]+) \| ppl ([\d.]+)", out)


def test_cli_eval_frequency(tmp_path, parquet):
    rc, out = _run(_args(tmp_path, parquet, **{"--eval-frequency": 10,
                                               "--eval-batches": 2}),
                   job_id="e0")
    assert rc == 0, out
    lines = _eval_lines(out)
    # steps 10, 20, 30; no duplicate final eval (30 % 10 == 0)
    assert [int(s) for s, *_ in lines] == [10, 20, 30], out
    for _, loss, ppl in lines:
        assert np.isfinite(float(loss)) and np.isfinite(float(ppl))


def test_cli_eval_is_deterministic_and_final_eval_fires(tmp_path, parquet):
    """Same params -> same eval loss: step 12 is past the final train step
    of a 12-step run, exercising the trailing off-boundary eval; two runs
    with identical seeds must report identical eval losses."""
    args = _args(tmp_path / "a", parquet,
                 **{"--eval-frequency": 7, "--eval-batches": 2,
                    "--training-steps": 12})
    rc, out1 = _run(args, job_id="e1")
    assert rc == 0, out1
    steps = [int(s) for s, *_ in _eval_lines(out1)]
    assert steps == [7, 12], out1  # in-loop at 7, trailing final at 12
    rc, out2 = _run(_args(tmp_path / "b", parquet,
                          **{"--eval-frequency": 7, "--eval-batches": 2,
                             "--training-steps": 12}), job_id="e2")
    assert rc == 0, out2
    assert _eval_lines(out1) == _eval_lines(out2)


def test_cli_separate_eval_dataset(tmp_path, parquet, tiny_parquet):
    """--eval-dataset points evaluation at a different file than --dataset."""
    rc, out = _run(_args(tmp_path, parquet,
                         **{"--eval-frequency": 15, "--eval-batches": 2,
                            "--eval-dataset": str(tiny_parquet)}),
                   job_id="e3")
    assert rc == 0, out
    assert len(_eval_lines(out)) == 2  # steps 15 and 30


def test_cli_eval_holdout_is_automatic(tmp_path, parquet):
    """Without --eval-dataset the first batch*eval_batches rows are carved
    out of training automatically (VERDICT r4 weak #6) — the run announces
    the holdout and completes; the old train/eval-overlap warning is gone."""
    rc, out = _run(_args(tmp_path, parquet, **{"--eval-frequency": 10,
                                               "--eval-batches": 2}),
                   job_id="eh0")
    assert rc == 0, out
    assert "Eval holdout: first 4 corpus rows reserved" in out, out
    assert "eval loss can look optimistically low" not in out
    assert len(_eval_lines(out)) == 3  # steps 10, 20, 30


def test_cli_eval_holdout_resume_guard(tmp_path, parquet):
    """Resuming with a different holdout (here: none) must fail loudly —
    the training-row mapping would silently shift otherwise."""
    rc, out = _run(_args(tmp_path, parquet,
                         **{"--eval-frequency": 10, "--eval-batches": 2,
                            "--raise-error": "", "--error-step": 12}),
                   job_id="eh1")
    assert rc == 0, out
    assert "Checkpoint saved at step 13" in out, out
    # same holdout: resumes
    rc, out = _run(_args(tmp_path, parquet,
                         **{"--eval-frequency": 10, "--eval-batches": 2,
                            "--checkpoint-id": "eh1"}), job_id="eh2")
    assert rc == 0, out
    assert "Resuming training from training_step 13" in out, out
    # no holdout: the restore raises and routes to the exit handler
    rc, out = _run(_args(tmp_path, parquet, **{"--checkpoint-id": "eh1"}),
                   job_id="eh3")
    assert "saved with an eval holdout of 4 rows" in out, out
