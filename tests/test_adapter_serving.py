"""Multi-tenant LoRA adapter serving (inference/adapters.py + the fused
adapter lane through engine/scheduler, deploy/publish.py sub-pointers).

Evidence ladder:

1. pool — adapter pages ride the SAME BlockAllocator discipline as KV
   blocks: page 0 is the reserved null page, exhaustion queues instead of
   crashing, cold adapters evict under pressure and reload CRC-verified,
   double-frees fail loudly;
2. engine/scheduler — K concurrent streams on K DIFFERENT adapters,
   batched through ONE decode dispatch per round, BIT-MATCH K sequential
   single-adapter runs, and the null adapter '' bit-matches an engine
   built with no adapter lane at all (adapter_rank=0);
3. integrity — a corrupt adapter artifact is rejected at page-in
   (request completes with reason ``adapter_rejected``), the pool and the
   base params untouched; verify_pointer rejects a publish whose adapter
   sub-pointer names flipped bytes;
4. hot swap — a new adapter version swapped mid-stream (the deploy
   reload path's mgr.swap) leaves the in-flight stream bit-exact on the
   version it pinned while requests admitted after the swap serve the new
   version.
"""

import json
import os

import numpy as np
import pytest


def _tiny_cfg(vocab=64, seq_len=64):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


def _init_params(cfg, seed=0):
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    model = Transformer(cfg)
    return model.init(jax.random.PRNGKey(seed),
                      jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]


@pytest.fixture(scope="module")
def cfg_params():
    cfg = _tiny_cfg()
    return cfg, _init_params(cfg)


def _engine(cfg, params, rank=4, pages=0, slots=3):
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)

    return InferenceEngine(cfg, params, slots=slots, max_len=32,
                           prefill_buckets=(8, 16), kv_layout="paged",
                           kv_block_size=8, adapter_rank=rank,
                           adapter_num_pages=pages)


def _write_adapter(root, layout, name, seed, step=1, alpha=32.0,
                   scale=0.5):
    from fault_tolerant_llm_training_tpu.inference.adapters import (
        init_adapter_factors, write_adapter_artifact)

    factors = init_adapter_factors(layout, seed=seed, scale=scale)
    ent = write_adapter_artifact(str(root), name, step, factors,
                                 rank=layout.rank, alpha=alpha)
    return os.path.join(str(root), ent["path"])


def _request(rid, prompt, n, adapter=""):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    return Request(id=rid, prompt=prompt, max_new_tokens=n,
                   adapter=adapter)


def _serve(engine, arts, reqs):
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Scheduler)

    for name, art_dir in arts.items():
        engine.adapters.register(name, art_dir)
    sched = Scheduler(engine, eos_token_id=None)
    for r in reqs:
        sched.submit(r)
    done = sched.run()
    engine.reset()
    return {c.request_id: c.tokens for c in done}, sched


# ------------------------------------------------------------------ 1. pool
def test_adapter_pool_reuses_block_allocator_discipline(cfg_params,
                                                        tmp_path):
    cfg, params = cfg_params
    eng = _engine(cfg, params)
    mgr = eng.adapters
    per = mgr.layout.pages_per_adapter
    art = _write_adapter(tmp_path, mgr.layout, "ta", seed=1)
    mgr.register("ta", art)

    assert not mgr.resident("ta")
    assert mgr.resident("")  # the null adapter is always servable
    assert mgr.page_in("ta")
    assert mgr.resident_pages() == per
    # pages came from the allocator, page 0 (null) never handed out
    rec_rows = mgr.acquire("ta", 0)[0]
    assert 0 not in set(int(r) for r in rec_rows)
    # double free fails loudly, same contract as the KV pools
    mgr.release(0)
    pages = list(rec_rows)
    mgr.evict("ta")
    with pytest.raises(ValueError, match="double free"):
        mgr.allocator.free([int(pages[0])])


def test_combined_footprint_eviction_under_pressure(cfg_params, tmp_path):
    """Pool sized for ONE resident adapter: the second tenant's request
    queues behind page-in while the first is pinned, then evicts the cold
    adapter once it drains — everything completes, nothing crashes, and
    the stream served after the evict/reload cycle is still bit-exact."""
    cfg, params = cfg_params
    eng = _engine(cfg, params)
    layout = eng._adapter_layout
    per = layout.pages_per_adapter
    arts = {"ta": _write_adapter(tmp_path, layout, "ta", seed=1),
            "tb": _write_adapter(tmp_path, layout, "tb", seed=2)}

    # room for exactly one adapter beside the null page
    eng_small = _engine(cfg, params, pages=per + 1)
    reqs = [_request("r0", [1, 2, 3], 6, adapter="ta"),
            _request("r1", [4, 5, 6], 6, adapter="tb")]
    conc, sched = _serve(eng_small, arts, reqs)
    m = sched.metrics()
    assert set(conc) == {"r0", "r1"}
    assert m["adapter_evictions"] >= 1  # ta evicted to make room for tb
    assert m["adapter_pageins"] >= 2
    assert m["adapter_rejects"] == 0
    assert m["adapter_waits"] >= 1  # r1 queued behind the busy pool

    # sequential reference runs on a roomy pool: eviction+reload must not
    # have perturbed either stream
    for r in reqs:
        one, _ = _serve(_engine(cfg, params), arts,
                        [_request(r.id, list(r.prompt), 6,
                                  adapter=r.adapter)])
        assert one[r.id] == conc[r.id]


def test_scheduler_admission_validates_adapters(cfg_params):
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Scheduler)

    cfg, params = cfg_params
    eng = _engine(cfg, params)
    sched = Scheduler(eng, eos_token_id=None)
    with pytest.raises(ValueError, match="unregistered adapter"):
        sched.submit(_request("r0", [1, 2], 4, adapter="ghost"))
    eng.reset()

    eng0 = _engine(cfg, params, rank=0)
    sched0 = Scheduler(eng0, eos_token_id=None)
    with pytest.raises(ValueError, match="adapter_rank=0"):
        sched0.submit(_request("r0", [1, 2], 4, adapter="ta"))


# ------------------------------------------------- 2. batched heterogeneous
def test_heterogeneous_batch_bitmatches_sequential(cfg_params, tmp_path):
    """Three slots serving three DIFFERENT adapters (one of them the null
    adapter) in the same fused decode dispatches must produce streams
    bitwise identical to three sequential single-adapter runs — and the
    null stream must bit-match an engine built without the adapter lane."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Scheduler)

    cfg, params = cfg_params
    eng = _engine(cfg, params)
    layout = eng._adapter_layout
    arts = {"ta": _write_adapter(tmp_path, layout, "ta", seed=10),
            "tb": _write_adapter(tmp_path, layout, "tb", seed=11)}
    reqs = [_request("r0", [1, 2, 3], 6, adapter="ta"),
            _request("r1", [4, 5, 6], 6, adapter="tb"),
            _request("r2", [7, 8, 9], 6, adapter="")]

    conc, sched = _serve(eng, arts, reqs)
    m = sched.metrics()
    assert sorted(m["adapters_resident"]) == ["ta", "tb"]
    assert m["adapters_served"] == 2

    for r in reqs:
        one, _ = _serve(_engine(cfg, params), arts,
                        [_request(r.id, list(r.prompt), 6,
                                  adapter=r.adapter)])
        assert one[r.id] == conc[r.id], (
            f"{r.id} ({r.adapter or 'null'}) diverged from its "
            f"sequential single-adapter run")

    # adapter-0 == no-adapter baseline, bitwise
    eng_base = _engine(cfg, params, rank=0)
    sched_base = Scheduler(eng_base, eos_token_id=None)
    sched_base.submit(_request("r2", [7, 8, 9], 6))
    base = {c.request_id: c.tokens for c in sched_base.run()}
    assert base["r2"] == conc["r2"], (
        "the null adapter must be bit-identical to adapter_rank=0")


# --------------------------------------------------------------- 3. integrity
def _corrupt_one_factor(art_dir):
    victim = sorted(f for f in os.listdir(art_dir)
                    if f.endswith(".npy"))[0]
    path = os.path.join(art_dir, victim)
    with open(path, "r+b") as fh:
        fh.seek(-1, os.SEEK_END)
        byte = fh.read(1)
        fh.seek(-1, os.SEEK_END)
        fh.write(bytes([byte[0] ^ 0xFF]))


def test_corrupt_adapter_rejected_pool_and_params_untouched(cfg_params,
                                                            tmp_path):
    cfg, params = cfg_params
    eng = _engine(cfg, params)
    layout = eng._adapter_layout
    arts = {"ta": _write_adapter(tmp_path, layout, "ta", seed=10),
            "evil": _write_adapter(tmp_path, layout, "evil", seed=66)}
    _corrupt_one_factor(arts["evil"])

    reqs = [_request("r0", [1, 2, 3], 6, adapter="evil"),
            _request("r1", [4, 5, 6], 6, adapter="ta")]
    done, sched = _serve(eng, arts, reqs)
    m = sched.metrics()
    # the corrupt tenant is REJECTED (no tokens), never paged in; the
    # healthy tenant on the same pool serves normally
    assert done["r0"] == []
    assert m["adapter_rejects"] == 1
    assert m["adapters_resident"] == ["ta"]
    by_id = {c.request_id: c for c in sched.completed}
    assert by_id["r0"].reason == "adapter_rejected"
    assert len(done["r1"]) == 6

    # ... and r1's stream equals a run where the corrupt artifact never
    # existed — the rejected page-in left pool AND params untouched
    clean, _ = _serve(_engine(cfg, params),
                      {"ta": arts["ta"]},
                      [_request("r1", [4, 5, 6], 6, adapter="ta")])
    assert clean["r1"] == done["r1"]


def test_verify_pointer_rejects_corrupt_adapter_publish(tmp_path):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        write_manifest)
    from fault_tolerant_llm_training_tpu.deploy.publish import (
        Publisher, adapter_pointer, verify_pointer)
    from fault_tolerant_llm_training_tpu.inference.adapters import (
        AdapterLayout)

    # a fake manifested checkpoint step for the main pointer target
    step_dir = tmp_path / "checkpoint_pub" / "20"
    step_dir.mkdir(parents=True)
    (step_dir / "payload.bin").write_bytes(b"weights" * 64)
    write_manifest(str(step_dir), 20)

    layout = AdapterLayout.from_cfg(_tiny_cfg(), 4)
    art = _write_adapter(tmp_path, layout, "ta", seed=3)
    sub = adapter_pointer(str(tmp_path), "ta", art)
    assert sub is not None and sub["rank"] == 4

    pub = Publisher(str(tmp_path), "pub")
    ptr = pub.publish(20, adapters={"ta": sub})
    assert ptr is not None
    assert verify_pointer(str(tmp_path), ptr) == (True, "ok")
    # published.json carries the tenant -> adapter map
    with open(tmp_path / "published.json") as fh:
        assert "ta" in json.load(fh)["adapters"]

    _corrupt_one_factor(art)
    ok, detail = verify_pointer(str(tmp_path), ptr)
    assert not ok and "adapter ta" in detail


# ----------------------------------------------------------------- 4. hot swap
def test_hot_swap_midstream_preserves_inflight_slots(cfg_params, tmp_path):
    """Swap a NEW version of an adapter in mid-decode (what the deploy
    reload does inside its prefill-pause): the in-flight stream must keep
    decoding the version it pinned, bit-exact end to end, while a request
    admitted after the swap serves the new version."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Scheduler)

    cfg, params = cfg_params
    eng = _engine(cfg, params)
    layout = eng._adapter_layout
    art_v1 = _write_adapter(tmp_path / "v1", layout, "ta", seed=10,
                            step=1)
    art_v2 = _write_adapter(tmp_path / "v2", layout, "ta", seed=99,
                            step=2, scale=0.7)

    # reference streams: all-v1 and all-v2 sequential runs
    ref_v1, _ = _serve(_engine(cfg, params), {"ta": art_v1},
                       [_request("r0", [1, 2, 3], 8, adapter="ta")])
    ref_v2, _ = _serve(_engine(cfg, params), {"ta": art_v2},
                       [_request("r1", [4, 5, 6], 6, adapter="ta")])
    assert ref_v1["r0"][:6] != ref_v2["r1"]  # the versions really differ

    eng.adapters.register("ta", art_v1)
    sched = Scheduler(eng, eos_token_id=None)
    sched.submit(_request("r0", [1, 2, 3], 8, adapter="ta"))
    for _ in range(3):  # r0 prefills and decodes a few tokens on v1
        sched.step()
    assert eng.adapters.active_slots().get("ta", 0) == 1

    assert eng.adapters.swap("ta", art_v2)  # both versions now resident
    sched.submit(_request("r1", [4, 5, 6], 6, adapter="ta"))
    done = {c.request_id: c.tokens for c in sched.run()}

    assert done["r0"] == ref_v1["r0"], (
        "the in-flight slot must finish on the version it pinned")
    assert done["r1"] == ref_v2["r1"], (
        "a request admitted after the swap must serve the new version")
    # the drained v1 pages were reclaimed — no stale-version leak
    assert eng.adapters.stats()["stale_versions"] == 0
    sched.audit_block_leaks(strict=True)
