"""The scan-form trunk (layer_impl="scan") computes the identical function
as the reference-shaped loop form — one XLA-compiled block body over
layer-stacked params instead of n_layers unrolled blocks.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.models.llama import (
    stack_layer_params,
    unstack_layer_params,
)
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.sharding import param_pspecs
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    make_optimizer,
    make_train_step,
)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, attention_impl="xla")


def _setup(seed=0):
    cfg = get_config("tiny", **FP32)
    loop_model = Transformer(cfg)
    scan_model = Transformer(cfg.replace(layer_impl="scan"))
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    loop_params = loop_model.init(jax.random.PRNGKey(1),
                                  jnp.asarray(tokens))["params"]
    return cfg, loop_model, scan_model, loop_params, tokens


def test_scan_param_layout_and_roundtrip():
    cfg, loop_model, scan_model, loop_params, tokens = _setup()
    scan_init = scan_model.init(jax.random.PRNGKey(1),
                                jnp.asarray(tokens))["params"]
    stacked = stack_layer_params(loop_params, cfg.n_layers)
    # same tree structure and shapes as a native scan init
    a = jax.tree_util.tree_structure(scan_init)
    b = jax.tree_util.tree_structure(stacked)
    assert a == b
    wq = stacked["layers"]["block"]["attention"]["wq"]["kernel"]
    assert wq.shape[0] == cfg.n_layers
    back = unstack_layer_params(stacked, cfg.n_layers)
    for x, y in zip(jax.tree_util.tree_leaves(loop_params),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# Loop and scan bodies compile separately, so XLA's fusion choices differ
# at the last-ulp level (~1e-6 relative on fp32; the positions plumbing is
# bitwise identical — verified against the table path). Tolerances reflect
# that compile-level noise, not an algorithmic difference.
def test_scan_logits_match_loop():
    cfg, loop_model, scan_model, loop_params, tokens = _setup()
    stacked = stack_layer_params(loop_params, cfg.n_layers)
    want = loop_model.apply({"params": loop_params}, jnp.asarray(tokens))
    got = scan_model.apply({"params": stacked}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_scan_remat_logits_match_loop():
    cfg, loop_model, scan_model, loop_params, tokens = _setup(seed=4)
    stacked = stack_layer_params(loop_params, cfg.n_layers)
    remat_model = Transformer(cfg.replace(layer_impl="scan", remat=True))
    want = loop_model.apply({"params": loop_params}, jnp.asarray(tokens))
    got = remat_model.apply({"params": stacked}, jnp.asarray(tokens))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_scan_train_step_matches_loop():
    """One full train step (loss, grads through the scanned trunk, AdamW
    update) from identical weights gives identical metrics and an
    equivalent updated state."""
    cfg, loop_model, scan_model, loop_params, tokens = _setup(seed=2)
    labels = np.concatenate(
        [tokens[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
    opt = make_optimizer(1e-3, warmup_steps=2)

    def run(model, params):
        state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                           opt_state=opt.init(params))
        step = jax.jit(make_train_step(model, opt, 1.0))
        new_state, metrics = step(state, jnp.asarray(tokens),
                                  jnp.asarray(labels))
        return new_state, np.asarray(metrics["packed"])

    loop_state, loop_m = run(loop_model, loop_params)
    scan_state, scan_m = run(scan_model,
                             stack_layer_params(loop_params, cfg.n_layers))
    np.testing.assert_allclose(scan_m, loop_m, rtol=1e-6, atol=1e-7)
    # updated params agree layer-for-layer after unstacking
    back = unstack_layer_params(scan_state.params, cfg.n_layers)
    for x, y in zip(jax.tree_util.tree_leaves(loop_state.params),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-6, atol=1e-7)


def test_scan_params_shard_under_fsdp(eight_devices):
    """The path rules cover the 3-d scan leaves: embed dims still shard
    over fsdp with the leading layer axis replicated."""
    cfg = get_config("tiny", layer_impl="scan", **FP32)
    model = Transformer(cfg)
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                              jnp.zeros((1, 8), jnp.int32))
    specs = param_pspecs(abstract["params"])
    wq_spec = specs["layers"]["block"]["attention"]["wq"]["kernel"]
    # leading layer axis -> 'pipe' (size 1 here, so effectively replicated)
    assert wq_spec == jax.sharding.PartitionSpec("pipe", "fsdp", "tensor")
    mesh = make_mesh(dp=1, fsdp=8)
    with use_mesh(mesh):
        params = jax.jit(
            lambda k: model.init(k, jnp.zeros((1, 8), jnp.int32))["params"],
            out_shardings=jax.tree_util.tree_map(
                lambda s: NamedSharding(mesh, s), specs,
                is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec)),
        )(jax.random.PRNGKey(0))
    wq = params["layers"]["block"]["attention"]["wq"]["kernel"]
    shard = wq.sharding.shard_shape(wq.shape)
    assert shard[0] == cfg.n_layers  # layer axis replicated
    assert shard[1] == wq.shape[1] // 8  # embed dim sharded 8-way
