"""Checkpoint interop with the reference's torch format
(checkpoint/convert.py + scripts/convert_checkpoint.py).

The migration contract: a reference user's ``torch.save`` checkpoint
(ref: utils.py:74-81 — {model, optimizer, lr_scheduler, training_step})
converts losslessly into a TrainState and back, and training resumed from a
converted checkpoint is bit-exact with a native resume.
"""

import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.checkpoint.convert import (
    reference_param_names,
    state_from_torch_ckpt,
    state_to_torch_ckpt,
)
from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    make_optimizer,
    make_train_step,
)

from test_fault_tolerance import (  # reuse the CLI harness + data fixture
    REPO,
    _args,
    _env,
    _losses_by_step,
    _run,
    parquet,  # noqa: F401  (imported fixture registers in this module)
)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32, attention_impl="xla")


def _trained_state(n_steps=3):
    cfg = get_config("tiny", **FP32)
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 32), jnp.int32))["params"]
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    step_fn = jax.jit(make_train_step(model, opt, 1.0))
    rng = np.random.default_rng(3)
    for _ in range(n_steps):
        toks = rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
        labels = np.concatenate(
            [toks[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
        state, metrics = step_fn(state, jnp.asarray(toks), jnp.asarray(labels))
    return cfg, model, opt, state, step_fn


def test_name_map_matches_reference_layout():
    """Names, order, and orientation of the torch-side dict: registration
    order (AdamW's param indexing) and nn.Linear's (out, in) shapes."""
    cfg, model, opt, state, _ = _trained_state(n_steps=0)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3)
    names = [n for n, _, _ in reference_param_names(cfg.n_layers)]
    assert list(ckpt["model"]) == names  # exact registration order
    assert names[0] == "tok_embeddings.weight"
    assert names[1] == "layers.0.attention.wq.weight"
    assert names[-1] == "output.weight"
    # nn.Linear orientation: torch (out, in) == flax kernel (in, out).T
    wq_t = ckpt["model"]["layers.0.attention.wq.weight"]
    wq_f = state.params["layers_0"]["attention"]["wq"]["kernel"]
    assert wq_t.shape == wq_f.shape[::-1]
    np.testing.assert_array_equal(wq_t.T, np.asarray(wq_f))
    # w1 is non-square (64 -> 192 in the tiny preset): transposition bugs
    # cannot hide behind symmetric shapes
    w1 = ckpt["model"]["layers.0.feed_forward.w1.weight"]
    assert w1.shape[0] != w1.shape[1]
    # optimizer indices cover every param in order, with per-param step
    opt_sd = ckpt["optimizer"]
    assert sorted(opt_sd["state"]) == list(range(len(names)))
    assert opt_sd["param_groups"][0]["params"] == list(range(len(names)))
    assert ckpt["lr_scheduler"]["last_epoch"] == 0


def test_export_carries_warmup_scaled_lr():
    """Mid-warmup export must hold the *current* scaled lr (what a native
    torch checkpoint stores), not the base rate — LambdaLR semantics:
    factor = (step+1)/(warmup+1)."""
    cfg, model, opt, state, _ = _trained_state(n_steps=3)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3, warmup_steps=10)
    want = 1e-3 * (3 + 1) / (10 + 1)
    assert ckpt["optimizer"]["param_groups"][0]["lr"] == pytest.approx(want)
    assert ckpt["lr_scheduler"]["_last_lr"] == [pytest.approx(want)]
    assert ckpt["lr_scheduler"]["base_lrs"] == [1e-3]
    # past warmup the scaled rate equals the base rate
    late = state.replace(step=jnp.asarray(50, jnp.int32))
    ckpt = state_to_torch_ckpt(late, cfg.n_layers, 1e-3, warmup_steps=10)
    assert ckpt["optimizer"]["param_groups"][0]["lr"] == pytest.approx(1e-3)


def test_scan_form_state_round_trips_through_torch_format():
    """A scan-trained state (layer-stacked params) exports through the
    reference's per-layer layout and re-imports into either trunk form."""
    from fault_tolerant_llm_training_tpu.models.llama import (
        stack_layer_params,
    )

    cfg, loop_model, opt, loop_state, _ = _trained_state(n_steps=2)
    scan_model = Transformer(cfg.replace(layer_impl="scan"))
    scan_state = loop_state.replace(
        params=stack_layer_params(loop_state.params, cfg.n_layers),
        opt_state=(
            loop_state.opt_state[0]._replace(
                mu=stack_layer_params(loop_state.opt_state[0].mu,
                                      cfg.n_layers),
                nu=stack_layer_params(loop_state.opt_state[0].nu,
                                      cfg.n_layers)),
        ) + loop_state.opt_state[1:])
    # scan export == loop export, key for key
    a = state_to_torch_ckpt(scan_state, cfg.n_layers, 1e-3)
    b = state_to_torch_ckpt(loop_state, cfg.n_layers, 1e-3)
    for k in b["model"]:
        np.testing.assert_array_equal(a["model"][k], b["model"][k])
    # import back as scan: matches the original scan state exactly
    back = state_from_torch_ckpt(a, scan_model, opt, jnp.float32)
    for x, y in zip(jax.tree_util.tree_leaves(scan_state),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_string_keyed_optimizer_state_accepted():
    """torch state keys may round-trip as strings (e.g. via JSON)."""
    cfg, model, opt, state, _ = _trained_state(n_steps=2)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3)
    ckpt["optimizer"]["state"] = {
        str(k): v for k, v in ckpt["optimizer"]["state"].items()}
    back = state_from_torch_ckpt(ckpt, model, opt, jnp.float32)
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_round_trip_is_bit_exact_and_resumes_identically():
    cfg, model, opt, state, step_fn = _trained_state(n_steps=3)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3)
    back = state_from_torch_ckpt(ckpt, model, opt, jnp.float32)
    assert int(back.step) == int(state.step) == 3
    for a, b in zip(jax.tree_util.tree_leaves(state),
                    jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the states are interchangeable: one more identical step from each
    rng = np.random.default_rng(9)
    toks = rng.integers(0, cfg.vocab_size, (2, 32)).astype(np.int32)
    labels = np.concatenate(
        [toks[:, 1:], np.full((2, 1), -100, np.int32)], axis=1)
    _, m1 = step_fn(state, jnp.asarray(toks), jnp.asarray(labels))
    _, m2 = step_fn(back, jnp.asarray(toks), jnp.asarray(labels))
    np.testing.assert_array_equal(np.asarray(m1["packed"]),
                                  np.asarray(m2["packed"]))


def test_moments_land_on_the_right_leaves():
    """Distinguishable exp_avg values must land on their matching flax
    leaves, transposed — catches index-order and orientation mix-ups."""
    cfg, model, opt, state, _ = _trained_state(n_steps=0)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3)
    names = [n for n, _, _ in reference_param_names(cfg.n_layers)]
    for i, name in enumerate(names):
        ckpt["optimizer"]["state"][i]["exp_avg"] = np.full_like(
            ckpt["optimizer"]["state"][i]["exp_avg"], float(i))
    back = state_from_torch_ckpt(ckpt, model, opt, jnp.float32)
    mu = back.opt_state[0].mu
    w1_idx = names.index("layers.0.feed_forward.w1.weight")
    got = np.asarray(mu["layers_0"]["feed_forward"]["w1"]["kernel"])
    assert got.shape == state.params["layers_0"]["feed_forward"]["w1"][
        "kernel"].shape
    np.testing.assert_array_equal(got, np.full_like(got, float(w1_idx)))


@pytest.mark.parametrize("wrong", ["missing_key", "bad_indices"])
def test_malformed_reference_checkpoint_fails_loudly(wrong):
    cfg, model, opt, state, _ = _trained_state(n_steps=0)
    ckpt = state_to_torch_ckpt(state, cfg.n_layers, 1e-3)
    if wrong == "missing_key":
        del ckpt["model"]["layers.1.ffn_norm.weight"]
        with pytest.raises(KeyError, match="ffn_norm"):
            state_from_torch_ckpt(ckpt, model, opt, jnp.float32)
    else:
        ckpt["optimizer"]["state"].pop(0)
        with pytest.raises(ValueError, match="param indices"):
            state_from_torch_ckpt(ckpt, model, opt, jnp.float32)


def _convert(cmd, tmp_path, **flags):
    argv = [sys.executable, str(REPO / "scripts" / "convert_checkpoint.py"),
            cmd, "--model", "tiny", "--vocab-size", "259",
            "--sequence-length", "128", "--learning-rate", "1e-3",
            "--lr-warmup-steps", "5"]
    for k, v in flags.items():
        argv += [k, str(v)]
    r = subprocess.run(argv, capture_output=True, text=True, env=_env(),
                       timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr
    return r.stdout


def test_cli_end_to_end_torch_round_trip(tmp_path, parquet):
    """train 10 steps -> Orbax ckpt -> torch .ckpt -> Orbax ckpt -> resume:
    the resumed run's losses are bit-exact with an uninterrupted run."""
    torch = pytest.importorskip("torch")
    ckpts = tmp_path / "ckpts"
    base_args = {"--checkpoint-path": str(ckpts), "--learning-rate": "1e-3",
                 "--lr-warmup-steps": "5"}
    # uninterrupted 20-step baseline
    rc, baseline = _run(_args(tmp_path, parquet, **dict(
        base_args, **{"--training-steps": 20})), job_id="cv_base")
    assert rc == 0, baseline
    # 10-step run that checkpoints at step 10
    rc, out = _run(_args(tmp_path, parquet, **dict(
        base_args, **{"--training-steps": 10,
                      "--checkpoint-frequency": 10})), job_id="cv1")
    assert rc == 0, out

    torch_file = tmp_path / "checkpoint_cv1.ckpt"
    _convert("to-torch", tmp_path, **{"--checkpoint-path": ckpts,
                                      "--job-id": "cv1",
                                      "--output": torch_file})
    ckpt = torch.load(torch_file, map_location="cpu", weights_only=False)
    assert ckpt["training_step"] == 10
    assert set(ckpt) == {"model", "optimizer", "lr_scheduler",
                         "training_step"}  # ref utils.py:75-80
    assert ckpt["model"]["tok_embeddings.weight"].dtype == torch.bfloat16
    assert "lr_lambdas" in ckpt["lr_scheduler"]  # LambdaLR schema

    _convert("to-tpu", tmp_path, **{"--input": torch_file,
                                    "--checkpoint-path": ckpts,
                                    "--job-id": "cv2", "--batch-size": 2})
    rc, resumed = _run(_args(tmp_path, parquet, **dict(
        base_args, **{"--training-steps": 20,
                      "--checkpoint-id": "cv2"})), job_id="cv3")
    assert rc == 0, resumed
    assert "Resuming training from training_step 10" in resumed
    base_losses = _losses_by_step(baseline)
    res_losses = _losses_by_step(resumed)
    steps = [str(s) for s in range(11, 20)]
    assert all(s in res_losses for s in steps), resumed
    assert [res_losses[s] for s in steps] == [base_losses[s] for s in steps]


def test_moe_state_rejected_with_clear_error():
    """MoE param trees cannot map to the reference's dense format; the
    converter must say so instead of dying on a missing key."""
    cfg = get_config("tiny-moe", dtype=jnp.float32, param_dtype=jnp.float32,
                     attention_impl="xla")
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, 16), jnp.int32))["params"]
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt.init(params))
    with pytest.raises(ValueError, match="MoE states"):
        state_to_torch_ckpt(state, cfg.n_layers, 1e-3)


def test_cli_converts_checkpoint_saved_on_sharded_mesh(tmp_path, parquet):
    """A checkpoint written by a dp=2 x fsdp=4 run (device-sharded arrays)
    converts to the torch format: the converter restores with explicit
    single-device shardings (regression: deserialization used to fail with
    'sharding should be specified' for any multi-device-saved state)."""
    torch = pytest.importorskip("torch")
    ckpts = tmp_path / "ckpts"
    rc, out = _run(_args(tmp_path, parquet, **{
        "--checkpoint-path": str(ckpts), "--batch-size": "8",
        "--training-steps": "8", "--checkpoint-frequency": "8",
        "--dp": "2", "--fsdp": "4"}), job_id="shcv", xla_devices=8)
    assert rc == 0, out
    out_file = tmp_path / "checkpoint_shcv.ckpt"
    _convert("to-torch", tmp_path, **{"--checkpoint-path": ckpts,
                                      "--job-id": "shcv",
                                      "--output": out_file})
    ckpt = torch.load(out_file, map_location="cpu", weights_only=False)
    assert ckpt["training_step"] == 8
