"""DevicePrefetcher unit tests (data/prefetch.py).

The multihost end-to-end tests exercise the consumer-thread staging mode
through the full trainer; these pin the contract down directly: staging
mode selection, state threading, exception surfacing, and stop().
"""

import numpy as np
import pytest

import jax

from fault_tolerant_llm_training_tpu.data.prefetch import DevicePrefetcher


class _StubLoader:
    def __init__(self, n=4, fail_at=None):
        self.n = n
        self.i = 0
        self.fail_at = fail_at
        self.resumed = False

    def resume(self):
        self.resumed = True

    def __next__(self):
        if self.fail_at is not None and self.i == self.fail_at:
            raise ValueError("boom")
        if self.i >= self.n:
            raise StopIteration
        i = self.i
        self.i += 1
        return (np.full((2, 4), i, np.int32), np.full((2, 4), -i, np.int32))

    def get_state(self):
        return {"index": self.i}


@pytest.mark.parametrize("stage_in_worker", [True, False])
def test_prefetcher_stages_and_threads_state(stage_in_worker):
    pf = DevicePrefetcher(_StubLoader(n=3), depth=2,
                          stage_in_worker=stage_in_worker)
    items = list(iter(pf))
    assert pf.loader.resumed
    assert len(items) == 3
    for i, (inputs, labels, state) in enumerate(items):
        # device arrays out in both modes; the staging just happens on a
        # different thread (stage_in_worker=False is the multi-process mode)
        assert isinstance(inputs, jax.Array) and isinstance(labels, jax.Array)
        assert int(inputs[0, 0]) == i and int(labels[0, 0]) == -i
        # the state snapshot matches the batch it was produced after
        assert state == {"index": i + 1}


def test_prefetcher_surfaces_worker_exception():
    pf = DevicePrefetcher(_StubLoader(n=5, fail_at=2), depth=2)
    it = iter(pf)
    next(it)
    next(it)
    with pytest.raises(ValueError, match="boom"):
        while True:
            next(it)


def test_prefetcher_stop_drains():
    pf = DevicePrefetcher(_StubLoader(n=100), depth=2)
    it = iter(pf)
    next(it)
    pf.stop()  # must not deadlock on a full queue
    assert pf._stop.is_set()
