"""Paged KV cache + chunked prefill (ops/attention.py, inference/).

Evidence ladder for the block-paged serving cache:

1. ops — ``paged_cached_attention`` over a scattered block pool BIT-MATCHES
   ``cached_attention`` over the contiguous layout, including when freed
   table entries point at a garbage-filled null block (masked positions
   contribute exact fp32 zeros, so stale blocks cannot leak);
2. allocator — exhaustion returns None (callers queue, never crash), block
   0 is never handed out, double-frees fail loudly;
3. engine — the paged engine's greedy AND sampled token streams equal the
   ring engine's over a mixed eviction/refill workload (same params, same
   seeds), chunked prefill is logit-identical to single-shot prefill (eager
   at the model level; compiled engine-vs-engine for the token stream — the
   two XLA regimes differ at bf16 so each is compared within its own), and
   the ring layout rejects the long prompt the pages now serve;
4. scheduler — admission by free-block count queues on pool exhaustion and
   still completes everything, blocks are freed exactly once on eviction,
   and a drain signal landing mid-chunked-prefill stops at a chunk
   boundary with the request reported unserved and its blocks returned;
5. packed prefill — with ``prefill_batch > 1`` the scheduler streams up to
   P pending requests' next chunks through ONE (P, bucket) dispatch per
   round: token streams are BITWISE identical to sequential one-at-a-time
   prefill (batch is a parallel GEMM dimension — per-row contraction
   shapes are unchanged), a drain landing mid-packed-prefill frees every
   pending row's blocks exactly once, and the lane's invariants are
   enforced (engine/scheduler width agreement, paged-only, no spec mode).
"""

import numpy as np
import pytest


def _tiny_cfg(vocab=64, seq_len=64):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


# --------------------------------------------------------------------- 1. ops
def test_paged_attention_bitmatches_contiguous():
    """Scatter a contiguous (B, K, T, D) cache into a shuffled block pool;
    the gathered attention must equal the contiguous attention bitwise."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.ops.attention import (
        cached_attention, gather_kv_blocks, paged_cached_attention)

    rng = np.random.default_rng(0)
    B, K, H, bs, NB, D = 2, 2, 4, 4, 4, 8
    T = NB * bs
    k = rng.standard_normal((B, K, T, D)).astype(np.float32)
    v = rng.standard_normal((B, K, T, D)).astype(np.float32)
    q = rng.standard_normal((B, 3, H, D)).astype(np.float32)
    offsets = np.array([5, T - 3], np.int32)

    # blocks 1..B*NB in shuffled order; block 0 stays garbage (null block)
    perm = rng.permutation(np.arange(1, B * NB + 1))
    tables = perm.reshape(B, NB).astype(np.int32)
    pool_k = rng.standard_normal((B * NB + 1, K, bs, D)).astype(np.float32)
    pool_v = rng.standard_normal((B * NB + 1, K, bs, D)).astype(np.float32)
    for b in range(B):
        for n in range(NB):
            pool_k[tables[b, n]] = k[b, :, n * bs:(n + 1) * bs]
            pool_v[tables[b, n]] = v[b, :, n * bs:(n + 1) * bs]

    np.testing.assert_array_equal(
        np.asarray(gather_kv_blocks(jnp.asarray(pool_k),
                                    jnp.asarray(tables))), k)
    ref = cached_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                           jnp.asarray(offsets))
    out = paged_cached_attention(jnp.asarray(q), jnp.asarray(pool_k),
                                 jnp.asarray(pool_v), jnp.asarray(tables),
                                 jnp.asarray(offsets))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))

    # free the blocks wholly beyond each slot's valid region: their table
    # entries fall back to the garbage null block, output must not move —
    # masked positions are exact zeros, stale content cannot leak
    tables2 = tables.copy()
    for b in range(B):
        first_dead = -(-(int(offsets[b]) + q.shape[1]) // bs)
        tables2[b, first_dead:] = 0
    out2 = paged_cached_attention(jnp.asarray(q), jnp.asarray(pool_k),
                                  jnp.asarray(pool_v), jnp.asarray(tables2),
                                  jnp.asarray(offsets))
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref))


def test_write_paged_kv_masks_invalid_positions():
    """Invalid (padding / inactive-slot) writes divert into null block 0;
    no allocated block is touched."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        write_paged_kv)

    K, bs, D = 2, 4, 3
    pool = jnp.zeros((4, K, bs, D), jnp.float32)
    new = jnp.ones((1, K, 6, D), jnp.float32)  # 6 positions, only 5 valid
    tables = jnp.asarray([[2, 3]], jnp.int32)
    valid = jnp.asarray([[True] * 5 + [False]])
    out = np.asarray(write_paged_kv(pool, new,
                                    tables, jnp.zeros((1,), jnp.int32),
                                    valid))
    assert out[2].sum() == bs * K * D          # block 2: positions 0..3
    assert out[3, :, 0, :].sum() == K * D      # block 3: position 4 only
    assert out[3, :, 1:, :].sum() == 0         # padding position diverted
    assert out[1].sum() == 0                   # unrelated block untouched


# --------------------------------------------------------------- 2. allocator
def test_block_allocator_contract():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)

    a = BlockAllocator(num_blocks=5)
    assert a.capacity == 4                     # block 0 reserved
    first = a.alloc(3)
    assert first is not None and 0 not in first
    assert a.alloc(2) is None                  # exhaustion queues...
    assert a.free_count == 1                   # ...and takes nothing
    rest = a.alloc(1)
    assert 0 not in rest and not (set(first) & set(rest))
    a.free(first)
    with pytest.raises(ValueError, match="double free"):
        a.free(first)
    a.free(rest)
    assert a.free_count == a.capacity


# ------------------------------------------------------------------ 3. engine
@pytest.fixture(scope="module")
def engines():
    """One param set, two layouts: paged (block_size 8, buckets 8/16) and
    ring (same buckets) over the same 32-position, 2-slot cache."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    paged = InferenceEngine(cfg, params, slots=2, max_len=32,
                            prefill_buckets=(8, 16), kv_layout="paged",
                            kv_block_size=8)
    # ring gets a 32 bucket so it can single-shot the prompt the paged
    # engine must chunk; for prompts <= 16 both engines pick the same bucket
    ring = InferenceEngine(cfg, params, slots=2, max_len=32,
                           prefill_buckets=(8, 16, 32), kv_layout="ring")
    return cfg, model, params, paged, ring


def _stream(engine, requests, eos=None):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    engine.reset()
    sched = Scheduler(engine, eos_token_id=eos)
    for r in requests:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def test_paged_stream_bitmatches_ring(engines):
    """Mixed greedy/sampled workload with slot eviction + refill: token
    streams must be identical across layouts, and every block must come
    home to the allocator afterwards."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    cfg, _, _, paged, ring = engines
    rng = np.random.default_rng(1)
    reqs = [Request(id=f"r{i}",
                    prompt=rng.integers(3, cfg.vocab_size, size=pl).tolist(),
                    max_new_tokens=gen, temperature=t, top_p=0.9, seed=i)
            for i, (pl, gen, t) in enumerate(
                [(6, 8, 0.0), (12, 10, 0.8), (16, 6, 0.0), (9, 12, 0.7)])]
    ring_sched, ring_out = _stream(ring, list(reqs))
    paged_sched, paged_out = _stream(paged, list(reqs))
    assert paged_out == ring_out
    assert len(paged_out) == 4
    # after drain the prefix cache legitimately retains committed prompt
    # blocks (one cache reference each); flushing must return ALL of them
    assert (paged_sched.allocator.used_count
            == paged_sched.prefix_cache.cached_blocks)
    paged_sched.prefix_cache.flush()
    assert paged_sched.allocator.free_count == paged_sched.allocator.capacity
    assert not paged_sched.block_tables.any()


def test_chunked_prefill_logits_bitmatch_single_shot(engines):
    """Model level, eager: feeding a 20-token prompt through the paged cache
    in two chunks (16 then 4) yields BITWISE the same last-chunk logits as
    one single-shot 20-token call, and both equal the uncached forward."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        init_paged_cache)

    cfg, model, params, _, _ = engines
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(3, cfg.vocab_size, size=(1, 20)),
                      jnp.int32)
    full = np.asarray(model.apply({"params": params}, ids))

    row = jnp.asarray([[1, 2, 3, 4]], jnp.int32)

    cache = init_paged_cache(cfg, 1, 32, 8)
    one_shot, _ = model.apply({"params": params}, ids, cache.k, cache.v,
                              jnp.zeros((1,), jnp.int32), block_tables=row,
                              method="forward_with_cache")
    np.testing.assert_array_equal(np.asarray(one_shot), full)

    cache = init_paged_cache(cfg, 1, 32, 8)
    c1, (k, v) = model.apply({"params": params}, ids[:, :16], cache.k,
                             cache.v, jnp.zeros((1,), jnp.int32),
                             block_tables=row, method="forward_with_cache")
    c2, _ = model.apply({"params": params}, ids[:, 16:], k, v,
                        jnp.full((1,), 16, jnp.int32), block_tables=row,
                        method="forward_with_cache")
    np.testing.assert_array_equal(np.asarray(c1),
                                  np.asarray(one_shot)[:, :16])
    np.testing.assert_array_equal(np.asarray(c2),
                                  np.asarray(one_shot)[:, 16:])


def test_chunked_prefill_stream_matches_ring_single_shot(engines):
    """Engine level, compiled: the paged engine CHUNKS a 20-token prompt
    (largest bucket 16), the ring engine single-shots it through its 32
    bucket — greedy continuations must be token-identical."""
    cfg, _, _, paged, ring = engines
    rng = np.random.default_rng(2)
    prompt = rng.integers(3, cfg.vocab_size, size=20).tolist()
    gen = 6
    zeros2 = np.zeros(2, np.float32)
    ones2 = np.ones(2, np.float32)
    izeros2 = np.zeros(2, np.int32)
    active = np.array([True, False])

    ring.reset()
    ring_got = [ring.prefill(0, prompt)]
    for step in range(1, gen):
        nxt = ring.decode_step(np.array([ring_got[-1], 0], np.int32),
                               active, zeros2, ones2, izeros2,
                               np.full(2, step, np.int32))
        ring_got.append(int(nxt[0]))

    paged.reset()
    row = np.arange(1, paged.max_blocks_per_slot + 1, dtype=np.int32)
    chunks = []
    first = paged.prefill(0, prompt, block_row=row,
                          on_chunk=lambda: chunks.append(1))
    assert len(chunks) == 2            # 16 + 4 (best-fit bucket 8)
    got = [first]
    tables = np.zeros((paged.slots, paged.max_blocks_per_slot), np.int32)
    tables[0] = row
    for step in range(1, gen):
        nxt = paged.decode_step(
            np.array([got[-1], 0], np.int32), active, zeros2, ones2,
            izeros2, np.full(2, step, np.int32), block_tables=tables)
        got.append(int(nxt[0]))
    assert got == ring_got


def test_long_prompt_served_paged_rejected_ring(engines):
    """The capability the pages bought: a prompt longer than the largest
    AOT prefill bucket is served (chunked) under paged, rejected by ring."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)

    cfg, _, params, paged, _ = engines
    prompt = list(range(3, 3 + 24))  # 24 > paged's largest bucket 16
    paged.reset()
    row = np.arange(1, paged.max_blocks_per_slot + 1, dtype=np.int32)
    assert isinstance(paged.prefill(0, prompt, block_row=row), int)
    small_ring = InferenceEngine(cfg, params, slots=1, max_len=32,
                                 prefill_buckets=(16,), kv_layout="ring")
    with pytest.raises(ValueError, match="outside"):
        small_ring.prefill(0, prompt)


# --------------------------------------------------------------- 4. scheduler
class _FakePagedEngine:
    """Paged-engine façade for scheduler-policy tests (no XLA): echoes a
    deterministic token, honors the chunked-prefill stop_check contract."""

    def __init__(self, slots=4, max_len=32, block_size=8, num_blocks=None,
                 bucket=16):
        self.slots = slots
        self.max_len = max_len
        self.kv_layout = "paged"
        self.block_size = block_size
        self.max_blocks_per_slot = -(-max_len // block_size)
        self.num_blocks = num_blocks or slots * self.max_blocks_per_slot + 1
        self.bucket = bucket

    def prefill(self, slot, token_ids, block_row=None, temperature=0.0,
                top_p=1.0, seed=0, stop_check=None, on_chunk=None):
        n = len(token_ids)
        start = 0
        while start < n:
            start += min(self.bucket, n - start)
            if on_chunk is not None:
                on_chunk()
            if start < n and stop_check is not None and stop_check():
                return None
        return 1

    def decode_step(self, tokens, active, temperature, top_p, seeds, steps,
                    block_tables=None):
        assert block_tables is not None
        return np.where(active, tokens + 1, 0).astype(np.int32)


def test_admission_queues_on_block_exhaustion():
    """4 free slots but only 4 usable blocks at 2 blocks/request: admission
    is bounded by BLOCKS (2 concurrent), everything still completes."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakePagedEngine(slots=4, max_len=32, block_size=8, num_blocks=5)
    sched = Scheduler(eng)
    for i in range(5):
        sched.submit(Request(id=f"r{i}", prompt=[5] * 8, max_new_tokens=8))
    sched.run()
    assert len(sched.completed) == 5
    assert sched.max_concurrent == 2           # blocks, not slots, bound it
    assert sched.allocator.free_count == sched.allocator.capacity
    assert not sched.block_tables.any()


def test_submit_rejects_request_larger_than_pool():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    sched = Scheduler(_FakePagedEngine(slots=2, max_len=32, block_size=8,
                                       num_blocks=3))
    with pytest.raises(ValueError, match="usable blocks"):
        sched.submit(Request(id="big", prompt=[5] * 20, max_new_tokens=12))
    with pytest.raises(ValueError, match="exceeds"):
        sched.submit(Request(id="huge", prompt=[5] * 30, max_new_tokens=10))


def test_drain_mid_chunked_prefill_reports_unserved():
    """stop_check fires between prefill chunks: the current chunk finishes,
    the request is reported unserved, its blocks come back, admission
    closes — then completed in-flight work still drains."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakePagedEngine(slots=2, max_len=64, block_size=8, bucket=16)
    fired = {"on": False}
    sched = Scheduler(eng, stop_check=lambda: fired["on"])
    sched.submit(Request(id="short", prompt=[5] * 8, max_new_tokens=4))
    sched.step()                               # short admitted, decoding
    fired["on"] = True                         # signal lands mid-queue
    sched.submit(Request(id="long", prompt=[5] * 40, max_new_tokens=8))
    while sched.pending():
        sched.step()
    assert not sched.admission_open
    assert [r.id for r in sched.unserved()] == ["long"]
    assert [c.request_id for c in sched.completed] == ["short"]
    assert sched.allocator.free_count == sched.allocator.capacity
    assert not sched.block_tables.any()
    assert sched.prefill_chunks >= 2           # short's + long's first chunk


def test_paged_metrics_surface():
    """The /metrics gauges the obs satellite added: block gauges move with
    allocation and the chunk counter lands in scheduler metrics()."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    reg = MetricRegistry()
    eng = _FakePagedEngine(slots=2, max_len=32, block_size=8, bucket=4)
    sched = Scheduler(eng, registry=reg)
    sched.submit(Request(id="r0", prompt=[5] * 10, max_new_tokens=6))
    sched.step()
    text = reg.render()
    assert "ftl_serve_kv_blocks_free" in text
    assert "ftl_serve_kv_block_utilization" in text
    assert "ftl_serve_prefill_chunks_total" in text
    m = sched.metrics()
    assert m["prefill_chunks"] == 3            # 10 tokens / 4-token bucket
    assert m["kv_blocks_total"] == sched.allocator.capacity
    assert m["kv_block_utilization_peak"] > 0


# ---------------------------------------------------------- 5. packed prefill
@pytest.fixture(scope="module")
def packed_engine(engines):
    """Same params as the ``engines`` fixture's paged engine, but compiled
    with the packed (P=2, bucket) prefill programs alongside the
    sequential ladder."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)

    cfg, _, params, _, _ = engines
    return InferenceEngine(cfg, params, slots=2, max_len=32,
                           prefill_buckets=(8, 16), kv_layout="paged",
                           kv_block_size=8, prefill_batch=2)


def _run_sched(engine, requests, prefill_batch=1):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    engine.reset()
    sched = Scheduler(engine, prefill_batch=prefill_batch)
    for r in requests:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def test_packed_prefill_streams_bitmatch_sequential(engines, packed_engine):
    """Mixed greedy/sampled workload with multi-chunk prompts and a slot
    turnover: the packed lane's token streams must be BITWISE identical
    to sequential one-prompt-at-a-time prefill (same per-row chunk
    shapes, same gather kernel), with the round/occupancy accounting the
    metrics satellite added."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    cfg, _, _, paged, _ = engines
    rng = np.random.default_rng(3)
    reqs = [Request(id=f"r{i}",
                    prompt=rng.integers(3, cfg.vocab_size, size=pl).tolist(),
                    max_new_tokens=gen, temperature=t, top_p=0.9, seed=i)
            for i, (pl, gen, t) in enumerate(
                [(20, 6, 0.0), (9, 8, 0.8), (24, 5, 0.0), (11, 7, 0.7)])]
    seq_sched, seq_out = _run_sched(paged, list(reqs))
    pak_sched, pak_out = _run_sched(packed_engine, list(reqs),
                                    prefill_batch=2)
    assert pak_out == seq_out
    assert len(pak_out) == 4
    m = pak_sched.metrics()
    # identical chunking discipline: the packed rows walked the same
    # bucket sequence the sequential lane did
    assert m["prefill_chunks"] == seq_sched.metrics()["prefill_chunks"]
    assert m["prefill_packed_rounds"] > 0
    assert m["prefill_packed_rows"] >= m["prefill_packed_rounds"]
    assert 0.0 < m["prefill_packed_occupancy"] <= 1.0
    # the fixture engines read through the gather kernel -> every chunk
    # lands on the gather counter, none on the in-place one
    assert m["prefill_gather_chunks"] == m["prefill_chunks"]
    assert m["prefill_inplace_chunks"] == 0


def test_drain_mid_packed_prefill_frees_all_rows(packed_engine):
    """The drain signal lands between packed rounds while BOTH slots hold
    half-prefilled rows: every pending row's blocks come back exactly
    once, both requests are reported unserved, and the leak audit stays
    clean."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    packed_engine.reset()
    fired = {"on": False}
    sched = Scheduler(packed_engine, prefill_batch=2,
                      stop_check=lambda: fired["on"])
    for i in range(2):
        sched.submit(Request(id=f"long{i}", prompt=[5 + i] * 24,
                             max_new_tokens=4))
    sched.step()                     # both admitted; round 1 of 2 runs
    assert len(sched._pending_prefill) == 2
    fired["on"] = True               # signal lands between rounds
    while sched.pending():
        sched.step()
    assert not sched.admission_open
    assert sorted(r.id for r in sched.unserved()) == ["long0", "long1"]
    assert sched.completed == []
    assert sched.allocator.free_count == sched.allocator.capacity
    assert not sched.block_tables.any()
    assert sched.audit_block_leaks(strict=True) == []


def test_packed_lane_validates(engines, packed_engine):
    """The lane's mutual exclusions, both layers: engine bounds P by slots
    and requires pages; the scheduler refuses spec mode, engines without
    the packed entry point, and width disagreement with the engine."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    cfg, _, params, _, _ = engines
    with pytest.raises(ValueError, match="prefill_batch"):
        InferenceEngine(cfg, params, slots=2, max_len=32,
                        prefill_buckets=(8,), kv_layout="paged",
                        kv_block_size=8, prefill_batch=3)
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(cfg, params, slots=2, max_len=32,
                        prefill_buckets=(8, 16, 32), kv_layout="ring",
                        prefill_batch=2)
    fake = _FakePagedEngine(slots=4)
    with pytest.raises(ValueError, match="prefill_packed"):
        Scheduler(fake, prefill_batch=2)
    fake_spec = _FakePagedEngine(slots=4)
    fake_spec.spec_k = 2
    with pytest.raises(ValueError, match="speculative"):
        Scheduler(fake_spec, prefill_batch=2)
    with pytest.raises(ValueError, match="prefill_batch"):
        Scheduler(packed_engine, prefill_batch=3)  # engine compiled P=2
