"""Tiered KV-block lifecycle (inference/kv_cache.py export/import, the
scheduler's spill tier, and the drain-time block-shipment handoff).

Evidence ladder:

1. primitive — ``export_blocks``/``import_blocks`` round-trip a scattered
   set of pool blocks through a checksummed host artifact BITWISE, refuse
   the reserved null block on both sides, and the reject matrix (flipped
   payload byte, truncated file, missing file, torn manifest, geometry
   mismatch) raises ``KVBlockIntegrityError`` BEFORE any device write;
2. spill tier — on pool exhaustion the scheduler preempts the coldest
   request to the host tier and restores it on demand: every stream is
   bitwise identical to an unconstrained-pool reference (fold_in(seed,
   step) statelessness), shared prefix-cache blocks are never spilled,
   a corrupted spill artifact degrades to a bit-exact replay, and the
   strict leak guard audits blocks ACROSS tiers (a vanished artifact is
   a leak, same as a lost device block);
3. handoff — a draining host exports an in-flight request's committed
   blocks as an artifact a second scheduler imports instead of replaying
   the prefix; the continuation is bitwise identical either way, and a
   CRC-rejected artifact falls back to the replay with the same stream;
4. journal/router — ``handoff`` records fold into advisory artifact
   pointers that never touch ownership, ride along on exactly the next
   migration (stale artifacts are dropped), and the router's
   verify-before-ship rejects a corrupt artifact into replay.
"""

import glob
import os

import numpy as np
import pytest


def _tiny_cfg(vocab=64, seq_len=128):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


# ------------------------------------------------------------- 1. primitive
def _filled_cache(cfg, seed=0, slots=2, max_len=32, block_size=8):
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        init_paged_cache)

    cache = init_paged_cache(cfg, slots=slots, max_len=max_len,
                             block_size=block_size)
    rng = np.random.default_rng(seed)
    k = tuple(jnp.asarray(rng.standard_normal(a.shape), a.dtype)
              for a in cache.k)
    v = tuple(jnp.asarray(rng.standard_normal(a.shape), a.dtype)
              for a in cache.v)
    return cache.replace(k=k, v=v)


def test_block_roundtrip_bitwise(tmp_path):
    """Export scattered blocks [3, 1, 2], import them as [5, 6, 7] of a
    zeroed cache: every layer's K and V must match bitwise, untouched
    rows must stay zero, and lengths are the caller's business."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        artifact_bytes, export_blocks, import_blocks, init_paged_cache,
        verify_block_artifact)

    cfg = _tiny_cfg(seq_len=64)
    cache = _filled_cache(cfg)
    d = str(tmp_path / "art")
    man = export_blocks(cache, [3, 1, 2], d, length=17,
                        meta={"request_id": "r0"})
    assert artifact_bytes(man) > 0
    assert verify_block_artifact(d)["length"] == 17

    fresh = init_paged_cache(cfg, slots=2, max_len=32, block_size=8)
    out, man2 = import_blocks(fresh, d, [5, 6, 7])
    assert man2["meta"]["request_id"] == "r0"
    for l in range(len(cache.k)):
        for src, dst in ((3, 5), (1, 6), (2, 7)):
            np.testing.assert_array_equal(np.asarray(out.k[l][dst]),
                                          np.asarray(cache.k[l][src]))
            np.testing.assert_array_equal(np.asarray(out.v[l][dst]),
                                          np.asarray(cache.v[l][src]))
        np.testing.assert_array_equal(np.asarray(out.k[l][4]),
                                      np.zeros_like(np.asarray(out.k[l][4])))
    # import never touches lengths — the engine wrapper owns the slot
    np.testing.assert_array_equal(np.asarray(out.lengths),
                                  np.asarray(fresh.lengths))


def test_null_block_refused_both_ways(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks, import_blocks)

    cfg = _tiny_cfg(seq_len=64)
    cache = _filled_cache(cfg)
    with pytest.raises(ValueError, match="null block"):
        export_blocks(cache, [0, 1], str(tmp_path / "a"), length=4)
    export_blocks(cache, [1], str(tmp_path / "b"), length=4)
    with pytest.raises(ValueError, match="null block"):
        import_blocks(cache, str(tmp_path / "b"), [0])


def test_import_reject_matrix(tmp_path):
    """Flipped byte, truncated file, missing file, torn manifest and a
    geometry mismatch must all raise KVBlockIntegrityError — and the
    verify runs BEFORE any device write, so the target cache is never
    half-imported."""
    import json

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        BLOCK_MANIFEST_NAME, KVBlockIntegrityError, export_blocks,
        import_blocks, init_paged_cache)

    cfg = _tiny_cfg(seq_len=64)
    cache = _filled_cache(cfg)
    fresh = init_paged_cache(cfg, slots=2, max_len=32, block_size=8)

    def fresh_artifact(name):
        d = str(tmp_path / name)
        export_blocks(cache, [3, 1], d, length=9)
        return d

    # flipped payload byte
    d = fresh_artifact("flip")
    p = os.path.join(d, "block_00001.bin")
    raw = bytearray(open(p, "rb").read())
    raw[7] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises(KVBlockIntegrityError, match="CRC"):
        import_blocks(fresh, d, [5, 6])
    # the failed import wrote nothing
    for l in range(len(fresh.k)):
        np.testing.assert_array_equal(
            np.asarray(fresh.k[l][5]),
            np.zeros_like(np.asarray(fresh.k[l][5])))

    # truncated payload
    d = fresh_artifact("trunc")
    p = os.path.join(d, "block_00000.bin")
    open(p, "wb").write(open(p, "rb").read()[:-3])
    with pytest.raises(KVBlockIntegrityError, match="size"):
        import_blocks(fresh, d, [5, 6])

    # missing payload
    d = fresh_artifact("gone")
    os.unlink(os.path.join(d, "block_00001.bin"))
    with pytest.raises(KVBlockIntegrityError, match="missing"):
        import_blocks(fresh, d, [5, 6])

    # torn manifest (files/blocks disagree)
    d = fresh_artifact("torn")
    man_path = os.path.join(d, BLOCK_MANIFEST_NAME)
    man = json.load(open(man_path))
    man["files"].popitem()
    json.dump(man, open(man_path, "w"))
    with pytest.raises(KVBlockIntegrityError, match="torn"):
        import_blocks(fresh, d, [5, 6])

    # geometry mismatch: same artifact, different block size
    d = fresh_artifact("geom")
    other = init_paged_cache(cfg, slots=2, max_len=32, block_size=16)
    with pytest.raises(KVBlockIntegrityError, match="geometry"):
        import_blocks(other, d, [1, 2])

    # dest-count mismatch is a caller bug, not corruption
    d = fresh_artifact("count")
    with pytest.raises(ValueError):
        import_blocks(fresh, d, [5])


# ------------------------------------------------------------- 2. spill tier
@pytest.fixture(scope="module")
def tier_setup():
    """One param set + the unconstrained-pool reference streams every
    spill/handoff test must reproduce bitwise."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def build(slots=4, num_blocks=None):
        return InferenceEngine(cfg, params, slots=slots, max_len=128,
                               prefill_buckets=(16, 32), kv_layout="paged",
                               kv_block_size=8, kv_num_blocks=num_blocks)

    rng = np.random.default_rng(3)
    reqs = [
        Request(id="A", prompt=rng.integers(3, 64, size=17).tolist(),
                max_new_tokens=40, seed=1),
        Request(id="B", prompt=rng.integers(3, 64, size=19).tolist(),
                max_new_tokens=40, seed=2),
        Request(id="C", prompt=rng.integers(3, 64, size=16).tolist(),
                max_new_tokens=12, temperature=0.8, top_p=0.9, seed=3),
    ]
    sched = Scheduler(build())
    for r in reqs:
        sched.submit(r)
    sched.run()
    ref = {c.request_id: c.tokens for c in sched.completed}
    assert set(ref) == {"A", "B", "C"}
    return {"build": build, "reqs": reqs, "ref": ref}


def _run_constrained(tier_setup, tmp_path, on_spill=None, num_blocks=18):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    sched = Scheduler(tier_setup["build"](num_blocks=num_blocks),
                      spill_dir=str(tmp_path / "tier"), on_spill=on_spill)
    for r in tier_setup["reqs"]:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def test_spill_restore_bitwise(tier_setup, tmp_path):
    """17-usable-block pool vs three requests needing 20: the scheduler
    must spill, restore, and still produce the exact unconstrained
    streams — with the cross-tier leak guard clean at drain."""
    sched, out = _run_constrained(tier_setup, tmp_path)
    assert sched.spill_exports >= 1 and sched.spill_restores >= 1
    assert sched.spill_rejects == 0
    assert out == tier_setup["ref"]
    assert sched.audit_block_leaks(strict=True) == []
    assert not sched._spilled and sched.discard_spilled() == 0


def test_spill_corrupt_falls_back_to_replay(tier_setup, tmp_path):
    """A byte flipped in every spill artifact (the chaos ``spill_corrupt``
    shape, manifest spared): each restore must CRC-reject and re-admit
    via replay — streams still bitwise equal the reference."""
    def corrupt(art_dir, ordinal):
        payloads = sorted(glob.glob(os.path.join(art_dir, "block_*.bin")))
        raw = bytearray(open(payloads[0], "rb").read())
        raw[3] ^= 0xFF
        open(payloads[0], "wb").write(bytes(raw))

    sched, out = _run_constrained(tier_setup, tmp_path, on_spill=corrupt)
    assert sched.spill_exports >= 1 and sched.spill_rejects >= 1
    assert sched.spill_restores == 0
    assert out == tier_setup["ref"]
    assert sched.audit_block_leaks(strict=True) == []


def test_explicit_spill_api_and_double_raises(tier_setup, tmp_path):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    sched = Scheduler(tier_setup["build"](),
                      spill_dir=str(tmp_path / "tier"))
    sched.submit(tier_setup["reqs"][0])
    for _ in range(4):
        sched.step()
    slot = next(iter(sched.active))
    sched.spill(slot)
    assert tier_setup["reqs"][0].id in sched._spilled
    with pytest.raises(KeyError):
        sched.spill(slot)  # slot is empty now
    with pytest.raises(RuntimeError, match="double restore"):
        sched._restore_one("nope", slot, [])
    # disabled tier refuses explicitly
    plain = Scheduler(tier_setup["build"]())
    plain.submit(tier_setup["reqs"][1])
    plain.step()
    with pytest.raises(RuntimeError, match="disabled"):
        plain.spill(next(iter(plain.active)))
    plain.run()
    # the spilled request restores and completes bit-exactly
    sched.run()
    out = {c.request_id: c.tokens for c in sched.completed}
    assert out[tier_setup["reqs"][0].id] == \
        tier_setup["ref"][tier_setup["reqs"][0].id]


def test_leak_guard_sees_vanished_artifact(tier_setup, tmp_path):
    """A spilled artifact whose manifest disappears is a leaked block set
    — strict audit must raise, same contract as a lost device block."""
    import shutil

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        BLOCK_MANIFEST_NAME)
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    sched = Scheduler(tier_setup["build"](),
                      spill_dir=str(tmp_path / "tier"))
    sched.submit(tier_setup["reqs"][0])
    for _ in range(4):
        sched.step()
    sched.spill(next(iter(sched.active)))
    sp = sched._spilled[tier_setup["reqs"][0].id]
    os.unlink(os.path.join(sp.artifact_dir, BLOCK_MANIFEST_NAME))
    with pytest.raises(RuntimeError, match="leak"):
        sched.audit_block_leaks(strict=True)
    shutil.rmtree(sp.artifact_dir, ignore_errors=True)
    sched.discard_spilled()


def test_shared_prefix_stays_on_device(tier_setup, tmp_path):
    """Two requests sharing a 16-token prompt prefix: spilling one must
    export only its PRIVATE blocks (the shared leading blocks stay warm
    under the prefix cache) and the restore re-acquires them by content
    — continuation bitwise equal to never having spilled."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    rng = np.random.default_rng(11)
    common = rng.integers(3, 64, size=16).tolist()
    ra = Request(id="sa", prompt=common + [5, 6], max_new_tokens=24, seed=4)
    rb = Request(id="sb", prompt=common + [9], max_new_tokens=24, seed=5)

    ref_sched = Scheduler(tier_setup["build"]())
    for r in (ra, rb):
        ref_sched.submit(r)
    ref_sched.run()
    ref = {c.request_id: c.tokens for c in ref_sched.completed}

    sched = Scheduler(tier_setup["build"](),
                      spill_dir=str(tmp_path / "tier"))
    for r in (ra, rb):
        sched.submit(r)
    for _ in range(4):
        sched.step()
    victim = next(s for s, st in sched.active.items()
                  if st.request.id == "sb")
    sched.spill(victim)
    sp = sched._spilled["sb"]
    assert sp.private_positions[0] > 0, \
        "shared leading blocks must not be exported"
    assert sp.shared_tokens == common[:len(sp.shared_tokens)]
    sched.run()
    out = {c.request_id: c.tokens for c in sched.completed}
    assert out == ref
    assert sched.audit_block_leaks(strict=True) == []


# ---------------------------------------------------------------- 3. handoff
def test_handoff_ship_and_replay_fallback(tier_setup, tmp_path):
    """Host 1 decodes 7 rounds then drain-exports its slot; host 2 admits
    from the artifact (block import, no prefill replay) and must emit the
    exact reference continuation. With a flipped payload byte the import
    is CRC-rejected and the replay fallback emits the same stream."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    rng = np.random.default_rng(5)
    req = Request(id="H", prompt=rng.integers(3, 64, size=17).tolist(),
                  max_new_tokens=24, temperature=0.7, top_p=0.9, seed=9)
    ref_sched = Scheduler(tier_setup["build"](slots=2))
    ref_sched.submit(req)
    ref_sched.run()
    ref = ref_sched.completed[0].tokens

    s1 = Scheduler(tier_setup["build"](slots=2))
    s1.submit(req)
    for _ in range(7):
        s1.step()
    art = str(tmp_path / "handoff_H_g0")
    info = s1.export_handoff(next(iter(s1.active)), art, gen=0)
    assert info["blocks"] >= 1
    uns = s1.unserved()
    assert uns and uns[0].id == "H"
    assert list(uns[0].committed) == info["tokens"]
    assert s1.audit_block_leaks(strict=True) == []

    s2 = Scheduler(tier_setup["build"](slots=2))
    s2.submit(uns[0], handoff_artifact=art, handoff_gen=1)
    s2.run()
    assert s2.handoff_imports == 1 and s2.handoff_rejects == 0
    assert s2.completed[0].tokens == ref

    payloads = sorted(glob.glob(os.path.join(art, "block_*.bin")))
    raw = bytearray(open(payloads[1], "rb").read())
    raw[5] ^= 0xFF
    open(payloads[1], "wb").write(bytes(raw))
    s3 = Scheduler(tier_setup["build"](slots=2))
    s3.submit(uns[0], handoff_artifact=art, handoff_gen=1)
    s3.run()
    assert s3.handoff_rejects == 1 and s3.handoff_imports == 0
    assert s3.completed[0].tokens == ref


# ---------------------------------------------------------- 4. journal/router
def test_journal_handoff_fold_is_advisory(tmp_path):
    """A ``handoff`` record must set the artifact pointer WITHOUT taking
    ownership, and the router attaches it to exactly the next migration
    (a later generation means some survivor already consumed it)."""
    from fault_tolerant_llm_training_tpu.ft.lease import FileKVStore
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)
    from fault_tolerant_llm_training_tpu.inference.router import Router

    jdir = str(tmp_path / "journal")
    host = RequestJournal(jdir, writer="host_h0")
    host.handoff("r1", "h0", "/tmp/handoff_r1_g0", [7, 8], gen=0)
    host.requeue("r1", [1, 2, 3], 16, 0.0, 1.0, 0, [7, 8], gen=1)
    st = fold(jdir)["r1"]
    assert st.handoff_artifact == "/tmp/handoff_r1_g0"
    assert st.handoff_gen == 0
    assert st.gen == 1 and st.requeued and st.host is None

    router = Router(FileKVStore(str(tmp_path / "store")), jdir)
    item = router._item_from_state(st, src="h0")
    assert item["handoff"] == "/tmp/handoff_r1_g0"

    # after a migration at gen 2 the artifact is stale: never re-shipped
    router.journal.migrate("r1", "h0", "h1", 2, [1, 2, 3], 16, 0.0, 1.0,
                           0, [7, 8], handoff="/tmp/handoff_r1_g0")
    st2 = fold(jdir)["r1"]
    assert st2.gen == 2 and st2.host == "h1"
    assert router._item_from_state(st2, src="h1")["handoff"] == ""


def test_router_verifies_artifact_before_shipping(tmp_path):
    """The router's migrate path CRC-verifies the artifact: a good one is
    named in the migrate record, a corrupt one is rejected (counter +
    audit) and the migration degrades to plain replay."""
    from fault_tolerant_llm_training_tpu.ft.lease import FileKVStore
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks)
    from fault_tolerant_llm_training_tpu.inference.router import Router

    cfg = _tiny_cfg(seq_len=64)
    cache = _filled_cache(cfg)
    art = str(tmp_path / "handoff_rv_g0")
    export_blocks(cache, [1, 2], art, length=9)

    router = Router(FileKVStore(str(tmp_path / "store")),
                    str(tmp_path / "journal"))
    item = {"id": "rv", "gen": 1, "handoff": art}
    assert router._verify_handoff(item) == art

    p = os.path.join(art, "block_00000.bin")
    raw = bytearray(open(p, "rb").read())
    raw[0] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    assert router._verify_handoff(item) == ""
    assert router._verify_handoff({"id": "rv", "gen": 1, "handoff": ""}) \
        == ""
