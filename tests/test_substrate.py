"""L1 substrate tests: flags, schedule, grad clip, dtype registry."""

import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.utils import (
    PRECISION_STR_TO_DTYPE,
    get_args,
    linear_warmup_constant,
)
from fault_tolerant_llm_training_tpu.utils.grad_clip import (
    clip_grads_with_norm,
    global_norm,
)


def test_reference_training_cmd_parses():
    # The reference's shipped TRAINING_CMD (ref: train.sh:16-22) must parse.
    cfg = get_args(
        "--sequence-length 2048 --batch-size 1 --learning-rate 5e-5 "
        "--lr-warmup-steps 100 --training-steps 1000 --raise-error "
        "--error-step 600".split())
    assert cfg.sequence_length == 2048
    assert cfg.learning_rate == 5e-5
    assert cfg.raise_error and cfg.error_step == 600
    # chained resume plumbing (ref: train.sh:24-27)
    cfg2 = get_args(["--checkpoint-id", "444664"])
    assert cfg2.checkpoint_id == "444664"


def test_flag_defaults_match_reference():
    cfg = get_args([])
    # ref: utils.py:114-201 defaults
    assert cfg.sequence_length == 4096
    assert cfg.batch_size == 1
    assert cfg.learning_rate == 1e-5
    assert cfg.lr_warmup_steps == 10
    assert cfg.training_steps == 1000
    assert cfg.logging_frequency == 5
    assert cfg.grad_max_norm == 1
    assert cfg.model_dtype == "bf16"
    assert cfg.error_step == 100
    assert not cfg.raise_error


def test_schedule_matches_lambdalr_semantics():
    # ref: utils.py:43-53 — factor (t+1)/(warmup+1) for t < warmup, else 1.
    lr, warmup = 2.0, 10
    sched = linear_warmup_constant(lr, warmup)
    for t in range(25):
        expected = lr * ((t + 1) / (warmup + 1) if t < warmup else 1.0)
        assert np.isclose(float(sched(t)), expected), t


def test_grad_clip_matches_torch_semantics():
    grads = {"a": jnp.array([3.0, 4.0]), "b": jnp.array([[0.0]])}
    norm = float(global_norm(grads))
    assert np.isclose(norm, 5.0)
    clipped, total = clip_grads_with_norm(grads, max_norm=1.0)
    # torch coef: min(max_norm / (norm + 1e-6), 1) (ref: utils.py:62)
    coef = 1.0 / (5.0 + 1e-6)
    assert np.allclose(np.asarray(clipped["a"]), np.array([3.0, 4.0]) * coef)
    # no clipping when under the norm
    not_clipped, _ = clip_grads_with_norm(grads, max_norm=10.0)
    assert np.allclose(np.asarray(not_clipped["a"]), np.array([3.0, 4.0]))


def test_dtype_registry():
    # ref: utils.py:14-19
    assert PRECISION_STR_TO_DTYPE["bf16"] == jnp.bfloat16
    assert PRECISION_STR_TO_DTYPE["fp32"] == jnp.float32
    assert set(PRECISION_STR_TO_DTYPE) == {"fp16", "bf16", "fp32", "fp64"}


def test_hbm_usage_str_formats_and_degrades():
    """Best-effort HBM telemetry: formats when the backend reports stats,
    silently empty elsewhere (CPU backends return no memory_stats)."""
    from unittest import mock

    from fault_tolerant_llm_training_tpu.utils import metrics

    class _Dev:
        def memory_stats(self):
            return {"bytes_in_use": 2_500_000_000, "bytes_limit": 16_000_000_000}

    with mock.patch("jax.local_devices", return_value=[_Dev()]):
        assert metrics.hbm_usage_str() == "2.5/16.0 GB"

    class _NoStats:
        def memory_stats(self):
            return None

    with mock.patch("jax.local_devices", return_value=[_NoStats()]):
        assert metrics.hbm_usage_str() == ""


def test_cosine_schedule_shape():
    """Warmup matches the reference's +1 LambdaLR indexing; then cosine
    decays to the 10% floor at the horizon and stays there."""
    import numpy as np

    from fault_tolerant_llm_training_tpu.utils.schedules import (
        linear_warmup_constant,
        linear_warmup_cosine,
    )

    lr, warm, total = 1e-3, 10, 100
    cos = linear_warmup_cosine(lr, warm, total)
    const = linear_warmup_constant(lr, warm)
    for t in range(warm):  # identical during warmup
        np.testing.assert_allclose(float(cos(t)), float(const(t)), rtol=1e-6)
    assert float(cos(warm)) <= lr * 1.0001  # fp32 rounding headroom
    mid = float(cos((warm + total) // 2))
    assert 0.1 * lr < mid < lr  # strictly between the endpoints
    np.testing.assert_allclose(float(cos(total)), 0.1 * lr, rtol=1e-5)
    np.testing.assert_allclose(float(cos(total + 50)), 0.1 * lr, rtol=1e-5)
    assert all(float(cos(t)) >= float(cos(t + 1)) - 1e-12
               for t in range(warm, total))  # monotone decay
