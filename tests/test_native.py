"""Native hostloader (C++ via ctypes) vs its numpy fallback.

Parity is asserted by calling the module-level fallbacks directly (the
``_LIB is None`` branches) against the loaded library; the build itself is
exercised by importing the module (compiles + caches the .so on first use).
"""

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.data import native


def _fallback_collate(batch, pad_id):
    inputs = batch[:, :-1].copy()
    labels = batch[:, 1:].copy()
    labels[labels == pad_id] = -100
    return inputs, labels


def _fallback_pack(chunk, bos_id):
    inputs = chunk[:-1].copy()
    labels = chunk[1:].copy()
    labels[inputs == bos_id] = -100
    labels[labels == bos_id] = -100
    return inputs, labels


@pytest.fixture(scope="module")
def require_native():
    if not native.have_native():
        pytest.skip("native hostloader did not build (no g++?)")


def test_collate_parity(require_native):
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 50, (8, 129)).astype(np.int32)
    batch[rng.random(batch.shape) < 0.2] = 7  # pad id
    got_i, got_l = native.collate_clm(batch, pad_id=7)
    want_i, want_l = _fallback_collate(batch, 7)
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_array_equal(got_l, want_l)


def test_pack_parity(require_native):
    rng = np.random.default_rng(1)
    chunk = rng.integers(0, 30, (257,)).astype(np.int32)
    chunk[rng.random(chunk.shape) < 0.1] = 1  # bos id
    got_i, got_l = native.pack_clm(chunk, bos_id=1)
    want_i, want_l = _fallback_pack(chunk, 1)
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_array_equal(got_l, want_l)


def test_byte_tokenize_parity(require_native):
    text = "hello, wörld \U0001f680"
    got = native.byte_tokenize(text, bos_id=1, offset=3)
    data = np.frombuffer(text.encode("utf-8"), np.uint8).astype(np.int32) + 3
    want = np.concatenate([[1], data]).astype(np.int32)
    np.testing.assert_array_equal(got, want)
    # no-BOS variant and empty string
    np.testing.assert_array_equal(native.byte_tokenize(text, -1, 3), data)
    np.testing.assert_array_equal(native.byte_tokenize("", 1, 3),
                                  np.asarray([1], np.int32))
    assert native.byte_tokenize("", -1, 3).size == 0
