"""Native hostloader (C++ via ctypes) vs the in-module numpy fallback.

Parity runs every public function twice — once with the built library and
once with ``_LIB`` monkeypatched to None — so the *real* fallback branches
(the path taken on machines without g++) are the oracle, not a re-typed
copy. Importing the module and calling a binding exercises the lazy build.
"""

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.data import native


@pytest.fixture()
def fallback(monkeypatch):
    """Force the in-module numpy fallback path."""
    monkeypatch.setattr(native, "_LIB", None)
    monkeypatch.setattr(native, "_TRIED", True)
    return native


@pytest.fixture(scope="module")
def require_native():
    if not native.have_native():
        pytest.skip("native hostloader did not build (no g++?)")


def test_collate_parity(require_native, fallback, monkeypatch):
    rng = np.random.default_rng(0)
    batch = rng.integers(0, 50, (8, 129)).astype(np.int32)
    batch[rng.random(batch.shape) < 0.2] = 7  # pad id
    want_i, want_l = native.collate_clm(batch, pad_id=7)  # fallback active
    monkeypatch.undo()
    got_i, got_l = native.collate_clm(batch, pad_id=7)  # native active
    assert native._LIB is not None
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_array_equal(got_l, want_l)


def test_pack_parity(require_native, fallback, monkeypatch):
    rng = np.random.default_rng(1)
    chunk = rng.integers(0, 30, (257,)).astype(np.int32)
    chunk[rng.random(chunk.shape) < 0.1] = 1  # bos id
    want_i, want_l = native.pack_clm(chunk, bos_id=1)
    monkeypatch.undo()
    got_i, got_l = native.pack_clm(chunk, bos_id=1)
    assert native._LIB is not None
    np.testing.assert_array_equal(got_i, want_i)
    np.testing.assert_array_equal(got_l, want_l)


def test_byte_tokenize_parity(require_native, fallback, monkeypatch):
    cases = [("hello, wörld \U0001f680", 1), ("hello", -1), ("", 1), ("", -1)]
    want = [native.byte_tokenize(t, bos, 3) for t, bos in cases]
    monkeypatch.undo()
    got = [native.byte_tokenize(t, bos, 3) for t, bos in cases]
    assert native._LIB is not None
    for w, g in zip(want, got):
        np.testing.assert_array_equal(w, g)
    # spot-check absolute values, not just agreement
    data = np.frombuffer("hello".encode(), np.uint8).astype(np.int32) + 3
    np.testing.assert_array_equal(got[1], data)
    np.testing.assert_array_equal(got[2], np.asarray([1], np.int32))
