"""Model parity tests (ref: model.py:9-380)."""

import jax
import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.models.llama import RMSNorm
from fault_tolerant_llm_training_tpu.ops.attention import xla_attention


def test_ffn_hidden_rounding_matches_reference():
    # ref: model.py:243-247 with the train.py:43-53 config -> 14336
    assert get_config("llama3-8b").ffn_hidden_dim == 14336
    # dataclass-default config: dim 4096, no multiplier, multiple_of 256
    assert get_config("llama3-8b", ffn_dim_multiplier=None,
                      multiple_of=256).ffn_hidden_dim == 11008


def test_param_count_8b():
    # SURVEY.md §2.1 #6: ≈8.05B at the reference trainer config.
    cfg = get_config("llama3-8b")
    assert abs(cfg.param_count() - 8.05e9) < 0.01e9


def test_param_count_matches_eval_shape():
    for preset in ("tiny", "gpt2-125m"):
        cfg = get_config(preset)
        m = Transformer(cfg)
        shapes = jax.eval_shape(
            m.init, jax.random.PRNGKey(0),
            jnp.zeros((1, 8), jnp.int32))["params"]
        n = sum(int(np.prod(s.shape)) for s in jax.tree_util.tree_leaves(shapes))
        assert n == cfg.param_count(), preset


def test_rmsnorm_fp32_internal():
    # ref: model.py:43-48 — norm in fp32, cast back, then scale.
    norm = RMSNorm(dim=8, eps=1e-5, param_dtype=jnp.float32)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 4, 8)),
                    jnp.float32)
    params = norm.init(jax.random.PRNGKey(0), x)
    out = norm.apply(params, x)
    xf = np.asarray(x, np.float64)
    want = xf / np.sqrt((xf ** 2).mean(-1, keepdims=True) + 1e-5)
    np.testing.assert_allclose(np.asarray(out), want, rtol=1e-5, atol=1e-5)


def test_attention_is_causal():
    # Perturbing future tokens must not change current logits.
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    m = Transformer(cfg)
    t1 = jnp.asarray(np.random.default_rng(0).integers(0, 512, (1, 16)))
    t2 = t1.at[:, 10:].set(7)
    params = m.init(jax.random.PRNGKey(0), t1)["params"]
    l1 = m.apply({"params": params}, t1)
    l2 = m.apply({"params": params}, t2)
    np.testing.assert_allclose(np.asarray(l1[:, :10]), np.asarray(l2[:, :10]),
                               rtol=1e-5, atol=1e-5)
    assert not np.allclose(np.asarray(l1[:, 10:]), np.asarray(l2[:, 10:]))


def test_gqa_grouped_einsum_matches_repeated_kv():
    # The grouped einsum must equal the reference's repeat_kv expansion
    # (ref: model.py:129-138,204-205) followed by plain MHA.
    rng = np.random.default_rng(0)
    b, s, h, kv, d = 2, 16, 8, 2, 16
    q = jnp.asarray(rng.standard_normal((b, s, h, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, kv, d)), jnp.float32)
    out = xla_attention(q, k, v, causal=True)
    k_rep = jnp.repeat(k, h // kv, axis=2)
    v_rep = jnp.repeat(v, h // kv, axis=2)
    want = xla_attention(q, k_rep, v_rep, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want), rtol=1e-5,
                               atol=1e-5)


def test_attention_matches_manual_softmax():
    rng = np.random.default_rng(1)
    b, s, h, d = 1, 8, 2, 4
    q = rng.standard_normal((b, s, h, d)).astype(np.float32)
    k = rng.standard_normal((b, s, h, d)).astype(np.float32)
    v = rng.standard_normal((b, s, h, d)).astype(np.float32)
    got = np.asarray(xla_attention(jnp.asarray(q), jnp.asarray(k),
                                   jnp.asarray(v), causal=True))
    # manual per-head causal softmax attention
    want = np.zeros_like(got)
    for hi in range(h):
        scores = q[0, :, hi] @ k[0, :, hi].T / np.sqrt(d)
        for i in range(s):
            scores[i, i + 1:] = -np.inf
        p = np.exp(scores - scores.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        want[0, :, hi] = p @ v[0, :, hi]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_remat_same_output():
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32)
    t = jnp.asarray(np.random.default_rng(0).integers(0, 512, (1, 16)))
    m = Transformer(cfg)
    params = m.init(jax.random.PRNGKey(0), t)["params"]
    m_remat = Transformer(cfg.replace(remat=True))
    l1 = m.apply({"params": params}, t)
    l2 = m_remat.apply({"params": params}, t)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), rtol=1e-6)


def test_embed_one_hot_matches_gather():
    # The iota one-hot matmul embedding (used under tensor parallelism, where
    # the vocab-sharded table cannot be gathered efficiently) must equal the
    # plain gather lookup.
    cfg = get_config("tiny", attention_impl="xla", dtype=jnp.float32,
                     param_dtype=jnp.float32, embed_impl="gather")
    t = jnp.asarray(np.random.default_rng(1).integers(0, 512, (2, 16)))
    m = Transformer(cfg)
    params = m.init(jax.random.PRNGKey(0), t)["params"]
    l_gather = m.apply({"params": params}, t)
    l_onehot = Transformer(cfg.replace(embed_impl="one_hot")).apply(
        {"params": params}, t)
    np.testing.assert_allclose(np.asarray(l_gather), np.asarray(l_onehot),
                               rtol=1e-6, atol=1e-6)


def _tiny_fp32(**kw):
    return get_config("tiny", dtype=jnp.float32, param_dtype=jnp.float32,
                      **kw)


def test_fused_projections_same_tree_and_function():
    """cfg.fused_w13 / cfg.fused_qkv keep the param tree (names, shapes,
    init values) byte-identical to the separate nn.Dense modules — the
    concat happens on the weight side at compute time — and compute the
    same function up to reduction order (BASELINE.md round 4: fused_w13
    is the default, +2.2% headline; fused_qkv is a measured rejection
    kept as an option)."""
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, 512, (2, 128)), jnp.int32)
    base = _tiny_fp32(fused_w13=False, fused_qkv=False)
    m0 = Transformer(base)
    p0 = m0.init(jax.random.PRNGKey(0), toks)["params"]
    ref = m0.apply({"params": p0}, toks)
    for kw in (dict(fused_w13=True), dict(fused_qkv=True),
               dict(fused_w13=True, fused_qkv=True),
               dict(qkv_einsum=True),
               dict(qkv_einsum=True, attention_impl="pallas",
                    rope_impl="fused")):
        m = Transformer(_tiny_fp32(**kw))
        p = m.init(jax.random.PRNGKey(0), toks)["params"]
        assert (jax.tree_util.tree_structure(p)
                == jax.tree_util.tree_structure(p0)), kw
        for a, b in zip(jax.tree_util.tree_leaves(p),
                        jax.tree_util.tree_leaves(p0)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        out = m.apply({"params": p0}, toks)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-5, err_msg=str(kw))


def test_rope_impl_fused_matches_xla_in_model():
    """The model's rope_impl='fused' branch (in-kernel rope, the TPU
    default) equals the rope_impl='xla' pallas path — logits and grads.
    Forced onto the pallas path explicitly so the branch runs (interpret
    mode) on CPU."""
    toks = jnp.asarray(
        np.random.default_rng(1).integers(0, 512, (2, 128)), jnp.int32)
    m_x = Transformer(_tiny_fp32(attention_impl="pallas", rope_impl="xla"))
    m_f = Transformer(_tiny_fp32(attention_impl="pallas", rope_impl="fused"))
    p = m_x.init(jax.random.PRNGKey(0), toks)["params"]
    out_x = m_x.apply({"params": p}, toks)
    out_f = m_f.apply({"params": p}, toks)
    np.testing.assert_allclose(np.asarray(out_f), np.asarray(out_x),
                               rtol=1e-4, atol=1e-5)

    def loss(model, params):
        # sin keeps the cotangents bounded — a sum-of-squares loss over
        # all logits produces O(100)-magnitude grads whose fp32
        # association noise swamps the comparison
        return jnp.sum(jnp.sin(model.apply({"params": params}, toks)))

    g_x = jax.grad(lambda p: loss(m_x, p))(p)
    g_f = jax.grad(lambda p: loss(m_f, p))(p)
    # Per-leaf relative norm: the two rope paths are mathematically
    # identical but associate fp32 sums differently, and two layers of
    # compounding amplifies isolated elements past any sane elementwise
    # bound while the leaf-level agreement stays ~1e-6.
    for a, b in zip(jax.tree_util.tree_leaves(g_x),
                    jax.tree_util.tree_leaves(g_f)):
        a, b = np.asarray(a, np.float64), np.asarray(b, np.float64)
        rel = np.linalg.norm(a - b) / (np.linalg.norm(a) + 1e-12)
        assert rel < 1e-4, rel


def test_fused_wo_matches_dense_wo():
    """cfg.fused_wo (default ON): contracting wo against the kernel's
    head-major output equals transpose+reshape+Dense — same param tree,
    same function (rope-fused pallas path, interpret mode)."""
    toks = jnp.asarray(
        np.random.default_rng(2).integers(0, 512, (2, 128)), jnp.int32)
    kw = dict(attention_impl="pallas", rope_impl="fused")
    m0 = Transformer(_tiny_fp32(fused_wo=False, **kw))
    m1 = Transformer(_tiny_fp32(fused_wo=True, **kw))
    p = m0.init(jax.random.PRNGKey(0), toks)["params"]
    p1 = m1.init(jax.random.PRNGKey(0), toks)["params"]
    assert (jax.tree_util.tree_structure(p)
            == jax.tree_util.tree_structure(p1))
    o0 = m0.apply({"params": p}, toks)
    o1 = m1.apply({"params": p}, toks)
    np.testing.assert_allclose(np.asarray(o1), np.asarray(o0),
                               rtol=1e-4, atol=1e-5)
    g0 = jax.grad(lambda p: jnp.sum(jnp.sin(m0.apply({"params": p},
                                                     toks))))(p)
    g1 = jax.grad(lambda p: jnp.sum(jnp.sin(m1.apply({"params": p},
                                                     toks))))(p)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        a = np.asarray(a, np.float64)
        b = np.asarray(b, np.float64)
        assert (np.linalg.norm(a - b)
                / (np.linalg.norm(a) + 1e-12)) < 1e-4
