"""Data layer parity + checkpointable-state tests (ref: dataset.py)."""

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.data import (
    ByteTokenizer,
    CollatorForCLM,
    DataLoader,
    IterableParquetDataset,
    ParquetDataset,
)


@pytest.fixture()
def tok():
    return ByteTokenizer()


def test_byte_tokenizer_roundtrip(tok):
    text = "hello wörld"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_token_id
    assert tok.decode(ids) == text
    assert tok.vocab_size == 259


def test_byte_tokenizer_pad_truncate(tok):
    # encode_plus semantics the datasets rely on (ref: dataset.py:29-35)
    out = tok.encode_plus("abc", max_length=10, padding="max_length",
                          truncation=True, padding_side="right")
    ids = out["input_ids"]
    assert len(ids) == 10
    np.testing.assert_array_equal(
        ids[:4],
        np.concatenate([[tok.bos_token_id], tok.encode("abc", add_bos=False)]))
    assert all(i == tok.pad_token_id for i in ids[4:])
    out2 = tok.encode_plus("abcdefghijkl", max_length=5, padding="max_length",
                           truncation=True)
    assert len(out2["input_ids"]) == 5


def test_map_dataset_wraparound_and_len(tiny_parquet, tok):
    ds = ParquetDataset(tiny_parquet, tok, sequence_length=16,
                        training_samples=1000)
    # __len__ is the *requested* count (ref: dataset.py:24-25)
    assert len(ds) == 1000
    # wraparound indexing (ref: dataset.py:28)
    np.testing.assert_array_equal(
        ds[5]["input_ids"], ds[5 + ds._source.real_length]["input_ids"])
    assert len(ds[0]["input_ids"]) == 17  # seq_len + 1


def test_sharded_parquet_source_matches_single_file(tmp_path, tok):
    """A directory (or glob) of shards must index identically to the same
    rows in one file — shard layout cannot perturb the checkpointable data
    position (a single global row index)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    docs = [f"document number {i}" for i in range(30)]
    single = tmp_path / "all.parquet"
    pq.write_table(pa.table({"text": docs}), single)
    shards = tmp_path / "shards"
    shards.mkdir()
    # deliberately unequal shard sizes; names sort lexicographically
    for name, lo, hi in [("a.parquet", 0, 7), ("b.parquet", 7, 19),
                         ("c.parquet", 19, 30)]:
        pq.write_table(pa.table({"text": docs[lo:hi]}), shards / name)

    one = ParquetDataset(str(single), tok, 16, training_samples=60)
    for source in (str(shards), str(shards / "*.parquet")):
        many = ParquetDataset(source, tok, 16, training_samples=60)
        assert many._source.real_length == 30
        for i in (0, 6, 7, 18, 19, 29, 45):  # incl. shard edges + wraparound
            np.testing.assert_array_equal(one[i]["input_ids"],
                                          many[i]["input_ids"])


def test_sharded_parquet_source_errors(tmp_path, tok):
    with pytest.raises(FileNotFoundError):
        ParquetDataset(str(tmp_path / "none" / "*.parquet"), tok, 16, 10)


def test_collator_shift_and_mask(tok):
    collator = CollatorForCLM(sequence_length=4, pad_token_id=tok.pad_token_id)
    ex = [{"input_ids": [1, 5, 6, tok.pad_token_id, tok.pad_token_id]}]
    inputs, labels = collator(ex)
    assert inputs.shape == (1, 4) and labels.shape == (1, 4)
    # shift: inputs = ids[:-1], labels = ids[1:] (ref: dataset.py:47-48)
    np.testing.assert_array_equal(inputs[0], [1, 5, 6, tok.pad_token_id])
    # pad labels -> -100 (ref: dataset.py:50)
    np.testing.assert_array_equal(labels[0], [5, 6, -100, -100])


def test_packed_dataset_legacy_quirks(tiny_parquet, tok):
    """The reference clears the buffer each sample and re-reads the last doc
    (ref: dataset.py:78,93) — legacy mode must reproduce that exactly."""
    ds = IterableParquetDataset(tiny_parquet, tok, sequence_length=32,
                                bos_token_id=tok.bos_token_id, legacy=True)
    it = iter(ds)
    idx_before = ds.current_index
    inputs, labels = next(it)
    assert inputs.shape == (32,) and labels.shape == (32,)
    # the last consumed doc is re-read next time: current_index went up by
    # (#docs consumed) then back down 1
    assert ds.current_index >= idx_before
    # BOS masking: where input or label is BOS, label == -100
    # (ref: dataset.py:99-100)
    bos_pos = (inputs == tok.bos_token_id) | (labels == tok.bos_token_id)
    assert np.all(labels[bos_pos] == -100)


def test_packed_dataset_fixed_mode_advances(tiny_parquet, tok):
    """With documents longer than seq_len+1, the reference's quirk pair
    (buffer cleared every __next__ + current_index -= 1, dataset.py:78,93)
    makes legacy mode re-yield the *same* truncated document forever; fixed
    mode must advance through the corpus instead."""
    legacy = IterableParquetDataset(tiny_parquet, tok, 32,
                                    tok.bos_token_id, legacy=True)
    fixed = IterableParquetDataset(tiny_parquet, tok, 32,
                                   tok.bos_token_id, legacy=False)
    l1, l2 = next(iter(legacy)), next(legacy)
    f1, f2 = next(iter(fixed)), next(fixed)
    np.testing.assert_array_equal(l1[0], l2[0])  # the quirk, reproduced
    assert not np.array_equal(f1[0], f2[0])  # the fix, behind the flag
    assert fixed.current_index > legacy.current_index


def test_dataset_state_roundtrip_map(tiny_parquet, tok):
    ds = ParquetDataset(tiny_parquet, tok, 16, training_samples=100)
    collator = CollatorForCLM(16, tok.pad_token_id)
    loader = DataLoader(ds, batch_size=4, collator=collator)
    loader.resume()
    batches = [next(loader) for _ in range(3)]
    state = loader.get_state()
    next_batch = next(loader)

    ds2 = ParquetDataset(tiny_parquet, tok, 16, training_samples=100)
    loader2 = DataLoader(ds2, batch_size=4, collator=collator)
    loader2.set_state(state)
    resumed = next(loader2)
    np.testing.assert_array_equal(next_batch[0], resumed[0])
    np.testing.assert_array_equal(next_batch[1], resumed[1])


def test_dataset_state_roundtrip_packed(tiny_parquet, tok):
    for legacy in (True, False):
        ds = IterableParquetDataset(tiny_parquet, tok, 32, tok.bos_token_id,
                                    legacy=legacy)
        loader = DataLoader(ds, batch_size=2)
        loader.resume()
        for _ in range(3):
            next(loader)
        state = loader.get_state()
        want = next(loader)

        ds2 = IterableParquetDataset(tiny_parquet, tok, 32, tok.bos_token_id,
                                     legacy=legacy)
        loader2 = DataLoader(ds2, batch_size=2)
        loader2.set_state(state)
        got = next(loader2)
        np.testing.assert_array_equal(want[0], got[0])
        np.testing.assert_array_equal(want[1], got[1])


def test_smoke_harness_runs(tiny_parquet, capsys):
    """The runnable data smoke test (ref: dataset.py:104-166) exercises both
    dataset classes and reports the loss-mask percentage."""
    from fault_tolerant_llm_training_tpu.data.__main__ import main

    main(["--dataset", tiny_parquet, "--sequence-length", "64",
          "--batch-size", "2"])
    out = capsys.readouterr().out
    assert "data smoke test OK" in out
    assert "[map] batch" in out and "[packed/fixed]" in out


def test_pretokenize_cache_matches_direct_path(tiny_parquet, tok, tmp_path):
    """Cached rows equal on-the-fly tokenization bit-for-bit, the cache is
    reused on reconstruction, and a changed config gets its own file."""
    cache = str(tmp_path / "tokcache")
    plain = ParquetDataset(tiny_parquet, tok, 16, training_samples=40)
    cached = ParquetDataset(tiny_parquet, tok, 16, training_samples=40,
                            pretokenize_dir=cache)
    for i in range(40):
        np.testing.assert_array_equal(
            np.asarray(cached[i]["input_ids"], np.int32),
            np.asarray(plain[i]["input_ids"], np.int32))
    import os

    files = sorted(os.listdir(cache))
    npys = [f for f in files if f.endswith(".npy")]
    assert len(npys) == 1
    mtime = os.path.getmtime(os.path.join(cache, npys[0]))
    # reconstruction reuses the existing cache (no rebuild)
    again = ParquetDataset(tiny_parquet, tok, 16, training_samples=40,
                           pretokenize_dir=cache)
    assert os.path.getmtime(os.path.join(cache, npys[0])) == mtime
    np.testing.assert_array_equal(
        np.asarray(again[7]["input_ids"], np.int32),
        np.asarray(plain[7]["input_ids"], np.int32))
    # a different sequence length is a different cache identity
    ParquetDataset(tiny_parquet, tok, 24, training_samples=40,
                   pretokenize_dir=cache)
    npys2 = [f for f in os.listdir(cache) if f.endswith(".npy")]
    assert len(npys2) == 2


def test_pretokenize_cache_cli_losses_identical(tmp_path, tiny_parquet):
    """Full CLI: a --pretokenize-dir run reproduces the uncached loss
    sequence exactly (same data, same order)."""
    from test_fault_tolerance import _args, _losses, _run

    base_args = {"--dataset": str(tiny_parquet), "--training-steps": "10"}
    rc, plain = _run(_args(tmp_path / "a", str(tiny_parquet), **base_args),
                     job_id="ptk1")
    assert rc == 0, plain
    rc, cached = _run(_args(tmp_path / "b", str(tiny_parquet), **dict(
        base_args, **{"--pretokenize-dir": str(tmp_path / "cache")})),
        job_id="ptk2")
    assert rc == 0, cached
    assert "Pretokenization complete" in cached
    assert _losses(plain) == _losses(cached)


def test_shuffle_permutes_within_epoch(tiny_parquet, tok):
    """--shuffle: each epoch visits every row exactly once, in a seeded
    order that differs from sequential and differs between epochs
    (VERDICT r3 weak #3: the reference's strict document order produces
    loss artifacts in multi-epoch runs)."""
    ds_seq = ParquetDataset(tiny_parquet, tok, sequence_length=16,
                            training_samples=1000)
    n = ds_seq._source.real_length
    ds = ParquetDataset(tiny_parquet, tok, sequence_length=16,
                        training_samples=1000, shuffle_seed=0)

    def epoch_rows(dataset, epoch):
        return [bytes(np.asarray(dataset[epoch * n + i]["input_ids"]))
                for i in range(n)]

    seq0 = epoch_rows(ds_seq, 0)
    e0, e1 = epoch_rows(ds, 0), epoch_rows(ds, 1)
    assert sorted(e0) == sorted(seq0)  # a permutation: same multiset
    assert sorted(e1) == sorted(seq0)
    assert e0 != seq0  # actually shuffled
    assert e0 != e1    # re-shuffled per epoch
    # deterministic for the same seed
    ds2 = ParquetDataset(tiny_parquet, tok, sequence_length=16,
                         training_samples=1000, shuffle_seed=0)
    assert epoch_rows(ds2, 0) == e0


def test_shuffle_resume_mid_epoch_bit_exact(tiny_parquet, tok):
    """get_state/set_state across a mid-epoch (and mid-permutation)
    boundary reproduces the exact remaining sample stream — the O(1)
    resume contract is shuffle-invariant."""
    mk = lambda: ParquetDataset(tiny_parquet, tok, sequence_length=16,
                                training_samples=64, shuffle_seed=3)
    ref = mk()
    stream = [np.asarray(next(ref)["input_ids"]) for _ in range(40)]
    a = mk()
    for _ in range(17):
        next(a)
    state = a.get_state()
    b = mk()
    b.set_state(state)
    for i in range(17, 40):
        np.testing.assert_array_equal(np.asarray(next(b)["input_ids"]),
                                      stream[i])


def test_shuffle_mismatch_on_resume_raises(tiny_parquet, tok):
    """Resuming a shuffled checkpoint without --shuffle (or vice versa, or
    with a different seed) must fail loudly instead of silently changing
    the data order."""
    ds = ParquetDataset(tiny_parquet, tok, 16, 64, shuffle_seed=1)
    state = ds.get_state()
    plain = ParquetDataset(tiny_parquet, tok, 16, 64)
    with pytest.raises(ValueError, match="shuffle_seed"):
        plain.set_state(state)
    other = ParquetDataset(tiny_parquet, tok, 16, 64, shuffle_seed=2)
    with pytest.raises(ValueError, match="shuffle_seed"):
        other.set_state(state)
    # pre-shuffle checkpoints (no key) resume on an unshuffled run
    legacy_state = {"kind": "map", "next_index": 5}
    plain.set_state(legacy_state)
    assert plain._next_index == 5


def test_shuffle_packed_dataset_state_roundtrip(tiny_parquet, tok):
    """The packed (iterable) dataset walks the permuted document order and
    resumes bit-exactly mid-stream."""
    mk = lambda: IterableParquetDataset(tiny_parquet, tok, 16,
                                        bos_token_id=tok.bos_token_id,
                                        shuffle_seed=5)
    ref = mk()
    stream = [next(ref) for _ in range(12)]
    a = mk()
    for _ in range(7):
        next(a)
    b = mk()
    b.set_state(a.get_state())
    for i in range(7, 12):
        got = next(b)
        np.testing.assert_array_equal(got[0], stream[i][0])
        np.testing.assert_array_equal(got[1], stream[i][1])
    # shuffled vs sequential: different sample stream
    seq = IterableParquetDataset(tiny_parquet, tok, 16,
                                 bos_token_id=tok.bos_token_id)
    assert any(not np.array_equal(next(seq)[0], s[0]) for s in stream[:5])


def test_eval_holdout_excludes_rows_from_training(tiny_parquet, tok):
    """VERDICT r4 weak #6: with ``holdout_rows=k`` the training mapping
    never touches rows [0, k) — plain order, wraparound, and shuffled —
    while an eval dataset (holdout 0) reads exactly those rows."""
    k = 4
    ds = ParquetDataset(tiny_parquet, tok, 32, 64 * 3, holdout_rows=k)
    n = ds._source.real_length
    rows = {ds._row(i) for i in range(2 * n)}  # > one epoch of positions
    assert rows == set(range(k, n))  # every training row, no held-out row

    shuf = ParquetDataset(tiny_parquet, tok, 32, 64 * 3, shuffle_seed=7,
                          holdout_rows=k)
    rows = {shuf._row(i) for i in range(2 * (n - k))}  # two full epochs
    assert rows == set(range(k, n))

    packed = IterableParquetDataset(tiny_parquet, tok, 32, holdout_rows=k)
    rows = {packed._row(i) for i in range(2 * n)}
    assert rows == set(range(k, n))

    eval_ds = ParquetDataset(tiny_parquet, tok, 32, k)
    assert {eval_ds._row(i) for i in range(k)} == set(range(k))


def test_eval_holdout_state_guard(tiny_parquet, tok):
    """A resume that changes the holdout size shifts every training row —
    it must raise instead of silently remapping; equal holdout restores."""
    ds = ParquetDataset(tiny_parquet, tok, 32, 64, holdout_rows=4)
    state = ds.get_state()
    ds2 = ParquetDataset(tiny_parquet, tok, 32, 64, holdout_rows=4)
    ds2.set_state(state)  # same carve: fine
    ds3 = ParquetDataset(tiny_parquet, tok, 32, 64, holdout_rows=8)
    with pytest.raises(ValueError, match="holdout"):
        ds3.set_state(state)
    ds4 = ParquetDataset(tiny_parquet, tok, 32, 64)
    with pytest.raises(ValueError, match="holdout"):
        ds4.set_state(state)


def test_eval_holdout_rejects_whole_corpus(tiny_parquet, tok):
    with pytest.raises(ValueError, match="consumes the whole"):
        ParquetDataset(tiny_parquet, tok, 32, 64, holdout_rows=64)


def test_shuffle_fingerprint_guard(tiny_parquet, tok):
    """ADVICE r4: a checkpoint carrying a permutation fingerprint from a
    different Generator stream (e.g. another NumPy release) must refuse to
    resume instead of silently reordering data."""
    ds = ParquetDataset(tiny_parquet, tok, 32, 64, shuffle_seed=3)
    state = ds.get_state()
    assert state["shuffle_fingerprint"] is not None
    ds2 = ParquetDataset(tiny_parquet, tok, 32, 64, shuffle_seed=3)
    ds2.set_state(state)  # same stream: fine
    bad = dict(state, shuffle_fingerprint=[0] * 8)
    with pytest.raises(ValueError, match="fingerprint"):
        ds2.set_state(bad)
    legacy = {k: v for k, v in state.items() if k != "shuffle_fingerprint"}
    ds2.set_state(legacy)  # pre-r5 checkpoints lack the key: accepted


def test_feistel_shuffle_is_a_permutation_per_epoch(tiny_parquet, tok):
    """VERDICT r4 #6: the O(1)-memory Feistel option must keep the exact
    path's semantics — every row exactly once per epoch, deterministic,
    epoch-varying — without materializing any index array."""
    from fault_tolerant_llm_training_tpu.data.parquet import _feistel_row

    ds = ParquetDataset(tiny_parquet, tok, 32, 64 * 4, shuffle_seed=7,
                        shuffle_impl="feistel")
    n = ds._source.real_length
    e0 = [ds._row(i) for i in range(n)]
    e1 = [ds._row(n + i) for i in range(n)]
    assert sorted(e0) == list(range(n))  # bijection over the corpus
    assert sorted(e1) == list(range(n))
    assert e0 != e1  # epochs differ
    assert e0 != list(range(n))  # actually shuffled
    assert e0 == [ds._row(i) for i in range(n)]  # deterministic
    assert ds._perm is None  # no O(n) array was ever built
    # odd domain sizes exercise the cycle-walk
    for m in (3, 5, 17, 1000):
        assert sorted(_feistel_row(i, m, 7, 0) for i in range(m)) == \
            list(range(m))


def test_feistel_shuffle_state_roundtrip_and_guards(tiny_parquet, tok):
    """Mid-epoch resume is bit-exact; an impl mismatch on resume raises."""
    ds = ParquetDataset(tiny_parquet, tok, 32, 64 * 2, shuffle_seed=7,
                        shuffle_impl="feistel")
    for _ in range(9):
        next(ds)
    state = ds.get_state()
    rest = ParquetDataset(tiny_parquet, tok, 32, 64 * 2, shuffle_seed=7,
                          shuffle_impl="feistel")
    rest.set_state(state)
    for _ in range(5):
        a, b = next(ds), next(rest)
        np.testing.assert_array_equal(np.asarray(a["input_ids"]),
                                      np.asarray(b["input_ids"]))
    wrong = ParquetDataset(tiny_parquet, tok, 32, 64 * 2, shuffle_seed=7)
    with pytest.raises(ValueError, match="shuffle-impl"):
        wrong.set_state(state)
