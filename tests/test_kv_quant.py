"""Quantized serving: int8 paged KV pools + quantize-at-publish weights.

Layers, cheapest first:

1. numerics — symmetric per-block quantization round-trips inside the
   half-scale error bound, zero rows exactly;
2. geometry — the int8 mode halves the per-block byte cost (the capacity
   receipt the bench pins at fleet scale) and the scale pool rides every
   lifecycle primitive: offset-0 writes own their block's scale, COW
   copies carry scales bitwise, export/import round-trips q AND scale;
3. integrity — the block-artifact reject matrix holds with scale
   segments in the payload, and a bf16 artifact can never be imported
   into an int8 pool (dtype is part of the wire geometry);
4. engine — ``kv_dtype`` validation, gather-vs-pallas stream equality,
   and the within-dtype bit-exactness contracts (exact spec-verify,
   spill/restore) asserted unchanged under int8 KV;
5. deploy — ``--weights-dtype int8``: the quantized artifact publishes
   with its own CRC manifest, hot-reloads without touching the
   full-precision checkpoint, and a corrupt or step-mismatched artifact
   is rejected while serving continues.
"""

import os
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
CACHE = "/tmp/jax_test_compile_cache"


def _tiny_cfg(vocab=64, seq_len=64):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


# ------------------------------------------------------------- 1. numerics
def test_quantize_rows_roundtrip_error_bound():
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KV_QUANT_QMAX, quantize_rows)

    rng = np.random.default_rng(0)
    rows = rng.standard_normal((32, 4, 16)).astype(np.float32)
    scale = np.abs(rows).max(axis=-1) / KV_QUANT_QMAX      # (R, K)
    q = np.asarray(quantize_rows(jnp.asarray(rows), jnp.asarray(scale)))
    assert q.dtype == np.int8 and np.abs(q).max() <= KV_QUANT_QMAX
    deq = q.astype(np.float32) * scale[:, :, None]
    # round-to-nearest at the row's own amax scale: error <= scale/2
    assert (np.abs(deq - rows) <= scale[:, :, None] * 0.5 + 1e-7).all()

    # zero rows (and their zero scales) round-trip exactly
    zq = np.asarray(quantize_rows(jnp.zeros((2, 4, 16)),
                                  jnp.zeros((2, 4))))
    assert (zq == 0).all()


def test_int8_pool_halves_block_bytes():
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        QuantPool, bf16_block_bytes, block_bytes, block_layout,
        init_paged_cache)

    cfg = _tiny_cfg()
    import jax.numpy as jnp

    bf16 = init_paged_cache(cfg, slots=2, max_len=32, block_size=8)
    int8 = init_paged_cache(cfg, slots=2, max_len=32, block_size=8,
                            dtype=jnp.int8)
    assert all(isinstance(p, QuantPool) for p in int8.k + int8.v)
    assert int8.num_blocks == bf16.num_blocks
    assert int8.block_size == bf16.block_size

    # the parallel scale pool appears in the wire layout...
    fields = [str(seg["field"]) for seg in block_layout(int8)]
    assert any(f.endswith("_scale") for f in fields)
    assert not any(f.endswith("_scale")
                   for f in (str(s["field"]) for s in block_layout(bf16)))
    # ...and the capacity receipt holds: >= 1.9x blocks at a byte budget
    assert bf16_block_bytes(int8) == block_bytes(bf16)
    ratio = block_bytes(bf16) / block_bytes(int8)
    assert ratio >= 1.9, f"int8 block only {ratio:.2f}x smaller"


# ------------------------------------------------------ 2. scale lifecycle
def test_scale_set_at_offset0_and_kept_at_higher_offsets():
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KV_QUANT_QMAX, init_paged_cache, write_paged_kv)

    cfg = _tiny_cfg()
    cache = init_paged_cache(cfg, slots=1, max_len=32, block_size=8,
                             dtype=jnp.int8)
    pool = cache.k[0]
    tables = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    rng = np.random.default_rng(1)
    r0 = rng.standard_normal((1, cfg.kv_heads, 1, cfg.head_dim)) * 2.0
    pool = write_paged_kv(pool, jnp.asarray(r0, jnp.float32), tables,
                          jnp.asarray([0], jnp.int32),
                          jnp.ones((1, 1), bool))
    want = np.abs(r0[0, :, 0, :]).max(axis=-1) / KV_QUANT_QMAX
    np.testing.assert_allclose(np.asarray(pool.scale)[1], want, rtol=1e-6)

    # a LOUDER row at offset 1 quantizes at the existing scale (clipped),
    # never rewrites it — the no-requantization invariant
    scale_before = np.asarray(pool.scale).copy()
    r1 = rng.standard_normal((1, cfg.kv_heads, 1, cfg.head_dim)) * 50.0
    pool = write_paged_kv(pool, jnp.asarray(r1, jnp.float32), tables,
                          jnp.asarray([1], jnp.int32),
                          jnp.ones((1, 1), bool))
    np.testing.assert_array_equal(np.asarray(pool.scale)[1:],
                                  scale_before[1:])
    assert np.abs(np.asarray(pool.q)[1, :, 1, :]).max() == KV_QUANT_QMAX


def test_cow_copy_carries_scale_bitwise():
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        QuantPool, copy_kv_block, init_paged_cache)

    cfg = _tiny_cfg()
    cache = init_paged_cache(cfg, slots=1, max_len=32, block_size=8,
                             dtype=jnp.int8)
    rng = np.random.default_rng(2)
    pool = QuantPool(
        q=jnp.asarray(rng.integers(-127, 128, cache.k[0].q.shape),
                      jnp.int8),
        scale=jnp.asarray(rng.random(cache.k[0].scale.shape),
                          jnp.float32))
    out = copy_kv_block(pool, jnp.asarray(2), jnp.asarray(4))
    np.testing.assert_array_equal(np.asarray(out.q[4]),
                                  np.asarray(pool.q[2]))
    np.testing.assert_array_equal(np.asarray(out.scale[4]),
                                  np.asarray(pool.scale[2]))
    np.testing.assert_array_equal(np.asarray(out.q[3]),
                                  np.asarray(pool.q[3]))


# ------------------------------------------------- 3. artifact + integrity
def _filled_int8_cache(cfg, seed=0, slots=2, max_len=32, block_size=8):
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        QuantPool, init_paged_cache)

    cache = init_paged_cache(cfg, slots=slots, max_len=max_len,
                             block_size=block_size, dtype=jnp.int8)
    rng = np.random.default_rng(seed)

    def fill(p):
        return QuantPool(
            q=jnp.asarray(rng.integers(-127, 128, p.q.shape), jnp.int8),
            scale=jnp.asarray(rng.random(p.scale.shape), jnp.float32))

    return cache.replace(k=tuple(fill(p) for p in cache.k),
                         v=tuple(fill(p) for p in cache.v))


def test_export_import_roundtrips_q_and_scale(tmp_path):
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks, import_blocks, init_paged_cache,
        verify_block_artifact)

    cfg = _tiny_cfg()
    cache = _filled_int8_cache(cfg)
    d = str(tmp_path / "art")
    man = export_blocks(cache, [3, 1, 2], d, length=17,
                        meta={"request_id": "q0"})
    assert man["geometry"]["dtype"] == "int8"
    assert verify_block_artifact(d)["length"] == 17

    fresh = init_paged_cache(cfg, slots=2, max_len=32, block_size=8,
                             dtype=jnp.int8)
    out, _ = import_blocks(fresh, d, [5, 6, 7])
    for l in range(len(cache.k)):
        for src, dst in ((3, 5), (1, 6), (2, 7)):
            for pools in ((cache.k, out.k), (cache.v, out.v)):
                np.testing.assert_array_equal(
                    np.asarray(pools[1][l].q[dst]),
                    np.asarray(pools[0][l].q[src]))
                np.testing.assert_array_equal(
                    np.asarray(pools[1][l].scale[dst]),
                    np.asarray(pools[0][l].scale[src]))
        np.testing.assert_array_equal(
            np.asarray(out.k[l].q[4]),
            np.zeros_like(np.asarray(out.k[l].q[4])))


def test_import_reject_matrix_int8(tmp_path):
    """The 6-way reject matrix (flipped byte, truncated payload, missing
    payload, torn manifest, geometry mismatch, dest-count bug) holds with
    scale segments in the payload — and nothing lands on device before
    verification completes."""
    import json

    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        BLOCK_MANIFEST_NAME, KVBlockIntegrityError, export_blocks,
        import_blocks, init_paged_cache)

    cfg = _tiny_cfg()
    cache = _filled_int8_cache(cfg)
    fresh = init_paged_cache(cfg, slots=2, max_len=32, block_size=8,
                             dtype=jnp.int8)

    def fresh_artifact(name):
        d = str(tmp_path / name)
        export_blocks(cache, [3, 1], d, length=9)
        return d

    d = fresh_artifact("flip")
    p = os.path.join(d, "block_00001.bin")
    raw = bytearray(open(p, "rb").read())
    raw[7] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    with pytest.raises(KVBlockIntegrityError, match="CRC"):
        import_blocks(fresh, d, [5, 6])
    for l in range(len(fresh.k)):
        np.testing.assert_array_equal(
            np.asarray(fresh.k[l].q[5]),
            np.zeros_like(np.asarray(fresh.k[l].q[5])))

    d = fresh_artifact("trunc")
    p = os.path.join(d, "block_00000.bin")
    open(p, "wb").write(open(p, "rb").read()[:-3])
    with pytest.raises(KVBlockIntegrityError, match="size"):
        import_blocks(fresh, d, [5, 6])

    d = fresh_artifact("gone")
    os.unlink(os.path.join(d, "block_00001.bin"))
    with pytest.raises(KVBlockIntegrityError, match="missing"):
        import_blocks(fresh, d, [5, 6])

    d = fresh_artifact("torn")
    man_path = os.path.join(d, BLOCK_MANIFEST_NAME)
    man = json.load(open(man_path))
    man["files"].popitem()
    json.dump(man, open(man_path, "w"))
    with pytest.raises(KVBlockIntegrityError, match="torn"):
        import_blocks(fresh, d, [5, 6])

    # geometry: same dtype, different block size
    d = fresh_artifact("geom")
    other = init_paged_cache(cfg, slots=2, max_len=32, block_size=16,
                             dtype=jnp.int8)
    with pytest.raises(KVBlockIntegrityError, match="geometry"):
        import_blocks(other, d, [1, 2])

    d = fresh_artifact("count")
    with pytest.raises(ValueError):
        import_blocks(fresh, d, [5])


def test_mixed_dtype_import_rejected_both_ways(tmp_path):
    """dtype is wire geometry: a bf16 artifact can never scatter into an
    int8 pool (or vice versa) — the fleet's mixed-dtype-host guard."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KVBlockIntegrityError, export_blocks, import_blocks,
        init_paged_cache)

    cfg = _tiny_cfg()
    int8_cache = _filled_int8_cache(cfg)
    bf16_cache = init_paged_cache(cfg, slots=2, max_len=32, block_size=8)

    d8 = str(tmp_path / "int8")
    export_blocks(int8_cache, [1, 2], d8, length=9)
    with pytest.raises(KVBlockIntegrityError, match="geometry"):
        import_blocks(bf16_cache, d8, [1, 2])

    rng = np.random.default_rng(3)
    bf16_full = bf16_cache.replace(
        k=tuple(jnp.asarray(rng.standard_normal(a.shape), a.dtype)
                for a in bf16_cache.k),
        v=tuple(jnp.asarray(rng.standard_normal(a.shape), a.dtype)
                for a in bf16_cache.v))
    d16 = str(tmp_path / "bf16")
    export_blocks(bf16_full, [1, 2], d16, length=9)
    fresh8 = init_paged_cache(cfg, slots=2, max_len=32, block_size=8,
                              dtype=jnp.int8)
    with pytest.raises(KVBlockIntegrityError, match="geometry"):
        import_blocks(fresh8, d16, [1, 2])


# ----------------------------------------------------------- 4. the engine
def _init_params(cfg, seed=0):
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    return Transformer(cfg).init(
        jax.random.PRNGKey(seed),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]


def _streams(engine, reqs):
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    sched = Scheduler(engine, eos_token_id=None)
    for i, (prompt, gen, kw) in enumerate(reqs):
        sched.submit(Request(id=f"r{i}", prompt=list(prompt),
                             max_new_tokens=gen, **kw))
    done = sched.run()
    assert len(done) == len(reqs)
    return {c.request_id: c.tokens for c in done}, sched


def test_engine_kv_dtype_validation():
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)

    cfg = _tiny_cfg()
    params = _init_params(cfg)
    with pytest.raises(ValueError, match="unknown kv_dtype"):
        InferenceEngine(cfg, params, slots=1, max_len=32, kv_dtype="fp8")
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(cfg, params, slots=1, max_len=32,
                        kv_layout="ring", kv_dtype="int8")
    import jax.numpy as jnp

    with pytest.raises(ValueError, match="conflicts"):
        InferenceEngine(cfg, params, slots=1, max_len=32,
                        kv_layout="paged", kv_block_size=8,
                        kv_dtype="int8", cache_dtype=jnp.float32)


def test_int8_streams_deterministic_and_burst_bitmatches_per_token():
    """Within-dtype, within-kernel bit-exactness under int8, for BOTH the
    gather oracle and the fused-dequant pallas kernels: streams are
    deterministic across reset(), and burst decode bit-matches per-token
    decode. (Cross-kernel greedy agreement is NOT a contract in int8 mode
    — the oracle dequantizes through bf16 while the fused kernels keep
    the fp32 dequant in-register, so a near-tie argmax may flip; the
    kernel parity check bounds that gap numerically.)"""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import QuantPool
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    enable_compilation_cache(CACHE)
    cfg = _tiny_cfg()
    params = _init_params(cfg)
    rng = np.random.default_rng(5)
    reqs = [Request(id="g", prompt=rng.integers(3, 64, size=12).tolist(),
                    max_new_tokens=8),
            Request(id="s", prompt=rng.integers(3, 64, size=9).tolist(),
                    max_new_tokens=8, temperature=0.8, top_p=0.9, seed=7)]
    kw = dict(slots=2, max_len=32, prefill_buckets=(16,),
              kv_layout="paged", kv_block_size=8, kv_dtype="int8")

    def stream(engine, burst):
        engine.reset()
        sched = Scheduler(engine, eos_token_id=None, decode_burst=burst)
        for r in reqs:
            sched.submit(Request(id=r.id, prompt=list(r.prompt),
                                 max_new_tokens=r.max_new_tokens,
                                 temperature=r.temperature, top_p=r.top_p,
                                 seed=r.seed))
        sched.run()
        return {c.request_id: c.tokens for c in sched.completed}

    for impl in ("gather", "pallas"):
        engine = InferenceEngine(cfg, params, paged_kernel=impl, **kw)
        assert engine.kv_dtype == "int8"
        assert all(isinstance(p, QuantPool) for p in engine.cache.k)
        seq = stream(engine, burst=1)
        assert all(isinstance(p, QuantPool) for p in engine.cache.k), (
            "reset() lost the QuantPool mode")
        assert stream(engine, burst=1) == seq, (
            f"{impl}: int8 decode not deterministic across reset")
        assert stream(engine, burst=4) == seq, (
            f"{impl}: int8 burst decode diverged from per-token")
        del engine


def test_int8_fused_sampler_bitmatches_host_sampler():
    """The fused-sampling contract under int8: sampling inside the fused
    pallas decode program emits the same stream as syncing the logits
    plane and sampling on host."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.sampler import (
        sample_slot_tokens)

    enable_compilation_cache(CACHE)
    cfg = _tiny_cfg()
    params = _init_params(cfg)
    eng = InferenceEngine(cfg, params, slots=2, max_len=32,
                          prefill_buckets=(8, 16), kv_block_size=8,
                          paged_kernel="pallas", kv_dtype="int8")
    rng = np.random.default_rng(12)
    prompts = [rng.integers(3, cfg.vocab_size, size=n).tolist()
               for n in (6, 11)]
    rows = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    temperature = np.array([0.0, 0.8], np.float32)
    top_p = np.array([1.0, 0.9], np.float32)
    seeds = np.array([0, 123], np.int32)
    active = np.array([True, True])

    def run(fused):
        eng.reset()
        toks = np.array([eng.prefill(s, prompts[s], block_row=rows[s],
                                     temperature=float(temperature[s]),
                                     top_p=float(top_p[s]),
                                     seed=int(seeds[s]))
                         for s in (0, 1)], np.int32)
        stream = [toks.copy()]
        for step in range(1, 7):
            steps = np.full(2, step, np.int32)
            if fused:
                toks = eng.decode_step(toks, active, temperature, top_p,
                                       seeds, steps, block_tables=rows)
            else:
                logits = eng.decode_logits(toks, active, block_tables=rows)
                toks = np.asarray(sample_slot_tokens(
                    logits, seeds, steps, temperature, top_p, eng.top_k))
            stream.append(np.asarray(toks).copy())
        return np.stack(stream)

    np.testing.assert_array_equal(run(fused=True), run(fused=False))


def test_greedy_spec_stream_bitmatches_nonspec_under_int8():
    """The exact spec-verify contract survives quantization: with BOTH
    pools int8 (target and draft share cache_dtype), greedy spec streams
    bit-match plain int8 decode — rejected speculative rows cannot
    disturb a committed block's scale (the offset-0 ownership
    invariant)."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)

    enable_compilation_cache(CACHE)
    cfg = _tiny_cfg()
    params = _init_params(cfg, seed=0)
    draft_params = _init_params(cfg, seed=9)
    rng = np.random.default_rng(6)
    reqs = [(rng.integers(3, 64, size=n).tolist(), 8, {})
            for n in (20, 9, 13)]
    kw = dict(slots=2, max_len=48, prefill_buckets=(16,),
              kv_layout="paged", kv_block_size=16, kv_num_blocks=7,
              kv_dtype="int8")

    base = InferenceEngine(cfg, params, **kw)
    want, _ = _streams(base, reqs)
    del base

    spec = InferenceEngine(cfg, params, draft_cfg=cfg,
                           draft_params=draft_params, spec_k=2,
                           draft_num_blocks=7, **kw)
    got, sched = _streams(spec, reqs)
    assert got == want
    m = sched.metrics()
    assert m["spec_rounds"] > 0
    assert m["kv_dtype"] == "int8"
    assert m["kv_bytes_per_block"] > 0


def test_spill_restore_bitwise_under_int8(tmp_path):
    """Spill-to-host and restore stay bit-exact WITHIN the int8 mode: a
    block-starved pool producing the same streams as an unconstrained
    one proves the scale pool survives the round trip."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    enable_compilation_cache(CACHE)
    cfg = _tiny_cfg(seq_len=128)
    params = _init_params(cfg)

    def build(num_blocks=None):
        return InferenceEngine(cfg, params, slots=4, max_len=128,
                               prefill_buckets=(16, 32),
                               kv_layout="paged", kv_block_size=8,
                               kv_num_blocks=num_blocks, kv_dtype="int8")

    rng = np.random.default_rng(3)
    reqs = [Request(id="A", prompt=rng.integers(3, 64, size=17).tolist(),
                    max_new_tokens=40, seed=1),
            Request(id="B", prompt=rng.integers(3, 64, size=19).tolist(),
                    max_new_tokens=40, seed=2)]

    ref_sched = Scheduler(build())
    for r in reqs:
        ref_sched.submit(r)
    ref_sched.run()
    ref = {c.request_id: c.tokens for c in ref_sched.completed}

    sched = Scheduler(build(num_blocks=12),
                      spill_dir=str(tmp_path / "tier"))
    for r in reqs:
        sched.submit(r)
    sched.run()
    out = {c.request_id: c.tokens for c in sched.completed}
    assert out == ref
    assert sched.spill_rejects == 0


# ---------------------------------------------------- 5. quantized weights
def test_weights_artifact_publish_verify_reload(tmp_path):
    """End to end: --weights-dtype int8's artifact publishes with its own
    CRC manifest, the hot swap installs it bit-identically to an engine
    built from the artifact directly, a corrupt artifact and a
    step-mismatched sub-pointer are both rejected with serving intact."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager)
    from fault_tolerant_llm_training_tpu.deploy.publish import (
        Publisher, load_weights_artifact, quantize_tensor, read_pointer,
        verify_pointer)
    from fault_tolerant_llm_training_tpu.deploy.reload import (
        HotReloader, PointerWatcher)
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.training.state import TrainState
    from fault_tolerant_llm_training_tpu.training.step import make_optimizer

    enable_compilation_cache(CACHE)
    cfg = _tiny_cfg()
    params_a = _init_params(cfg, seed=0)
    params_b = _init_params(cfg, seed=1)
    state = TrainState(step=jnp.asarray(20, jnp.int32), params=params_b,
                       opt_state=make_optimizer(1e-4, 1).init(params_b))
    mngr = CheckpointManager(str(tmp_path), "pub", enable_async=False,
                             max_to_keep=4)
    mngr.save(20, state, {"next_index": 0}, wait=True)
    mngr.close()

    # per-tensor quantization error bound, on a real leaf
    import jax

    leaf = np.asarray(jax.tree_util.tree_leaves(params_b)[0], np.float32)
    q, s = quantize_tensor(leaf)
    assert q.dtype == np.int8
    assert (np.abs(q.astype(np.float32) * s - leaf) <= s * 0.5 + 1e-7).all()

    pub = Publisher(str(tmp_path), "pub")
    w = pub.quantize_weights(20, cfg)
    assert w["dtype"] == "int8" and w["nbytes"] > 0
    ptr = pub.publish(20, weights=w)
    assert ptr.weights == w
    assert verify_pointer(str(tmp_path), ptr) == (True, "ok")
    # int8 payload: at most half the bf16 checkpoint's parameter bytes
    assert w["nbytes"] * 2 <= sum(
        a.nbytes for a in jax.tree_util.tree_leaves(params_b))

    def fresh_engine():
        e = InferenceEngine(cfg, params_a, slots=2, max_len=48)
        e.restored_step = 0
        return e

    engine = fresh_engine()
    sched = Scheduler(engine)
    reloader = HotReloader(engine, sched, cfg, str(tmp_path))
    assert reloader.maybe_reload(PointerWatcher(str(tmp_path)).poll())
    assert engine.restored_step == 20 and reloader.rejects == 0

    prompt = [5, 9, 2, 14, 7]

    def run(sch, rid):
        sch.submit(Request(id=rid, prompt=list(prompt), max_new_tokens=8,
                           temperature=0.0))
        done = []
        while sch.pending():
            done.extend(sch.step())
        return {c.request_id: c.tokens for c in done}[rid]

    got = run(sched, "swapped")
    ref_engine = InferenceEngine(cfg, load_weights_artifact(
        str(tmp_path), w), slots=2, max_len=48)
    assert got == run(Scheduler(ref_engine), "ref"), (
        "post-swap stream diverged from the artifact's weights")

    # corrupt one payload byte: verify-before-load rejects, serving holds
    victim = os.path.join(str(tmp_path), w["path"], "t0000.npy")
    raw = bytearray(open(victim, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(raw))
    engine2 = fresh_engine()
    sched2 = Scheduler(engine2)
    rel2 = HotReloader(engine2, sched2, cfg, str(tmp_path))
    assert rel2.maybe_reload(read_pointer(str(tmp_path))) is False
    assert rel2.rejects == 1 and engine2.restored_step == 0
    assert sched2.admission_open
    raw[len(raw) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(raw))

    # a weights sub-entry naming the wrong step is rejected up front
    pub.publish(20, weights=dict(w, step=19))
    engine3 = fresh_engine()
    rel3 = HotReloader(engine3, Scheduler(engine3), cfg, str(tmp_path))
    assert rel3.maybe_reload(read_pointer(str(tmp_path))) is False
    assert rel3.rejects == 1 and engine3.restored_step == 0
