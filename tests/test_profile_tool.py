"""scripts/profile_step.py trace parsing (hermetic: synthetic trace file)."""

import gzip
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "profile_step", REPO / "scripts" / "profile_step.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules["profile_step"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_parse_trace_aggregates_device_ops(tmp_path):
    trace = {
        "traceEvents": [
            {"ph": "M", "pid": 3, "name": "process_name",
             "args": {"name": "/device:TPU:0"}},
            {"ph": "M", "pid": 9, "name": "process_name",
             "args": {"name": "/host:CPU"}},
            # device ops: two fusions (same family), one pallas call, the
            # whole-program span and a lane aggregate (both skipped)
            {"ph": "X", "pid": 3, "name": "fusion.12", "dur": 3000},
            {"ph": "X", "pid": 3, "name": "fusion.7", "dur": 1000},
            {"ph": "X", "pid": 3, "name": "attention.4", "dur": 2000},
            {"ph": "X", "pid": 3, "name": "jit_train_step(123)", "dur": 9999},
            {"ph": "X", "pid": 3, "name": "1", "dur": 8888},
            # host-side op: must be ignored
            {"ph": "X", "pid": 9, "name": "fusion.99", "dur": 7777},
        ]
    }
    d = tmp_path / "plugins" / "profile" / "run1"
    d.mkdir(parents=True)
    with gzip.open(d / "vm.trace.json.gz", "wt") as fh:
        json.dump(trace, fh)

    tool = _load_tool()
    cats, total = tool.parse_trace(str(tmp_path), steps=2)
    # durations are us over 2 steps -> ms/step
    assert cats == {"fusion": 2.0, "attention": 1.0}
    assert total == 3.0


def test_parse_trace_missing_dir_raises(tmp_path):
    tool = _load_tool()
    try:
        tool.parse_trace(str(tmp_path / "nope"), steps=1)
    except FileNotFoundError:
        return
    raise AssertionError("expected FileNotFoundError")
