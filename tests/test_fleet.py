"""Serving fleet (ft/lease.py, ft/retry.py, inference/journal.py,
inference/router.py, inference/scheduler.py replay admission).

Four layers of evidence:

1. substrate — bounded-deadline retry semantics under a fake clock, and
   the file KV store's atomic round-trips;
2. membership — lease expiry renders a dead verdict, tombstones fence,
   and a host that cannot renew self-fences (all fake-clock, no sleeps);
3. journal — per-writer append files fold to one per-request state,
   requeue/migrate generations outrank stale assigns, prefix-divergent
   committed streams raise (the determinism contract is checked, not
   assumed), and a torn tail from a SIGKILLed writer is skipped;
4. migration — the router assigns by free-block count, never migrates
   the same dead host twice, completes fully-committed requests in
   place, and — on a REAL tiny engine — a request re-admitted from its
   journaled committed prefix continues bit-identically to the unfailed
   stream for both greedy and sampled decoding, with the survivor's
   block-leak audit clean afterwards.
"""

import json
import os

import pytest

from fault_tolerant_llm_training_tpu.ft.lease import (
    FileKVStore,
    LeaseRegistry,
)
from fault_tolerant_llm_training_tpu.ft.retry import (
    RetryDeadlineExceeded,
    retry_with_backoff,
)

@pytest.fixture(autouse=True, scope="module")
def _inference_names():
    # inference/ must not be imported at collect time
    # (test_no_test_module_imports_inference_at_module_scope); these names
    # are used in ~every test below, so bind them at run time instead of
    # repeating the import in each function.
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal,
        fold,
        persist_unserved,
    )
    from fault_tolerant_llm_training_tpu.inference.router import Router

    globals().update(RequestJournal=RequestJournal, fold=fold,
                     persist_unserved=persist_unserved, Router=Router)


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


# ------------------------------------------------------------- 1. retry layer
def test_retry_succeeds_after_transient_failures():
    clock = _Clock()
    calls = []

    def flaky():
        calls.append(clock.t)
        if len(calls) < 3:
            raise OSError("transient")
        return "value"

    out = retry_with_backoff(flaky, deadline_seconds=5.0, clock=clock,
                             sleep=clock.sleep)
    assert out == "value"
    assert len(calls) == 3


def test_retry_deadline_is_bounded_and_raises():
    clock = _Clock()

    def always_down():
        raise OSError("store down")

    with pytest.raises(RetryDeadlineExceeded) as ei:
        retry_with_backoff(always_down, deadline_seconds=2.0, clock=clock,
                           sleep=clock.sleep, what="lease renew")
    # one deadline for the WHOLE call: the fake clock advanced past it and
    # no further (backoff is clipped to the remaining window)
    assert clock.t - 100.0 <= 2.0 + 1e-6
    assert ei.value.attempts >= 2
    assert "lease renew" in str(ei.value)


def test_retry_does_not_catch_unlisted_exceptions():
    with pytest.raises(KeyError):
        retry_with_backoff(lambda: {}["missing"], deadline_seconds=1.0,
                           clock=_Clock(), sleep=lambda dt: None)


# ---------------------------------------------------------------- 2. KV store
def test_kv_store_round_trip_and_list(tmp_path):
    store = FileKVStore(str(tmp_path / "kv"))
    assert store.get("fleet/lease/h0") is None
    store.set("fleet/lease/h0", "a")
    store.set("fleet/lease/h1", "b")
    store.set("fleet/lease/h0", "a2")  # atomic replace
    assert store.get("fleet/lease/h0") == "a2"
    assert store.list("fleet/lease") == {"h0": "a2", "h1": "b"}
    store.delete("fleet/lease/h0")
    assert store.get("fleet/lease/h0") is None
    with pytest.raises(ValueError):
        store.set("../escape", "nope")


# --------------------------------------------------------------- 3. membership
def _registry(store, host_id, clock):
    return LeaseRegistry(store, host_id=host_id, ttl_seconds=2.0,
                         clock=clock, monotonic=clock, sleep=clock.sleep)


def test_lease_expiry_renders_dead_verdict(tmp_path):
    clock = _Clock()
    store = FileKVStore(str(tmp_path / "kv"))
    h0 = _registry(store, "h0", clock)
    h1 = _registry(store, "h1", clock)
    router = _registry(store, None, clock)
    assert h0.register(2, 30, 16)
    assert h1.register(2, 30, 16)
    assert router.live() == ["h0", "h1"]
    assert router.dead() == []

    # h0 stops renewing; h1 keeps its heartbeat
    clock.t += 1.5
    assert h1.renew(1, 20, 16)
    clock.t += 1.0  # h0's lease is now 2.5s old > ttl 2.0
    assert router.live() == ["h1"]
    assert router.dead() == ["h0"]
    leases = router.leases()
    assert not leases["h0"].live and leases["h0"].age > 2.0
    assert leases["h1"].slots_free == 1 and leases["h1"].blocks_free == 20


def test_tombstone_fences_even_a_live_lease(tmp_path):
    clock = _Clock()
    store = FileKVStore(str(tmp_path / "kv"))
    h0 = _registry(store, "h0", clock)
    router = _registry(store, None, clock)
    assert h0.register(2, 30, 16)
    assert not h0.fenced()
    router.tombstone("h0")
    assert h0.fenced()  # sticky verdict: renewal cannot un-fence
    assert h0.renew(2, 30, 16) and h0.fenced()
    assert router.dead() == ["h0"] and router.live() == []


def test_host_self_fences_when_renewal_goes_stale(tmp_path):
    clock = _Clock()
    h0 = _registry(FileKVStore(str(tmp_path / "kv")), "h0", clock)
    assert h0.register(2, 30, 16)
    clock.t += 1.0
    assert not h0.fenced()
    clock.t += 1.5  # 2.5s since the last successful renewal > ttl
    assert h0.fenced()


# ------------------------------------------------------------------ 4. journal
def _params(rid="reqA", prompt=(1, 2, 3)):
    return dict(request_id=rid, prompt=list(prompt), max_new_tokens=8,
                temperature=0.0, top_p=1.0, seed=7)


def test_journal_fold_round_trip(tmp_path):
    jd = str(tmp_path / "journal")
    router = RequestJournal(jd, writer="router")
    host = RequestJournal(jd, writer="host_h0")
    p = _params()
    router.assign(p["request_id"], "h0", p["prompt"], p["max_new_tokens"],
                  p["temperature"], p["top_p"], p["seed"])
    host.progress("reqA", "h0", [5], gen=0)
    host.progress("reqA", "h0", [5, 6], gen=0)
    st = fold(jd)["reqA"]
    assert (st.host, st.gen, st.committed, st.done) == ("h0", 0, [5, 6],
                                                        False)
    assert st.prompt == [1, 2, 3] and st.seed == 7
    host.done("reqA", "h0", [5, 6, 7], "length", gen=0)
    st = fold(jd)["reqA"]
    assert st.done and st.done_tokens == [5, 6, 7] and st.reason == "length"
    assert st.committed == [5, 6, 7]


def test_journal_migrate_outranks_stale_assign(tmp_path):
    jd = str(tmp_path / "journal")
    router = RequestJournal(jd, writer="router")
    p = _params()
    router.assign("reqA", "h0", p["prompt"], 8, 0.0, 1.0, 7)
    router.migrate("reqA", "h0", "h1", gen=1, prompt=p["prompt"],
                   max_new_tokens=8, temperature=0.0, top_p=1.0, seed=7,
                   committed=[5, 6])
    st = fold(jd)["reqA"]
    assert (st.host, st.gen, st.migrations) == ("h1", 1, 1)
    assert st.committed == [5, 6]


def test_journal_divergent_streams_raise(tmp_path):
    jd = str(tmp_path / "journal")
    host = RequestJournal(jd, writer="host_h0")
    host.progress("reqA", "h0", [5, 6], gen=0)
    host.progress("reqA", "h0", [5, 9, 9], gen=0)  # NOT a prefix extension
    with pytest.raises(ValueError, match="journal divergence"):
        fold(jd)


def test_journal_torn_tail_is_skipped(tmp_path):
    jd = str(tmp_path / "journal")
    host = RequestJournal(jd, writer="host_h0")
    host.progress("reqA", "h0", [5], gen=0)
    with open(host.path, "a") as fh:
        fh.write('{"kind":"progress","id":"reqA","committed":[5,6')  # torn
    assert fold(jd)["reqA"].committed == [5]


def test_persist_unserved_writes_requeue_at_next_gen(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    jd = str(tmp_path / "journal")
    router = RequestJournal(jd, writer="router")
    p = _params()
    router.assign("reqA", "h0", p["prompt"], 8, 0.0, 1.0, 7)
    host = RequestJournal(jd, writer="host_h0")
    n = persist_unserved(
        host, [Request(id="reqA", prompt=[1, 2, 3], max_new_tokens=8,
                       seed=7, committed=(5,))],
        reason="drain", gens={"reqA": 0})
    assert n == 1
    st = fold(jd)["reqA"]
    # the requeue outranks the assign regardless of file read order
    assert st.requeued and st.host is None and st.gen == 1
    assert st.committed == [5]


# ---------------------------------------------------- 5. router state machine
def _fleet(tmp_path):
    clock = _Clock()
    store = FileKVStore(str(tmp_path / "kv"))
    jd = str(tmp_path / "journal")
    router = Router(store, jd, clock=clock)
    # Router's lease registry must share the fake clock end to end
    router.lease.monotonic = clock
    router.lease.sleep = clock.sleep
    return clock, store, jd, router


def test_router_assigns_to_host_with_most_free_blocks(tmp_path):
    clock, store, jd, router = _fleet(tmp_path)
    _registry(store, "h0", clock).register(1, 10, 16)
    _registry(store, "h1", clock).register(1, 40, 16)
    router.submit("reqA", [1, 2, 3], 8, 0.0, 1.0, 7)
    router.refresh()
    assert router.assign_pending() == 1
    assert fold(jd)["reqA"].host == "h1"
    # the estimate was charged locally: a second request (before any new
    # heartbeat) must not dogpile h1 once its slot estimate is consumed
    router.submit("reqB", [4, 5], 8, 0.0, 1.0, 8)
    assert router.assign_pending() == 1
    assert fold(jd)["reqB"].host == "h0"


def test_router_holds_requests_with_no_live_host(tmp_path):
    clock, store, jd, router = _fleet(tmp_path)
    router.submit("reqA", [1, 2, 3], 8, 0.0, 1.0, 7)
    assert router.assign_pending() == 0
    assert len(router.pending) == 1
    _registry(store, "h0", clock).register(2, 30, 16)
    router.refresh()
    assert router.assign_pending() == 1
    assert fold(jd)["reqA"].host == "h0"


def test_router_sweep_migrates_dead_host_exactly_once(tmp_path):
    clock, store, jd, router = _fleet(tmp_path)
    h0 = _registry(store, "h0", clock)
    h1 = _registry(store, "h1", clock)
    h0.register(2, 30, 16)
    h1.register(2, 30, 16)
    router.submit("reqA", [1, 2, 3], 8, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()
    victim = fold(jd)["reqA"].host
    survivor = "h1" if victim == "h0" else "h0"
    RequestJournal(jd, writer=f"host_{victim}").progress(
        "reqA", victim, [5, 6], gen=0)

    clock.t += 3.0  # victim's lease expires; survivor renews
    (h1 if survivor == "h1" else h0).renew(2, 30, 16)
    assert router.sweep() == 1
    router.assign_pending()
    st = fold(jd)["reqA"]
    assert (st.host, st.gen, st.committed) == (survivor, 1, [5, 6])
    assert router.lease.is_tombstoned(victim)

    # a fresh router (restart) sweeps again: the request already moved,
    # so the second verdict migrates nothing — exactly-once by fold
    router2 = Router(store, jd, clock=clock)
    router2.lease.monotonic = clock
    assert router2.sweep() == 0
    assert fold(jd)["reqA"].migrations == 1


def test_router_completes_fully_committed_migration_in_place(tmp_path):
    clock, store, jd, router = _fleet(tmp_path)
    _registry(store, "h0", clock).register(2, 30, 16)
    router.submit("reqA", [1, 2, 3], 4, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()
    # h0 journaled all 4 tokens but died before the done record landed
    RequestJournal(jd, writer="host_h0").progress(
        "reqA", "h0", [5, 6, 7, 8], gen=0)
    clock.t += 3.0
    router.sweep()
    router.assign_pending()
    st = fold(jd)["reqA"]
    assert st.done and st.reason == "length" and st.done_tokens == [5, 6, 7, 8]
    assert st.migrations == 0  # completed from the journal, not re-decoded


def test_router_adopts_requeued_requests(tmp_path):
    clock, store, jd, router = _fleet(tmp_path)
    # a draining serve.py persisted an unserved request (gen bump included)
    serve = RequestJournal(jd, writer="serve_123")
    serve.requeue("reqA", [1, 2, 3], 8, 0.0, 1.0, 7, committed=[],
                  gen=1)
    _registry(store, "h0", clock).register(2, 30, 16)
    router.refresh()
    assert router.adopt_requeued() == 1
    assert router.adopt_requeued() == 0  # idempotent while pending
    router.assign_pending()
    st = fold(jd)["reqA"]
    assert st.host == "h0" and st.gen == 2 and not st.requeued
    assert router.adopt_requeued() == 0  # and after re-admission


def test_fleet_metric_names_on_registry():
    from fault_tolerant_llm_training_tpu.obs.registry import REGISTRY

    text = REGISTRY.render()
    for name in ("fleet_hosts_live", "requests_migrated_total",
                 "fleet_lease_age_seconds"):
        assert name in text


# ------------------------------------------- 6. bit-exact migration (real engine)
@pytest.mark.parametrize("temperature", [0.0, 0.8])
def test_migrated_stream_bitmatches_unfailed_run(tmp_path, temperature):
    """The zero-lost guarantee's strong form: re-admitting a request from
    its journaled committed prefix (prompt + committed replay, fold_in
    PRNG) continues the EXACT stream the dead host would have produced —
    greedy and sampled — and the survivor drains leak-clean."""
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine,
    )
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request,
        Scheduler,
    )
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    cfg = get_config("tiny", vocab_size=64, seq_len=64, layer_impl="loop")
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def run(committed=()):
        engine = InferenceEngine(cfg, params, slots=2, max_len=48)
        sched = Scheduler(engine)
        sched.submit(Request(id="r", prompt=[5, 9, 2, 7],
                             max_new_tokens=10, temperature=temperature,
                             seed=123, committed=tuple(committed)))
        while sched.pending():
            sched.step()
        sched.audit_block_leaks(strict=True)  # survivor leak guard
        return sched.completed[-1].tokens

    full = run()
    assert len(full) == 10
    for cut in (1, 4, 9):
        assert run(committed=full[:cut]) == full, (
            f"replay from {cut} committed token(s) diverged "
            f"(temperature={temperature})")


def test_scheduler_rejects_fully_committed_submission():
    """A request whose committed prefix already reaches max_new_tokens has
    nothing to decode: the router must complete it from the journal, and
    the scheduler refuses it loudly rather than underflowing the replay."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request,
        Scheduler,
    )

    class _NoEngine:
        slots = 1
        max_len = 64

    sched = Scheduler(_NoEngine())
    with pytest.raises(ValueError, match="nothing to decode"):
        sched.submit(Request(id="r", prompt=[1], max_new_tokens=2,
                             committed=(3, 4)))
