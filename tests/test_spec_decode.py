"""Speculative decoding (inference/ spec mode): five layers of evidence.

1. kernel — ``spec_accept`` degenerates to exact argmax matching for
   greedy rows, and for sampled rows its emitted tokens follow the TARGET
   distribution in closed form (the Leviathan/Chen guarantee) on a
   3-token toy vocab;
2. numerics — ``verify_with_cache``'s chunked scoring agrees with the
   sequential S=1 steps it replaces (argmax + allclose on an fp32 model;
   the engine's AOT verify program micro-steps S=1 shapes precisely so
   this agreement is bitwise in production — engine.py ``_verify_fn``);
3. streams — a greedy speculative stream is BIT-identical to the
   non-speculative paged path across chunked prefill and block-pool
   eviction/refill (slow: builds two real engines);
4. lifecycle — dual-pool admission/rollback/double-free contracts and
   mid-prompt drain exactness, pinned against a fake spec engine;
5. tree — multi-branch rejection matches the target law in closed form,
   scheduler tree rounds refeed/bank/attribute branches correctly and
   drain leak-free, greedy EXACT-mode tree streams (prefix caches on AND
   off, draft mirror included) bit-match non-spec decode, and the
   ``fork_slot`` COW beam primitive honors the allocator contract.

Module scope imports nothing from the package: the collect-only guard at
the bottom asserts NO test module pays the draft path's import cost (or
any inference/ import) at collection time.
"""

import ast
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
CACHE = "/tmp/jax_test_compile_cache"


# ------------------------------------------------------- 1. accept kernel
def test_spec_accept_greedy_is_exact_argmax_matching():
    """With temperature <= 0 both q and p are one-hots: the accept test
    ``u * q(d) < p(d)`` keeps exactly the leading run of draft tokens that
    equal the target argmax, and the bonus/correction token IS the target
    argmax at the first divergence — so greedy needs no randomness and the
    emitted prefix equals what sequential argmax decoding would produce."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.sampler import spec_accept

    rng = np.random.default_rng(0)
    v, k = 7, 3
    for trial in range(50):
        target_logits = rng.normal(size=(k + 1, v)).astype(np.float32)
        draft_tokens = rng.integers(0, v, size=k).astype(np.int32)
        # greedy draft distributions are one-hots at the proposal
        draft_probs = np.eye(v, dtype=np.float32)[draft_tokens]
        out, acc = spec_accept(
            jnp.asarray(draft_tokens), jnp.asarray(draft_probs),
            jnp.asarray(target_logits),
            jax.random.PRNGKey(trial), jnp.float32(0.0), jnp.float32(1.0))
        argmax = target_logits.argmax(axis=-1)
        expect_a = 0
        while expect_a < k and draft_tokens[expect_a] == argmax[expect_a]:
            expect_a += 1
        assert int(acc) == expect_a
        expected = list(draft_tokens[:expect_a]) + [argmax[expect_a]]
        assert np.asarray(out)[: expect_a + 1].tolist() == expected


def test_spec_rejection_sampling_matches_target_distribution():
    """k=1 on a 3-token vocab with draft law q != target law p: across many
    independent rounds the emitted first token must be distributed as p
    EXACTLY (not as q, not as some blend), and the acceptance probability
    equals sum_a min(p_a, q_a) — the closed forms from Leviathan et al.
    2023, Thm 1. Empirical check at ~4 sigma on 8000 trials."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.sampler import spec_accept

    q = jnp.asarray([0.5, 0.3, 0.2], jnp.float32)
    p = np.array([0.2, 0.5, 0.3], np.float32)
    target_logits = jnp.log(jnp.asarray(p))[None, :].repeat(2, axis=0)
    n = 8000

    def one_round(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q)).astype(jnp.int32)
        out, acc = spec_accept(d[None], q[None, :], target_logits, ka,
                               jnp.float32(1.0), jnp.float32(1.0))
        return out[0], (acc > 0).astype(jnp.int32)

    keys = jax.random.split(jax.random.PRNGKey(42), n)
    toks, accepted = jax.jit(jax.vmap(one_round))(keys)
    toks, accepted = np.asarray(toks), np.asarray(accepted)

    emp = np.bincount(toks, minlength=3) / n
    se = np.sqrt(p * (1 - p) / n)
    np.testing.assert_allclose(emp, p, atol=float((4 * se).max()))
    accept_rate = accepted.mean()
    expect_accept = float(np.minimum(p, np.asarray(q)).sum())
    se_a = np.sqrt(expect_accept * (1 - expect_accept) / n)
    assert abs(accept_rate - expect_accept) < 4 * se_a


# ---------------------------------------------------- 2. verify-k numerics
def test_verify_chunk_scores_agree_with_sequential_steps():
    """``verify_with_cache`` scores (B, k+1) candidates in one forward; its
    row j must agree with the j-th sequential S=1 ``forward_with_cache``
    step on the same committed prefix — same masked attention, same
    positions. On an fp32 model the two differ only by shape-dependent
    matmul accumulation order, so argmax equality plus allclose pins the
    contract (the engine's AOT verify program micro-steps the S=1 shapes
    exactly, making this agreement bitwise in production)."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        init_paged_cache)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = get_config("tiny", vocab_size=64, seq_len=64,
                     dtype=jnp.float32, param_dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(3, 64, size=(1, 8)), jnp.int32)
    cand = jnp.asarray(rng.integers(3, 64, size=(1, 3)), jnp.int32)

    bs = 8
    cache = init_paged_cache(cfg, slots=1, max_len=32, block_size=bs)
    tables = jnp.arange(1, 32 // bs + 1, dtype=jnp.int32)[None, :]
    _, (k0, v0) = model.apply(
        {"params": params}, prompt, cache.k, cache.v,
        jnp.zeros((1,), jnp.int32), block_tables=tables,
        method="forward_with_cache")

    offsets = jnp.full((1,), 8, jnp.int32)
    chunk, _ = model.apply(
        {"params": params}, cand, k0, v0, offsets, block_tables=tables,
        method="verify_with_cache")

    ck, cv, rows = k0, v0, []
    for j in range(3):
        step, (ck, cv) = model.apply(
            {"params": params}, cand[:, j:j + 1], ck, cv, offsets + j,
            block_tables=tables, method="forward_with_cache")
        rows.append(np.asarray(step)[:, 0])
    seq_logits = np.stack(rows, axis=1)

    np.testing.assert_allclose(np.asarray(chunk), seq_logits,
                               rtol=1e-5, atol=1e-5)
    assert (np.asarray(chunk).argmax(-1) == seq_logits.argmax(-1)).all()


# ----------------------------------------------------- 3. stream equality
@pytest.mark.slow
def test_greedy_spec_stream_bitmatches_nonspec_paged():
    """End to end: the same request set (chunked long prompts, more
    requests than the block pools admit at once, so slots evict and refill
    into reused blocks) generates BIT-identical greedy token streams with
    and without speculation — the tentpole invariant. The draft is an
    independently-initialized model, so acceptance is poor: exactness must
    come from the verify/commit path, not from a lucky good draft."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    draft_params = Transformer(cfg).init(
        jax.random.PRNGKey(9),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    rng = np.random.default_rng(5)
    lens = [20, 9, 36, 13, 20, 5]  # 36 and 20 exceed the 16 bucket: chunked
    reqs = [(rng.integers(3, 64, size=n).tolist(), 10) for n in lens]
    kw = dict(slots=2, max_len=48, prefill_buckets=(16,), kv_layout="paged",
              kv_block_size=16, kv_num_blocks=7)  # 6 usable: 2 concurrent

    def streams(engine):
        sched = Scheduler(engine, eos_token_id=None)
        for i, (prompt, gen) in enumerate(reqs):
            sched.submit(Request(id=f"r{i}", prompt=prompt,
                                 max_new_tokens=gen))
        done = sched.run()
        assert len(done) == len(reqs)
        return {c.request_id: c.tokens for c in done}, sched

    base = InferenceEngine(cfg, params, **kw)
    want, _ = streams(base)
    del base

    spec = InferenceEngine(cfg, params, draft_cfg=cfg,
                           draft_params=draft_params, spec_k=2,
                           draft_num_blocks=7, **kw)
    got, sched = streams(spec)
    assert got == want
    # both pools fully drained back to the free lists via a prefix-cache
    # flush each: committed prompt blocks stay cache-held after drain in
    # BOTH pools now (the draft runs a mirror of the target's radix tree)
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity
    assert (sched.draft_allocator.used_count
            == sched.draft_prefix_cache.cached_blocks)
    sched.draft_prefix_cache.flush()
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity
    m = sched.metrics()
    assert m["spec_rounds"] > 0 and m["spec_draft_tokens"] > 0


# ------------------------------------------------ 4. dual-pool lifecycle
class _FakeSpecEngine:
    """Host-side double of the spec engine: chunked prefill that consults
    ``stop_check`` between chunks, and accept-all spec rounds. Lets the
    scheduler's dual-pool bookkeeping be pinned without any compiles."""

    kv_layout = "paged"

    def __init__(self, slots=2, block_size=4, num_blocks=13,
                 draft_num_blocks=13, spec_k=2, max_len=32):
        self.slots, self.block_size = slots, block_size
        self.num_blocks, self.draft_num_blocks = num_blocks, draft_num_blocks
        self.spec_k, self.max_len = spec_k, max_len
        self.max_blocks_per_slot = -(-max_len // block_size)
        self.prefill_chunk = 4

    def prefill(self, slot, prompt, block_row=None, draft_block_row=None,
                temperature=0.0, top_p=1.0, seed=0, stop_check=None,
                on_chunk=None):
        start = 0
        while start < len(prompt):
            if on_chunk is not None:
                on_chunk()
            start += self.prefill_chunk
            if start < len(prompt) and stop_check is not None and stop_check():
                return None  # drain fired between chunks
        return 1

    def spec_round(self, tokens, lengths, active, temperature, top_p, seeds,
                   steps, block_tables=None, draft_block_tables=None):
        out = np.full((self.slots, self.spec_k + 1), 2, np.int32)
        acc = np.full((self.slots,), self.spec_k, np.int32)
        return out, acc


def test_mid_prompt_drain_frees_both_pools_and_reports_unserved():
    """A drain signal landing BETWEEN prefill chunks must abort the
    admission, free the target AND draft blocks it grabbed, report the
    request unserved, and let already-active requests run to completion —
    the signal-drain exactness contract extended to the dual-pool mode."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeSpecEngine(slots=2)
    chunks = {"n": 0}
    sched = Scheduler(eng, eos_token_id=None,
                      stop_check=lambda: chunks["n"] >= 2)
    orig = sched._count_chunk

    def counting():
        chunks["n"] += 1
        orig()

    sched._count_chunk = counting
    sched.submit(Request(id="short", prompt=[1] * 4, max_new_tokens=6))
    sched.submit(Request(id="long", prompt=[1] * 12, max_new_tokens=6))
    done = sched.run()

    assert [c.request_id for c in done] == ["short"]
    assert [r.id for r in sched.unserved()] == ["long"]
    assert not sched.admission_open
    # every block of both pools is back on the free lists; the long
    # request's partial grab did not leak
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity
    assert (sched.block_tables == 0).all()
    assert (sched.draft_block_tables == 0).all()
    # the accept-all fake banks k+1 tokens per round: 2 rounds for 6
    sc = done[0]
    assert sc.spec_proposed > 0 and sc.spec_emitted_not_proposed > 0


def test_draft_pool_shortage_rolls_back_target_grab():
    """Combined-footprint admission: when the draft pool cannot cover the
    head of the queue, the target blocks already grabbed for it must be
    returned immediately (not stranded until the request eventually
    admits), and the request waits FIFO until BOTH pools can cover it."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    # target pool covers two 3-block requests, draft pool only one
    eng = _FakeSpecEngine(slots=2, num_blocks=13, draft_num_blocks=4,
                          max_len=12)
    sched = Scheduler(eng, eos_token_id=None)
    sched.submit(Request(id="a", prompt=[1] * 6, max_new_tokens=6))
    sched.submit(Request(id="b", prompt=[1] * 6, max_new_tokens=6))
    sched.step()
    assert len(sched.active) == 1
    # b's aborted admission left NO target blocks allocated beyond a's
    assert (sched.allocator.used_count
            == sched._blocks_needed(sched.active[0].request))
    done = sched.run()
    assert {c.request_id for c in done} == {"a", "b"}
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity


def test_block_allocator_double_free_raises():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)

    alloc = BlockAllocator(num_blocks=5)
    blocks = alloc.alloc(3)
    assert blocks is not None and alloc.free_count == 1
    assert alloc.alloc(2) is None  # exhaustion queues, never crashes
    alloc.free(blocks)
    assert alloc.free_count == alloc.capacity
    with pytest.raises(ValueError, match="double free"):
        alloc.free(blocks)


# ------------------------------------------------- 5. tree speculation
def test_tree_accept_multibranch_matches_target_distribution():
    """Multi-branch rejection on a 3-token vocab, shape (2,): the primary
    child is sampled from its draft law q, the sibling is a deterministic
    pick (given the primary) whose honest proposal law is therefore a
    point mass — exactly the one-hot q row the engine writes for
    siblings. Every branch trial is a valid rejection-sampling step, so
    the FIRST emitted token's marginal must be the target p EXACTLY, and
    the acceptance rate has a closed form strictly above linear
    speculation's sum(min(p, q)). Checked at ~4 sigma on 8000 rounds.

    Closed form for this construction (p=[.2,.5,.3], q=[.5,.3,.2],
    sibling = primary+1 mod 3): linear acceptance sum(min(p,q)) = 0.7;
    only primary 0 can be rejected (mass .5 * .6 = .3), the residual is
    [0, 2/3, 1/3] and its sibling is token 1 — accepted with prob 2/3 —
    so tree acceptance = 0.7 + 0.3 * 2/3 = 0.9."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.sampler import tree_accept

    q = jnp.asarray([0.5, 0.3, 0.2], jnp.float32)
    p = np.array([0.2, 0.5, 0.3], np.float32)
    child = jnp.asarray([[1, 2], [-1, -1], [-1, -1]], jnp.int32)
    logits = jnp.log(jnp.asarray(p))[None, :].repeat(3, axis=0)
    n = 8000

    def one_round(key):
        kd, ka = jax.random.split(key)
        t0 = jax.random.categorical(kd, jnp.log(q)).astype(jnp.int32)
        sib = (t0 + 1) % 3
        toks = jnp.stack([jnp.int32(0), t0, sib])
        probs = jnp.stack([q, q, jax.nn.one_hot(sib, 3)])
        out, path, a = tree_accept(toks, probs, logits, ka,
                                   jnp.float32(1.0), jnp.float32(1.0),
                                   child, 1)
        return out[0], a

    keys = jax.random.split(jax.random.PRNGKey(7), n)
    toks, acc = jax.jit(jax.vmap(one_round))(keys)
    toks, acc = np.asarray(toks), np.asarray(acc)

    emp = np.bincount(toks, minlength=3) / n
    se = np.sqrt(p * (1 - p) / n)
    np.testing.assert_allclose(emp, p, atol=float((4 * se).max()))
    expect_accept = 0.9
    se_a = np.sqrt(expect_accept * (1 - expect_accept) / n)
    assert abs(acc.mean() - expect_accept) < 4 * se_a


def test_tree_round_banking_attributes_branches_and_drains_clean():
    """Scheduler tree rounds against a host-side double: refeed windows
    carry exactly the tokens the previous round banked (prefill = round 0
    with one token), acceptance lands in the spec counters under the tree
    budget, off-primary path rows feed the branch-utilization gauge, and
    a mid-stream drain leaves both pools leak-free (strict leak guard
    runs inside Scheduler.run)."""
    from fault_tolerant_llm_training_tpu.inference.engine import TreeShape
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    shape = TreeShape((2, 1))

    class _FakeTreeEngine(_FakeSpecEngine):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.spec_tree = shape
            self._tree_refeed = shape.depth + 1
            self.seen_refeed = []

        def spec_tree_round(self, refeed, refeed_len, lengths, active,
                            temperature, top_p, seeds, rounds,
                            block_tables=None, draft_block_tables=None,
                            shape=None):
            s = self.spec_tree
            for i in range(self.slots):
                if active[i]:
                    self.seen_refeed.append(
                        list(refeed[i, :refeed_len[i]]))
            out = np.full((self.slots, s.depth + 1), 2, np.int32)
            acc = np.full((self.slots,), s.depth, np.int32)
            path = np.zeros((self.slots, s.depth), np.int32)
            path[:, 0] = s.primary_rows[0] + 1  # accepted SIBLING at L1
            path[:, 1] = s.primary_rows[1]
            return out, acc, path

    eng = _FakeTreeEngine(slots=2)
    sched = Scheduler(eng, eos_token_id=None)
    sched.submit(Request(id="a", prompt=[1] * 4, max_new_tokens=7))
    sched.submit(Request(id="b", prompt=[1] * 4, max_new_tokens=5))
    done = sched.run()
    assert {c.request_id for c in done} == {"a", "b"}
    # round 1's refeed is the prefill token alone; every later round
    # refeeds the 3 tokens (accepted pair + bonus) banked before it
    assert eng.seen_refeed[:2] == [[1], [1]]
    assert all(r == [2, 2, 2] for r in eng.seen_refeed[2:])
    m = sched.metrics()
    assert m["spec_tree_rounds"] > 0
    assert m["spec_tree_nodes"] > 0
    assert m["spec_tree_nodes"] % shape.size == 0
    # each round accepts one off-primary and one primary node
    assert m["spec_tree_branch_utilization"] == 0.5
    assert m["spec_draft_tokens"] % (shape.size - 1) == 0
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity

    # mid-stream drain: stop after the first tree round — active slots
    # finish, the queued request is reported unserved, leak guard clean
    eng2 = _FakeTreeEngine(slots=1)
    sched2 = Scheduler(eng2, eos_token_id=None)
    for i in range(3):
        sched2.submit(Request(id=f"r{i}", prompt=[1] * 4, max_new_tokens=9))
    sched2.run(stop=lambda: sched2.iterations >= 1)  # strict guard inside
    assert len(sched2.unserved()) >= 1
    assert not sched2.admission_open
    assert sched2.allocator.free_count == sched2.allocator.capacity
    assert (sched2.draft_allocator.free_count
            == sched2.draft_allocator.capacity)


@pytest.mark.slow
def test_greedy_tree_spec_stream_bitmatches_nonspec_paged():
    """Tree tentpole end to end: greedy EXACT-mode tree streams are
    BIT-identical to non-speculative paged decode across chunked prefill
    and block-pool eviction/refill, cache-on AND cache-off — the repeated
    prompt additionally pins the satellite contract that prefix-cache
    hits (including the DRAFT-pool mirror's) leave spec streams
    unchanged. The draft is independently initialized, so exactness must
    come from the verify/commit path, not draft quality."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    draft_params = Transformer(cfg).init(
        jax.random.PRNGKey(9),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    rng = np.random.default_rng(5)
    shared = rng.integers(3, 64, size=20).tolist()
    reqs = [(shared, 10), (shared, 8)]  # adjacent duplicates: cache hits
    for n in (9, 36, 13, 5):            # 36 exceeds the 16 bucket: chunked
        reqs.append((rng.integers(3, 64, size=n).tolist(), 10))
    kw = dict(slots=2, max_len=48, prefill_buckets=(16,), kv_layout="paged",
              kv_block_size=16, kv_num_blocks=7)  # 6 usable: evict/refill

    def streams(engine):
        sched = Scheduler(engine, eos_token_id=None)
        for i, (prompt, gen) in enumerate(reqs):
            sched.submit(Request(id=f"r{i}", prompt=prompt,
                                 max_new_tokens=gen))
        done = sched.run()
        assert len(done) == len(reqs)
        return {c.request_id: c.tokens for c in done}, sched

    base = InferenceEngine(cfg, params, **kw)
    want, _ = streams(base)
    del base

    spec_kw = dict(draft_cfg=cfg, draft_params=draft_params, spec_k=3,
                   spec_tree="2,1,1", draft_num_blocks=7)
    tree = InferenceEngine(cfg, params, **spec_kw, **kw)
    got, sched = streams(tree)
    assert got == want
    m = sched.metrics()
    assert m["spec_tree_rounds"] > 0 and m["spec_tree_nodes"] > 0
    # the adjacent duplicate prompt hit BOTH radix trees: the draft
    # mirror absorbed at least its one fully-committed block
    assert m["draft_prefix_hit_tokens"] >= 16
    assert m["prefix_hit_tokens"] >= 16
    del tree

    off = InferenceEngine(cfg, params, prefix_cache=False, **spec_kw, **kw)
    got_off, sched_off = streams(off)
    assert got_off == want
    assert "draft_prefix_hit_rate" not in sched_off.metrics()


@pytest.mark.slow
def test_fork_slot_cow_beam_contract():
    """COW beam fork over the paged substrate: ``engine.fork_slot``
    aliases full shared blocks (refcount 2 — the prefix cache's sharing
    contract), duplicates only the partial boundary block into a fresh
    allocation, both beams decode independently afterwards, and each row
    frees through the uniform allocator path exactly once — the second
    free of the same row raises. Exhaustion acquires nothing."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, cfg.seq_len), jnp.int32)
    )["params"]
    eng = InferenceEngine(cfg, params, slots=2, max_len=32,
                          prefill_buckets=(16,), kv_layout="paged",
                          kv_block_size=8, prefix_cache=False)
    alloc = BlockAllocator(eng.num_blocks)
    rng = np.random.default_rng(3)
    prompt = rng.integers(3, 64, size=12).tolist()  # 1.5 blocks committed
    src_blocks = alloc.alloc(3)
    src_row = np.zeros((eng.max_blocks_per_slot,), np.int32)
    src_row[:3] = src_blocks
    first = eng.prefill(0, prompt, block_row=src_row, seed=1)

    dst_row = eng.fork_slot(0, 1, length=12, src_row=src_row,
                            allocator=alloc)
    assert dst_row is not None
    # full block aliased (refcount 2), boundary block freshly private
    assert dst_row[0] == src_row[0] and alloc.refcount(src_row[0]) == 2
    assert dst_row[1] != src_row[1] and alloc.refcount(dst_row[1]) == 1
    assert int(np.asarray(eng.cache.lengths)[1]) == 12

    # both beams decode through their own tables (shared prefix read-only)
    tables = np.stack([src_row, dst_row])
    toks = np.array([first, first], np.int32)
    for i in range(3):
        toks = eng.decode_step(
            toks, np.array([True, True]),
            np.array([0.9, 0.9], np.float32), np.ones(2, np.float32),
            np.array([1, 2], np.int32),
            np.full(2, 12 + i, np.int32), block_tables=tables)

    # exhaustion acquires nothing: drain the pool, then fork at a
    # non-aligned length must return None without touching refcounts
    rest = alloc.alloc(alloc.free_count)
    used_before = alloc.used_count
    assert eng.fork_slot(0, 1, length=12, src_row=src_row,
                         allocator=alloc) is None
    assert alloc.used_count == used_before
    alloc.free(rest)

    # uniform free path: each row exactly once; the second free raises
    dst_blocks = [int(b) for b in dst_row[:2]]
    alloc.free(dst_blocks)
    assert alloc.refcount(src_row[0]) == 1
    alloc.free(src_blocks)
    assert alloc.free_count == alloc.capacity
    with pytest.raises(ValueError, match="double free"):
        alloc.free(dst_blocks)


# ------------------------------------------------- 6. collect-only guard
def test_no_test_module_imports_inference_at_module_scope():
    """Collecting the test suite must not import the inference package
    (and with it jax program-building code): every test imports it inside
    the test function. Walks only module-scope statements — imports inside
    functions are the sanctioned pattern."""
    offenders = []
    for path in sorted((REPO / "tests").glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        stack = list(tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.If, ast.Try)):
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name.startswith("fault_tolerant_llm_training_tpu"
                                   ".inference"):
                    offenders.append(f"{path.name}: {name}")
    assert not offenders, (
        "module-scope inference/ imports break collect-time isolation: "
        f"{offenders}")
