"""Speculative decoding (inference/ spec mode): four layers of evidence.

1. kernel — ``spec_accept`` degenerates to exact argmax matching for
   greedy rows, and for sampled rows its emitted tokens follow the TARGET
   distribution in closed form (the Leviathan/Chen guarantee) on a
   3-token toy vocab;
2. numerics — ``verify_with_cache``'s chunked scoring agrees with the
   sequential S=1 steps it replaces (argmax + allclose on an fp32 model;
   the engine's AOT verify program micro-steps S=1 shapes precisely so
   this agreement is bitwise in production — engine.py ``_verify_fn``);
3. streams — a greedy speculative stream is BIT-identical to the
   non-speculative paged path across chunked prefill and block-pool
   eviction/refill (slow: builds two real engines);
4. lifecycle — dual-pool admission/rollback/double-free contracts and
   mid-prompt drain exactness, pinned against a fake spec engine.

Module scope imports nothing from the package: the collect-only guard at
the bottom asserts NO test module pays the draft path's import cost (or
any inference/ import) at collection time.
"""

import ast
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
CACHE = "/tmp/jax_test_compile_cache"


# ------------------------------------------------------- 1. accept kernel
def test_spec_accept_greedy_is_exact_argmax_matching():
    """With temperature <= 0 both q and p are one-hots: the accept test
    ``u * q(d) < p(d)`` keeps exactly the leading run of draft tokens that
    equal the target argmax, and the bonus/correction token IS the target
    argmax at the first divergence — so greedy needs no randomness and the
    emitted prefix equals what sequential argmax decoding would produce."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.sampler import spec_accept

    rng = np.random.default_rng(0)
    v, k = 7, 3
    for trial in range(50):
        target_logits = rng.normal(size=(k + 1, v)).astype(np.float32)
        draft_tokens = rng.integers(0, v, size=k).astype(np.int32)
        # greedy draft distributions are one-hots at the proposal
        draft_probs = np.eye(v, dtype=np.float32)[draft_tokens]
        out, acc = spec_accept(
            jnp.asarray(draft_tokens), jnp.asarray(draft_probs),
            jnp.asarray(target_logits),
            jax.random.PRNGKey(trial), jnp.float32(0.0), jnp.float32(1.0))
        argmax = target_logits.argmax(axis=-1)
        expect_a = 0
        while expect_a < k and draft_tokens[expect_a] == argmax[expect_a]:
            expect_a += 1
        assert int(acc) == expect_a
        expected = list(draft_tokens[:expect_a]) + [argmax[expect_a]]
        assert np.asarray(out)[: expect_a + 1].tolist() == expected


def test_spec_rejection_sampling_matches_target_distribution():
    """k=1 on a 3-token vocab with draft law q != target law p: across many
    independent rounds the emitted first token must be distributed as p
    EXACTLY (not as q, not as some blend), and the acceptance probability
    equals sum_a min(p_a, q_a) — the closed forms from Leviathan et al.
    2023, Thm 1. Empirical check at ~4 sigma on 8000 trials."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.sampler import spec_accept

    q = jnp.asarray([0.5, 0.3, 0.2], jnp.float32)
    p = np.array([0.2, 0.5, 0.3], np.float32)
    target_logits = jnp.log(jnp.asarray(p))[None, :].repeat(2, axis=0)
    n = 8000

    def one_round(key):
        kd, ka = jax.random.split(key)
        d = jax.random.categorical(kd, jnp.log(q)).astype(jnp.int32)
        out, acc = spec_accept(d[None], q[None, :], target_logits, ka,
                               jnp.float32(1.0), jnp.float32(1.0))
        return out[0], (acc > 0).astype(jnp.int32)

    keys = jax.random.split(jax.random.PRNGKey(42), n)
    toks, accepted = jax.jit(jax.vmap(one_round))(keys)
    toks, accepted = np.asarray(toks), np.asarray(accepted)

    emp = np.bincount(toks, minlength=3) / n
    se = np.sqrt(p * (1 - p) / n)
    np.testing.assert_allclose(emp, p, atol=float((4 * se).max()))
    accept_rate = accepted.mean()
    expect_accept = float(np.minimum(p, np.asarray(q)).sum())
    se_a = np.sqrt(expect_accept * (1 - expect_accept) / n)
    assert abs(accept_rate - expect_accept) < 4 * se_a


# ---------------------------------------------------- 2. verify-k numerics
def test_verify_chunk_scores_agree_with_sequential_steps():
    """``verify_with_cache`` scores (B, k+1) candidates in one forward; its
    row j must agree with the j-th sequential S=1 ``forward_with_cache``
    step on the same committed prefix — same masked attention, same
    positions. On an fp32 model the two differ only by shape-dependent
    matmul accumulation order, so argmax equality plus allclose pins the
    contract (the engine's AOT verify program micro-steps the S=1 shapes
    exactly, making this agreement bitwise in production)."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        init_paged_cache)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = get_config("tiny", vocab_size=64, seq_len=64,
                     dtype=jnp.float32, param_dtype=jnp.float32)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    rng = np.random.default_rng(3)
    prompt = jnp.asarray(rng.integers(3, 64, size=(1, 8)), jnp.int32)
    cand = jnp.asarray(rng.integers(3, 64, size=(1, 3)), jnp.int32)

    bs = 8
    cache = init_paged_cache(cfg, slots=1, max_len=32, block_size=bs)
    tables = jnp.arange(1, 32 // bs + 1, dtype=jnp.int32)[None, :]
    _, (k0, v0) = model.apply(
        {"params": params}, prompt, cache.k, cache.v,
        jnp.zeros((1,), jnp.int32), block_tables=tables,
        method="forward_with_cache")

    offsets = jnp.full((1,), 8, jnp.int32)
    chunk, _ = model.apply(
        {"params": params}, cand, k0, v0, offsets, block_tables=tables,
        method="verify_with_cache")

    ck, cv, rows = k0, v0, []
    for j in range(3):
        step, (ck, cv) = model.apply(
            {"params": params}, cand[:, j:j + 1], ck, cv, offsets + j,
            block_tables=tables, method="forward_with_cache")
        rows.append(np.asarray(step)[:, 0])
    seq_logits = np.stack(rows, axis=1)

    np.testing.assert_allclose(np.asarray(chunk), seq_logits,
                               rtol=1e-5, atol=1e-5)
    assert (np.asarray(chunk).argmax(-1) == seq_logits.argmax(-1)).all()


# ----------------------------------------------------- 3. stream equality
@pytest.mark.slow
def test_greedy_spec_stream_bitmatches_nonspec_paged():
    """End to end: the same request set (chunked long prompts, more
    requests than the block pools admit at once, so slots evict and refill
    into reused blocks) generates BIT-identical greedy token streams with
    and without speculation — the tentpole invariant. The draft is an
    independently-initialized model, so acceptance is poor: exactness must
    come from the verify/commit path, not from a lucky good draft."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64)
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    draft_params = Transformer(cfg).init(
        jax.random.PRNGKey(9),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    rng = np.random.default_rng(5)
    lens = [20, 9, 36, 13, 20, 5]  # 36 and 20 exceed the 16 bucket: chunked
    reqs = [(rng.integers(3, 64, size=n).tolist(), 10) for n in lens]
    kw = dict(slots=2, max_len=48, prefill_buckets=(16,), kv_layout="paged",
              kv_block_size=16, kv_num_blocks=7)  # 6 usable: 2 concurrent

    def streams(engine):
        sched = Scheduler(engine, eos_token_id=None)
        for i, (prompt, gen) in enumerate(reqs):
            sched.submit(Request(id=f"r{i}", prompt=prompt,
                                 max_new_tokens=gen))
        done = sched.run()
        assert len(done) == len(reqs)
        return {c.request_id: c.tokens for c in done}, sched

    base = InferenceEngine(cfg, params, **kw)
    want, _ = streams(base)
    del base

    spec = InferenceEngine(cfg, params, draft_cfg=cfg,
                           draft_params=draft_params, spec_k=2,
                           draft_num_blocks=7, **kw)
    got, sched = streams(spec)
    assert got == want
    # both pools fully drained back to the free lists (the target pool via
    # a prefix-cache flush: committed prompt blocks stay cache-held after
    # drain; the draft pool opts out of caching so it must already be free)
    assert sched.allocator.used_count == sched.prefix_cache.cached_blocks
    sched.prefix_cache.flush()
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity
    m = sched.metrics()
    assert m["spec_rounds"] > 0 and m["spec_draft_tokens"] > 0


# ------------------------------------------------ 4. dual-pool lifecycle
class _FakeSpecEngine:
    """Host-side double of the spec engine: chunked prefill that consults
    ``stop_check`` between chunks, and accept-all spec rounds. Lets the
    scheduler's dual-pool bookkeeping be pinned without any compiles."""

    kv_layout = "paged"

    def __init__(self, slots=2, block_size=4, num_blocks=13,
                 draft_num_blocks=13, spec_k=2, max_len=32):
        self.slots, self.block_size = slots, block_size
        self.num_blocks, self.draft_num_blocks = num_blocks, draft_num_blocks
        self.spec_k, self.max_len = spec_k, max_len
        self.max_blocks_per_slot = -(-max_len // block_size)
        self.prefill_chunk = 4

    def prefill(self, slot, prompt, block_row=None, draft_block_row=None,
                temperature=0.0, top_p=1.0, seed=0, stop_check=None,
                on_chunk=None):
        start = 0
        while start < len(prompt):
            if on_chunk is not None:
                on_chunk()
            start += self.prefill_chunk
            if start < len(prompt) and stop_check is not None and stop_check():
                return None  # drain fired between chunks
        return 1

    def spec_round(self, tokens, lengths, active, temperature, top_p, seeds,
                   steps, block_tables=None, draft_block_tables=None):
        out = np.full((self.slots, self.spec_k + 1), 2, np.int32)
        acc = np.full((self.slots,), self.spec_k, np.int32)
        return out, acc


def test_mid_prompt_drain_frees_both_pools_and_reports_unserved():
    """A drain signal landing BETWEEN prefill chunks must abort the
    admission, free the target AND draft blocks it grabbed, report the
    request unserved, and let already-active requests run to completion —
    the signal-drain exactness contract extended to the dual-pool mode."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    eng = _FakeSpecEngine(slots=2)
    chunks = {"n": 0}
    sched = Scheduler(eng, eos_token_id=None,
                      stop_check=lambda: chunks["n"] >= 2)
    orig = sched._count_chunk

    def counting():
        chunks["n"] += 1
        orig()

    sched._count_chunk = counting
    sched.submit(Request(id="short", prompt=[1] * 4, max_new_tokens=6))
    sched.submit(Request(id="long", prompt=[1] * 12, max_new_tokens=6))
    done = sched.run()

    assert [c.request_id for c in done] == ["short"]
    assert [r.id for r in sched.unserved()] == ["long"]
    assert not sched.admission_open
    # every block of both pools is back on the free lists; the long
    # request's partial grab did not leak
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity
    assert (sched.block_tables == 0).all()
    assert (sched.draft_block_tables == 0).all()
    # the accept-all fake banks k+1 tokens per round: 2 rounds for 6
    sc = done[0]
    assert sc.spec_proposed > 0 and sc.spec_emitted_not_proposed > 0


def test_draft_pool_shortage_rolls_back_target_grab():
    """Combined-footprint admission: when the draft pool cannot cover the
    head of the queue, the target blocks already grabbed for it must be
    returned immediately (not stranded until the request eventually
    admits), and the request waits FIFO until BOTH pools can cover it."""
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    # target pool covers two 3-block requests, draft pool only one
    eng = _FakeSpecEngine(slots=2, num_blocks=13, draft_num_blocks=4,
                          max_len=12)
    sched = Scheduler(eng, eos_token_id=None)
    sched.submit(Request(id="a", prompt=[1] * 6, max_new_tokens=6))
    sched.submit(Request(id="b", prompt=[1] * 6, max_new_tokens=6))
    sched.step()
    assert len(sched.active) == 1
    # b's aborted admission left NO target blocks allocated beyond a's
    assert (sched.allocator.used_count
            == sched._blocks_needed(sched.active[0].request))
    done = sched.run()
    assert {c.request_id for c in done} == {"a", "b"}
    assert sched.allocator.free_count == sched.allocator.capacity
    assert sched.draft_allocator.free_count == sched.draft_allocator.capacity


def test_block_allocator_double_free_raises():
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        BlockAllocator)

    alloc = BlockAllocator(num_blocks=5)
    blocks = alloc.alloc(3)
    assert blocks is not None and alloc.free_count == 1
    assert alloc.alloc(2) is None  # exhaustion queues, never crashes
    alloc.free(blocks)
    assert alloc.free_count == alloc.capacity
    with pytest.raises(ValueError, match="double free"):
        alloc.free(blocks)


# ------------------------------------------------- 5. collect-only guard
def test_no_test_module_imports_inference_at_module_scope():
    """Collecting the test suite must not import the inference package
    (and with it jax program-building code): every test imports it inside
    the test function. Walks only module-scope statements — imports inside
    functions are the sanctioned pattern."""
    offenders = []
    for path in sorted((REPO / "tests").glob("*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        stack = list(tree.body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.If, ast.Try)):
                stack.extend(ast.iter_child_nodes(node))
                continue
            if isinstance(node, ast.Import):
                names = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                names = [node.module or ""]
            else:
                continue
            for name in names:
                if name.startswith("fault_tolerant_llm_training_tpu"
                                   ".inference"):
                    offenders.append(f"{path.name}: {name}")
    assert not offenders, (
        "module-scope inference/ imports break collect-time isolation: "
        f"{offenders}")
