"""Frozen audit-string contract.

The audit strings in utils/logging.py are the system's verification API —
the reference README greps Slurm ``.out`` files for them, and the
fault-tolerance tests assert on them. This module freezes each string
against a pinned literal (NOT imported constants compared to themselves:
the pin must break when anyone edits the string), and enforces the
flight-recorder invariant: audit strings are only ever emitted through
``obs.events.emit_audit``, which pairs every byte-identical log line with
exactly one structured event.
"""

import logging
import re
from pathlib import Path

from fault_tolerant_llm_training_tpu.obs import events as events_mod
from fault_tolerant_llm_training_tpu.utils import logging as ftl_logging

import pytest

REPO = Path(__file__).resolve().parent.parent
PKG = REPO / "fault_tolerant_llm_training_tpu"


@pytest.fixture(autouse=True)
def _fresh_recorder():
    events_mod._RECORDER = events_mod.FlightRecorder()
    yield
    events_mod._RECORDER = events_mod.FlightRecorder()

# Pinned byte-for-byte. ref: utils.py:68,71,73,81,86,88,90; train.py:81,84,
# 116,118 — plus the serving trail introduced with inference/serve.py.
FROZEN = {
    "AUDIT_CANCELLED": "[EXIT HANDLER] Job cancelled, terminating.",
    "AUDIT_TIMEOUT_SAVING": "[EXIT HANDLER] Job timed out, saving checkpoint.",
    "AUDIT_ERROR_SAVING":
        "[EXIT HANDLER] Error during training encountered, saving checkpoint.",
    "AUDIT_SAVED_FMT": "[EXIT HANDLER] Checkpoint saved at step {step}",
    "AUDIT_REQUEUE_FAILED_FMT":
        "[EXIT HANDLER] Failed to requeue job {job_id}.",
    "AUDIT_REQUEUED":
        "[EXIT HANDLER] sbatch requeued, new job will load the last checkpoint",
    "AUDIT_UNKNOWN_FMT":
        "[EXIT HANDLER] Unknown exit signal {type}, terminating.",
    "AUDIT_RESUME_FMT": "Resuming training from training_step {step}",
    "AUDIT_START": "Starting training!",
    "AUDIT_COMPLETED": "Training completed",
    "AUDIT_STEP_FMT": "Training step: {step} | Loss: {loss:.2f}",
    "AUDIT_SERVE_START": "Starting serving!",
    "AUDIT_SERVE_READY_FMT":
        "Serving ready | model {model} | checkpoint step {step} | "
        "slots {slots}",
    "AUDIT_SERVE_STEP_FMT":
        "Serve step: {step} | Active: {active} | Queued: {queued} | "
        "Done: {done}",
    "AUDIT_SERVE_DRAINING_FMT":
        "[EXIT HANDLER] Signal {signum} received, draining {active} "
        "in-flight request(s), admission stopped.",
    "AUDIT_SERVE_DRAINED_FMT":
        "[EXIT HANDLER] Drained; {completed} request(s) completed, "
        "{queued} queued request(s) not admitted.",
    "AUDIT_REQUEST_DONE_FMT":
        "Request {id} done | {reason} | prompt {prompt_tokens} tok | "
        "generated {new_tokens} tok | ttft {ttft_ms:.0f} ms | "
        "{tps:.1f} tok/s",
    "AUDIT_SERVE_COMPLETED": "Serving completed",
    "AUDIT_SERVE_PREFIX_FMT":
        "Prefix cache | lookups {lookups} | hit rate {rate:.3f} | "
        "hit tokens {hit_tokens} | cached blocks {cached} | "
        "cow copies {cow} | evictions {evictions}",
    "AUDIT_SERVE_PREFILL_FMT":
        "Packed prefill | rounds {rounds} | rows {rows} | occupancy "
        "{occupancy:.3f} | inplace chunks {inplace} | gather chunks "
        "{gather}",
    "AUDIT_SERVE_TREE_SPEC_FMT":
        "Tree spec | shape {shape} | rounds {rounds} | nodes {nodes} | "
        "accepted/round {per_round:.2f} | branch util {util:.3f}",
    "AUDIT_KV_LEAK_FMT":
        "[KV LEAK] {pool} pool: {leaked} block(s) leaked after drain "
        "({used} allocated, {cached} prefix-cached)",
    "AUDIT_CHAOS_INJECT_FMT": "[CHAOS] Injected {fault} at step {step}",
    "AUDIT_CKPT_VERIFY_FAILED_FMT":
        "[CKPT VERIFY] Checkpoint step {step} failed integrity check: "
        "{detail}",
    "AUDIT_CKPT_FALLBACK_FMT":
        "[CKPT VERIFY] Falling back to checkpoint step {step} "
        "(newest passing)",
    "AUDIT_CKPT_PARTIAL_SKIPPED_FMT":
        "[CKPT FINALIZE] Skipped partial checkpoint directory {name}",
    "AUDIT_TRACE_AUTO_FMT":
        "[TRACE] Step time regressed {ratio:.1f}x vs rolling median; "
        "capturing profiler window at step {step}",
    "AUDIT_PUBLISH_FMT":
        "[DEPLOY] Published checkpoint step {step} (digest {digest})",
    "AUDIT_RELOAD_FMT":
        "[DEPLOY] Weights reloaded: step {old} -> {new} | {active} "
        "in-flight | swap {ms:.0f} ms",
    "AUDIT_RELOAD_REJECTED_FMT":
        "[DEPLOY] Publish of step {step} rejected: {detail}; serving "
        "continues on step {current}",
    "AUDIT_FLEET_JOIN_FMT":
        "[FLEET] Host {host} joined: {slots} slot(s), {blocks} free "
        "block(s), lease ttl {ttl:.1f}s",
    "AUDIT_FLEET_LEAVE_FMT": "[FLEET] Host {host} left ({reason})",
    "AUDIT_FLEET_DEAD_FMT":
        "[FLEET] Host {host} declared dead: lease age {age:.1f}s > ttl "
        "{ttl:.1f}s; fencing and migrating {inflight} in-flight "
        "request(s)",
    "AUDIT_FLEET_MIGRATE_FMT":
        "[FLEET] Migrating request {id}: {src} -> {dst} (gen {gen}, "
        "{committed} committed token(s) replayed)",
    "AUDIT_FLEET_REQUEUE_FMT":
        "[FLEET] Requeued request {id} to the journal ({committed} "
        "committed token(s), reason {reason})",
    "AUDIT_LATENCY_FMT":
        "[LATENCY] Request {id} | trace {trace} | ttft {ttft_ms:.0f} ms "
        "| tpot {tpot_ms:.2f} ms | {tokens} tok | {reason}",
    "AUDIT_KV_TIER_FMT":
        "[KV TIER] Spill {action} request {id}: {blocks} block(s), "
        "{bytes} byte(s) (tier={tier})",
    "AUDIT_HANDOFF_FMT":
        "[HANDOFF] Block-shipment {action} request {id} (gen {gen}): "
        "{blocks} block(s), {detail}",
    "AUDIT_KV_QUANT_FMT":
        "[KV QUANT] dtype={dtype} | {bytes_per_block} B/block "
        "({ratio:.2f}x vs bf16) | {blocks_total} pool block(s)",
    "AUDIT_DISAGG_SHIP_FMT":
        "[DISAGG] Shipment {action} request {id} seq {seq} (gen {gen}): "
        "blocks [{start}, {end}), {detail}",
    "AUDIT_DISAGG_PLACE_FMT":
        "[DISAGG] Placement {action} request {id} (gen {gen}): {detail}",
    "AUDIT_KV_STORE_FMT":
        "[KV STORE] {action} key {key} request {id}: {blocks} block(s), "
        "{detail}",
    "AUDIT_KV_XPORT_FMT":
        "[KV XPORT] {action} lane {lane} request {id}: {blocks} block(s), "
        "{detail}",
    "AUDIT_FLEETSCOPE_FEDERATE_FMT":
        "[FLEETSCOPE] Federated {hosts} host(s): {series} series, "
        "{rollups} fleet rollup(s), {stale} stale, {failures} "
        "scrape failure(s)",
    "AUDIT_FLEETSCOPE_TIMELINE_FMT":
        "[FLEETSCOPE] Timeline: {events} event(s) from {hosts} host(s) "
        "in HLC order, {anomalies} anomalie(s)",
    "AUDIT_FLEETSCOPE_TREND_OK_FMT":
        "[FLEETSCOPE] Bench trend: {metrics} pinned metric(s) across "
        "{receipts} receipt(s) within {tolerance_pct}% of baseline",
    "AUDIT_FLEETSCOPE_TREND_REGRESSION_FMT":
        "[FLEETSCOPE] Bench trend REGRESSION: {receipt} {metric} "
        "{delta_pct:+.1f}% ({baseline} -> {current}, {direction} is "
        "better)",
    "AUDIT_ADAPTER_FMT":
        "[ADAPTER] {action} adapter {name}: {pages} page(s), {detail}",
    "AUDIT_ADAPTER_SUMMARY_FMT":
        "[ADAPTER] drain summary | served {served} adapter(s) | "
        "page-ins {pageins} | evictions {evictions} | resident "
        "{resident_bytes} byte(s) | rejects {rejects}",
}


def test_audit_strings_are_byte_identical_to_pins():
    for name, pinned in FROZEN.items():
        actual = getattr(ftl_logging, name)
        assert actual == pinned, (
            f"{name} drifted from the frozen contract:\n"
            f"  pinned : {pinned!r}\n  actual : {actual!r}\n"
            f"These strings are the grep-the-.out-file verification API — "
            f"changing one silently breaks the reference's checks.")


def test_no_new_unpinned_audit_strings():
    declared = {n for n in dir(ftl_logging) if n.startswith("AUDIT_")}
    assert declared == set(FROZEN), (
        "utils/logging.py and the frozen pin table disagree; add the new "
        "string (and its pin) here so it is contract-checked too")


def test_audit_strings_emitted_only_through_emit_audit():
    """``logger.info(AUDIT_*`` must not exist outside obs/events.py: the raw
    form logs the text without the paired structured event, so the flight
    recorder would silently miss that emission."""
    pattern = re.compile(r"\.\s*info\(\s*AUDIT_")
    offenders = []
    for path in [REPO / "train.py", *PKG.rglob("*.py")]:
        if path == PKG / "obs" / "events.py":
            continue  # the docstring naming the banned form
        for i, line in enumerate(path.read_text().splitlines(), 1):
            if pattern.search(line):
                offenders.append(f"{path.relative_to(REPO)}:{i}: {line.strip()}")
    assert not offenders, (
        "raw logger.info(AUDIT_*) call sites found — route these through "
        "obs.events.emit_audit:\n" + "\n".join(offenders))


def test_emit_audit_pairs_one_event_per_emission(tmp_path):
    """Every emit_audit call: the audit text logged exactly once,
    byte-identical, plus exactly one structured event with matching step."""
    path = str(tmp_path / "ev.jsonl")
    events_mod.configure(path, job="contract")
    log = logging.getLogger("ftl-test-contract")
    lines = []

    class _Capture(logging.Handler):
        def emit(self, record):
            lines.append(record.getMessage())

    log.addHandler(_Capture())
    log.setLevel(logging.INFO)

    emissions = [
        (ftl_logging.AUDIT_STEP_FMT.format(step=7, loss=2.5), "step", 7),
        (ftl_logging.AUDIT_SAVED_FMT.format(step=7), "exit", 7),
        (ftl_logging.AUDIT_TIMEOUT_SAVING, "signal", None),
        (ftl_logging.AUDIT_RESUME_FMT.format(step=7), "resume", 7),
    ]
    for text, kind, step in emissions:
        events_mod.emit_audit(log, text, kind, step=step)
    events_mod.flush()
    evs = events_mod.read_events(path)
    assert len(evs) == len(emissions) == len(lines)
    for (text, kind, step), ev, line in zip(emissions, evs, lines):
        assert line == text
        assert ev["kind"] == kind
        assert ev.get("step") == step
        assert ev["audit"] is True
    events_mod.configure(None)
