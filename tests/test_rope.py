"""RoPE parity: the real-arithmetic interleaved rotation must match an
independent numpy complex-exponential implementation of the reference's math
(ref: model.py:51-126 — adjacent-pair view_as_complex in fp32)."""

import jax.numpy as jnp
import numpy as np

from fault_tolerant_llm_training_tpu.ops.rope import (
    apply_rope,
    precompute_rope,
    rope_cos_sin,
)


def numpy_complex_rope(x: np.ndarray, theta: float) -> np.ndarray:
    """Independent oracle: complex rotation over adjacent element pairs."""
    b, s, h, d = x.shape
    freqs = 1.0 / (theta ** (np.arange(0, d, 2, dtype=np.float64) / d))
    angles = np.outer(np.arange(s), freqs)  # (S, D/2)
    rot = np.exp(1j * angles)  # (S, D/2)
    xc = x.astype(np.float64).reshape(b, s, h, d // 2, 2)
    xc = xc[..., 0] + 1j * xc[..., 1]  # (B, S, H, D/2)
    out = xc * rot[None, :, None, :]
    return np.stack([out.real, out.imag], axis=-1).reshape(b, s, h, d)


def test_rope_matches_complex_oracle():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 16, 3, 8)).astype(np.float32)
    theta = 500000.0
    cos, sin = precompute_rope(8, 32, theta)
    got = np.asarray(apply_rope(jnp.asarray(x), cos, sin))
    want = numpy_complex_rope(x, theta)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_rope_preserves_norm():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 8, 2, 16)).astype(np.float32))
    cos, sin = precompute_rope(16, 8, 10000.0)
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1),
                               np.linalg.norm(np.asarray(x), axis=-1),
                               rtol=1e-5)


def test_rope_positions_indexing():
    # Explicit positions must equal the implicit prefix positions.
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((1, 8, 2, 8)).astype(np.float32))
    cos, sin = precompute_rope(8, 32, 10000.0)
    implicit = apply_rope(x, cos, sin)
    explicit = apply_rope(x, cos, sin, positions=jnp.arange(8)[None, :])
    np.testing.assert_allclose(np.asarray(implicit), np.asarray(explicit),
                               rtol=1e-6)
    # A shifted window matches the oracle shifted rows.
    shifted = apply_rope(x, cos, sin, positions=jnp.arange(4, 12)[None, :])
    oracle_full = numpy_complex_rope(
        np.concatenate([np.zeros((1, 4, 2, 8), np.float32), np.asarray(x)],
                       axis=1), 10000.0)
    np.testing.assert_allclose(np.asarray(shifted), oracle_full[:, 4:],
                               rtol=1e-5, atol=1e-5)


def test_rope_cos_sin_matches_table_gather():
    # The gather-free per-token form (used under sequence parallelism) must
    # equal indexing the precomputed table at the same positions.
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((2, 8, 2, 8)).astype(np.float32))
    positions = jnp.asarray(rng.integers(0, 32, (2, 8)).astype(np.int32))
    table_cos, table_sin = precompute_rope(8, 32, 500000.0)
    via_gather = apply_rope(x, table_cos, table_sin, positions=positions)
    cos, sin = rope_cos_sin(8, 500000.0, positions)
    assert cos.shape == (2, 8, 4)
    via_outer = apply_rope(x, cos, sin)
    np.testing.assert_allclose(np.asarray(via_outer), np.asarray(via_gather),
                               rtol=1e-5, atol=1e-6)
