"""Multi-host fault-tolerance coordination (ft/multihost.py).

Real multi-process agreement needs a pod; these tests pin down the policy
function (pure), the single-process identity paths, and the synced check
wiring — the pieces that must hold before the KV voting round even matters.
"""

import signal

import pytest

from fault_tolerant_llm_training_tpu.ft.multihost import (
    agree_on_signal,
    barrier,
    combine_signals,
    should_resubmit,
)
from fault_tolerant_llm_training_tpu.ft.signals import SignalFlag, TrainingSignal

USR1 = int(signal.SIGUSR1)
TERM = int(signal.SIGTERM)


def test_combine_signals_policy():
    assert combine_signals([]) is None
    assert combine_signals([0, 0, 0]) is None
    assert combine_signals([0, USR1, 0]) == USR1
    assert combine_signals([TERM, TERM]) == TERM
    # mixed mid-grace-period view: the save-and-requeue path wins
    assert combine_signals([TERM, USR1, 0]) == USR1
    assert combine_signals([7, 9]) == 7  # deterministic for exotic codes


def test_single_process_identity():
    assert agree_on_signal(None) is None
    assert agree_on_signal(USR1) == USR1
    assert should_resubmit()
    barrier("test")  # no-op, must not raise


def test_synced_check_raises_same_signal():
    flag = SignalFlag()
    flag._handler(USR1, None)
    with pytest.raises(TrainingSignal) as e:
        flag.check(synced=True)
    assert e.value.args == ("Exception", USR1)
    flag.check(synced=True)  # cleared after raise


def test_watchdog_paths():
    """The fence's bounded-wait primitive: completion returns the value,
    exceptions re-raise in the caller, a timeout abandons with the
    cancellation token set, and a positive poll abandons within the poll
    interval instead of burning the whole timeout."""
    import time

    from fault_tolerant_llm_training_tpu.ft.multihost import watchdog

    ok, val = watchdog(lambda c: 42, 5.0)
    assert ok and val == 42

    with pytest.raises(RuntimeError, match="boom"):
        watchdog(lambda c: (_ for _ in ()).throw(RuntimeError("boom")), 5.0)

    seen = {}

    def _slow(cancelled):
        seen["cancelled"] = cancelled
        time.sleep(30)

    t0 = time.monotonic()
    ok, val = watchdog(_slow, 0.3)
    assert not ok and val is None
    assert time.monotonic() - t0 < 5
    assert seen["cancelled"].is_set()  # abandoned thread was told

    t0 = time.monotonic()
    ok, _ = watchdog(lambda c: time.sleep(30), 30.0,
                     poll=lambda: True, poll_seconds=0.2)
    assert not ok
    assert time.monotonic() - t0 < 5  # poll cut the wait, not the timeout


class _StubTrainer:
    def __init__(self, replicated):
        self.state = object()
        self.error_is_replicated = replicated
        self.saved_with = None
        self.fenced = False
        self.cfg = type("C", (), {"resubmit_command": "true"})()

    def coordinate_local_error(self):
        self.fenced = True
        return True

    def save_checkpoint(self, wait=True, coordinated=True, fault=False):
        self.saved_with = dict(wait=wait, coordinated=coordinated,
                               fault=fault)
        return 7


def test_host_local_error_runs_fence_then_saves(monkeypatch):
    """On a pod, an error of unknown provenance must run the fault fence
    before the coordinated save (unilaterally entering the pre-save barrier
    would hang); replicated errors save directly, fence skipped."""
    import logging

    import jax

    from fault_tolerant_llm_training_tpu.ft import handler

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    logger = logging.getLogger()
    t = _StubTrainer(replicated=False)
    handler.handle_exit(t, handler.CODE_ERROR, logger)
    assert t.fenced
    assert t.saved_with == dict(wait=True, coordinated=True, fault=True)
    t = _StubTrainer(replicated=True)
    handler.handle_exit(t, handler.CODE_ERROR, logger)
    assert not t.fenced
    assert t.saved_with == dict(wait=True, coordinated=True, fault=True)


class _PeerFaultTrainer(_StubTrainer):
    """Save raises PeerHostError once (a peer faulted mid-save), then works."""

    def __init__(self):
        super().__init__(replicated=True)
        self.saves = 0
        self.fences = 0

    def coordinate_local_error(self):
        self.fences += 1
        return True

    def save_checkpoint(self, wait=True, coordinated=True, fault=False):
        from fault_tolerant_llm_training_tpu.ft.multihost import PeerHostError

        self.saves += 1
        if self.saves == 1:
            raise PeerHostError()
        self.saved_with = dict(wait=wait, coordinated=coordinated,
                               fault=fault)
        return 9


def test_exit_handler_retries_save_after_peer_fault(monkeypatch):
    """ADVICE r5 medium: a PeerHostError raised DURING the exit-handler
    save (a peer faulted while this host drained/barriered) must not
    escape handle_exit and skip the checkpoint — the handler runs the
    fence and retries the save once, coordinated."""
    import logging

    import jax

    from fault_tolerant_llm_training_tpu.ft import handler

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    t = _PeerFaultTrainer()
    handler.handle_exit(t, handler.CODE_ERROR, logging.getLogger())
    assert t.saves == 2  # first save raised, retry landed
    assert t.fences == 1  # the fence ran between the attempts
    assert t.saved_with == dict(wait=True, coordinated=True, fault=True)


def test_persistent_waiter_paths():
    """ADVICE r5: the per-step bounded wait must not spawn/join a fresh
    thread every call. Same contract as watchdog (value, re-raise,
    timeout abandonment with the token set, poll short-cut) plus: the
    worker is REUSED across runs and across re-raised exceptions, and a
    wedged worker is discarded so the next run gets a fresh one."""
    import threading
    import time

    from fault_tolerant_llm_training_tpu.ft.multihost import PersistentWaiter

    w = PersistentWaiter()
    idents = []

    def _ok(cancelled):
        idents.append(threading.get_ident())
        return 42

    ok, val = w.run(_ok, 5.0)
    assert ok and val == 42
    ok, val = w.run(_ok, 5.0)
    assert ok and val == 42
    assert idents[0] == idents[1]  # one worker, reused — no per-call spawn

    with pytest.raises(RuntimeError, match="boom"):
        w.run(lambda c: (_ for _ in ()).throw(RuntimeError("boom")), 5.0)
    ok, _ = w.run(_ok, 5.0)  # an exception must not kill the worker
    assert ok and idents[-1] == idents[0]

    seen = {}

    def _slow(cancelled):
        seen["cancelled"] = cancelled
        time.sleep(30)

    t0 = time.monotonic()
    ok, val = w.run(_slow, 0.3)
    assert not ok and val is None
    assert time.monotonic() - t0 < 5
    assert seen["cancelled"].is_set()  # abandoned task was told

    ok, val = w.run(_ok, 5.0)  # wedged worker discarded, fresh one serves
    assert ok and val == 42
    assert idents[-1] != idents[0]

    t0 = time.monotonic()
    ok, _ = w.run(lambda c: time.sleep(30), 30.0,
                  poll=lambda: True, poll_seconds=0.2)
    assert not ok
    assert time.monotonic() - t0 < 5  # poll cut the wait, not the timeout


class _RecordingKV:
    """Fake jax.distributed KV client recording granted get timeouts."""

    def __init__(self, behavior):
        self.calls = []
        self.behavior = behavior

    def blocking_key_value_get(self, key, timeout_ms):
        self.calls.append((key, timeout_ms))
        return self.behavior(key, timeout_ms)


def test_gather_stops_one_deadline_bounds_whole_gather(monkeypatch):
    """ADVICE r5: each peer used to be granted the FULL timeout
    sequentially (N-1 slow peers -> (N-1) x timeout fence). One monotonic
    deadline now bounds the whole gather: later peers only get what is
    left, and an exhausted budget returns None without another get."""
    import time

    import jax

    from fault_tolerant_llm_training_tpu.ft import multihost

    monkeypatch.setattr(jax, "process_count", lambda: 2)

    def _slow_first(key, timeout_ms):
        if key.endswith("/0"):
            time.sleep(0.2)
        return "5"

    kv = _RecordingKV(_slow_first)
    monkeypatch.setattr(multihost, "_kv", lambda: kv)
    stops = multihost.gather_stops(1.0)
    assert stops == {0: 5, 1: 5}
    assert kv.calls[0][1] <= 1000
    assert kv.calls[1][1] <= 850  # peer 1 got only the REMAINING budget

    def _eats_budget(key, timeout_ms):
        time.sleep(0.3)
        return "5"

    kv = _RecordingKV(_eats_budget)
    monkeypatch.setattr(multihost, "_kv", lambda: kv)
    assert multihost.gather_stops(0.25) is None
    assert len(kv.calls) == 1  # peer 1 was never granted a negative wait

    def _raises(key, timeout_ms):
        raise RuntimeError("peer dead")

    kv = _RecordingKV(_raises)
    monkeypatch.setattr(multihost, "_kv", lambda: kv)
    assert multihost.gather_stops(1.0) is None  # get failure -> None, as before


class _WriteOnceKV:
    """Fake KV with the real store's write-once publish semantics; peer 1
    always votes 'no signal' in any round."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, val):
        if key in self.store:
            raise RuntimeError(f"write-once collision on {key}")
        self.store[key] = val

    def key_value_try_get(self, key):
        if key.endswith("/1"):
            return "0"
        return self.store[key]  # KeyError -> 'not published yet'

    def key_value_delete(self, key):
        self.store.pop(key, None)

    def key_value_dir_get(self, prefix):
        return []


def test_agree_on_signal_oneshot_rounds_do_not_collide(monkeypatch):
    """ADVICE r5: round_id=None used to publish the constant key
    ftl_sig/0/<me>, so a SECOND synced one-shot check collided on the
    write-once publish and read round one's stale votes. Each one-shot
    now draws a fresh reserved-namespace round."""
    import jax

    from fault_tolerant_llm_training_tpu.ft import multihost

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    kv = _WriteOnceKV()
    monkeypatch.setattr(multihost, "_kv", lambda: kv)

    assert multihost.agree_on_signal(USR1, timeout_seconds=5.0) == USR1
    assert multihost.agree_on_signal(USR1, timeout_seconds=5.0) == USR1
    oneshot = [k for k in kv.store if k.startswith("ftl_sig/oneshot")]
    assert len(oneshot) == 2  # two distinct rounds, no collision

    # explicit rounds are untouched: integer keys, R-2 garbage-collected
    for r in range(3):
        assert multihost.agree_on_signal(0, round_id=r,
                                         timeout_seconds=5.0) is None
    assert "ftl_sig/0/0" not in kv.store  # deleted when round 2 published
    assert "ftl_sig/2/0" in kv.store


_WORKER = """
import os, sys
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize(sys.argv[2], num_processes=2, process_id=pid)
from fault_tolerant_llm_training_tpu.ft.multihost import (
    agree_on_signal, barrier, should_resubmit)
local = 10 if pid == 0 else None  # only host 0 saw USR1
verdict = agree_on_signal(local)
barrier('test_multihost')
print(f'verdict={verdict} resubmit={should_resubmit()}', flush=True)
assert verdict == 10
"""


def _launch_pair(extra_args, job_id, n=2, signal_to=None,
                 wait_for=None, timeout=240, signal_target=0):
    """Run n train.py processes as one jax.distributed cluster; returns
    (returncodes, outputs). Optionally sends ``signal_to`` (a signal number)
    to process ``signal_target`` once ``wait_for`` appears in process 0's
    output."""
    import os
    import socket
    import subprocess
    import sys
    import threading

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = [sys.executable, os.path.join(repo_root, "train.py"),
            "--tokenizer-name-or-path", "byte", "--model", "tiny",
            "--sequence-length", "128", "--batch-size", "4",
            "--logging-frequency", "2", "--distributed"] + extra_args
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            coord = f"localhost:{s.getsockname()[1]}"
        procs = []
        for i in range(n):
            env = {**os.environ, "PYTHONPATH": repo_root,
                   "JAX_PLATFORMS": "cpu", "SLURM_JOB_ID": job_id,
                   "JAX_COMPILATION_CACHE_DIR": "/tmp/jax_test_compile_cache",
                   "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
                   "JAX_COORDINATOR_ADDRESS": coord,
                   "JAX_NUM_PROCESSES": str(n), "JAX_PROCESS_ID": str(i)}
            env.pop("PALLAS_AXON_POOL_IPS", None)
            env.pop("XLA_FLAGS", None)
            procs.append(subprocess.Popen(
                base, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        try:
            if signal_to is not None:
                # Reader thread so the timeout holds even if the process
                # goes silent before printing the wait_for marker.
                lines = []
                fired = threading.Event()

                def _reader():
                    for line in procs[0].stdout:
                        lines.append(line)
                        if not fired.is_set() and wait_for in line:
                            procs[signal_target].send_signal(signal_to)
                            fired.set()

                rt = threading.Thread(target=_reader, daemon=True)
                rt.start()
                rt.join(timeout)
                if rt.is_alive() or not fired.is_set():
                    raise subprocess.TimeoutExpired(base, timeout)
                procs[0].wait(timeout=timeout)
                outs = ["".join(lines)]
                outs += [p.communicate(timeout=timeout)[0] for p in procs[1:]]
            else:
                outs = [p.communicate(timeout=timeout)[0] for p in procs]
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            outs = [p.communicate()[0] or "" for p in procs]
            continue
        return [p.returncode for p in procs], outs
    return [p.returncode for p in procs], outs


def test_two_process_usr1_chain_and_resume(tmp_path, parquet2, multiprocess_cpu_jit):
    """End-to-end pod preemption: USR1 lands on host 0 only; the cluster
    agrees, both hosts run the coordinated sharded save at the SAME step,
    only host 0 resubmits, and a chained 2-process job resumes from that
    step (the reference chain of SURVEY.md §3.4-3.5, multi-host edition)."""
    import re
    import signal as _sig

    ckpt = str(tmp_path / "ckpts")
    marker = tmp_path / "resub.txt"
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", "100000", "--signal-sync-frequency", "3",
         "--resubmit-command", f"touch {marker}"],
        job_id="mh_usr1", signal_to=_sig.SIGUSR1,
        wait_for="Training step: 4")
    assert rcs == [0, 0], outs
    saved = [re.search(r"Checkpoint saved at step (\d+)", o) for o in outs]
    assert all(saved), outs
    assert saved[0].group(1) == saved[1].group(1), "hosts saved different steps"
    assert "[EXIT HANDLER] Job timed out, saving checkpoint." in outs[0]
    assert "sbatch requeued" in outs[0]
    assert "sbatch requeued" not in outs[1]  # only process 0 chains the job
    assert marker.exists()

    step = int(saved[0].group(1))
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", str(step + 5), "--checkpoint-id", "mh_usr1"],
        job_id="mh_resume")
    assert rcs == [0, 0], outs
    for o in outs:
        assert f"Resuming training from training_step {step}" in o, o
        assert "Training completed" in o


def test_two_process_periodic_checkpointing_and_eval(tmp_path, parquet2, multiprocess_cpu_jit):
    """Periodic coordinated saves on a pod: the pre-save barrier runs with
    the dispatch pipeline drained (regression: entering the barrier with
    steps in flight interleaves collectives differently per host and
    crashes gloo), and both hosts finish with the checkpoints on disk.
    Held-out eval runs on the same cluster: every host dispatches the same
    eval program order (no cross-host divergence) and reports the same
    token-weighted loss."""
    import re

    ckpt = str(tmp_path / "ckpts")
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", "12", "--checkpoint-frequency", "4",
         "--eval-frequency", "6", "--eval-batches", "2"],
        job_id="mh_per")
    assert rcs == [0, 0], outs
    for o in outs:
        assert "Training completed" in o, o
    root = tmp_path / "ckpts" / "checkpoint_mh_per"
    steps = sorted(int(p.name) for p in root.iterdir() if p.name.isdigit())
    assert 8 in steps, steps
    evals = [re.findall(r"Eval \| step (\d+) \| loss ([\d.]+)", o)
             for o in outs]
    assert [s for s, _ in evals[0]] == ["6", "12"], outs[0]
    assert evals[0] == evals[1], "hosts disagree on eval losses"


def test_two_process_local_error_fence_saves_and_resumes(tmp_path, parquet2, multiprocess_cpu_jit):
    """VERDICT r4 weak #1: a HOST-LOCAL (non-replicated) error on one host
    must still produce the reference's −1 guarantee (always save,
    ref utils.py:69-81) at pod scale. Process 1 raises alone mid-run; the
    fault fence converges both hosts on the same step, both run the
    coordinated save, both exit 0, nobody resubmits — and a chained
    2-process job resumes from that checkpoint."""
    import re

    ckpt = str(tmp_path / "ckpts")
    marker = tmp_path / "resub.txt"
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", "100000", "--signal-sync-frequency", "3",
         "--raise-error", "--error-step", "6", "--error-local-rank", "1",
         "--peer-timeout-seconds", "60",
         "--resubmit-command", f"touch {marker}"],
        job_id="mh_localerr")
    assert rcs == [0, 0], outs
    saved = [re.search(r"Checkpoint saved at step (\d+)", o) for o in outs]
    assert all(saved), outs
    assert saved[0].group(1) == saved[1].group(1), "hosts saved different steps"
    # −1 audit trail on both hosts; no resubmit anywhere (−1 semantics)
    for o in outs:
        assert ("[EXIT HANDLER] Error during training encountered, "
                "saving checkpoint.") in o, o
        assert "sbatch requeued" not in o, o
        assert "terminating without a checkpoint" not in o, o
    assert not marker.exists()
    # the erroring host raised at step 6; the save is at >= 7 dispatched
    step = int(saved[0].group(1))
    assert step >= 7, outs

    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", str(step + 4), "--checkpoint-id", "mh_localerr"],
        job_id="mh_localerr_resume")
    assert rcs == [0, 0], outs
    for o in outs:
        assert f"Resuming training from training_step {step}" in o, o
        assert "Training completed" in o, o


def test_two_process_peer_death_degrades_cleanly(tmp_path, parquet2, multiprocess_cpu_jit):
    """VERDICT r4 weak #1 (watchdog half): SIGKILL one host mid-run — the
    survivor must NOT hang in its next collective until the scheduler
    shoots it; it detects the silent peer via the wait watchdog and exits
    0 with the degraded audit line, writing no (possibly corrupt)
    checkpoint."""
    import signal as _sig

    ckpt = str(tmp_path / "ckpts")
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", "100000", "--signal-sync-frequency", "3",
         "--peer-timeout-seconds", "20"],
        job_id="mh_peerdeath", signal_to=_sig.SIGKILL,
        wait_for="Training step: 4", signal_target=1)
    assert rcs[0] == 0, outs
    assert rcs[1] != 0  # SIGKILLed
    assert "terminating without a checkpoint" in outs[0], outs[0]
    assert "Checkpoint saved at step" not in outs[0], outs[0]
    # no committed checkpoint dir may exist (atomic Orbax commit)
    root = tmp_path / "ckpts" / "checkpoint_mh_peerdeath"
    if root.exists():
        assert not [p for p in root.iterdir() if p.name.isdigit()], (
            list(root.iterdir()))


def test_three_process_local_error_fence(tmp_path, parquet2, multiprocess_cpu_jit):
    """The fence is N-generic, not a 2-host special case: with three hosts,
    one raising alone, gather_stops collects two peers' stops, the laggards
    catch up to the cluster maximum, and all three save the SAME step and
    exit 0 without resubmitting."""
    import re

    ckpt = str(tmp_path / "ckpts")
    rcs, outs = _launch_pair(
        ["--dataset", parquet2, "--checkpoint-path", ckpt,
         "--training-steps", "100000", "--signal-sync-frequency", "3",
         "--batch-size", "6",  # divisible by 3 hosts' data sharding
         "--raise-error", "--error-step", "6", "--error-local-rank", "1",
         "--peer-timeout-seconds", "60", "--resubmit-command", "true"],
        job_id="mh3_localerr", n=3)
    assert rcs == [0, 0, 0], outs
    saved = [re.search(r"Checkpoint saved at step (\d+)", o) for o in outs]
    assert all(saved), outs
    assert len({m.group(1) for m in saved}) == 1, "hosts saved different steps"
    for o in outs:
        assert "sbatch requeued" not in o, o
        assert "terminating without a checkpoint" not in o, o


def test_two_process_sharded_data_matches_replicated(tmp_path, parquet2, multiprocess_cpu_jit):
    """--data-sharding host (the pod default via auto) must reproduce the
    replicated-read trajectory line-for-line: same losses, same grad
    norms, while each host tokenizes only its own rows
    (tests/test_sharded_data.py proves array-level bit-identity; this
    pins the full CLI path end-to-end)."""
    import re

    def _lines(mode):
        ckpt = str(tmp_path / f"ckpts_{mode}")
        rcs, outs = _launch_pair(
            ["--dataset", parquet2, "--checkpoint-path", ckpt,
             "--training-steps", "8", "--logging-frequency", "1",
             "--data-sharding", mode],
            job_id=f"mh_ds_{mode}")
        assert rcs == [0, 0], outs
        assert "Training completed" in outs[0]
        return [ln for ln in outs[0].splitlines()
                if re.search(r"Training step: \d+ \| Loss|grad_norm", ln)]

    host = _lines("host")
    rep = _lines("replicated")
    # strip timestamps/throughput; keep step, loss, grad_norm
    strip = lambda lns: [re.sub(r"^.*?(Training step|Metrics)", r"\1",
                                re.sub(r"\| tokens/s.*$", "", ln)).strip()
                         for ln in lns]
    assert strip(host) == strip(rep)
    assert len(host) >= 8


@pytest.fixture(scope="module")
def parquet2(tmp_path_factory):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(3)
    words = ["alpha", "bravo", "charlie", "delta", "echo"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(20, 120))))
            for _ in range(128)]
    path = tmp_path_factory.mktemp("data2") / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), path)
    return str(path)


def test_two_process_agreement(tmp_path, multiprocess_cpu_jit):
    """Real jax.distributed 2-process run: the host that saw no signal
    reaches the same USR1 verdict; only process 0 resubmits."""
    import os
    import socket
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    # bind-then-close port discovery has a TOCTOU race with other processes
    # on the machine — retry with a fresh port on failure
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            coord = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        try:
            outs = [p.communicate(timeout=120)[0] for p in procs]
        except subprocess.TimeoutExpired:
            # a foreign listener on the stolen port hangs the rendezvous
            for p in procs:
                p.kill()
            outs = [p.communicate()[0] for p in procs]
            continue
        if all(p.returncode == 0 for p in procs):
            break
    assert all(p.returncode == 0 for p in procs), outs
    assert "verdict=10 resubmit=True" in outs[0], outs[0]
    assert "verdict=10 resubmit=False" in outs[1], outs[1]
