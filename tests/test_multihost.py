"""Multi-host fault-tolerance coordination (ft/multihost.py).

Real multi-process agreement needs a pod; these tests pin down the policy
function (pure), the single-process identity paths, and the synced check
wiring — the pieces that must hold before the allgather even matters.
"""

import signal

import pytest

from fault_tolerant_llm_training_tpu.ft.multihost import (
    agree_on_signal,
    barrier,
    combine_signals,
    should_resubmit,
)
from fault_tolerant_llm_training_tpu.ft.signals import SignalFlag, TrainingSignal

USR1 = int(signal.SIGUSR1)
TERM = int(signal.SIGTERM)


def test_combine_signals_policy():
    assert combine_signals([]) is None
    assert combine_signals([0, 0, 0]) is None
    assert combine_signals([0, USR1, 0]) == USR1
    assert combine_signals([TERM, TERM]) == TERM
    # mixed mid-grace-period view: the save-and-requeue path wins
    assert combine_signals([TERM, USR1, 0]) == USR1
    assert combine_signals([7, 9]) == 7  # deterministic for exotic codes


def test_single_process_identity():
    assert agree_on_signal(None) is None
    assert agree_on_signal(USR1) == USR1
    assert should_resubmit()
    barrier("test")  # no-op, must not raise


def test_synced_check_raises_same_signal():
    flag = SignalFlag()
    flag._handler(USR1, None)
    with pytest.raises(TrainingSignal) as e:
        flag.check(synced=True)
    assert e.value.args == ("Exception", USR1)
    flag.check(synced=True)  # cleared after raise


class _StubTrainer:
    def __init__(self, replicated):
        self.state = object()
        self.error_is_replicated = replicated
        self.saved_with = None
        self.cfg = type("C", (), {"resubmit_command": "true"})()

    def save_checkpoint(self, wait=True, coordinated=True):
        self.saved_with = dict(wait=wait, coordinated=coordinated)
        return 7


def test_host_local_error_skips_coordinated_save(monkeypatch, caplog):
    """On a pod, an error of unknown provenance must not enter the pre-save
    barrier (the other hosts never reach it); replicated errors may."""
    import logging

    import jax

    from fault_tolerant_llm_training_tpu.ft import handler

    monkeypatch.setattr(jax, "process_count", lambda: 2)
    logger = logging.getLogger()
    with caplog.at_level(logging.INFO):
        t = _StubTrainer(replicated=False)
        handler.handle_exit(t, handler.CODE_ERROR, logger)
        assert t.saved_with is None
        assert any("cannot write a coordinated checkpoint" in r.message
                   for r in caplog.records)
    t = _StubTrainer(replicated=True)
    handler.handle_exit(t, handler.CODE_ERROR, logger)
    assert t.saved_with == dict(wait=True, coordinated=True)


_WORKER = """
import os, sys
os.environ.pop('PALLAS_AXON_POOL_IPS', None)
os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=2'
import jax
jax.config.update('jax_platforms', 'cpu')
pid = int(sys.argv[1])
jax.distributed.initialize(sys.argv[2], num_processes=2, process_id=pid)
from fault_tolerant_llm_training_tpu.ft.multihost import (
    agree_on_signal, barrier, should_resubmit)
local = 10 if pid == 0 else None  # only host 0 saw USR1
verdict = agree_on_signal(local)
barrier('test_multihost')
print(f'verdict={verdict} resubmit={should_resubmit()}', flush=True)
assert verdict == 10
"""


def test_two_process_agreement(tmp_path):
    """Real jax.distributed 2-process run: the host that saw no signal
    reaches the same USR1 verdict; only process 0 resubmits."""
    import os
    import socket
    import subprocess
    import sys

    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    # bind-then-close port discovery has a TOCTOU race with other processes
    # on the machine — retry with a fresh port on failure
    for attempt in range(3):
        with socket.socket() as s:
            s.bind(("localhost", 0))
            coord = f"localhost:{s.getsockname()[1]}"
        procs = [subprocess.Popen(
            [sys.executable, str(script), str(i), coord],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env) for i in range(2)]
        try:
            outs = [p.communicate(timeout=120)[0] for p in procs]
        except subprocess.TimeoutExpired:
            # a foreign listener on the stolen port hangs the rendezvous
            for p in procs:
                p.kill()
            outs = [p.communicate()[0] for p in procs]
            continue
        if all(p.returncode == 0 for p in procs):
            break
    assert all(p.returncode == 0 for p in procs), outs
    assert "verdict=10 resubmit=True" in outs[0], outs[0]
    assert "verdict=10 resubmit=False" in outs[1], outs[1]
