"""Disaggregated prefill/decode serving (inference/scheduler.py roles,
inference/journal.py ship/prefill_done/decode records, inference/router.py
role-aware placement).

Evidence ladder:

1. roles — the scheduler validates its role, refuses shipments on a
   prefill engine, and dedicated roles require the paged layout;
2. shipping — a prefill-role run exports each committed chunk as a
   CRC-manifested artifact the moment it commits: seq-ordered,
   contiguously tiled from block 0, every non-final shipment covering
   FULL committed blocks only (a decode engine can never read an
   uncommitted position), each artifact verifiable before its record
   exists;
3. decode admission — importing the shipments reproduces the colocated
   stream BITWISE for greedy and sampled decoding, shared-prompt
   prefixes are deduped through the decode engine's prefix cache instead
   of re-imported, and a poisoned shipment degrades to the bit-exact
   committed-prefix replay;
4. router — placement is role- and dtype-aware: fresh intake lands on
   prefill capacity, ``prefill_done`` advances to a decode host via a
   ``decode`` record carrying router-VERIFIED shipments (one bad
   artifact drops the list into replay), and a mixed-dtype
   prefill->decode pair is refused AT PLACEMENT TIME, before any prefill
   runs;
5. drain — both roles stop admission, persist unserved work, and leave
   the block-leak audit clean.
"""

import glob
import json
import os

import numpy as np
import pytest


def _tiny_cfg(vocab=64, seq_len=128):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def sleep(self, dt):
        self.t += dt


@pytest.fixture(scope="module")
def disagg_setup():
    """One tiny model + the colocated reference streams every
    disaggregated pipeline below must reproduce bitwise. Prompts are
    long enough (40+ tokens, chunk 32) to cross chunk boundaries, so
    prefill ships MORE than one incremental artifact per request."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def build(slots=4, num_blocks=None):
        return InferenceEngine(cfg, params, slots=slots, max_len=128,
                               prefill_buckets=(16, 32), kv_layout="paged",
                               kv_block_size=8, kv_num_blocks=num_blocks)

    rng = np.random.default_rng(17)
    common = rng.integers(3, 64, size=16).tolist()
    reqs = [
        Request(id="g", prompt=rng.integers(3, 64, size=41).tolist(),
                max_new_tokens=20, seed=1),
        Request(id="s", prompt=rng.integers(3, 64, size=37).tolist(),
                max_new_tokens=16, temperature=0.8, top_p=0.9, seed=2),
        Request(id="p1", prompt=common + rng.integers(3, 64,
                                                      size=20).tolist(),
                max_new_tokens=12, seed=3),
        Request(id="p2", prompt=common + rng.integers(3, 64,
                                                      size=23).tolist(),
                max_new_tokens=12, temperature=0.7, seed=4),
    ]
    sched = Scheduler(build())
    for r in reqs:
        sched.submit(r)
    sched.run()
    ref = {c.request_id: c.tokens for c in sched.completed}
    assert set(ref) == {"g", "s", "p1", "p2"}
    return {"build": build, "reqs": reqs, "ref": ref,
            "Request": Request, "Scheduler": Scheduler}


def _run_prefill(setup, tmp_path, reqs=None, corrupt=None):
    """Run a prefill-role scheduler to completion; returns (sched, ships)
    where ships[rid] is the seq-ordered journal-shaped shipment list."""
    Scheduler = setup["Scheduler"]
    ships = {}

    def on_ship(req, art_dir, ordinal, seq, start, end, length):
        if corrupt is not None:
            corrupt(req, art_dir, ordinal, seq)
        ships.setdefault(req.id, []).append(
            {"artifact": art_dir, "seq": seq, "start_block": start,
             "end_block": end, "length": length})

    pre = Scheduler(setup["build"](), role="prefill",
                    ship_dir=str(tmp_path / "ships"), on_ship=on_ship)
    for r in (reqs if reqs is not None else setup["reqs"]):
        pre.submit(r)
    pre.run()
    return pre, ships


def _run_decode(setup, ships, prefill_completed, reqs=None):
    Request, Scheduler = setup["Request"], setup["Scheduler"]
    first = {c.request_id: c.tokens for c in prefill_completed}
    dec = Scheduler(setup["build"](), role="decode")
    for r in (reqs if reqs is not None else setup["reqs"]):
        dec.submit(Request(id=r.id, prompt=list(r.prompt),
                           max_new_tokens=r.max_new_tokens,
                           temperature=r.temperature, top_p=r.top_p,
                           seed=r.seed, committed=tuple(first[r.id])),
                   shipments=ships.get(r.id), ship_gen=0)
    dec.run()
    return dec, {c.request_id: c.tokens for c in dec.completed}


# ---------------------------------------------------------------- 1. roles
def test_role_validation(disagg_setup):
    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    with pytest.raises(ValueError, match="unknown engine role"):
        Scheduler(disagg_setup["build"](), role="hybrid")

    # dedicated roles ship block artifacts: the paged layout is required
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg(seq_len=64)
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0),
        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    ring = InferenceEngine(cfg, params, slots=2, max_len=48,
                           kv_layout="ring")
    with pytest.raises(ValueError, match="paged"):
        Scheduler(ring, role="prefill")

    # a prefill engine exports shipments; it can never accept them
    pre = Scheduler(disagg_setup["build"](), role="prefill")
    with pytest.raises(ValueError, match="cannot[\\s\\S]*accept"):
        pre.submit(Request(id="x", prompt=[1, 2, 3], max_new_tokens=4,
                           committed=(9,)),
                   shipments=[{"artifact": "/nope", "seq": 0,
                               "start_block": 0, "end_block": 1,
                               "length": 3}], ship_gen=0)


# -------------------------------------------------------------- 2. shipping
def test_incremental_shipment_ordering(disagg_setup, tmp_path):
    """Shipments leave the prefill engine AS chunks commit — seq-ordered,
    contiguous from block 0, and never covering a position the prefill
    has not committed (full blocks only until the final shipment)."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        verify_block_artifact)

    pre, ships = _run_prefill(disagg_setup, tmp_path)
    assert pre.ship_exports >= len(disagg_setup["reqs"])
    assert all(c.reason == "prefill" for c in pre.completed)
    assert all(len(c.tokens) == 1 for c in pre.completed)
    bs = 8
    for r in disagg_setup["reqs"]:
        lst = ships[r.id]
        n_blocks = -(-len(r.prompt) // bs)
        # 40-ish-token prompts with chunk 32 cross a chunk boundary:
        # the pipeline is INCREMENTAL, not one artifact at the end
        assert len(lst) >= 2, f"{r.id}: expected streaming shipments"
        assert [s["seq"] for s in lst] == list(range(len(lst)))
        assert lst[0]["start_block"] == 0
        for a, b in zip(lst, lst[1:]):
            assert b["start_block"] == a["end_block"]
        assert lst[-1]["end_block"] == n_blocks
        assert lst[-1]["length"] == len(r.prompt)
        for s in lst:
            man = verify_block_artifact(s["artifact"])
            assert man["length"] == s["length"]
            assert man["meta"]["request_id"] == r.id
            assert len(man["blocks"]) == s["end_block"] - s["start_block"]
            if s is not lst[-1]:
                # decode must never read an uncommitted position: every
                # non-final shipment ends at or before the commit point
                assert s["end_block"] * bs <= s["length"]
    assert pre.audit_block_leaks(strict=True) == []


# ------------------------------------------------------- 3. decode admission
@pytest.mark.parametrize("which", ["greedy", "sampled"])
def test_disagg_bitmatch(disagg_setup, tmp_path, which):
    """The tentpole guarantee: prefill engine -> shipped blocks -> decode
    engine emits the EXACT stream the colocated engine does, for greedy
    and sampled requests alike (fold_in(seed, step) statelessness)."""
    pre, ships = _run_prefill(disagg_setup, tmp_path)
    dec, out = _run_decode(disagg_setup, ships, pre.completed)
    ids = (["g", "p1"] if which == "greedy" else ["s", "p2"])
    for rid in ids:
        assert out[rid] == disagg_setup["ref"][rid], (
            f"{rid}: disaggregated stream diverged from colocated")
    assert dec.ship_imports >= 1 and dec.ship_rejects == 0
    assert dec.audit_block_leaks(strict=True) == []


def test_prefix_cache_dedupes_shipped_blocks(disagg_setup, tmp_path):
    """p1/p2 share a 16-token (2-block) prompt prefix: the decode engine
    must satisfy the second import's leading blocks from its own prefix
    cache instead of re-importing them from the artifact."""
    reqs = [r for r in disagg_setup["reqs"] if r.id in ("p1", "p2")]
    pre, ships = _run_prefill(disagg_setup, tmp_path, reqs=reqs)
    dec, out = _run_decode(disagg_setup, ships, pre.completed, reqs=reqs)
    assert out == {r.id: disagg_setup["ref"][r.id] for r in reqs}
    # the second admission hit the shared prefix: fewer blocks imported
    # than shipped, and the prefix cache records the hit tokens
    m = dec.metrics()
    assert m["engine_role"] == "decode"
    assert dec.ship_imports == 2
    assert m.get("prefix_hit_tokens", 0) >= 16
    assert dec.audit_block_leaks(strict=True) == []


def test_poisoned_shipment_falls_back_to_replay(disagg_setup, tmp_path):
    """A flipped payload byte in one shipment (manifest spared — the
    chaos ``ship_corrupt`` shape): the decode admission CRC-rejects the
    import and replays the committed prefix, emitting the exact
    reference stream with nothing lost."""
    def corrupt(req, art_dir, ordinal, seq):
        if req.id == "g" and seq == 1:
            p = sorted(glob.glob(os.path.join(art_dir, "block_*.bin")))[0]
            raw = bytearray(open(p, "rb").read())
            raw[5] ^= 0xFF
            open(p, "wb").write(bytes(raw))

    pre, ships = _run_prefill(disagg_setup, tmp_path, corrupt=corrupt)
    dec, out = _run_decode(disagg_setup, ships, pre.completed)
    assert dec.ship_rejects == 1
    assert out == disagg_setup["ref"]
    assert dec.audit_block_leaks(strict=True) == []


def test_batch_import_verifies_before_any_device_write(disagg_setup,
                                                       tmp_path):
    """``import_block_batch`` is the admission fast path: a request's
    whole shipment train lands as ONE scatter per pool array. Atomicity
    contract: a CRC failure in ANY artifact of the batch — here the
    last — raises before the FIRST device write, so the earlier, intact
    artifacts must not land either: the pool stays bit-identical."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KVBlockIntegrityError)

    pre, ships = _run_prefill(disagg_setup, tmp_path)
    train = ships["g"]
    assert len(train) >= 2                 # a real multi-chunk train
    eng = disagg_setup["build"]()
    before = [np.asarray(a) for a in (*eng.cache.k, *eng.cache.v)]
    p = sorted(glob.glob(os.path.join(
        str(train[-1]["artifact"]), "block_*.bin")))[0]
    raw = bytearray(open(p, "rb").read())
    raw[3] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    parts, dest = [], 1
    for s in train:
        n = int(s["end_block"]) - int(s["start_block"])
        parts.append((str(s["artifact"]), list(range(dest, dest + n))))
        dest += n
    with pytest.raises(KVBlockIntegrityError):
        eng.import_pool_block_batch(parts)
    after = [np.asarray(a) for a in (*eng.cache.k, *eng.cache.v)]
    for b, a in zip(before, after):
        assert np.array_equal(b, a)


# ------------------------------------------------------------------ 4. router
def _registry(store, host_id, clock, ttl=2.0):
    from fault_tolerant_llm_training_tpu.ft.lease import LeaseRegistry

    return LeaseRegistry(store, host_id=host_id, ttl_seconds=ttl,
                         clock=clock, monotonic=clock, sleep=clock.sleep)


def _router(tmp_path):
    from fault_tolerant_llm_training_tpu.ft.lease import FileKVStore
    from fault_tolerant_llm_training_tpu.inference.router import Router

    clock = _Clock()
    store = FileKVStore(str(tmp_path / "kv"))
    jd = str(tmp_path / "journal")
    router = Router(store, jd, clock=clock)
    router.lease.monotonic = clock
    router.lease.sleep = clock.sleep
    return clock, store, jd, router


def test_role_aware_placement(tmp_path):
    """Fresh intake needs prefill capacity, committed history needs
    decode capacity — a request is never parked on a host whose role
    cannot advance it."""
    from fault_tolerant_llm_training_tpu.inference.journal import fold

    clock, store, jd, router = _router(tmp_path)
    _registry(store, "pre0", clock).register(2, 40, 8, role="prefill")
    _registry(store, "dec0", clock).register(2, 30, 8, role="decode")
    router.submit("fresh", [1, 2, 3], 8, 0.0, 1.0, 7)
    router.refresh()
    assert router.assign_pending() == 1
    assert fold(jd)["fresh"].host == "pre0"

    # a requeued request with committed history is decode-stage work
    router.journal.requeue("cont", [4, 5, 6], 8, 0.0, 1.0, 9,
                           committed=[11, 12], gen=1)
    router.refresh()
    router.adopt_requeued()
    assert router.assign_pending() == 1
    assert fold(jd)["cont"].host == "dec0"


def test_prefill_done_advances_to_decode_host(tmp_path):
    """``prefill_done`` + verified shipments become ONE ``decode`` record
    at gen+1: ownership moves to the dtype-matching decode host with the
    shipment list attached; a second loop never re-places it."""
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks, init_paged_cache)

    clock, store, jd, router = _router(tmp_path)
    _registry(store, "pre0", clock).register(2, 40, 8, role="prefill")
    _registry(store, "dec0", clock).register(2, 30, 8, role="decode")
    router.submit("rA", list(range(3, 19)), 8, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()
    assert fold(jd)["rA"].host == "pre0"

    cache = init_paged_cache(_tiny_cfg(seq_len=64), slots=2, max_len=32,
                             block_size=8)
    art = str(tmp_path / "ship_rA_00")
    export_blocks(cache, [1, 2], art, length=16)
    host = RequestJournal(jd, writer="host_pre0")
    host.ship("rA", "pre0", art, seq=0, start_block=0, end_block=2,
              length=16, gen=0)
    host.prefill_done("rA", "pre0", [42], gen=0, kv_dtype="bf16")

    assert router.advance_prefilled() == 1
    st = fold(jd)["rA"]
    assert (st.host, st.gen, st.committed) == ("dec0", 1, [42])
    rec = [json.loads(l) for l in open(os.path.join(jd, "router.jsonl"))
           if '"decode"' in l][-1]
    assert rec["kind"] == "decode" and rec["host"] == "dec0"
    assert [s["artifact"] for s in rec["shipments"]] == [art]
    assert router.advance_prefilled() == 0  # idempotent across loops


def test_router_rejects_poisoned_shipment_into_replay(tmp_path):
    """One bad artifact drops the WHOLE shipment list: the decode record
    still lands (ownership advances) but with shipments=[] — the decode
    host replays the committed prefix instead of importing."""
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks, init_paged_cache)

    clock, store, jd, router = _router(tmp_path)
    _registry(store, "pre0", clock).register(2, 40, 8, role="prefill")
    _registry(store, "dec0", clock).register(2, 30, 8, role="decode")
    router.submit("rB", list(range(3, 19)), 8, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()

    cache = init_paged_cache(_tiny_cfg(seq_len=64), slots=2, max_len=32,
                             block_size=8)
    host = RequestJournal(jd, writer="host_pre0")
    arts = []
    for seq, blocks in enumerate(([1], [2])):
        art = str(tmp_path / f"ship_rB_{seq:02d}")
        export_blocks(cache, blocks, art, length=8 * (seq + 1))
        host.ship("rB", "pre0", art, seq=seq, start_block=seq,
                  end_block=seq + 1, length=8 * (seq + 1), gen=0)
        arts.append(art)
    p = glob.glob(os.path.join(arts[1], "block_*.bin"))[0]
    raw = bytearray(open(p, "rb").read())
    raw[0] ^= 0xFF
    open(p, "wb").write(bytes(raw))
    host.prefill_done("rB", "pre0", [42], gen=0, kv_dtype="bf16")

    assert router.advance_prefilled() == 1
    rec = [json.loads(l) for l in open(os.path.join(jd, "router.jsonl"))
           if '"decode"' in l][-1]
    assert rec["shipments"] == []  # replay fallback, ownership advanced
    assert fold(jd)["rB"].host == "dec0"


def test_mixed_dtype_pair_rejected_at_placement_time(tmp_path):
    """An int8 prefill host with only a bf16 decode peer can never
    produce an importable shipment: the router refuses the pair BEFORE
    any prefill runs (the request waits), and admits the moment an int8
    decode host joins."""
    from fault_tolerant_llm_training_tpu.inference.journal import fold

    clock, store, jd, router = _router(tmp_path)
    _registry(store, "pre8", clock).register(2, 40, 8, role="prefill",
                                             kv_dtype="int8")
    _registry(store, "dec16", clock).register(2, 30, 8, role="decode",
                                              kv_dtype="bf16")
    router.submit("rC", [1, 2, 3], 8, 0.0, 1.0, 7)
    router.refresh()
    assert router.assign_pending() == 0  # refused before prefill started
    assert ("rC", "pre8") in router._place_rejected
    assert "rC" not in fold(jd)

    _registry(store, "dec8", clock).register(2, 30, 8, role="decode",
                                             kv_dtype="int8")
    router.refresh()
    assert router.assign_pending() == 1
    assert fold(jd)["rC"].host == "pre8"


def test_prefill_host_death_keeps_shipments_alive(tmp_path):
    """The prefill host dies AFTER prefill_done: the sweep must NOT
    migrate the request into a re-prefill — the shipments live on shared
    disk and advance_prefilled still places the decode half."""
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        export_blocks, init_paged_cache)

    clock, store, jd, router = _router(tmp_path)
    pre = _registry(store, "pre0", clock)
    dec = _registry(store, "dec0", clock)
    pre.register(2, 40, 8, role="prefill")
    dec.register(2, 30, 8, role="decode")
    router.submit("rD", list(range(3, 19)), 8, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()

    cache = init_paged_cache(_tiny_cfg(seq_len=64), slots=2, max_len=32,
                             block_size=8)
    art = str(tmp_path / "ship_rD_00")
    export_blocks(cache, [1, 2], art, length=16)
    host = RequestJournal(jd, writer="host_pre0")
    host.ship("rD", "pre0", art, seq=0, start_block=0, end_block=2,
              length=16, gen=0)
    host.prefill_done("rD", "pre0", [42], gen=0, kv_dtype="bf16")

    clock.t += 3.0  # pre0's lease expires; dec0 renews
    dec.renew(2, 30, 8, role="decode")
    assert router.sweep() == 0  # prefill-done work is NOT lost with pre0
    assert router.advance_prefilled() == 1
    st = fold(jd)["rD"]
    assert st.host == "dec0" and st.gen == 1


def test_single_token_prefill_completes_in_place(tmp_path):
    """max_new_tokens == 1: the sampled first token IS the stream — the
    router records done at gen+1 instead of writing a decode record the
    scheduler would refuse."""
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)

    clock, store, jd, router = _router(tmp_path)
    _registry(store, "pre0", clock).register(2, 40, 8, role="prefill")
    _registry(store, "dec0", clock).register(2, 30, 8, role="decode")
    router.submit("r1", [1, 2, 3], 1, 0.0, 1.0, 7)
    router.refresh()
    router.assign_pending()
    RequestJournal(jd, writer="host_pre0").prefill_done(
        "r1", "pre0", [42], gen=0, kv_dtype="bf16")
    router.advance_prefilled()
    st = fold(jd)["r1"]
    assert st.done and st.done_tokens == [42] and st.reason == "length"


def test_stale_generation_shipments_are_dropped(tmp_path):
    """Ship records fold newest-generation-only: a re-prefill after a
    migration re-ships at its own gen and the stale set must not mix."""
    from fault_tolerant_llm_training_tpu.inference.journal import (
        RequestJournal, fold)

    jd = str(tmp_path / "journal")
    host = RequestJournal(jd, writer="host_pre0")
    host.ship("rS", "pre0", "/tmp/old_0", seq=0, start_block=0,
              end_block=1, length=8, gen=0)
    host.ship("rS", "pre1", "/tmp/new_0", seq=0, start_block=0,
              end_block=1, length=8, gen=2)
    host.ship("rS", "pre1", "/tmp/new_1", seq=1, start_block=1,
              end_block=2, length=16, gen=2)
    st = fold(jd)["rS"]
    assert st.ship_gen == 2
    assert [s["artifact"] for s in st.shipments] == ["/tmp/new_0",
                                                     "/tmp/new_1"]


# ------------------------------------------------------------------- 5. drain
def test_drain_on_both_roles(disagg_setup, tmp_path):
    """Both roles honor the drain contract: admission stops, unserved
    work persists with its committed baseline, and the strict block-leak
    audit is clean."""
    Request, Scheduler = disagg_setup["Request"], disagg_setup["Scheduler"]

    # prefill role: one request finishes its prefill, one never admits
    pre, ships = _run_prefill(disagg_setup, tmp_path,
                              reqs=[disagg_setup["reqs"][0]])
    pre.stop_admission()
    pre.submit(Request(id="late", prompt=[5, 6, 7], max_new_tokens=4,
                       seed=9))
    uns = pre.unserved()
    assert [r.id for r in uns] == ["late"]
    assert pre.audit_block_leaks(strict=True) == []

    # decode role: drain mid-decode, the slot's committed stream persists
    dec = Scheduler(disagg_setup["build"](), role="decode")
    r = disagg_setup["reqs"][0]
    first = {c.request_id: c.tokens for c in pre.completed}
    dec.submit(Request(id=r.id, prompt=list(r.prompt),
                       max_new_tokens=r.max_new_tokens, seed=r.seed,
                       committed=tuple(first[r.id])),
               shipments=ships[r.id], ship_gen=0)
    for _ in range(3):
        dec.step()
    dec.stop_admission()
    slot = next(iter(dec.active))
    info = dec.export_handoff(slot, str(tmp_path / "handoff_drain"),
                              gen=1)
    uns = dec.unserved()
    assert [u.id for u in uns] == [r.id]
    assert list(uns[0].committed) == info["tokens"]
    ref = disagg_setup["ref"][r.id]
    assert list(uns[0].committed) == ref[:len(uns[0].committed)]
    assert dec.audit_block_leaks(strict=True) == []
