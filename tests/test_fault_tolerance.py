"""End-to-end fault-tolerance tests driving the real CLI (train.py).

These are the executable form of the reference's log-based verification
(SURVEY.md §4): the three evidence chains — injected error, USR1 timeout
with requeue, scancel — are asserted on the same audit strings the
reference's README greps for, plus a bit-exactness upgrade: the resumed loss
sequence must equal the uninterrupted run's exactly.
"""

import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
CACHE = "/tmp/jax_test_compile_cache"


def _env():
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env["JAX_COMPILATION_CACHE_DIR"] = CACHE  # reuse compiles across runs
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["PYTHONFAULTHANDLER"] = "1"  # stack dumps on timeout SIGABRT (_run)
    return env


def _args(tmp_path, parquet, **over):
    base = {
        "--dataset": parquet,
        "--checkpoint-path": str(tmp_path / "ckpts"),
        "--tokenizer-name-or-path": "byte",
        "--model": "tiny",
        "--sequence-length": "128",
        "--batch-size": "2",
        "--training-steps": "30",
        "--lr-warmup-steps": "5",
        "--learning-rate": "1e-3",
        "--logging-frequency": "1",
    }
    base.update({k: str(v) for k, v in over.items()})
    argv = [sys.executable, str(REPO / "train.py")]
    for k, v in base.items():
        argv.append(k)
        if v != "":
            argv.append(v)
    return argv


def _run(argv, job_id, timeout=240, send_signal=None, wait_for=None,
         xla_devices=None):
    env = _env()
    env["SLURM_JOB_ID"] = job_id
    if xla_devices is not None:
        # Same raised collective-stuck timeouts as the in-process runs
        # (see COLLECTIVE_TIMEOUT_FLAGS in conftest.py): the 20 s/40 s
        # defaults abort a many-virtual-device subprocess mid-run.
        from conftest import COLLECTIVE_TIMEOUT_FLAGS
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={xla_devices} "
            + COLLECTIVE_TIMEOUT_FLAGS)
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    if send_signal is not None:
        # wait until training is underway (wait_for string seen), then
        # signal. Reading runs on a helper thread so a child that wedges
        # without printing still hits the deadline (a blocking
        # `for line in proc.stdout` only checks time when a line arrives).
        import queue as _queue
        import threading as _threading

        lines = _queue.Queue()

        def _reader():
            for line in proc.stdout:
                lines.put(line)
            lines.put(None)

        _threading.Thread(target=_reader, daemon=True).start()
        out_lines = []
        deadline = time.time() + timeout
        fired = False
        while True:
            try:
                line = lines.get(timeout=max(0.1, deadline - time.time()))
            except _queue.Empty:
                line = ""
            if line is None:
                break
            if line:
                out_lines.append(line)
                if not fired and wait_for in line:
                    proc.send_signal(send_signal)
                    fired = True
            if time.time() > deadline:
                proc.kill()
                break
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()  # reap: a leaked trainer starves later tests
            proc.wait()
        return proc.returncode, "".join(out_lines)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        # Reap the child: a leaked trainer keeps grinding the shared CPU
        # and poisons every later test in the session (observed: two
        # leaked 8-virtual-device runs starving a third into its own
        # timeout). SIGABRT first: PYTHONFAULTHANDLER dumps every thread's
        # stack into the captured output, so the raised error shows WHERE
        # it hung. CPU-only subprocess — safe to kill.
        import signal as _signal
        proc.send_signal(_signal.SIGABRT)
        try:
            out, _ = proc.communicate(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
            out, _ = proc.communicate()
        raise AssertionError(
            f"trainer subprocess timed out after {timeout}s; output + "
            f"faulthandler stacks:\n{out[-8000:]}")
    return proc.returncode, out


def _losses(out):
    return [line.split("Loss: ")[1].strip()
            for line in out.splitlines() if "| Loss: " in line]


def _losses_by_step(out):
    """step -> loss string, parsed from 'Training step: N | Loss: X' lines."""
    return {line.split("|")[0].split(":")[-1].strip():
            line.split("Loss: ")[1].strip()
            for line in out.splitlines() if "| Loss: " in line}


@pytest.fixture(scope="module")
def parquet(tmp_path_factory):
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    words = ["alpha", "bravo", "charlie", "delta", "echo"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(20, 120))))
            for _ in range(128)]
    path = tmp_path_factory.mktemp("data") / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), path)
    return str(path)


def test_clean_run_completes(tmp_path, parquet):
    rc, out = _run(_args(tmp_path, parquet), job_id="t0")
    assert rc == 0, out
    assert "Starting training!" in out
    assert "Training completed" in out  # ref: train.py:118
    assert len(_losses(out)) == 30


def test_injected_error_saves_no_resubmit_then_bitexact_resume(tmp_path, parquet):
    """The reference chain: --raise-error at N -> save, no requeue
    (ref: utils.py:69-81), then a chained job resumes with an identical loss
    trajectory (upgrade over the reference's visual log check)."""
    rc, baseline = _run(_args(tmp_path / "base", parquet), job_id="b0")
    assert rc == 0
    base_losses = _losses(baseline)

    argv = _args(tmp_path, parquet, **{"--raise-error": "",
                                       "--error-step": "10"})
    rc, out = _run(argv, job_id="j1")
    assert rc == 0, out
    assert "[EXIT HANDLER] Error during training encountered, saving checkpoint." in out
    assert "Checkpoint saved at step" in out
    assert "sbatch requeued" not in out  # error path never resubmits
    # the startup budget line (est save vs USR1 lead, checkpoint/manager.py)
    # and the fault path's observed write log
    assert "Checkpoint budget | state" in out
    assert "signal lead 120 s" in out
    assert "Checkpoint write |" in out
    ckpt_dir = tmp_path / "ckpts" / "checkpoint_j1"
    assert ckpt_dir.exists()

    rc, out2 = _run(_args(tmp_path, parquet, **{"--checkpoint-id": "j1"}),
                    job_id="j2")
    assert rc == 0, out2
    assert "Resuming training from training_step" in out2  # ref: train.py:81
    assert "Training completed" in out2
    # Bit-exact continuity: every post-resume loss equals the uninterrupted
    # run's loss at the same step.
    for step_str, loss in _losses_by_step(out2).items():
        step = int(step_str)
        assert base_losses[step] == loss, (step, base_losses[step], loss)


def test_checkpoint_budget_warns_when_lead_too_short(tmp_path, parquet):
    """--signal-lead-seconds 0 makes ANY estimated save exceed the lead:
    the startup budget check (checkpoint/manager.py, SURVEY §7.3 #2) must
    WARN — the branch that fires on a real cluster when the flagship save
    cannot fit the scheduler's USR1 window — and training still proceeds
    (the warning informs; it must not block)."""
    argv = _args(tmp_path, parquet,
                 **{"--signal-lead-seconds": "0", "--training-steps": "5"})
    rc, out = _run(argv, job_id="bw1")
    assert rc == 0, out
    assert "Checkpoint budget EXCEEDED" in out
    assert "Training completed" in out


def test_resume_on_different_topology(tmp_path, parquet):
    """SURVEY.md §7.3 hard part 3: a checkpoint written on one topology must
    resume on another with the same loss trajectory. Here: save on a single
    device, resume on an 8-device dp=2 x fsdp=4 mesh. Losses are compared
    numerically (cross-device psum order may differ in the last ulps, and
    the log prints 2 decimals). Batch 8 so the batch axis divides the
    resumed mesh's dp x fsdp = 8-way data sharding."""
    rc, baseline = _run(_args(tmp_path / "base", parquet,
                              **{"--batch-size": "8"}), job_id="tb0")
    assert rc == 0
    base_losses = _losses(baseline)

    argv = _args(tmp_path, parquet, **{"--batch-size": "8",
                                       "--raise-error": "",
                                       "--error-step": "10"})
    rc, out = _run(argv, job_id="tp1")
    assert rc == 0, out
    assert "Checkpoint saved at step" in out

    argv = _args(tmp_path, parquet, **{"--batch-size": "8",
                                       "--checkpoint-id": "tp1",
                                       "--dp": "2", "--fsdp": "4"})
    rc, out2 = _run(argv, job_id="tp2", xla_devices=8)
    assert rc == 0, out2
    assert "Resuming training from training_step" in out2
    assert "Training completed" in out2
    resumed = _losses_by_step(out2)
    assert len(resumed) >= 10
    for step_str, loss in resumed.items():
        step = int(step_str)
        assert abs(float(base_losses[step]) - float(loss)) <= 0.02, (
            step, base_losses[step], loss)


def test_usr1_saves_and_resubmits(tmp_path, parquet):
    """ref chain: USR1 -> save + sbatch requeue (utils.py:69-88)."""
    marker = tmp_path / "resubmitted.txt"
    argv = _args(tmp_path, parquet,
                 **{"--training-steps": "100000",
                    "--resubmit-command": f"touch {marker}"})
    rc, out = _run(argv, job_id="u1", send_signal=signal.SIGUSR1,
                   wait_for="Training step: 3")
    assert rc == 0, out
    assert "[EXIT HANDLER] Job timed out, saving checkpoint." in out
    assert "Checkpoint saved at step" in out
    assert "[EXIT HANDLER] sbatch requeued, new job will load the last checkpoint" in out
    assert marker.exists()
    assert (tmp_path / "ckpts" / "checkpoint_u1").exists()


def test_sigterm_terminates_without_save(tmp_path, parquet):
    """ref chain: scancel -> terminate, no checkpoint (utils.py:67-68)."""
    argv = _args(tmp_path, parquet, **{"--training-steps": "100000"})
    rc, out = _run(argv, job_id="c1", send_signal=signal.SIGTERM,
                   wait_for="Training step: 3")
    assert rc == 0, out
    assert "[EXIT HANDLER] Job cancelled, terminating." in out
    assert "saving checkpoint" not in out
    assert not (tmp_path / "ckpts" / "checkpoint_c1" / "0").exists()


def test_usr1_with_periodic_saves_in_flight(tmp_path, parquet):
    """USR1 while async periodic checkpointing is active: the fault-path
    save must serialize behind any in-flight periodic write (Orbax commit
    order), resubmit once, and the chained job must resume from the fault
    step — not a stale periodic step."""
    marker = tmp_path / "resub.txt"
    argv = _args(tmp_path, parquet,
                 **{"--training-steps": "100000",
                    "--checkpoint-frequency": "2",
                    "--resubmit-command": f"touch {marker}"})
    rc, out = _run(argv, job_id="pr1", send_signal=signal.SIGUSR1,
                   wait_for="Training step: 5")
    assert rc == 0, out
    assert "[EXIT HANDLER] Job timed out, saving checkpoint." in out
    saved = [l for l in out.splitlines() if "Checkpoint saved at step" in l]
    assert saved, out
    fault_step = int(saved[-1].rsplit(" ", 1)[1])
    assert marker.exists()

    rc, out2 = _run(_args(tmp_path, parquet,
                          **{"--training-steps": str(fault_step + 5),
                             "--checkpoint-id": "pr1"}), job_id="pr2")
    assert rc == 0, out2
    assert f"Resuming training from training_step {fault_step}" in out2, out2
    assert "Training completed" in out2


def test_profile_dir_writes_trace(tmp_path, parquet):
    """--profile-dir wraps the loop in jax.profiler traces (SURVEY §5.1 —
    the reference has no profiling subsystem at all)."""
    prof = tmp_path / "trace"
    argv = _args(tmp_path, parquet, **{"--training-steps": "4",
                                       "--profile-dir": str(prof)})
    rc, out = _run(argv, job_id="prof1")
    assert rc == 0, out
    assert list(prof.rglob("*.trace.json.gz")), (
        f"no trace written under {prof}")


def test_periodic_checkpointing_and_latest_resume(tmp_path, parquet):
    """--checkpoint-frequency N writes periodic async saves on top of the
    reference's fault-triggered-only saves (SURVEY.md §5.4 build note), and
    a chained job resumes from the LATEST periodic step, losing at most the
    steps since it."""
    argv = _args(tmp_path, parquet, **{"--training-steps": "17",
                                       "--checkpoint-frequency": "5"})
    rc, out = _run(argv, job_id="p1")
    assert rc == 0, out
    ckpt_root = tmp_path / "ckpts" / "checkpoint_p1"
    steps = sorted(int(p.name) for p in ckpt_root.iterdir() if p.name.isdigit())
    assert 15 in steps, steps  # latest periodic boundary before 17

    rc, out2 = _run(_args(tmp_path, parquet,
                          **{"--training-steps": "20",
                             "--checkpoint-id": "p1"}), job_id="p2")
    assert rc == 0, out2
    assert "Resuming training from training_step 15" in out2, out2
    assert "Training completed" in out2


def test_nonfinite_gradient_routes_to_error_path(tmp_path, parquet):
    """A NaN/Inf grad norm must take the same -1 save path as the torch
    error_if_nonfinite raise (ref: utils.py:61)."""
    argv = _args(tmp_path, parquet, **{"--learning-rate": "1e18",
                                       "--training-steps": "200"})
    rc, out = _run(argv, job_id="n1")
    assert rc == 0, out
    # Either the loss diverges to a non-finite grad norm (expected with an
    # absurd LR) and the error path saves, or the run completes — assert the
    # first actually happened.
    assert "non-finite gradient norm" in out
    assert "[EXIT HANDLER] Error during training encountered, saving checkpoint." in out
