"""Parallelism tests on the 8-device virtual CPU mesh (SURVEY.md §4 build
note: DP/FSDP paths must be testable without a TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding

from fault_tolerant_llm_training_tpu.models import Transformer, get_config
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.parallel.sharding import (
    batch_pspec,
    param_pspecs,
)
from fault_tolerant_llm_training_tpu.training.state import TrainState
from fault_tolerant_llm_training_tpu.training.step import (
    make_optimizer,
    make_train_step,
)

FP32 = dict(dtype=jnp.float32, param_dtype=jnp.float32)


def _setup(mesh, cfg):
    model = Transformer(cfg)
    opt = make_optimizer(1e-3, warmup_steps=2)

    def init_fn(key):
        dummy = jnp.zeros((1, 32), jnp.int32)
        params = model.init(key, dummy)["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    specs = param_pspecs(abstract)
    shardings = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    state = jax.jit(init_fn, out_shardings=shardings)(jax.random.PRNGKey(0))
    step_fn = jax.jit(make_train_step(model, opt, 1.0),
                      out_shardings=(shardings, None))
    return state, step_fn


def _batches(n, vocab, batch=8, seq=32):
    rng = np.random.default_rng(7)
    toks = rng.integers(0, vocab, (n, batch, seq)).astype(np.int32)
    labels = np.concatenate(
        [toks[:, :, 1:], np.full((n, batch, 1), -100, np.int32)], axis=2)
    return toks, labels


def _run(mesh_kwargs, n_steps=3, **cfg_overrides):
    over = dict(attention_impl="xla", **FP32)
    over.update(cfg_overrides)
    cfg = get_config("tiny", **over)
    mesh = make_mesh(**mesh_kwargs)
    with use_mesh(mesh):
        state, step_fn = _setup(mesh, cfg)
        toks, labels = _batches(n_steps, cfg.vocab_size)
        bsh = NamedSharding(mesh, batch_pspec())
        losses = []
        for i in range(n_steps):
            t = jax.device_put(toks[i], bsh)
            l = jax.device_put(labels[i], bsh)
            state, metrics = step_fn(state, t, l)
            losses.append(float(metrics["loss"]))
    return losses, state


def test_dp_matches_single_device(eight_devices):
    base, _ = _run(dict(dp=1, devices=[jax.devices()[0]]))
    dp, _ = _run(dict(dp=8))
    np.testing.assert_allclose(base, dp, rtol=1e-5, atol=1e-6)


def test_fsdp_matches_single_device(eight_devices):
    base, _ = _run(dict(dp=1, devices=[jax.devices()[0]]))
    fsdp, _ = _run(dict(dp=2, fsdp=4))
    np.testing.assert_allclose(base, fsdp, rtol=1e-5, atol=1e-6)


def test_tp_matches_single_device(eight_devices):
    base, _ = _run(dict(dp=1, devices=[jax.devices()[0]]))
    tp, _ = _run(dict(dp=2, tp=4))
    np.testing.assert_allclose(base, tp, rtol=1e-5, atol=1e-6)


def test_sp_ring_contiguous_matches_single_device(eight_devices):
    """Sequence parallelism through the full train step (ring attention in
    the model, batch sharded over ('data','sequence')) reproduces the
    single-device loss trajectory."""
    base, _ = _run(dict(dp=1, devices=[jax.devices()[0]]))
    sp, _ = _run(dict(dp=2, sp=4), attention_impl="ring",
                 sp_layout="contiguous")
    np.testing.assert_allclose(base, sp, rtol=5e-5, atol=1e-6)


def test_sp_ring_zigzag_matches_single_device(eight_devices):
    """The zigzag layout (token permutation in the step + balanced ring
    schedule) is loss-invariant: seq 32 over sp=4 -> 8 chunks of 4."""
    base, _ = _run(dict(dp=1, devices=[jax.devices()[0]]))
    zz, _ = _run(dict(dp=2, sp=4), attention_impl="ring", sp_layout="zigzag")
    np.testing.assert_allclose(base, zz, rtol=5e-5, atol=1e-6)


def test_fsdp_actually_shards_params(eight_devices):
    cfg = get_config("tiny", attention_impl="xla", **FP32)
    mesh = make_mesh(dp=1, fsdp=8)
    with use_mesh(mesh):
        state, _ = _setup(mesh, cfg)
    kernel = state.params["layers_0"]["attention"]["wq"]["kernel"]
    # embed dim (axis 0) sharded 8-way over fsdp
    db = kernel.sharding.shard_shape(kernel.shape)
    assert db[0] == kernel.shape[0] // 8


def test_8b_fsdp_state_fits_per_device_budget(eight_devices):
    """Capacity planning without allocation: the llama3-8b TrainState
    (bf16 params + AdamW moments, ~48 GB global) sharded by the path rules
    over an fsdp=8 mesh must fit a v5e-class 16 GB HBM per device — i.e.
    the rules actually partition every large tensor (a rule regression
    shows up here as a >16 GB shard, not as an OOM on a real pod)."""
    from fault_tolerant_llm_training_tpu.training.step import make_optimizer

    cfg = get_config("llama3-8b")
    model = Transformer(cfg)
    opt = make_optimizer(1e-4, warmup_steps=10)

    def init_fn(key):
        params = model.init(key, jnp.zeros((1, 32), jnp.int32))["params"]
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt.init(params))

    abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    specs = param_pspecs(abstract)
    mesh = make_mesh(dp=1, fsdp=8)
    per_device = 0
    for leaf, spec in zip(
            jax.tree_util.tree_leaves(abstract),
            jax.tree_util.tree_leaves(
                specs, is_leaf=lambda x: isinstance(
                    x, jax.sharding.PartitionSpec))):
        shard = NamedSharding(mesh, spec).shard_shape(leaf.shape)
        per_device += int(np.prod(shard)) * leaf.dtype.itemsize
    total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(abstract))
    assert total > 40e9, total  # sanity: this really is the 8B state
    # near-even split: per-device within 25% of total/8, and under 16 GB
    assert per_device < 16e9, per_device
    assert per_device < 1.25 * total / 8, (per_device, total)


def test_param_pspec_rules_cover_all_params():
    cfg = get_config("gpt2-125m", **FP32)
    model = Transformer(cfg)
    abstract = jax.eval_shape(model.init, jax.random.PRNGKey(0),
                              jnp.zeros((1, 8), jnp.int32))
    specs = param_pspecs(abstract["params"])
    flat = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
    # every 2D matrix must have at least one sharded logical axis
    n_sharded = sum(1 for s in flat if any(a is not None for a in s))
    assert n_sharded > cfg.n_layers * 7  # qkvo + w123 per layer minimum
