"""Fused paged-attention decode kernel + burst decode (ops/paged_attention.py,
ops/attention.py dispatch, inference/engine.py, inference/scheduler.py).

Evidence ladder for the in-place decode path:

1. kernel — the Pallas block-indexed kernels (S=1 decode and S>1 chunk) run
   in interpret mode equal the gather-then-attend reference within fp32
   accumulation tolerance over ADVERSARIAL pool states (garbage null block,
   freed entries fallen back to 0, stale table entries aimed at orphaned
   garbage blocks, prefix-cache rows sharing blocks, a copy-on-write final
   block, offsets landing exactly on block boundaries, chunks straddling
   block boundaries), and their output is BITWISE invariant to the bytes in
   masked positions — stale content cannot leak through the online softmax;
2. dispatch — ``paged_attention`` routes "gather" bit-exactly, routes
   "pallas" by query length (S == 1 -> decode kernel, S > 1 -> chunk kernel;
   the former silent gather fallback for S > 1 is gone), rejects unknown
   impls; ``multihead_attention`` accepts the "ring" impl configs.py admits
   and resolves it to the dense equivalent instead of raising;
3. engine — the fused sampling epilogue's token stream bit-matches the
   unfused baseline (sync full logits, sample on host with the SAME
   sampler.py function) for greedy and seeded sampled slots alike;
4. scheduler — burst decode (n tokens per dispatch) emits bit-identical
   streams to per-token decode across burst in {1, 4, 8} and across both
   kernels, EOS/budget overshoot is truncated on banking, and the dispatch
   accounting (``decode_dispatches_total`` / ``decode_host_syncs_total`` /
   ``decode_burst_tokens``) shows dispatches/token <= 1/(n * active slots).
"""

import numpy as np
import pytest


def _tiny_cfg(**kw):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    kw.setdefault("vocab_size", 64)
    kw.setdefault("seq_len", 64)
    kw.setdefault("layer_impl", "loop")
    return get_config("tiny", **kw)


# -------------------------------------------------------------------- 1. kernel
def _attend(q, pool_k, pool_v, tables, offsets, impl):
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.ops.attention import paged_attention

    return np.asarray(paged_attention(
        jnp.asarray(q), jnp.asarray(pool_k), jnp.asarray(pool_v),
        jnp.asarray(tables), jnp.asarray(offsets), impl=impl))


def _adversarial_pool(rng, dtype=np.float32):
    """Four slots over one pool, each an adversarial table/offset shape.

    slot 0: offset == 2*bs  (decode query lands on the FIRST position of
            block 2; blocks past it freed -> null-block 0 fallback)
    slot 1: offset == bs-1  (query on the LAST position of block 0; tail
            entries left STALE, aimed at orphaned garbage blocks)
    slot 2: prefix-cache row — shares its first two blocks with slot 3
    slot 3: same shared prefix, but its FINAL block is a copy-on-write
            private copy of slot 2's block 2 that diverges at the end
    """
    K, H, bs, NB, D = 2, 4, 8, 4, 16
    B = 4
    N = 16                                    # pool blocks incl. null block 0
    pool_k = rng.standard_normal((N, K, bs, D)).astype(dtype)
    pool_v = rng.standard_normal((N, K, bs, D)).astype(dtype)

    tables = np.zeros((B, NB), np.int32)
    tables[0] = [1, 2, 3, 0]                  # block 3 covers the boundary pos
    tables[1] = [4, 14, 15, 0]                # 14/15 stale: nobody owns them
    tables[2] = [5, 6, 7, 0]                  # shared prefix: blocks 5, 6
    tables[3] = [5, 6, 8, 0]                  # COW copy of block 7 -> block 8
    pool_k[8], pool_v[8] = pool_k[7].copy(), pool_v[7].copy()
    pool_k[8, :, -1], pool_v[8, :, -1] = 0.25, -0.5     # diverged tail

    offsets = np.array([2 * bs, bs - 1, 2 * bs + 5, 2 * bs + 7], np.int32)
    q = rng.standard_normal((B, 1, H, D)).astype(dtype)
    return q, pool_k, pool_v, tables, offsets


def test_pallas_kernel_matches_gather_on_adversarial_pools():
    rng = np.random.default_rng(7)
    q, pk, pv, tables, offs = _adversarial_pool(rng)
    ref = _attend(q, pk, pv, tables, offs, "gather")
    out = _attend(q, pk, pv, tables, offs, "pallas")
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_pallas_kernel_output_invariant_to_masked_bytes():
    """Rewrite every byte the masks are supposed to hide — the null block,
    the orphaned stale blocks, the positions past each offset inside live
    blocks — and the kernel output must not move by a single bit."""
    rng = np.random.default_rng(8)
    q, pk, pv, tables, offs = _adversarial_pool(rng)
    base = _attend(q, pk, pv, tables, offs, "pallas")

    pk2, pv2 = pk.copy(), pv.copy()
    for blk in (0, 14, 15):                       # null + stale garbage
        pk2[blk] = rng.standard_normal(pk[blk].shape)
        pv2[blk] = rng.standard_normal(pv[blk].shape)
    bs = pk.shape[2]
    for b in range(tables.shape[0]):              # live-block tails past the
        last = int(offs[b]) // bs                 # decode position itself
        pk2[tables[b, last], :, int(offs[b]) % bs + 1:] = 9.0
        pv2[tables[b, last], :, int(offs[b]) % bs + 1:] = -9.0
    np.testing.assert_array_equal(
        _attend(q, pk2, pv2, tables, offs, "pallas"), base)


def test_pallas_kernel_rejects_multi_query():
    from fault_tolerant_llm_training_tpu.ops.paged_attention import (
        paged_decode_attention)

    rng = np.random.default_rng(9)
    q, pk, pv, tables, offs = _adversarial_pool(rng)
    q3 = np.repeat(q, 3, axis=1)
    with pytest.raises(ValueError, match="decode"):
        paged_decode_attention(q3, pk, pv, tables, offs)


def _adversarial_chunk_pool(rng, s_q=5, dtype=np.float32):
    """Four slots mid-prefill, each an adversarial S>1 chunk geometry.

    slot 0: chunk starts exactly ON a block boundary (offset == 2*bs)
    slot 1: chunk STRADDLES a block boundary (rows span blocks 0 and 1);
            the table tail entry is stale, aimed at an orphaned garbage
            block that starts past the LAST row — must be skipped wholesale
    slot 2: prefix-cache row — shares its first two blocks with slot 3
    slot 3: same shared prefix, but its final block is a copy-on-write
            private copy of slot 2's that diverges in the rows the chunk
            actually lands on
    """
    K, H, bs, NB, D = 2, 4, 8, 4, 16
    B = 4
    N = 16                                    # pool blocks incl. null block 0
    pool_k = rng.standard_normal((N, K, bs, D)).astype(dtype)
    pool_v = rng.standard_normal((N, K, bs, D)).astype(dtype)

    tables = np.zeros((B, NB), np.int32)
    tables[0] = [1, 2, 3, 0]
    tables[1] = [4, 5, 14, 0]                 # 14 stale: past the last row
    tables[2] = [6, 7, 8, 0]                  # shared prefix: blocks 6, 7
    tables[3] = [6, 7, 9, 0]                  # COW copy of block 8 -> block 9
    pool_k[9], pool_v[9] = pool_k[8].copy(), pool_v[8].copy()
    pool_k[9, :, -3:], pool_v[9, :, -3:] = 0.25, -0.5   # diverged tail

    offsets = np.array([2 * bs, bs - 2, 2 * bs + 1, 2 * bs + 3], np.int32)
    q = rng.standard_normal((B, s_q, H, D)).astype(dtype)
    return q, pool_k, pool_v, tables, offsets


@pytest.mark.parametrize("s_q", [2, 5])
def test_pallas_chunk_kernel_matches_gather_on_adversarial_pools(s_q):
    rng = np.random.default_rng(14)
    q, pk, pv, tables, offs = _adversarial_chunk_pool(rng, s_q=s_q)
    ref = _attend(q, pk, pv, tables, offs, "gather")
    out = _attend(q, pk, pv, tables, offs, "pallas")
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_pallas_chunk_kernel_output_invariant_to_masked_bytes():
    """Rewrite every pool byte outside the union of the rows' live sets —
    the null block, the orphaned stale block, every lane past each chunk's
    last row (k_pos > offsets[b] + S - 1 for the owning slot) — and the
    chunk kernel output must not move by a single bit. The live set is
    per-ROW: a lane is live iff SOME slot's boundary admits it, which is
    exactly the union the per-row causal mask protects."""
    rng = np.random.default_rng(15)
    q, pk, pv, tables, offs = _adversarial_chunk_pool(rng)
    base = _attend(q, pk, pv, tables, offs, "pallas")

    s_q = q.shape[1]
    n, _, bs, _ = pk.shape
    live = np.zeros((n, bs), bool)
    for b in range(tables.shape[0]):
        for i in range(tables.shape[1]):
            for lane in range(bs):
                if i * bs + lane <= int(offs[b]) + s_q - 1:
                    live[tables[b, i], lane] = True
    pk2 = np.where(live[:, None, :, None], pk,
                   rng.standard_normal(pk.shape).astype(pk.dtype))
    pv2 = np.where(live[:, None, :, None], pv,
                   rng.standard_normal(pv.shape).astype(pv.dtype))
    assert not np.array_equal(pk2, pk)       # the rewrite actually happened
    np.testing.assert_array_equal(
        _attend(q, pk2, pv2, tables, offs, "pallas"), base)


def test_pallas_chunk_kernel_rejects_single_query():
    from fault_tolerant_llm_training_tpu.ops.paged_attention import (
        paged_chunk_attention)

    rng = np.random.default_rng(16)
    q, pk, pv, tables, offs = _adversarial_pool(rng)    # S == 1 shapes
    with pytest.raises(ValueError, match="S > 1"):
        paged_chunk_attention(q, pk, pv, tables, offs)


# ------------------------------------------------------------------ 2. dispatch
def test_paged_attention_dispatch_routes_and_validates(monkeypatch):
    from fault_tolerant_llm_training_tpu.ops import (
        paged_attention as pa_mod)
    from fault_tolerant_llm_training_tpu.ops.attention import (
        paged_cached_attention)

    rng = np.random.default_rng(10)
    q, pk, pv, tables, offs = _adversarial_pool(rng)
    import jax.numpy as jnp
    ref = np.asarray(paged_cached_attention(
        jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
        jnp.asarray(tables), jnp.asarray(offs)))
    # "gather" IS paged_cached_attention, bitwise
    np.testing.assert_array_equal(_attend(q, pk, pv, tables, offs, "gather"),
                                  ref)
    # "pallas" dispatches on S: the decode kernel for S == 1, the chunk
    # kernel for S > 1 — no silent gather fallback. Prove the route (spy on
    # the kernel entry points) AND the result (fp32-close to gather; online
    # softmax reorders the reduction, so closeness, not bitwise).
    routed = []
    for name in ("paged_decode_attention", "paged_chunk_attention"):
        orig = getattr(pa_mod, name)
        monkeypatch.setattr(
            pa_mod, name,
            lambda *a, _orig=orig, _n=name, **k: (routed.append(_n),
                                                  _orig(*a, **k))[1])
    np.testing.assert_allclose(_attend(q, pk, pv, tables, offs, "pallas"),
                               ref, rtol=1e-5, atol=1e-6)
    qc, pkc, pvc, tablesc, offsc = _adversarial_chunk_pool(
        np.random.default_rng(17), s_q=3)
    np.testing.assert_allclose(
        _attend(qc, pkc, pvc, tablesc, offsc, "pallas"),
        _attend(qc, pkc, pvc, tablesc, offsc, "gather"),
        rtol=1e-5, atol=1e-6)
    assert routed == ["paged_decode_attention", "paged_chunk_attention"]
    with pytest.raises(ValueError, match="impl"):
        _attend(q, pk, pv, tables, offs, "vllm")


def test_multihead_attention_ring_impl_routes_dense():
    """configs.py admits attention_impl='ring'; a direct single-device call
    must resolve to the equivalent dense kernel, not raise (satellite: the
    dispatch previously raised on the impl its own config admitted)."""
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.ops.attention import (
        multihead_attention, xla_attention)

    cfg = _tiny_cfg(attention_impl="ring")    # admitted by __post_init__
    assert cfg.attention_impl == "ring"
    rng = np.random.default_rng(11)
    q = jnp.asarray(rng.standard_normal((2, 16, 4, 8)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((2, 16, 2, 8)), jnp.float32)
    out = multihead_attention(q, k, v, impl="ring")
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(xla_attention(q, k, v)))


def test_config_validates_paged_kernel():
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    assert _tiny_cfg(paged_kernel="pallas").paged_kernel == "pallas"
    with pytest.raises(ValueError, match="paged_kernel"):
        get_config("tiny", paged_kernel="cuda")


# -------------------------------------------------------------------- 3. engine
@pytest.fixture(scope="module")
def paged_engines():
    """One param set, two paged engines: the gather reference kernel and the
    Pallas in-place kernel, same slots/blocks/buckets."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
    gather = InferenceEngine(cfg, params, slots=2, max_len=32,
                             prefill_buckets=(8, 16), kv_block_size=8,
                             paged_kernel="gather")
    pallas = InferenceEngine(cfg, params, slots=2, max_len=32,
                             prefill_buckets=(8, 16), kv_block_size=8,
                             paged_kernel="pallas")
    return cfg, gather, pallas


def test_engine_rejects_bad_kernel_combinations():
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    with pytest.raises(ValueError, match="paged_kernel"):
        InferenceEngine(cfg, params, slots=1, max_len=16,
                        prefill_buckets=(8,), paged_kernel="cuda")
    with pytest.raises(ValueError, match="paged"):
        InferenceEngine(cfg, params, slots=1, max_len=16,
                        prefill_buckets=(8, 16), kv_layout="ring",
                        paged_kernel="pallas")


def test_fused_sampler_bitmatches_host_sampler(paged_engines):
    """Same engine, two regimes: (a) fused decode_step — sampling runs inside
    the decode program, 4 bytes/slot sync; (b) unfused decode_logits — the
    (slots, V) fp32 plane syncs to host and sample_slot_tokens picks there.
    Slot 0 greedy, slot 1 seeded top-p: streams must be bit-identical."""
    from fault_tolerant_llm_training_tpu.inference.sampler import (
        sample_slot_tokens)

    cfg, eng, _ = paged_engines
    rng = np.random.default_rng(12)
    prompts = [rng.integers(3, cfg.vocab_size, size=n).tolist()
               for n in (6, 11)]
    rows = np.array([[1, 2, 3, 4], [5, 6, 7, 8]], np.int32)
    temperature = np.array([0.0, 0.8], np.float32)
    top_p = np.array([1.0, 0.9], np.float32)
    seeds = np.array([0, 123], np.int32)
    active = np.array([True, True])

    def run(fused):
        eng.reset()
        toks = np.array([eng.prefill(s, prompts[s], block_row=rows[s],
                                     temperature=float(temperature[s]),
                                     top_p=float(top_p[s]),
                                     seed=int(seeds[s]))
                         for s in (0, 1)], np.int32)
        stream = [toks.copy()]
        for step in range(1, 7):
            steps = np.full(2, step, np.int32)
            if fused:
                toks = eng.decode_step(toks, active, temperature, top_p,
                                       seeds, steps, block_tables=rows)
            else:
                logits = eng.decode_logits(toks, active, block_tables=rows)
                toks = np.asarray(sample_slot_tokens(
                    logits, seeds, steps, temperature, top_p, eng.top_k))
            stream.append(np.asarray(toks).copy())
        return np.stack(stream)

    np.testing.assert_array_equal(run(fused=True), run(fused=False))


# ----------------------------------------------------------------- 4. scheduler
def _stream(engine, requests, eos=None, burst=1, registry=None):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    engine.reset()
    sched = Scheduler(engine, eos_token_id=eos, registry=registry,
                      decode_burst=burst)
    for r in requests:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def _requests(cfg, n=4):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    rng = np.random.default_rng(13)
    return [Request(id=f"r{i}",
                    prompt=rng.integers(3, cfg.vocab_size, size=pl).tolist(),
                    max_new_tokens=gen, temperature=t, top_p=0.9, seed=i)
            for i, (pl, gen, t) in enumerate(
                [(6, 13, 0.0), (12, 13, 0.8), (9, 13, 0.0), (11, 13, 0.7)]
                [:n])]


def test_burst_streams_bitmatch_sequential_across_kernels(paged_engines):
    """Burst n in {1, 4, 8} over both kernels: every emitted stream must be
    bit-identical to per-token decode (max_new_tokens=13 is deliberately not
    a burst multiple — _bank_burst truncates the budget overshoot), greedy
    slots must also bit-match ACROSS kernels, and the dispatch counters must
    show the 1/n amortization the fused path exists for."""
    from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry

    cfg, gather, pallas = paged_engines
    reqs = _requests(cfg)
    _, seq = _stream(gather, list(reqs), burst=1)
    reg = MetricRegistry()
    s4, b4 = _stream(gather, list(reqs), burst=4, registry=reg)
    _, b8 = _stream(gather, list(reqs), burst=8)
    assert seq == b4 == b8

    _, pseq = _stream(pallas, list(reqs), burst=1)
    _, pb4 = _stream(pallas, list(reqs), burst=4)
    assert pseq == pb4
    # greedy slots bit-match across kernels (sampled slots are only fp32-close
    # in logit space, so a top-p boundary may legitimately flip)
    for r in ("r0", "r2"):
        assert pseq[r] == seq[r]

    m = s4.metrics()
    assert m["decode_burst"] == 4
    assert m["decode_tokens"] == 4 * 12    # token 1 of 13 comes from prefill
    # 2 active slots per dispatch: amortization beats even the 1/n bar
    assert m["dispatches_per_token"] <= 1 / 4 + 0.05
    assert m["host_syncs_per_token"] <= 1 / 4 + 0.05
    rendered = reg.render()
    for name in ("decode_dispatches_total", "decode_host_syncs_total",
                 "decode_burst_tokens"):
        assert name in rendered


def test_burst_banking_truncates_at_eos(paged_engines):
    """Pick a token the greedy stream actually emits mid-sequence and rerun
    with it as EOS: burst decode overshoots it inside the device loop, and
    _bank_burst must truncate so the finished stream equals the sequential
    EOS stream exactly."""
    cfg, gather, _ = paged_engines
    reqs = _requests(cfg, n=2)
    _, free = _stream(gather, list(reqs), burst=1)
    eos = free["r0"][len(free["r0"]) // 2]    # mid-stream greedy token
    _, seq = _stream(gather, list(reqs), eos=eos, burst=1)
    _, b4 = _stream(gather, list(reqs), eos=eos, burst=4)
    assert seq == b4
    assert len(b4["r0"]) < len(free["r0"])    # EOS actually truncated it


def test_scheduler_validates_decode_burst(paged_engines):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    _, gather, _ = paged_engines
    with pytest.raises(ValueError, match="decode_burst"):
        Scheduler(gather, eos_token_id=None, decode_burst=0)
