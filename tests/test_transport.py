"""Pluggable KV transport (inference/transport.py): the zero-copy
in-memory push lane for block trains, sub-train (partial prefix)
addressability in the fleet store, and decode-gauge prefill pacing.

Evidence ladder:

1. lanes — the mem-lane disaggregated pipeline reproduces the colocated
   stream BITWISE for bf16 and int8 pools, and the fabric-resident
   device arrays are byte-identical to the fs artifact's payload files
   (the two lanes carry the same train);
2. sub-train addressability — a prompt that is a proper PREFIX of a
   longer published train is served partially: exactly the covered
   blocks land on device, the rest of the train stays on disk, and the
   stream matches the no-store reference bitwise;
3. fallback ladder — poisoned mem metadata (the ``mem_corrupt`` shape)
   degrades that train to the fs artifact with the stream intact;
   poisoning the fs payload too degrades to the committed-prefix
   replay — mem -> fs -> replay, nothing lost at any rung;
4. mixed dtype — a bf16 train is geometry-rejected by an int8 pool on
   BOTH lanes before any device write;
5. pacing — a starved decode fleet (pacing() below the prompt's block
   need) defers prefill admission without reordering the queue, a
   recovered fleet admits normally, and pacing() -> None (no decode
   peers visible) never stalls.
"""

import glob
import os

import numpy as np
import pytest


def _tiny_cfg(vocab=64, seq_len=128):
    from fault_tolerant_llm_training_tpu.models.configs import get_config

    return get_config("tiny", vocab_size=vocab, seq_len=seq_len,
                      layer_impl="loop")


@pytest.fixture(scope="module")
def xport_setup():
    """One tiny model, builders per kv-dtype, and the bf16 colocated
    reference streams the transported pipelines must reproduce."""
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    cfg = _tiny_cfg()
    model = Transformer(cfg)
    params = model.init(jax.random.PRNGKey(0),
                        jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]

    def build(kv_dtype="bf16", slots=4):
        return InferenceEngine(cfg, params, slots=slots, max_len=128,
                               prefill_buckets=(16, 32), kv_layout="paged",
                               kv_block_size=8, kv_dtype=kv_dtype)

    rng = np.random.default_rng(23)
    reqs = [
        Request(id="g", prompt=rng.integers(3, 64, size=41).tolist(),
                max_new_tokens=16, seed=1),
        Request(id="s", prompt=rng.integers(3, 64, size=37).tolist(),
                max_new_tokens=12, temperature=0.8, top_p=0.9, seed=2),
    ]

    def reference(kv_dtype="bf16"):
        sched = Scheduler(build(kv_dtype))
        for r in reqs:
            sched.submit(r)
        sched.run()
        return {c.request_id: c.tokens for c in sched.completed}

    return {"build": build, "reqs": reqs, "reference": reference,
            "ref": reference("bf16"), "Request": Request,
            "Scheduler": Scheduler}


def _mem_pipeline(setup, tmp_path, kv_dtype="bf16", poison=None,
                  corrupt_fs=None):
    """Run prefill -> decode over a shared MemFabric; returns
    (pre, dec, streams, ships). ``poison(fabric, ships)`` runs between
    the roles (the mem_corrupt window), ``corrupt_fs(ships)`` too."""
    from fault_tolerant_llm_training_tpu.inference.transport import (
        MemFabric, MemTransport)

    Request, Scheduler = setup["Request"], setup["Scheduler"]
    fabric = MemFabric()
    ships = {}

    def on_ship(req, art_dir, ordinal, seq, start, end, length):
        ships.setdefault(req.id, []).append(
            {"artifact": art_dir, "seq": seq, "start_block": start,
             "end_block": end, "length": length, "lane": "mem"})

    pre = Scheduler(setup["build"](kv_dtype), role="prefill",
                    ship_dir=str(tmp_path / f"ships_{kv_dtype}"),
                    on_ship=on_ship, transport=MemTransport(fabric))
    for r in setup["reqs"]:
        pre.submit(r)
    pre.run()
    if poison is not None:
        poison(fabric, ships)
    if corrupt_fs is not None:
        corrupt_fs(ships)
    first = {c.request_id: c.tokens for c in pre.completed}
    dec = Scheduler(setup["build"](kv_dtype), role="decode",
                    transport=MemTransport(fabric))
    for r in setup["reqs"]:
        dec.submit(Request(id=r.id, prompt=list(r.prompt),
                           max_new_tokens=r.max_new_tokens,
                           temperature=r.temperature, top_p=r.top_p,
                           seed=r.seed, committed=tuple(first[r.id])),
                   shipments=ships.get(r.id), ship_gen=0)
    dec.run()
    return pre, dec, {c.request_id: c.tokens for c in dec.completed}, ships


# ----------------------------------------------------------------- 1. lanes
@pytest.mark.parametrize("kv_dtype", ["bf16", "int8"])
def test_mem_lane_bitmatch(xport_setup, tmp_path, kv_dtype):
    """The tentpole guarantee, per storage dtype: trains pushed through
    the mem lane land the EXACT colocated stream, and the fabric holds
    byte-identical payloads to the fs artifacts it rides with."""
    ref = (xport_setup["ref"] if kv_dtype == "bf16"
           else xport_setup["reference"](kv_dtype))
    pre, dec, out, ships = _mem_pipeline(xport_setup, tmp_path, kv_dtype)
    assert out == ref, "mem-lane stream diverged from colocated"
    # every export was pushed; every import landed on the mem lane
    assert len(pre.transport.fabric) == pre.ship_exports >= 2
    assert dec.mem_lane_imports == len(xport_setup["reqs"])
    assert dec.lane_fallbacks == 0 and dec.ship_rejects == 0
    assert dec.transport.land_seconds["mem"] > 0.0
    assert dec.transport.lane_bytes["mem"] > 0
    m = dec.metrics()
    assert m["kv_transport_lane"] == "mem"
    assert m["kv_transport_mem_imports"] == len(xport_setup["reqs"])
    # lane equivalence down to the bytes: each pushed train's device
    # arrays re-serialize to the artifact's per-block payload files
    for lst in ships.values():
        for s in lst:
            train = pre.transport.fabric.get(s["artifact"])
            files = sorted(glob.glob(os.path.join(s["artifact"],
                                                  "block_*.bin")))
            assert len(files) == s["end_block"] - s["start_block"]
            for j, path in enumerate(files):
                mem_bytes = b"".join(np.asarray(a[j]).tobytes()
                                     for a in train.arrays)
                assert mem_bytes == open(path, "rb").read(), (
                    f"{os.path.basename(s['artifact'])} block {j}: mem "
                    f"payload != fs payload")
    assert dec.audit_block_leaks(strict=True) == []


# -------------------------------------------- 2. sub-train addressability
def test_partial_prefix_hit_lands_covered_blocks_only(xport_setup,
                                                      tmp_path):
    """Publish a 5-block train; a prompt covering only its first 2
    blocks must fetch partially: depth < train blocks, exactly the
    covered rows written, stream bit-exact vs the no-store run."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        block_layout, block_payload)
    from fault_tolerant_llm_training_tpu.inference.kvstore import (
        BlockStore)
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)

    Request, Scheduler = xport_setup["Request"], xport_setup["Scheduler"]
    rng = np.random.default_rng(5)
    prompt_a = rng.integers(3, 64, size=40).tolist()   # 5 full blocks
    prompt_b = prompt_a[:20]                           # 2 full blocks + 4
    store_dir = str(tmp_path / "store")

    pub = Scheduler(xport_setup["build"](),
                    kv_store=BlockStore(store_dir, writer="pub"))
    pub.submit(Request(id="a", prompt=prompt_a, max_new_tokens=4, seed=3))
    pub.run()
    assert pub.store_publishes == 1

    store = BlockStore(store_dir, writer="probe")
    hit = store.match(chain_hashes(prompt_b, 8))
    assert hit is not None and hit.partial
    assert (hit.depth, hit.blocks) == (2, 5)

    # landing surface: exactly the covered rows change, nothing else
    eng = xport_setup["build"]()
    layout_before = [np.asarray(seg["array"])
                     for seg in block_layout(eng.cache)]
    manifest = eng.import_pool_block_batch(
        [(hit.art_dir, [1, 2])], allow_partial=True)[0]
    assert len(manifest["blocks"]) == 5   # the train is longer on disk
    for row, src in ((1, 0), (2, 1)):
        want = open(os.path.join(hit.art_dir,
                                 f"block_{src:05d}.bin"), "rb").read()
        assert block_payload(eng.cache, row) == want
    for si, seg in enumerate(block_layout(eng.cache)):
        got = np.asarray(seg["array"])
        assert np.array_equal(got[3:], layout_before[si][3:]), (
            "rows beyond the covered prefix changed")

    # end to end: the partial fetch feeds the prefix cache and the
    # stream still matches the storeless reference bitwise
    ref = Scheduler(xport_setup["build"]())
    ref.submit(Request(id="b", prompt=prompt_b, max_new_tokens=8, seed=4))
    ref.run()
    want = {c.request_id: c.tokens for c in ref.completed}

    fetch = Scheduler(xport_setup["build"](),
                      kv_store=BlockStore(store_dir, writer="fetch"))
    fetch.submit(Request(id="b", prompt=prompt_b, max_new_tokens=8,
                         seed=4))
    fetch.run()
    got = {c.request_id: c.tokens for c in fetch.completed}
    assert got == want
    assert fetch.store_fetches == 1
    assert fetch.store_partial_hits == 1
    assert fetch.metrics()["kv_store_partial_hits"] == 1
    assert fetch.audit_block_leaks(strict=True) == []


# ------------------------------------------------------ 3. fallback ladder
def test_mem_poison_degrades_to_fs_lane(xport_setup, tmp_path):
    """mem_corrupt shape: poisoning one pushed train's manifest metadata
    fails the digest verify, and that request's WHOLE train degrades to
    the fs artifacts — stream bit-exact, nothing replayed."""
    def poison(fabric, ships):
        assert fabric.poison(ships["g"][0]["artifact"])

    pre, dec, out, _ = _mem_pipeline(xport_setup, tmp_path / "p1",
                                     poison=poison)
    assert out == xport_setup["ref"]
    assert dec.lane_fallbacks == 1 and dec.ship_rejects == 0
    # the untouched request still lands on the mem lane
    assert dec.mem_lane_imports == 1
    assert dec.metrics()["kv_transport_lane_fallbacks"] == 1
    assert dec.audit_block_leaks(strict=True) == []


def test_mem_and_fs_poison_degrade_to_replay(xport_setup, tmp_path):
    """Both rungs poisoned: mem digest mismatch AND a flipped fs payload
    byte. The ladder bottoms out at the committed-prefix replay and the
    stream is still bit-exact — the full mem -> fs -> replay contract."""
    def poison(fabric, ships):
        assert fabric.poison(ships["g"][0]["artifact"])

    def corrupt_fs(ships):
        p = sorted(glob.glob(os.path.join(
            ships["g"][0]["artifact"], "block_*.bin")))[0]
        raw = bytearray(open(p, "rb").read())
        raw[7] ^= 0xFF
        open(p, "wb").write(bytes(raw))

    pre, dec, out, _ = _mem_pipeline(xport_setup, tmp_path / "p2",
                                     poison=poison, corrupt_fs=corrupt_fs)
    assert out == xport_setup["ref"], "replay rung lost the stream"
    assert dec.lane_fallbacks >= 1
    assert dec.ship_rejects == 1
    assert dec.audit_block_leaks(strict=True) == []


# -------------------------------------------------------- 4. mixed dtype
def test_mixed_dtype_rejected_on_both_lanes(xport_setup, tmp_path):
    """A bf16 train cannot land in an int8 pool: geometry-rejected on
    the mem lane AND the fs lane, before any device write."""
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        KVBlockIntegrityError)
    from fault_tolerant_llm_training_tpu.inference.transport import (
        MemTransport)

    xport = MemTransport()
    src = xport_setup["build"]("bf16", slots=2)
    art = str(tmp_path / "mixed_train")
    xport.export(src.cache, [1, 2], art, length=16,
                 meta={"kind": "ship", "request_id": "x"})
    dst = xport_setup["build"]("int8", slots=2)
    before = [np.asarray(a.q if hasattr(a, "q") else a)
              for a in (*dst.cache.k, *dst.cache.v)]
    for lane in ("mem", "fs"):
        with pytest.raises(KVBlockIntegrityError, match="geometry"):
            xport.import_batch(dst, [(art, [1, 2])], lane=lane)
    after = [np.asarray(a.q if hasattr(a, "q") else a)
             for a in (*dst.cache.k, *dst.cache.v)]
    for b, a in zip(before, after):
        assert np.array_equal(b, a), "rejected import touched the pool"


# ------------------------------------------------------------- 5. pacing
def test_pacing_defers_prefill_under_starved_decode_pool(xport_setup,
                                                         tmp_path):
    """ROADMAP item 2's control plane: pacing() below the head prompt's
    block need defers admission (queue intact, FIFO preserved); restored
    capacity admits; pacing() -> None never stalls."""
    Scheduler = xport_setup["Scheduler"]
    state = {"free": 0}
    pre = Scheduler(xport_setup["build"](), role="prefill",
                    ship_dir=str(tmp_path / "paced_ships"),
                    pacing=lambda: state["free"])
    for r in xport_setup["reqs"]:
        pre.submit(r)
    for _ in range(4):
        pre.step()
    assert not pre.active and not pre.completed
    assert len(pre.queue) == len(xport_setup["reqs"])  # nothing dropped
    assert pre.prefill_paced >= 4  # every deferred round counted
    assert pre.metrics()["prefill_paced"] == pre.prefill_paced

    state["free"] = 10_000  # the decode fleet drained its backlog
    pre.run()
    assert {c.request_id for c in pre.completed} == {"g", "s"}
    assert all(c.reason == "prefill" for c in pre.completed)
    assert pre.ship_exports >= 2
    assert pre.audit_block_leaks(strict=True) == []

    # no decode peers visible yet (pacing None): admission proceeds —
    # a lone prefill host must not deadlock before the fleet assembles
    lone = Scheduler(xport_setup["build"](), role="prefill",
                     ship_dir=str(tmp_path / "lone_ships"),
                     pacing=lambda: None)
    lone.submit(xport_setup["reqs"][0])
    lone.run()
    assert lone.prefill_paced == 0
    assert [c.request_id for c in lone.completed] == ["g"]
