"""Chaos subsystem tests (chaos/, checkpoint integrity, fallback restore).

Layers, cheapest first:

- schedule grammar: good specs parse (inline + JSON file), bad specs raise;
- injector: each entry fires exactly once at its step under a fixed seed,
  through the real delivery paths (a real SIGUSR1 via os.kill, the
  reference-shaped simulated exception, the prefetch-worker stall);
- integrity manifests: write/verify on synthetic step dirs, every corruption
  mode detected (flip, truncate, delete);
- manager-level recovery: save two steps, corrupt the newest, restore falls
  back — audited — to the older one bit-exact, metrics counted;
- one slow end-to-end subprocess scenario (ckpt_corrupt through train.py's
  real exit handler and resume path), chaos+slow marked so tier-1 skips it
  — scripts/chaos_campaign.py runs the full matrix.
"""

import json
import os
import signal
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.chaos import (
    SERVE_FAULTS,
    ChaosInjector,
    parse_schedule,
)
from fault_tolerant_llm_training_tpu.chaos.schedule import parse_duration
from fault_tolerant_llm_training_tpu.obs import events as events_mod

pytestmark = pytest.mark.chaos

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_recorder():
    events_mod._RECORDER = events_mod.FlightRecorder()
    yield
    events_mod._RECORDER = events_mod.FlightRecorder()


# ------------------------------------------------------------------ grammar
def test_parse_inline_schedule_sorted_with_defaults():
    entries = parse_schedule(
        "step=140:loader_stall=5s;step=50:sigusr1;"
        "step=80:exception@rank=1;step=120:ckpt_corrupt")
    assert [(e.step, e.fault, e.arg, e.rank) for e in entries] == [
        (50, "sigusr1", None, -1),
        (80, "exception", None, 1),
        (120, "ckpt_corrupt", None, -1),
        (140, "loader_stall", 5.0, -1),
    ]
    assert not any(e.fired for e in entries)


def test_parse_duration_forms():
    assert parse_duration("5s") == 5.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration("1.5") == 1.5
    # defaulted duration when the arg is omitted
    (e,) = parse_schedule("step=3:kv_delay")
    assert e.arg == 1.0
    (e,) = parse_schedule("step=3:loader_stall")
    assert e.arg == 2.0


def test_parse_json_file(tmp_path):
    path = tmp_path / "sched.json"
    path.write_text(json.dumps([
        {"step": 12, "fault": "ckpt_corrupt"},
        {"step": 15, "fault": "loader_stall", "arg": "500ms", "rank": 0},
    ]))
    for spec in (str(path), "@" + str(path)):
        entries = parse_schedule(spec)
        assert [(e.step, e.fault, e.arg, e.rank) for e in entries] == [
            (12, "ckpt_corrupt", None, -1),
            (15, "loader_stall", 0.5, 0),
        ]


@pytest.mark.parametrize("spec", [
    "step=5:warp_core_breach",       # unknown fault
    "step=-2:sigusr1",               # negative step
    "sigusr1@step=5",                # bad entry syntax
    "step=5:sigusr1=3s",             # arg on a no-arg fault
    "step=5:loader_stall=fast",      # unparseable duration
    ";;",                            # empty after splitting
])
def test_parse_bad_specs_raise(spec):
    with pytest.raises(ValueError):
        parse_schedule(spec)


def test_parse_allowed_restricts_fault_set():
    with pytest.raises(ValueError, match="not supported in this context"):
        parse_schedule("step=5:exception", allowed=SERVE_FAULTS)
    assert parse_schedule("step=5:sigterm", allowed=SERVE_FAULTS)


def test_bad_json_schedules_raise(tmp_path):
    not_list = tmp_path / "a.json"
    not_list.write_text('{"steps": 3}')
    with pytest.raises(ValueError, match="list of entries"):
        parse_schedule("@" + str(not_list))
    bad_entry = tmp_path / "b.json"
    bad_entry.write_text('[{"step": 3}]')
    with pytest.raises(ValueError, match="needs 'step' and 'fault'"):
        parse_schedule("@" + str(bad_entry))


def test_parse_time_and_prob_triggers():
    entries = parse_schedule("t=30s:sigterm;p=0.1:kv_delay=250ms;"
                             "step=5:sigusr1")
    by_fault = {e.fault: e for e in entries}
    assert (by_fault["sigterm"].trigger, by_fault["sigterm"].when) == \
        ("time", 30.0)
    assert (by_fault["kv_delay"].trigger, by_fault["kv_delay"].when) == \
        ("prob", 0.1)
    assert by_fault["kv_delay"].arg == 0.25
    assert (by_fault["sigusr1"].trigger, by_fault["sigusr1"].step) == \
        ("step", 5)


@pytest.mark.parametrize("spec", [
    "p=0:sigusr1",          # probability must be > 0
    "p=1.5:sigusr1",        # probability must be <= 1
    "p=maybe:sigusr1",      # unparseable probability
    "t=fast:sigusr1",       # unparseable duration
    "when=5:sigusr1",       # unknown trigger key
])
def test_parse_bad_trigger_specs_raise(spec):
    with pytest.raises(ValueError):
        parse_schedule(spec)


def test_parse_json_time_and_prob_triggers(tmp_path):
    path = tmp_path / "sched.json"
    path.write_text(json.dumps([
        {"t": "2s", "fault": "sigterm"},
        {"p": 0.5, "fault": "kv_delay"},
    ]))
    entries = parse_schedule(str(path))
    assert [(e.trigger, e.when) for e in entries] == [("prob", 0.5),
                                                      ("time", 2.0)]
    # exactly one trigger key per entry
    path.write_text(json.dumps([{"step": 3, "p": 0.5, "fault": "sigusr1"}]))
    with pytest.raises(ValueError, match="exactly one"):
        parse_schedule(str(path))


def test_time_trigger_fires_once_after_elapsed():
    inj = ChaosInjector(parse_schedule("t=50ms:loader_stall=0s"), seed=0)
    inj.on_batch(0)
    assert not inj.entries[0].fired, "must not fire before the elapse"
    time.sleep(0.06)
    inj.on_batch(1)
    assert inj.entries[0].fired
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("chaos_loader_stall") == 1
    inj.on_batch(2)  # latched
    assert kinds.count("chaos_loader_stall") == 1


def test_prob_trigger_fires_and_latches():
    inj = ChaosInjector(parse_schedule("p=1:loader_stall=0s"), seed=0)
    inj.on_batch(0)  # p=1.0: first visit fires
    assert inj.entries[0].fired
    inj.on_batch(1)
    assert [e["kind"] for e in events_mod._RECORDER.ring].count(
        "chaos_loader_stall") == 1


def test_from_config_legacy_raise_error_alias():
    class Cfg:
        chaos = ""
        raise_error = True
        error_step = 7
        error_local_rank = -1
        seed = 0

    inj = ChaosInjector.from_config(Cfg())
    assert [(e.step, e.fault, e.rank) for e in inj.entries] == [
        (7, "exception", -1)]
    assert ChaosInjector.from_config(
        type("C", (), {"chaos": "", "raise_error": False})()) is None


# ----------------------------------------------------------------- injector
class _FakeTrainer:
    def __init__(self):
        self.error_is_replicated = False
        self.drained = 0

    def _drain_inflight(self, *a, **k):
        self.drained += 1


def _injected_count(fault: str) -> float:
    from fault_tolerant_llm_training_tpu.chaos.injector import _M_INJECTED

    return _M_INJECTED.labels(**{"class": fault}).value


def test_exception_fires_exactly_once_with_reference_shape():
    inj = ChaosInjector(parse_schedule("step=3:exception"), seed=0)
    tr = _FakeTrainer()
    before = _injected_count("exception")
    for step in (0, 1, 2):
        inj.on_train_step(tr, step)  # pre-step: nothing fires
    with pytest.raises(Exception) as ei:
        inj.on_train_step(tr, 3)
    # the reference's exact error shape: handler classifies via args[1]
    assert ei.value.args == ("Simulated exception to test signal handler", -1)
    assert tr.error_is_replicated and tr.drained == 1
    assert inj.entries[0].fired
    # latched: revisiting the step (or any later one) never re-fires
    for step in (3, 4, 5):
        inj.on_train_step(tr, step)
    assert _injected_count("exception") == before + 1
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("chaos_exception") == 1


def test_sigusr1_delivered_through_real_signal_path():
    from fault_tolerant_llm_training_tpu.ft.signals import (
        SignalFlag,
        TrainingSignal,
    )

    old_usr1 = signal.getsignal(signal.SIGUSR1)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        flag = SignalFlag()
        flag.register()
        inj = ChaosInjector(parse_schedule("step=2:sigusr1"), seed=0)
        inj.on_train_step(None, 1)
        assert flag.signum is None
        inj.on_train_step(None, 2)  # os.kill -> handler -> flag
        assert flag.signum == signal.SIGUSR1
        with pytest.raises(TrainingSignal) as ei:
            flag.check()
        assert ei.value.signum == signal.SIGUSR1
        inj.on_train_step(None, 2)  # latched
        assert flag.signum is None
    finally:
        signal.signal(signal.SIGUSR1, old_usr1)
        signal.signal(signal.SIGTERM, old_term)


def test_kv_delay_sleeps_and_kv_fail_raises_peer_error():
    from fault_tolerant_llm_training_tpu.ft.multihost import PeerHostError

    inj = ChaosInjector(
        parse_schedule("step=1:kv_delay=200ms;step=2:kv_fail"), seed=0)
    tr = _FakeTrainer()
    t0 = time.monotonic()
    inj.on_sync_boundary(tr, 1)
    assert time.monotonic() - t0 >= 0.2
    inj.on_sync_boundary(tr, 1)  # latched: no second sleep
    with pytest.raises(PeerHostError):
        inj.on_sync_boundary(tr, 2)
    assert tr.error_is_replicated


class _CountingLoader:
    """Minimal DataLoader protocol for DevicePrefetcher: batches are
    (index, index) pairs; state is the next batch index."""

    def __init__(self, n):
        self.n = n
        self.i = 0

    def __next__(self):
        if self.i >= self.n:
            raise StopIteration
        arr = np.full((1,), self.i, dtype=np.int32)
        self.i += 1
        return arr, arr

    def get_state(self):
        return {"next_index": self.i}

    def resume(self):
        pass


def test_loader_stall_delays_one_batch_without_reordering_or_replay():
    from fault_tolerant_llm_training_tpu.data.prefetch import DevicePrefetcher

    inj = ChaosInjector(parse_schedule("step=2:loader_stall=300ms"), seed=0)
    pf = DevicePrefetcher(_CountingLoader(5), depth=1,
                          chaos_on_batch=inj.on_batch, start_batch=0)
    t0 = time.monotonic()
    got = [(int(np.asarray(i)[0]), st["next_index"]) for i, _l, st in pf]
    # every batch delivered exactly once, in order, with its own state
    assert got == [(i, i + 1) for i in range(5)]
    assert time.monotonic() - t0 >= 0.3
    assert inj.entries[0].fired
    assert [e["kind"] for e in events_mod._RECORDER.ring].count(
        "chaos_loader_stall") == 1


def test_loader_stall_respects_resume_start_batch():
    """Schedule steps are GLOBAL: a resumed prefetcher starting at step 10
    must not re-fire an entry scheduled for (already passed) step 2, and
    must fire one scheduled inside its window."""
    from fault_tolerant_llm_training_tpu.data.prefetch import DevicePrefetcher

    inj = ChaosInjector(
        parse_schedule("step=2:loader_stall=10s;step=11:loader_stall=100ms"),
        seed=0)
    pf = DevicePrefetcher(_CountingLoader(4), depth=1,
                          chaos_on_batch=inj.on_batch, start_batch=10)
    t0 = time.monotonic()
    assert len(list(pf)) == 4
    elapsed = time.monotonic() - t0
    assert elapsed < 5.0, "the pre-resume stall entry must not re-fire"
    assert not inj.entries[0].fired  # step 2 is in the past, stays pending
    assert inj.entries[1].fired


def test_publish_corrupt_flips_byte_but_spares_manifest(tmp_path):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        MANIFEST_NAME,
        verify_step_dir,
        write_manifest,
    )
    from fault_tolerant_llm_training_tpu.utils.logging import logger

    d = _make_step_dir(tmp_path, step=20)
    write_manifest(str(d), 20)
    manifest_before = (d / MANIFEST_NAME).read_bytes()

    inj = ChaosInjector(parse_schedule("step=20:publish_corrupt"), seed=0)
    assert inj.on_publish(str(d), 19, logger) is None  # not its step
    corrupted = inj.on_publish(str(d), 20, logger)
    assert corrupted is not None and str(d) in corrupted
    # the manifest is spared — the corruption is what it must CATCH
    assert (d / MANIFEST_NAME).read_bytes() == manifest_before
    ok, detail = verify_step_dir(str(d))
    assert not ok and "crc mismatch" in detail
    assert inj.on_publish(str(d), 20, logger) is None  # latched
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("chaos_publish_corrupt") == 2  # audit + detail event


def test_reload_signal_fires_at_reload_ordinal():
    from fault_tolerant_llm_training_tpu.ft.signals import SignalFlag

    old_usr1 = signal.getsignal(signal.SIGUSR1)
    old_term = signal.getsignal(signal.SIGTERM)
    try:
        flag = SignalFlag()
        flag.register()
        inj = ChaosInjector(parse_schedule("step=2:reload_signal"), seed=0)
        inj.on_reload(1)
        assert flag.signum is None
        inj.on_reload(2)  # second swap: real SIGUSR1 mid-swap
        assert flag.signum == signal.SIGUSR1
        flag.signum = None
        inj.on_reload(2)  # latched
        assert flag.signum is None
    finally:
        signal.signal(signal.SIGUSR1, old_usr1)
        signal.signal(signal.SIGTERM, old_term)


def test_serve_faults_allow_reload_signal():
    assert parse_schedule("step=1:reload_signal", allowed=SERVE_FAULTS)
    with pytest.raises(ValueError, match="not supported in this context"):
        parse_schedule("step=1:publish_corrupt", allowed=SERVE_FAULTS)


# ------------------------------------------------------- integrity manifests
def _make_step_dir(tmp_path, step=10):
    d = tmp_path / "checkpoint_x" / str(step)
    (d / "state").mkdir(parents=True)
    (d / "state" / "arr0.bin").write_bytes(os.urandom(4096))
    (d / "state" / "arr1.bin").write_bytes(os.urandom(1024))
    (d / "data.json").write_text('{"next_index": 5}')
    return d


def test_manifest_roundtrip_and_corruption_modes(tmp_path):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        MANIFEST_NAME,
        verify_step_dir,
        write_manifest,
    )

    d = _make_step_dir(tmp_path)
    # pre-manifest: legacy checkpoints verify ok
    ok, detail = verify_step_dir(str(d))
    assert ok and "legacy" in detail
    write_manifest(str(d), 10)
    manifest = json.loads((d / MANIFEST_NAME).read_text())
    assert set(manifest["files"]) == {os.path.join("state", "arr0.bin"),
                                      os.path.join("state", "arr1.bin"),
                                      "data.json"}
    assert verify_step_dir(str(d)) == (True, "ok")

    # bit flip mid-file
    target = d / "state" / "arr0.bin"
    raw = bytearray(target.read_bytes())
    raw[2048] ^= 0xFF
    target.write_bytes(bytes(raw))
    ok, detail = verify_step_dir(str(d))
    assert not ok and "crc mismatch" in detail
    raw[2048] ^= 0xFF
    target.write_bytes(bytes(raw))
    assert verify_step_dir(str(d)) == (True, "ok")

    # truncation
    target.write_bytes(bytes(raw[:100]))
    ok, detail = verify_step_dir(str(d))
    assert not ok and "size mismatch" in detail
    target.write_bytes(bytes(raw))

    # deletion
    os.remove(d / "data.json")
    ok, detail = verify_step_dir(str(d))
    assert not ok and "missing file" in detail

    # unreadable manifest
    (d / MANIFEST_NAME).write_text("{not json")
    ok, detail = verify_step_dir(str(d))
    assert not ok and "unreadable manifest" in detail


# ------------------------------------------------- manager-level recovery
def _tiny_state(value: float):
    import jax.numpy as jnp

    return {"w": jnp.full((64,), value, jnp.float32),
            "b": jnp.arange(8, dtype=jnp.float32) * value}


def test_corrupt_newest_checkpoint_falls_back_bit_exact(tmp_path):
    """The recovery chain end-to-end at the manager layer: two verified
    saves, seeded corruption of the newest (via the injector's real
    post_fault_save path), restore lands on the OLDER step bit-exact, with
    the verify-failure audit + counter and the fallback audit."""
    from fault_tolerant_llm_training_tpu.checkpoint import manager as mgr_mod
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )
    from fault_tolerant_llm_training_tpu.utils.logging import logger

    mngr = CheckpointManager(str(tmp_path), "cc1", enable_async=False)
    state10 = _tiny_state(1.5)
    mngr.save(10, state10, {"next_index": 20}, wait=True)
    mngr.save(13, _tiny_state(2.5), {"next_index": 26}, wait=True)
    assert sorted(mngr._mngr.all_steps()) == [10, 13]

    # arm + trip a ckpt_corrupt exactly as the trainer would
    inj = ChaosInjector(parse_schedule("step=12:ckpt_corrupt"), seed=0)
    with pytest.raises(Exception):
        inj.on_train_step(_FakeTrainer(), 12)
    corrupted = inj.post_fault_save(mngr.directory, 13, logger)
    assert corrupted is not None and f"{os.sep}13{os.sep}" in corrupted

    before = mgr_mod._M_VERIFY_FAILURES.value
    restored, data, step = mngr.restore(_tiny_state(0.0))
    assert step == 10
    assert data["next_index"] == 20
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.asarray(state10["w"]))
    np.testing.assert_array_equal(np.asarray(restored["b"]),
                                  np.asarray(state10["b"]))
    assert mgr_mod._M_VERIFY_FAILURES.value == before + 1
    kinds = [e["kind"] for e in events_mod._RECORDER.ring]
    assert kinds.count("ckpt_verify_failed") == 1
    assert kinds.count("ckpt_fallback") == 1
    mngr.close()


def test_all_steps_corrupt_raises_integrity_error(tmp_path):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointIntegrityError,
        CheckpointManager,
    )

    mngr = CheckpointManager(str(tmp_path), "cc2", enable_async=False)
    mngr.save(5, _tiny_state(1.0), {"next_index": 10}, wait=True)
    step_dir = Path(mngr.directory) / "5"
    for f in (step_dir / "state").rglob("*"):
        if f.is_file():
            f.write_bytes(os.urandom(max(1, f.stat().st_size)))
            break
    with pytest.raises(CheckpointIntegrityError):
        mngr.restore(_tiny_state(0.0))
    mngr.close()


def test_async_save_under_buffer_donation_is_not_torn(tmp_path):
    """Regression: the train step donates its state buffers, so an async
    (wait=False) save whose device-to-host copy drains in the background
    could read buffers XLA had already reused for LATER steps — a torn
    checkpoint whose dir name, data position, and array contents disagree
    (found by scripts/chaos_campaign.py: dir 10 restoring as step 12).
    manager.save must snapshot before returning; the restored values must
    be the ones current at the save call, no matter how many donated
    updates ran while the write drained."""
    import functools

    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def update(state):
        return {"step": state["step"] + 1,
                "w": state["w"] * 1.000001 + 0.001}

    state = {"step": jnp.zeros((), jnp.int32),
             "w": jnp.full((128, 128), 0.1, jnp.float32)}
    for _ in range(10):
        state = update(state)
    expected_w = np.asarray(state["w"])

    mngr = CheckpointManager(str(tmp_path), "tear", enable_async=True)
    mngr.save(10, state, {"next_index": 20}, wait=False)
    for _ in range(25):  # donated buffers reused while the write drains
        state = update(state)
    mngr.wait_until_finished()

    template = {"step": jnp.zeros((), jnp.int32),
                "w": jnp.zeros((128, 128), jnp.float32)}
    restored, data, step = mngr.restore(template)
    assert step == 10
    assert int(restored["step"]) == 10, (
        "async save captured post-donation buffers (torn checkpoint)")
    np.testing.assert_array_equal(np.asarray(restored["w"]), expected_w)
    assert data["next_index"] == 20
    mngr.close()


def test_finalize_sweep_audits_partial_dirs_once(tmp_path):
    from fault_tolerant_llm_training_tpu.checkpoint.manager import (
        CheckpointManager,
    )

    mngr = CheckpointManager(str(tmp_path), "cc3", enable_async=False)
    leftover = Path(mngr.directory) / "7.orbax-checkpoint-tmp-123"
    leftover.mkdir(parents=True)
    mngr.save(5, _tiny_state(1.0), {"next_index": 10}, wait=True)
    mngr.wait_until_finished()  # second sweep: audit must not repeat
    audits = [e for e in events_mod._RECORDER.ring
              if e["kind"] == "ckpt_partial_skipped"]
    assert len(audits) == 1
    assert audits[0]["name"] == "7.orbax-checkpoint-tmp-123"
    # the partial dir is never eligible for restore and never manifested
    assert not (leftover / "integrity.json").exists()
    mngr.close()


# --------------------------------------------------------------- end-to-end
@pytest.mark.slow
def test_e2e_ckpt_corrupt_fault_then_verified_fallback_resume(tmp_path):
    """Full chain through train.py: the ckpt_corrupt fault dies like a code
    error, the exit handler saves + the injector corrupts that save; the
    chained job's restore detects the corruption, falls back to the last
    periodic checkpoint, and resumes from it. (Resumed jobs may die in this
    container's known post-resume native crash — the verification evidence
    lands before that point, so assertions are on the audit trail.)"""
    from test_fault_tolerance import _args, _run

    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(0)
    words = ["alpha", "bravo", "charlie", "delta", "echo"]
    docs = [" ".join(rng.choice(words, size=int(rng.integers(20, 120))))
            for _ in range(128)]
    pq_path = tmp_path / "train_data.parquet"
    pq.write_table(pa.table({"text": docs}), pq_path)

    argv = _args(tmp_path, str(pq_path),
                 **{"--chaos": "step=12:ckpt_corrupt",
                    "--checkpoint-frequency": "5"})
    rc, out = _run(argv, job_id="cc1")
    assert rc == 0, out
    assert "[CHAOS] Injected ckpt_corrupt at step 12" in out
    assert "Checkpoint saved at step 13" in out
    assert "[CHAOS] Corrupted checkpoint step 13" in out

    rc2, out2 = _run(_args(tmp_path, str(pq_path),
                           **{"--checkpoint-id": "cc1",
                              "--checkpoint-frequency": "5"}),
                     job_id="cc2")
    assert ("[CKPT VERIFY] Checkpoint step 13 failed integrity check"
            in out2), out2
    assert ("[CKPT VERIFY] Falling back to checkpoint step 10"
            in out2), out2
    assert "Resuming training from training_step 10" in out2, out2
