"""Observability layer tests: metric registry rendering, flight recorder,
goodput stitching across a synthetic 3-restart chain, the /metrics endpoint,
heartbeats, trace windows, and the resume-aware throughput meter — plus one
end-to-end run of train.py with a live /metrics scrape."""

import json
import logging
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

from fault_tolerant_llm_training_tpu.obs import events as events_mod
from fault_tolerant_llm_training_tpu.obs.events import (
    FlightRecorder,
    read_events,
)
from fault_tolerant_llm_training_tpu.obs.goodput import (
    failure_class,
    format_report,
    load_chain,
    stitch,
)
from fault_tolerant_llm_training_tpu.obs.prometheus import (
    HeartbeatThread,
    MetricsServer,
)
from fault_tolerant_llm_training_tpu.obs.registry import MetricRegistry
from fault_tolerant_llm_training_tpu.obs.trace import parse_window
from fault_tolerant_llm_training_tpu.utils import metrics as metrics_mod
from fault_tolerant_llm_training_tpu.utils.metrics import Throughput

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _fresh_recorder():
    """The module recorder deliberately carries its ring across configure()
    (pre-configuration events must survive into the file); tests need a
    clean slate instead."""
    events_mod._RECORDER = events_mod.FlightRecorder()
    yield
    events_mod._RECORDER = events_mod.FlightRecorder()


# ------------------------------------------------------------------ registry

def test_registry_counter_gauge_histogram_render():
    r = MetricRegistry()
    c = r.counter("ftl_test_total", "a counter")
    c.inc()
    c.inc(2)
    g = r.gauge("ftl_test_gauge", "a gauge")
    g.set(1.5)
    h = r.histogram("ftl_test_seconds", "a histogram", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    text = r.render()
    assert "# HELP ftl_test_total a counter" in text
    assert "# TYPE ftl_test_total counter" in text
    assert "ftl_test_total 3" in text
    assert "ftl_test_gauge 1.5" in text
    # cumulative buckets + +Inf == count
    assert 'ftl_test_seconds_bucket{le="0.1"} 1' in text
    assert 'ftl_test_seconds_bucket{le="1"} 2' in text
    assert 'ftl_test_seconds_bucket{le="+Inf"} 3' in text
    assert "ftl_test_seconds_count 3" in text
    assert text.endswith("\n")


def test_registry_labels_and_kind_conflict():
    r = MetricRegistry()
    fam = r.counter("ftl_req_total", "requests")
    fam.labels(reason="eos").inc()
    fam.labels(reason="length").inc(4)
    text = r.render()
    assert 'ftl_req_total{reason="eos"} 1' in text
    assert 'ftl_req_total{reason="length"} 4' in text
    # same family object on re-registration; conflicting kind rejected
    assert r.counter("ftl_req_total") is fam
    with pytest.raises(ValueError):
        r.gauge("ftl_req_total")
    with pytest.raises(ValueError):
        fam.inc(-1)


def test_histogram_quantile_bucket_resolution():
    r = MetricRegistry()
    h = r.histogram("ftl_q_seconds", buckets=(0.1, 1.0, 10.0))
    for _ in range(9):
        h.observe(0.05)
    h.observe(5.0)
    child = h.labels()
    assert child.quantile(0.5) == 0.1
    assert child.quantile(0.99) == 10.0


# ----------------------------------------------------------- flight recorder

def test_flight_recorder_ring_file_and_torn_tail(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    rec = FlightRecorder(path, capacity=4, job="j9", host=1,
                         clock=lambda: 123.0)
    for i in range(6):
        rec.emit("step", step=i, steps=1)
    rec.flush()
    # ring keeps only the last `capacity`
    assert [e["step"] for e in rec.ring] == [2, 3, 4, 5]
    # the file keeps everything, with job/host/clock stamped
    evs = read_events(path)
    assert [e["step"] for e in evs] == list(range(6))
    assert evs[0]["job"] == "j9" and evs[0]["host"] == 1
    assert evs[0]["t"] == 123.0
    rec.close()
    # a torn tail line (crash mid-write) must not poison the reader
    with open(path, "a") as fh:
        fh.write('{"t": 124.0, "kind": "ste')
    assert len(read_events(path)) == 6


def test_configure_carries_preconfig_events_into_file(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    events_mod.configure(None)  # reset to memory-only
    events_mod.emit(kind="signal", signum=10)  # before the file exists
    rec = events_mod.configure(path, job="jj")
    events_mod.emit(kind="exit", error_type=10)
    events_mod.flush()
    kinds = [e["kind"] for e in read_events(path)]
    assert kinds == ["signal", "exit"]
    rec.close()
    events_mod.configure(None)


def test_emit_audit_logs_text_and_emits_exactly_one_event(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    events_mod.configure(path, job="audit")
    log = logging.getLogger("ftl-test-audit")
    records = []

    class _Capture(logging.Handler):
        def emit(self, record):
            records.append(record.getMessage())

    log.addHandler(_Capture())
    log.setLevel(logging.INFO)
    text = "[EXIT HANDLER] Checkpoint saved at step 427"
    events_mod.emit_audit(log, text, "exit", step=427, cls="timeout")
    events_mod.flush()
    assert records == [text]  # byte-identical, logged exactly once
    evs = read_events(path)
    assert len(evs) == 1
    assert evs[0]["kind"] == "exit" and evs[0]["step"] == 427
    assert evs[0]["audit"] is True and evs[0]["cls"] == "timeout"
    events_mod.configure(None)


# ------------------------------------------------------------------- goodput

def _chain_events():
    """Synthetic 3-restart chain: timeout (clean save, no replay) →
    injected error (clean save) → scancel (NO save: 5 steps replayed).

    Tokens/step = 100; step windows of 5 steps over 10 s each.
    """
    ev = []

    def step(job, t, last, dur=10.0, steps=5, tokens=500):
        ev.append({"t": t, "kind": "step", "job": job, "host": 0,
                   "step": last, "dur": dur, "steps": steps,
                   "tokens": tokens})

    # job a: steps 1..10, USR1 timeout at t=25, saved @10
    ev.append({"t": 0.0, "kind": "start", "job": "a", "host": 0, "step": 0,
               "tokens_per_step": 100})
    step("a", 10.0, 5)
    step("a", 20.0, 10)
    ev.append({"t": 25.0, "kind": "signal", "job": "a", "host": 0,
               "signum": 10, "cls": "timeout"})
    ev.append({"t": 27.0, "kind": "exit", "job": "a", "host": 0,
               "error_type": 10, "cls": "timeout", "saved": True,
               "saved_step": 10})
    # job b: restores @10, steps 11..20, injected error at t=90, saved @20
    ev.append({"t": 57.0, "kind": "ckpt_restore", "job": "b", "host": 0,
               "step": 10, "dur": 2.0})
    step("b", 70.0, 15)
    step("b", 80.0, 20)
    ev.append({"t": 90.0, "kind": "signal", "job": "b", "host": 0,
               "signum": -1, "cls": "error"})
    ev.append({"t": 92.0, "kind": "exit", "job": "b", "host": 0,
               "error_type": -1, "cls": "error", "saved": True,
               "saved_step": 20})
    # job c: restores @15 (periodic save gap!), replays 16..20, reaches 30,
    # then scancel with NO save
    ev.append({"t": 112.0, "kind": "ckpt_restore", "job": "c", "host": 0,
               "step": 15, "dur": 2.0})
    step("c", 130.0, 20)   # steps 16..20: all replay
    step("c", 140.0, 25)
    step("c", 150.0, 30)
    ev.append({"t": 152.0, "kind": "exit", "job": "c", "host": 0,
               "error_type": 15, "cls": "cancel", "saved": False})
    return ev


def test_goodput_three_restart_chain(tmp_path):
    report = stitch(_chain_events())
    assert report.jobs == ["a", "b", "c"]
    assert report.steps_reached == 30
    # productive windows: a(2) + b(2) + c's last two = 60 s; replay = 10 s
    assert report.productive_seconds == pytest.approx(60.0)
    assert report.replay_seconds == pytest.approx(10.0)
    assert report.wall_seconds == pytest.approx(152.0)
    assert report.goodput_pct == pytest.approx(100 * 60 / 152.0)
    # MTTR: a→b fault 25 → first b window 70 = 45; b→c 90 → 130 = 40
    assert len(report.restarts) == 2
    assert report.restarts[0].failure == "timeout"
    assert report.restarts[0].mttr_seconds == pytest.approx(45.0)
    assert report.restarts[1].failure == "error"
    assert report.restarts[1].mttr_seconds == pytest.approx(40.0)
    assert report.mttr_seconds == pytest.approx(42.5)
    # replay: only the b→c restart re-trained tokens (steps 16..20)
    assert report.restarts[0].replayed_tokens == 0
    assert report.restarts[1].replayed_steps == 5
    assert report.restarts[1].replayed_tokens == 500
    assert report.tokens_replayed == 500
    assert report.tokens_trained == 3000  # 30 net-new steps x 100
    lost = report.lost_by_class
    assert set(lost) == {"timeout", "error"}
    assert lost["timeout"] == pytest.approx(45.0)
    assert lost["error"] == pytest.approx(50.0)  # 40 restart + 10 replay
    # the human report renders every headline number
    text = format_report(report)
    assert "goodput" in text and "MTTR" in text
    assert "timeout" in text and "error" in text


def test_goodput_cli_prints_headline_numbers(tmp_path):
    by_job = {}
    for ev in _chain_events():
        by_job.setdefault(ev["job"], []).append(ev)
    for job, evs in by_job.items():
        with open(tmp_path / f"events_{job}.jsonl", "w") as fh:
            for ev in evs:
                fh.write(json.dumps(ev) + "\n")
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "goodput_report.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    assert "goodput" in out.stdout
    assert "39.5 %" in out.stdout            # 100 * 60 / 152
    assert "MTTR 42.5 s" in out.stdout
    assert "timeout" in out.stdout and "error" in out.stdout
    # --json emits the same accounting machine-readably
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "goodput_report.py"),
         "--json", str(tmp_path)],
        capture_output=True, text=True, timeout=60)
    data = json.loads(out.stdout)
    assert data["tokens_replayed"] == 500
    assert data["restarts"][1]["failure"] == "error"


def test_goodput_stitch_single_job_no_restarts():
    evs = [{"t": 0.0, "kind": "start", "job": "x", "host": 0},
           {"t": 10.0, "kind": "step", "job": "x", "host": 0, "step": 5,
            "dur": 10.0, "steps": 5, "tokens": 500},
           {"t": 10.5, "kind": "complete", "job": "x", "host": 0}]
    r = stitch(evs)
    assert not r.restarts and r.mttr_seconds == 0.0
    assert r.goodput_pct == pytest.approx(100 * 10.0 / 10.5)


def test_failure_class_mapping():
    assert failure_class(10) == "timeout"
    assert failure_class(15) == "cancel"
    assert failure_class(-1) == "error"
    assert failure_class(None) == "unknown"
    assert failure_class(99) == "unknown"


def test_load_chain_accepts_files_dirs_and_globs(tmp_path):
    p = tmp_path / "events_a.jsonl"
    p.write_text('{"t": 1.0, "kind": "start", "job": "a", "host": 0}\n')
    assert len(load_chain([str(p)])) == 1
    assert len(load_chain([str(tmp_path)])) == 1
    assert len(load_chain([str(tmp_path / "events_*.jsonl")])) == 1


# ---------------------------------------------------------- /metrics + beats

def test_metrics_server_scrape_and_healthz():
    r = MetricRegistry()
    r.counter("ftl_scrape_total", "scrapes").inc(7)
    srv = MetricsServer(r, host="127.0.0.1")
    port = srv.start()
    try:
        resp = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10)
        assert resp.headers["Content-Type"].startswith(
            "text/plain; version=0.0.4")
        body = resp.read().decode()
        assert "ftl_scrape_total 7" in body
        health = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).read()
        assert health == b"ok\n"
    finally:
        srv.stop()


def test_heartbeat_single_process_self_beat():
    r = MetricRegistry()
    hb = HeartbeatThread(step_fn=lambda: 42, registry=r,
                         clock=lambda: 1000.0)
    hb.beat_once()
    snap = r.snapshot()
    steps = snap["ftl_host_heartbeat_step"]["series"]
    ages = snap["ftl_host_heartbeat_age_seconds"]["series"]
    assert len(steps) == 1
    (label, step), = steps.items()
    assert step == 42 and label.startswith("host=")
    assert list(ages.values())[0] >= 0.0


# -------------------------------------------------------------- trace window

def test_parse_window():
    assert parse_window("3:7") == (3, 7)
    assert parse_window("5") == (5, 5)
    for bad in ("", "a:b", "5:3", "-1:4", "1:2:3"):
        with pytest.raises(ValueError):
            parse_window(bad)


def test_auto_trace_arms_once_on_regression_with_bounded_capture():
    from fault_tolerant_llm_training_tpu.obs.trace import AutoTraceWindow

    starts, stops = [], []
    w = AutoTraceWindow("/tmp/t", threshold=2.0, min_samples=4,
                        capture_steps=3, profiler_start=starts.append,
                        profiler_stop=lambda: stops.append(True))
    # warmup: too few samples — even a huge outlier cannot arm yet
    for step in range(3):
        assert w.observe(step, 100.0 if step == 2 else 0.1) is None
    assert not starts
    w2 = AutoTraceWindow("/tmp/t", threshold=2.0, min_samples=4,
                         capture_steps=3, profiler_start=starts.append,
                         profiler_stop=lambda: stops.append(True))
    for step in range(6):
        assert w2.observe(step, 0.1) is None
    assert w2.observe(6, 0.15) is None, "below 2x median: no arm"
    ratio = w2.observe(7, 0.5)  # 5x the rolling median
    assert ratio == pytest.approx(5.0)
    assert starts == ["/tmp/t"] and w2.active
    assert w2.trigger_step == 7
    for step in (8, 9, 10):
        assert w2.observe(step, 0.5) is None  # captured steps don't re-arm
    assert stops == [True] and w2.done and not w2.active
    # once per run: a later, larger regression never re-arms
    assert w2.observe(11, 9.0) is None
    assert starts == ["/tmp/t"]


def test_auto_trace_close_stops_armed_capture_and_validates():
    from fault_tolerant_llm_training_tpu.obs.trace import AutoTraceWindow

    with pytest.raises(ValueError):
        AutoTraceWindow("/tmp/t", threshold=1.0)
    stops = []
    w = AutoTraceWindow("/tmp/t", min_samples=2, profiler_start=lambda d: None,
                        profiler_stop=lambda: stops.append(True))
    for step in range(4):
        w.observe(step, 0.1)
    assert w.observe(4, 1.0) is not None and w.active
    w.close()  # loop exited inside the window
    assert stops == [True] and w.done
    w.close()  # idempotent
    assert stops == [True]


def test_profile_tool_reexports_shared_parser():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "profile_step", REPO / "scripts" / "profile_step.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    from fault_tolerant_llm_training_tpu.obs.trace import parse_trace
    assert mod.parse_trace is parse_trace


# ------------------------------------------------- resume-aware throughput

def test_throughput_reset_restarts_warmup_and_tags_window():
    tp = Throughput(tokens_per_step=100, warmup_steps=1)
    for _ in range(3):
        tp.step()
    assert tp.tokens_per_sec > 0
    tp.reset(tag="post_resume")
    # the meter restarted: the pre-reset (restore-skewed) window is gone
    assert tp.tokens_per_sec == 0.0
    assert tp.window_tag == "post_resume"
    for _ in range(3):
        tp.step()
    assert tp.tokens_per_sec > 0
    tp.clear_tag()
    assert tp.window_tag is None


def test_device_memory_stats_picks_most_loaded_device(monkeypatch):
    monkeypatch.setattr(
        metrics_mod, "per_device_memory_stats",
        lambda: [("0", 100, 1000), ("1", 900, 1000), ("2", 400, 1000)])
    used, limit = metrics_mod.device_memory_stats()
    assert (used, limit) == (900, 1000)
    assert metrics_mod.hbm_usage_str() == "0.0/0.0 GB"  # 900 B in GB


def test_device_memory_stats_none_without_backend_stats(monkeypatch):
    monkeypatch.setattr(metrics_mod, "per_device_memory_stats", lambda: [])
    assert metrics_mod.device_memory_stats() == (None, None)
    assert metrics_mod.hbm_usage_str() == ""


# -------------------------------------------------------------- end to end

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_train_e2e_live_metrics_scrape_and_event_log(tmp_path, tiny_parquet):
    """Run the real CLI with --metrics-port and scrape /metrics while it
    trains: the step-time histogram, tokens/s gauge, and checkpoint-duration
    series must be live; afterwards the flight-recorder JSONL must contain
    the full start → steps → ckpt_save → complete trail."""
    port = _free_port()
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["JAX_COMPILATION_CACHE_DIR"] = "/tmp/jax_test_compile_cache"
    env["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"] = "0"
    env["SLURM_JOB_ID"] = "obs1"
    argv = [sys.executable, str(REPO / "train.py"),
            "--dataset", tiny_parquet,
            "--checkpoint-path", str(tmp_path / "ckpts"),
            "--tokenizer-name-or-path", "byte",
            "--model", "tiny",
            "--sequence-length", "128",
            "--batch-size", "2",
            "--training-steps", "40",
            "--lr-warmup-steps", "5",
            "--learning-rate", "1e-3",
            "--logging-frequency", "1",
            "--checkpoint-frequency", "10",
            "--metrics-port", str(port)]
    proc = subprocess.Popen(argv, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, env=env)
    scraped = None
    try:
        deadline = time.time() + 300
        while time.time() < deadline and proc.poll() is None:
            try:
                body = urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=5
                ).read().decode()
            except OSError:
                time.sleep(0.5)
                continue
            if ("ftl_train_tokens_per_sec{" in body
                    and "ftl_ckpt_save_seconds_count" in body
                    and "ftl_train_step_seconds_count" in body):
                scraped = body
                break
            time.sleep(0.5)
        out, _ = proc.communicate(timeout=max(10.0, deadline - time.time()))
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
    assert proc.returncode == 0, out[-4000:]
    assert scraped is not None, f"no live scrape captured:\n{out[-4000:]}"
    # the three required series, live mid-run
    assert "ftl_train_step_seconds_bucket" in scraped
    assert 'ftl_train_tokens_per_sec{window=' in scraped
    assert "ftl_ckpt_save_seconds_count" in scraped
    assert "ftl_train_tokens_total" in scraped
    # flight recorder: default location <ckpt-path>/events/events_<job>.jsonl
    ev_path = tmp_path / "ckpts" / "events" / "events_obs1.jsonl"
    assert ev_path.exists(), out[-4000:]
    evs = read_events(str(ev_path))
    kinds = [e["kind"] for e in evs]
    assert kinds[0] == "start"
    assert "step" in kinds and "ckpt_save" in kinds
    assert kinds[-1] == "complete"
    step_evs = [e for e in evs if e["kind"] == "step"]
    # every step event is either a paired audit emission or the synthetic
    # tail window that closes the accounting after a trailing pre-save drain
    assert all(e.get("audit") or e.get("tail") for e in step_evs)
    assert step_evs[-1]["step"] == 39  # steps are 0-indexed
    # window accounting covers every trained step exactly once
    assert sum(e["steps"] for e in step_evs) == 40
    assert sum(e["tokens"] for e in step_evs) == 40 * 2 * 128
    # and the stitcher accepts a real single-job log
    report = stitch(evs)
    assert report.steps_reached == 39  # highest 0-indexed step
    assert not report.restarts
    assert report.goodput_pct > 0
