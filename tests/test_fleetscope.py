"""Fleet observability plane tests: hybrid logical clocks (monotonicity
under injected clock skew, two-writer merge, fold determinism), HLC
stamps on every recorder, the /metrics federation aggregator (per-host
re-export, fleet rollups, histogram merges, stale-host gauge), the
exposition parser's escaping roundtrip, and the bench-regression
sentinel (green on committed receipts, red on a synthetic regression)."""

import json
import os
import sys
from pathlib import Path

import pytest

from fault_tolerant_llm_training_tpu.ft.lease import (
    FileKVStore,
    LeaseRegistry,
)
from fault_tolerant_llm_training_tpu.obs import events as events_mod
from fault_tolerant_llm_training_tpu.obs import federate, hlc
from fault_tolerant_llm_training_tpu.obs.federate import (
    Federator,
    family_of,
    parse_metrics_text,
)
from fault_tolerant_llm_training_tpu.obs.registry import (
    MetricRegistry,
    escape_help,
    escape_label_value,
)

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from scripts import bench_trend, fleet_timeline  # noqa: E402


@pytest.fixture(autouse=True)
def _fresh_clock_and_recorder():
    """Zero the process HLC and flight recorder per test."""
    hlc.reset()
    events_mod._RECORDER = events_mod.FlightRecorder()
    yield
    hlc.reset()
    events_mod._RECORDER = events_mod.FlightRecorder()


class FakeTime:
    """Injectable physical clock that tests can step (even backwards)."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t


# ------------------------------------------------------------------ HLC

def test_hlc_pack_order_is_string_order():
    stamps = [hlc.pack(w, c)
              for w in (0, 1, 5, 1 << 40) for c in (0, 1, 255)]
    assert sorted(stamps) == sorted(
        stamps, key=lambda s: hlc.unpack(s))
    assert hlc.ZERO < hlc.pack(1, 0)
    assert hlc.unpack("garbage") == (0, 0)
    assert hlc.unpack(None) == (0, 0)
    assert hlc.unpack(hlc.pack(123, 7)) == (123, 7)


def test_hlc_monotonic_when_clock_steps_backwards():
    ft = FakeTime(100.0)
    c = hlc.HLC(physical=ft)
    stamps = [c.tick()]
    ft.t = 50.0  # OS clock stepped back mid-sequence
    for _ in range(5):
        stamps.append(c.tick())
    ft.t = 200.0  # clock recovers
    stamps.append(c.tick())
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == len(stamps)
    # wall component never went backwards; counter absorbed the rewind
    walls = [hlc.unpack(s)[0] for s in stamps]
    assert walls == sorted(walls)
    # after recovery the wall advances and the counter resets
    assert hlc.unpack(stamps[-1]) == (int(200.0 * 1e6), 0)


def test_hlc_two_writer_merge_orders_receive_after_send():
    ahead = hlc.HLC(physical=FakeTime(200.0))   # writer with fast clock
    behind = hlc.HLC(physical=FakeTime(100.0))  # reader 100 s behind
    sent = ahead.tick()
    # before the merge the behind clock stamps below the remote
    assert behind.tick() < sent
    got = behind.merge(sent)
    assert got > sent
    # every subsequent local tick also sorts after the merged stamp,
    # even though the reader's physical clock is still behind
    assert behind.tick() > sent


def test_hlc_observe_advances_without_minting():
    c = hlc.HLC(physical=FakeTime(100.0))
    remote = hlc.pack(int(500.0 * 1e6), 3)
    c.observe(remote)
    assert c.read() == remote  # adopted, not incremented
    assert c.tick() > remote   # the next real event sorts after it
    c.observe("not-a-stamp")   # garbage is a no-op, never a crash
    c.observe(None)


def test_recorders_stamp_hlc(tmp_path):
    ft = FakeTime(100.0)
    hlc.reset(ft)
    rec = events_mod.FlightRecorder(str(tmp_path / "ev.jsonl"),
                                    job="t", host=0, clock=ft)
    rec.emit("step", step=1)
    ft.t = 50.0  # skew: wall t goes backwards, hlc must not
    rec.emit("step", step=2)
    rec.flush()
    evs = events_mod.read_events(str(tmp_path / "ev.jsonl"))
    assert all(e.get("hlc") for e in evs)
    assert evs[0]["hlc"] < evs[1]["hlc"]
    assert evs[1]["t"] < evs[0]["t"]  # the wall clock DID lie


def test_journal_fold_observes_hlc_deterministically(tmp_path):
    from fault_tolerant_llm_training_tpu.inference import journal
    hlc.reset(FakeTime(100.0))
    j1 = journal.RequestJournal(str(tmp_path), writer="h0")
    j1.assign("r1", "h0", [1, 2], 8, 0.0, 1.0, 0)
    j1.progress("r1", "h0", [5], gen=0)
    folded_a = journal.fold(str(tmp_path))
    stamp_after_first_fold = hlc.clock().read()
    # a fresh reader folding the same files lands on the same HLC state
    hlc.reset(FakeTime(100.0))
    folded_b = journal.fold(str(tmp_path))
    assert hlc.clock().read() == stamp_after_first_fold
    assert sorted(folded_a) == sorted(folded_b)
    # and the reader's next stamp sorts after every folded record
    top = max(r.get("hlc", hlc.ZERO)
              for r in _jsonl_records(tmp_path))
    assert hlc.tick() > top


def _jsonl_records(root):
    out = []
    for path in Path(root).rglob("*.jsonl"):
        for line in path.read_text().splitlines():
            if line.strip():
                out.append(json.loads(line))
    return out


def test_lease_renewal_carries_and_merges_hlc(tmp_path):
    store = FileKVStore(str(tmp_path))
    hlc.reset(FakeTime(500.0))
    LeaseRegistry(store, host_id="h0").renew(
        slots_free=4, blocks_free=8, block_size=16, metrics_port=9100)
    sent = hlc.clock().read()
    # a reader 400 s behind sweeps the lease and must advance past it
    hlc.reset(FakeTime(100.0))
    reader = LeaseRegistry(store, host_id=None)
    leases = reader.leases()
    assert leases["h0"].metrics_port == 9100
    assert leases["h0"].hlc
    assert hlc.tick() > sent


# ------------------------------------------------------------ exposition

def test_registry_escapes_labels_and_help():
    assert escape_label_value('a"b\\c\nd') == 'a\\"b\\\\c\\nd'
    assert escape_help("up\\down\nnext") == "up\\\\down\\nnext"
    r = MetricRegistry()
    c = r.counter("ftl_esc_total", 'tricky "help"\nwith newline')
    c.labels(tok='bad "tok"\nnl').inc(3)
    text = r.render()
    assert '\\"tok\\"\\nnl' in text
    assert "# HELP ftl_esc_total" in text
    assert "\nwith" not in text  # HELP newline escaped, single line
    meta, samples = parse_metrics_text(text)
    (name, labels, value), = [s for s in samples
                              if s[0] == "ftl_esc_total"]
    assert labels["tok"] == 'bad "tok"\nnl'  # roundtrip exact
    assert value == 3


def test_registry_histogram_renders_sum_and_count():
    r = MetricRegistry()
    h = r.histogram("ftl_esc_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    text = r.render()
    assert "ftl_esc_seconds_sum" in text
    assert "ftl_esc_seconds_count 2" in text
    meta, samples = parse_metrics_text(text)
    assert meta["ftl_esc_seconds"]["kind"] == "histogram"
    assert family_of("ftl_esc_seconds_bucket", meta) == "ftl_esc_seconds"
    assert family_of("ftl_esc_seconds_count", meta) == "ftl_esc_seconds"


# ------------------------------------------------------------ federation

def _host_registry(tps, tokens_total, ttfts):
    r = MetricRegistry()
    r.gauge("ftl_serve_tokens_per_sec", "tput").set(tps)
    r.counter("ftl_serve_tokens_generated_total", "tok").inc(tokens_total)
    h = r.histogram("ftl_serve_ttft_seconds", "ttft")
    for v in ttfts:
        h.observe(v)
    return r


def _fleet(tmp_path, clock, pages, renew=((32, 9101), (32, 9102)),
           **kw):
    store = FileKVStore(str(tmp_path / "fleet"))
    for i, (blocks, port) in enumerate(renew):
        LeaseRegistry(store, host_id=f"h{i}", clock=clock).renew(
            slots_free=4, blocks_free=blocks, block_size=16,
            metrics_port=port)

    def fetch(host, port):
        if host not in pages:
            raise OSError("scrape refused")
        return pages[host]

    return Federator(str(tmp_path / "fleet"), clock=clock, fetch=fetch,
                     **kw)


def test_federator_rollups_match_per_host_sums(tmp_path):
    clock = FakeTime(1000.0)
    pages = {
        "h0": _host_registry(10.0, 100, [0.05, 0.08]).render(),
        "h1": _host_registry(25.0, 250, [0.05, 3.0]).render(),
    }
    fed = _fleet(tmp_path, clock, pages, slo_ttft_ms=100.0)
    text = fed.render()
    meta, samples = parse_metrics_text(text)
    by = {}
    for name, labels, value in samples:
        by.setdefault(name, []).append((labels, value))
    # per-host re-export carries host= labels
    hosts = {lb["host"] for lb, _ in by["ftl_serve_tokens_per_sec"]}
    assert hosts == {"h0", "h1"}
    # fleet rollups are the exact per-host sums
    assert by["fleet_tokens_per_sec"][0][1] == 35.0
    assert by["fleet_ftl_serve_tokens_generated_total"][0][1] == 350.0
    assert by["fleet_hosts_live"][0][1] == 2
    assert by["fleet_hosts_stale"][0][1] == 0
    free = {lb["role"]: v for lb, v in by["fleet_kv_blocks_free"]}
    assert free == {"both": 64}
    # merged histogram: count is the fleet count, buckets cumulative
    assert by["fleet_ttft_seconds_count"][0][1] == 4
    inf_bucket = [v for lb, v in by["fleet_ttft_seconds_bucket"]
                  if lb["le"] == "+Inf"]
    assert inf_bucket == [4.0]
    # 3 of 4 requests under the 100 ms SLO bar (bucket resolution)
    slo = {lb["slo"]: v for lb, v in by["fleet_slo_attainment"]}
    assert slo["ttft"] == 0.75
    # HELP/TYPE exactly once per family, however many hosts carry it
    for line in ("# TYPE ftl_serve_tokens_per_sec gauge",
                 "# TYPE fleet_ttft_seconds histogram"):
        assert text.count(line) == 1
    assert fed.last["hosts"] == 2
    assert fed.last["failures"] == 0


def test_federator_flags_stale_host_before_fence(tmp_path):
    clock = FakeTime(1000.0)
    pages = {"h0": _host_registry(10.0, 1, [0.05]).render(),
             "h1": _host_registry(10.0, 1, [0.05]).render()}
    fed = _fleet(tmp_path, clock, pages)
    # h1's lease ages past stale_factor*ttl but NOT past ttl: live by
    # the router's fence rules, wedged by the operator's
    ttl = fed.leases.ttl
    clock.t += 0.8 * ttl
    store = FileKVStore(str(tmp_path / "fleet"))
    LeaseRegistry(store, host_id="h0", clock=clock).renew(
        slots_free=4, blocks_free=32, block_size=16, metrics_port=9101)
    meta, samples = parse_metrics_text(fed.render())
    vals = {name: (labels, value) for name, labels, value in samples}
    assert vals["fleet_hosts_stale"][1] == 1
    assert vals["fleet_hosts_live"][1] == 2
    ages = {lb["host"]: v for n, lb, v in samples
            if n == "fleet_lease_age_seconds"}
    assert ages["h1"] > ages["h0"]


def test_federator_counts_scrape_failures(tmp_path):
    clock = FakeTime(1000.0)
    pages = {"h0": _host_registry(10.0, 1, [0.05]).render()}  # h1 refuses
    fed = _fleet(tmp_path, clock, pages)
    meta, samples = parse_metrics_text(fed.render())
    vals = {name: value for name, labels, value in samples
            if not labels}
    assert vals["fleet_scrape_failures_total"] == 1
    assert vals["fleet_hosts_scraped"] == 1
    assert vals["fleet_tokens_per_sec"] == 10.0


def test_federator_rolls_up_block_store_bytes(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.kvstore import (
        BLOCK_MANIFEST_NAME,
        BlockStore,
    )
    clock = FakeTime(1000.0)
    store = BlockStore(str(tmp_path / "kv"), writer="h0", clock=clock)
    for key, nbytes in (("aa", 4096), ("bb", 1024)):
        store._append({"kind": "put", "key": key, "blocks": 1,
                       "bytes": nbytes, "length": 16, "host": "h0"})
        os.makedirs(store.train_dir(key))
        Path(store.train_dir(key), BLOCK_MANIFEST_NAME).touch()
    store._append({"kind": "evict", "key": "bb"})  # swept by the LRU
    fed = _fleet(tmp_path, clock, {},
                 renew=(), kv_store_dir=str(tmp_path / "kv"))
    meta, samples = parse_metrics_text(fed.render())
    vals = {name: value for name, labels, value in samples if not labels}
    assert vals["fleet_kv_store_resident_bytes"] == 4096
    assert vals["fleet_kv_store_evicted_bytes"] == 1024


# ------------------------------------------------------------- timeline

def test_timeline_orders_by_hlc_not_wall_clock(tmp_path):
    # router clock runs 50 s BEHIND: wall order says the fence happened
    # before the kill it reacted to; the HLC (merged when the router
    # read h0's trail) restores the causal order
    killer = hlc.HLC(physical=FakeTime(100.0))
    router = hlc.HLC(physical=FakeTime(50.0))
    kill = {"t": 100.0, "hlc": killer.tick(), "kind": "chaos_host_kill",
            "job": "fleet_h0", "host": 0, "fault": "host_kill"}
    router.merge(kill["hlc"])  # router reads h0's trail (receive event)
    fence = {"t": 50.0, "hlc": router.tick(), "kind": "fleet_dead",
             "job": "router", "host": 0, "reason": "lease expired"}
    migrate = {"t": 50.1, "hlc": router.tick(), "kind": "fleet_migrate",
               "job": "router", "host": 0, "src": "h0", "dst": "h1"}
    legacy = {"t": 70.0, "kind": "step", "job": "fleet_h1", "host": 1}
    (tmp_path / "events_h0.jsonl").write_text(json.dumps(kill) + "\n")
    (tmp_path / "events_router.jsonl").write_text(
        json.dumps(fence) + "\n" + json.dumps(migrate) + "\n")
    (tmp_path / "events_h1.jsonl").write_text(json.dumps(legacy) + "\n")
    files = fleet_timeline.collect([str(tmp_path)])
    entries = fleet_timeline.build_timeline(files)
    kinds = [e["rec"]["kind"] for e in entries]
    # wall order would read [fence, migrate, step, kill] — backwards;
    # the unstamped legacy record interleaves at its wall position
    assert kinds == ["step", "chaos_host_kill", "fleet_dead",
                     "fleet_migrate"]
    assert [e["anomaly"] for e in entries] == [
        None, "CHAOS", "FENCE", "MIGRATE"]
    # reading the files in any order folds to the identical timeline
    assert fleet_timeline.build_timeline(reversed(files)) == entries
    text = fleet_timeline.format_timeline(entries)
    assert "[CHAOS]" in text and "[FENCE]" in text
    assert text.index("[CHAOS]") < text.index("[FENCE]")
    # the pre-HLC record is flagged as wall-clock-ordered
    legacy_line = [ln for ln in text.splitlines() if " step" in ln][0]
    assert " ~ " in legacy_line


# ------------------------------------------------------------- sentinel

def _write_receipt(root, name, **fields):
    with open(os.path.join(root, name), "w") as fh:
        json.dump(dict({"bench": name}, **fields), fh)


def test_bench_trend_green_then_regression(tmp_path, capsys):
    receipts = tmp_path / "receipts"
    receipts.mkdir()
    _write_receipt(str(receipts), "BENCH_disagg_cpu.json", value=2.0)
    _write_receipt(str(receipts), "BENCH_serving_latency_cpu.json",
                   value=40.0)
    history = str(tmp_path / "trend.jsonl")
    rc = bench_trend.main(["--receipts-dir", str(receipts),
                           "--history", history])
    assert rc == 0
    assert len(bench_trend.load_history(history)) == 1  # appended
    # higher-is-better metric degrades 12% -> fail, metric named
    degraded = tmp_path / "degraded"
    degraded.mkdir()
    _write_receipt(str(degraded), "BENCH_disagg_cpu.json", value=1.76)
    rc = bench_trend.main(["--receipts-dir", str(receipts),
                           "--history", history,
                           "--current-dir", str(degraded)])
    assert rc == 3
    out = capsys.readouterr().out
    assert "REGRESSION: BENCH_disagg_cpu.json value" in out
    # --current-dir runs never pollute the history
    assert len(bench_trend.load_history(history)) == 1
    # lower-is-better: p99 latency UP 20% is also a regression
    worse_lat = tmp_path / "lat"
    worse_lat.mkdir()
    _write_receipt(str(worse_lat), "BENCH_serving_latency_cpu.json",
                   value=48.0)
    assert bench_trend.main(["--receipts-dir", str(receipts),
                             "--history", history,
                             "--current-dir", str(worse_lat)]) == 3
    # within tolerance passes
    fine = tmp_path / "fine"
    fine.mkdir()
    _write_receipt(str(fine), "BENCH_disagg_cpu.json", value=1.95)
    assert bench_trend.main(["--receipts-dir", str(receipts),
                             "--history", history,
                             "--current-dir", str(fine)]) == 0


def test_bench_trend_baseline_is_best_ever_recorded(tmp_path):
    receipts = tmp_path / "receipts"
    receipts.mkdir()
    _write_receipt(str(receipts), "BENCH_disagg_cpu.json", value=2.0)
    history = tmp_path / "trend.jsonl"
    history.write_text(json.dumps(
        {"ts": 1.0, "metrics":
         {"BENCH_disagg_cpu.json": {"value": 3.0}}}) + "\n")
    base = bench_trend.baseline_from(
        bench_trend.load_history(str(history)),
        bench_trend.read_pinned(str(receipts)),
        "BENCH_disagg_cpu.json", "value", "higher")
    assert base == 3.0  # history high-water mark beats the committed one
    # the committed 2.0 is a 33% regression against that baseline
    rc = bench_trend.main(["--receipts-dir", str(receipts),
                           "--history", str(history), "--no-history"])
    assert rc == 3


def test_bench_trend_pins_cover_committed_receipts():
    committed = bench_trend.read_pinned(str(REPO))
    # every pinned receipt that exists in the repo parses to >=1 metric
    for receipt in committed:
        assert committed[receipt], receipt
    assert "BENCH_disagg_cpu.json" in committed
