"""Fleet-global KV-block store (inference/kvstore.py + scheduler fetch/
publish, router cache-affinity placement, ft/retry.py seeded jitter).

Evidence ladder:

1. journal — per-writer fsync'd JSONL folds to per-train state across
   handles (a restarted sweeper re-folds to the same view), refcount
   double-release raises both at the handle and in the fold, torn tails
   from a SIGKILLed writer are skipped, a torn put (no manifest) is
   invisible;
2. artifacts — on a REAL tiny paged engine: publish round-trips the
   exact pool bytes (artifact payloads byte-equal ``block_payload`` of
   the canonical cached blocks), identical chain hashes dedup to one
   resident train, publish rejects key/block count mismatches;
3. eviction — fleet-global LRU by journaled last-use never evicts a
   refcounted train, evicts it once released, and a half-evicted
   directory is finished without new journal records;
4. scheduler — a second engine-reset scheduler FETCHES the published
   train (batched verify-before-first-device-write import) and streams
   bit-identically to a cold local prefill; a poisoned payload is
   rejected with the pool byte-for-byte untouched and zero references
   left behind, then degrades to the local chunked prefill with the
   stream still bit-exact;
5. placement — the router's pick_host prefers the host whose published
   trains cover the deepest prefix of the intake prompt, but a free
   slot still dominates affinity (a full affinity host never starves a
   cold peer);
6. retry jitter — seeded full jitter draws every sleep from
   [0, min(delay, remaining)), replays exactly under a fixed seed, and
   the default (no seed) keeps the deterministic full-delay ladder.

Module scope imports nothing from the package inference/ tree
(collect-only guard in test_spec_decode.py).
"""

import json
import os

import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.ft.retry import (
    RetryDeadlineExceeded,
    retry_with_backoff,
)

CACHE = "/tmp/jax_test_compile_cache"


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------- 1. journal
def test_fold_restart_idempotence_and_refcounts(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore

    clock = _Clock()
    store = BlockStore(str(tmp_path), writer="h0", clock=clock)
    # hand-journal a train's life: the fold needs no artifact on disk
    store._append({"kind": "put", "key": "k1", "blocks": 2, "bytes": 64,
                   "length": 32, "host": "h0"})
    clock.advance(1.0)
    store.acquire("k1", "fetch-a")
    clock.advance(1.0)
    store.touch("k1")
    st = store.fold()["k1"]
    assert st.refs == 1 and st.blocks == 2 and st.bytes == 64
    assert st.last_use == pytest.approx(102.0)
    assert st.hosts == {"h0"}

    # a second handle (the restarted sweeper) folds to the SAME state
    other = BlockStore(str(tmp_path), writer="sweeper", clock=clock)
    st2 = other.fold()["k1"]
    assert (st2.refs, st2.blocks, st2.last_use) == (1, 2, st.last_use)

    store.release("k1", "fetch-a")
    assert other.fold()["k1"].refs == 0
    # releasing a ref this handle does not hold raises at the handle...
    with pytest.raises(ValueError, match="double release"):
        store.release("k1", "fetch-a")
    # ...and an unbalanced unref in the JOURNAL raises at fold time
    store._append({"kind": "unref", "key": "k1", "owner": "ghost"})
    with pytest.raises(ValueError, match="double release"):
        other.fold()


def test_fold_skips_torn_tail_and_bad_writer_names(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore

    store = BlockStore(str(tmp_path), writer="h0")
    store._append({"kind": "put", "key": "k1", "blocks": 1, "bytes": 8,
                   "length": 16, "host": "h0"})
    # SIGKILL mid-append: a torn, newline-less tail must be skipped
    with open(store._journal_path, "a") as fh:
        fh.write('{"kind": "put", "key": "k2", "blo')
    folded = BlockStore(str(tmp_path), writer="h1").fold()
    assert "k1" in folded and "k2" not in folded
    with pytest.raises(ValueError, match="bad store writer"):
        BlockStore(str(tmp_path), writer="../escape")


def test_torn_put_is_invisible(tmp_path):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)

    store = BlockStore(str(tmp_path), writer="h0")
    key = chain_hashes(list(range(16)), 16)[0].hex()
    # a publisher SIGKILLed between payload write and manifest rename
    # leaves payloads but no manifest: never visible, never matched
    os.makedirs(store.train_dir(key))
    with open(os.path.join(store.train_dir(key), "block_00000.bin"),
              "wb") as fh:
        fh.write(b"\0" * 64)
    assert not store.has(key)
    assert store.match(chain_hashes(list(range(16)), 16)) is None
    assert store.resident() == {}


# ----------------------------------------------------------- 2. artifacts
@pytest.fixture(scope="module")
def compiled_engine():
    import jax
    import jax.numpy as jnp

    from fault_tolerant_llm_training_tpu.inference.engine import (
        InferenceEngine, enable_compilation_cache)
    from fault_tolerant_llm_training_tpu.models.configs import get_config
    from fault_tolerant_llm_training_tpu.models.llama import Transformer

    enable_compilation_cache(CACHE)
    cfg = get_config("tiny", vocab_size=64, seq_len=64, layer_impl="loop")
    params = Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, cfg.seq_len), jnp.int32)
    )["params"]
    eng = InferenceEngine(cfg, params, slots=2, max_len=48,
                          prefill_buckets=(16,), kv_layout="paged",
                          kv_block_size=16)
    return cfg, params, eng


def _serve(engine, reqs, store):
    from fault_tolerant_llm_training_tpu.inference.scheduler import Scheduler

    engine.enable_prefix_cache = True
    engine.reset()
    sched = Scheduler(engine, eos_token_id=None, kv_store=store)
    for r in reqs:
        sched.submit(r)
    sched.run()
    return sched, {c.request_id: c.tokens for c in sched.completed}


def _prompt(cfg, n, seed=11):
    rng = np.random.default_rng(seed)
    return rng.integers(3, cfg.vocab_size, size=n).tolist()


def test_publish_roundtrip_bitwise_and_dedup(tmp_path, compiled_engine):
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        block_payload)
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    cfg, _, eng = compiled_engine
    store = BlockStore(str(tmp_path), writer="h0")
    prompt = _prompt(cfg, 32)  # two full 16-token blocks
    sched, _ = _serve(eng, [Request(id="a", prompt=list(prompt),
                                    max_new_tokens=4)], store)
    assert sched.store_publishes == 1
    key = chain_hashes(prompt, 16)[-1].hex()
    assert store.has(key)
    st = store.resident()[key]
    assert st.blocks == 2 and st.host == "h0" and st.length == 32

    # artifact payloads are byte-identical to the canonical cached pool
    # blocks — a fetch therefore reproduces the publisher's exact bytes
    hit = sched.prefix_cache.match(prompt)
    assert hit.depth == 2
    for i, blk in enumerate(hit.blocks):
        with open(os.path.join(store.train_dir(key),
                               f"block_{i:05d}.bin"), "rb") as fh:
            assert fh.read() == block_payload(eng.cache, blk)

    # identical chain hashes dedup: a second serve of the same prompt
    # fetches (tested below) but publishes nothing new
    sched2, _ = _serve(eng, [Request(id="b", prompt=list(prompt),
                                     max_new_tokens=4)], store)
    assert sched2.store_publishes == 0
    assert store.puts == 1

    with pytest.raises(ValueError, match="one key per block"):
        store.publish(eng.cache, chain_hashes(prompt, 16), [1],
                      length=32)


# ------------------------------------------------------------ 3. eviction
def test_lru_sweep_respects_refcounts(tmp_path, compiled_engine):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)

    cfg, _, eng = compiled_engine
    clock = _Clock()
    store = BlockStore(str(tmp_path), writer="h0", clock=clock)
    old_keys = chain_hashes(list(range(16)), 16)
    new_keys = chain_hashes(list(range(16, 32)), 16)
    store.publish(eng.cache, old_keys, [1], length=16)
    clock.advance(5.0)
    store.publish(eng.cache, new_keys, [2], length=16)
    old, new = old_keys[0].hex(), new_keys[0].hex()

    # the LRU victim (old) is mid-fetch: the sweeper must skip it and
    # take the next unreferenced train instead
    store.acquire(old, "importer")
    assert store.sweep(max_bytes=0) == [new]
    assert store.has(old) and not store.has(new)
    store.release(old, "importer")
    assert store.sweep(max_bytes=0) == [old]
    assert store.resident() == {} and store.resident_bytes() == 0


def test_sweep_finishes_half_evicted_dirs_without_new_records(
        tmp_path, compiled_engine):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)

    cfg, _, eng = compiled_engine
    store = BlockStore(str(tmp_path), writer="h0")
    keys = chain_hashes(list(range(16)), 16)
    store.publish(eng.cache, keys, [1], length=16)
    key = keys[0].hex()
    # the sweeper journaled the evict, then died before the rmtree
    store._append({"kind": "evict", "key": key})
    assert os.path.isdir(store.train_dir(key))

    def evict_records():
        n = 0
        jdir = os.path.join(str(tmp_path), "journal")
        for name in os.listdir(jdir):
            with open(os.path.join(jdir, name)) as fh:
                n += sum(1 for ln in fh if '"evict"' in ln)
        return n

    before = evict_records()
    restarted = BlockStore(str(tmp_path), writer="sweeper")
    assert restarted.sweep(max_bytes=1 << 30) == []
    assert not os.path.isdir(store.train_dir(key))  # death finished
    assert evict_records() == before                # re-migrated nothing


# ----------------------------------------------------------- 4. scheduler
def test_fetched_stream_bitmatches_local_prefill(tmp_path, compiled_engine):
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.scheduler import Request

    cfg, _, eng = compiled_engine
    store = BlockStore(str(tmp_path), writer="h0")
    prompt = _prompt(cfg, 32, seed=23)
    reqs = lambda: [Request(id="r", prompt=list(prompt), max_new_tokens=8),
                    Request(id="s", prompt=list(prompt[:16]) + [5],
                            max_new_tokens=8, temperature=0.8, top_p=0.9,
                            seed=3)]
    _, cold = _serve(eng, reqs(), None)            # no store: pure local

    pub, _ = _serve(eng, reqs(), store)            # publisher host
    assert pub.store_publishes >= 1 and pub.store_fetches == 0

    fetch_store = BlockStore(str(tmp_path), writer="h1")
    con, warm = _serve(eng, reqs(), fetch_store)   # consumer host
    assert con.store_fetches >= 1 and con.store_fetch_blocks >= 2
    assert con.store_rejects == 0
    assert warm == cold                            # bit-exact streams
    m = con.metrics()
    assert m["kv_store_fetches"] == con.store_fetches
    assert m["kv_store_fetch_blocks"] == con.store_fetch_blocks
    # the fetch's journaled refs all released; h1 is residency evidence
    assert fetch_store._held == set()
    assert any("h1" in st.hosts
               for st in fetch_store.resident().values())


def test_poisoned_train_rejects_with_zero_device_writes(
        tmp_path, compiled_engine):
    from fault_tolerant_llm_training_tpu.inference.kv_cache import (
        block_layout)
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)
    from fault_tolerant_llm_training_tpu.inference.scheduler import (
        Request, Scheduler)

    cfg, _, eng = compiled_engine
    store = BlockStore(str(tmp_path), writer="h0")
    prompt = _prompt(cfg, 32, seed=31)
    _, cold = _serve(eng, [Request(id="r", prompt=list(prompt),
                                   max_new_tokens=8)], None)
    _serve(eng, [Request(id="r", prompt=list(prompt),
                         max_new_tokens=8)], store)

    # poison one payload byte; the manifest (and so `has`) still commits
    key = chain_hashes(prompt, 16)[-1].hex()
    path = os.path.join(store.train_dir(key), "block_00001.bin")
    raw = bytearray(open(path, "rb").read())
    raw[7] ^= 0xFF
    with open(path, "wb") as fh:
        fh.write(raw)

    eng.enable_prefix_cache = True
    eng.reset()
    sched = Scheduler(eng, eos_token_id=None,
                      kv_store=BlockStore(str(tmp_path), writer="h1"))
    req = Request(id="p", prompt=list(prompt), max_new_tokens=8)
    before = [np.asarray(seg["array"]).copy()
              for seg in block_layout(eng.cache)]
    free_before = sched.allocator.free_count
    sched._maybe_store_fetch(req)
    # verify-before-first-device-write: the reject left the ENTIRE pool
    # byte-identical, every allocated block freed, every store ref dropped
    assert sched.store_rejects == 1
    after = [np.asarray(seg["array"]) for seg in block_layout(eng.cache)]
    assert all(a.tobytes() == b.tobytes() for a, b in zip(before, after))
    assert sched.allocator.free_count == free_before
    assert sched.kv_store._held == set()

    # ...and the degraded path (local chunked prefill) still streams
    # bit-exactly; the poisoned key dedups the republish
    sched.submit(req)
    sched.run()
    assert {c.request_id: c.tokens for c in sched.completed} == {
        "p": cold["r"]}
    assert sched.store_rejects == 2 and sched.store_publishes == 0


# ------------------------------------------------------------ 5. placement
def test_router_affinity_prefers_deepest_prefix_host(tmp_path):
    from fault_tolerant_llm_training_tpu.ft.lease import FileKVStore
    from fault_tolerant_llm_training_tpu.inference.kvstore import BlockStore
    from fault_tolerant_llm_training_tpu.inference.prefix_cache import (
        chain_hashes)
    from fault_tolerant_llm_training_tpu.inference.router import Router

    store_dir = str(tmp_path / "kvstore")
    prompt = list(range(3, 35))  # two full 16-token blocks
    keys = chain_hashes(prompt, 16)
    pub = BlockStore(store_dir, writer="h1")
    pub._append({"kind": "put", "key": keys[-1].hex(), "blocks": 2,
                 "bytes": 64, "length": 32, "host": "h1"})
    # residency needs the manifest on disk; content is irrelevant here
    os.makedirs(pub.train_dir(keys[-1].hex()))
    with open(os.path.join(pub.train_dir(keys[-1].hex()),
                           "integrity.json"), "w") as fh:
        fh.write("{}")

    router = Router(FileKVStore(str(tmp_path / "lease")),
                    str(tmp_path / "journal"), kv_store_dir=store_dir)
    est = lambda slots, blocks: {"stamp": 1.0, "slots": slots,
                                 "blocks": blocks, "block_size": 16,
                                 "role": "both", "kv_dtype": "bf16"}
    # h0 has MORE free blocks; affinity still sends the intake to h1,
    # where the published train makes admission a fetch, not a prefill
    router.est = {"h0": est(2, 100), "h1": est(2, 10)}
    item = {"id": "r", "prompt": prompt, "max_new_tokens": 8, "gen": 0}
    assert router.pick_host(item) == "h1"
    depths = router._affinity_depths(item)
    assert depths == {"h1": 2}
    # a free slot dominates affinity: h1 full => the cold host admits now
    router.est = {"h0": est(2, 100), "h1": est(0, 10)}
    assert router.pick_host(item) == "h0"
    # no matching prefix anywhere: classic most-free-blocks placement
    other = {"id": "q", "prompt": [9] * 32, "max_new_tokens": 8, "gen": 0}
    router.est = {"h0": est(2, 100), "h1": est(2, 10)}
    assert router.pick_host(other) == "h0"


# --------------------------------------------------------- 6. retry jitter
def _jitter_sleeps(seed, deadline=10.0, attempts=6):
    clock = _Clock()
    sleeps = []

    def sleep(dt):
        sleeps.append(dt)
        clock.advance(dt or 1e-3)  # zero draws still make progress

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < attempts:
            raise OSError("transient")
        return "ok"

    out = retry_with_backoff(flaky, deadline_seconds=deadline, clock=clock,
                             sleep=sleep, jitter_seed=seed)
    assert out == "ok"
    return sleeps


def test_seeded_jitter_bounds_and_determinism():
    a = _jitter_sleeps(seed=42)
    b = _jitter_sleeps(seed=42)
    assert a == b                       # replays exactly under a fixed seed
    assert a != _jitter_sleeps(seed=43)  # and the seed actually matters
    # FULL jitter: every sleep drawn from [0, min(delay, remaining)) where
    # delay doubles 0.05 -> 0.1 -> ... capped at 1.0
    delay = 0.05
    for s in a:
        assert 0.0 <= s <= delay
        delay = min(delay * 2.0, 1.0)


def test_unseeded_backoff_keeps_deterministic_ladder():
    sleeps = _jitter_sleeps(seed=None)
    assert sleeps == [0.05, 0.1, 0.2, 0.4, 0.8]


def test_seeded_jitter_still_bounded_by_deadline():
    clock = _Clock()

    def always_down():
        raise OSError("down")

    with pytest.raises(RetryDeadlineExceeded):
        retry_with_backoff(always_down, deadline_seconds=2.0, clock=clock,
                           sleep=clock.advance, jitter_seed=7)
    assert clock.t - 100.0 <= 2.0 + 1e-6
