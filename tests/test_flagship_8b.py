"""Execute the flagship llama3-8b config — the reference's exact trained
shape (ref: train.py:43-53, ~8.05B params) — on the virtual 8-device fsdp
mesh: >=3 real optimizer steps with finite loss, then a save/restore round
trip at full state size (params + AdamW moments, ~48 GB in bf16).

The reference's whole evidence base is this model actually training
(ref: logs/output_444664.out:9-93); round 1 only shape-checked it. This is
a SLOW test (tens of minutes on a 1-core CPU host; ~48 GB of disk for the
checkpoint) and runs only when RUN_SLOW_8B=1. Evidence from a real run is
recorded in logs/flagship_8b_cpu.out and BASELINE.md.

Config deltas from the trained reference shape, all orthogonal to the
model: seq_len 64 (CPU FLOPs; the reference trains at 2048) and the loop
trunk. The loop form is load-bearing here, not a preference: under the
scan trunk XLA hoists the loop-invariant all-gather of the fsdp-sharded
(32, 4096, ...) weight stacks out of the while loop, materializing a full
16 GB weight copy per virtual device (8x = OOM-killed at 130 GB RSS on
this 125 GB host). With 32 unrolled layers the scheduler places each
layer's gather at its use site and frees it after. Vocab stays 131072, so
the vocab-blocked CE path (ops/cross_entropy.py) engages exactly as it
would at the reference scale.
"""

import gc
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fault_tolerant_llm_training_tpu.checkpoint.manager import (
    CheckpointManager,
)
from fault_tolerant_llm_training_tpu.models import get_config
from fault_tolerant_llm_training_tpu.parallel.mesh import make_mesh, use_mesh
from fault_tolerant_llm_training_tpu.utils.harness import synthetic_batch

pytestmark = pytest.mark.skipif(
    os.environ.get("RUN_SLOW_8B") != "1",
    reason="flagship 8B execution: ~48 GB disk + tens of minutes; "
           "set RUN_SLOW_8B=1 to run")


def test_flagship_8b_trains_and_round_trips(eight_devices, tmp_path):
    import time
    t0 = time.time()

    def log(msg):
        print(f"[8b +{time.time() - t0:7.1f}s] {msg}", flush=True)

    cfg = get_config("llama3-8b", seq_len=64, layer_impl="loop")
    mesh = make_mesh(fsdp=8)
    with use_mesh(mesh):
        # Init on ONE device, then reshard. A sharded init program ends in
        # FSDP all-gathers that sit idle while 8 virtual devices serialize
        # ~8B params of RNG through one core — long enough to trip XLA's
        # CPU in-process collective stuck detector (AwaitAndLogIfStuck ->
        # abort). Single-device init has no collectives at all; device_put
        # then lays the state out on the mesh. (Virtual-mesh workaround
        # only: on real chips the sharded init is the right path, and the
        # conftest's raised --xla_cpu_collective_* timeouts cover the
        # step/save collectives here.)
        log("building state on one device (init ~8.05B params)...")
        from fault_tolerant_llm_training_tpu.models import Transformer
        from fault_tolerant_llm_training_tpu.parallel.sharding import (
            param_pspecs,
        )
        from fault_tolerant_llm_training_tpu.training.state import TrainState
        from fault_tolerant_llm_training_tpu.training.step import (
            make_optimizer,
            make_train_step,
        )
        from jax.sharding import NamedSharding

        model = Transformer(cfg)
        opt = make_optimizer(3e-4, warmup_steps=10)

        def init_fn(key):
            params = model.init(
                key, jnp.zeros((1, cfg.seq_len), jnp.int32))["params"]
            return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                              opt_state=opt.init(params))

        single = jax.jit(init_fn)(jax.random.PRNGKey(0))
        log("resharding onto the fsdp mesh...")
        abstract = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(mesh, s), param_pspecs(abstract),
            is_leaf=lambda x: isinstance(x, jax.sharding.PartitionSpec))
        state = jax.device_put(single, shardings)
        jax.block_until_ready(state.params)
        del single
        gc.collect()
        step_fn = jax.jit(make_train_step(model, opt, 1.0),
                          donate_argnums=(0,),
                          out_shardings=(shardings, None))
        n_params = sum(int(np.prod(l.shape))
                       for l in jax.tree_util.tree_leaves(state.params))
        log(f"param count: {n_params:,}")
        assert abs(n_params - 8.05e9) / 8.05e9 < 0.01

        toks, labels = synthetic_batch(cfg, 1)
        losses = []
        for i in range(3):
            state, metrics = step_fn(state, toks, labels)
            losses.append(float(metrics["loss"]))
            log(f"step {i}: loss {losses[-1]:.4f}")
        assert all(np.isfinite(x) for x in losses)
        # Random init at vocab 131072: first loss must sit near ln(V).
        assert abs(losses[0] - np.log(cfg.vocab_size)) < 1.0
        assert losses[2] < losses[0]  # it is actually optimizing

        # Fingerprint a few leaves before freeing the live state.
        leaves = jax.tree_util.tree_leaves(state.params)
        probe = [np.asarray(leaves[i][(0,) * leaves[i].ndim],
                            dtype=np.float32) for i in (0, len(leaves) // 2,
                                                        len(leaves) - 1)]
        step_now = int(state.step)

        log("saving full state (~48 GB)...")
        mngr = CheckpointManager(str(tmp_path), "flagship", max_to_keep=1)
        mngr.save(step_now, state, {"probe": "8b"}, wait=True)
        log("save committed")

        abstract = jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding),
            state)
        del state, leaves
        gc.collect()

        log("restoring...")
        restored, data_state, step = mngr.restore(abstract)
        mngr.close()
        assert step == step_now and data_state == {"probe": "8b"}
        r_leaves = jax.tree_util.tree_leaves(restored.params)
        for want, idx in zip(probe, (0, len(r_leaves) // 2,
                                     len(r_leaves) - 1)):
            got = np.asarray(r_leaves[idx][(0,) * r_leaves[idx].ndim],
                             dtype=np.float32)
            np.testing.assert_array_equal(got, want)  # bit-exact restore
        log("restore verified bit-exact on probed leaves")

        # The restored state steps again — optimizer state round-tripped.
        restored, metrics = step_fn(restored, toks, labels)
        final = float(metrics["loss"])
        log(f"post-restore step: loss {final:.4f}")
        assert np.isfinite(final) and final < losses[0]
