"""Multi-host fault-tolerance coordination (SURVEY.md §5.3 TPU equivalent).

The reference is single-process (SURVEY.md §2.3) — its signal handler and
checkpoint writer never have to agree with anyone. On a TPU pod every host
process receives Slurm's SIGUSR1/SIGTERM independently and at slightly
different times, and a host that unilaterally stops stepping deadlocks the
others inside the next XLA collective. The protocol here:

1. every host records signals locally (ft/signals.py flag pattern);
2. at each check boundary the hosts *agree* on one verdict via a KV-store
   voting round (``agree_on_signal`` — host-side gRPC, no device
   collective) — so either every host raises ``TrainingSignal`` at the
   same step, or none does;
3. the coordinated Orbax save runs on all hosts (sharded per-host writes,
   Orbax's own barrier commits atomically);
4. only process 0 resubmits the Slurm chain (``should_resubmit``) — the
   reference's single ``sbatch`` call (ref: utils.py:84) must not become
   N duplicate jobs.

Signal-combination policy: USR1 (timeout pre-warning, save + requeue) wins
over TERM (cancel, no save) when hosts disagree mid-grace-period — the
Slurm timeout chain delivers USR1 first, so a mixed view means a preemption
is in progress and losing the checkpoint would be the worse failure.

Host-local (non-replicated) faults — the pod fault fence
--------------------------------------------------------
The reference's −1 path always saves (ref: utils.py:69-81). On a pod a
*host-local* error (one process's data loader dies, a local OSError, ...)
cannot simply enter the coordinated save: the other hosts are still
stepping and would never reach the pre-save barrier, while the erroring
host's silence strands THEM inside their next device collective. The fence
closes both holes using the jax.distributed KV store — a host-side gRPC
channel that involves no device collectives, so it can be used at any
moment without draining the dispatch pipeline:

1. the erroring host publishes ``ftl_fault/err/<proc>`` as the exception
   unwinds (``announce_local_error``);
2. every host polls that prefix (non-blocking) before each dispatch and
   raises ``PeerHostError`` — routed through the same −1 exit policy —
   when any peer has announced;
3. in the exit handler, all hosts run the *fence*: publish their own
   last-dispatched step, gather everyone's (bounded by a watchdog),
   dispatch real catch-up steps to the cluster maximum, and only then run
   the ordinary coordinated save — every host saves the SAME step;
4. every blocking multihost wait is bounded: device-side waits (metric
   consume, pre-save drain/barrier, the collective checkpoint write) run
   under ``watchdog``; the KV-side waits (signal agreement, stop-gather)
   poll their own deadlines. On expiry with no peer-fault announcement
   pending, the peer is presumed dead (SIGKILL, kernel panic) and the
   survivor degrades to a clean no-save ``exit 0``
   (``die_uncoordinated``) instead of hanging until the scheduler shoots
   it.
"""

import itertools
import os
import queue
import signal
import threading
from typing import Callable, Dict, Iterable, Optional, Tuple

import jax

_USR1 = int(signal.SIGUSR1)  # 10: save + requeue
_TERM = int(signal.SIGTERM)  # 15: no save

# KV-store namespace for the fault fence (one incident per process lifetime:
# after a fence the job exits, so keys never need generation counters).
_ERR_PREFIX = "ftl_fault/err/"
_STOP_PREFIX = "ftl_fault/stop/"
_DEAD_PREFIX = "ftl_fault/dead/"
# Signal-agreement rounds: ftl_sig/<round>/<proc> (rounds are the loop's
# boundary counter, identical on every host by construction). One-shot
# rounds (round_id=None) use ftl_sig/oneshot<n>/<proc> with a process-local
# monotonic counter — "oneshot" cannot collide with the integer round ids.
_SIG_PREFIX = "ftl_sig/"
_ONESHOT_ROUNDS = itertools.count()
# Heartbeats (obs/prometheus.py): ftl_hb/<proc> = "<unix time>:<step>",
# overwritten in place each interval (unlike the fault keys, which are
# one-incident write-once).
_HB_PREFIX = "ftl_hb/"
_LOCAL_HEARTBEAT: Dict[int, Tuple[float, int]] = {}  # single-process mirror

# Audit line for the degraded (dead-peer) exit; tests and operators grep it.
AUDIT_UNCOORDINATED_FMT = ("[EXIT HANDLER] Pod fault fence failed ({reason}); "
                           "terminating without a checkpoint.")


class PeerHostError(Exception):
    """Raised between dispatches when another host announced a local fault.

    ``args == ("Exception", -1)`` so the exit-policy classification
    (ft/handler.py ``classify_exception``) routes it down the reference's
    −1 path: save (coordinated, via the fence) and do NOT resubmit.
    """

    def __init__(self):
        super().__init__("Exception", -1)


def combine_signals(signums: Iterable[int]) -> Optional[int]:
    """One cluster-wide verdict from per-host signal numbers (0/None = none)."""
    seen = {int(s) for s in signums if s}
    if not seen:
        return None
    if _USR1 in seen:
        return _USR1
    if _TERM in seen:
        return _TERM
    return min(seen)  # deterministic pick for exotic codes


def agree_on_signal(local_signum: Optional[int],
                    round_id: Optional[int] = None,
                    timeout_seconds: float = 300.0,
                    logger=None) -> Optional[int]:
    """One cluster-wide signal verdict per sync boundary, over the
    jax.distributed KV store — publish ``ftl_sig/<round>/<me>``, poll
    every peer's key, ``combine_signals`` the votes.

    Until round 5 this was a device-collective ``process_allgather``,
    which (a) forced a full dispatch-pipeline drain at every boundary
    (a device collective issued concurrently with in-flight steps
    interleaves differently across hosts), and (b) could WEDGE a
    survivor's device queue forever when a peer faulted after the
    survivor entered the allgather — queued device programs cannot be
    abandoned, so even the fence's eventual pre-save barrier queued
    behind the dead collective and the whole pod lost its checkpoint
    (review r5). The KV round involves no device work: no drain is
    needed, a peer's fault announcement interrupts the wait within the
    poll interval (→ ``PeerHostError`` → fence → coordinated save), and
    a silent peer degrades via ``die_uncoordinated`` after
    ``timeout_seconds``.

    ``round_id`` must advance identically on every host (the loop's
    boundary counter does; boundaries are a pure function of
    training_step). ``round_id=None`` draws a fresh round from a
    process-local monotonic counter in a reserved ``oneshot`` namespace:
    a constant key here would make a second synced check collide on the
    write-once publish and read the first round's stale votes (ADVICE
    r5). One-shot callers must therefore make the same *sequence* of
    one-shot calls on every host — the same lockstep contract explicit
    round ids already require. Each host deletes its own round-(R-2) key
    when publishing round R — publishing R implies every host completed
    R-1, which implies nobody still reads R-2 — so the store stays
    O(hosts). Single-process (the reference's regime and all CPU tests):
    identity."""
    if jax.process_count() == 1:
        return local_signum
    import time as _time

    client = _kv()
    rid = (f"oneshot{next(_ONESHOT_ROUNDS)}" if round_id is None
           else int(round_id))
    me = jax.process_index()
    # A failed publish must RAISE (review r5): swallowing it would let
    # this host finish its round on the peers' keys and train on, while
    # every peer burns the full timeout on the missing key and dies
    # uncoordinated. Raising routes this host through the normal
    # host-local-fault path (announce -> fence -> coordinated save).
    client.key_value_set(f"{_SIG_PREFIX}{rid}/{me}",
                         str(int(local_signum or 0)))
    if round_id is not None and rid >= 2:
        try:
            client.key_value_delete(f"{_SIG_PREFIX}{rid - 2}/{me}")
        except Exception:
            pass
    votes = []
    deadline = _time.monotonic() + timeout_seconds
    for p in range(jax.process_count()):
        key = f"{_SIG_PREFIX}{rid}/{p}"
        while True:
            try:
                votes.append(int(_kv_try_get(client, key)))
                break
            except Exception:
                pass  # peer has not published this round yet
            if peer_error_pending():
                raise PeerHostError()
            if _time.monotonic() > deadline:
                die_uncoordinated(
                    logger if logger is not None else _default_logger(),
                    f"peer {p} absent from signal agreement round {rid}")
            _time.sleep(0.05)
    return combine_signals(votes)


def _default_logger():
    from ..utils.logging import logger as _l

    return _l


def barrier(name: str) -> None:
    """Block until every host reaches this point (pre-save drain)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def is_coordinator() -> bool:
    return jax.process_index() == 0


def should_resubmit() -> bool:
    """Exactly one host chains the next Slurm job (ref: utils.py:84)."""
    return is_coordinator()


# --------------------------------------------------------------- fault fence
def _kv():
    """The jax.distributed KV client, or None (single-process runs)."""
    from jax._src import distributed

    return distributed.global_state.client


def _kv_try_get(client, key: str) -> str:
    """``key_value_try_get`` only exists on newer jaxlibs. Emulate it with
    a short-deadline blocking get on older ones — both raise when the key
    is not yet published, which is exactly what the poll loops catch. An
    AttributeError here must NOT reach those loops' blanket excepts: it
    looks identical to 'peer not published yet' and silently burns the
    whole agreement timeout on every call (seen on jaxlib 0.4.36)."""
    try_get = getattr(client, "key_value_try_get", None)
    if try_get is not None:
        return try_get(key)
    return client.blocking_key_value_get(key, 50)


def _kv_set(prefix: str, value: str) -> None:
    """Best-effort keyed publish under this process's index: a dead KV
    connection must never mask the fault being reported."""
    client = _kv()
    if client is None:
        return
    try:
        client.key_value_set(f"{prefix}{jax.process_index()}", value)
    except Exception:
        pass


def announce_local_error(dispatched_step: int) -> None:
    """Publish this host's local fault so peers stop dispatching.

    Called as the exception unwinds (training/loop.py ``run``) — BEFORE the
    exit handler — so the peers' per-dispatch poll sees it within one
    iteration and the dispatch skew stays bounded.
    """
    _kv_set(_ERR_PREFIX, str(int(dispatched_step)))


def peer_error_pending() -> bool:
    """Non-blocking: has ANY host (possibly this one) announced a fault?"""
    client = _kv()
    if client is None:
        return False
    try:
        return bool(client.key_value_dir_get(_ERR_PREFIX))
    except Exception:
        return False


def publish_stop(dispatched_step: int) -> None:
    """Publish this host's last-dispatched step count for the fence."""
    _kv_set(_STOP_PREFIX, str(int(dispatched_step)))


def gather_stops(timeout_seconds: float) -> Optional[Dict[int, int]]:
    """Collect every host's published stop step; None if a peer never
    publishes within the timeout (it died before reaching its fence).

    One monotonic deadline bounds the WHOLE gather: granting each peer the
    full timeout sequentially would let N-1 slow-but-alive peers stretch
    the fence to (N-1) x timeout while the fast hosts' own peers burn
    their budgets waiting for a key this host would publish only after —
    the fence's documented bound is ~2x peer_timeout total, not per peer
    (ADVICE r5)."""
    client = _kv()
    if client is None:
        return None
    import time as _time

    stops: Dict[int, int] = {}
    deadline = _time.monotonic() + timeout_seconds
    for p in range(jax.process_count()):
        remaining_ms = int((deadline - _time.monotonic()) * 1000)
        if remaining_ms <= 0:
            return None
        try:
            val = client.blocking_key_value_get(
                f"{_STOP_PREFIX}{p}", remaining_ms)
        except Exception:
            return None
        stops[p] = int(val)
    return stops


def publish_dead() -> None:
    """Mark this host unable to reach the agreed step (fence catch-up
    failed). The fence's drain watchdog polls this (``watchdog(...,
    poll=peer_dead_pending)``) and degrades within the poll interval
    instead of waiting the full timeout for steps that will never
    execute."""
    _kv_set(_DEAD_PREFIX, "1")


def publish_heartbeat(step: int) -> None:
    """Publish ``(now, step)`` under this host's heartbeat key. Heartbeat
    keys are the one KV surface that is overwritten in place: newer jaxlibs
    take ``allow_overwrite``; older ones need a delete-then-set (both
    best-effort — a flaky KV channel must never take down training)."""
    import time as _time

    value = f"{_time.time():.3f}:{int(step)}"
    client = _kv()
    if client is None:
        _LOCAL_HEARTBEAT[0] = (_time.time(), int(step))
        return
    key = f"{_HB_PREFIX}{jax.process_index()}"
    try:
        try:
            client.key_value_set(key, value, allow_overwrite=True)
        except TypeError:  # jaxlib without the kwarg
            try:
                client.key_value_delete(key)
            except Exception:
                pass
            client.key_value_set(key, value)
    except Exception:
        pass


def read_heartbeats() -> Dict[int, Tuple[float, int]]:
    """Every host's last published heartbeat: {process -> (unix time,
    step)}. Hosts that never published are absent — a host missing from the
    map after startup is as alarming as a stale one. Single-process runs
    return the local mirror so the metric surface is identical off-pod."""
    client = _kv()
    if client is None:
        return dict(_LOCAL_HEARTBEAT)
    beats: Dict[int, Tuple[float, int]] = {}
    for p in range(jax.process_count()):
        try:
            raw = _kv_try_get(client, f"{_HB_PREFIX}{p}")
            t, step = raw.split(":")
            beats[p] = (float(t), int(step))
        except Exception:
            continue  # not published yet (or torn mid-overwrite)
    return beats


def peer_dead_pending() -> bool:
    client = _kv()
    if client is None:
        return False
    try:
        return bool(client.key_value_dir_get(_DEAD_PREFIX))
    except Exception:
        return False


def watchdog(fn: Callable, timeout_seconds: float,
             poll: Optional[Callable[[], bool]] = None,
             poll_seconds: float = 2.0) -> Tuple[bool, object]:
    """Run a blocking wait with a bound: ``(True, result)`` on completion,
    ``(False, None)`` on timeout (or when ``poll()`` turns true first —
    e.g. a peer declaring itself dead, so the caller degrades within the
    poll interval instead of burning the whole timeout).

    ``fn(cancelled)`` receives a ``threading.Event`` that is SET before
    the watchdog gives up. A pure wait (``np.asarray``) may ignore it —
    an abandoned thread that merely finishes waiting is harmless. A
    COMPOUND wait (drain loop + collective) MUST check it between phases
    and go silent once set: an abandoned thread that wakes later (the
    fence's catch-up completes the very steps it was blocked on) and then
    issues a fresh device collective would interleave with the fence's
    own collectives in different orders on different hosts — the exact
    cross-thread hazard data/prefetch.py documents. The wait runs in a
    daemon thread while the caller blocks in ``join`` — strictly
    sequential until abandonment. Exceptions from ``fn`` are re-raised
    here, in the calling thread; after abandonment they are discarded.
    """
    import time as _time

    box: list = [None, None]  # [result, exception]
    cancelled = threading.Event()

    def _run():
        try:
            box[0] = fn(cancelled)
        except BaseException as e:  # re-raised below, in the caller
            box[1] = e

    t = threading.Thread(target=_run, daemon=True)
    t.start()
    deadline = _time.monotonic() + timeout_seconds
    while True:
        remaining = deadline - _time.monotonic()
        if remaining <= 0:
            break
        t.join(min(poll_seconds, remaining) if poll else remaining)
        if not t.is_alive():
            break
        if poll is not None and poll():
            break
    if t.is_alive():
        cancelled.set()
        return False, None
    if box[1] is not None:
        raise box[1]
    return True, box[0]


class PersistentWaiter:
    """``watchdog`` semantics on ONE long-lived worker thread.

    ``watchdog`` spawns and joins a fresh daemon thread per call; on the
    per-step metric-consume path that is a thread create/destroy every
    training step (ADVICE r5). The waiter keeps a single lazily-spawned
    worker fed through a queue, so the steady-state cost of a bounded wait
    is an Event handoff. The abandonment contract is ``watchdog``'s: on
    timeout (or ``poll()`` turning true) the task's ``cancelled`` event is
    set, ``(False, None)`` is returned, and — because a wedged wait cannot
    be interrupted — the worker is discarded ALONG WITH its queue; the
    next ``run`` lazily spawns a fresh one. A discarded worker that later
    finishes its task sees ``cancelled`` set and exits instead of racing
    the replacement for new work; its exception, if any, is discarded,
    exactly as an abandoned ``watchdog`` thread's would be.

    ``run`` serializes callers (one worker, one wait at a time) — the
    intended user is the training loop's single driver thread.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _worker(tasks: "queue.Queue") -> None:
        while True:
            fn, cancelled, box, done = tasks.get()
            try:
                box[0] = fn(cancelled)
            except BaseException as e:  # re-raised in run(), in the caller
                box[1] = e
            done.set()
            if cancelled.is_set():
                return  # abandoned: a fresh worker owns the successor queue

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._queue = queue.Queue()
            self._thread = threading.Thread(
                target=self._worker, args=(self._queue,), daemon=True)
            self._thread.start()

    def run(self, fn: Callable, timeout_seconds: float,
            poll: Optional[Callable[[], bool]] = None,
            poll_seconds: float = 2.0) -> Tuple[bool, object]:
        import time as _time

        box: list = [None, None]  # [result, exception]
        cancelled = threading.Event()
        done = threading.Event()
        with self._lock:
            self._ensure_worker()
            self._queue.put((fn, cancelled, box, done))
            deadline = _time.monotonic() + timeout_seconds
            while True:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    break
                done.wait(min(poll_seconds, remaining) if poll else remaining)
                if done.is_set():
                    break
                if poll is not None and poll():
                    break
            if not done.is_set():
                cancelled.set()
                self._thread = None
                self._queue = None
                return False, None
        if box[1] is not None:
            raise box[1]
        return True, box[0]


def die_uncoordinated(logger, reason: str) -> None:
    """Degraded exit for a dead peer: no checkpoint is writable (a
    coordinated save needs every host; this host's own state may be
    donated into a hung computation), so log the audit line, flush, and
    ``os._exit(0)`` — exit 0 keeps the Slurm never-mark-failed contract
    (ref: train.py:119,129), and skipping teardown avoids joining runtime
    threads that are wedged in a dead collective. No resubmit: −1
    semantics (a chained job would meet the same dead node)."""
    import logging

    try:
        from ..obs import events

        events.emit_audit(logger,
                          AUDIT_UNCOORDINATED_FMT.format(reason=reason),
                          "exit", degraded=True, reason=reason)
        events.flush()  # the .out file dies with the node; the JSONL lives
        logging.shutdown()  # flush the pipe before the hard exit
    except Exception:
        pass
    os._exit(0)
