"""Multi-host fault-tolerance coordination (SURVEY.md §5.3 TPU equivalent).

The reference is single-process (SURVEY.md §2.3) — its signal handler and
checkpoint writer never have to agree with anyone. On a TPU pod every host
process receives Slurm's SIGUSR1/SIGTERM independently and at slightly
different times, and a host that unilaterally stops stepping deadlocks the
others inside the next XLA collective. The protocol here:

1. every host records signals locally (ft/signals.py flag pattern);
2. at each check boundary the hosts *agree* on one verdict via a tiny
   process allgather (``agree_on_signal``) — so either every host raises
   ``TrainingSignal`` at the same step, or none does;
3. the coordinated Orbax save runs on all hosts (sharded per-host writes,
   Orbax's own barrier commits atomically);
4. only process 0 resubmits the Slurm chain (``should_resubmit``) — the
   reference's single ``sbatch`` call (ref: utils.py:84) must not become
   N duplicate jobs.

Signal-combination policy: USR1 (timeout pre-warning, save + requeue) wins
over TERM (cancel, no save) when hosts disagree mid-grace-period — the
Slurm timeout chain delivers USR1 first, so a mixed view means a preemption
is in progress and losing the checkpoint would be the worse failure.
"""

import signal
from typing import Iterable, Optional

import jax

_USR1 = int(signal.SIGUSR1)  # 10: save + requeue
_TERM = int(signal.SIGTERM)  # 15: no save


def combine_signals(signums: Iterable[int]) -> Optional[int]:
    """One cluster-wide verdict from per-host signal numbers (0/None = none)."""
    seen = {int(s) for s in signums if s}
    if not seen:
        return None
    if _USR1 in seen:
        return _USR1
    if _TERM in seen:
        return _TERM
    return min(seen)  # deterministic pick for exotic codes


def agree_on_signal(local_signum: Optional[int]) -> Optional[int]:
    """Allgather each host's pending signal and apply ``combine_signals``.

    Single-process (the reference's regime and all CPU tests): identity.
    """
    if jax.process_count() == 1:
        return local_signum
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        jnp.int32(local_signum or 0))
    return combine_signals(int(x) for x in gathered.flatten())


def barrier(name: str) -> None:
    """Block until every host reaches this point (pre-save drain)."""
    if jax.process_count() == 1:
        return
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)


def is_coordinator() -> bool:
    return jax.process_index() == 0


def should_resubmit() -> bool:
    """Exactly one host chains the next Slurm job (ref: utils.py:84)."""
    return is_coordinator()
