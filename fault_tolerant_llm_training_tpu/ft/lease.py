"""Fleet membership: heartbeat leases over a crash-safe file KV store.

The pod fault fence (ft/multihost.py) taught the pattern: liveness is a
lease the holder must keep renewing, death is a *verdict* rendered by a
peer from lease age, and a fence (tombstone) makes the verdict sticky so
a zombie that wakes up late cannot double-commit. This module ports that
pattern to the serving fleet, with two deliberate differences:

- the substrate is a plain directory (:class:`FileKVStore`, atomic
  tmp+rename writes) rather than the jax.distributed client, so fleet
  hosts are ordinary OS processes and NO process is load-bearing — the
  store survives any participant being SIGKILLed mid-write;
- freshness is carried in the lease VALUE (the holder stamps wall time at
  each renewal), not in filesystem mtime, so the verdict logic is pure
  data and testable with a fake clock.

Every store op in the lease path goes through
:func:`ft.retry.retry_with_backoff` with a bounded deadline: a dead or
wedged store yields a failed renewal / a raised deadline — a clean
verdict — never a hang.

Split-brain safety contract (enforced across router.py and fleet.py):
the router writes the tombstone BEFORE journaling any migration, and a
host treats EITHER a tombstone on itself OR ``ttl`` elapsed since its own
last successful renewal (monotonic clock) as a self-fence — it abandons
its in-flight work without another journal write. A host that cannot
prove its lease is live can therefore never race a migrated replica.
"""

import json
import os
import tempfile
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

from ..obs import hlc
from .retry import RetryDeadlineExceeded, retry_with_backoff

__all__ = ["FileKVStore", "HostLease", "LeaseRegistry"]

LEASE_PREFIX = "fleet/lease"
TOMBSTONE_PREFIX = "fleet/dead"


class FileKVStore:
    """Directory-backed KV store with atomic, torn-write-proof updates.

    Keys are slash-separated paths (``fleet/lease/host_0``); values are
    strings. ``set`` writes a temp file in the destination directory and
    ``os.replace``s it into place, so readers see either the old value or
    the new one, never a partial write — the same finalize discipline as
    checkpoint publishing."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        parts = [p for p in key.split("/") if p]
        if not parts or any(p == ".." for p in parts):
            raise ValueError(f"bad KV key: {key!r}")
        return os.path.join(self.root, *parts)

    def set(self, key: str, value: str) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                                   prefix=".kv_tmp_")
        try:
            with os.fdopen(fd, "w") as fh:
                fh.write(value)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def get(self, key: str) -> Optional[str]:
        try:
            with open(self._path(key)) as fh:
                return fh.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> Dict[str, str]:
        """All key -> value pairs directly under ``prefix``."""
        base = self._path(prefix)
        out: Dict[str, str] = {}
        try:
            names = sorted(os.listdir(base))
        except FileNotFoundError:
            return out
        for name in names:
            if name.startswith(".kv_tmp_"):
                continue
            val = self.get(f"{prefix}/{name}")
            if val is not None:
                out[name] = val
        return out


@dataclass
class HostLease:
    """One host's decoded lease record plus its age at read time."""
    host_id: str
    t: float                 # wall time stamped by the holder at renewal
    ttl: float
    slots_free: int
    blocks_free: int
    block_size: int
    pid: int
    age: float               # reader's now - t
    role: str = "both"       # engine role: both | prefill | decode
    kv_dtype: str = "bf16"   # paged pool storage dtype (ship geometry)
    metrics_port: int = 0    # bound /metrics port (0 = not exporting)
    hlc: str = ""            # holder's HLC at renewal (obs/hlc.py)

    @property
    def live(self) -> bool:
        return self.age <= self.ttl


class LeaseRegistry:
    """Register/renew/read heartbeat leases with capacity metadata.

    One instance per participant. Hosts call :meth:`renew` every loop
    iteration (publishing free slot/block counts the router routes by);
    the router calls :meth:`leases` each sweep and renders dead verdicts
    from lease age. All store traffic is retried with a bounded deadline.
    """

    def __init__(self, store: FileKVStore, host_id: Optional[str] = None,
                 ttl_seconds: float = 2.0, deadline_seconds: float = 1.0,
                 clock: Callable[[], float] = time.time,
                 monotonic: Callable[[], float] = time.monotonic,
                 sleep: Callable[[float], None] = time.sleep):
        self.store = store
        self.host_id = host_id
        self.ttl = float(ttl_seconds)
        self.deadline = float(deadline_seconds)
        self.clock = clock
        self.monotonic = monotonic
        self.sleep = sleep
        self._last_renew_mono: Optional[float] = None

    def _retry(self, fn, what: str):
        return retry_with_backoff(fn, deadline_seconds=self.deadline,
                                  clock=self.monotonic, sleep=self.sleep,
                                  retry_on=(OSError,), what=what)

    # ------------------------------------------------------------- holder side
    def renew(self, slots_free: int, blocks_free: int,
              block_size: int, role: str = "both",
              kv_dtype: str = "bf16", metrics_port: int = 0) -> bool:
        """Stamp a fresh lease; returns False on a bounded-deadline failure
        (the caller counts a failed renewal toward its self-fence).
        ``role``/``kv_dtype`` ride in the lease value so the router can
        place by engine role and reject mixed-dtype prefill->decode pairs
        at placement time (shipped blocks are geometry-checked artifacts).
        ``metrics_port`` advertises the host's bound /metrics endpoint so
        the federation aggregator (obs/federate.py) can discover scrape
        targets from the lease sweep alone. The holder's HLC rides in the
        value too: every lease sweep doubles as an HLC exchange, which is
        what keeps fleet clocks causally merged without a dedicated RPC."""
        if self.host_id is None:
            raise ValueError("renew() requires a host_id")
        value = json.dumps({
            "t": self.clock(), "ttl": self.ttl,
            "slots_free": int(slots_free), "blocks_free": int(blocks_free),
            "block_size": int(block_size), "pid": os.getpid(),
            "role": str(role), "kv_dtype": str(kv_dtype),
            "metrics_port": int(metrics_port), "hlc": hlc.tick(),
        })
        try:
            self._retry(
                lambda: self.store.set(f"{LEASE_PREFIX}/{self.host_id}", value),
                what=f"lease renew {self.host_id}")
        except RetryDeadlineExceeded:
            return False
        self._last_renew_mono = self.monotonic()
        return True

    register = renew  # first renewal IS registration; no separate handshake

    def leave(self) -> None:
        if self.host_id is None:
            raise ValueError("leave() requires a host_id")
        try:
            self._retry(
                lambda: self.store.delete(f"{LEASE_PREFIX}/{self.host_id}"),
                what=f"lease leave {self.host_id}")
        except RetryDeadlineExceeded:
            pass  # expired leases read as dead anyway; leave is best-effort

    def fenced(self) -> bool:
        """Self-fence check for the holder: True once this host can no
        longer prove its own lease is live — either a peer tombstoned it,
        or ``ttl`` elapsed (monotonic) since its last successful renewal.
        After True the host must not journal further progress."""
        if self.host_id is None:
            raise ValueError("fenced() requires a host_id")
        if self._last_renew_mono is not None and (
                self.monotonic() - self._last_renew_mono) > self.ttl:
            return True
        try:
            return self.is_tombstoned(self.host_id)
        except RetryDeadlineExceeded:
            return True  # can't disprove the fence -> fence

    # ------------------------------------------------------------- reader side
    def leases(self, now: Optional[float] = None) -> Dict[str, HostLease]:
        now = self.clock() if now is None else now
        raw = self._retry(lambda: self.store.list(LEASE_PREFIX),
                          what="lease sweep")
        out: Dict[str, HostLease] = {}
        for host, val in raw.items():
            try:
                d = json.loads(val)
                out[host] = HostLease(
                    host_id=host, t=float(d["t"]), ttl=float(d["ttl"]),
                    slots_free=int(d.get("slots_free", 0)),
                    blocks_free=int(d.get("blocks_free", 0)),
                    block_size=int(d.get("block_size", 1)),
                    pid=int(d.get("pid", 0)),
                    age=max(0.0, now - float(d["t"])),
                    role=str(d.get("role", "both")),
                    kv_dtype=str(d.get("kv_dtype", "bf16")),
                    metrics_port=int(d.get("metrics_port", 0)),
                    hlc=str(d.get("hlc", "")))
                # receive event: sweeping a lease merges the holder's HLC
                # into the reader's clock (obs/hlc.py) — the piggyback
                # that keeps fleet clocks causal without a new RPC
                hlc.observe(out[host].hlc)
            except (ValueError, KeyError, TypeError):
                continue  # torn/garbage lease reads as absent, not as a crash
        return out

    def live(self, now: Optional[float] = None) -> List[str]:
        tombs = self.tombstones()
        return [h for h, l in sorted(self.leases(now).items())
                if l.live and h not in tombs]

    def dead(self, now: Optional[float] = None) -> List[str]:
        """Hosts holding a lease that is expired or tombstoned."""
        tombs = self.tombstones()
        return [h for h, l in sorted(self.leases(now).items())
                if not l.live or h in tombs]

    def tombstone(self, host_id: str) -> None:
        """Fence a dead host. MUST be written before any migration record
        for that host's requests is journaled (see module docstring)."""
        value = json.dumps({"t": self.clock(), "by": self.host_id or "router"})
        self._retry(
            lambda: self.store.set(f"{TOMBSTONE_PREFIX}/{host_id}", value),
            what=f"tombstone {host_id}")

    def is_tombstoned(self, host_id: str) -> bool:
        return self._retry(
            lambda: self.store.get(f"{TOMBSTONE_PREFIX}/{host_id}"),
            what=f"tombstone check {host_id}") is not None

    def tombstones(self) -> List[str]:
        return sorted(self._retry(
            lambda: self.store.list(TOMBSTONE_PREFIX),
            what="tombstone sweep").keys())
