"""Bounded retry-with-backoff for transient coordinator/KV-store failures.

The lease/membership path (ft/lease.py) and the deploy pointer watcher
(deploy/reload.py) both poll shared state that can fail transiently — a
slow NFS rename, a pointer file mid-replace, a KV-store op hitting a
restarting coordinator. The failure policy is the same everywhere and is
deliberately *bounded*: retry with exponential backoff against a single
monotonic deadline, then raise :class:`RetryDeadlineExceeded` so the
caller renders a clean verdict (stale lease, no pointer this poll, failed
renewal) instead of hanging on a dead coordinator forever.

Clock and sleep are injectable so tests drive the deadline without
wall-clock waits, mirroring the fake-clock idiom in the pod fault fence.
"""

import random
import time
from typing import Callable, Optional, Tuple, Type

__all__ = ["RetryDeadlineExceeded", "retry_with_backoff"]


class RetryDeadlineExceeded(RuntimeError):
    """The bounded deadline elapsed without a successful attempt.

    ``last_error`` carries the final attempt's exception (``None`` only if
    the deadline was already spent before the first attempt could run)."""

    def __init__(self, what: str, deadline_seconds: float, attempts: int,
                 last_error: Optional[BaseException]):
        self.what = what
        self.deadline_seconds = deadline_seconds
        self.attempts = attempts
        self.last_error = last_error
        detail = f": {last_error!r}" if last_error is not None else ""
        super().__init__(
            f"{what} failed for {deadline_seconds:.1f}s "
            f"({attempts} attempt(s)){detail}")


def retry_with_backoff(
    fn: Callable,
    *,
    deadline_seconds: float,
    base_delay: float = 0.05,
    max_delay: float = 1.0,
    retry_on: Tuple[Type[BaseException], ...] = (OSError,),
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    what: str = "kv-store op",
    jitter_seed: Optional[int] = None,
):
    """Call ``fn()`` until it succeeds or the deadline elapses.

    One deadline bounds the WHOLE call (the gather_stops pattern from the
    pod fence), not each attempt — so a dead coordinator costs at most
    ``deadline_seconds`` before the caller gets its verdict. Backoff
    doubles from ``base_delay`` up to ``max_delay`` and is clipped to the
    time remaining, so the final sleep never overshoots the deadline.

    ``jitter_seed`` enables seeded FULL jitter: each sleep draws uniformly
    from ``[0, min(delay, remaining))`` instead of sleeping the cap
    exactly, which decorrelates the store-fetch / lease / pointer-watcher
    callers that otherwise dogpile shared state on identical schedules.
    Seeded, not wall-clock-random, so a retry trace replays exactly under
    a fixed seed; ``None`` (the default) keeps the deterministic
    full-delay behavior every existing caller pins.
    """
    if deadline_seconds <= 0:
        raise ValueError(f"deadline_seconds must be > 0, got {deadline_seconds}")
    deadline = clock() + deadline_seconds
    delay = base_delay
    rng = random.Random(jitter_seed) if jitter_seed is not None else None
    attempts = 0
    last_error: Optional[BaseException] = None
    while True:
        if clock() >= deadline:
            raise RetryDeadlineExceeded(what, deadline_seconds, attempts,
                                        last_error)
        attempts += 1
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop by design
            last_error = e
            if on_retry is not None:
                on_retry(attempts, e)
            remaining = deadline - clock()
            if remaining <= 0:
                raise RetryDeadlineExceeded(what, deadline_seconds, attempts,
                                            last_error)
            cap = min(delay, remaining)
            sleep(rng.uniform(0.0, cap) if rng is not None else cap)
            delay = min(delay * 2.0, max_delay)
