"""Signal capture (ref: utils.py:93-97, train.py:89-90).

The reference's handler raises an exception *directly from the signal
handler*, which can fire anywhere in Python — including inside the checkpoint
write (SURVEY.md §5.3 lists this as a known race). Under JAX the situation is
sharper still: a Python exception cannot interrupt XLA execution at all.

So this framework uses the flag pattern (SURVEY.md §7.1): the POSIX handler
only records the signal number (an atomic int store); the host loop calls
``check()`` between step dispatches — and during setup phase boundaries,
closing the reference's unprotected-setup window (train.py:42-84 runs ~35 s
before handlers are registered at :89) — which re-raises it as a
``TrainingSignal`` carrying the same ``("Exception", signum)`` args shape the
reference's classification logic expects (train.py:122-126).

Signal-number contract (Linux): SIGUSR1=10 (Slurm pre-timeout warning, armed
by ``--signal=USR1@120``, ref train.sh:12), SIGTERM=15 (scancel); injected
code errors use -1.
"""

import contextlib
import os
import signal
from typing import Optional

_FAULT_SIGNALS = {signal.SIGUSR1, signal.SIGTERM}


def inject(signum: int) -> None:
    """Deliver a real POSIX signal to this process (the chaos injection
    path, chaos/injector.py). Routing through ``os.kill`` — not a direct
    flag mutation — means the installed handler, the first-signal-wins
    latch, ``deferred()`` masking and the multihost agreement all run
    exactly as they would for a scheduler-sent signal."""
    os.kill(os.getpid(), signum)


class TrainingSignal(Exception):
    """Raised between steps when a POSIX signal was received.

    ``args == ("Exception", signum)`` so ``e.args[1]`` yields the error type,
    exactly like the reference's re-raise (ref: utils.py:97).
    """

    def __init__(self, signum: int):
        super().__init__("Exception", signum)
        self.signum = signum


class SignalFlag:
    """Records the latest fault signal; checked by the host loop."""

    def __init__(self):
        self.signum: Optional[int] = None
        self.received: list = []  # every fault signal, in arrival order

    def _handler(self, signum, frame):
        self.received.append(signum)
        if self.signum is None:
            # First signal wins: a SIGTERM chasing the USR1 pre-warning (the
            # Slurm grace-period pattern) must not flip a pending
            # save-and-requeue into a no-save cancel. The reference has the
            # inverse race — its second signal raises *inside* the save
            # handler and truncates the checkpoint (SURVEY.md §5.3).
            self.signum = signum

    def register(self) -> None:
        """Install for SIGUSR1 and SIGTERM (ref: train.py:89-90) — call as
        early as possible, before model build."""
        signal.signal(signal.SIGUSR1, self._handler)
        signal.signal(signal.SIGTERM, self._handler)

    def check(self, synced: bool = False) -> None:
        """Raise ``TrainingSignal`` if a fault signal is pending.

        ``synced=True`` first agrees on a cluster-wide verdict with the
        other hosts (ft/multihost.py ``agree_on_signal``, a one-shot
        KV-store voting round here — the trainer's loop manages proper
        round ids itself): either every host raises at this boundary or
        none does — a host raising alone would deadlock the rest inside
        the next XLA collective. Single-process: identical to
        ``synced=False``.
        """
        signum = self.signum
        if synced:
            from .multihost import agree_on_signal

            signum = agree_on_signal(signum)
        if signum is not None:
            self.signum = None
            raise TrainingSignal(signum)

    @contextlib.contextmanager
    def deferred(self):
        """Block fault-signal *delivery* (pthread_sigmask) for the scope.

        A signal interrupting native code (XLA compilation, the axon/PJRT
        client handshake, an Orbax commit) can wedge the process via EINTR
        mishandling deep in C++ — observed hanging backend init. During
        setup and during the exit handler the signals are therefore blocked
        at the OS level; they stay *pending* and are delivered (and recorded
        by the flag) the moment the scope exits, where the next ``check()``
        picks them up at a safe boundary.
        """
        signal.pthread_sigmask(signal.SIG_BLOCK, _FAULT_SIGNALS)
        try:
            yield
        finally:
            signal.pthread_sigmask(signal.SIG_UNBLOCK, _FAULT_SIGNALS)
