"""Exit policy table (ref: utils.py:65-90) — the heart of the reference.

Dispatch on the integer error type:

- 15 (SIGTERM / scancel)  -> log, terminate, NO save (intentional: the user
                             cancelled; ref utils.py:67-68, README.md:45-47)
- 10 (SIGUSR1 / timeout)  -> save checkpoint + self-resubmit the Slurm chain
                             (ref: utils.py:69-88)
- -1 (Python error)       -> save checkpoint, NO resubmit (a code bug would
                             just recur; ref utils.py:69-81, README.md:41)
- anything else           -> log unknown, terminate

The caller always exits 0 afterwards (ref: train.py:119,129) so Slurm never
marks the job failed. Audit strings are byte-identical to the reference's
(see utils/logging.py) — they are the machine-checkable contract.

Differences from the reference (all safety upgrades, SURVEY.md §5.3):
- the save is an atomic-commit Orbax write, so a SIGTERM landing mid-save
  cannot leave a truncated checkpoint the next job would load;
- resubmission is attempted even when no state exists yet (signal during
  setup), keeping the job chain alive through the reference's fatal window;
- the resubmit command is validated by return code like the reference
  (utils.py:84-88) but overridable for hermetic tests.
"""

import os

import jax

from .multihost import PeerHostError

from ..obs import events
from ..obs.goodput import failure_class
from ..utils.config import JOBID, WORKDIR
from ..utils.logging import (
    AUDIT_CANCELLED,
    AUDIT_ERROR_SAVING,
    AUDIT_REQUEUE_FAILED_FMT,
    AUDIT_REQUEUED,
    AUDIT_SAVED_FMT,
    AUDIT_TIMEOUT_SAVING,
    AUDIT_UNKNOWN_FMT,
)

SIGNAL_TIMEOUT = 10  # SIGUSR1
SIGNAL_CANCEL = 15  # SIGTERM
CODE_ERROR = -1


def classify_exception(e: BaseException) -> int:
    """ref: train.py:122-126 — ``e.args[1]`` if present, else -1."""
    if len(e.args) >= 2 and isinstance(e.args[1], int):
        return e.args[1]
    return CODE_ERROR


def resubmit(logger, command: str = "") -> bool:
    """Chain the next job: ``sbatch $WORKDIR/train.sh $SLURM_JOB_ID``
    (ref: utils.py:83-88). Returns True on queue success. On a pod, only
    process 0 submits — N hosts must not queue N duplicate jobs."""
    from .multihost import should_resubmit

    if not should_resubmit():
        return True
    cmd = command or f"sbatch {WORKDIR}/train.sh {JOBID}"
    ret = os.system(cmd)
    if ret != 0:
        events.emit_audit(logger,
                          AUDIT_REQUEUE_FAILED_FMT.format(job_id=JOBID),
                          "requeue", ok=False)
        return False
    events.emit_audit(logger, AUDIT_REQUEUED, "requeue", ok=True)
    return True


def handle_exit(trainer, error_type: int, logger) -> None:
    """Policy dispatch (ref: utils.py:65-90). ``trainer`` may be None or
    partially constructed (signal during setup).

    Every branch both logs the byte-identical audit string AND emits the
    structured event (obs/events.py) the goodput stitcher reads; the
    ``finally`` flush is the flight-recorder guarantee — the event log is
    durable on every exit path, including a save that itself dies."""
    try:
        _handle_exit(trainer, error_type, logger)
    finally:
        events.flush()


def _handle_exit(trainer, error_type: int, logger) -> None:
    cls = failure_class(error_type)
    if error_type == SIGNAL_CANCEL:
        events.emit_audit(logger, AUDIT_CANCELLED, "exit",
                          error_type=error_type, cls=cls, saved=False)
        return
    if error_type in (CODE_ERROR, SIGNAL_TIMEOUT):
        if error_type == SIGNAL_TIMEOUT:
            events.emit_audit(logger, AUDIT_TIMEOUT_SAVING, "signal",
                              signum=error_type, cls=cls)
        else:
            events.emit_audit(logger, AUDIT_ERROR_SAVING, "signal",
                              signum=error_type, cls=cls)
        saved_step = None
        if trainer is not None and getattr(trainer, "state", None) is not None:
            # Coordination: signal exits were agreed cluster-wide
            # (ft/signals.py synced check), and deterministic code errors
            # (injection, non-finite grads) hit every host at the same step.
            # An error of unknown provenance may be host-local: a unilateral
            # coordinated (barrier + collective Orbax write) save would
            # hang, so on a pod those first run the fault fence
            # (ft/multihost.py): every host — the erroring one announced as
            # it unwound, the others raised PeerHostError off their
            # per-dispatch poll — converges on the cluster-maximum
            # dispatched step, after which the coordinated save is safe and
            # every host saves the SAME step. The fence does not return
            # when a peer is dead: the degraded path exits 0 without a
            # checkpoint rather than hanging the survivors.
            coordinated = (error_type == SIGNAL_TIMEOUT
                           or getattr(trainer, "error_is_replicated", False))
            if not coordinated and jax.process_count() > 1:
                coordinated = trainer.coordinate_local_error()
            try:
                saved_step = trainer.save_checkpoint(wait=True,
                                                     coordinated=coordinated,
                                                     fault=True)
            except PeerHostError:
                # A peer faulted DURING this save (its announcement tripped
                # a guarded wait inside the drain/barrier). Escaping here
                # would skip the checkpoint entirely (ADVICE r5): instead
                # run the fence now — it converges every host on the same
                # step — and retry the save once, coordinated. The fence's
                # no-return degraded paths still cover dead peers.
                logger.info("[EXIT HANDLER] Peer fault during save; "
                            "running the fence and retrying once.")
                trainer.coordinate_local_error()
                saved_step = trainer.save_checkpoint(wait=True,
                                                     coordinated=True,
                                                     fault=True)
            events.emit_audit(logger, AUDIT_SAVED_FMT.format(step=saved_step),
                              "exit", step=saved_step, error_type=error_type,
                              cls=cls, saved=True, saved_step=saved_step)
            # Armed ckpt_corrupt faults corrupt the checkpoint AFTER its
            # commit + integrity manifest (chaos/injector.py) — the next
            # job's restore must catch it and fall back.
            chaos = getattr(trainer, "chaos", None)
            if chaos is not None and saved_step is not None:
                chaos.post_fault_save(trainer.ckpt_mngr.directory,
                                      saved_step, logger)
        else:
            logger.info("[EXIT HANDLER] No training state to save yet.")
            events.emit(kind="exit", error_type=error_type, cls=cls,
                        saved=False, no_state=True)
        if error_type == SIGNAL_TIMEOUT:
            command = ""
            if trainer is not None:
                command = trainer.cfg.resubmit_command
            resubmit(logger, command)
        return
    events.emit_audit(logger, AUDIT_UNKNOWN_FMT.format(type=error_type),
                      "exit", error_type=error_type, cls=cls, saved=False)
