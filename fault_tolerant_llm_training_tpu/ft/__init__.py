from .signals import SignalFlag, TrainingSignal
from .handler import handle_exit, classify_exception

__all__ = ["SignalFlag", "TrainingSignal", "handle_exit", "classify_exception"]
