from .configs import TransformerConfig, PRESETS, get_config
from .llama import Transformer

__all__ = ["TransformerConfig", "PRESETS", "get_config", "Transformer"]
